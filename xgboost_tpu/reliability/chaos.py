"""Composed-fault chaos soak: seeded schedules, scenario templates,
checked invariants, one-command replay.

Every fault test before this module fires exactly ONE fault at a known
seam.  Production failure is *coincidence*: a slow decode while a replica
connection drops while a checkpoint write tears.  This harness closes the
gap (docs/reliability.md "Integrity & chaos"):

- :func:`generate_plan` — a **pure function of (scenario, seed)** that
  composes N faults from the scenario's seam/kind catalog into one
  :class:`~xgboost_tpu.reliability.faults.FaultPlan` dict.  Same seed →
  same schedule, byte for byte; there is no other source of randomness.
- **Scenario templates** (:data:`SCENARIOS`) — an external-memory
  training run, a serving fleet under traffic, a lifecycle hot-swap
  cycle, a multi-process elastic training run, a coordinator-failover
  run (the supervised tracker SIGKILL'd at a journal write), a
  stall-watchdog run (a delay past tight budgets), and a
  resource-exhaustion run (ENOSPC at checkpoint commits, injected
  memory/fd pressure through the governor — the degradation ladders
  must absorb it bitwise); each knows which
  (seam, kind) pairs its stack must *survive* (a green episode means the
  faults fired AND the contract held — nothing in a catalog is allowed
  to be fatal).
- :func:`run_episode` — install the plan, run the scenario under a
  wall-clock deadline, then check the invariants:

  1. **no hang**: the episode finished before its deadline;
  2. **no silent wrong bits**: where the determinism contract applies
     (``twin=True``) the episode's result digest is bitwise-equal to a
     fault-free twin run of the same scenario;
  3. **accounting**: the ``xtb_faults_injected_total`` delta equals the
     plan's own fired ledger (the harness, not an unrelated bug, caused
     every observed fault) — both measured in the driver process;
  4. scenario invariants: no dropped fleet requests, a flight-recorder
     dump for every replica death, checkpoint scrub counts matching the
     fired damage, a lifecycle reject for every reject-class fault.

- :func:`soak` — round-robin episodes across scenarios under a budget,
  guaranteeing a minimum episode count (cheap scenarios fill the tail
  when the budget runs dry), then **replays the first episode's seed**
  and requires the identical schedule and outcome — so ANY red episode
  in a soak report is a one-command repro:
  ``python scripts/chaos_soak.py --replay <scenario> <seed>``.

Kill-kind faults appear only in catalogs whose seams fire inside
launcher-spawned subprocesses (workers, or the supervised tracker child
for ``tracker.journal``) — a kill at a driver-side seam would take the
harness down with it (``os._exit``), which is why the lifecycle catalog
injects ``exception`` at ``lifecycle.swap`` here and leaves the
kill-mid-swap replay to ``scripts/lifecycle_smoke.py``'s subprocess rig.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import random
import tempfile
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import faults, lockdep

__all__ = ["CatalogEntry", "Scenario", "SCENARIOS", "EpisodeReport",
           "generate_plan", "run_episode", "soak"]


_instruments = None


def _ins():
    global _instruments
    if _instruments is None:
        from ..telemetry.registry import get_registry

        reg = get_registry()
        _instruments = (
            reg.counter("xtb_chaos_episodes_total",
                        "chaos episodes run, by scenario and outcome",
                        ("scenario", "outcome")),
            reg.histogram("xtb_chaos_episode_seconds",
                          "wall-clock per chaos episode", ("scenario",)),
        )
    return _instruments


def _counter_total(name: str) -> float:
    """Sum of a counter family across all label sets (0 when the family
    was never registered)."""
    from ..telemetry.registry import get_registry

    fam = get_registry().get(name)
    if fam is None:
        return 0.0
    return sum(child.value for _values, child in fam.collect())


def _counter_labeled(name: str, *label_values: str) -> float:
    """One label set's counter value (0 when family/child absent)."""
    from ..telemetry.registry import get_registry

    fam = get_registry().get(name)
    if fam is None:
        return 0.0
    for values, child in fam.collect():
        if values == tuple(label_values):
            return float(child.value)
    return 0.0


def _digest(*parts) -> str:
    h = hashlib.sha256()
    for p in parts:
        if isinstance(p, str):
            p = p.encode()
        h.update(bytes(p))
        h.update(b"|")
    return h.hexdigest()


# ---------------------------------------------------------------------------
# schedule generation
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CatalogEntry:
    """One injectable (seam, kind) with sampled parameters.  ``params``
    values are sampled per entry draw: a list is a uniform choice, an
    ``(int, int)`` tuple a ``randrange``, a ``(float, float)`` tuple a
    rounded ``uniform``.  ``post`` (optional, pure) patches the sampled
    spec for coupled fields (e.g. elastic kills pin ``at`` to ``round``)."""

    site: str
    kind: str
    params: Dict[str, Any] = dataclasses.field(default_factory=dict)
    post: Optional[Callable[[dict], dict]] = None


def _sample_entry(entry: CatalogEntry, rng: random.Random) -> dict:
    spec: dict = {"site": entry.site, "kind": entry.kind}
    for key, rng_spec in entry.params.items():
        if isinstance(rng_spec, list):
            spec[key] = rng.choice(rng_spec)
        elif isinstance(rng_spec[0], float):
            spec[key] = round(rng.uniform(rng_spec[0], rng_spec[1]), 4)
        else:
            spec[key] = rng.randrange(rng_spec[0], rng_spec[1])
    if entry.post is not None:
        spec = entry.post(spec)
    return spec


def generate_plan(scenario: str, seed: int,
                  n_faults: Optional[int] = None) -> dict:
    """The seeded schedule: a fault-plan dict composing ``n_faults``
    (default 2–4, seed-chosen) entries from the scenario's catalog.  Pure
    in (scenario, seed, n_faults) — the replay guarantee rests here."""
    sc = SCENARIOS[scenario]
    rng = random.Random((zlib.crc32(scenario.encode()) << 32)
                        ^ (int(seed) * 0x9E3779B1))
    n = int(n_faults) if n_faults is not None else rng.randint(2, 4)
    n = max(1, min(n, sc.max_faults))
    specs = [_sample_entry(sc.catalog[rng.randrange(len(sc.catalog))], rng)
             for _ in range(n)]
    if sc.per_plan_caps:
        seen: Dict[Tuple[str, str], int] = {}
        kept = []
        for spec in specs:
            key = (spec["site"], spec["kind"])
            seen[key] = seen.get(key, 0) + 1
            if seen[key] <= sc.per_plan_caps.get(key, n):
                kept.append(spec)
        specs = kept
    return {"faults": specs}


# ---------------------------------------------------------------------------
# scenario templates
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Scenario:
    name: str
    catalog: Tuple[CatalogEntry, ...]
    run: Callable[[str], dict]        # workdir -> artifacts (must contain
    #                                   "digest" when twin=True)
    check: Callable[[List[tuple], dict, Optional[dict]], Dict[str, str]]
    twin: bool = True                 # compare digest vs a fault-free run
    cost_hint_s: float = 5.0
    deadline_s: float = 120.0
    # cap on composed faults per episode: the fleet's reroute budget
    # survives 3 severed connections per request, not unbounded chains
    max_faults: int = 4
    # per-(site, kind) caps applied AFTER sampling (deterministic drop of
    # the extras): some faults compose into a strictly stronger fault —
    # two transient page corruptions can land on a decode AND its retry
    # (the prefetch pool interleaves invocation numbering), which IS a
    # persistent corruption and correctly fails loud
    per_plan_caps: Dict[Tuple[str, str], int] = dataclasses.field(
        default_factory=dict)


def _no_checks(fired, artifacts, baseline) -> Dict[str, str]:
    return {}


# ------------------------------------------------------------------ extmem
def _extmem_data():
    import numpy as np

    rng = np.random.default_rng(20260804)
    Xs = [rng.standard_normal((600, 8)).astype(np.float32)
          for _ in range(3)]
    ys = [(X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32) for X in Xs]
    return Xs, ys


def _run_extmem(workdir: str) -> dict:
    import numpy as np

    import xgboost_tpu as xtb
    from ..data.extmem import _zstd_available
    from .checkpoint import CheckpointCallback, latest_checkpoint, scrub_dir

    Xs, ys = _extmem_data()

    class _Iter(xtb.DataIter):
        def __init__(self):
            super().__init__()
            self.i = 0

        def next(self, input_data):
            if self.i >= len(Xs):
                return 0
            input_data(data=Xs[self.i], label=ys[self.i])
            self.i += 1
            return 1

        def reset(self):
            self.i = 0

    # on_host=False puts every page behind a decode boundary (zstd blob or
    # CRC-gated DiskPage spill), which is where extmem.page_decode fires
    d = xtb.ExtMemQuantileDMatrix(_Iter(), max_bin=32, on_host=False,
                                  compress=_zstd_available())
    ckpt = os.path.join(workdir, "ckpt")
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                     "max_bin": 32, "eta": 0.3}, d, 6,
                    callbacks=[CheckpointCallback(ckpt, interval=2)],
                    verbose_eval=False)
    scrub = scrub_dir(ckpt)
    state = latest_checkpoint(ckpt)
    preds = np.asarray(bst.predict(d), np.float64)
    return {"digest": _digest(bytes(bst.serialize()), preds.tobytes()),
            "ckpt_valid": len(scrub["valid"]),
            "ckpt_corrupt": len(scrub["corrupt"]),
            "resumable": state is not None}


def _check_extmem(fired, artifacts, baseline) -> Dict[str, str]:
    inv = {}
    ckpt_hits = sum(n for spec, n in fired
                    if spec.site == "checkpoint.write")
    total = artifacts["ckpt_valid"] + artifacts["ckpt_corrupt"]
    inv["ckpt_scrub_matches_plan"] = (
        "ok" if artifacts["ckpt_corrupt"] == ckpt_hits
        else f"FAIL: scrub found {artifacts['ckpt_corrupt']} corrupt "
             f"checkpoints, plan damaged {ckpt_hits}")
    inv["ckpt_population"] = ("ok" if total == 3
                              else f"FAIL: {total} checkpoint files != 3")
    inv["resume_fallback"] = (
        "ok" if artifacts["resumable"] == (artifacts["ckpt_valid"] > 0)
        else "FAIL: latest_checkpoint disagrees with the scrub walk")
    return inv


# ------------------------------------------------------------------- fleet
_FLEET_FIXTURE: dict = {}


def _fleet_fixture():
    """One tiny booster + request rows + expected predictions, built once
    per process (the in-process twin every fleet episode compares
    against)."""
    if not _FLEET_FIXTURE:
        import numpy as np

        import xgboost_tpu as xtb

        rng = np.random.default_rng(7)
        X = rng.standard_normal((400, 6)).astype(np.float32)
        y = (X[:, 0] - X[:, 2] > 0).astype(np.float32)
        bst = xtb.train({"objective": "binary:logistic", "max_depth": 3},
                        xtb.DMatrix(X, label=y), 5, verbose_eval=False)
        Q = rng.standard_normal((64, 6)).astype(np.float32)
        _FLEET_FIXTURE.update(bst=bst, Q=Q)
    return _FLEET_FIXTURE["bst"], _FLEET_FIXTURE["Q"]


_N_FLEET_REQ = 24


def _run_fleet(workdir: str) -> dict:
    import numpy as np

    from ..serving.fleet import FleetConfig, ServingFleet

    bst, Q = _fleet_fixture()
    cfg = FleetConfig(n_replicas=2, max_respawns=8, nthread_per_replica=1,
                      cache_dir=os.path.join(
                          tempfile.gettempdir(), "xtb_chaos_warm"))
    outs: List[bytes] = []
    with ServingFleet({"m": bst}, cfg) as fleet:
        for i in range(_N_FLEET_REQ):
            rows = Q[(i * 5) % 48: (i * 5) % 48 + 16]
            # predict() raising = a dropped request = a red episode
            outs.append(np.ascontiguousarray(
                fleet.predict("m", rows, timeout=180), np.float32
            ).tobytes())
        deaths = len(fleet.flight_dumps)
        dumps = len([p for p in fleet.flight_dumps.values()
                     if os.path.exists(p)])
    return {"digest": _digest(*outs), "completed": len(outs),
            "deaths": deaths, "dumps": dumps}


def _check_fleet(fired, artifacts, baseline) -> Dict[str, str]:
    inv = {}
    severed = sum(n for spec, n in fired
                  if (spec.site == "fleet.dispatch"
                      and spec.kind == "drop_connection")
                  or (spec.site == "wire.frame" and spec.kind == "corrupt"))
    inv["no_dropped_requests"] = (
        "ok" if artifacts["completed"] == _N_FLEET_REQ
        else f"FAIL: {artifacts['completed']}/{_N_FLEET_REQ} completed")
    inv["deaths_match_severed"] = (
        "ok" if artifacts["deaths"] == severed
        else f"FAIL: {artifacts['deaths']} replica deaths, plan severed "
             f"{severed} connections")
    inv["flight_dump_per_death"] = (
        "ok" if artifacts["dumps"] == artifacts["deaths"]
        else f"FAIL: {artifacts['dumps']} flight dumps for "
             f"{artifacts['deaths']} deaths")
    return inv


# --------------------------------------------------------------- lifecycle
def _run_lifecycle(workdir: str) -> dict:
    import numpy as np

    import xgboost_tpu as xtb
    from ..lifecycle import GateConfig, LifecycleConfig, LifecycleManager
    from ..serving.fleet import FleetConfig, ServingFleet
    from ..serving.modelstore import ModelStore

    bst, Q = _fleet_fixture()
    rng = np.random.default_rng(11)
    X2 = rng.standard_normal((300, 6)).astype(np.float32)
    y2 = (X2[:, 0] - X2[:, 2] > 0).astype(np.float32)
    cfg = FleetConfig(n_replicas=1, max_respawns=2, nthread_per_replica=1,
                      cache_dir=os.path.join(
                          tempfile.gettempdir(), "xtb_chaos_warm"))
    with ServingFleet({"m": bst}, cfg) as fleet:
        mgr = LifecycleManager(
            fleet, "m", config=LifecycleConfig(
                rounds_per_cycle=2,
                gate=GateConfig(min_improvement=-1e9)))
        report = mgr.run_cycle((X2, y2))
        served = np.ascontiguousarray(
            fleet.predict("m", Q, timeout=180), np.float32)
        active = fleet.active_version("m")
        expected = ModelStore(fleet.store_dir).booster("m", active).predict(
            xtb.DMatrix(Q))
    reason = "accepted" if report.swapped else report.decision.reason
    return {"digest": _digest(served.tobytes(), reason),
            "swapped": bool(report.swapped), "reason": reason,
            "serving_matches_active": bool(
                np.array_equal(served, np.asarray(expected, np.float32)))}


def _check_lifecycle(fired, artifacts, baseline) -> Dict[str, str]:
    inv = {}
    rejecting = sum(
        n for spec, n in fired
        if (spec.site in ("lifecycle.validate", "lifecycle.swap")
            and spec.kind == "exception")
        or (spec.site == "modelstore.publish" and spec.kind == "corrupt"))
    inv["serving_is_active_version"] = (
        "ok" if artifacts["serving_matches_active"]
        else "FAIL: fleet serves bytes that are not the active version's")
    if rejecting:
        inv["reject_fault_rejects"] = (
            "ok" if not artifacts["swapped"]
            else "FAIL: a reject-class fault fired but the swap went "
                 "through")
    else:
        inv["clean_cycle_swaps"] = (
            "ok" if artifacts["swapped"]
            else f"FAIL: no reject-class fault fired yet the cycle was "
                 f"rejected ({artifacts['reason']})")
    return inv


# ------------------------------------------------------------------ online
_N_ONLINE_BASE = 6      # 16-row requests of reference-distribution traffic
_N_ONLINE_SHIFT = 12    # 16-row requests of shifted traffic (forces drift)


def _run_online(workdir: str) -> dict:
    """The closed loop under fault: serve live traffic with feedback
    sampling on, join deterministic labels by trace id, let the drift
    detector trip on a distribution shift, and run the retrain cycle —
    all while the plan's faults fire at the join, the retrain decision,
    and the lifecycle gate."""
    import numpy as np

    import xgboost_tpu as xtb
    from ..lifecycle import GateConfig, LifecycleConfig
    from ..online import DriftConfig, OnlineConfig, OnlineScheduler
    from ..serving.fleet import FleetConfig, ServingFleet
    from ..serving.modelstore import ModelStore

    bst, Q = _fleet_fixture()
    rng = np.random.default_rng(23)
    blocks = [rng.standard_normal((16, 6)).astype(np.float32)
              for _ in range(_N_ONLINE_BASE)]
    blocks += [(rng.standard_normal((16, 6)) + 4.0).astype(np.float32)
               for _ in range(_N_ONLINE_SHIFT)]
    cfg = FleetConfig(n_replicas=1, max_respawns=2, nthread_per_replica=1,
                      cache_dir=os.path.join(
                          tempfile.gettempdir(), "xtb_chaos_warm"))
    with ServingFleet({"m": bst}, cfg) as fleet:
        sch = OnlineScheduler(fleet, "m", config=OnlineConfig(
            sample_every=1, join_horizon_s=600.0, min_retrain_rows=128,
            window_rows=4096, page_rows=64,
            spool_dir=os.path.join(workdir, "window"),
            drift=DriftConfig(min_rows=48, max_feature_ks=0.3),
            lifecycle=LifecycleConfig(
                rounds_per_cycle=2,
                gate=GateConfig(min_improvement=-1e9))))
        sch.enable()
        traces: List[str] = []
        completed = 0
        for rows in blocks:
            fut = fleet.submit("m", rows)
            traces.append(fut.trace_id)
            fut.result(timeout=180)
            completed += 1
        # feedback frames ride the replica socket BEHIND each result, so
        # the last one may still be in flight when the last predict
        # resolves — wait for the intake to settle before labeling
        deadline = time.monotonic() + 60.0
        while (sch.hub.stats()["offered"] < len(traces)
               and time.monotonic() < deadline):
            time.sleep(0.02)
        for tr, rows in zip(traces, blocks):
            sch.label(tr, (rows[:, 0] - rows[:, 2] > 0).astype(np.float32))
        out = sch.step()
        outcome = str(out["outcome"])
        join = sch.hub.stats()
        window_rows = len(sch.window)
        # sampling off BEFORE the verification serve: its requests must
        # not race fresh feedback frames into the join accounting
        sch.disable()
        served = np.ascontiguousarray(
            fleet.predict("m", Q, timeout=180), np.float32)
        active = fleet.active_version("m")
        expected = ModelStore(fleet.store_dir).booster("m", active).predict(
            xtb.DMatrix(Q))
    return {"digest": _digest(served.tobytes(), outcome,
                              json.dumps(join, sort_keys=True),
                              str(window_rows)),
            "completed": completed, "outcome": outcome,
            "swapped": outcome == "swapped",
            "drift_triggered": outcome not in ("idle", "deferred"),
            "join": join, "window_rows": window_rows,
            "serving_matches_active": bool(np.array_equal(
                served, np.asarray(expected, np.float32)))}


def _check_online(fired, artifacts, baseline) -> Dict[str, str]:
    inv = {}
    n_req = _N_ONLINE_BASE + _N_ONLINE_SHIFT
    rejecting = sum(n for spec, n in fired
                    if spec.kind == "exception"
                    and spec.site in ("online.retrain",
                                      "lifecycle.validate"))
    label_faults = sum(n for spec, n in fired
                       if spec.site == "online.label_join"
                       and spec.kind == "exception")
    inv["no_dropped_requests"] = (
        "ok" if artifacts["completed"] == n_req
        else f"FAIL: {artifacts['completed']}/{n_req} completed")
    inv["serving_is_active_version"] = (
        "ok" if artifacts["serving_matches_active"]
        else "FAIL: fleet serves bytes that are not the active version's")
    inv["drift_detected"] = (
        "ok" if artifacts["drift_triggered"]
        else f"FAIL: shifted traffic did not trip the drift edge "
             f"(outcome {artifacts['outcome']})")
    if rejecting:
        inv["reject_fault_rejects"] = (
            "ok" if not artifacts["swapped"]
            else "FAIL: a reject-class fault fired but the swap went "
                 "through")
    else:
        inv["clean_cycle_swaps"] = (
            "ok" if artifacts["swapped"]
            else f"FAIL: no reject-class fault fired yet the cycle did "
                 f"not swap ({artifacts['outcome']})")
    join = artifacts["join"]
    inv["label_fault_accounting"] = (
        "ok" if join["dropped"].get("fault", 0) == label_faults
        else f"FAIL: {join['dropped'].get('fault', 0)} labels dropped to "
             f"faults, plan fired {label_faults}")
    # the join's conservation law: every counted intake ends matched,
    # pending, or dropped (fault/untraced drops happen before counting)
    lhs = join["offered"] + join["labeled"]
    rhs = (2 * join["matched"]
           + join["pending_features"] + join["pending_labels"]
           + sum(v for k, v in join["dropped"].items()
                 if k not in ("fault", "untraced")))
    inv["join_conservation"] = (
        "ok" if lhs == rhs
        else f"FAIL: offered+labeled {lhs} != matched*2+pending+dropped "
             f"{rhs} ({join})")
    return inv


# ----------------------------------------------------------------- elastic
def _elastic_chaos_worker(rank, world, *, ckpt_dir, out_path, rounds,
                          num_shards):
    import numpy as np

    import xgboost_tpu as xtb
    from .. import collective as coll

    rng = np.random.default_rng(0)
    X = rng.normal(size=(1200, 5)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)

    def data_fn(smap, rank, world):
        rows = np.sort(np.concatenate(
            [np.arange(s, len(X), smap.num_shards)
             for s in smap.shards_of(rank)]))
        return xtb.DMatrix(X[rows], label=y[rows])

    cfg = xtb.ElasticConfig(data_fn, ckpt_dir, num_shards=num_shards)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                     "eta": 0.3, "max_bin": 32}, None, rounds, elastic=cfg,
                    verbose_eval=False)
    if coll.get_rank() == 0 and out_path:
        with open(out_path, "wb") as fh:
            fh.write(bytes(bst.save_raw()))


def _run_elastic(workdir: str) -> dict:
    import functools

    from ..launcher import run_distributed
    from .checkpoint import latest_checkpoint

    ckpt = os.path.join(workdir, "ck")
    out = os.path.join(workdir, "model.ubj")
    # the plan reaches the WORKERS via the launcher's env passthrough;
    # driver-side it fires nothing (the accounting invariant holds at 0)
    plan = faults.active()
    plan_json = (json.dumps({"faults": [dataclasses.asdict(s)
                                        for s in plan.specs]})
                 if plan is not None else None)
    run_distributed(
        functools.partial(_elastic_chaos_worker, ckpt_dir=ckpt,
                          out_path=out, rounds=6, num_shards=4),
        num_workers=2, platform="cpu", timeout=300, rendezvous="tracker",
        elastic=True, fault_plan=plan_json, max_respawns=0)
    st = latest_checkpoint(ckpt)
    with open(out, "rb") as fh:
        model = fh.read()
    return {"digest": _digest(model), "round": st.round if st else -1,
            "world": st.world if st else -1, "model_bytes": len(model)}


def _check_elastic(fired, artifacts, baseline) -> Dict[str, str]:
    inv = {}
    inv["finished_all_rounds"] = (
        "ok" if artifacts["round"] == 6
        else f"FAIL: finished at round {artifacts['round']}, wanted 6")
    inv["model_written"] = ("ok" if artifacts["model_bytes"] > 0
                            else "FAIL: rank 0 wrote no model")
    return inv


# ------------------------------------------------------------ tracker_kill
def _active_plan_json() -> Optional[str]:
    """The installed plan re-serialized for the launcher's env
    passthrough (driver-side it fires nothing — the subprocess scenarios'
    accounting invariant holds at 0)."""
    plan = faults.active()
    if plan is None:
        return None
    return json.dumps({"faults": [dataclasses.asdict(s)
                                  for s in plan.specs]})


def _run_tracker_kill(workdir: str) -> dict:
    import functools

    from ..launcher import run_distributed
    from .checkpoint import latest_checkpoint

    plan = faults.active()
    kills = sum(1 for s in (plan.specs if plan else [])
                if s.site == "tracker.journal" and s.kind == "kill")
    ckpt = os.path.join(workdir, "ck")
    out = os.path.join(workdir, "model.ubj")
    stats = run_distributed(
        functools.partial(_elastic_chaos_worker, ckpt_dir=ckpt,
                          out_path=out, rounds=6, num_shards=4),
        num_workers=2, platform="cpu", timeout=300, rendezvous="tracker",
        elastic=True, fault_plan=_active_plan_json(), max_respawns=0,
        tracker_failover=True)
    st = latest_checkpoint(ckpt)
    with open(out, "rb") as fh:
        model = fh.read()
    return {"digest": _digest(model), "round": st.round if st else -1,
            "world": st.world if st else -1,
            "respawns": int(stats["tracker_respawns"]),
            "pauses_s": [round(p, 3) for p in stats["tracker_pauses_s"]],
            "kills_scheduled": kills}


def _check_tracker_kill(fired, artifacts, baseline) -> Dict[str, str]:
    """The bitwise-vs-twin check (run_episode does it: twin=True) is the
    heart — a SIGKILL'd coordinator must not change one model bit."""
    inv = {}
    inv["finished_all_rounds"] = (
        "ok" if artifacts["round"] == 6
        else f"FAIL: finished at round {artifacts['round']}, wanted 6")
    inv["world_preserved"] = (
        "ok" if artifacts["world"] == 2
        else f"FAIL: world {artifacts['world']} != 2 — a tracker death "
             "must not cost a worker")
    inv["respawns_bounded"] = (
        "ok" if artifacts["respawns"] <= artifacts["kills_scheduled"]
        else f"FAIL: {artifacts['respawns']} tracker respawns for "
             f"{artifacts['kills_scheduled']} scheduled kills")
    if artifacts["kills_scheduled"]:
        inv["tracker_respawned"] = (
            "ok" if artifacts["respawns"] >= 1
            else "FAIL: a tracker kill was scheduled but no respawn "
                 "happened (the kill never fired?)")
    return inv


# ------------------------------------------------------------------- stall
_STALL_BUDGET_S = 1.5


def _run_stall(workdir: str) -> dict:
    import functools
    import glob

    from ..launcher import run_distributed
    from .checkpoint import latest_checkpoint

    plan = faults.active()
    stalls = sum(1 for s in (plan.specs if plan else [])
                 if s.kind == "delay" and s.site == "train.round"
                 and s.seconds >= 3.0 * _STALL_BUDGET_S)
    ckpt = os.path.join(workdir, "ck")
    out = os.path.join(workdir, "model.ubj")
    flight_dir = os.path.join(workdir, "flight")
    # tight budgets + a scenario-local flight dir, env-inherited by the
    # spawned workers; restored so later episodes (fleet, lifecycle) keep
    # the production defaults
    overrides = {
        "XGBOOST_TPU_FLIGHT_DIR": flight_dir,
        "XGBOOST_TPU_WATCHDOG_COLLECTIVE_WAIT_S": str(_STALL_BUDGET_S),
        "XGBOOST_TPU_WATCHDOG_TRACKER_JOIN_S": str(_STALL_BUDGET_S),
    }
    saved = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        stats = run_distributed(
            functools.partial(_elastic_chaos_worker, ckpt_dir=ckpt,
                              out_path=out, rounds=6, num_shards=4),
            num_workers=2, platform="cpu", timeout=200,
            rendezvous="tracker", elastic=True,
            fault_plan=_active_plan_json(), max_respawns=0)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    st = latest_checkpoint(ckpt)
    with open(out, "rb") as fh:
        model = fh.read()
    stacks = glob.glob(os.path.join(flight_dir, "stacks_*.txt"))
    return {"digest": _digest(model), "round": st.round if st else -1,
            "world": st.world if st else -1, "stacks": len(stacks),
            "tolerated": len(stats["tolerated"]),
            "stalls_scheduled": stalls}


def _check_stall(fired, artifacts, baseline) -> Dict[str, str]:
    """A delay past the watchdog budget must produce a stack dump and
    recovery through the elastic regroup — never a hang (the episode
    deadline, `no_hang`, is the other half of the contract)."""
    inv = {}
    inv["finished_all_rounds"] = (
        "ok" if artifacts["round"] == 6
        else f"FAIL: finished at round {artifacts['round']}, wanted 6")
    if artifacts["stalls_scheduled"]:
        inv["stack_dump_written"] = (
            "ok" if artifacts["stacks"] >= 1
            else "FAIL: a stall-class delay fired but the watchdog left "
                 "no faulthandler dump")
        inv["stalled_peer_declared_dead"] = (
            "ok" if artifacts["world"] == 1
            else f"FAIL: world {artifacts['world']} — the survivors did "
                 "not regroup past the stalled rank")
    else:
        inv["no_false_positive"] = (
            "ok" if artifacts["world"] == 2 and artifacts["stacks"] == 0
            else f"FAIL: no stall-class fault, yet world="
                 f"{artifacts['world']} stacks={artifacts['stacks']} — "
                 "the watchdog escalated a legitimately slow run")
    return inv


# ---------------------------------------------------------------- resource
def _run_resource(workdir: str) -> dict:
    """Paged training with checkpoints under resource exhaustion: the
    extmem episode's shape, but the catalog throws disk_full at the
    checkpoint commits, mem_pressure/fd_exhaust at the governor polls,
    and slow_disk at the page loads — the degradation-ladder contract is
    that the run COMPLETES with bitwise-identical model bytes and every
    ladder step is counted (docs/reliability.md "Resource pressure &
    graceful degradation")."""
    import numpy as np

    import xgboost_tpu as xtb
    from ..data.extmem import _zstd_available
    from . import resources as _resources
    from .checkpoint import CheckpointCallback, latest_checkpoint, scrub_dir

    _resources.reset()  # levels from a previous episode must not leak in
    degraded0 = {
        sub: _counter_labeled("xtb_resource_degraded_total", sub)
        for sub in ("checkpoint", "extmem", "journal")}
    errors0 = _counter_total("xtb_resource_errors_total")
    Xs, ys = _extmem_data()

    class _Iter(xtb.DataIter):
        def __init__(self):
            super().__init__()
            self.i = 0

        def next(self, input_data):
            if self.i >= len(Xs):
                return 0
            input_data(data=Xs[self.i], label=ys[self.i])
            self.i += 1
            return 1

        def reset(self):
            self.i = 0

    d = xtb.ExtMemQuantileDMatrix(_Iter(), max_bin=32, on_host=False,
                                  compress=_zstd_available())
    ckpt = os.path.join(workdir, "ckpt")
    import warnings as _warnings

    with _warnings.catch_warnings():
        # degradation is LOUD by design; the soak only needs the counters
        _warnings.simplefilter("ignore", RuntimeWarning)
        cb = CheckpointCallback(ckpt, interval=2)
        bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                         "max_bin": 32, "eta": 0.3}, d, 6,
                        callbacks=[cb], verbose_eval=False)
    scrub = scrub_dir(ckpt)
    state = latest_checkpoint(ckpt)
    preds = np.asarray(bst.predict(d), np.float64)
    degraded = {
        sub: _counter_labeled("xtb_resource_degraded_total", sub) - v0
        for sub, v0 in degraded0.items()}
    gov = _resources.get_governor()
    out = {"digest": _digest(bytes(bst.serialize()), preds.tobytes()),
           "ckpt_valid": len(scrub["valid"]),
           "ckpt_corrupt": len(scrub["corrupt"]),
           "ckpt_skipped": len(cb.skipped_rounds),
           "resumable": state is not None,
           "degraded": degraded,
           "errors_classified": _counter_total(
               "xtb_resource_errors_total") - errors0,
           "mem_level": gov.level("memory"),
           "fd_level": gov.level("fd")}
    _resources.reset()
    return out


def _check_resource(fired, artifacts, baseline) -> Dict[str, str]:
    inv = {}
    disk_hits = sum(n for spec, n in fired
                    if spec.site == "checkpoint.write"
                    and spec.kind == "disk_full")
    mem_hits = sum(n for spec, n in fired
                   if spec.site == "resource.pressure"
                   and spec.kind == "mem_pressure")
    fd_hits = sum(n for spec, n in fired
                  if spec.site == "resource.pressure"
                  and spec.kind == "fd_exhaust")
    deg = artifacts["degraded"]
    # every disk_full at a checkpoint commit is >= 1 ladder step
    # (pruned_to_1; +1 more when the retry also failed and the snapshot
    # was skipped), so steps ∈ [hits, 2*hits]
    inv["checkpoint_ladder_counted"] = (
        "ok" if disk_hits <= deg["checkpoint"] <= 2 * disk_hits
        else f"FAIL: {deg['checkpoint']} checkpoint ladder steps for "
             f"{disk_hits} injected disk_full hits")
    inv["no_corrupt_snapshots"] = (
        "ok" if artifacts["ckpt_corrupt"] == 0
        else f"FAIL: {artifacts['ckpt_corrupt']} corrupt checkpoints — "
             "a degraded save must commit whole or not at all")
    want_resumable = artifacts["ckpt_valid"] > 0
    inv["resume_fallback"] = (
        "ok" if artifacts["resumable"] == want_resumable
        else "FAIL: latest_checkpoint disagrees with the scrub walk")
    if mem_hits or fd_hits:
        inv["governor_engaged"] = (
            "ok" if (artifacts["mem_level"] > 0) == bool(mem_hits)
            and (artifacts["fd_level"] > 0) == bool(fd_hits)
            else f"FAIL: injected pressure (mem={mem_hits} fd={fd_hits}) "
                 f"but governor levels are mem={artifacts['mem_level']} "
                 f"fd={artifacts['fd_level']}")
        inv["errors_classified"] = (
            "ok" if fd_hits == 0 or artifacts["errors_classified"] >= fd_hits
            else "FAIL: injected fd_exhaust was not classified into "
                 "xtb_resource_errors_total")
    return inv


# ---------------------------------------------------------- fleet_degraded
_N_DEGRADED_BASE = 24
_N_DEGRADED_EXTRA = 8
_DEGRADED_BREAKER_S = 0.1
_DEGRADED_HB_TIMEOUT_S = 2.0
# respawn cycles + jitter stacking, not the 180s request timeout: the
# whole point of the degraded-network plane is that the tail is bounded
# by DETECTION budgets (heartbeat deadline, breaker cooldown, hedge)
_DEGRADED_P99_CAP_S = 8.0


def _run_fleet_degraded(workdir: str) -> dict:
    """A 2-SHARD fleet on a gray network: shard 0's first replica
    (``s0:replica0``) sees late frames (seeded per-frame jitter at its
    shard's ``wire.recv`` seam), shard 1's first replica
    (``s1:replica0``) goes half-open (its frames — pongs included —
    vanish inbound while its rx direction stays up).  Driver-side seams
    only, like the ``fleet`` scenario.  The contract: every request on
    BOTH shards completes with exact bits (twin=True digest — traffic
    alternates shard-pinned tenants), shard 0's EWMA breaker ejects the
    laggard and readmits it after cooldown, shard 1's liveness ladder
    (no pong AND no frame) declares the half-open replica and the
    respawn restores strength WITHIN shard 1 — each shard's
    degraded-network plane acts on its own state, neither disturbs the
    other (docs/reliability.md "Degraded networks", docs/serving.md
    "Sharded topology")."""
    import numpy as np

    from ..serving.fleet import FleetConfig, ServingFleet, shard_of

    plan = faults.active()
    cuts = sum(1 for s in (plan.specs if plan else [])
               if s.site == "wire.recv" and s.kind == "blackhole_rx")
    opened0 = _counter_labeled("xtb_net_breaker_transitions_total", "open")
    closed0 = _counter_labeled("xtb_net_breaker_transitions_total",
                               "closed")
    hedges0 = _counter_total("xtb_net_hedges_total")
    bst, Q = _fleet_fixture()
    cfg = FleetConfig(n_replicas=4, n_shards=2, max_respawns=4,
                      nthread_per_replica=1,
                      cache_dir=os.path.join(
                          tempfile.gettempdir(), "xtb_chaos_warm"),
                      heartbeat_s=0.25,
                      heartbeat_timeout_s=_DEGRADED_HB_TIMEOUT_S,
                      breaker_latency_s=_DEGRADED_BREAKER_S,
                      breaker_cooldown_s=0.5,
                      hedge_quantile=0.9, hedge_min_s=0.05)
    # deterministic shard-pinned tenants: request i alternates shards,
    # so the SAME i maps to the same (tenant, rows) in base and replay
    # passes — the digest and extras_match_base contracts need that
    tenant_for = [next(t for t in (f"g{j}" for j in range(64))
                       if shard_of("m", t, 2) == k) for k in (0, 1)]
    outs: List[bytes] = []
    lats: List[float] = []
    with ServingFleet({"m": bst}, cfg) as fleet:

        def _req(i: int) -> None:
            rows = Q[(i * 5) % 48: (i * 5) % 48 + 16]
            t = time.monotonic()
            # predict() raising = a dropped request = a red episode
            outs.append(np.ascontiguousarray(
                fleet.predict("m", rows, tenant=tenant_for[i % 2],
                              timeout=180), np.float32
            ).tobytes())
            lats.append(time.monotonic() - t)

        for i in range(_N_DEGRADED_BASE):
            _req(i)
        if cuts:
            # the liveness verdict is wall-clocked (no pong AND no other
            # frame past the deadline): hold the episode open until the
            # half-open replica is actually declared, bounded
            deadline = time.monotonic() + 20.0
            while (not fleet.flight_dumps
                   and time.monotonic() < deadline):
                time.sleep(0.1)
        for j in range(_N_DEGRADED_EXTRA):
            # the same rows as requests 0..N-1: the recovered fleet must
            # return the same bytes the degraded one did
            _req(j)
        if _counter_labeled("xtb_net_breaker_transitions_total",
                            "open") > opened0:
            # readmission is wall-clocked too (cooldown, then a
            # heartbeat probe): hold the episode open until the ejected
            # replica is readmitted, bounded
            deadline = time.monotonic() + 10.0
            while (_counter_labeled("xtb_net_breaker_transitions_total",
                                    "closed") <= closed0
                   and time.monotonic() < deadline):
                time.sleep(0.1)
        deaths = len(fleet.flight_dumps)
    ordered = sorted(lats)
    p99 = ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))]
    return {"digest": _digest(*outs), "completed": len(outs),
            "expected": _N_DEGRADED_BASE + _N_DEGRADED_EXTRA,
            "deaths": deaths, "cuts_scheduled": cuts,
            "p99_s": round(p99, 3),
            "lats": [round(x, 4) for x in lats],
            "extras_match_base": all(
                outs[_N_DEGRADED_BASE + j] == outs[j]
                for j in range(_N_DEGRADED_EXTRA)),
            "breaker_opened": _counter_labeled(
                "xtb_net_breaker_transitions_total", "open") - opened0,
            "breaker_closed": _counter_labeled(
                "xtb_net_breaker_transitions_total", "closed") - closed0,
            "hedges": _counter_total("xtb_net_hedges_total") - hedges0}


def _check_fleet_degraded(fired, artifacts, baseline) -> Dict[str, str]:
    inv = {}
    inv["no_dropped_requests"] = (
        "ok" if artifacts["completed"] == artifacts["expected"]
        else f"FAIL: {artifacts['completed']}/{artifacts['expected']} "
             "requests completed")
    inv["recovered_fleet_bitwise"] = (
        "ok" if artifacts["extras_match_base"]
        else "FAIL: post-recovery predictions differ from the same "
             "rows' pre-degradation bytes")
    inv["p99_bounded"] = (
        "ok" if artifacts["p99_s"] <= _DEGRADED_P99_CAP_S
        else f"FAIL: p99 {artifacts['p99_s']}s > {_DEGRADED_P99_CAP_S}s "
             "— the tail must be bounded by detection budgets, not the "
             "request timeout")
    if artifacts["cuts_scheduled"]:
        inv["half_open_replica_declared"] = (
            "ok" if artifacts["deaths"] >= 1
            else "FAIL: a blackhole_rx was scheduled but the liveness "
                 "ladder never declared the half-open replica")
    else:
        inv["no_false_death"] = (
            "ok" if artifacts["deaths"] == 0
            else f"FAIL: {artifacts['deaths']} replica deaths with no "
                 "rx cut scheduled — jitter alone must not kill")
    inv["deaths_bounded"] = (
        "ok" if artifacts["deaths"] <= 5   # 1 + max_respawns
        else f"FAIL: {artifacts['deaths']} deaths exceed the respawn "
             "budget + 1")
    lats = artifacts["lats"]
    # conditions under which an EWMA (alpha 0.2) trip is GUARANTEED:
    # the first-ever sample seeds the EWMA directly, and five
    # consecutive samples above 2x the threshold lift any EWMA past it
    # (0.2 * sum(0.8^i, i<5) = 0.672 > 0.5) — queue-wait-inflated
    # latencies only arise once a breaker is already open, so either
    # branch implies an `open` transition happened
    trip_certain = bool(lats) and (
        lats[0] > 2 * _DEGRADED_BREAKER_S
        or any(all(v > 2 * _DEGRADED_BREAKER_S for v in lats[i:i + 5])
               for i in range(len(lats) - 4)))
    if trip_certain:
        inv["breaker_ejected"] = (
            "ok" if artifacts["breaker_opened"] >= 1
            else "FAIL: sustained slow results yet the breaker never "
                 "opened")
    if artifacts["breaker_opened"]:
        inv["breaker_readmitted"] = (
            "ok" if artifacts["breaker_closed"] >= 1
            else "FAIL: the breaker opened but never readmitted the "
                 "replica after the link healed")
    return inv


# ----------------------------------------------------------- net_partition
def _run_net_partition(workdir: str) -> dict:
    """3-rank elastic training through an asymmetric partition: one
    rank's tracker-seam sends vanish (``blackhole_tx``) while its
    inbound stays live — the half-open wedge.  The relay's per-link
    deadline attributes the silence, declares the RANK (not its
    process: the peer is alive behind the cut), sends the
    ``declared_dead`` rejoin invitation, and holds the regroup open
    inside the readmission grace until the severed rank reconnects —
    world 3 is restored in the SAME regroup, no round ever commits at
    world 2, so the model must be bitwise-identical to the fault-free
    twin (run_episode's twin check; that is this scenario's heart)."""
    import functools
    import glob

    from ..launcher import run_distributed
    from .checkpoint import latest_checkpoint

    plan = faults.active()
    cuts = sum(1 for s in (plan.specs if plan else [])
               if s.site == "tracker.message" and s.kind == "blackhole_tx")
    readmit0 = _counter_labeled("xtb_net_readmissions_total", "readmitted")
    ckpt = os.path.join(workdir, "ck")
    out = os.path.join(workdir, "model.ubj")
    flight_dir = os.path.join(workdir, "flight")
    # a tight per-link deadline (the thing under test) + a frozen
    # telemetry cadence: the periodic registry ship rides the same
    # tracker.message seam on a wall clock, and suppressing it keeps
    # each worker's per-site invocation numbering deterministic — which
    # is what makes `at` a replayable partition onset
    overrides = {
        "XGBOOST_TPU_FLIGHT_DIR": flight_dir,
        "XGBOOST_TPU_LINK_TIMEOUT_S": "2.0",
        "XGBOOST_TPU_TELEMETRY_INTERVAL": "3600",
    }
    saved = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        run_distributed(
            functools.partial(_elastic_chaos_worker, ckpt_dir=ckpt,
                              out_path=out, rounds=6, num_shards=6),
            num_workers=3, platform="cpu", timeout=200,
            rendezvous="tracker", elastic=True,
            fault_plan=_active_plan_json(), max_respawns=0)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    st = latest_checkpoint(ckpt)
    with open(out, "rb") as fh:
        model = fh.read()
    stacks = glob.glob(os.path.join(flight_dir, "stacks_*.txt"))
    return {"digest": _digest(model), "round": st.round if st else -1,
            "world": st.world if st else -1, "stacks": len(stacks),
            "cuts_scheduled": cuts,
            "readmitted": _counter_labeled(
                "xtb_net_readmissions_total", "readmitted") - readmit0}


def _check_net_partition(fired, artifacts, baseline) -> Dict[str, str]:
    inv = {}
    inv["finished_all_rounds"] = (
        "ok" if artifacts["round"] == 6
        else f"FAIL: finished at round {artifacts['round']}, wanted 6")
    inv["world_restored"] = (
        "ok" if artifacts["world"] == 3
        else f"FAIL: world {artifacts['world']} != 3 — the half-open "
             "rank was not readmitted (rounds committed without it)")
    inv["no_watchdog_escalation"] = (
        "ok" if artifacts["stacks"] == 0
        else f"FAIL: {artifacts['stacks']} stack dumps — recovery must "
             "ride the link deadline, not the stall watchdog")
    if artifacts["cuts_scheduled"]:
        # the readmission counter lives in the DRIVER's registry (the
        # tracker runs in the driver process), so the grace window's
        # outcome is visible here even though the cut fires in a worker
        inv["readmitted_same_regroup"] = (
            "ok" if artifacts["readmitted"] >= 1
            else "FAIL: a partition was scheduled but no rank was "
                 "readmitted inside the grace window")
    return inv


def _pin_kill_at(spec: dict) -> dict:
    # a {rank, round} kill re-fires when a survivor inherits the rank and
    # redoes the round (docs/reliability.md, the elastic sharp edge):
    # pin `at` to the round so it fires exactly once per process
    spec["at"] = spec["round"]
    return spec


SCENARIOS: Dict[str, Scenario] = {
    "extmem": Scenario(
        name="extmem",
        catalog=(
            CatalogEntry("extmem.page_decode", "corrupt", {"at": (0, 3)}),
            CatalogEntry("extmem.page_load", "delay",
                         {"seconds": (0.001, 0.02), "at": (0, 6)}),
            CatalogEntry("checkpoint.write", "truncate",
                         {"round": [2, 4, 6]}),
            CatalogEntry("checkpoint.write", "corrupt",
                         {"round": [2, 4, 6]}),
            CatalogEntry("train.round", "delay",
                         {"seconds": (0.001, 0.01), "round": (0, 6)}),
        ),
        run=_run_extmem, check=_check_extmem, twin=True,
        cost_hint_s=4.0, deadline_s=120.0,
        per_plan_caps={("extmem.page_decode", "corrupt"): 1}),
    "fleet": Scenario(
        name="fleet",
        catalog=(
            CatalogEntry("fleet.dispatch", "drop_connection",
                         {"at": (0, 20)}),
            CatalogEntry("fleet.dispatch", "delay",
                         {"seconds": (0.001, 0.05), "at": (0, 20)}),
            CatalogEntry("wire.frame", "corrupt", {"at": (0, 20)}),
        ),
        run=_run_fleet, check=_check_fleet, twin=True,
        cost_hint_s=25.0, deadline_s=300.0, max_faults=3),
    "lifecycle": Scenario(
        name="lifecycle",
        catalog=(
            CatalogEntry("lifecycle.validate", "exception", {}),
            CatalogEntry("lifecycle.swap", "exception", {}),
            # at=1: the SECOND publish in the episode is the cycle's
            # candidate (the first is fleet bringup publishing the
            # incumbent, whose corruption is the attach-gate's test, not
            # this scenario's — a refused incumbent fails bringup loudly)
            CatalogEntry("modelstore.publish", "corrupt", {"at": [1]}),
            CatalogEntry("lifecycle.validate", "delay",
                         {"seconds": (0.001, 0.05)}),
            CatalogEntry("fleet.dispatch", "delay",
                         {"seconds": (0.001, 0.03), "at": (0, 3)}),
        ),
        run=_run_lifecycle, check=_check_lifecycle, twin=False,
        cost_hint_s=25.0, deadline_s=300.0),
    "online": Scenario(
        name="online",
        catalog=(
            # driver-side seams only: faults.install() does not export
            # the plan to replica subprocess env, and the fault-
            # accounting invariant counts the driver's registry
            CatalogEntry("online.label_join", "exception",
                         {"at": (0, _N_ONLINE_BASE + _N_ONLINE_SHIFT)}),
            CatalogEntry("online.retrain", "exception", {}),
            CatalogEntry("online.retrain", "delay",
                         {"seconds": (0.001, 0.05)}),
            CatalogEntry("lifecycle.validate", "exception", {}),
            CatalogEntry("fleet.dispatch", "delay",
                         {"seconds": (0.001, 0.03),
                          "at": (0, _N_ONLINE_BASE + _N_ONLINE_SHIFT)}),
        ),
        run=_run_online, check=_check_online, twin=False,
        cost_hint_s=30.0, deadline_s=300.0,
        # bounded label loss: each join fault costs one 16-row block,
        # and the window floor (128 of 288 rows) must stay reachable
        per_plan_caps={("online.label_join", "exception"): 2}),
    "elastic": Scenario(
        name="elastic",
        catalog=(
            CatalogEntry("train.round", "kill",
                         {"rank": [1], "round": [2, 3]}, post=_pin_kill_at),
            CatalogEntry("train.round", "delay",
                         {"seconds": (0.001, 0.02), "rank": [0],
                          "round": (0, 5)}),
            CatalogEntry("collective.allreduce", "delay",
                         {"seconds": (0.001, 0.01), "at": (0, 30)}),
        ),
        run=_run_elastic, check=_check_elastic, twin=False,
        cost_hint_s=45.0, deadline_s=300.0),
    "tracker_kill": Scenario(
        name="tracker_kill",
        catalog=(
            # at=0 dies at the roster write (right after rendezvous),
            # at=1 at the first progress write — both mid-job; the kill
            # fires in the TRACKER subprocess (the launcher clears the
            # plan env for respawns, so successors survive)
            CatalogEntry("tracker.journal", "kill", {"at": [0, 1]}),
            CatalogEntry("train.round", "delay",
                         {"seconds": (0.2, 0.5), "times": [4, 8]}),
            CatalogEntry("collective.allreduce", "delay",
                         {"seconds": (0.001, 0.01), "at": (0, 30)}),
        ),
        run=_run_tracker_kill, check=_check_tracker_kill, twin=True,
        cost_hint_s=50.0, deadline_s=300.0, max_faults=3,
        per_plan_caps={("tracker.journal", "kill"): 2}),
    "resource": Scenario(
        name="resource",
        catalog=(
            # ENOSPC at a checkpoint commit: times=1 heals on the pruned
            # retry, times=2 skips the snapshot — both must stay bitwise
            CatalogEntry("checkpoint.write", "disk_full",
                         {"round": [2, 4, 6], "times": [1, 2]}),
            CatalogEntry("checkpoint.write", "slow_disk",
                         {"seconds": (0.001, 0.05), "round": (1, 7)}),
            CatalogEntry("resource.pressure", "mem_pressure",
                         {"at": (0, 6)}),
            CatalogEntry("resource.pressure", "fd_exhaust",
                         {"at": (0, 6)}),
            CatalogEntry("extmem.page_load", "slow_disk",
                         {"seconds": (0.001, 0.02), "at": (0, 6)}),
        ),
        run=_run_resource, check=_check_resource, twin=True,
        cost_hint_s=4.0, deadline_s=120.0),
    "stall": Scenario(
        name="stall",
        catalog=(
            # a delay far past the scenario's 1.5s watchdog budgets: the
            # collective-wait guard dumps + severs, the tracker's join
            # ladder declares the sleeper dead, the survivors regroup —
            # dump + recovery, never a deadline red
            CatalogEntry("train.round", "delay",
                         {"seconds": (6.0, 9.0), "rank": [1],
                          "round": [2, 3]}, post=_pin_kill_at),
            # benign: well under budget — must NOT trip the ladder
            CatalogEntry("train.round", "delay",
                         {"seconds": (0.05, 0.3), "rank": [0],
                          "round": (0, 5), "times": [1, 3]}),
            CatalogEntry("watchdog.escalate", "delay",
                         {"seconds": (0.01, 0.05)}),
        ),
        run=_run_stall, check=_check_stall, twin=False,
        cost_hint_s=40.0, deadline_s=240.0, max_faults=3),
    "fleet_degraded": Scenario(
        name="fleet_degraded",
        catalog=(
            # driver-side seams only (like `fleet`), on a 2-SHARD fleet:
            # shard 0's rx path for its first replica jitters, shard 1's
            # first replica's inbound frames — pongs included — vanish.
            # The rank filters are disjoint (full shard-prefixed
            # labels), so neither spec starves the other's invocations,
            # and each shard's degradation plane is exercised alone
            CatalogEntry("wire.recv", "latency",
                         {"rank": ["s0:replica0"], "seconds": (0.3, 0.6),
                          "times": [3, 4, 5],
                          "jitter_seed": (0, 1 << 16)}),
            CatalogEntry("wire.recv", "blackhole_rx",
                         {"rank": ["s1:replica0"], "times": [40]}),
            CatalogEntry("wire.frame", "throttle",
                         {"rank": ["s0:replica0"],
                          "bytes_per_s": (1e5, 4e5), "times": [2, 4]}),
        ),
        run=_run_fleet_degraded, check=_check_fleet_degraded, twin=True,
        cost_hint_s=30.0, deadline_s=300.0, max_faults=3,
        # one jitter window and one half-open link per episode: a second
        # latency spec would stack past the p99 cap, a second rx cut
        # would double the respawn budget the deaths bound assumes
        per_plan_caps={("wire.recv", "latency"): 1,
                       ("wire.recv", "blackhole_rx"): 1}),
    "net_partition": Scenario(
        name="net_partition",
        catalog=(
            # the cut: one rank's sends vanish mid-training.  `at` is
            # the worker's tracker.message invocation index (start
            # handshake, coll_join, then contributes — deterministic
            # with periodic telemetry frozen), so 6..15 lands on a
            # contribute send.  The flavor specs below budget at most
            # 3+3 claimed invocations (0..5), so they can never starve
            # the cut's pinned invocation
            CatalogEntry("tracker.message", "blackhole_tx",
                         {"rank": [1, 2], "at": (6, 16), "times": [1]}),
            CatalogEntry("tracker.message", "latency",
                         {"seconds": (0.05, 0.2), "times": [2, 3],
                          "jitter_seed": (0, 1 << 16)}),
            CatalogEntry("tracker.message", "throttle",
                         {"bytes_per_s": (2e5, 8e5), "times": [2, 3]}),
        ),
        run=_run_net_partition, check=_check_net_partition, twin=True,
        cost_hint_s=60.0, deadline_s=300.0, max_faults=3,
        # one asymmetric cut per episode: two simultaneous cuts could
        # leave a lone survivor wedged on both links at once
        per_plan_caps={("tracker.message", "blackhole_tx"): 1,
                       ("tracker.message", "latency"): 1,
                       ("tracker.message", "throttle"): 1}),
}


# ---------------------------------------------------------------------------
# episode runner + soak driver
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class EpisodeReport:
    scenario: str
    seed: int
    plan: dict
    ok: bool
    hung: bool
    seconds: float
    invariants: Dict[str, str]
    artifacts: Dict[str, Any] = dataclasses.field(default_factory=dict)
    error: str = ""

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @property
    def repro(self) -> str:
        return (f"python scripts/chaos_soak.py --replay {self.scenario} "
                f"{self.seed}")


_BASELINES: Dict[str, dict] = {}


def _baseline(sc: Scenario) -> Optional[dict]:
    """The fault-free twin: the SAME runner with no plan installed, once
    per scenario per process."""
    if not sc.twin:
        return None
    if sc.name not in _BASELINES:
        assert faults.active() is None, \
            "baseline must run with no fault plan installed"
        with tempfile.TemporaryDirectory(prefix="xtb_chaos_base_") as wd:
            _BASELINES[sc.name] = sc.run(wd)
    return _BASELINES[sc.name]


def run_episode(scenario: str, seed: int, *,
                n_faults: Optional[int] = None,
                deadline_s: Optional[float] = None,
                plan: Optional[dict] = None) -> EpisodeReport:
    """One composed-fault episode: generate the seeded plan, run the
    scenario under the deadline, check every invariant.  Replayable by
    construction — see the module docstring.  ``plan`` overrides the
    seeded schedule (hand-written repros; the seed then only labels the
    report)."""
    sc = SCENARIOS[scenario]
    deadline = float(deadline_s if deadline_s is not None
                     else sc.deadline_s)
    plan_dict = plan if plan is not None \
        else generate_plan(scenario, seed, n_faults)
    baseline = _baseline(sc)  # before the plan installs: twin is fault-free

    counted_before = _counter_total("xtb_faults_injected_total")
    lockdep_before = len(lockdep.reports()) if lockdep.enabled() else 0
    plan = faults.install(json.loads(json.dumps(plan_dict)))
    outcome: Dict[str, Any] = {}
    t0 = time.monotonic()
    body = threading.Thread(
        target=lambda: outcome.update(_safe_run(sc)), daemon=True,
        name=f"xtb-chaos-{scenario}-{seed}")
    body.start()
    body.join(deadline)
    hung = body.is_alive()
    seconds = time.monotonic() - t0
    fired = plan.fired()
    fired_specs = plan.fired_by_spec()
    faults.clear()
    from . import resources as _resources

    # governor levels must not leak across episodes: a mem_pressure from
    # a resource episode would brown out the NEXT fleet episode's
    # requests (an un-replayable red)
    _resources.reset()
    counted_delta = _counter_total("xtb_faults_injected_total") \
        - counted_before

    invariants: Dict[str, str] = {}
    invariants["no_hang"] = (
        "ok" if not hung
        else f"FAIL: episode still running after {deadline}s deadline")
    error = str(outcome.get("error", ""))
    invariants["completed"] = (
        "ok" if not error and not hung
        else f"FAIL: {error or 'deadline'}")
    invariants["fault_accounting"] = (
        "ok" if counted_delta == fired
        else f"FAIL: xtb_faults_injected_total moved {counted_delta}, "
             f"plan fired {fired}")
    if lockdep.enabled():
        # the witness must stay silent under fire: fault-path code taking
        # locks out of order or across seams is exactly what chaos exists
        # to flush out
        leaked = lockdep.reports()[lockdep_before:]
        invariants["lockdep_silent"] = (
            "ok" if not leaked
            else "FAIL: " + "; ".join(
                f"[{r['kind']}] {r['msg']}" for r in leaked[:4]))
    artifacts = outcome.get("artifacts") or {}
    if sc.twin and baseline is not None and not error and not hung:
        invariants["bitwise_vs_twin"] = (
            "ok" if artifacts.get("digest") == baseline.get("digest")
            else "FAIL: result digest differs from the fault-free twin")
    if artifacts and not hung:
        invariants.update(sc.check(fired_specs, artifacts, baseline))
    ok = all(v == "ok" for v in invariants.values())
    episodes, ep_seconds = _ins()
    episodes.labels(scenario, "green" if ok else "red").inc()
    ep_seconds.labels(scenario).observe(seconds)
    return EpisodeReport(scenario=scenario, seed=int(seed), plan=plan_dict,
                         ok=ok, hung=hung, seconds=seconds,
                         invariants=invariants, artifacts=artifacts,
                         error=error)


def _safe_run(sc: Scenario) -> dict:
    with tempfile.TemporaryDirectory(prefix="xtb_chaos_") as wd:
        try:
            return {"artifacts": sc.run(wd)}
        except BaseException as e:  # red episode, not a dead soak
            return {"error": f"{type(e).__name__}: {e}"}


def soak(master_seed: int, *, budget_s: float = 120.0,
         min_episodes: int = 20,
         scenarios: Optional[List[str]] = None,
         replay_check: bool = True) -> Dict[str, Any]:
    """Round-robin episodes across ``scenarios`` until the budget is spent
    AND at least ``min_episodes`` ran; when the remaining budget cannot
    afford the next scenario in the rotation, the cheapest one fills the
    tail (never silently: the report carries a ``downgraded`` count).
    Ends with a replay of the first episode's seed, requiring an
    identical schedule and outcome — the determinism half of the chaos
    contract, checked on every soak, not just in tests."""
    names = list(scenarios or SCENARIOS)
    for n in names:
        if n not in SCENARIOS:
            raise ValueError(f"unknown chaos scenario {n!r}; "
                             f"known: {sorted(SCENARIOS)}")
    cheapest = min(names, key=lambda n: SCENARIOS[n].cost_hint_s)
    reports: List[EpisodeReport] = []
    downgraded = 0
    t0 = time.monotonic()
    i = 0
    while True:
        elapsed = time.monotonic() - t0
        if len(reports) >= min_episodes and elapsed >= budget_s:
            break
        pick = names[i % len(names)]
        if (SCENARIOS[pick].cost_hint_s > budget_s - elapsed
                and pick != cheapest):
            if len(reports) >= min_episodes:
                # the rotation's next scenario no longer fits and the
                # floor is met: stop, rather than spinning the remaining
                # budget away on the cheapest scenario
                break
            pick = cheapest
            downgraded += 1
        seed = (int(master_seed) * 1000003 + i) & 0x7FFFFFFF
        rep = run_episode(pick, seed)
        reports.append(rep)
        if rep.hung:
            break  # the stuck thread cannot be reclaimed: stop, report red
        i += 1
    replay = None
    if replay_check and reports and not reports[0].hung:
        first = reports[0]
        again = run_episode(first.scenario, first.seed)
        replay = {
            "scenario": first.scenario, "seed": first.seed,
            "schedule_identical": again.plan == first.plan,
            "outcome_identical": (
                again.ok == first.ok
                and again.artifacts.get("digest")
                == first.artifacts.get("digest")),
        }
        reports.append(again)
    green = sum(1 for r in reports if r.ok)
    return {
        "master_seed": int(master_seed),
        "budget_s": budget_s,
        "episodes": [r.to_json() for r in reports],
        "green": green,
        "red": len(reports) - green,
        "downgraded": downgraded,
        "replay": replay,
        "ok": (green == len(reports)
               and (replay is None
                    or (replay["schedule_identical"]
                        and replay["outcome_identical"]))),
        "wall_s": time.monotonic() - t0,
    }
