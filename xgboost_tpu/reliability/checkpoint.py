"""Crash-safe training checkpoints: atomic write, keep-last-K, corruption
fallback, and the :class:`CheckpointCallback` / resume glue.

The failure model is the Rabit lineage's (XGBoost paper §5: workers die and
come back; recovery = last committed model + round counter): a worker can be
killed at ANY instruction — including halfway through writing a checkpoint —
and a relaunch must find a usable snapshot.  Three mechanisms:

1. **Atomic commit.**  Each checkpoint is written to a same-directory temp
   file, flushed, ``fsync``-ed, then ``os.replace``-d into place (and the
   directory fsync-ed), so a crash leaves either the old set or the new
   file, never a half-written one under the final name.
2. **Self-validating format.**  ``XTBCKPT1`` magic + length-prefixed JSON
   meta + the ``Booster.serialize()`` payload + a trailing SHA-256 over
   everything before it.  Truncation, bit rot, or a torn write all fail the
   checksum and the file is *skipped with a warning*, not trusted.
3. **Keep-last-K fallback.**  ``load_latest`` walks newest → oldest and
   returns the first valid snapshot, so one corrupt file costs K-1 rounds
   of progress, not the run.

What a checkpoint carries is the full *training* state, not just the model:
the serialized Booster (model + config), the completed-round counter, the
eval history, and per-callback state (e.g. EarlyStopping's best/patience),
so ``train(..., resume_from=dir)`` continues bit-identically to a run that
was never interrupted (tests/test_reliability.py holds the parity).

Telemetry: ``xtb_checkpoint_seconds`` (write latency histogram),
``xtb_checkpoints_total``, ``xtb_checkpoint_corrupt_total``.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import struct
import tempfile
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..callback import TrainingCallback
from . import faults
from . import resources as _resources

__all__ = ["CheckpointManager", "CheckpointCallback", "CheckpointState",
           "latest_checkpoint", "scrub_dir", "collect_callback_state",
           "restore_callback_state"]

_MAGIC = b"XTBCKPT1"
_SUFFIX = ".xtbckpt"
_DIGEST = hashlib.sha256
_DIGEST_LEN = 32

_instruments = None  # (seconds hist, saved counter, corrupt counter)


def _ins():
    global _instruments
    if _instruments is None:
        from ..telemetry.registry import get_registry

        reg = get_registry()
        _instruments = (
            reg.histogram("xtb_checkpoint_seconds",
                          "checkpoint write latency"),
            reg.counter("xtb_checkpoints_total", "checkpoints committed"),
            reg.counter("xtb_checkpoint_corrupt_total",
                        "invalid checkpoint files skipped at load"),
        )
    return _instruments


@dataclasses.dataclass
class CheckpointState:
    """One decoded checkpoint.

    ``world`` and ``shard_map`` (meta version 2) carry the elastic
    membership at save time: the world size the checkpoint was written
    under and the shard→rank data assignment
    (:class:`~xgboost_tpu.elastic.ShardMap` dict form), so a regrouped
    survivor or a replacement worker can rebuild exactly the data it now
    owns.  Version-1 files (pre-elastic) decode with both as ``None``."""

    round: int                      # completed boosting rounds
    booster_bytes: bytes            # Booster.serialize() payload
    history: Dict[str, Any]         # CallbackContainer.history at save time
    callback_state: Dict[str, Any]  # {"ClassName@i": state_dict()}
    path: str = ""
    world: Optional[int] = None          # world size at save (v2)
    shard_map: Optional[Dict[str, Any]] = None  # ShardMap.to_dict() (v2)


# newest meta version written; every version in _READ_VERSIONS still loads
# (the pre-elastic v1 fallback is pinned by tests/test_elastic.py)
_META_VERSION = 2
_READ_VERSIONS = (1, 2)


def _encode(state: CheckpointState) -> bytes:
    meta = json.dumps({
        "version": _META_VERSION,
        "round": int(state.round),
        "booster_len": len(state.booster_bytes),
        "history": state.history,
        "callback_state": state.callback_state,
        "world": state.world,
        "shard_map": state.shard_map,
    }).encode()
    body = (_MAGIC + struct.pack(">I", len(meta)) + meta
            + bytes(state.booster_bytes))
    return body + _DIGEST(body).digest()


def _decode(blob: bytes, path: str = "") -> CheckpointState:
    """Raises ValueError on ANY structural or integrity problem."""
    if len(blob) < len(_MAGIC) + 4 + _DIGEST_LEN:
        raise ValueError("checkpoint too short")
    if blob[: len(_MAGIC)] != _MAGIC:
        raise ValueError("bad checkpoint magic")
    body, digest = blob[:-_DIGEST_LEN], blob[-_DIGEST_LEN:]
    if _DIGEST(body).digest() != digest:
        raise ValueError("checkpoint checksum mismatch")
    (meta_len,) = struct.unpack(">I", blob[len(_MAGIC): len(_MAGIC) + 4])
    meta_start = len(_MAGIC) + 4
    if meta_start + meta_len > len(body):
        raise ValueError("checkpoint meta length out of range")
    meta = json.loads(body[meta_start: meta_start + meta_len].decode())
    version = int(meta.get("version", 1))
    if version not in _READ_VERSIONS:
        # a future format this reader cannot interpret: skip to the
        # next-newest file (load_latest's corruption-fallback path)
        raise ValueError(f"unsupported checkpoint meta version {version}")
    booster = body[meta_start + meta_len:]
    if len(booster) != int(meta["booster_len"]):
        raise ValueError("checkpoint booster payload length mismatch")
    world = meta.get("world")
    return CheckpointState(
        round=int(meta["round"]), booster_bytes=booster,
        history=meta.get("history", {}),
        callback_state=meta.get("callback_state", {}), path=path,
        world=int(world) if world is not None else None,
        shard_map=meta.get("shard_map"))


class CheckpointManager:
    """Atomic keep-last-K checkpoint files under one directory."""

    def __init__(self, directory: str, keep_last: int = 3) -> None:
        self.directory = os.fspath(directory)
        self.keep_last = max(int(keep_last), 1)
        os.makedirs(self.directory, exist_ok=True)

    # -------------------------------------------------------------- write
    def _path(self, round: int) -> str:
        return os.path.join(self.directory, f"ckpt_{round:08d}{_SUFFIX}")

    def save(self, state: CheckpointState) -> str:
        """Atomically commit ``state`` as the round-``state.round``
        checkpoint and prune beyond ``keep_last``.  Returns the path."""
        t0 = time.perf_counter()
        blob = _encode(state)
        final = self._path(state.round)
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as fh:
                fh.write(blob)
                fh.flush()
                os.fsync(fh.fileno())
            # fault seam: a torn write — the file commits under its final
            # name but the tail never hit the disk (what a crash between
            # write() and fsync() can leave on weaker filesystems); or a
            # bit flip between encode and disk (``corrupt``).  The
            # trailing SHA-256 makes load_latest skip either.
            spec = faults.maybe_inject("checkpoint.write", round=state.round)
            if spec is not None and spec.kind == "truncate":
                keep = (spec.keep_bytes if spec.keep_bytes is not None
                        else len(blob) // 2)
                with open(tmp, "r+b") as fh:
                    fh.truncate(max(int(keep), 0))
            elif spec is not None and spec.kind == "corrupt":
                with open(tmp, "wb") as fh:
                    fh.write(faults.corrupt_bytes(blob, spec))
            os.replace(tmp, final)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError as ue:
                _resources.note_os_error(ue, "checkpoint.cleanup")
            raise
        self._fsync_dir()
        self.prune()
        hist, saved, _corrupt = _ins()
        hist.observe(time.perf_counter() - t0)
        saved.inc()
        return final

    def _fsync_dir(self) -> None:
        try:
            dfd = os.open(self.directory, os.O_RDONLY)
        except OSError as e:  # platform without directory fds
            _resources.note_os_error(e, "checkpoint.fsync_dir")
            return
        try:
            os.fsync(dfd)
        except OSError as e:
            _resources.note_os_error(e, "checkpoint.fsync_dir")
        finally:
            os.close(dfd)

    def prune(self, keep: Optional[int] = None) -> None:
        """Delete checkpoints beyond the newest ``keep`` (default
        ``keep_last``).  ``keep=1`` is the disk-pressure ladder's
        aggressive step: free everything but the newest snapshot so the
        retry after an ENOSPC has room to commit."""
        keep = self.keep_last if keep is None else max(int(keep), 1)
        for path in self.files()[: -keep]:
            try:
                os.unlink(path)
            except OSError as e:
                _resources.note_os_error(e, "checkpoint.prune")

    # --------------------------------------------------------------- read
    def files(self) -> List[str]:
        """Checkpoint paths sorted oldest → newest by round number."""
        out = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        except OSError as e:
            _resources.note_os_error(e, "checkpoint.list")
            return []
        for name in names:
            if name.startswith("ckpt_") and name.endswith(_SUFFIX):
                out.append(os.path.join(self.directory, name))
        return sorted(out)

    def load_latest(self) -> Optional[CheckpointState]:
        """Newest VALID checkpoint, or None.  Corrupt/truncated/zero-byte
        files are skipped with a warning (and counted), falling back to the
        next-newest — the keep-last-K contract."""
        for path in reversed(self.files()):
            try:
                with open(path, "rb") as fh:
                    state = _decode(fh.read(), path=path)
                state.path = path
                return state
            except (OSError, ValueError, KeyError, TypeError,
                    json.JSONDecodeError, struct.error,
                    UnicodeDecodeError) as e:
                _ins()[2].inc()
                warnings.warn(
                    f"skipping invalid checkpoint {path!r}: {e}",
                    RuntimeWarning, stacklevel=2)
        return None


def latest_checkpoint(directory: str) -> Optional[CheckpointState]:
    """Newest valid checkpoint under ``directory`` (None when the directory
    is missing or holds no usable checkpoint)."""
    if not os.path.isdir(directory):
        return None
    return CheckpointManager(directory).load_latest()


def scrub_dir(directory: str) -> Dict[str, List[str]]:
    """Proactive checkpoint-directory scrub: run every ``.xtbckpt`` file
    through the same XTBCKPT magic/structure/SHA-256 walk ``load_latest``
    uses (one decoder — a format change cannot make the scrubber and the
    loader disagree).  Returns ``{"valid": [paths], "corrupt": [paths]}``;
    corrupt files count into ``xtb_checkpoint_corrupt_total`` AND
    ``xtb_integrity_corrupt_total{boundary="checkpoint"}``, the pass into
    ``xtb_integrity_scrub_total{target="checkpoint"}``.  Read-only: a
    corrupt file is *reported*, not deleted — keep-last-K pruning and the
    load-time fallback already bound its blast radius."""
    from . import integrity as _integrity

    valid: List[str] = []
    corrupt: List[str] = []
    for path in CheckpointManager(directory).files():
        try:
            with open(path, "rb") as fh:
                _decode(fh.read(), path=path)
            valid.append(path)
        except (OSError, ValueError, KeyError, TypeError,
                json.JSONDecodeError, struct.error, UnicodeDecodeError):
            corrupt.append(path)
            _ins()[2].inc()
            _integrity.corrupt_detected("checkpoint")
    _integrity.scrubbed("checkpoint")
    return {"valid": valid, "corrupt": corrupt}


# ---------------------------------------------------------------------------
# callback-state capture/restore (EarlyStopping best/patience etc.)
# ---------------------------------------------------------------------------


def _state_keys(callbacks: Sequence[TrainingCallback]
                ) -> List[Tuple[str, TrainingCallback]]:
    """Stable per-run keys: class name + index among same-class callbacks
    (train() rebuilds the same callback list on relaunch, so keys line up)."""
    seen: Dict[str, int] = {}
    out = []
    for cb in callbacks:
        name = type(cb).__name__
        idx = seen.get(name, 0)
        seen[name] = idx + 1
        out.append((f"{name}@{idx}", cb))
    return out


def collect_callback_state(callbacks: Sequence[TrainingCallback]
                           ) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, cb in _state_keys(callbacks):
        fn = getattr(cb, "state_dict", None)
        if fn is None:
            continue
        state = fn()
        if state is not None:
            out[key] = state
    return out


def restore_callback_state(callbacks: Sequence[TrainingCallback],
                           saved: Dict[str, Any]) -> None:
    for key, cb in _state_keys(callbacks):
        state = saved.get(key)
        fn = getattr(cb, "load_state", None)
        if state is not None and fn is not None:
            fn(state)


class CheckpointCallback(TrainingCallback):
    """Persist the Booster + training state every ``interval`` rounds.

    Unlike :class:`~xgboost_tpu.callback.TrainingCheckPoint` (model-only,
    non-atomic, unbounded file count), this writes the crash-safe format
    above and is the counterpart of ``train(..., resume_from=dir)``.  Under
    multi-process training only rank 0 writes by default — trees are
    bitwise-identical across ranks, so one snapshot serves every worker on
    a shared filesystem (the Rabit CheckPoint contract)."""

    # train() dispatches run-last callbacks after the rest: the snapshot
    # must capture THIS round's EarlyStopping decision (best/patience) and
    # booster attrs, not last round's — train() appends EarlyStopping
    # after user callbacks, so without the reorder a resume would replay
    # a one-round-stale stopping state
    _run_last = True

    def __init__(self, directory: str, interval: int = 1,
                 keep_last: int = 3, only_rank0: bool = True,
                 shard_map: Optional[Dict[str, Any]] = None) -> None:
        self.manager = CheckpointManager(directory, keep_last=keep_last)
        self.interval = max(int(interval), 1)
        self.only_rank0 = only_rank0
        self.last_saved_round: Optional[int] = None
        # elastic shard ownership (ShardMap.to_dict()): set/refreshed by
        # train(..., elastic=...) so every checkpoint records who owned
        # which data shards — the recovery and absorption source of truth
        self.shard_map: Optional[Dict[str, Any]] = shard_map
        self._container = None  # bound by train() for history + peer state
        # rounds whose snapshot was skipped on the disk-pressure ladder
        # (pruned-retry also failed): training continued, this records
        # the durability gap (tests + resource_smoke assert on it)
        self.skipped_rounds: list = []

    def _bind_container(self, container) -> None:
        self._container = container

    def after_iteration(self, model, epoch: int, evals_log) -> bool:
        from .. import collective

        # governor tick: one deterministic poll per round — the
        # resource.pressure seam fires here, and real headroom on the
        # checkpoint directory is measured (rate-limited)
        _resources.get_governor().poll(self.manager.directory)
        if (epoch + 1) % self.interval:
            return False
        if self.only_rank0 and collective.get_rank() != 0:
            return False
        if not hasattr(model, "serialize"):  # cv aggregate stand-in
            return False
        peers = (self._container.callbacks if self._container is not None
                 else [self])
        state = CheckpointState(
            round=model.num_boosted_rounds(),
            booster_bytes=bytes(model.serialize()),
            history=evals_log if evals_log is not None else {},
            callback_state=collect_callback_state(
                [cb for cb in peers if cb is not self]),
            world=collective.get_world_size(),
            shard_map=self.shard_map,
        )
        self._save_degradable(state)
        return False

    def _save_degradable(self, state: CheckpointState) -> None:
        """The disk-pressure ladder around one checkpoint commit
        (docs/reliability.md "Resource pressure & graceful degradation"):

        1. nominal: atomic save, as ever;
        2. ENOSPC/EDQUOT: prune to the single newest snapshot (freeing
           keep-last-K minus one files) and retry ONCE — on a genuinely
           full disk the prune is what makes room;
        3. still failing: SKIP this round's snapshot with a loud warning
           and ``xtb_resource_degraded_total{subsystem="checkpoint"}``,
           and keep training — a missing checkpoint costs recovery
           granularity, never the run.  Non-disk OS errors re-raise
           unchanged (a permission bug is a bug, not pressure).
        """
        try:
            self.manager.save(state)
        except OSError as e:
            kind = _resources.note_os_error(e, "checkpoint.write")
            if kind not in _resources.DISK_ERRNOS:
                raise
            self.manager.prune(keep=1)
            _resources.degraded_event(
                "checkpoint", "pruned_to_1", round=state.round, errno=kind)
            try:
                self.manager.save(state)
            except OSError as e2:
                kind2 = _resources.note_os_error(e2, "checkpoint.write")
                if kind2 not in _resources.DISK_ERRNOS:
                    raise
                self.skipped_rounds.append(state.round)
                _resources.degraded_event(
                    "checkpoint", "snapshot_skipped", round=state.round,
                    errno=kind2)
                return
        self.last_saved_round = state.round
