"""Exponential backoff with deterministic jitter.

The repo's one retry policy: tracker worker connect, the jax.distributed
rendezvous, and anything else that races a peer's startup go through
:func:`retry_call` instead of hand-rolled sleep loops.  Jitter matters —
N workers retrying in lockstep re-collide on every attempt (the thundering
herd the reference's ``kRetry`` backoff also staggers) — but *random*
jitter would make distributed runs unreproducible, so the jitter here is
drawn from a generator seeded by ``(op, seed)``: different ranks passing
their rank as ``seed`` de-synchronize, while the same rank replays the
same schedule every run.

Every retry (not the first attempt) counts into
``xtb_retries_total{op=...}`` so a healthy-looking job that is quietly
reconnecting in a loop shows up in telemetry.

Stream independence is part of the contract: each :func:`backoff_delays`
call builds its own ``random.Random`` seeded only by ``(op, seed)``, so
one consumer's draws can never perturb another's schedule.  The
integrity-retry path (``data/extmem.py`` page re-reads, op
``"integrity.page"``) leans on exactly this — its delay is deterministic
per (seam, attempt) no matter what the fault-injection plan or any other
backoff user drew in between (pinned by
``tests/test_integrity.py::test_integrity_backoff_deterministic_per_op_and_attempt``).
"""
from __future__ import annotations

import random
import time
import zlib
from typing import Callable, Iterator, Optional, Tuple, Type, TypeVar

__all__ = ["backoff_delays", "retry_call", "RetriesExhausted"]

T = TypeVar("T")

_counter = None  # xtb_retries_total family, created lazily


class RetriesExhausted(RuntimeError):
    """All attempts failed; ``__cause__`` is the last underlying error."""


def _count_retry(op: str) -> None:
    global _counter
    if _counter is None:
        from ..telemetry.registry import get_registry

        _counter = get_registry().counter(
            "xtb_retries_total", "retried operations (attempts after the "
            "first)", ("op",))
    _counter.labels(op).inc()


def backoff_delays(retries: int, *, base: float = 0.05, factor: float = 2.0,
                   max_delay: float = 10.0, jitter: float = 0.25,
                   op: str = "op", seed: int = 0) -> Iterator[float]:
    """Yield ``retries`` sleep durations: ``base * factor**i`` capped at
    ``max_delay``, each scaled by a deterministic factor in
    ``[1-jitter, 1+jitter]`` drawn from a ``(op, seed)``-seeded RNG."""
    rng = random.Random(zlib.crc32(op.encode()) ^ (seed * 0x9E3779B1))
    for i in range(retries):
        d = min(base * (factor ** i), max_delay)
        if jitter:
            d *= 1.0 + jitter * (2.0 * rng.random() - 1.0)
        yield d


def retry_call(fn: Callable[[], T], *, op: str, retries: int = 5,
               base: float = 0.05, factor: float = 2.0,
               max_delay: float = 10.0, jitter: float = 0.25, seed: int = 0,
               retry_on: Tuple[Type[BaseException], ...] = (OSError,),
               retry_if: Optional[Callable[[BaseException], bool]] = None,
               sleep: Callable[[float], None] = time.sleep,
               on_retry: Optional[Callable[[int, BaseException], None]] = None,
               ) -> T:
    """Call ``fn`` with up to ``retries`` backed-off re-attempts on
    ``retry_on`` exceptions.  Raises :class:`RetriesExhausted` (chained to
    the last error) when every attempt fails; any exception outside
    ``retry_on`` propagates immediately — only the failure modes the caller
    declared transient are retried.  ``retry_if`` further narrows within
    ``retry_on`` (e.g. broad RuntimeErrors filtered by message): an
    exception failing the predicate propagates unwrapped, immediately —
    retrying a permanent failure only buries the real error under backoff."""
    delays = backoff_delays(retries, base=base, factor=factor,
                            max_delay=max_delay, jitter=jitter, op=op,
                            seed=seed)
    last: Optional[BaseException] = None
    for attempt in range(retries + 1):
        try:
            return fn()
        except retry_on as e:
            if retry_if is not None and not retry_if(e):
                raise
            last = e
            if attempt >= retries:
                break
            _count_retry(op)
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(next(delays))
    raise RetriesExhausted(
        f"{op}: all {retries + 1} attempts failed: {last}") from last
