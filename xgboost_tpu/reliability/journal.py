"""Tracker state journal: the coordinator's replayable on-disk memory.

The `RabitTracker` is the one process whose death used to end (or wedge)
the whole job: the rendezvous roster, relay epoch, and regroup state
lived only in its heap.  This module gives it the same crash discipline
checkpoints gave the model (reliability/checkpoint.py): every membership
transition is appended to an fsync'd journal with XTBCKPT-style checksum
framing, and a respawned tracker replays the last valid record to pick
up exactly where its predecessor died — the re-adoption protocol in
docs/reliability.md "Coordinator failover & watchdog".

File format (append-only)::

    "XTBJRNL1"                                  file header, written once
    "JR" | u32 len | u32 crc32(payload) | payload(JSON)   per record

``load()`` walks the records front to back and returns the LAST fully
valid one; a torn tail (the tracker was SIGKILL'd mid-append — the
``tracker.journal`` fault seam injects exactly this) or a flipped byte
fails that record's CRC and the walk stops at the previous good state,
which is always a committed membership transition.  The file is
compacted (atomic rewrite with a single record) once it accumulates
``COMPACT_EVERY`` records, so a long-running job's journal stays tiny.

What a record carries is deliberately small — everything needed to
re-form the job, nothing that can be rederived: the listening port,
original worker count, elastic flag, relay epoch, the live roster with
each rank's last reported resume round (from the piggybacked watchdog
progress markers), the latest shard map any rank reported, and whether a
regroup was pending.  Model state never enters the journal: recovery
reloads it from the elastic checkpoints, same as any worker death.

Telemetry: ``xtb_tracker_journal_writes_total``,
``xtb_tracker_journal_recoveries_total`` (docs/observability.md).
"""
from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any, Dict, Optional

__all__ = ["TrackerJournal", "MAGIC", "COMPACT_EVERY"]

MAGIC = b"XTBJRNL1"
_REC = b"JR"
_HDR = struct.Struct(">II")  # payload length, crc32(payload)
COMPACT_EVERY = 512
# one journal record is a tiny roster dict; anything bigger is a
# corrupted length prefix and must not drive an allocation
_MAX_RECORD = 1 << 22

_instruments = None


def _ins():
    global _instruments
    if _instruments is None:
        from ..telemetry.registry import get_registry

        reg = get_registry()
        _instruments = (
            reg.counter("xtb_tracker_journal_writes_total",
                        "tracker journal records committed (fsync'd "
                        "membership transitions)"),
            reg.counter("xtb_tracker_journal_recoveries_total",
                        "tracker restarts that recovered state from the "
                        "journal"),
        )
    return _instruments


class TrackerJournal:
    """Append-only checksummed journal for one tracker's state."""

    def __init__(self, path: str) -> None:
        self.path = os.fspath(path)
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        self._records_since_open = 0

    # -------------------------------------------------------------- write
    def append(self, state: Dict[str, Any]) -> None:
        """Commit one state record: frame, append, flush, fsync.  The
        ``tracker.journal`` fault seam fires first, so a kill-kind spec
        deterministically dies the tracker process at a journal write, a
        corrupt-kind spec damages the record to prove the torn-tail walk
        ignores it, and a ``disk_full`` spec drives the ENOSPC ladder:
        force a compaction (a single-record rewrite — on a genuinely full
        disk the shrink IS what frees space) and retry ONCE, then degrade
        loudly (``xtb_resource_degraded_total{subsystem="journal"}``) and
        keep running — a missed journal record costs failover coverage
        for one transition, never the job.  Non-disk OS errors re-raise
        (the tracker's caller warns on them, as before)."""
        from . import resources as _resources

        try:
            self._append_once(state)
        except OSError as e:
            kind = _resources.note_os_error(e, "tracker.journal")
            if kind not in _resources.DISK_ERRNOS:
                raise
            # ladder step 1: compact to a single record, then retry
            self._compact(state)
            _resources.degraded_event("journal", "forced_compaction",
                                      errno=kind)
            try:
                self._append_once(state)
            except OSError as e2:
                kind2 = _resources.note_os_error(e2, "tracker.journal")
                if kind2 not in _resources.DISK_ERRNOS:
                    raise
                _resources.degraded_event("journal", "record_skipped",
                                          errno=kind2)

    def _append_once(self, state: Dict[str, Any]) -> None:
        from . import faults

        payload = json.dumps(state, sort_keys=True).encode()
        spec = faults.maybe_inject("tracker.journal")
        if spec is not None and spec.kind == "corrupt":
            # damage AFTER the CRC is computed over the original payload:
            # the record must fail verification at load, not decode wrong
            frame = (_REC + _HDR.pack(len(payload), zlib.crc32(payload))
                     + faults.corrupt_bytes(payload, spec))
        else:
            frame = (_REC + _HDR.pack(len(payload), zlib.crc32(payload))
                     + payload)
        fresh = not os.path.exists(self.path)
        with open(self.path, "ab") as fh:
            if fresh or fh.tell() == 0:
                fh.write(MAGIC)
            fh.write(frame)
            fh.flush()
            os.fsync(fh.fileno())
        _ins()[0].inc()
        self._records_since_open += 1
        if self._records_since_open >= COMPACT_EVERY:
            self._compact(state)

    def _compact(self, state: Dict[str, Any]) -> None:
        """Atomic rewrite with a single record (tmp + fsync + rename).
        A failed compaction is classified and counted
        (``xtb_resource_errors_total``), never silently dropped — the
        journal keeps appending to the uncompacted file."""
        from . import resources as _resources

        payload = json.dumps(state, sort_keys=True).encode()
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as fh:
                fh.write(MAGIC + _REC
                         + _HDR.pack(len(payload), zlib.crc32(payload))
                         + payload)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
        except OSError as e:
            _resources.note_os_error(e, "journal.compact")
            try:
                os.unlink(tmp)
            except OSError as ue:
                _resources.note_os_error(ue, "journal.compact")
        self._records_since_open = 0

    # --------------------------------------------------------------- read
    def load(self, count_recovery: bool = False,
             repair: bool = False) -> Optional[Dict[str, Any]]:
        """The last fully valid record, or None (missing/empty/unreadable
        file, bad header, or no record surviving the CRC walk).  A torn
        or corrupted tail stops the walk at the previous good record.

        ``repair=True`` (the recovering tracker passes it) additionally
        TRUNCATES a detected torn/damaged tail: appends land after the
        last committed record, not after garbage the next recovery's
        walk would stop at — without repair, a post-tear append would be
        permanently unreachable."""
        try:
            with open(self.path, "rb") as fh:
                blob = fh.read()
        except FileNotFoundError:
            return None  # no journal yet: a fresh tracker, not an error
        except OSError as e:
            from . import resources as _resources

            _resources.note_os_error(e, "journal.load")
            return None
        if not blob.startswith(MAGIC):
            return None
        off = len(MAGIC)
        valid_end = off
        last: Optional[Dict[str, Any]] = None
        while off + len(_REC) + _HDR.size <= len(blob):
            if blob[off: off + len(_REC)] != _REC:
                break  # framing lost: nothing after this can be trusted
            off += len(_REC)
            n, crc = _HDR.unpack_from(blob, off)
            off += _HDR.size
            if n > _MAX_RECORD or off + n > len(blob):
                break  # torn tail / insane length
            payload = blob[off: off + n]
            off += n
            if zlib.crc32(payload) != crc:
                break  # damaged record: stop at the previous good state
            try:
                last = json.loads(payload.decode())
            except (ValueError, UnicodeDecodeError):
                break
            valid_end = off
        if repair and valid_end < len(blob):
            try:
                with open(self.path, "r+b") as fh:
                    fh.truncate(valid_end)
                    fh.flush()
                    os.fsync(fh.fileno())
            except OSError as e:  # read-only media: appends were
                from . import resources as _resources  # impossible anyway

                _resources.note_os_error(e, "journal.repair")
        if last is not None and count_recovery:
            _ins()[1].inc()
        return last
