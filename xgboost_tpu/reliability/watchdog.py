"""Stall watchdog: per-seam budgets, a deterministic escalation ladder,
and liveness progress markers — a stalled-but-alive process becomes a
*detected* fault instead of an invisible wedge.

Every other reliability layer reacts to a process *dying* (tracker EOF
fan-out, relay departure, replica death rerouting).  A process that is
alive but stuck — a peer sleeping in a driver bug, a decode thread lost
in a syscall, a replica wedged mid-execute — moves no sockets and trips
nothing until an outer chaos deadline declares the whole episode red.
This module closes that gap with three pieces (docs/reliability.md
"Coordinator failover & watchdog"):

- **Guards** (:func:`guard`): a context manager bracketing one blocking
  operation at a named seam with a wall-clock budget.  A monitor thread
  walks the in-flight set and escalates deterministically:

  1. ``warn``  (1.0x budget) — stderr warning + flight-ring event +
     ``xtb_watchdog_escalations_total{seam,stage="warn"}``;
  2. ``dump``  (1.5x budget) — ``faulthandler.dump_traceback`` of ALL
     threads into the flight-recorder directory
     (:func:`~xgboost_tpu.telemetry.flight.dump_stacks`) plus a flight
     ring dump, so the postmortem exists *before* anything is killed;
  3. ``stall`` (2.0x budget) — the op's ``stalled`` flag is set and its
     ``on_stall`` callback runs (close the relay socket, exit the
     replica), steering the failure into an EXISTING recovery path
     (elastic regroup, replica reroute) instead of a hang.  The
     ``watchdog.escalate`` fault seam fires here so chaos plans can
     perturb the ladder deterministically.

- **Progress markers** (:func:`progress`): cheap process-local liveness
  breadcrumbs (current round, collective seq, page index, request id)
  that ship to the driver inside every telemetry snapshot
  (``telemetry.distributed.snapshot_payload``).  The tracker compares a
  rank's successive markers with :func:`advanced` — a *slow but
  progressing* worker keeps resetting its staleness clock; only frozen
  markers age (pinned by ``tests/test_watchdog.py``).

- **Budgets**: per-seam seconds, overridable per seam via
  ``XGBOOST_TPU_WATCHDOG_<SEAM>_S`` (seam upper-cased, dots to
  underscores, e.g. ``XGBOOST_TPU_WATCHDOG_COLLECTIVE_WAIT_S``);
  ``XGBOOST_TPU_WATCHDOG=0`` disables every guard (each then costs one
  cached flag test).

This module is the one place allowed to own unbounded blocking
primitives — everywhere else the xtblint XTB7xx family rejects
``.wait()`` / queue ``.get()`` / ``.result()`` / socket connects without
an explicit timeout (docs/static_analysis.md).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = ["guard", "note", "progress", "markers", "advanced",
           "marker_age", "check_now", "enabled", "budget_for", "configure",
           "reset", "DEFAULT_BUDGETS", "STAGES", "WARN_AT", "DUMP_AT",
           "STALL_AT", "ENV_ENABLE"]

ENV_ENABLE = "XGBOOST_TPU_WATCHDOG"
_ENV_PREFIX = "XGBOOST_TPU_WATCHDOG_"
_ENV_TICK = "XGBOOST_TPU_WATCHDOG_TICK_S"

# escalation thresholds as multiples of the seam budget
WARN_AT, DUMP_AT, STALL_AT = 1.0, 1.5, 2.0
STAGES = ("warn", "dump", "stall")

# Per-seam budget defaults (seconds).  Generous on purpose: the watchdog
# exists to catch *wedges*, not to police slow rounds — the false-positive
# contract (tests/test_watchdog.py) is that legitimate slowness under
# budget never escalates.  Every value is env-overridable (module doc).
DEFAULT_BUDGETS: Dict[str, float] = {
    "collective.wait": 300.0,   # one blocked collective (relay op_timeout
    #                             is 600s; the watchdog dumps first)
    "extmem.decode": 180.0,     # one page decode/stage wait
    "replica.execute": 120.0,   # one replica request, admission to reply
    "lifecycle.phase": 900.0,   # one lifecycle phase (train can be long)
    "tracker.peer": 300.0,      # tracker-side: a rank's progress markers
    #                             frozen while its channel stays up
    "tracker.join": 120.0,      # tracker-side: a member not reaching its
    #                             round boundary during a pending regroup
}
_FALLBACK_BUDGET = 300.0

_lock = threading.Lock()
_ops: Dict[int, "_Operation"] = {}
_next_id = 0
_monitor: Optional[threading.Thread] = None
_markers: Dict[str, Dict[str, Any]] = {}
_enabled_override: Optional[bool] = None
_tick_override: Optional[float] = None
_instruments = None


def _ins():
    global _instruments
    if _instruments is None:
        from ..telemetry.registry import get_registry

        _instruments = get_registry().counter(
            "xtb_watchdog_escalations_total",
            "watchdog escalations by seam and ladder stage "
            "(warn -> dump -> stall)", ("seam", "stage"))
    return _instruments


def enabled() -> bool:
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get(ENV_ENABLE, "").strip() != "0"


def budget_for(seam: str) -> float:
    """The seam's budget in seconds (env override, else the default)."""
    env = _ENV_PREFIX + seam.upper().replace(".", "_") + "_S"
    raw = os.environ.get(env, "").strip()
    if raw:
        try:
            return max(0.05, float(raw))
        except ValueError:
            pass
    return DEFAULT_BUDGETS.get(seam, _FALLBACK_BUDGET)


def _tick_s() -> float:
    if _tick_override is not None:
        return _tick_override
    try:
        return max(0.02, float(os.environ.get(_ENV_TICK, "1.0")))
    except ValueError:
        return 1.0


class _Operation:
    """One in-flight guarded operation."""

    __slots__ = ("seam", "budget", "t0", "detail", "on_stall", "stage",
                 "stalled", "stack_path", "done")

    def __init__(self, seam: str, budget: float,
                 on_stall: Optional[Callable[["_Operation"], None]],
                 detail: Dict[str, Any]) -> None:
        self.seam = seam
        self.budget = budget
        self.t0 = time.monotonic()
        self.detail = detail
        self.on_stall = on_stall
        self.stage = 0           # 0 = nominal, then warn/dump/stall
        self.stalled = False     # set at the stall stage; pollable
        self.stack_path: Optional[str] = None
        self.done = False        # guard exited: must never escalate

    def elapsed(self, now: Optional[float] = None) -> float:
        return (time.monotonic() if now is None else now) - self.t0


class _NoopGuard:
    """Shared disabled-path guard: one attribute read per poll."""

    stalled = False
    stage = 0
    stack_path = None

    def __enter__(self) -> "_NoopGuard":
        return self

    def __exit__(self, *exc: Any) -> None:
        return None


_NOOP = _NoopGuard()


class guard:
    """Bracket one blocking operation at ``seam`` under the watchdog.

    Returns an object with ``stalled`` (set once the ladder reached the
    stall stage — pollable from wait loops), ``stage``, and
    ``stack_path`` (the faulthandler dump, once written).  ``on_stall``
    runs ONCE at the stall stage, from the monitor thread — it must only
    poke another thread awake (close a socket, set a flag), never block.
    """

    __slots__ = ("_op", "_id")

    def __init__(self, seam: str, *, budget_s: Optional[float] = None,
                 on_stall: Optional[Callable[["_Operation"], None]] = None,
                 **detail: Any) -> None:
        if not enabled():
            self._op = None
            self._id = -1
            return
        self._op = _Operation(
            seam, budget_for(seam) if budget_s is None else float(budget_s),
            on_stall, detail)
        self._id = _register(self._op)

    def __enter__(self):
        if self._op is None:
            return _NOOP
        return self._op

    def __exit__(self, *exc: Any) -> None:
        if self._op is not None:
            # flag FIRST: the monitor snapshots the op set lock-free, so
            # an op completing right at a stage threshold must not have a
            # destructive stall action run against healthy work
            self._op.done = True
            with _lock:
                _ops.pop(self._id, None)
        return None


def _register(op: _Operation) -> int:
    global _next_id, _monitor
    with _lock:
        _next_id += 1
        oid = _next_id
        _ops[oid] = op
        if _monitor is None or not _monitor.is_alive():
            _monitor = threading.Thread(target=_monitor_loop, daemon=True,
                                        name="xtb-watchdog")
            _monitor.start()
    return oid


def _monitor_loop() -> None:
    while True:
        time.sleep(_tick_s())
        try:
            check_now()
        except Exception:  # pragma: no cover - the watchdog must not die
            pass


def check_now(now: Optional[float] = None) -> List[tuple]:
    """Walk the in-flight set once and apply due escalations; returns
    ``[(seam, stage), ...]`` for every transition taken this call.  The
    monitor thread calls this every tick; tests call it directly for
    deterministic stage control."""
    now = time.monotonic() if now is None else now
    with _lock:
        live = list(_ops.values())
    fired: List[tuple] = []
    for op in live:
        e = op.elapsed(now)
        while op.stage < len(STAGES) and not op.done:
            threshold = (WARN_AT, DUMP_AT, STALL_AT)[op.stage]
            if e < op.budget * threshold:
                break
            op.stage += 1
            stage = STAGES[op.stage - 1]
            _escalate(op, stage)
            fired.append((op.seam, stage))
    return fired


def _escalate(op: _Operation, stage: str) -> None:
    import sys

    from ..telemetry import flight

    _ins().labels(op.seam, stage).inc()
    flight.record("fault", "watchdog." + stage, seam=op.seam,
                  elapsed_s=round(op.elapsed(), 3), budget_s=op.budget,
                  **op.detail)
    print(f"[watchdog] {stage}: {op.seam} blocked "
          f"{op.elapsed():.1f}s (budget {op.budget:.1f}s) "
          f"{op.detail or ''}", file=sys.stderr, flush=True)
    if stage == "dump":
        # the all-thread stack dump lands BEFORE anything is killed: the
        # postmortem must exist even if the stall stage takes the process
        op.stack_path = flight.dump_stacks()
        try:
            flight.dump()
        except OSError as e:
            from . import resources

            resources.note_os_error(e, "watchdog.dump")
    elif stage == "stall":
        from . import faults

        try:
            # deterministic perturbation point for chaos plans (delay /
            # exception); an injected exception must not kill the monitor
            faults.maybe_inject("watchdog.escalate")
        except faults.FaultInjected:
            pass
        op.stalled = True
        # last-instant completion check: the destructive poke must not
        # hit work that just finished (the window is now one statement,
        # not a whole monitor tick)
        if op.on_stall is not None and not op.done:
            try:
                op.on_stall(op)
            except Exception:  # the recovery poke must not kill the monitor
                pass


def note(seam: str, stage: str, **detail: Any) -> None:
    """Escalation bookkeeping for ladders the module does not drive
    itself (the tracker's join/peer monitors): counter + flight event +
    stderr line, same shape as a guard escalation."""
    import sys

    from ..telemetry import flight

    _ins().labels(seam, stage).inc()
    flight.record("fault", "watchdog." + stage, seam=seam, **detail)
    print(f"[watchdog] {stage}: {seam} {detail}", file=sys.stderr,
          flush=True)


# ---------------------------------------------------------------------------
# liveness progress markers
# ---------------------------------------------------------------------------


def progress(key: str, **detail: Any) -> None:
    """Record a liveness breadcrumb under ``key`` (e.g. ``train.round``
    round=i, ``collective`` seq=n, ``extmem.page`` page=j).  Cheap — one
    dict store — and JSON-able: markers ride every shipped telemetry
    snapshot so the tracker can tell a slow-but-progressing peer from a
    frozen one."""
    with _lock:
        _markers[key] = {"t_mono": time.monotonic(), **detail}


def markers() -> Dict[str, Dict[str, Any]]:
    """A copy of this process's current progress markers."""
    with _lock:
        return {k: dict(v) for k, v in _markers.items()}


def advanced(prev: Optional[Dict[str, dict]],
             cur: Optional[Dict[str, dict]]) -> bool:
    """True when ``cur`` shows PROGRESS over ``prev``: a new marker key or
    any marker whose payload (timestamps excluded) changed.  A re-shipped
    identical marker set is a heartbeat, not progress — heartbeat-loss and
    progress-loss are different faults and only the latter ages a peer
    toward the stall ladder."""
    if not cur:
        return False
    if not prev:
        return True

    def strip(m: Dict[str, dict]) -> Dict[str, dict]:
        return {k: {kk: vv for kk, vv in v.items() if kk != "t_mono"}
                for k, v in m.items()}

    return strip(cur) != strip(prev)


def marker_age(marks: Optional[Dict[str, dict]],
               now: Optional[float] = None) -> Optional[float]:
    """Seconds since the newest marker in ``marks`` was recorded (sender's
    monotonic clock — only meaningful same-host), or None."""
    if not marks:
        return None
    newest = max((float(v.get("t_mono", 0.0)) for v in marks.values()),
                 default=0.0)
    return (time.monotonic() if now is None else now) - newest


# ---------------------------------------------------------------------------
# test hooks
# ---------------------------------------------------------------------------


def configure(*, enabled: Optional[bool] = None,
              tick_s: Optional[float] = None) -> None:
    """Override the env-driven enable flag / monitor tick (tests)."""
    global _enabled_override, _tick_override
    _enabled_override = enabled
    _tick_override = tick_s


def reset() -> None:
    """Drop every in-flight op, marker, and override (test isolation).
    The monitor thread is left running — it is harmless when idle."""
    global _enabled_override, _tick_override
    with _lock:
        _ops.clear()
        _markers.clear()
    _enabled_override = None
    _tick_override = None
