"""Runtime lockdep witness — the dynamic half of the XTB9xx contract.

The static rule (analysis/lockorder.py) proves lock-order and
blocking-under-lock discipline for the call graphs it can resolve; this
module witnesses the paths it cannot — dynamic dispatch, callbacks,
threads handed locks through closures — by watching the real lock
traffic of a live process:

- **Order graph**: every *unbounded* blocking acquire taken while other
  witnessed locks are held adds held→acquired edges to a global order
  graph.  The first acquire that would close a cycle (an ABBA: thread 1
  took A then B sometime, thread 2 now takes A while holding B) is
  reported with the established path — before it can deadlock, because
  the edge direction conflict is visible on first occurrence even when
  the interleaving never actually wedges.
- **Seam witness**: reliability/faults.py calls :func:`note_seam` at the
  top of ``maybe_inject`` when armed, so any witnessed lock held across
  a fault seam — the runtime analogue of static XTB902 — is reported
  (once per lock/seam pair).  Locks declared serialization locks via
  :func:`mark_serial` (the runtime analogue of ``_XTB_SERIAL_LOCKS``)
  are exempt.
- **Self-deadlock**: a thread re-acquiring a non-reentrant lock it
  already holds is reported immediately (the inner acquire would hang).

Armed by ``XGBOOST_TPU_LOCKDEP=1`` (read once, at package import —
:func:`maybe_install_from_env` runs before any sibling module creates a
lock, so module-level locks are witnessed too).  When the variable is
unset NOTHING is patched and the cost is exactly zero: ``threading.Lock``
is still the raw C factory.  When armed, only locks *created by package
code* are wrapped (creation site resolved by stack walk); third-party
locks (JAX, stdlib) stay raw, so overhead is confined to the package's
own synchronization.

Reports accumulate in-process (:func:`reports`, capped), land in the
flight recorder ring, and are printed at exit with the
``XTB-LOCKDEP-VIOLATION`` marker the nightly suite greps for.  Set
``XGBOOST_TPU_LOCKDEP_RAISE=1`` to raise :class:`LockdepViolation` at
the offending acquire instead (pinpoints the stack in a repro run).
"""
from __future__ import annotations

import _thread
import atexit
import os
import sys
import threading
from typing import Any, Dict, List, Optional, Set, Tuple

__all__ = ["install", "uninstall", "enabled", "maybe_install_from_env",
           "mark_serial", "named_lock", "note_seam", "reports", "clear",
           "LockdepViolation", "ENV_ENABLE", "ENV_RAISE"]

ENV_ENABLE = "XGBOOST_TPU_LOCKDEP"
ENV_RAISE = "XGBOOST_TPU_LOCKDEP_RAISE"

_OFF_VALUES = ("", "0", "false", "off", "no")

# package root (".../xgboost_tpu") — only locks created by files under it
# are wrapped
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SELF_FILE = os.path.abspath(__file__)

# witness state.  The state lock is a raw _thread lock on purpose: it is
# not created through the patched factories (no recursion into the
# witness) and not part of any ordering the package declares — it is
# only ever held for graph/report bookkeeping, never across user code.
_state_lock = _thread.allocate_lock()
_order: Dict[str, Set[str]] = {}      # key -> keys acquired while key held
_serial_keys: Set[str] = set()        # mark_serial()-declared keys
_seam_seen: Set[Tuple[str, str]] = set()
_reports: List[Dict[str, Any]] = []
_MAX_REPORTS = 64

_tls = threading.local()              # .held: List[str]; .busy: bool

_installed = False
_orig_lock = threading.Lock
_orig_rlock = threading.RLock
_orig_condition = threading.Condition


class LockdepViolation(RuntimeError):
    """Raised at the offending acquire when XGBOOST_TPU_LOCKDEP_RAISE=1."""


def _creation_site() -> Optional[str]:
    """``"rel/path.py:lineno"`` of the package frame creating a lock, or
    None when the creator is outside the package (lock stays raw)."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        base = os.path.basename(fn)
        if os.path.abspath(fn) != _SELF_FILE and base != "threading.py":
            break
        f = f.f_back
    if f is None:
        return None
    fn = os.path.abspath(f.f_code.co_filename)
    if not fn.startswith(_PKG_DIR + os.sep):
        return None
    rel = fn[len(_PKG_DIR) + 1:].replace(os.sep, "/")
    return f"{rel}:{f.f_lineno}"


def _held() -> List[str]:
    tls = _tls.__dict__
    held = tls.get("held")
    if held is None:
        held = tls["held"] = []
    return held


def _push(key: str) -> None:
    _held().append(key)


def _pop(key: str) -> None:
    held = _tls.__dict__.get("held")
    if not held:
        return
    # LIFO discipline is the overwhelmingly common case -> O(1) pop
    if held[-1] == key:
        held.pop()
        return
    for i in range(len(held) - 2, -1, -1):
        if held[i] == key:
            del held[i]
            return


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """BFS path src -> dst in the order graph (caller holds _state_lock)."""
    if src == dst:
        return [src]
    prev: Dict[str, str] = {}
    frontier = [src]
    while frontier:
        nxt: List[str] = []
        for node in frontier:
            for succ in _order.get(node, ()):
                if succ in prev or succ == src:
                    continue
                prev[succ] = node
                if succ == dst:
                    path = [dst]
                    while path[-1] != src:
                        path.append(prev[path[-1]])
                    path.reverse()
                    return path
                nxt.append(succ)
        frontier = nxt
    return None


def _report(kind: str, msg: str) -> None:
    entry = {"kind": kind, "msg": msg,
             "thread": threading.current_thread().name}
    with _state_lock:
        if len(_reports) < _MAX_REPORTS:
            _reports.append(entry)
    # ring-append may itself take a witnessed lock (flight._lock): the
    # busy flag stops the witness from recursing into itself
    _tls.busy = True
    try:
        from ..telemetry import flight

        flight.record("lockdep", kind, msg=msg)
    except Exception:  # pragma: no cover - telemetry must not mask this
        pass
    finally:
        _tls.busy = False
    if os.environ.get(ENV_RAISE, "").strip().lower() not in _OFF_VALUES:
        raise LockdepViolation(f"[{kind}] {msg}")


def _check_before_acquire(key: str, reentrant: bool) -> None:
    """Order/self-deadlock check for an *unbounded* blocking acquire of
    ``key``.  Bounded acquires (trylock / timeout) skip this: they cannot
    participate in a deadlock cycle, matching static XTB901 semantics."""
    tls = _tls.__dict__
    if tls.get("busy"):
        return
    held = tls.get("held")
    if not held:
        return
    for h in dict.fromkeys(held):
        if h == key:
            if not reentrant:
                _report("self-deadlock",
                        f"thread re-acquires non-reentrant lock {key} "
                        f"it already holds (inner acquire would hang)")
            continue
        if key in _order.get(h, ()):  # fast path: edge already recorded
            continue
        with _state_lock:
            succ = _order.setdefault(h, set())
            if key in succ:
                continue
            path = _find_path(key, h)
            succ.add(key)
        if path is not None:
            cycle = " -> ".join(path + [key])
            _report("order",
                    f"lock-order inversion: acquiring {key} while holding "
                    f"{h}, but the established order is {cycle}")


class _WitnessLock:
    """threading.Lock wrapper: witness bookkeeping around the raw lock."""

    __slots__ = ("_inner", "_key")
    _reentrant = False

    def __init__(self, inner: Any, key: str) -> None:
        self._inner = inner
        self._key = key

    @property
    def _xtb_key(self) -> str:
        return self._key

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # uncontended leaf acquires (nothing held) dominate real traffic:
        # the order/seam machinery only engages when this thread already
        # holds a witnessed lock, so the fast path is one dict.get
        tls = _tls.__dict__
        held = tls.get("held")
        if held is None:
            held = tls["held"] = []
        elif held and blocking and timeout < 0:
            _check_before_acquire(self._key, self._reentrant)
        got = self._inner.acquire(blocking, timeout)
        if got:
            held.append(self._key)
        return got

    def release(self) -> None:
        self._inner.release()
        _pop(self._key)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        tls = _tls.__dict__
        held = tls.get("held")
        if held is None:
            held = tls["held"] = []
        elif held:
            _check_before_acquire(self._key, self._reentrant)
        self._inner.acquire()
        held.append(self._key)
        return True

    def __exit__(self, *exc: Any) -> None:
        self._inner.release()
        held = _tls.__dict__.get("held")
        if held:
            if held[-1] == self._key:
                held.pop()
            else:
                _pop(self._key)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<{type(self).__name__} {self._key} of {self._inner!r}>"


class _WitnessRLock(_WitnessLock):
    """threading.RLock wrapper.  Re-entrant acquires are legal (no
    self-deadlock report); the ``_release_save``/``_acquire_restore``/
    ``_is_owned`` trio keeps ``threading.Condition`` working on top —
    Condition.wait drops every recursion level, so the witness drops
    every held entry too (a lock being waited on is not held)."""

    __slots__ = ()
    _reentrant = True

    def _release_save(self) -> Tuple[Any, int]:
        state = self._inner._release_save()
        held = getattr(_tls, "held", None)
        n = 0
        if held:
            n = held.count(self._key)
            if n:
                _tls.held = [k for k in held if k != self._key]
        return (state, n)

    def _acquire_restore(self, saved: Tuple[Any, int]) -> None:
        state, n = saved
        self._inner._acquire_restore(state)
        for _ in range(n):
            _push(self._key)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def _lock_factory() -> Any:
    inner = _orig_lock()
    key = _creation_site()
    return inner if key is None else _WitnessLock(inner, key)


def _rlock_factory() -> Any:
    inner = _orig_rlock()
    key = _creation_site()
    return inner if key is None else _WitnessRLock(inner, key)


def _condition_factory(lock: Any = None) -> Any:
    # the no-arg form must route through the patched RLock factory so the
    # implicit lock is witnessed (keyed at the Condition creation site)
    return _orig_condition(lock if lock is not None else _rlock_factory())


def named_lock(name: str, *, reentrant: bool = False) -> Any:
    """A witnessed lock with an explicit key, regardless of creation site
    or arming — unit tests and ad-hoc tools build deliberate ABBA pairs
    with these without patching threading."""
    if reentrant:
        return _WitnessRLock(_orig_rlock(), name)
    return _WitnessLock(_orig_lock(), name)


def mark_serial(lock: Any) -> Any:
    """Declare ``lock`` a serialization lock: holding it across a fault
    seam is its documented contract, not a violation (runtime analogue of
    the static ``_XTB_SERIAL_LOCKS`` declaration; still in the order
    graph).  No-op on raw (unwitnessed) locks.  Returns the lock."""
    key = getattr(lock, "_xtb_key", None)
    if key is not None:
        with _state_lock:
            _serial_keys.add(key)
    return lock


def note_seam(site: str) -> None:
    """Called by faults.maybe_inject when armed: report (once per
    lock/seam pair) every non-serial witnessed lock held across it."""
    if getattr(_tls, "busy", False):
        return
    held = getattr(_tls, "held", None)
    if not held:
        return
    for h in dict.fromkeys(held):
        pair = (h, site)
        if h in _serial_keys or pair in _seam_seen:
            continue
        with _state_lock:
            if pair in _seam_seen:
                continue
            _seam_seen.add(pair)
        _report("seam",
                f"lock {h} held across fault seam {site!r} — collect under "
                f"the lock, cross the seam after release (or mark_serial)")


def reports() -> List[Dict[str, Any]]:
    """Accumulated violation reports (copies; capped at {cap})."""
    with _state_lock:
        return [dict(r) for r in _reports]


reports.__doc__ = reports.__doc__.format(cap=_MAX_REPORTS)  # type: ignore


def clear() -> None:
    """Drop reports and the learned order graph (test isolation)."""
    with _state_lock:
        _reports.clear()
        _order.clear()
        _seam_seen.clear()


def enabled() -> bool:
    return _installed


def install() -> bool:
    """Patch the threading lock factories and arm the seam hook.
    Idempotent; returns True when armed after the call."""
    global _installed
    if _installed:
        return True
    threading.Lock = _lock_factory  # type: ignore[assignment]
    threading.RLock = _rlock_factory  # type: ignore[assignment]
    threading.Condition = _condition_factory  # type: ignore[assignment]
    from . import faults

    faults._lockdep_seam = note_seam
    atexit.register(_atexit_report)
    _installed = True
    return True


def uninstall() -> None:
    """Restore the raw factories and disarm the seam hook.  Locks already
    wrapped keep witnessing; state (graph, reports) is kept — clear()
    drops it."""
    global _installed
    if not _installed:
        return
    threading.Lock = _orig_lock  # type: ignore[assignment]
    threading.RLock = _orig_rlock  # type: ignore[assignment]
    threading.Condition = _orig_condition  # type: ignore[assignment]
    from . import faults

    faults._lockdep_seam = None
    _installed = False


def maybe_install_from_env() -> bool:
    """Arm iff ``XGBOOST_TPU_LOCKDEP`` is set truthy.  Called from
    package import *before* sibling modules create their module-level
    locks, so those are witnessed too."""
    if os.environ.get(ENV_ENABLE, "").strip().lower() in _OFF_VALUES:
        return False
    return install()


def _atexit_report() -> None:  # pragma: no cover - interpreter teardown
    # plain list read, no lock taken: an XTB903-clean handler that still
    # gets the marker out even if a witness structure is mid-update
    n = len(_reports)
    if not n:
        return
    sys.stderr.write(f"XTB-LOCKDEP-VIOLATION: {n} report(s)\n")
    for r in _reports[:_MAX_REPORTS]:
        sys.stderr.write(f"  [{r['kind']}] ({r['thread']}) {r['msg']}\n")
