"""xgboost_tpu.reliability — fault tolerance for training and serving.

Long boosting runs and always-on serving both assume workers die and come
back (the Rabit elastic model; "Out-of-Core GPU Gradient Boosting",
arXiv:2005.09148, is hours of wall-clock per model).  This package holds
the three pieces that make that survivable:

- **Checkpoints** (checkpoint.py): :class:`CheckpointCallback` atomically
  persists Booster + training state every N rounds (tmp + fsync + rename,
  keep-last-K, checksum-validated fallback past corrupt files);
  ``train(..., resume_from=dir)`` continues bit-identically.
- **Retry/backoff** (retry.py): :func:`retry_call`, exponential backoff
  with deterministic per-rank jitter — tracker connect and the
  jax.distributed rendezvous go through it; retries count into
  ``xtb_retries_total``.
- **Fault injection** (faults.py): a deterministic, env/config-driven
  plan (kill rank k at round r, drop the tracker connection, delay or fail
  an allreduce, truncate a checkpoint, flip a byte in a payload) fired at
  named seams in training, the collective, the tracker, and the serving
  batcher — the harness the kill/resume and abort fan-out tests drive.
  Fired faults count into ``xtb_faults_injected_total``.
- **Integrity accounting** (integrity.py): the ``xtb_integrity_*``
  counters behind every checksum boundary — wire frames, tracker
  messages, extmem pages, model arenas, checkpoints (docs/reliability.md
  "Integrity & chaos").
- **Resource governor** (resources.py): per-resource degradation levels
  (memory/disk/fd/overload), OS-error classification
  (``note_os_error`` → ``xtb_resource_errors_total``), and the graceful
  degradation ladders — checkpoint prune-retry-skip under ENOSPC,
  journal forced compaction, clean publish aborts, extmem cache/prefetch
  shrink, fleet AIMD + SLO brownout (docs/reliability.md "Resource
  pressure & graceful degradation").
- **Chaos soak** (chaos.py): seeded multi-fault schedules composed over
  the seam catalog, run through scenario templates with checked
  invariants and bit-for-bit replay (``scripts/chaos_soak.py``).

docs/reliability.md is the guide (checkpoint format, resume semantics,
fault-plan schema, serving degradation behavior).
"""
from __future__ import annotations

# the lockdep witness must arm (XGBOOST_TPU_LOCKDEP=1) before the sibling
# imports below run: they create module-level locks at import, and only
# locks created through the patched factories are witnessed
from . import lockdep
lockdep.maybe_install_from_env()

from . import faults, integrity, resources, watchdog
from .checkpoint import (CheckpointCallback, CheckpointManager,
                         CheckpointState, latest_checkpoint, scrub_dir)
from .faults import FaultInjected, FaultPlan, FaultSpec, corrupt_bytes
from .journal import TrackerJournal
from .retry import RetriesExhausted, backoff_delays, retry_call

__all__ = [
    "TrackerJournal",
    "lockdep",
    "watchdog",
    "CheckpointCallback",
    "CheckpointManager",
    "CheckpointState",
    "latest_checkpoint",
    "scrub_dir",
    "FaultInjected",
    "FaultPlan",
    "FaultSpec",
    "corrupt_bytes",
    "faults",
    "integrity",
    "resources",
    "RetriesExhausted",
    "backoff_delays",
    "retry_call",
]
