"""End-to-end data-integrity accounting: the ``xtb_integrity_*`` families.

Every byte that crosses a process or storage boundary in this repo is
checksummed and verified at the receiving side (docs/reliability.md
"Integrity & chaos" has the coverage table): fleet wire frames and
tracker/relay messages carry a CRC-32, external-memory pages verify a
per-page CRC at decode, model-store arenas re-verify their SHA-256 at
replica attach and on scrub, and checkpoints have carried a trailing
SHA-256 since PR 3.  This module is the shared *accounting* for all of
them — one place that answers "how often does verification run, how often
does it fail, and what happened next":

- :func:`corrupt_detected` — a verification FAILED: the payload was
  damaged and the damage was caught (the contract: caught, never decoded).
- :func:`retried` — a recoverable boundary re-read the source once
  (extmem pages re-decode from their backing store before failing loud).
- :func:`quarantined` — a component was fenced off after a failed
  verification (a fleet connection dropped, a replica that reported a
  diverged arena).
- :func:`scrubbed` — a proactive verification walk completed (model-store
  arena scrub, checkpoint-directory scrub).

Registration is lazy (first event creates the families) so importing the
integrity-checked modules costs nothing when telemetry is never touched.
"""
from __future__ import annotations

__all__ = ["corrupt_detected", "retried", "quarantined", "scrubbed"]

_instruments = None


def _ins():
    global _instruments
    if _instruments is None:
        from ..telemetry.registry import get_registry

        reg = get_registry()
        _instruments = (
            reg.counter("xtb_integrity_corrupt_total",
                        "corrupted payloads detected at an integrity "
                        "boundary (checksum/structure verification "
                        "failed)", ("boundary",)),
            reg.counter("xtb_integrity_retry_total",
                        "integrity re-reads: a failed verification "
                        "retried once from the backing store",
                        ("boundary",)),
            reg.counter("xtb_integrity_quarantine_total",
                        "components fenced off after a failed "
                        "verification (connection dropped, replica "
                        "quarantined)", ("boundary",)),
            reg.counter("xtb_integrity_scrub_total",
                        "proactive integrity scrub passes completed",
                        ("target",)),
        )
    return _instruments


def corrupt_detected(boundary: str) -> None:
    """Count one detected corruption at ``boundary`` (``wire`` /
    ``tracker`` / ``page`` / ``arena`` / ``checkpoint``) — and land it in
    the flight ring, so a postmortem shows WHICH boundary went bad."""
    _ins()[0].labels(boundary).inc()
    from ..telemetry import flight

    flight.record("fault", "integrity.corrupt", boundary=boundary)


def retried(boundary: str) -> None:
    _ins()[1].labels(boundary).inc()


def quarantined(boundary: str) -> None:
    _ins()[2].labels(boundary).inc()


def scrubbed(target: str) -> None:
    _ins()[3].labels(target).inc()
