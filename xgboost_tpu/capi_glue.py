"""Python side of the C API (reference: include/xgboost/c_api.h,
src/c_api/c_api.cc).

native/xtb_capi.cc embeds CPython and calls these helpers with raw buffer
addresses; everything heavy (array construction, training, prediction)
happens here so the C layer stays a thin ABI shim.  Results that must
outlive a call (prediction buffers, eval strings) are pinned on the owning
handle object, mirroring the reference's per-handle XGBAPIThreadLocalEntry
return-buffer convention (c_api.cc).
"""
from __future__ import annotations

import ctypes
import json
from typing import List, Optional

import numpy as np

from .core import Booster
from .data.dmatrix import DMatrix

_F32 = ctypes.POINTER(ctypes.c_float)


def _buf(addr: int, n: int, dtype) -> np.ndarray:
    """Copy n elements of dtype from a raw address into a numpy array."""
    ctype = np.ctypeslib.as_ctypes_type(dtype)
    arr = np.ctypeslib.as_array(
        ctypes.cast(addr, ctypes.POINTER(ctype)), shape=(n,))
    return np.array(arr, dtype=dtype)  # copy: the caller's buffer may die


def dmatrix_from_mat(addr: int, nrow: int, ncol: int, missing: float) -> DMatrix:
    X = _buf(addr, nrow * ncol, np.float32).reshape(nrow, ncol)
    return DMatrix(X, missing=missing)


def dmatrix_from_mat_nthread(addr: int, nrow: int, ncol: int, missing: float,
                             nthread: int) -> DMatrix:
    """XGDMatrixCreateFromMat_omp: the nthread argument configures the
    native ParallelFor pool for THIS ingest (utils/native.py; 0/negative =
    default), the scope omp_set_num_threads has in the reference's _omp
    path — the prior width is restored afterwards."""
    from .utils import native

    prev = native.get_nthread()
    native.set_nthread(int(nthread))
    try:
        return dmatrix_from_mat(addr, nrow, ncol, missing)
    finally:
        native.set_nthread(prev)


_PIN_DICT_LOCK = __import__("threading").Lock()


def _pin_per_thread(owner, tag: str, objs) -> None:
    """Pin result buffers per (handle, calling thread) — the reference's
    XGBAPIThreadLocalEntry convention (c_api.cc).  Concurrent read-only
    callers through the narrowed C-API dispatch (native/xtb_capi.cc
    API_BEGIN_READ) each keep their own last return alive; a buffer dies on
    the same thread's next same-kind call on the handle or with the handle.
    Dict creation is locked: two first-callers racing getattr/setattr would
    otherwise orphan one thread's dict — and free its just-returned
    buffer — mid-read."""
    import threading

    d = getattr(owner, tag, None)
    if d is None:
        with _PIN_DICT_LOCK:
            d = getattr(owner, tag, None)
            if d is None:
                d = {}
                setattr(owner, tag, d)
    d[threading.get_ident()] = objs
    if len(d) > 64:
        # thread-per-request embedders would otherwise pin one buffer per
        # dead thread ident forever; prune entries whose thread is gone
        # (the reference's thread_local entries die at thread exit)
        live = {t.ident for t in threading.enumerate()}
        with _PIN_DICT_LOCK:
            for ident in [k for k in d if k not in live]:
                d.pop(ident, None)


def _drop_missing_csr(csr, missing: float):
    """Remove entries that mean "missing" (NaN, or == missing when the
    sentinel is finite) so the stored sparsity pattern IS the non-missing
    set — the reference filters at construction (src/data/adapter.h
    IsValidFunctor), which keeps XGDMatrixNumNonMissing consistent with
    XGDMatrixGetDataAsCSR."""
    import scipy.sparse as sp

    coo = csr.tocoo()
    vals = np.asarray(coo.data, np.float32)
    keep = np.isfinite(vals)
    if missing is not None and not np.isnan(missing):
        keep &= vals != np.float32(missing)
    if keep.all():
        return csr
    return sp.csr_matrix(
        (vals[keep], (coo.row[keep], coo.col[keep])), shape=csr.shape)


def dmatrix_from_csr(indptr_addr: int, indices_addr: int, data_addr: int,
                     n_indptr: int, nnz: int, ncol: int) -> DMatrix:
    import scipy.sparse as sp

    indptr = _buf(indptr_addr, n_indptr, np.uint64).astype(np.int64)
    indices = _buf(indices_addr, nnz, np.uint32).astype(np.int64)
    data = _buf(data_addr, nnz, np.float32)
    csr = sp.csr_matrix((data, indices, indptr), shape=(n_indptr - 1, ncol))
    return DMatrix(_drop_missing_csr(csr, np.nan))


def dmatrix_set_float_info(d, field: str, addr: int, n: int) -> None:
    vals = _buf(addr, n, np.float32)
    if isinstance(d, _ProxyDMatrix):
        # iterator protocol: meta staged on the proxy rides into input_data
        d.kwargs[field] = vals
        return
    if field == "label":
        d.set_label(vals)
    elif field == "weight":
        d.set_weight(vals)
    elif field == "base_margin":
        d.set_base_margin(vals)
    elif field == "label_lower_bound":
        d.info.label_lower_bound = vals
    elif field == "label_upper_bound":
        d.info.label_upper_bound = vals
    else:
        raise ValueError(f"unknown float field {field!r}")


def dmatrix_set_uint_info(d, field: str, addr: int, n: int) -> None:
    vals = _buf(addr, n, np.uint32)
    if isinstance(d, _ProxyDMatrix):
        d.kwargs[field] = vals
        return
    if field == "group":
        d.set_group(vals.astype(np.int64))
    else:
        raise ValueError(f"unknown uint field {field!r}")


def dmatrix_num_row(d: DMatrix) -> int:
    return int(d.num_row())


def dmatrix_num_col(d: DMatrix) -> int:
    return int(d.num_col())


def booster_create(dmats: List[DMatrix]) -> Booster:
    return Booster(cache=list(dmats))


def booster_set_param(b: Booster, name: str, value: Optional[str]) -> None:
    if name == "eval_metric" and value is not None:
        # repeated SetParam("eval_metric", ...) calls APPEND (reference
        # Learner::SetParam semantics — c_api consumers configure multiple
        # metrics exactly this way, e.g. the R binding's metrics vector)
        cur = b.params.get("eval_metric")
        if cur is not None:
            lst = cur if isinstance(cur, list) else [cur]
            if value not in lst:
                b.set_param(name, lst + [value])
            return
    b.set_param(name, value)


def booster_update_one_iter(b: Booster, it: int, dtrain: DMatrix) -> None:
    b.update(dtrain, it)


def booster_boost_one_iter(b: Booster, dtrain: DMatrix, grad_addr: int,
                           hess_addr: int, n: int) -> None:
    b.boost(dtrain, _buf(grad_addr, n, np.float32),
            _buf(hess_addr, n, np.float32))


def booster_eval_one_iter(b: Booster, it: int, dmats: List[DMatrix],
                          names: List[str]) -> bytes:
    msg = b.eval_set(list(zip(dmats, names)), it)
    out = msg.encode()
    b._capi_eval_str = out  # pinned (c_api.cc ret_str convention)
    return out


def booster_predict(b: Booster, d: DMatrix, option_mask: int,
                    ntree_limit: int, training: int):
    """Legacy XGBoosterPredict semantics (c_api.cc):
    option_mask 1 = margin, 2 = contribs, 4 = approx contribs, 8 = leaf,
    16 = interactions; ntree_limit counts TREES and converts to boosting
    rounds via trees_per_round (c_api.cc GetIterationFromTreeLimit)."""
    if ntree_limit:
        b._configure()
        tpr = max(b.trees_per_round, 1)
        it_range = (0, -(-int(ntree_limit) // tpr))  # ceil division
    else:
        it_range = (0, 0)
    kw = dict(iteration_range=it_range, training=bool(training))
    if option_mask & 8:
        out = b.predict(d, pred_leaf=True, **kw)
    elif option_mask & 16:
        out = b.predict(d, pred_interactions=True, **kw)
    elif option_mask & 4:
        out = b.predict(d, pred_contribs=True, approx_contribs=True, **kw)
    elif option_mask & 2:
        out = b.predict(d, pred_contribs=True, **kw)
    else:
        out = b.predict(d, output_margin=bool(option_mask & 1), **kw)
    out = np.ascontiguousarray(np.asarray(out, np.float32).reshape(-1))
    # alive until this thread's next predict on b (per-thread pinning keeps
    # concurrent shared-lock readers from freeing each other's returns)
    _pin_per_thread(b, "_capi_pred_pin", (out,))
    return int(out.size), int(out.ctypes.data)


def booster_save_model(b: Booster, path: str) -> None:
    b.save_model(path)


def booster_load_model(b: Booster, path: str) -> None:
    b.load_model(path)


def booster_save_raw(b: Booster, raw_format: str) -> tuple:
    buf = bytes(b.save_raw(raw_format))
    _pin_per_thread(b, "_capi_raw_buf", (buf,))
    return len(buf), buf


def booster_load_raw(b: Booster, addr: int, n: int) -> None:
    b.load_model(bytes(_buf(addr, n, np.uint8)))


def booster_get_attr(b: Booster, name: str):
    v = b.attr(name)
    if v is None:
        return None
    out = v.encode()
    _pin_per_thread(b, "_capi_attr_str", (out,))
    return out


def booster_set_attr(b: Booster, name: str, value: Optional[str]) -> None:
    b.set_attr(**{name: value})


def booster_num_boosted_rounds(b: Booster) -> int:
    return int(b.num_boosted_rounds())


def booster_num_features(b: Booster) -> int:
    return int(b.num_features())


def booster_get_categories(b: Booster) -> bytes:
    """JSON category mapping (reference: XGBoosterGetCategories,
    src/data/cat_container.h) — ``null`` when trained without categories."""
    out = json.dumps(b.get_categories()).encode()
    _pin_per_thread(b, "_capi_categories_buf", (out,))  # C caller borrows
    return out


def dmatrix_get_categories(d: DMatrix) -> bytes:
    out = json.dumps(d.get_categories()).encode()
    _pin_per_thread(d, "_capi_categories_buf", (out,))
    return out


# =====================================================================
# Round-3 surface expansion: array-interface ingestion, inplace predict,
# DataIter callbacks, dump/slice/feature-info, config IO, collective +
# tracker C API (reference: include/xgboost/c_api.h; src/c_api/c_api.cc,
# src/c_api/coll_c_api.cc).

def _from_array_interface(spec) -> np.ndarray:
    """Decode a JSON-encoded numpy __array_interface__ (the reference's
    ArrayInterface, src/data/array_interface.h) into a host copy."""
    if isinstance(spec, (str, bytes)):
        spec = json.loads(spec)
    dt = np.dtype(str(spec["typestr"]))
    shape = tuple(int(s) for s in spec["shape"])
    n = int(np.prod(shape)) if shape else 1
    if spec.get("strides") not in (None, []):
        raise ValueError("strided array interface is not supported; pass a "
                         "C-contiguous array")
    addr = int(spec["data"][0])
    ctype = ctypes.c_char * (n * dt.itemsize)
    raw = ctype.from_address(addr)
    return np.frombuffer(bytes(raw), dtype=dt).reshape(shape).copy()


def _pin_str_array(owner, tag: str, strings):
    """Build a NUL-terminated char** pinned per (owner, thread); returns
    (len, address).  The reference keeps such returns in per-handle
    thread-local entries (c_api.cc XGBAPIThreadLocalEntry) — per-thread
    storage is what keeps the shared-lock READ entry points
    (native/xtb_capi.cc API_BEGIN_READ) from freeing each other's
    returns on one handle."""
    bufs = [str(s).encode() for s in strings]
    arr = (ctypes.c_char_p * len(bufs))(*bufs)
    _pin_per_thread(owner, tag, (bufs, arr))  # keep both alive
    return len(bufs), ctypes.addressof(arr) if bufs else 0


def _pin_array(owner, tag: str, arr: np.ndarray):
    arr = np.ascontiguousarray(arr)
    _pin_per_thread(owner, tag, (arr,))
    return int(arr.size), int(arr.ctypes.data)


def _cfg(config) -> dict:
    return json.loads(config) if config else {}


# ------------------------------------------------------------- DMatrix
def dmatrix_from_dense(data_json: str, config: str) -> DMatrix:
    c = _cfg(config)
    X = _from_array_interface(data_json).astype(np.float32)
    return DMatrix(X, missing=float(c.get("missing", np.nan)))


def dmatrix_from_csr_ai(indptr_j: str, indices_j: str, data_j: str,
                        ncol: int, config: str) -> DMatrix:
    import scipy.sparse as sp

    c = _cfg(config)
    indptr = _from_array_interface(indptr_j).astype(np.int64)
    indices = _from_array_interface(indices_j).astype(np.int64)
    data = _from_array_interface(data_j).astype(np.float32)
    missing = float(c.get("missing", np.nan))
    csr = sp.csr_matrix((data, indices, indptr),
                        shape=(len(indptr) - 1, int(ncol)))
    return DMatrix(_drop_missing_csr(csr, missing))


def dmatrix_from_uri(config: str) -> DMatrix:
    c = _cfg(config)
    uri = c["uri"]
    try:  # XGDMatrixSaveBinary snapshots round-trip through the URI loader
        with np.load(uri, allow_pickle=False) as z:
            return _dmatrix_from_npz(z)
    except (OSError, ValueError):
        pass
    return DMatrix(str(uri))


def _dmatrix_from_npz(z) -> DMatrix:
    if "csr_indptr" in z:
        import scipy.sparse as sp

        X = sp.csr_matrix((z["csr_data"], z["csr_indices"], z["csr_indptr"]),
                          shape=tuple(z["shape"]))
    else:
        X = z["dense"]
    d = DMatrix(X)
    for field in ("label", "weight", "base_margin", "label_lower_bound",
                  "label_upper_bound", "group_ptr"):
        if field in z:
            setattr(d.info, field, z[field])
    if "feature_names" in z:
        d.info.feature_names = [str(s) for s in z["feature_names"]]
    if "feature_types" in z:
        d.info.feature_types = [str(s) for s in z["feature_types"]]
    return d


def dmatrix_save_binary(d: DMatrix, fname: str, silent: int) -> None:
    """Own snapshot format (npz): the reference's binary DMatrix format is
    version-locked internal state, not a portability contract."""
    out = {}
    if d._kind == "dense":
        out["dense"] = d.host_dense()
    else:
        indptr, indices, values, shape = d._csr
        out.update(csr_indptr=indptr, csr_indices=indices, csr_data=values,
                   shape=np.asarray(shape))
    info = d.info
    for field in ("label", "weight", "base_margin", "label_lower_bound",
                  "label_upper_bound", "group_ptr"):
        v = getattr(info, field, None)
        if v is not None:
            out[field] = np.asarray(v)
    if info.feature_names:
        out["feature_names"] = np.asarray(info.feature_names, dtype="U")
    if info.feature_types:
        out["feature_types"] = np.asarray(info.feature_types, dtype="U")
    with open(fname, "wb") as fh:  # file object: np.savez won't append .npz
        np.savez(fh, **out)


def dmatrix_slice(d: DMatrix, idx_addr: int, n: int,
                  allow_groups: int) -> DMatrix:
    idx = _buf(idx_addr, n, np.int32).astype(np.int64)
    if not allow_groups and d.info.group_ptr is not None:
        # the plain slice API refuses grouped matrices like the reference
        # (c_api.cc CHECK on group); the Ex variant opts in
        raise ValueError("slicing a DMatrix with query groups requires "
                         "XGDMatrixSliceDMatrixEx with allow_groups=1")
    return d.slice(idx)


def dmatrix_set_str_feature_info(d: DMatrix, field: str, names) -> None:
    if field == "feature_name":
        d.info.feature_names = [str(s) for s in names] or None
    elif field == "feature_type":
        d.info.feature_types = [str(s) for s in names] or None
    else:
        raise ValueError(f"unknown string feature field {field!r}")


def dmatrix_get_str_feature_info(d: DMatrix, field: str):
    if field == "feature_name":
        vals = d.info.feature_names or []
    elif field == "feature_type":
        vals = d.info.feature_types or []
    else:
        raise ValueError(f"unknown string feature field {field!r}")
    return _pin_str_array(d, "_capi_strinfo", vals)


def dmatrix_get_float_info(d: DMatrix, field: str):
    v = getattr(d.info, field, None)
    if field not in ("label", "weight", "base_margin", "label_lower_bound",
                     "label_upper_bound", "feature_weights"):
        raise ValueError(f"unknown float field {field!r}")
    arr = (np.zeros(0, np.float32) if v is None
           else np.asarray(v, np.float32).reshape(-1))
    return _pin_array(d, "_capi_finfo", arr)


def dmatrix_get_uint_info(d: DMatrix, field: str):
    if field != "group_ptr":
        raise ValueError(f"unknown uint field {field!r}")
    v = d.info.group_ptr
    arr = (np.zeros(0, np.uint32) if v is None
           else np.asarray(v, np.uint32).reshape(-1))
    return _pin_array(d, "_capi_uinfo", arr)


def dmatrix_num_nonmissing(d: DMatrix) -> int:
    if d._kind == "dense":
        return int(np.isfinite(d.host_dense()).sum())
    indptr, _i, values, _s = d._csr
    return int(np.isfinite(values).sum())


def dmatrix_data_split_mode(d: DMatrix) -> int:
    return 0  # kRow; column split is not supported on this runtime


def dmatrix_get_data_as_csr(d: DMatrix, config: str):
    if d._kind == "dense":
        import scipy.sparse as sp

        X = d.host_dense()
        mask = np.isfinite(X)
        # build from the mask directly so real zeros stay explicit
        rows, cols = np.nonzero(mask)
        csr = sp.csr_matrix((X[rows, cols], (rows, cols)), shape=X.shape)
        indptr, indices, values = csr.indptr, csr.indices, csr.data
    else:
        indptr, indices, values, _shape = d._csr
        finite = np.isfinite(np.asarray(values, np.float32))
        if not finite.all():
            # keep the export consistent with XGDMatrixNumNonMissing when
            # the stored pattern still carries explicit-NaN entries
            cum = np.concatenate([[0], np.cumsum(finite)])
            indptr = cum[np.asarray(indptr, np.int64)]
            indices = np.asarray(indices)[finite]
            values = np.asarray(values)[finite]
    ip = np.ascontiguousarray(indptr, np.uint64)
    ix = np.ascontiguousarray(indices, np.uint32)
    va = np.ascontiguousarray(values, np.float32)
    d._capi_csr = (ip, ix, va)
    return (int(ip.ctypes.data), int(ix.ctypes.data), int(va.ctypes.data),
            int(ip.size), int(va.size))


def dmatrix_get_quantile_cut(d: DMatrix, config: str):
    cuts = getattr(d, "_cuts", None)
    if cuts is None:
        ell = getattr(d, "_ellpack", None)
        if ell is None:
            raise ValueError(
                "DMatrix carries no quantile cuts; construct a "
                "QuantileDMatrix or train first (reference: "
                "XGDMatrixGetQuantileCut requires a binned matrix)")
        cuts = ell.cuts
    indptr = np.ascontiguousarray(cuts.cut_ptrs, np.uint64)
    values = np.ascontiguousarray(cuts.cut_values, np.float32)
    d._capi_qcut = (indptr, values)
    ip_json = json.dumps({"data": [int(indptr.ctypes.data), True],
                          "shape": [int(indptr.size)], "typestr": "<u8",
                          "version": 3}).encode()
    va_json = json.dumps({"data": [int(values.ctypes.data), True],
                          "shape": [int(values.size)], "typestr": "<f4",
                          "version": 3}).encode()
    d._capi_qcut_json = (ip_json, va_json)
    return ip_json, va_json


# ---------------------------------------------- proxy + DataIter callbacks
class _ProxyDMatrix:
    """Staging slot filled by XGProxyDMatrixSetData* between iterator
    callbacks (reference: src/data/proxy_dmatrix.h)."""

    def __init__(self) -> None:
        self.data = None
        self.kwargs = {}

    def set_dense(self, array_if: str) -> None:
        self.data = _from_array_interface(array_if).astype(np.float32)

    def set_csr(self, indptr_j: str, indices_j: str, data_j: str,
                ncol: int) -> None:
        import scipy.sparse as sp

        indptr = _from_array_interface(indptr_j).astype(np.int64)
        indices = _from_array_interface(indices_j).astype(np.int64)
        data = _from_array_interface(data_j).astype(np.float32)
        self.data = sp.csr_matrix((data, indices, indptr),
                                  shape=(len(indptr) - 1, int(ncol)))

    def set_info(self, field: str, addr: int, n: int, dtype) -> None:
        self.kwargs[field] = _buf(addr, n, dtype)


def proxy_create() -> "_ProxyDMatrix":
    return _ProxyDMatrix()


def proxy_set_dense(p: "_ProxyDMatrix", array_if: str) -> None:
    p.set_dense(array_if)


def proxy_set_csr(p: "_ProxyDMatrix", indptr_j: str, indices_j: str,
                  data_j: str, ncol: int) -> None:
    p.set_csr(indptr_j, indices_j, data_j, ncol)


from .data.extmem import DataIter as _DataIter  # noqa: E402


class _CCallbackIter(_DataIter):
    """Adapts the C iterator protocol (reset/next function pointers +
    proxy handle) onto the Python DataIter protocol."""

    def __init__(self, iter_addr: int, proxy: "_ProxyDMatrix",
                 reset_addr: int, next_addr: int,
                 cache_prefix=None) -> None:
        super().__init__(cache_prefix=cache_prefix)
        self._reset_fn = ctypes.CFUNCTYPE(None, ctypes.c_void_p)(reset_addr)
        self._next_fn = ctypes.CFUNCTYPE(
            ctypes.c_int, ctypes.c_void_p)(next_addr)
        self._iter_addr = iter_addr
        self._proxy = proxy

    def reset(self) -> None:
        self._reset_fn(self._iter_addr)

    def next(self, input_data) -> int:
        self._proxy.data = None
        self._proxy.kwargs = {}
        status = int(self._next_fn(self._iter_addr))
        if not status:
            return 0
        if self._proxy.data is None:
            raise RuntimeError("iterator next() returned 1 without staging "
                               "data on the proxy DMatrix")
        input_data(data=self._proxy.data, **self._proxy.kwargs)
        return 1


def _iter_batches(it: "_CCallbackIter"):
    from .data.extmem import _iterate

    batches = list(_iterate(it))
    if not batches:
        raise ValueError("iterator produced no batches")
    return batches


def _assemble_from_batches(batches, missing: float) -> DMatrix:
    import scipy.sparse as sp

    mats = [b["data"] for b in batches]
    if any(sp.issparse(m) for m in mats):
        X = sp.vstack([sp.csr_matrix(m) for m in mats])
    else:
        X = np.concatenate([np.asarray(m) for m in mats], axis=0)
    kw = {}
    for field in ("label", "weight", "base_margin", "label_lower_bound",
                  "label_upper_bound", "group", "qid"):
        if all(field in b for b in batches):
            kw[field] = np.concatenate(
                [np.asarray(b[field]).reshape(len(b[field]), -1)
                 for b in batches]).squeeze()
        elif any(field in b for b in batches):
            raise ValueError(
                f"iterator staged {field!r} on some batches but not all")
    d = DMatrix(X, missing=missing, **{k: v for k, v in kw.items()
                                       if k == "label"})
    if "weight" in kw:
        d.set_weight(kw["weight"])
    if "base_margin" in kw:
        d.set_base_margin(kw["base_margin"])
    if "label_lower_bound" in kw:
        d.info.label_lower_bound = np.asarray(kw["label_lower_bound"],
                                              np.float32)
    if "label_upper_bound" in kw:
        d.info.label_upper_bound = np.asarray(kw["label_upper_bound"],
                                              np.float32)
    # group arrives as per-batch COUNT vectors (each batch's groups are
    # contiguous), qid as per-row ids — both concatenate directly
    if "qid" in kw:
        d.set_qid(kw["qid"])
    elif "group" in kw:
        d.set_group(np.asarray(kw["group"], np.int64))
    return d


def dmatrix_from_callback(iter_addr: int, proxy, reset_addr: int,
                          next_addr: int, config: str) -> DMatrix:
    """XGDMatrixCreateFromCallback: raw-path external iterator, backed by
    SparsePageDMatrix (sparse_page_dmatrix.h role) — raw CSR pages spill
    (zstd / disk with cache_prefix-style on_host=False), training replays
    them through the binned extmem passes, prediction streams the raw
    pages with exact thresholds."""
    from .data.extmem import SparsePageDMatrix

    c = _cfg(config)
    it = _CCallbackIter(iter_addr, proxy, reset_addr, next_addr,
                        cache_prefix=c.get("cache_prefix"))
    d = SparsePageDMatrix(it, missing=float(c.get("missing", np.nan)),
                          max_bin=int(c.get("max_bin", 256)),
                          on_host=c.get("cache_prefix") is None)
    # meta the binned ingestion doesn't collect: group/qid/label bounds
    # staged on the proxy per batch
    for field, setter in (("qid", d.set_qid),
                          ("group", lambda v: d.set_group(
                              np.asarray(v, np.int64)))):
        vals = [m[field] for m in d._raw_meta if field in m]
        if vals:
            if len(vals) != len(d._raw_meta):
                raise ValueError(
                    f"iterator staged {field!r} on some batches but not all")
            setter(np.concatenate([np.asarray(v).reshape(-1) for v in vals]))
            break  # qid wins; group counts concatenate after it
    for field in ("label_lower_bound", "label_upper_bound"):
        vals = [m[field] for m in d._raw_meta if field in m]
        if vals:
            setattr(d.info, field,
                    np.concatenate([np.asarray(v, np.float32).reshape(-1)
                                    for v in vals]))
    return d


def quantile_dmatrix_from_callback(iter_addr: int, proxy, ref,
                                   reset_addr: int, next_addr: int,
                                   config: str) -> DMatrix:
    from .data.dmatrix import QuantileDMatrix

    c = _cfg(config)
    it = _CCallbackIter(iter_addr, proxy, reset_addr, next_addr)
    base = _assemble_from_batches(_iter_batches(it),
                                  float(c.get("missing", np.nan)))
    if base._kind == "dense":
        raw = base.host_dense()
    else:
        import scipy.sparse as sp

        indptr, indices, values, shape = base._csr
        raw = sp.csr_matrix((values, indices, indptr), shape=shape)
    q = QuantileDMatrix(raw, max_bin=int(c.get("max_bin", 256)), ref=ref)
    q.info = base.info
    return q


def extmem_quantile_dmatrix_from_callback(iter_addr: int, proxy, ref,
                                          reset_addr: int, next_addr: int,
                                          config: str) -> DMatrix:
    from .data.extmem import ExtMemQuantileDMatrix

    c = _cfg(config)
    it = _CCallbackIter(iter_addr, proxy, reset_addr, next_addr,
                        cache_prefix=c.get("cache_prefix"))
    return ExtMemQuantileDMatrix(
        it, max_bin=int(c.get("max_bin", 256)), ref=ref,
        missing=float(c.get("missing", np.nan)),
        on_host=bool(c.get("on_host", True)))


# ------------------------------------------------------------- Booster
def booster_reset(b: Booster) -> None:
    b._caches.clear()


def booster_slice(b: Booster, begin: int, end: int, step: int) -> Booster:
    if end == 0:
        end = b.num_boosted_rounds()
    return b[begin:end:(step or 1)]


def booster_train_one_iter(b: Booster, dtrain: DMatrix, it: int,
                           grad_j: str, hess_j: str) -> None:
    grad = _from_array_interface(grad_j).astype(np.float32)
    hess = _from_array_interface(hess_j).astype(np.float32)
    b.boost(dtrain, grad.reshape(grad.shape[0], -1),
            hess.reshape(hess.shape[0], -1))


def _predict_with_config(b: Booster, d: DMatrix, c: dict):
    t = int(c.get("type", 0))
    it_range = (int(c.get("iteration_begin", 0)),
                int(c.get("iteration_end", 0)))
    kw = dict(iteration_range=it_range,
              training=bool(c.get("training", False)))
    if t == 6:
        out = b.predict(d, pred_leaf=True, **kw)
    elif t in (4, 5):
        out = b.predict(d, pred_interactions=True,
                        approx_contribs=(t == 5), **kw)
    elif t in (2, 3):
        out = b.predict(d, pred_contribs=True, approx_contribs=(t == 3), **kw)
    else:
        out = b.predict(d, output_margin=(t == 1), **kw)
    out = np.asarray(out, np.float32)
    if bool(c.get("strict_shape", False)) and out.ndim == 1:
        out = out.reshape(-1, 1)
    shape = np.asarray(out.shape, np.uint64)
    flat = np.ascontiguousarray(out.reshape(-1))
    _pin_per_thread(b, "_capi_pred_pin", (flat, shape))
    return (int(shape.ctypes.data), int(shape.size),
            int(flat.ctypes.data))


def booster_predict_from_dmatrix(b: Booster, d: DMatrix, config: str):
    return _predict_with_config(b, d, _cfg(config))


def booster_inplace_predict_dense(b: Booster, values_j: str, config: str,
                                  meta: Optional[DMatrix]):
    c = _cfg(config)
    X = _from_array_interface(values_j).astype(np.float32)
    missing = float(c.get("missing", np.nan))
    if not np.isnan(missing):
        X = np.where(X == missing, np.nan, X)
    d = DMatrix(X)
    if meta is not None:
        d.info = meta.info
    return _predict_with_config(b, d, c)


def booster_inplace_predict_csr(b: Booster, indptr_j: str, indices_j: str,
                                values_j: str, ncol: int, config: str,
                                meta: Optional[DMatrix]):
    import scipy.sparse as sp

    c = _cfg(config)
    indptr = _from_array_interface(indptr_j).astype(np.int64)
    indices = _from_array_interface(indices_j).astype(np.int64)
    values = _from_array_interface(values_j).astype(np.float32)
    missing = float(c.get("missing", np.nan))
    csr = sp.csr_matrix((values, indices, indptr),
                        shape=(len(indptr) - 1, int(ncol)))
    d = DMatrix(_drop_missing_csr(csr, missing))
    if meta is not None:
        d.info = meta.info
    return _predict_with_config(b, d, c)


def booster_serialize(b: Booster):
    buf = bytes(b.serialize())
    _pin_per_thread(b, "_capi_serial_buf", (buf,))
    return len(buf), buf


def booster_unserialize(b: Booster, addr: int, n: int) -> None:
    b.unserialize(bytes(_buf(addr, n, np.uint8)))


def booster_save_json_config(b: Booster):
    out = b.save_config().encode()
    _pin_per_thread(b, "_capi_config_str", (out,))
    return len(out), out


def booster_load_json_config(b: Booster, config: str) -> None:
    b.load_config(config)


def booster_dump_model(b: Booster, fmap: str, with_stats: int, fmt: str,
                       fnames=None, ftypes=None):
    if fnames:
        # display names for THIS dump only — the reference builds a local
        # FeatureMap and leaves the learner untouched
        names = list(fnames)
        fmt = fmt or "text"
        if fmt == "json":
            dumps = [t.dump_json(names, bool(with_stats)) for t in b.trees]
        else:
            dumps = [t.dump_text(names, bool(with_stats)) for t in b.trees]
    else:
        dumps = b.get_dump(fmap=fmap or "", with_stats=bool(with_stats),
                           dump_format=fmt or "text")
    return _pin_str_array(b, "_capi_dump", dumps)


def booster_get_attr_names(b: Booster):
    return _pin_str_array(b, "_capi_attr_names", sorted(b.attributes))


def booster_set_str_feature_info(b: Booster, field: str, names) -> None:
    if field == "feature_name":
        b.feature_names = [str(s) for s in names] or None
    elif field == "feature_type":
        b.feature_types = [str(s) for s in names] or None
    else:
        raise ValueError(f"unknown string feature field {field!r}")


def booster_get_str_feature_info(b: Booster, field: str):
    if field == "feature_name":
        vals = b.feature_names or []
    elif field == "feature_type":
        vals = b.feature_types or []
    else:
        raise ValueError(f"unknown string feature field {field!r}")
    return _pin_str_array(b, "_capi_feat_strinfo", vals)


def booster_feature_score(b: Booster, config: str):
    c = _cfg(config)
    imp = b.get_score(importance_type=str(c.get("importance_type", "weight")))
    feats = sorted(imp)
    scores = np.asarray([imp[f] for f in feats], np.float32)
    n, feat_addr = _pin_str_array(b, "_capi_score_feats", feats)
    shape = np.asarray([len(feats)], np.uint64)
    _pin_per_thread(b, "_capi_score_out", (shape, scores))
    return (n, feat_addr, int(shape.ctypes.data), 1,
            int(scores.ctypes.data))


# ------------------------------------------------------------- globals
_build_info_str = None


def build_info() -> bytes:
    global _build_info_str
    if _build_info_str is None:
        import jax

        _build_info_str = json.dumps({
            "USE_TPU": True, "USE_CUDA": False, "USE_NCCL": False,
            "USE_FEDERATED": True, "JAX_VERSION": jax.__version__,
            "libc": "glibc", "BUILTIN_PREFETCH_PRESENT": True,
        }).encode()
    return _build_info_str


_global_config_str = None


def set_global_config(config: str) -> None:
    from . import config as _config

    _config.set_config(**json.loads(config))


def get_global_config() -> bytes:
    global _global_config_str
    from . import config as _config

    _global_config_str = json.dumps(_config.get_config()).encode()
    return _global_config_str


# ------------------------------------------------- collective + tracker
def communicator_init(config: str) -> None:
    from . import collective

    c = _cfg(config)
    collective.init(**{k.lower(): v for k, v in c.items()})


def communicator_finalize() -> None:
    from . import collective

    collective.finalize()


def communicator_get_rank() -> int:
    from . import collective

    return collective.get_rank()


def communicator_get_world_size() -> int:
    from . import collective

    return collective.get_world_size()


def communicator_is_distributed() -> int:
    from . import collective

    return int(collective.is_distributed())


def communicator_print(msg: str) -> None:
    from . import collective

    collective.communicator_print(msg)


_procname_buf = None


def communicator_get_processor_name() -> bytes:
    global _procname_buf
    from . import collective

    _procname_buf = collective.get_processor_name().encode()
    return _procname_buf


def communicator_broadcast(addr: int, size: int, root: int) -> None:
    from . import collective

    buf = _buf(addr, size, np.uint8)
    out = collective.broadcast(buf.tobytes(), root)
    ctypes.memmove(addr, bytes(out), size)


_ALLREDUCE_DTYPES = {0: np.float16, 1: np.float32, 2: np.float64,
                     4: np.int8, 5: np.int16, 6: np.int32, 7: np.int64,
                     8: np.uint8, 9: np.uint16, 10: np.uint32, 11: np.uint64}


def communicator_allreduce(addr: int, count: int, data_type: int,
                           op: int) -> None:
    from . import collective

    dt = _ALLREDUCE_DTYPES[int(data_type)]
    buf = _buf(addr, count, dt)
    out = np.asarray(collective.allreduce(buf, collective.Op(op)), dt)
    ctypes.memmove(addr, np.ascontiguousarray(out).ctypes.data,
                   count * np.dtype(dt).itemsize)


def tracker_create(config: str):
    from .tracker import RabitTracker

    c = _cfg(config)
    return RabitTracker(
        n_workers=int(c.get("n_workers", c.get("n_trees", 0)) or 0),
        host_ip=str(c.get("host", c.get("host_ip", "auto")) or "auto"),
        port=int(c.get("port", 0) or 0),
        sortby=str(c.get("sortby", "host")),
        timeout=int(c.get("timeout", 0) or 0))


def tracker_worker_args(t) -> bytes:
    out = json.dumps({k: str(v) for k, v in t.worker_args().items()}).encode()
    t._capi_args_str = out
    return out


def tracker_run(t, config: str) -> None:
    t.start()


def tracker_wait_for(t, config: str) -> None:
    c = _cfg(config)
    t.wait_for(timeout=int(c.get("timeout", 0) or 0))


def tracker_free(t) -> None:
    t.free()


# ---- columnar / CSC / info-interface ingestion ----
def _columnar_to_dense(data_json) -> np.ndarray:
    """Columnar table = JSON list of per-column __array_interface__ objects
    (reference: src/data/adapter.h ColumnarAdapter, arrow layout)."""
    cols = json.loads(data_json) if isinstance(data_json, (str, bytes)) else data_json
    out = []
    for spec in cols:
        if isinstance(spec, dict) and "mask" in spec:
            vals = _from_array_interface(spec).reshape(-1).astype(np.float32)
            mask_spec = spec["mask"]
            bits = _from_array_interface(mask_spec).reshape(-1)
            valid = np.unpackbits(bits.view(np.uint8),
                                  bitorder="little")[: len(vals)].astype(bool)
            vals = np.where(valid, vals, np.nan)
        else:
            vals = _from_array_interface(spec).reshape(-1).astype(np.float32)
        out.append(vals)
    return np.stack(out, axis=1)


def dmatrix_from_columnar(data_json: str, config: str) -> DMatrix:
    c = _cfg(config)
    return DMatrix(_columnar_to_dense(data_json),
                   missing=float(c.get("missing", np.nan)))


def proxy_set_columnar(p: "_ProxyDMatrix", data_json: str) -> None:
    p.data = _columnar_to_dense(data_json)


def booster_inplace_predict_columnar(b: Booster, values_j: str, config: str,
                                     meta: Optional[DMatrix]):
    c = _cfg(config)
    d = DMatrix(_columnar_to_dense(values_j))
    if meta is not None:
        d.info = meta.info
    return _predict_with_config(b, d, c)


def dmatrix_from_csc_ai(indptr_j: str, indices_j: str, data_j: str,
                        nrow: int, config: str) -> DMatrix:
    import scipy.sparse as sp

    c = _cfg(config)
    indptr = _from_array_interface(indptr_j).astype(np.int64)
    indices = _from_array_interface(indices_j).astype(np.int64)
    data = _from_array_interface(data_j).astype(np.float32)
    missing = float(c.get("missing", np.nan))
    csc = sp.csc_matrix((data, indices, indptr),
                        shape=(int(nrow), len(indptr) - 1))
    return DMatrix(_drop_missing_csr(csc.tocsr(), missing))


_INFO_FLOAT_FIELDS = ("label", "weight", "base_margin", "label_lower_bound",
                      "label_upper_bound", "feature_weights")


def dmatrix_set_info_from_interface(d: DMatrix, field: str,
                                    data_json: str) -> None:
    arr = _from_array_interface(data_json)
    if field in _INFO_FLOAT_FIELDS:
        dmatrix_set_float_info_values(d, field, arr.astype(np.float32))
    elif field in ("group", "qid"):
        if field == "qid":
            d.set_qid(arr.astype(np.int64).reshape(-1))
        else:
            d.set_group(arr.astype(np.int64).reshape(-1))
    else:
        raise ValueError(f"unknown info field {field!r}")


def dmatrix_set_float_info_values(d: DMatrix, field: str,
                                  vals: np.ndarray) -> None:
    if field == "label":
        d.set_label(vals)
    elif field == "weight":
        d.set_weight(vals)
    elif field == "base_margin":
        d.set_base_margin(vals)
    elif field == "feature_weights":
        d.info.feature_weights = vals
    else:
        setattr(d.info, field, vals)


def dmatrix_set_dense_info(d: DMatrix, field: str, addr: int, n: int,
                           dtype_code: int) -> None:
    # xgboost::DataType: 1=f32 2=f64 3=u32 4=u64
    dt = {1: np.float32, 2: np.float64, 3: np.uint32, 4: np.uint64}[dtype_code]
    arr = _buf(addr, n, dt)
    if field in ("group", "qid"):
        if field == "qid":
            d.set_qid(arr.astype(np.int64))
        else:
            d.set_group(arr.astype(np.int64))
    else:
        dmatrix_set_float_info_values(d, field, arr.astype(np.float32))


def dmatrix_get_info_ref(d: DMatrix, field: str) -> bytes:
    """Array-interface JSON view of an info field (XGDMatrixGetInfoRef)."""
    if field in _INFO_FLOAT_FIELDS:
        v = getattr(d.info, field, None)
        arr = (np.zeros(0, np.float32) if v is None
               else np.ascontiguousarray(v, np.float32))
    elif field == "group_ptr":
        v = d.info.group_ptr
        arr = (np.zeros(0, np.uint64) if v is None
               else np.ascontiguousarray(v, np.uint64))
    else:
        raise ValueError(f"unknown info field {field!r}")
    d._capi_inforef = arr
    out = json.dumps({"data": [int(arr.ctypes.data), True],
                      "shape": [int(arr.size)], "typestr": arr.dtype.str,
                      "version": 3}).encode()
    d._capi_inforef_json = out
    return out
