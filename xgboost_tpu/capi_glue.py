"""Python side of the C API (reference: include/xgboost/c_api.h,
src/c_api/c_api.cc).

native/xtb_capi.cc embeds CPython and calls these helpers with raw buffer
addresses; everything heavy (array construction, training, prediction)
happens here so the C layer stays a thin ABI shim.  Results that must
outlive a call (prediction buffers, eval strings) are pinned on the owning
handle object, mirroring the reference's per-handle XGBAPIThreadLocalEntry
return-buffer convention (c_api.cc).
"""
from __future__ import annotations

import ctypes
import json
from typing import List, Optional

import numpy as np

from .core import Booster
from .data.dmatrix import DMatrix

_F32 = ctypes.POINTER(ctypes.c_float)


def _buf(addr: int, n: int, dtype) -> np.ndarray:
    """Copy n elements of dtype from a raw address into a numpy array."""
    ctype = np.ctypeslib.as_ctypes_type(dtype)
    arr = np.ctypeslib.as_array(
        ctypes.cast(addr, ctypes.POINTER(ctype)), shape=(n,))
    return np.array(arr, dtype=dtype)  # copy: the caller's buffer may die


def dmatrix_from_mat(addr: int, nrow: int, ncol: int, missing: float) -> DMatrix:
    X = _buf(addr, nrow * ncol, np.float32).reshape(nrow, ncol)
    return DMatrix(X, missing=missing)


def dmatrix_from_csr(indptr_addr: int, indices_addr: int, data_addr: int,
                     n_indptr: int, nnz: int, ncol: int) -> DMatrix:
    import scipy.sparse as sp

    indptr = _buf(indptr_addr, n_indptr, np.uint64).astype(np.int64)
    indices = _buf(indices_addr, nnz, np.uint32).astype(np.int64)
    data = _buf(data_addr, nnz, np.float32)
    csr = sp.csr_matrix((data, indices, indptr), shape=(n_indptr - 1, ncol))
    return DMatrix(csr)


def dmatrix_set_float_info(d: DMatrix, field: str, addr: int, n: int) -> None:
    vals = _buf(addr, n, np.float32)
    if field == "label":
        d.set_label(vals)
    elif field == "weight":
        d.set_weight(vals)
    elif field == "base_margin":
        d.set_base_margin(vals)
    elif field == "label_lower_bound":
        d.info.label_lower_bound = vals
    elif field == "label_upper_bound":
        d.info.label_upper_bound = vals
    else:
        raise ValueError(f"unknown float field {field!r}")


def dmatrix_set_uint_info(d: DMatrix, field: str, addr: int, n: int) -> None:
    vals = _buf(addr, n, np.uint32)
    if field == "group":
        d.set_group(vals.astype(np.int64))
    else:
        raise ValueError(f"unknown uint field {field!r}")


def dmatrix_num_row(d: DMatrix) -> int:
    return int(d.num_row())


def dmatrix_num_col(d: DMatrix) -> int:
    return int(d.num_col())


def booster_create(dmats: List[DMatrix]) -> Booster:
    return Booster(cache=list(dmats))


def booster_set_param(b: Booster, name: str, value: Optional[str]) -> None:
    b.set_param(name, value)


def booster_update_one_iter(b: Booster, it: int, dtrain: DMatrix) -> None:
    b.update(dtrain, it)


def booster_boost_one_iter(b: Booster, dtrain: DMatrix, grad_addr: int,
                           hess_addr: int, n: int) -> None:
    b.boost(dtrain, _buf(grad_addr, n, np.float32),
            _buf(hess_addr, n, np.float32))


def booster_eval_one_iter(b: Booster, it: int, dmats: List[DMatrix],
                          names: List[str]) -> bytes:
    msg = b.eval_set(list(zip(dmats, names)), it)
    out = msg.encode()
    b._capi_eval_str = out  # pinned (c_api.cc ret_str convention)
    return out


def booster_predict(b: Booster, d: DMatrix, option_mask: int,
                    ntree_limit: int, training: int):
    """Legacy XGBoosterPredict semantics (c_api.cc):
    option_mask 1 = margin, 2 = contribs, 4 = approx contribs, 8 = leaf,
    16 = interactions; ntree_limit counts TREES and converts to boosting
    rounds via trees_per_round (c_api.cc GetIterationFromTreeLimit)."""
    if ntree_limit:
        b._configure()
        tpr = max(b.trees_per_round, 1)
        it_range = (0, -(-int(ntree_limit) // tpr))  # ceil division
    else:
        it_range = (0, 0)
    kw = dict(iteration_range=it_range, training=bool(training))
    if option_mask & 8:
        out = b.predict(d, pred_leaf=True, **kw)
    elif option_mask & 16:
        out = b.predict(d, pred_interactions=True, **kw)
    elif option_mask & 4:
        out = b.predict(d, pred_contribs=True, approx_contribs=True, **kw)
    elif option_mask & 2:
        out = b.predict(d, pred_contribs=True, **kw)
    else:
        out = b.predict(d, output_margin=bool(option_mask & 1), **kw)
    out = np.ascontiguousarray(np.asarray(out, np.float32).reshape(-1))
    b._capi_pred_buf = out  # keep alive until the next predict on b
    return int(out.size), int(out.ctypes.data)


def booster_save_model(b: Booster, path: str) -> None:
    b.save_model(path)


def booster_load_model(b: Booster, path: str) -> None:
    b.load_model(path)


def booster_save_raw(b: Booster, raw_format: str) -> tuple:
    buf = bytes(b.save_raw(raw_format))
    b._capi_raw_buf = buf
    return len(buf), buf


def booster_load_raw(b: Booster, addr: int, n: int) -> None:
    b.load_model(bytes(_buf(addr, n, np.uint8)))


def booster_get_attr(b: Booster, name: str):
    v = b.attr(name)
    if v is None:
        return None
    out = v.encode()
    b._capi_attr_str = out
    return out


def booster_set_attr(b: Booster, name: str, value: Optional[str]) -> None:
    b.set_attr(**{name: value})


def booster_num_boosted_rounds(b: Booster) -> int:
    return int(b.num_boosted_rounds())


def booster_num_features(b: Booster) -> int:
    return int(b.num_features())
