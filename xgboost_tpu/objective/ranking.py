"""Learning-to-rank objectives: LambdaMART (reference:
src/objective/lambdarank_obj.cc / .cu, 675+ LoC).

The reference samples ``lambdarank_num_pair_per_sample`` pairs per document
within each query group (pair_method="mean", the default) or uses top-k pairs.
Here groups are padded to a (G, S) doc tensor (S = max group size rounded up)
so ranks, pair sampling, and lambda accumulation are fixed-shape vectorized
ops; the per-group IDCG and rank discounts follow LambdaMARTCalcDeltaNDCG.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import ObjFunction, register_objective


def make_group_layout(group_ptr: np.ndarray):
    """Host: CSR group_ptr -> padded (G, S) row-index matrix + mask + the
    inverse map row -> flat (g*S + s) slot (rows appear exactly once, so the
    padded-grid gradients come back to row order with a GATHER, no scatter —
    TPU scatter-adds are serialized)."""
    sizes = np.diff(group_ptr)
    G = len(sizes)
    S = int(sizes.max()) if G else 1
    idx = np.zeros((G, S), dtype=np.int32)
    mask = np.zeros((G, S), dtype=bool)
    inv = np.zeros(int(group_ptr[-1]), dtype=np.int32)
    for g in range(G):
        n = sizes[g]
        rows = np.arange(group_ptr[g], group_ptr[g + 1])
        idx[g, :n] = rows
        mask[g, :n] = True
        inv[rows] = g * S + np.arange(n)
    return idx, mask, inv


class _LambdaRankBase(ObjFunction):
    def __init__(self, params):
        super().__init__(params)
        self.num_pair = int(params.get("lambdarank_num_pair_per_sample", 1))
        self._layout = None  # set by learner via set_group_info

    def set_group_info(self, group_ptr: np.ndarray) -> None:
        idx, mask, inv = make_group_layout(group_ptr)
        self._gidx = jnp.asarray(idx)
        self._gmask = jnp.asarray(mask)
        self._ginv = jnp.asarray(inv)

    def default_metric(self):
        return "ndcg"

    def _use_ndcg_weight(self) -> bool:
        return True

    def get_gradient(self, preds, labels, weights, iteration: int = 0):
        if self._layout is None and not hasattr(self, "_gidx"):
            raise ValueError(f"{self.name} requires group/qid information")
        pred = preds[:, 0] if preds.ndim == 2 else preds
        key = jax.random.PRNGKey(iteration)
        grad, hess = _lambda_gradients(
            pred,
            labels.astype(jnp.float32),
            self._gidx,
            self._gmask,
            self._ginv,
            key,
            self.num_pair,
            self._use_ndcg_weight(),
        )
        if weights is not None:
            # per-query weights broadcast over docs (reference: ltr weights are per group)
            grad = grad * weights if weights.shape == grad.shape else grad
            hess = hess * weights if weights.shape == hess.shape else hess
        return jnp.stack([grad, hess], axis=-1)[:, None, :].astype(jnp.float32)


import functools


@functools.partial(jax.jit, static_argnames=("num_pair", "ndcg_weight"))
def _lambda_gradients(pred, y, gidx, gmask, ginv, key, num_pair: int, ndcg_weight: bool):
    R = pred.shape[0]
    G, S = gidx.shape
    s = pred[gidx]  # (G, S)
    rel = y[gidx] * gmask
    s = jnp.where(gmask, s, -jnp.inf)

    # rank of each doc by current score, descending (1-based)
    order = jnp.argsort(-s, axis=1)
    arange = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (G, S))
    inv = jnp.argsort(order, axis=1)  # inverse permutation
    ranks = jnp.take_along_axis(arange, inv, axis=1) + 1  # (G, S) 1-based

    gain = (2.0 ** rel - 1.0) * gmask
    disc = 1.0 / jnp.log2(1.0 + ranks.astype(jnp.float32))
    ideal = jnp.sort(gain, axis=1)[:, ::-1]
    idisc = 1.0 / jnp.log2(2.0 + jnp.arange(S, dtype=jnp.float32))
    idcg = jnp.maximum(jnp.sum(ideal * idisc[None, :], axis=1), 1e-10)  # (G,)

    grad_g = jnp.zeros((G, S), jnp.float32)
    hess_g = jnp.zeros((G, S), jnp.float32)
    sizes = jnp.sum(gmask, axis=1).astype(jnp.int32)  # (G,)

    for p in range(num_pair):
        key, sub = jax.random.split(key)
        # uniform partner within group (resample j==i harmless: zero lambda)
        j = jax.random.randint(sub, (G, S), 0, jnp.maximum(S, 1)) % jnp.maximum(
            sizes[:, None], 1
        )
        s_j = jnp.take_along_axis(s, j, axis=1)
        rel_j = jnp.take_along_axis(rel, j, axis=1)
        rank_j = jnp.take_along_axis(ranks, j, axis=1)
        better = rel > rel_j  # this doc is the positive of the pair
        worse = rel < rel_j
        sig = jax.nn.sigmoid(-(s - s_j))  # for better pairs
        sig_w = jax.nn.sigmoid(-(s_j - s))
        if ndcg_weight:
            dg = jnp.abs(
                (2.0 ** rel - 2.0 ** rel_j)
                * (1.0 / jnp.log2(1.0 + ranks.astype(jnp.float32))
                   - 1.0 / jnp.log2(1.0 + rank_j.astype(jnp.float32)))
            ) / idcg[:, None]
        else:
            dg = jnp.ones((G, S), jnp.float32)
        lam_b = -sig * dg
        lam_w = sig_w * dg
        h_b = jnp.maximum(sig * (1 - sig) * dg, 1e-16)
        h_w = jnp.maximum(sig_w * (1 - sig_w) * dg, 1e-16)
        grad_g = grad_g + jnp.where(better & gmask, lam_b, 0.0) + jnp.where(
            worse & gmask, lam_w, 0.0
        )
        hess_g = hess_g + jnp.where((better | worse) & gmask, jnp.where(better, h_b, h_w), 0.0)

    # rows back from the padded grid via the precomputed inverse map — a pure
    # gather (each row owns exactly one (g, s) slot); no scatter on TPU.
    # ginv covers the real rows; the padded tail (R_pad - R_real) stays zero.
    grad = jnp.pad(grad_g.reshape(-1)[ginv], (0, R - ginv.shape[0]))
    hess = jnp.pad(hess_g.reshape(-1)[ginv], (0, R - ginv.shape[0]))
    return grad, hess


@register_objective("rank:ndcg")
class LambdaRankNDCG(_LambdaRankBase):
    pass


@register_objective("rank:pairwise")
class LambdaRankPairwise(_LambdaRankBase):
    def _use_ndcg_weight(self):
        return False

    def default_metric(self):
        return "map"


@register_objective("rank:map")
class LambdaRankMAP(_LambdaRankBase):
    def _use_ndcg_weight(self):
        return False

    def default_metric(self):
        return "map"
