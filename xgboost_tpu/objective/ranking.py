"""Learning-to-rank objectives: LambdaMART (reference:
src/objective/lambdarank_obj.cc / .cu, 675+ LoC).

The reference samples ``lambdarank_num_pair_per_sample`` pairs per document
within each query group (pair_method="mean", the default) or uses top-k pairs.
Here groups are padded to a (G, S) doc tensor (S = max group size rounded up)
so ranks, pair sampling, and lambda accumulation are fixed-shape vectorized
ops; the per-group IDCG and rank discounts follow LambdaMARTCalcDeltaNDCG.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import ObjFunction, register_objective


def make_group_layout(group_ptr: np.ndarray):
    """Host: CSR group_ptr -> padded (G, S) row-index matrix + mask + the
    inverse map row -> flat (g*S + s) slot (rows appear exactly once, so the
    padded-grid gradients come back to row order with a GATHER, no scatter —
    TPU scatter-adds are serialized)."""
    sizes = np.diff(group_ptr)
    G = len(sizes)
    S = int(sizes.max()) if G else 1
    idx = np.zeros((G, S), dtype=np.int32)
    mask = np.zeros((G, S), dtype=bool)
    inv = np.zeros(int(group_ptr[-1]), dtype=np.int32)
    for g in range(G):
        n = sizes[g]
        rows = np.arange(group_ptr[g], group_ptr[g + 1])
        idx[g, :n] = rows
        mask[g, :n] = True
        inv[rows] = g * S + np.arange(n)
    return idx, mask, inv


class _LambdaRankBase(ObjFunction):
    def __init__(self, params):
        super().__init__(params)
        # reference defaults (src/common/ranking_utils.h LambdaRankParam):
        # pair_method=topk, num_pair = 32 (topk) / 1 (mean),
        # normalization=true, score_normalization=true
        self.pair_method = str(params.get("lambdarank_pair_method", "topk"))
        if self.pair_method not in ("topk", "mean"):
            raise ValueError(
                f"lambdarank_pair_method must be 'topk' or 'mean', got "
                f"{self.pair_method!r}")
        np_default = 32 if self.pair_method == "topk" else 1
        self.num_pair = int(params.get("lambdarank_num_pair_per_sample",
                                       np_default))
        self.group_norm = str(params.get("lambdarank_normalization",
                                         "1")).lower() in ("1", "true")
        if str(params.get("lambdarank_unbiased", "0")).lower() in ("1",
                                                                   "true"):
            # position-bias EM debiasing (lambdarank_obj.h t_plus/t_minus)
            # is not implemented; silently ignoring it would train a
            # different model than the user asked for
            raise NotImplementedError(
                "lambdarank_unbiased=True (position-bias debiasing) is not "
                "supported yet")
        self.score_norm = str(params.get("lambdarank_score_normalization",
                                         "1")).lower() in ("1", "true")
        self._layout = None  # set by learner via set_group_info

    def set_group_info(self, group_ptr: np.ndarray) -> None:
        idx, mask, inv = make_group_layout(group_ptr)
        self._gidx = jnp.asarray(idx)
        self._gmask = jnp.asarray(mask)
        self._ginv = jnp.asarray(inv)
        self._gptr = jnp.asarray(np.asarray(group_ptr, np.int32))

    def default_metric(self):
        return "ndcg"

    def _use_ndcg_weight(self) -> bool:
        return True

    def get_gradient(self, preds, labels, weights, iteration: int = 0):
        if self._layout is None and not hasattr(self, "_gidx"):
            raise ValueError(f"{self.name} requires group/qid information")
        pred = preds[:, 0] if preds.ndim == 2 else preds
        if self.pair_method == "topk":
            if _native_lambdarank_ok():
                grad, hess = _lambda_gradients_topk_native(
                    pred, labels.astype(jnp.float32), self._gptr,
                    k=self.num_pair, ndcg_weight=self._use_ndcg_weight(),
                    score_norm=self.score_norm,
                    group_norm=self.group_norm)
            else:
                grad, hess = _lambda_gradients_topk(
                    pred, labels.astype(jnp.float32), self._gidx,
                    self._gmask, self._ginv, k=self.num_pair,
                    ndcg_weight=self._use_ndcg_weight(),
                    score_norm=self.score_norm, group_norm=self.group_norm)
        else:
            key = jax.random.PRNGKey(iteration)
            grad, hess = _lambda_gradients(
                pred,
                labels.astype(jnp.float32),
                self._gidx,
                self._gmask,
                self._ginv,
                key,
                self.num_pair,
                self._use_ndcg_weight(),
                group_norm=self.group_norm,
            )
        if weights is not None:
            # per-query weights broadcast over docs (reference: ltr weights are per group)
            grad = grad * weights if weights.shape == grad.shape else grad
            hess = hess * weights if weights.shape == hess.shape else hess
        return jnp.stack([grad, hess], axis=-1)[:, None, :].astype(jnp.float32)


import functools


def _native_lambdarank_ok() -> bool:
    """CPU gate for the native CSR-group top-k pair pass — the padded
    (G, k, S) pair tensors below cost hundreds of MB of masked
    intermediates per round that the sequential kernel never materializes
    (~4x at MSLR shapes).  Same per-host agreement story as the other
    kernels (utils/native.py)."""
    import os

    if os.environ.get("XTB_NO_NATIVE_LAMBDARANK", ""):
        return False
    if jax.default_backend() != "cpu":
        return False
    from ..utils import native

    return native.ffi_usable()


@functools.partial(jax.jit, static_argnames=("k", "ndcg_weight", "score_norm",
                                             "group_norm"))
def _lambda_gradients_topk_native(pred, y, gptr, *, k: int,
                                  ndcg_weight: bool, score_norm: bool,
                                  group_norm: bool):
    """FFI custom call into xtb_lambdarank_topk_impl — semantics mirror
    _lambda_gradients_topk (same sort order incl. stable ties, pair set,
    LambdaGrad weights, group normalization); gradients agree to f32
    tolerance (tests/test_native_parity.py pins it)."""
    import numpy as np

    from ..utils import native

    native.ensure_pool()
    R = pred.shape[0]
    shapes = (jax.ShapeDtypeStruct((R,), jnp.float32),
              jax.ShapeDtypeStruct((R,), jnp.float32))
    call = native.jax_ffi().ffi_call("xtb_lambdarank", shapes)
    return call(pred.astype(jnp.float32), y.astype(jnp.float32),
                gptr.astype(jnp.int32), k=np.int32(k),
                ndcg_weight=np.int32(ndcg_weight),
                score_norm=np.int32(score_norm),
                group_norm=np.int32(group_norm))


@functools.partial(jax.jit, static_argnames=("k", "ndcg_weight", "score_norm",
                                             "group_norm"))
def _lambda_gradients_topk(pred, y, gidx, gmask, ginv, *, k: int,
                           ndcg_weight: bool, score_norm: bool,
                           group_norm: bool):
    """Top-k LambdaMART gradients, the reference's DEFAULT pair method
    (lambdarank_obj.h MakePairs truncation branch): each of the top-k docs
    on the CURRENT model ranking pairs with every doc ranked below it, so
    the gradient concentrates exactly where ndcg@k moves.  Per-pair weights
    follow LambdaGrad (lambdarank_obj.h:91): |delta ndcg| / idcg, optional
    division by (|score diff| + 0.01) (lambdarank_score_normalization),
    hessian doubled; per-group log2(1+sum_lambda)/sum_lambda rescale
    (lambdarank_normalization, lambdarank_obj.cc:227).

    Memory: pairs form a (g_block, k, S) tensor; groups are processed in
    blocks via lax.map so MSLR-scale G never materializes G*k*S at once.
    """
    R = pred.shape[0]
    G, S = gidx.shape
    kk = min(k, S)
    # block size: ~2^22 pair cells per block keeps peak memory ~100MB
    gb = max(1, min(G, (1 << 22) // max(kk * S, 1)))
    n_blocks = (G + gb - 1) // gb
    Gp = n_blocks * gb
    pad_g = Gp - G

    s_all = jnp.where(gmask, pred[gidx], -jnp.inf)
    rel_all = y[gidx] * gmask
    if pad_g:
        s_all = jnp.concatenate(
            [s_all, jnp.full((pad_g, S), -jnp.inf, s_all.dtype)])
        rel_all = jnp.concatenate([rel_all, jnp.zeros((pad_g, S))])
        mask_all = jnp.concatenate([gmask, jnp.zeros((pad_g, S), bool)])
    else:
        mask_all = gmask

    irange = jnp.arange(kk, dtype=jnp.int32)
    jrange = jnp.arange(S, dtype=jnp.int32)
    # rank discounts by sorted position: rank = pos + 1 -> 1/log2(1 + rank)
    disc_i = 1.0 / jnp.log2(2.0 + irange.astype(jnp.float32))
    disc_j = 1.0 / jnp.log2(2.0 + jrange.astype(jnp.float32))

    def block(args):
        s, rel, mask = args  # (gb, S)
        order = jnp.argsort(-s, axis=1)  # stable; -inf padding sorts last
        inv_order = jnp.argsort(order, axis=1)
        s_srt = jnp.take_along_axis(s, order, axis=1)
        rel_srt = jnp.take_along_axis(rel, order, axis=1)
        m_srt = jnp.take_along_axis(mask, order, axis=1)
        cnt = jnp.sum(mask, axis=1).astype(jnp.int32)  # (gb,)

        gain_srt = (2.0 ** rel_srt - 1.0) * m_srt
        ideal = jnp.sort(gain_srt, axis=1)[:, ::-1]
        idcg = jnp.maximum(jnp.sum(ideal * disc_j[None, :], axis=1), 1e-10)

        si = s_srt[:, :kk][:, :, None]           # (gb, k, 1)
        sj = s_srt[:, None, :]                   # (gb, 1, S)
        reli = rel_srt[:, :kk][:, :, None]
        relj = rel_srt[:, None, :]
        valid = (m_srt[:, :kk][:, :, None] & m_srt[:, None, :]
                 & (jrange[None, None, :] > irange[None, :, None])
                 & (reli != relj))
        high_is_i = reli > relj
        s_high = jnp.where(high_is_i, si, sj)
        s_low = jnp.where(high_is_i, sj, si)
        sig = jax.nn.sigmoid(s_high - s_low)

        if ndcg_weight:
            gi = gain_srt[:, :kk][:, :, None]
            gj = gain_srt[:, None, :]
            delta = jnp.abs((gi - gj)
                            * (disc_i[None, :, None] - disc_j[None, None, :])
                            ) / idcg[:, None, None]
        else:
            delta = jnp.ones_like(sig)
        if score_norm:
            # LambdaGrad norm_by_diff: skip when all scores equal (first
            # iteration) — best == worst per group
            best = s_srt[:, 0]
            worst = jnp.take_along_axis(
                s_srt, jnp.maximum(cnt - 1, 0)[:, None], axis=1)[:, 0]
            spread = (best != worst)[:, None, None]
            delta = jnp.where(spread,
                              delta / (jnp.abs(s_high - s_low) + 0.01),
                              delta)

        lam = jnp.where(valid, (sig - 1.0) * delta, 0.0)  # high doc's grad
        hss = jnp.where(valid,
                        jnp.maximum(sig * (1.0 - sig), 1e-16) * delta * 2.0,
                        0.0)
        # endpoint accumulation in sorted coordinates
        sgn_i = jnp.where(high_is_i, 1.0, -1.0)
        grad_i = jnp.sum(lam * sgn_i, axis=2)                 # (gb, k)
        grad_j = jnp.sum(lam * (-sgn_i), axis=1)              # (gb, S)
        grad_srt = grad_j.at[:, :kk].add(grad_i)
        hess_srt = jnp.sum(hss, axis=1).at[:, :kk].add(jnp.sum(hss, axis=2))

        if group_norm:
            # sum_lambda accumulates -2 * (high-doc gradient) per pair
            sum_lambda = jnp.sum(-2.0 * lam, axis=(1, 2))
            norm = jnp.where(sum_lambda > 0.0,
                             jnp.log2(1.0 + sum_lambda)
                             / jnp.maximum(sum_lambda, 1e-16), 1.0)
            grad_srt = grad_srt * norm[:, None]
            hess_srt = hess_srt * norm[:, None]

        grad_blk = jnp.take_along_axis(grad_srt, inv_order, axis=1)
        hess_blk = jnp.take_along_axis(hess_srt, inv_order, axis=1)
        return grad_blk, hess_blk

    s_b = s_all.reshape(n_blocks, gb, S)
    rel_b = rel_all.reshape(n_blocks, gb, S)
    m_b = mask_all.reshape(n_blocks, gb, S)
    grad_g, hess_g = jax.lax.map(block, (s_b, rel_b, m_b))
    grad_g = grad_g.reshape(Gp, S)[:G].astype(jnp.float32)
    hess_g = hess_g.reshape(Gp, S)[:G].astype(jnp.float32)
    grad = jnp.pad(grad_g.reshape(-1)[ginv], (0, R - ginv.shape[0]))
    hess = jnp.pad(hess_g.reshape(-1)[ginv], (0, R - ginv.shape[0]))
    return grad, hess


@functools.partial(jax.jit, static_argnames=("num_pair", "ndcg_weight",
                                             "group_norm"))
def _lambda_gradients(pred, y, gidx, gmask, ginv, key, num_pair: int,
                      ndcg_weight: bool, group_norm: bool = True):
    R = pred.shape[0]
    G, S = gidx.shape
    s = pred[gidx]  # (G, S)
    rel = y[gidx] * gmask
    s = jnp.where(gmask, s, -jnp.inf)

    # rank of each doc by current score, descending (1-based)
    order = jnp.argsort(-s, axis=1)
    arange = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (G, S))
    inv = jnp.argsort(order, axis=1)  # inverse permutation
    ranks = jnp.take_along_axis(arange, inv, axis=1) + 1  # (G, S) 1-based

    gain = (2.0 ** rel - 1.0) * gmask
    disc = 1.0 / jnp.log2(1.0 + ranks.astype(jnp.float32))
    ideal = jnp.sort(gain, axis=1)[:, ::-1]
    idisc = 1.0 / jnp.log2(2.0 + jnp.arange(S, dtype=jnp.float32))
    idcg = jnp.maximum(jnp.sum(ideal * idisc[None, :], axis=1), 1e-10)  # (G,)

    grad_g = jnp.zeros((G, S), jnp.float32)
    hess_g = jnp.zeros((G, S), jnp.float32)
    sizes = jnp.sum(gmask, axis=1).astype(jnp.int32)  # (G,)

    for p in range(num_pair):
        key, sub = jax.random.split(key)
        # uniform partner within group (resample j==i harmless: zero lambda)
        j = jax.random.randint(sub, (G, S), 0, jnp.maximum(S, 1)) % jnp.maximum(
            sizes[:, None], 1
        )
        s_j = jnp.take_along_axis(s, j, axis=1)
        rel_j = jnp.take_along_axis(rel, j, axis=1)
        rank_j = jnp.take_along_axis(ranks, j, axis=1)
        better = rel > rel_j  # this doc is the positive of the pair
        worse = rel < rel_j
        sig = jax.nn.sigmoid(-(s - s_j))  # for better pairs
        sig_w = jax.nn.sigmoid(-(s_j - s))
        if ndcg_weight:
            dg = jnp.abs(
                (2.0 ** rel - 2.0 ** rel_j)
                * (1.0 / jnp.log2(1.0 + ranks.astype(jnp.float32))
                   - 1.0 / jnp.log2(1.0 + rank_j.astype(jnp.float32)))
            ) / idcg[:, None]
        else:
            dg = jnp.ones((G, S), jnp.float32)
        lam_b = -sig * dg
        lam_w = sig_w * dg
        # hessian doubled like the reference LambdaGrad (lambdarank_obj.h)
        h_b = jnp.maximum(sig * (1 - sig) * dg, 1e-16) * 2.0
        h_w = jnp.maximum(sig_w * (1 - sig_w) * dg, 1e-16) * 2.0
        grad_g = grad_g + jnp.where(better & gmask, lam_b, 0.0) + jnp.where(
            worse & gmask, lam_w, 0.0
        )
        hess_g = hess_g + jnp.where((better | worse) & gmask, jnp.where(better, h_b, h_w), 0.0)

    if group_norm:
        # mean-method normalization: 1 / n_pairs (lambdarank_obj.cc:230)
        grad_g = grad_g / float(num_pair)
        hess_g = hess_g / float(num_pair)
    # rows back from the padded grid via the precomputed inverse map — a pure
    # gather (each row owns exactly one (g, s) slot); no scatter on TPU.
    # ginv covers the real rows; the padded tail (R_pad - R_real) stays zero.
    grad = jnp.pad(grad_g.reshape(-1)[ginv], (0, R - ginv.shape[0]))
    hess = jnp.pad(hess_g.reshape(-1)[ginv], (0, R - ginv.shape[0]))
    return grad, hess


@register_objective("rank:ndcg")
class LambdaRankNDCG(_LambdaRankBase):
    pass


@register_objective("rank:pairwise")
class LambdaRankPairwise(_LambdaRankBase):
    def _use_ndcg_weight(self):
        return False

    def default_metric(self):
        return "map"


@register_objective("rank:map")
class LambdaRankMAP(_LambdaRankBase):
    def _use_ndcg_weight(self):
        return False

    def default_metric(self):
        return "map"
