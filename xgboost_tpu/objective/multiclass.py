"""Multiclass objectives (reference: src/objective/multiclass_obj.cu).

softprob/softmax gradients: p = softmax(margin); grad_k = p_k - 1[y==k],
hess_k = 2 p_k (1 - p_k) — matching SoftmaxMultiClassObj.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import ObjFunction, register_objective


class _SoftmaxBase(ObjFunction):
    def __init__(self, params):
        super().__init__(params)
        self.num_class = int(params.get("num_class", 0))
        if self.num_class < 2:
            raise ValueError(f"{self.name} requires num_class >= 2")

    def n_groups(self):
        return self.num_class

    def task_is_classification(self):
        return True

    def get_gradient(self, preds, labels, weights, iteration: int = 0):
        K = self.num_class
        p = jax.nn.softmax(preds, axis=1)  # (R, K)
        y = jax.nn.one_hot(labels.astype(jnp.int32), K, dtype=jnp.float32)
        grad = p - y
        hess = jnp.maximum(2.0 * p * (1.0 - p), 1e-16)
        if weights is not None:
            grad = grad * weights[:, None]
            hess = hess * weights[:, None]
        return jnp.stack([grad, hess], axis=-1).astype(jnp.float32)

    def init_estimation(self, labels, weights):
        return jnp.zeros(self.num_class, jnp.float32)

    def default_metric(self):
        return "mlogloss"


@register_objective("multi:softprob")
class SoftProb(_SoftmaxBase):
    def pred_transform(self, margin):
        return jax.nn.softmax(margin, axis=1)


@register_objective("multi:softmax")
class SoftMax(_SoftmaxBase):
    def pred_transform(self, margin):
        return jnp.argmax(margin, axis=1).astype(jnp.float32)

    def default_metric(self):
        return "merror"
