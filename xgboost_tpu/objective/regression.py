"""Regression / binary objectives (reference: src/objective/regression_obj.cu).

Gradients match the reference formulae line-for-line in math (not code):
e.g. squarederror grad = pred - y, hess = 1 (regression_obj.cu
LinearSquareLoss); logistic grad = sigmoid(x) - y, hess = p(1-p) with
scale_pos_weight applied to positive rows.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import ObjFunction, register_objective


def _apply_weight(grad, hess, weights):
    if weights is None:
        return grad, hess
    w = weights.reshape(-1, *([1] * (grad.ndim - 1)))
    return grad * w, hess * w


def _pack(grad, hess, weights):
    grad, hess = _apply_weight(grad, hess, weights)
    if grad.ndim == 1:
        grad, hess = grad[:, None], hess[:, None]
    return jnp.stack([grad, hess], axis=-1).astype(jnp.float32)


class _Elementwise(ObjFunction):
    def _grad(self, pred, y):  # -> (grad, hess), elementwise (any shape)
        raise NotImplementedError

    def n_groups(self) -> int:
        # multi-output regression: one output column per target
        # (reference: LearnerModelParam num_target / MultiStrategy)
        return max(int(self.params.get("num_target", 1) or 1), 1)

    def get_gradient(self, preds, labels, weights, iteration: int = 0):
        K = self.n_groups()
        if K > 1:
            y = labels.astype(jnp.float32).reshape(labels.shape[0], -1)
            g, h = self._grad(preds, y)  # elementwise on (R, K)
            return _pack(g, h, weights)
        pred = preds[:, 0] if preds.ndim == 2 else preds
        g, h = self._grad(pred, labels.astype(jnp.float32))
        return _pack(g, h, weights)


@register_objective("reg:squarederror")
class SquaredError(_Elementwise):
    def _grad(self, pred, y):
        return pred - y, jnp.ones_like(pred)

    def init_estimation(self, labels, weights):
        if labels.ndim == 2:  # per-target mean (fit_stump.cc multi-target)
            w = (jnp.ones(labels.shape[0]) if weights is None
                 else weights).astype(jnp.float32)
            return jnp.sum(labels * w[:, None], axis=0) / jnp.maximum(
                jnp.sum(w), 1e-6)
        w = jnp.ones_like(labels) if weights is None else weights
        return jnp.sum(labels * w) / jnp.maximum(jnp.sum(w), 1e-6)


@register_objective("reg:squaredlogerror")
class SquaredLogError(_Elementwise):
    def _grad(self, pred, y):
        pred = jnp.maximum(pred, -1 + 1e-6)
        t = jnp.log1p(pred) - jnp.log1p(y)
        g = t / (pred + 1)
        h = jnp.maximum((1 - t) / (pred + 1) ** 2, 1e-6)
        return g, h

    def default_metric(self):
        return "rmsle"


@register_objective("reg:pseudohubererror")
class PseudoHuber(_Elementwise):
    def _grad(self, pred, y):
        slope = float(self.params.get("huber_slope", 1.0))
        z = pred - y
        scale = 1 + (z / slope) ** 2
        sqrt_s = jnp.sqrt(scale)
        return z / sqrt_s, 1 / (scale * sqrt_s)

    def default_metric(self):
        return "mphe"


@register_objective("reg:absoluteerror")
class AbsoluteError(_Elementwise):
    """MAE with hess=1; exact leaf via adaptive quantile update
    (reference: src/objective/adaptive.cc UpdateTreeLeaf)."""

    def _grad(self, pred, y):
        return jnp.sign(pred - y), jnp.ones_like(pred)

    def init_estimation(self, labels, weights):
        return jnp.median(labels)

    def adaptive_leaf(self):
        return True

    def adaptive_alpha(self, k: int = 0) -> float:
        return 0.5

    def default_metric(self):
        return "mae"


def _sigmoid(x):
    return jax.nn.sigmoid(x)


class _LogisticBase(_Elementwise):
    def _grad(self, pred, y):
        p = _sigmoid(pred)
        spw = float(self.params.get("scale_pos_weight", 1.0))
        w = jnp.where(y == 1.0, spw, 1.0)
        return (p - y) * w, jnp.maximum(p * (1 - p), 1e-16) * w

    def pred_transform(self, margin):
        return _sigmoid(margin)

    def prob_to_margin(self, prob):
        p = jnp.clip(prob, 1e-7, 1 - 1e-7)
        return jnp.log(p / (1 - p))

    def margin_to_prob(self, margin):
        return _sigmoid(margin)

    def default_metric(self):
        return "logloss"


@register_objective("binary:logistic")
class BinaryLogistic(_LogisticBase):
    def task_is_classification(self):
        return True


@register_objective("reg:logistic")
class RegLogistic(_LogisticBase):
    def default_metric(self):
        return "rmse"


@register_objective("binary:logitraw")
class LogitRaw(_LogisticBase):
    def task_is_classification(self):
        return True

    def pred_transform(self, margin):
        return margin

    def default_metric(self):
        return "auc"


@register_objective("binary:hinge")
class Hinge(_Elementwise):
    def task_is_classification(self):
        return True

    def _grad(self, pred, y):
        yy = 2.0 * y - 1.0  # {0,1} -> {-1,1}
        active = yy * pred < 1.0
        return jnp.where(active, -yy, 0.0), jnp.where(active, 1.0, 1e-16)

    def pred_transform(self, margin):
        return (margin > 0).astype(jnp.float32)

    def default_metric(self):
        return "error"


class _ExpFamily(_Elementwise):
    """log-link count/positive objectives: pred is log(mu)."""

    def pred_transform(self, margin):
        return jnp.exp(margin)

    def prob_to_margin(self, prob):
        return jnp.log(jnp.maximum(prob, 1e-16))

    def margin_to_prob(self, margin):
        return jnp.exp(margin)


@register_objective("count:poisson")
class Poisson(_ExpFamily):
    def _grad(self, pred, y):
        # regression_obj.cu PoissonRegression: hess uses max_delta_step cap
        mds = float(self.params.get("max_delta_step", 0.7)) or 0.7
        mu = jnp.exp(pred)
        return mu - y, mu * jnp.exp(mds)

    def default_metric(self):
        return "poisson-nloglik"


@register_objective("reg:gamma")
class Gamma(_ExpFamily):
    def _grad(self, pred, y):
        mu = jnp.exp(pred)
        return 1.0 - y / mu, y / mu

    def default_metric(self):
        return "gamma-nloglik"


@register_objective("reg:tweedie")
class Tweedie(_ExpFamily):
    def _grad(self, pred, y):
        rho = float(self.params.get("tweedie_variance_power", 1.5))
        a = y * jnp.exp((1 - rho) * pred)
        b = jnp.exp((2 - rho) * pred)
        return -a + b, -(1 - rho) * a + (2 - rho) * b

    def default_metric(self):
        rho = float(self.params.get("tweedie_variance_power", 1.5))
        return f"tweedie-nloglik@{rho}"


class _MultiAlpha(ObjFunction):
    """Shared base for quantile/expectile: one output column per alpha
    (quantile_obj.cu / regression_obj.cu ExpectileRegression Targets())."""

    _alpha_param = "quantile_alpha"

    def _alphas(self):
        a = self.params.get(self._alpha_param, 0.5)
        if not isinstance(a, (list, tuple)):
            a = [a]
        return [float(x) for x in a]

    def n_groups(self) -> int:
        return len(self._alphas())

    def default_metric(self):
        a = self._alphas()
        return self._metric_base if len(a) > 1 else f"{self._metric_base}@{a[0]}"


@register_objective("reg:expectileerror")
class Expectile(_MultiAlpha):
    """Asymmetric squared loss: weight (1-alpha) for over-prediction, alpha
    for under (reference: regression_obj.cu ExpectileRegression; this round
    trains the alphas as independent columns, without the reference's
    non-crossing softplus chaining)."""

    _alpha_param = "expectile_alpha"
    _metric_base = "expectile"

    def _alphas(self):
        # accept quantile_alpha as an alias (round-1 compatibility)
        if self._alpha_param not in self.params and "quantile_alpha" in self.params:
            a = self.params["quantile_alpha"]
            return [float(x) for x in (a if isinstance(a, (list, tuple)) else [a])]
        return super()._alphas()

    def get_gradient(self, preds, labels, weights, iteration: int = 0):
        alphas = jnp.asarray(self._alphas(), jnp.float32)
        y = labels.astype(jnp.float32)[:, None]
        diff = preds - y  # (R, Q)
        w = jnp.where(diff >= 0, 1.0 - alphas[None, :], alphas[None, :])
        # NOTE: grad = w*diff, hess = w — deliberately WITHOUT the factor 2
        # of the analytic d/dp [w p^2]: the reference's kernel does the same
        # (regression_obj.cu:464-466), and matching it keeps leaf weights
        # identical under shared lambda/min_child_weight
        return _pack(w * diff, w, weights)

    def init_estimation(self, labels, weights):
        w = (jnp.ones_like(labels) if weights is None else weights)
        mean = jnp.sum(labels * w) / jnp.maximum(jnp.sum(w), 1e-6)
        return jnp.full((len(self._alphas()),), mean, jnp.float32)


@register_objective("reg:quantileerror")
class QuantileError(_MultiAlpha):
    """Pinball loss over one or many alphas (quantile_obj.cu trains all
    quantile_alpha levels as a multi-output model); exact per-leaf quantile
    via the adaptive update."""

    _metric_base = "quantile"

    def get_gradient(self, preds, labels, weights, iteration: int = 0):
        alphas = jnp.asarray(self._alphas(), jnp.float32)
        y = labels.astype(jnp.float32)[:, None]
        # pinball: dL/dpred = (1-alpha) for over-prediction, -alpha for under
        g = jnp.where(preds >= y, 1.0 - alphas[None, :], -alphas[None, :])
        return _pack(g, jnp.ones_like(g), weights)

    def init_estimation(self, labels, weights):
        return jnp.quantile(labels, jnp.asarray(self._alphas()))

    def adaptive_leaf(self):
        return True

    def adaptive_alpha(self, k: int = 0) -> float:
        return self._alphas()[k]
