"""Objective functions (reference: include/xgboost/objective.h:28 ObjFunction).

Each objective is a pure vectorized function family: ``get_gradient`` returns
per-row (grad, hess) pairs evaluated on device (the analogue of the CUDA
objective kernels in src/objective/regression_obj.cu etc.), plus the link
functions ``pred_transform`` / ``prob_to_margin`` and one-step Newton
``init_estimation`` (reference: ObjFunction::InitEstimation + FitStump,
src/tree/fit_stump.cc:34).

Registry dispatch by name mirrors XGBOOST_REGISTER_OBJECTIVE.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Type

import numpy as np

_REGISTRY: Dict[str, Type["ObjFunction"]] = {}


def register_objective(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        cls.name = name
        return cls

    return deco


def create_objective(name: str, params: dict) -> "ObjFunction":
    if name not in _REGISTRY:
        raise ValueError(
            f"Unknown objective {name!r}. Known: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[name](params)


def list_objectives():
    return sorted(_REGISTRY)


class ObjFunction:
    """Base objective (objective.h:28). Subclasses define gradient + links."""

    name = ""

    def __init__(self, params: dict) -> None:
        self.params = params

    # number of model outputs per row (1, or num_class for softmax family)
    def n_groups(self) -> int:
        return 1

    def task_is_classification(self) -> bool:
        return False

    def get_gradient(self, preds, labels, weights, iteration: int = 0):
        """(R,K) margin, (R,) or (R,K) labels -> (R, K, 2) f32 gpair."""
        raise NotImplementedError

    def pred_transform(self, margin):
        return margin

    def prob_to_margin(self, prob):
        return prob

    def margin_to_prob(self, margin):
        """Scalar inverse of prob_to_margin (for base_score serialization)."""
        return margin

    def init_estimation(self, labels, weights) -> float:
        """One Newton step from margin 0 (FitStump) -> base margin scalar."""
        import jax.numpy as jnp

        g = self.get_gradient(
            jnp.zeros((labels.shape[0], self.n_groups()), jnp.float32), labels, weights
        )
        G = jnp.sum(g[..., 0], axis=0)
        H = jnp.sum(g[..., 1], axis=0)
        return -G / jnp.maximum(H, 1e-6)

    def default_metric(self) -> str:
        return "rmse"

    # adaptive leaf update hook (reference: ObjFunction::UpdateTreeLeaf,
    # objective.h:129) — used by absoluteerror/quantile
    def adaptive_leaf(self) -> bool:
        return False


from . import regression  # noqa: E402,F401  (registers objectives)
from . import multiclass  # noqa: E402,F401
from . import ranking  # noqa: E402,F401
from . import survival  # noqa: E402,F401
