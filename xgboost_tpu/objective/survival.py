"""Survival objectives: AFT and Cox proportional hazards.

Reference: src/objective/aft_obj.cu + src/common/survival_util.h (AFT loss for
normal/logistic/extreme error distributions with interval censoring) and
regression_obj.cu CoxRegression (negative partial log-likelihood over risk
sets).  Gradients follow the published AFT formulation (Barnwal et al.,
indexed via PAPERS.md) — margins model log(time).

Censoring encoding matches the reference:
 - AFT: per-row [label_lower_bound, label_upper_bound]; equal bounds =
   uncensored, +inf upper = right-censored, -inf/0 lower = left-censored.
 - Cox: label sign carries the event flag (y > 0 event at time y, y < 0
   right-censored at time -y).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import ObjFunction, register_objective

_SQRT2PI = float(np.sqrt(2.0 * np.pi))
_EPS = 1e-12


def _norm_pdf(z):
    return jnp.exp(-0.5 * z * z) / _SQRT2PI


def _norm_cdf(z):
    return 0.5 * (1.0 + jax.lax.erf(z / np.sqrt(2.0)))


def _logis_pdf(z):
    e = jnp.exp(-jnp.abs(z))
    return e / (1 + e) ** 2


def _logis_cdf(z):
    return jax.nn.sigmoid(z)


def _extreme_pdf(z):
    # Gumbel (minimum) as used by AFT 'extreme': pdf = e^z * exp(-e^z)
    w = jnp.exp(jnp.clip(z, -700, 30))
    return w * jnp.exp(-w)


def _extreme_cdf(z):
    w = jnp.exp(jnp.clip(z, -700, 30))
    return 1.0 - jnp.exp(-w)


_DISTS = {
    "normal": (_norm_pdf, _norm_cdf),
    "logistic": (_logis_pdf, _logis_cdf),
    "extreme": (_extreme_pdf, _extreme_cdf),
}


def aft_neg_loglik(pred, y_lower, y_upper, dist: str, sigma: float):
    """Per-row AFT negative log likelihood (survival_util.h AFTLoss)."""
    pdf, cdf = _DISTS[dist]
    # double-where: autodiff evaluates BOTH branches, so infinite bounds must
    # be replaced by finite dummies before any transcendental touches them
    hi_finite = jnp.isfinite(y_upper)
    lo_pos = y_lower > 0
    log_lo = jnp.log(jnp.maximum(jnp.where(lo_pos, y_lower, 1.0), _EPS))
    log_hi = jnp.log(jnp.maximum(jnp.where(hi_finite, y_upper, 1.0), _EPS))
    z_lo = jnp.clip((log_lo - pred) / sigma, -15.0, 15.0)
    z_hi = jnp.clip((log_hi - pred) / sigma, -15.0, 15.0)
    uncensored = hi_finite & (jnp.abs(y_upper - y_lower) < 1e-12)
    # uncensored: -log( pdf(z)/ (sigma * t) ); the 1/t term is constant wrt pred
    ll_unc = jnp.log(jnp.maximum(pdf(z_lo), _EPS)) - jnp.log(
        sigma * jnp.maximum(y_lower, _EPS)
    )
    hi_cdf = jnp.where(hi_finite, cdf(z_hi), 1.0)
    lo_cdf = jnp.where(lo_pos, cdf(z_lo), 0.0)
    ll_cen = jnp.log(jnp.maximum(hi_cdf - lo_cdf, _EPS))
    return -jnp.where(uncensored, ll_unc, ll_cen)


@register_objective("survival:aft")
class AFT(ObjFunction):
    """Accelerated failure time (reference: aft_obj.cu AFTObj)."""

    def __init__(self, params):
        super().__init__(params)
        self.dist = str(params.get("aft_loss_distribution", "normal"))
        if self.dist not in _DISTS:
            raise ValueError(f"unknown aft_loss_distribution {self.dist!r}")
        self.sigma = float(params.get("aft_loss_distribution_scale", 1.0))
        self._bounds = None

    def set_bounds(self, lower, upper):
        lo = jnp.asarray(lower, jnp.float32)
        hi = (jnp.full_like(lo, jnp.inf) if upper is None
              else jnp.asarray(upper, jnp.float32))  # missing upper = right-censored
        self._bounds = (lo, hi)

    def _get_bounds(self, labels):
        if self._bounds is not None:
            lo, hi = self._bounds
            R = labels.shape[0]
            pad = R - lo.shape[0]
            if pad > 0:
                lo = jnp.concatenate([lo, jnp.ones(pad, jnp.float32)])
                hi = jnp.concatenate([hi, jnp.ones(pad, jnp.float32)])
            return lo, hi
        return labels.astype(jnp.float32), labels.astype(jnp.float32)

    def get_gradient(self, preds, labels, weights, iteration: int = 0):
        pred = preds[:, 0] if preds.ndim == 2 else preds
        lo, hi = self._get_bounds(labels)
        loss = lambda m: jnp.sum(aft_neg_loglik(m, lo, hi, self.dist, self.sigma))
        g = jax.grad(loss)(pred)
        # the loss is an elementwise sum, so the Hessian is diagonal and one
        # jvp of the gradient with a ones tangent yields it exactly; |.| + floor
        # mirrors the reference's hessian clipping (survival_util.h)
        _, hvp = jax.jvp(jax.grad(loss), (pred,), (jnp.ones_like(pred),))
        hess = jnp.maximum(jnp.abs(hvp), 1e-6)
        if weights is not None:
            g = g * weights
            hess = hess * weights
        return jnp.stack([g, hess], axis=-1)[:, None, :].astype(jnp.float32)

    def pred_transform(self, margin):
        return jnp.exp(margin)

    def prob_to_margin(self, prob):
        return jnp.log(jnp.maximum(prob, 1e-16))

    def margin_to_prob(self, margin):
        return jnp.exp(margin)

    def init_estimation(self, labels, weights):
        return jnp.zeros((), jnp.float32)

    def default_metric(self):
        return "aft-nloglik"


@register_objective("survival:cox")
class Cox(ObjFunction):
    """Cox partial likelihood (reference: regression_obj.cu CoxRegression).

    Labels: y > 0 event at time y; y < 0 right-censored at |y|.  Gradients use
    risk-set cumulative sums over the time-sorted order — two sorts + two
    cumsums on device, no O(R^2) loops.
    """

    def get_gradient(self, preds, labels, weights, iteration: int = 0):
        pred = preds[:, 0] if preds.ndim == 2 else preds
        y = labels.astype(jnp.float32)
        t = jnp.abs(y)
        event = (y > 0).astype(jnp.float32)
        w = jnp.ones_like(t) if weights is None else weights
        # sort by time ascending; risk set of i = rows with t >= t_i.
        # Ties use Breslow accumulation (reference: regression_obj.cu
        # CoxRegression accumulated_sum / last_abs_y): every member of a tie
        # group shares the group's risk denominator, and the event mass of the
        # whole group enters each member's accumulator.
        order = jnp.argsort(t)
        inv = jnp.argsort(order)
        r = jnp.exp(pred - jnp.max(pred)) * w  # scale-invariant partial lik.
        r_sorted = r[order]
        ts = t[order]
        revcum = jnp.cumsum(r_sorted[::-1])[::-1]
        g_start = jnp.searchsorted(ts, ts, side="left")  # first index of tie group
        g_end = jnp.searchsorted(ts, ts, side="right")  # one past last
        risk = jnp.maximum(revcum[g_start], _EPS)  # group-shared denominator
        ev_sorted = (event * w)[order]
        a = ev_sorted / risk
        b = ev_sorted / (risk * risk)
        cum_a = jnp.cumsum(a)
        cum_b = jnp.cumsum(b)
        acc_a = cum_a[g_end - 1]  # events with t_j <= t_i, whole tie group
        acc_b = cum_b[g_end - 1]
        grad_sorted = r_sorted * acc_a - ev_sorted
        hess_sorted = r_sorted * acc_a - r_sorted * r_sorted * acc_b
        grad = grad_sorted[inv]
        hess = jnp.maximum(hess_sorted[inv], 1e-6)
        return jnp.stack([grad, hess], axis=-1)[:, None, :].astype(jnp.float32)

    def pred_transform(self, margin):
        return jnp.exp(margin)  # hazard ratio

    def init_estimation(self, labels, weights):
        return jnp.zeros((), jnp.float32)

    def default_metric(self):
        return "cox-nloglik"
