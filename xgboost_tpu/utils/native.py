"""ctypes bindings for the native host runtime (native/xtb_native.cc).

The reference's host-side hot loops are C++ (dmlc-core text parsers, CSR
adapters src/data/adapter.h:538 FileAdapter, GK summaries
src/common/quantile.h); ours live in one small C-ABI library loaded here.
Pure-Python fallbacks keep everything working when the .so hasn't been built
(``make -C native``) — the library auto-builds on first use when a toolchain
is present.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_LIB = None
_TRIED = False


def jax_ffi():
    """The jax FFI module: ``jax.ffi`` (jax >= 0.5) or ``jax.extend.ffi``
    (0.4.x) — identical surface for everything this package uses
    (``ffi_call``, ``register_ffi_target``, ``pycapsule``, ``include_dir``).
    Every FFI call site routes through this shim so the native kernels stay
    live across the jax version seam."""
    import jax

    mod = getattr(jax, "ffi", None)
    if mod is not None and hasattr(mod, "ffi_call"):
        return mod
    import jax.extend.ffi as ffi  # jax 0.4.x

    return ffi


def _native_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native")


def load_native() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    so = os.path.join(_native_dir(), "libxtb_native.so")
    srcs = [os.path.join(_native_dir(), n)
            for n in ("xtb_native.cc", "xtb_kernels.h", "xtb_simd.h")]
    stale = (not os.path.exists(so)
             or any(os.path.exists(s)
                    and os.path.getmtime(s) > os.path.getmtime(so)
                    for s in srcs))
    if stale:
        try:
            subprocess.run(["make", "-C", _native_dir()], capture_output=True,
                           timeout=120, check=True)
        except Exception:
            if not os.path.exists(so):
                return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    c = ctypes
    lib.xtb_parse_libsvm.restype = c.c_void_p
    lib.xtb_parse_libsvm.argtypes = [c.c_char_p, c.c_int64]
    lib.xtb_parse_csv.restype = c.c_void_p
    lib.xtb_parse_csv.argtypes = [c.c_char_p, c.c_int64, c.c_int]
    lib.xtb_csr_rows.restype = c.c_int64
    lib.xtb_csr_nnz.restype = c.c_int64
    lib.xtb_csr_cols.restype = c.c_int32
    lib.xtb_csr_has_qid.restype = c.c_int32
    lib.xtb_csr_qid_count.restype = c.c_int64
    for f in (lib.xtb_csr_rows, lib.xtb_csr_nnz, lib.xtb_csr_cols,
              lib.xtb_csr_has_qid, lib.xtb_csr_qid_count, lib.xtb_csr_free,
              lib.xtb_dense_free):
        f.argtypes = [c.c_void_p]
    lib.xtb_csr_copy.argtypes = [c.c_void_p] + [c.c_void_p] * 5
    lib.xtb_dense_rows.restype = c.c_int64
    lib.xtb_dense_rows.argtypes = [c.c_void_p]
    lib.xtb_dense_cols.restype = c.c_int32
    lib.xtb_dense_cols.argtypes = [c.c_void_p]
    lib.xtb_dense_copy.argtypes = [c.c_void_p, c.c_void_p]
    lib.xtb_summary_new.restype = c.c_void_p
    lib.xtb_summary_new.argtypes = [c.c_int64]
    lib.xtb_summary_push.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64]
    lib.xtb_summary_query.argtypes = [c.c_void_p, c.c_void_p, c.c_int32, c.c_void_p]
    lib.xtb_summary_total.restype = c.c_double
    lib.xtb_summary_total.argtypes = [c.c_void_p]
    lib.xtb_summary_free.argtypes = [c.c_void_p]
    lib.xtb_shap_values.argtypes = [c.c_void_p, c.c_int64, c.c_int32,
                                    c.c_void_p, c.c_void_p, c.c_void_p,
                                    c.c_void_p, c.c_void_p, c.c_void_p,
                                    c.c_void_p, c.c_int32, c.c_void_p]
    lib.xtb_ellpack_bin.argtypes = [c.c_void_p, c.c_int64, c.c_int32,
                                    c.c_void_p, c.c_void_p, c.c_int32,
                                    c.c_int32, c.c_void_p]
    lib.xtb_hist_f32_u8.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                                    c.c_int64, c.c_int32, c.c_int32,
                                    c.c_int32, c.c_int32, c.c_int32,
                                    c.c_int32, c.c_void_p]
    lib.xtb_hist_packed4.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                                     c.c_int64, c.c_int32, c.c_int32,
                                     c.c_int32, c.c_int32, c.c_int32,
                                     c.c_void_p]
    _bind_pool_abi(lib)
    _LIB = lib
    if _NTHREAD is not None:  # pool configured before this lib loaded
        lib.xtb_set_nthread(_NTHREAD)
    if _SIMD is not None:  # simd level pinned before this lib loaded
        lib.xtb_simd_set(_SIMD)
    return lib


# --------------------------------------------------------------------------
# ParallelFor pool control (native/xtb_kernels.h XtbThreadPool).
#
# Each shared object (libxtb_native.so for the ctypes kernels, libxtb_ffi.so
# for the XLA custom calls) carries its own pool instance; configuration and
# stats reads fan out to every loaded library.  nthread precedence:
# explicit ``nthread`` param > ``XGBOOST_TPU_NTHREAD`` env > os.cpu_count().
# --------------------------------------------------------------------------

_NTHREAD: Optional[int] = None  # last applied effective thread count
_FFI_LIB = None                 # CDLL handle kept for the pool ABI

POOL_STAT_SLOTS = 13  # [regions, busy_ns, bucket_0 .. bucket_10]
POOL_PERF_SLOTS = 5   # [invocations, wall_ns, cycles, bytes, flops]


def _bind_pool_abi(lib) -> None:
    c = ctypes
    lib.xtb_set_nthread.restype = c.c_int
    lib.xtb_set_nthread.argtypes = [c.c_int]
    lib.xtb_get_nthread.restype = c.c_int
    lib.xtb_pool_alive_workers.restype = c.c_int
    lib.xtb_pool_faults_total.restype = c.c_int64
    lib.xtb_pool_regions_total.restype = c.c_int64
    lib.xtb_pool_n_kernels.restype = c.c_int
    lib.xtb_pool_kernel_name.restype = c.c_char_p
    lib.xtb_pool_kernel_name.argtypes = [c.c_int]
    lib.xtb_pool_kernel_stats.argtypes = [c.c_int, c.c_void_p]
    lib.xtb_pool_kernel_perf.argtypes = [c.c_int, c.c_void_p]
    lib.xtb_stream_triad.argtypes = [c.c_void_p, c.c_void_p, c.c_float,
                                     c.c_void_p, c.c_int64]
    lib.xtb_pool_instance_id.restype = c.c_uint64
    lib.xtb_simd_set.restype = c.c_int
    lib.xtb_simd_set.argtypes = [c.c_int]
    lib.xtb_simd_get.restype = c.c_int
    lib.xtb_simd_detected.restype = c.c_int
    lib.xtb_simd_lanes.restype = c.c_int
    lib.xtb_simd_name.restype = c.c_char_p
    lib.xtb_simd_name.argtypes = [c.c_int]


def _pool_libs() -> list:
    """Loaded kernel libraries, deduped by pool instance: gcc gives the
    pool's inline static STB_GNU_UNIQUE linkage, so libxtb_native.so and
    libxtb_ffi.so normally SHARE one pool in-process (configuring/killing/
    counting through either handle hits the same instance)."""
    seen, out = set(), []
    for lib in (load_native(), _FFI_LIB):
        if lib is None:
            continue
        pid = int(lib.xtb_pool_instance_id())
        if pid not in seen:
            seen.add(pid)
            out.append(lib)
    return out


_NTHREAD_CAP = 1024  # must mirror XtbThreadPool::resolve's clamp


def resolve_nthread(n: int = 0) -> int:
    """Effective thread count for ``nthread=n`` (0/negative = default),
    with the same 1024 cap the C++ pool applies — so the cached value,
    the gauge, and bench provenance report what the pool actually runs."""
    if n and int(n) > 0:
        return min(int(n), _NTHREAD_CAP)
    env = os.environ.get("XGBOOST_TPU_NTHREAD", "").strip()
    if env:
        try:
            v = int(env)
            if v > 0:
                return min(v, _NTHREAD_CAP)
        except ValueError:
            pass
    return min(os.cpu_count() or 1, _NTHREAD_CAP)


def set_nthread(n: int = 0) -> int:
    """Configure the native ParallelFor pools (both libraries) to ``n``
    threads (0 = default precedence above).  Kernel results are bitwise
    independent of this value (docs/native_threading.md); it only changes
    how many cores the native kernels use.  Idempotent and cheap when the
    effective count is unchanged."""
    global _NTHREAD
    _pool_fault_probe()
    eff = resolve_nthread(n)
    if eff == _NTHREAD:
        return eff
    for lib in _pool_libs():
        lib.xtb_set_nthread(eff)
    _NTHREAD = eff
    return eff


def get_nthread() -> int:
    """The currently applied pool width (resolving the default lazily)."""
    if _NTHREAD is None:
        return set_nthread(0)
    return _NTHREAD


def ensure_pool() -> None:
    """Dispatch-site hook (ops/histogram.py, ops/predict.py): apply the
    default pool width once before the first native kernel runs."""
    if _NTHREAD is None:
        set_nthread(0)


# --------------------------------------------------------------------------
# SIMD level control (native/xtb_simd.h).  Kernel output is bitwise
# level-INDEPENDENT (the lane-width axis of the determinism contract,
# fuzzed by tests/test_native_threads.py), so flipping this only selects
# which identical-output body runs.  Initial level: XGBOOST_TPU_SIMD env
# (scalar|avx2|neon|auto), else the best ISA cpuid reports.
# --------------------------------------------------------------------------

_SIMD: Optional[int] = None  # last applied level (C-side enum), None = auto
_SIMD_LEVELS = {"auto": -1, "scalar": 0, "avx2": 1, "neon": 2}


def set_simd(level="auto") -> str:
    """Set the active SIMD level on every loaded kernel library.

    ``level``: "auto" (best detected), "scalar", "avx2", "neon", or the
    C-side integer.  A level this HOST cannot run (e.g. "neon" on x86)
    resolves to the detected best; an unknown NAME raises — typos should
    be loud, not silently benchmark the wrong thing.  Returns the
    effective level name.
    """
    global _SIMD
    if not isinstance(level, int):
        key = str(level).lower()
        if key not in _SIMD_LEVELS:
            raise ValueError(
                f"unknown SIMD level {level!r}; expected one of "
                f"{sorted(_SIMD_LEVELS)}")
        lvl = _SIMD_LEVELS[key]
    else:
        lvl = int(level)
    eff = lvl
    for lib in _pool_libs():
        eff = int(lib.xtb_simd_set(lvl))
    _SIMD = eff if eff >= 0 else None
    return get_simd()


def get_simd() -> str:
    """The active SIMD level name on the loaded libraries ("scalar" when no
    native library is available — the pure-Python fallbacks are scalar)."""
    for lib in _pool_libs():
        return lib.xtb_simd_name(lib.xtb_simd_get()).decode()
    return "scalar"


def simd_info() -> dict:
    """Provenance record for benches (BENCH_LADDER.json metadata): active
    and detected ISA, lane width, and the raw CPU flags the detection saw."""
    info = {"active": "scalar", "detected": "scalar", "lanes": 1,
            "env": os.environ.get("XGBOOST_TPU_SIMD") or None}
    for lib in _pool_libs():
        info["active"] = lib.xtb_simd_name(lib.xtb_simd_get()).decode()
        info["detected"] = lib.xtb_simd_name(lib.xtb_simd_detected()).decode()
        info["lanes"] = int(lib.xtb_simd_lanes())
        break
    flags = []
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith(("flags", "Features")):
                    present = set(line.split(":", 1)[1].split())
                    flags = sorted(present & {"avx", "avx2", "avx512f",
                                              "fma", "sse4_2", "asimd",
                                              "neon", "sve"})
                    break
    except OSError:  # pragma: no cover - non-procfs hosts
        pass
    info["cpu_flags"] = flags
    return info


def _pool_fault_probe() -> None:
    """`native.parallel_for` fault seam (reliability/faults.py): fires when
    the pool is (re)configured.  ``kill``/``exception``/``delay`` apply at
    the seam; the caller-applied kinds (``drop_connection``/``truncate``)
    make the pool lose one worker thread before its next region — the pool
    must complete the region on the remaining threads, stay bitwise-correct,
    and respawn (pinned by tests/test_native_threads.py)."""
    try:
        from ..reliability.faults import maybe_inject
    except ImportError:  # pragma: no cover - partial install
        return
    spec = maybe_inject("native.parallel_for")
    if spec is not None and spec.kind in ("drop_connection", "truncate"):
        for lib in _pool_libs():
            lib.xtb_pool_kill_worker()


def pool_stats() -> dict:
    """Aggregated pool counters across loaded libraries:
    ``{"nthread", "alive_workers", "faults_total", "regions_total",
    "kernels": {name: {"regions", "busy_ns", "buckets": [11],
    "invocations", "wall_ns", "cycles", "bytes", "flops"}}}``.
    The last five come from the per-kernel XtbKernelPerf scopes (rdtsc
    cycles + modeled bytes/flops); the Python-side telemetry bridge
    (telemetry/native_pool.py) folds the deltas into the registry and
    scripts/bench_roofline.py turns them into achieved GB/s."""
    out = {
        "nthread": get_nthread(),
        "alive_workers": 0,
        "faults_total": 0,
        "regions_total": 0,
        "kernels": {},
    }
    for lib in _pool_libs():
        out["alive_workers"] += int(lib.xtb_pool_alive_workers())
        out["faults_total"] += int(lib.xtb_pool_faults_total())
        out["regions_total"] += int(lib.xtb_pool_regions_total())
        buf = (ctypes.c_int64 * POOL_STAT_SLOTS)()
        pbuf = (ctypes.c_int64 * POOL_PERF_SLOTS)()
        for k in range(int(lib.xtb_pool_n_kernels())):
            name = lib.xtb_pool_kernel_name(k).decode()
            lib.xtb_pool_kernel_stats(k, buf)
            lib.xtb_pool_kernel_perf(k, pbuf)
            agg = out["kernels"].setdefault(
                name, {"regions": 0, "busy_ns": 0,
                       "buckets": [0] * (POOL_STAT_SLOTS - 2),
                       "invocations": 0, "wall_ns": 0, "cycles": 0,
                       "bytes": 0, "flops": 0})
            agg["regions"] += int(buf[0])
            agg["busy_ns"] += int(buf[1])
            for i in range(POOL_STAT_SLOTS - 2):
                agg["buckets"][i] += int(buf[2 + i])
            for i, key in enumerate(("invocations", "wall_ns", "cycles",
                                     "bytes", "flops")):
                agg[key] += int(pbuf[i])
    return out


def stream_triad(b, c, scalar, a) -> bool:
    """Run the native STREAM-style triad ``a[i] = b[i] + scalar*c[i]``
    through the ParallelFor pool (scripts/bench_roofline.py's host-peak
    probe).  Arrays must be contiguous float32 of equal length.  Returns
    False when no native library is loaded (caller falls back to numpy)."""
    import numpy as np

    for lib in _pool_libs():
        n = int(a.shape[0])
        assert b.shape[0] == n and c.shape[0] == n
        lib.xtb_stream_triad(
            b.ctypes.data_as(ctypes.c_void_p),
            c.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_float(float(scalar)),
            a.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int64(n))
        return True
    a[:] = b + np.float32(scalar) * c
    return False


_FFI_READY: Optional[bool] = None

# Distributed veto: when a multi-process communicator finds the FFI kernels
# unavailable on ANY rank, every rank must take the XLA formulations —
# split gains differ from the native scan in the last ulp, and
# heterogeneous per-rank impls could pick different near-tie splits on the
# redundant per-process evaluation (collective.py flips this at init).
FFI_DISTRIBUTED_VETO = False


def load_ffi() -> bool:
    """Build/load the XLA FFI handler library and register its targets.

    Returns True when ``xtb_hist`` / ``xtb_split`` are registered as CPU
    custom calls (jax.ffi).  The pure_callback route is NOT used as a
    fallback — jax 0.9's CPU host-callback deadlocks on large operands —
    callers fall back to the XLA scatter/cumsum formulations instead."""
    global _FFI_READY, _FFI_LIB
    if _FFI_READY is not None:
        return _FFI_READY
    _FFI_READY = False
    nd = _native_dir()
    so = os.path.join(nd, "libxtb_ffi.so")
    srcs = [os.path.join(nd, n)
            for n in ("xtb_ffi.cc", "xtb_kernels.h", "xtb_simd.h")]
    try:
        stale = (not os.path.exists(so)
                 or any(os.path.exists(s)
                        and os.path.getmtime(s) > os.path.getmtime(so)
                        for s in srcs))
        if stale:
            # serialize concurrent builders (multi-process training on one
            # host): the Makefile writes via a temp + rename, the flock
            # makes sure only one make runs and the rest wait for it
            import fcntl

            with open(os.path.join(nd, ".ffi_build.lock"), "w") as lk:
                fcntl.flock(lk, fcntl.LOCK_EX)
                try:
                    subprocess.run(["make", "-C", nd, "ffi"],
                                   capture_output=True, timeout=180,
                                   check=True)
                finally:
                    fcntl.flock(lk, fcntl.LOCK_UN)
        import ctypes as c

        ffi = jax_ffi()
        lib = c.CDLL(so)
        for name, sym in (("xtb_hist", lib.XtbHist),
                          ("xtb_hist_q", lib.XtbHistQ),
                          ("xtb_split", lib.XtbSplit),
                          ("xtb_predict", lib.XtbPredict),
                          ("xtb_predict_binned", lib.XtbPredictBinned),
                          ("xtb_lambdarank", lib.XtbLambdaRank)):
            ffi.register_ffi_target(name, ffi.pycapsule(sym), platform="cpu")
        _bind_pool_abi(lib)
        _FFI_LIB = lib
        if _NTHREAD is not None:  # pool configured before this lib loaded
            lib.xtb_set_nthread(_NTHREAD)
        if _SIMD is not None:
            lib.xtb_simd_set(_SIMD)
        _FFI_READY = True
    except Exception:
        _FFI_READY = False
    return _FFI_READY


def ffi_usable() -> bool:
    """load_ffi() minus the distributed veto — the gate compute paths use."""
    return not FFI_DISTRIBUTED_VETO and load_ffi()


_WIRE_LIB = None
_WIRE_TRIED = False


def load_wire() -> Optional[ctypes.CDLL]:
    """The fleet wire rx library (native/xtb_wire.cc): one GIL release
    covers a whole frame read + CRC verify on serving sockets.  Same
    auto-build / graceful-None contract as :func:`load_native`;
    serving/wire.py keeps its pure-Python reader when this returns None,
    so the wire contract never depends on a toolchain."""
    global _WIRE_LIB, _WIRE_TRIED
    if _WIRE_LIB is not None or _WIRE_TRIED:
        return _WIRE_LIB
    _WIRE_TRIED = True
    nd = _native_dir()
    so = os.path.join(nd, "libxtb_wire.so")
    src = os.path.join(nd, "xtb_wire.cc")
    stale = (not os.path.exists(so)
             or (os.path.exists(src)
                 and os.path.getmtime(src) > os.path.getmtime(so)))
    if stale:
        try:
            subprocess.run(["make", "-C", nd, "wire"], capture_output=True,
                           timeout=120, check=True)
        except Exception:
            if not os.path.exists(so):
                return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    c = ctypes
    lib.xtb_wire_read_prefix.restype = c.c_int
    lib.xtb_wire_read_prefix.argtypes = [
        c.c_int, c.c_double, c.POINTER(c.c_uint), c.POINTER(c.c_ulonglong),
        c.POINTER(c.c_uint), c.POINTER(c.c_double)]
    lib.xtb_wire_read_body.restype = c.c_int
    lib.xtb_wire_read_body.argtypes = [
        c.c_int, c.c_void_p, c.c_ulonglong, c.c_double, c.c_uint]
    lib.xtb_wire_crc32.restype = c.c_uint
    lib.xtb_wire_crc32.argtypes = [c.c_uint, c.c_void_p, c.c_ulonglong]
    _WIRE_LIB = lib
    return lib


def parse_libsvm(path: str):
    """Parse a libsvm file -> (indptr, indices, values, labels, qid|None, n_col).

    Native fast path; pure-Python fallback parses the same grammar.
    """
    with open(path, "rb") as fh:
        raw = fh.read()
    lib = load_native()
    if lib is not None:
        h = lib.xtb_parse_libsvm(raw, len(raw))
        try:
            rows = lib.xtb_csr_rows(h)
            nnz = lib.xtb_csr_nnz(h)
            cols = lib.xtb_csr_cols(h)
            has_qid = bool(lib.xtb_csr_has_qid(h))
            if has_qid and lib.xtb_csr_qid_count(h) != rows:
                raise ValueError(
                    f"libsvm file has qid on only {lib.xtb_csr_qid_count(h)} "
                    f"of {rows} rows; qid must cover every row")
            indptr = np.empty(rows + 1, np.int64)
            indices = np.empty(nnz, np.int32)
            values = np.empty(nnz, np.float32)
            labels = np.empty(rows, np.float32)
            qids = np.empty(rows, np.int64) if has_qid else None
            lib.xtb_csr_copy(
                h, indptr.ctypes.data, indices.ctypes.data, values.ctypes.data,
                labels.ctypes.data, qids.ctypes.data if has_qid else None)
            return indptr, indices, values, labels, qids, cols
        finally:
            lib.xtb_csr_free(h)
    # fallback
    indptr, indices, values, labels, qids = [0], [], [], [], []
    n_col = 0
    has_qid = False
    for line in raw.decode("utf-8", "ignore").splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        labels.append(float(parts[0]))
        for tok in parts[1:]:
            if tok.startswith("#"):
                break
            k, v = tok.split(":", 1)
            if k == "qid":
                has_qid = True
                qids.append(int(v))
                continue
            idx = int(k)
            indices.append(idx)
            values.append(float(v))
            n_col = max(n_col, idx + 1)
        indptr.append(len(indices))
    if has_qid and len(qids) != len(labels):
        raise ValueError(
            f"libsvm file has qid on only {len(qids)} of {len(labels)} rows; "
            f"qid must cover every row")
    return (np.asarray(indptr, np.int64), np.asarray(indices, np.int32),
            np.asarray(values, np.float32), np.asarray(labels, np.float32),
            np.asarray(qids, np.int64) if has_qid else None, n_col)


def parse_csv(path: str, skip_header: Optional[bool] = None) -> np.ndarray:
    with open(path, "rb") as fh:
        raw = fh.read()
    if skip_header is None:
        # sniff: a first line whose first field isn't numeric is a header
        first = raw.split(b"\n", 1)[0].split(b",", 1)[0].strip()
        try:
            float(first)
            skip_header = False
        except ValueError:
            skip_header = bool(first)
    lib = load_native()
    if lib is not None:
        h = lib.xtb_parse_csv(raw, len(raw), int(skip_header))
        try:
            rows = lib.xtb_dense_rows(h)
            cols = lib.xtb_dense_cols(h)
            out = np.empty((rows, cols), np.float32)
            lib.xtb_dense_copy(h, out.ctypes.data)
            return out
        finally:
            lib.xtb_dense_free(h)
    return np.genfromtxt(path, delimiter=",", dtype=np.float32,
                         skip_header=int(skip_header))


_ELLPACK_DTYPE_CODES = {np.dtype(np.uint8): 0, np.dtype(np.int16): 1,
                        np.dtype(np.int32): 2}


def ellpack_bin_native(X: np.ndarray, cut_values: np.ndarray,
                       cut_ptrs: np.ndarray, n_bin_pad: int,
                       dtype) -> Optional[np.ndarray]:
    """Native Ellpack binning (xtb_kernels.h xtb_ellpack_bin_impl): bin a
    dense (R, F) f32 matrix against per-feature cuts, bitwise-equal to the
    XLA searchsorted path in data/ellpack.py (upper_bound, clamp into the
    top bin, NaN -> sentinel ``n_bin_pad``).  Streams X row-major once and
    writes the page sequentially through the threaded row-sharded kernel.
    Returns None when the native library is unavailable."""
    lib = load_native()
    if lib is None:
        return None
    code = _ELLPACK_DTYPE_CODES.get(np.dtype(dtype))
    if code is None:
        return None
    R, F = X.shape
    Xc = np.ascontiguousarray(X, np.float32)
    cv = np.ascontiguousarray(cut_values, np.float32)
    cp = np.ascontiguousarray(cut_ptrs, np.int32)
    out = np.empty((R, F), np.dtype(dtype))
    ensure_pool()
    lib.xtb_ellpack_bin(Xc.ctypes.data, R, F, cv.ctypes.data, cp.ctypes.data,
                        int(n_bin_pad), code, out.ctypes.data)
    return out


def shap_values_native(t: dict, X: np.ndarray,
                       max_depth: int) -> Optional[np.ndarray]:
    """Row-parallel exact TreeSHAP for one scalar-leaf numeric tree
    (native/xtb_kernels.h xtb_shap_values_impl — the f64 twin of the host
    walk in interpret/__init__.py, identical operation order).

    ``t`` is interpret's ``_tree_arrays`` dict; returns (R, F+1) with the
    bias column left at zero (the caller fills the tree expectation), or
    None when the native library is unavailable."""
    lib = load_native()
    if lib is None:
        return None
    R, F = X.shape
    Xc = np.ascontiguousarray(X, np.float64)
    left = np.ascontiguousarray(t["left"], np.int32)
    right = np.ascontiguousarray(t["right"], np.int32)
    feat = np.ascontiguousarray(t["feat"], np.int32)
    thr = np.ascontiguousarray(t["thr"], np.float64)
    dleft = np.ascontiguousarray(t["dleft"], np.uint8)
    value = np.ascontiguousarray(t["value"], np.float64)
    cover = np.ascontiguousarray(t["cover"], np.float64)
    out = np.zeros((R, F + 1), np.float64)
    ensure_pool()
    lib.xtb_shap_values(
        Xc.ctypes.data, R, F, left.ctypes.data, right.ctypes.data,
        feat.ctypes.data, thr.ctypes.data, dleft.ctypes.data,
        value.ctypes.data, cover.ctypes.data, int(max_depth),
        out.ctypes.data)
    return out


class StreamingQuantileSummary:
    """Per-feature streaming weighted quantile summary (GK-style merge-prune).

    Native-backed when available; numpy fallback keeps semantics identical.
    The external-memory sketcher now uses the page-wise
    ``data/quantile.py StreamingSketch`` (its merge is the bitwise-pinned
    distributed contract, docs/extmem.md); this remains the public
    bounded-memory single-column summary API (native kernel +
    tests/test_native_threads.py) for callers that cannot batch a page.
    """

    def __init__(self, budget: int = 2048):
        self.budget = budget
        self._lib = load_native()
        if self._lib is not None:
            self._h = self._lib.xtb_summary_new(budget)
        else:
            self._vals = np.zeros(0, np.float32)
            self._wts = np.zeros(0, np.float64)

    def push(self, values: np.ndarray, weights: Optional[np.ndarray] = None):
        values = np.ascontiguousarray(values, np.float32)
        if self._lib is not None:
            w = None if weights is None else np.ascontiguousarray(weights, np.float32)
            self._lib.xtb_summary_push(
                self._h, values.ctypes.data,
                w.ctypes.data if w is not None else None, len(values))
            return
        keep = ~np.isnan(values)
        v = values[keep]
        w = (np.ones(len(v)) if weights is None
             else np.asarray(weights, np.float64)[keep])
        pos = w > 0  # native path drops non-positive weights; keep parity
        v, w = v[pos], w[pos]
        self._vals = np.concatenate([self._vals, v])
        self._wts = np.concatenate([self._wts, w])
        if len(self._vals) > 2 * self.budget:
            self._prune()

    def _prune(self):
        order = np.argsort(self._vals, kind="stable")
        v, w = self._vals[order], self._wts[order]
        cdf = np.cumsum(w)
        targets = cdf[-1] * np.arange(1, self.budget + 1) / self.budget
        idx = np.searchsorted(cdf, targets, side="left")
        idx = np.clip(idx, 0, len(v) - 1)
        uniq, first = np.unique(idx, return_index=True)
        seg_w = np.diff(np.concatenate([[0.0], cdf[uniq]]))
        self._vals = v[uniq].astype(np.float32)
        self._wts = seg_w
    def total_weight(self) -> float:
        if self._lib is not None:
            return float(self._lib.xtb_summary_total(self._h))
        return float(self._wts.sum())

    def query(self, qs: np.ndarray) -> np.ndarray:
        qs = np.ascontiguousarray(qs, np.float64)
        out = np.empty(len(qs), np.float32)
        if self._lib is not None:
            self._lib.xtb_summary_query(self._h, qs.ctypes.data, len(qs),
                                        out.ctypes.data)
            return out
        if len(self._vals) == 0:
            return np.zeros(len(qs), np.float32)
        order = np.argsort(self._vals, kind="stable")
        v, w = self._vals[order], self._wts[order]
        cdf = np.cumsum(w)
        idx = np.searchsorted(cdf, qs * cdf[-1], side="left")
        return v[np.clip(idx, 0, len(v) - 1)].astype(np.float32)

    def __del__(self):
        if getattr(self, "_lib", None) is not None and getattr(self, "_h", None):
            try:
                self._lib.xtb_summary_free(self._h)
            except Exception:
                pass
