"""UBJSON reader/writer (reference: include/xgboost/json_io.h:188 UBJReader,
:230 UBJWriter — used for ``.ubj`` binary model files).

Implements the UBJSON draft-12 subset the reference emits: objects, arrays
(including optimized strongly-typed arrays with ``$`` type and ``#`` count),
strings, int8/16/32/64, float32/64, bools, null.  Python ints/floats map to the
smallest lossless tag, matching the reference writer's behavior.
"""
from __future__ import annotations

import struct
from typing import Any, BinaryIO

import numpy as np


def _write_int(fh: BinaryIO, v: int) -> None:
    if -128 <= v <= 127:
        fh.write(b"i" + struct.pack(">b", v))
    elif 0 <= v <= 255:
        fh.write(b"U" + struct.pack(">B", v))
    elif -(2**15) <= v < 2**15:
        fh.write(b"I" + struct.pack(">h", v))
    elif -(2**31) <= v < 2**31:
        fh.write(b"l" + struct.pack(">i", v))
    else:
        fh.write(b"L" + struct.pack(">q", v))


def _write_str_payload(fh: BinaryIO, s: str) -> None:
    b = s.encode("utf-8")
    _write_int(fh, len(b))
    fh.write(b)


def dump_ubjson(obj: Any, fh: BinaryIO) -> None:
    if obj is None:
        fh.write(b"Z")
    elif obj is True:
        fh.write(b"T")
    elif obj is False:
        fh.write(b"F")
    elif isinstance(obj, (int, np.integer)):
        _write_int(fh, int(obj))
    elif isinstance(obj, (float, np.floating)):
        fh.write(b"D" + struct.pack(">d", float(obj)))
    elif isinstance(obj, str):
        fh.write(b"S")
        _write_str_payload(fh, obj)
    elif isinstance(obj, np.ndarray) and obj.dtype == np.float32:
        fh.write(b"[$d#")
        _write_int(fh, obj.size)
        fh.write(obj.astype(">f4").tobytes())
    elif isinstance(obj, np.ndarray) and obj.dtype in (np.int32, np.dtype(">i4")):
        fh.write(b"[$l#")
        _write_int(fh, obj.size)
        fh.write(obj.astype(">i4").tobytes())
    elif isinstance(obj, (list, tuple, np.ndarray)):
        fh.write(b"[")
        for it in obj:
            dump_ubjson(it, fh)
        fh.write(b"]")
    elif isinstance(obj, dict):
        fh.write(b"{")
        for k, v in obj.items():
            _write_str_payload(fh, str(k))
            dump_ubjson(v, fh)
        fh.write(b"}")
    else:
        raise TypeError(f"UBJSON: unsupported type {type(obj)}")


_INT_FMT = {b"i": ">b", b"U": ">B", b"I": ">h", b"l": ">i", b"L": ">q"}
_FLOAT_FMT = {b"d": ">f", b"D": ">d"}


class _Reader:
    def __init__(self, fh: BinaryIO):
        self.fh = fh

    def _read_exact(self, n: int) -> bytes:
        """Short reads become EOFError, not struct.error — truncated or
        corrupt model buffers must fail with a clean python-level error
        (tests/test_model_io_fuzz.py)."""
        if n < 0:
            raise ValueError("UBJSON: negative length")
        b = self.fh.read(n)
        if len(b) != n:
            raise EOFError("unexpected end of UBJSON stream")
        return b

    def tag(self) -> bytes:
        return self._read_exact(1)

    def read_int(self, t: bytes) -> int:
        fmt = _INT_FMT[t]
        return struct.unpack(fmt, self._read_exact(struct.calcsize(fmt)))[0]

    def read_len(self) -> int:
        n = self.read_int(self.tag())
        if n < 0:
            raise ValueError("UBJSON: negative length")
        return n

    def read_str(self) -> str:
        n = self.read_len()
        return self._read_exact(n).decode("utf-8")

    def value(self, t: bytes) -> Any:
        if t in _INT_FMT:
            return self.read_int(t)
        if t in _FLOAT_FMT:
            fmt = _FLOAT_FMT[t]
            return struct.unpack(fmt, self._read_exact(struct.calcsize(fmt)))[0]
        if t == b"S":
            return self.read_str()
        if t == b"T":
            return True
        if t == b"F":
            return False
        if t == b"Z":
            return None
        if t == b"[":
            return self.array()
        if t == b"{":
            return self.obj()
        raise ValueError(f"UBJSON: bad tag {t!r}")

    def array(self) -> Any:
        t = self.tag()
        typ = None
        count = None
        if t == b"$":
            typ = self.tag()
            t = self.tag()
        if t == b"#":
            count = self.read_len()
        if typ is not None:
            assert count is not None
            if typ in _FLOAT_FMT:
                fmt = _FLOAT_FMT[typ]
                sz = struct.calcsize(fmt)
                arr = np.frombuffer(self._read_exact(sz * count), dtype=fmt).astype(
                    np.float32 if typ == b"d" else np.float64
                )
                return arr.tolist()
            if typ in _INT_FMT:
                fmt = _INT_FMT[typ]
                sz = struct.calcsize(fmt)
                return np.frombuffer(self._read_exact(sz * count), dtype=fmt).tolist()
            raise ValueError(f"UBJSON: bad array type {typ!r}")
        out = []
        if count is not None:
            for _ in range(count):
                out.append(self.value(self.tag()))
            return out
        while t != b"]":
            out.append(self.value(t))
            t = self.tag()
        return out

    def obj(self) -> dict:
        out = {}
        while True:
            t = self.tag()
            if t == b"}":
                return out
            # key: length tag already read
            n = self.read_int(t)
            key = self._read_exact(n).decode("utf-8")
            out[key] = self.value(self.tag())


def load_ubjson(fh: BinaryIO) -> Any:
    r = _Reader(fh)
    return r.value(r.tag())
