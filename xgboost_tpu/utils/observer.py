"""TrainingObserver: diff-friendly debug dumps of training internals.

Reference: src/common/observer.h:38 — under XGBOOST_USE_DEBUG_OUTPUT the
reference prints gradients/predictions/trees each iteration for cross-build
diffing.  Enable here with XGBOOST_TPU_DEBUG_OBSERVER=1 (or observe(True));
the Booster calls into this after every boosting round.
"""
from __future__ import annotations

import os
import sys
from typing import Optional

import numpy as np

_ENABLED: Optional[bool] = None


def enabled() -> bool:
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = os.environ.get("XGBOOST_TPU_DEBUG_OBSERVER", "0") in ("1", "true")
    return _ENABLED


def observe(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def _dump(name: str, arr, limit: int = 16) -> None:
    a = np.asarray(arr).reshape(-1)
    head = ", ".join(f"{v:.6g}" for v in a[:limit])
    print(f"[observer] {name}: n={a.size} sum={a.sum():.6g} head=[{head}]",
          file=sys.stderr, flush=True)


def observe_gradients(gpair, iteration: int) -> None:
    if enabled():
        _dump(f"iter{iteration}.grad", np.asarray(gpair)[..., 0])
        _dump(f"iter{iteration}.hess", np.asarray(gpair)[..., 1])


def observe_margin(margin, iteration: int) -> None:
    if enabled():
        _dump(f"iter{iteration}.margin", margin)


def observe_serving(snapshot: dict, tag: str = "serving") -> None:
    """Stream a ServingMetrics snapshot (serving/metrics.py) in the same
    diff-friendly one-line-per-signal format as the training dumps."""
    if not enabled():
        return
    print(f"[observer] {tag}: queue_depth={snapshot.get('queue_depth')} "
          f"queue_peak={snapshot.get('queue_peak')} "
          f"compiles_warmup={snapshot.get('compiles_warmup')} "
          f"compiles_steady={snapshot.get('compiles_steady')}",
          file=sys.stderr, flush=True)
    for name, m in sorted(snapshot.get("models", {}).items()):
        lat = m.get("latency_ms") or {}
        fmt = lambda v: "n/a" if v is None else f"{v:.3f}"  # noqa: E731
        print(f"[observer] {tag}.{name}: requests={m.get('requests')} "
              f"rows={m.get('rows')} errors={m.get('errors')} "
              f"batches={m.get('batches')} "
              f"p50={fmt(lat.get('p50'))}ms p95={fmt(lat.get('p95'))}ms "
              f"p99={fmt(lat.get('p99'))}ms",
              file=sys.stderr, flush=True)


def observe_tree(tree, iteration: int) -> None:
    if enabled():
        print(f"[observer] iter{iteration}.tree nodes={tree.n_nodes} "
              f"leaves={tree.num_leaves}", file=sys.stderr, flush=True)
        _dump(f"iter{iteration}.leaf_values",
              tree.split_conditions[tree.left_children == -1])
