"""Logging (reference: include/xgboost/logging.h:39-63 ConsoleLogger with
verbosity 0-3, XGBRegisterLogCallback redirection)."""
from __future__ import annotations

import sys
from typing import Callable, Optional

from ..config import get_config

_CALLBACK: Optional[Callable[[str], None]] = None

SILENT, WARNING, INFO, DEBUG = 0, 1, 2, 3


def register_log_callback(fn: Optional[Callable[[str], None]]) -> None:
    """Redirect log lines into the host application
    (reference: XGBRegisterLogCallback)."""
    global _CALLBACK
    _CALLBACK = fn


def _emit(msg: str) -> None:
    if _CALLBACK is not None:
        _CALLBACK(msg)
    else:
        print(msg, file=sys.stderr, flush=True)


def log(level: int, msg: str) -> None:
    if get_config().get("verbosity", 1) >= level:
        _emit(msg)


def console(msg: str) -> None:
    """User-facing output shown at default verbosity (the reference's
    ConsoleLogger CONSOLE channel: eval lines etc. — silenced only by
    verbosity=0, redirected by register_log_callback like everything
    else)."""
    log(WARNING, msg)


def warning(msg: str) -> None:
    log(WARNING, f"WARNING: {msg}")


def info(msg: str) -> None:
    log(INFO, msg)


def debug(msg: str) -> None:
    log(DEBUG, msg)
