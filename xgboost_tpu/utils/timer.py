"""Per-label cumulative timers (reference: src/common/timer.h:45 Monitor).

The reference brackets every hot method with Monitor::Start/Stop and emits
NVTX ranges under USE_NVTX; here Start/Stop also opens a jax.profiler
TraceAnnotation so the same labels show up in TPU profiler traces.
Printed at verbosity >= 3 like the reference (timer.cc).
"""
from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict, Optional

from ..config import get_config


class Monitor:
    def __init__(self, label: str = "") -> None:
        self.label = label
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self._open: Dict[str, float] = {}
        self._annotations: Dict[str, object] = {}

    def start(self, name: str) -> None:
        self._open[name] = time.perf_counter()
        try:
            import jax.profiler

            ann = jax.profiler.TraceAnnotation(f"{self.label}.{name}")
            ann.__enter__()
            self._annotations[name] = ann
        except Exception:
            pass

    def stop(self, name: str) -> None:
        t0 = self._open.pop(name, None)
        if t0 is not None:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1
        ann = self._annotations.pop(name, None)
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass

    def print_statistics(self) -> None:
        if get_config().get("verbosity", 1) < 3 or not self.totals:
            return
        print(f"======== Monitor ({self.label}) ========")
        for name in sorted(self.totals):
            print(f"{name}: {self.totals[name]*1e3:.3f}ms, {self.counts[name]} calls")
