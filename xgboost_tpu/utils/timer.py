"""Per-label cumulative timers (reference: src/common/timer.h:45 Monitor).

Now a thin shim over the telemetry span tracer (telemetry/spans.py): each
Start/Stop bracket opens a jax.profiler.TraceAnnotation (the reference's
NVTX range role) and — when telemetry is enabled — records into the
``xtb_phase_seconds`` histogram and the JSONL trace under the same
``label.name`` the TPU profiler shows.  Totals/counts accumulate locally
regardless of the telemetry flag and print at verbosity >= 3 like the
reference (timer.cc).

Re-entrancy: ``start(name)`` pushes onto a per-label stack, so nested or
overlapping brackets of the same label each close their own timestamp and
annotation (a second start() used to silently overwrite the open timestamp
and leak the previous annotation without __exit__).
"""
from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict, List, Tuple

from ..config import get_config
from ..telemetry import spans as _spans


class Monitor:
    def __init__(self, label: str = "") -> None:
        self.label = label
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        # name -> stack of (t0_ns, annotation-or-None): LIFO per label so
        # re-entrant brackets nest instead of clobbering each other
        self._open: Dict[str, List[Tuple[int, object]]] = defaultdict(list)

    def start(self, name: str) -> None:
        ann = _spans._annotation(f"{self.label}.{name}")
        self._open[name].append((time.perf_counter_ns(), ann))

    def stop(self, name: str) -> None:
        stack = self._open.get(name)
        if not stack:
            return  # unmatched stop: ignore, like the pop(None) before
        t0, ann = stack.pop()
        dur_ns = time.perf_counter_ns() - t0
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:  # pragma: no cover - profiler backend quirk
                pass
        self.totals[name] += dur_ns / 1e9
        self.counts[name] += 1
        if _spans.enabled():
            _spans.record_phase(f"{self.label}.{name}", t0, dur_ns)

    def print_statistics(self) -> None:
        if get_config().get("verbosity", 1) < 3 or not self.totals:
            return
        print(f"======== Monitor ({self.label}) ========")
        for name in sorted(self.totals):
            print(f"{name}: {self.totals[name]*1e3:.3f}ms, {self.counts[name]} calls")
