"""Booster: the trained model + training-step engine.

TPU-native analogue of the reference Learner + GBTree + Python Booster
(src/learner.cc:1030 LearnerImpl, src/gbm/gbtree.cc:225 DoBoost,
python-package/xgboost/core.py:1749 Booster).  One object plays all three
roles: it owns the objective, the tree list, per-DMatrix training caches
(binned Ellpack + margin cache — the prediction cache of
include/xgboost/cache.h:26), and the save/load surface.

Call stack for one boosting iteration (mirrors SURVEY §3.1):
  train() -> Booster.update(dtrain, i)
    -> objective.get_gradient on the cached margin           [device]
    -> HistTreeGrower.grow per output group                  [device loop]
    -> leaf_margin_delta updates the margin cache            [device]
    -> RegTree.from_grown appends the host model
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .context import Context
from .data.dmatrix import DMatrix
from .metric import create_metric
from .models.tree import RegTree
from .objective import ObjFunction, create_objective
from .ops.predict import predict_leaf_ids
from .ops.split import SplitParams
from .params import TrainParam, canonicalize, split_unknown
from .telemetry import span
from .tree.grow import HistTreeGrower, leaf_margin_delta

__all__ = ["Booster"]


class _Cache:
    """Per-DMatrix training cache: margin (+ binned Ellpack for training).

    Eval-only DMatrices never pay for sketching/binning: the Ellpack is built
    lazily on first training touch (finding: eval sets only need the raw
    feature matrix for the predictor)."""

    def __init__(self, dmat: DMatrix, max_bin: int, ref: Optional[DMatrix] = None,
                 mesh=None, distributed: bool = False):
        self.dmat = dmat
        self.max_bin = max_bin
        self.ref = ref
        self.mesh = mesh
        self.distributed = distributed
        self.ellpack = None
        self.n_padded = dmat.num_row()  # grows to the padded size on ensure_train
        self.margin: Optional[Any] = None  # (n_padded, K) device
        self.n_trees_applied = 0
        self.weights_version = 0  # DART tree-weight epoch this margin reflects
        self.raw_X: Optional[Any] = None  # lazily staged raw matrix for eval predict

    @property
    def is_extmem(self) -> bool:
        return hasattr(self.dmat, "_pages")

    def ensure_train_raw(self) -> None:
        """Label/weight/valid arrays WITHOUT sketching or binning: the exact
        updater walks raw host values, so the quantile sketch + Ellpack +
        device upload would be pure wasted startup cost."""
        import jax.numpy as jnp

        if self.ellpack is not None or getattr(self, "_raw_ready", False):
            return  # binned arrays already cover the raw path's needs
        R = self.dmat.num_row()
        self.valid = jnp.ones(R, bool)
        self.labels = jnp.asarray(self.dmat.get_label())
        w = self.dmat.get_weight()
        self.weights = None if w is None else jnp.asarray(w)
        self.n_padded = R
        self._raw_ready = True

    def ensure_train(self) -> None:
        """Build the binned page + padded label/weight/valid device arrays."""
        import jax.numpy as jnp

        if self.is_extmem:
            if getattr(self, "_extmem_ready", False):
                return
            d = self.dmat
            R_pad = d.n_padded_total
            self.valid = jnp.asarray(d.valid_mask())
            lab = d.padded_labels()
            self.labels = jnp.asarray(lab if lab is not None
                                      else np.zeros(R_pad, np.float32))
            w = d.padded_weights()
            self.weights = None if w is None else jnp.asarray(w)
            if self.margin is not None and self.margin.shape[0] != R_pad:
                extra = R_pad - self.margin.shape[0]
                self.margin = jnp.concatenate(
                    [self.margin, jnp.zeros((extra, self.margin.shape[1]), jnp.float32)], 0)
            self.n_padded = R_pad
            self._extmem_ready = True
            return
        if self.ellpack is not None:
            return
        # pages must split evenly over the mesh: row_align = lcm(1024, n)
        # (VERDICT r3 #10 — arbitrary device counts, not just powers of two)
        import math

        align = 1024 if self.mesh is None else math.lcm(
            1024, self.mesh.devices.size)
        self.ellpack = self.dmat.ensure_ellpack(max_bin=self.max_bin,
                                                ref=self.ref,
                                                distributed=self.distributed,
                                                row_align=align)
        if self.mesh is not None:
            from .parallel import shard_rows

            # sharded COPY kept on the cache; the DMatrix's page stays intact
            # for later single-device training on the same matrix
            (self.bins,) = shard_rows(self.mesh, self.ellpack.bins)
        else:
            self.bins = self.ellpack.bins
        R_pad = self.ellpack.n_padded
        R = self.ellpack.n_rows
        self.valid = jnp.arange(R_pad) < R
        lab = self.dmat.get_label()
        pad = ((0, R_pad - R),) + tuple((0, 0) for _ in range(lab.ndim - 1))
        self.labels = jnp.asarray(np.pad(lab, pad))
        w = self.dmat.get_weight()
        self.weights = None if w is None else jnp.asarray(np.pad(w, (0, R_pad - R)))
        if self.margin is not None and self.margin.shape[0] != R_pad:
            extra = R_pad - self.margin.shape[0]
            self.margin = jnp.concatenate(
                [self.margin, jnp.zeros((extra, self.margin.shape[1]), jnp.float32)], axis=0
            )
        self.n_padded = R_pad

    def base_margin_init(self, base_score, K: int):
        import jax.numpy as jnp

        R_pad = self.n_padded
        user = self.dmat.info.base_margin
        if user is not None and self.is_extmem:
            m = self.dmat.padded_base_margin().reshape(R_pad, -1)
            if m.shape[1] != K:
                m = np.broadcast_to(m, (R_pad, K))
            return jnp.asarray(m.astype(np.float32))
        if user is not None:
            m = np.asarray(user, np.float32).reshape(len(user), -1)
            if m.shape[1] != K:
                m = np.broadcast_to(m, (m.shape[0], K))
            out = np.zeros((R_pad, K), np.float32)
            out[: m.shape[0]] = m
            return jnp.asarray(out)
        base = np.broadcast_to(np.asarray(base_score, np.float32).reshape(-1), (K,))
        return jnp.broadcast_to(jnp.asarray(base), (R_pad, K)).astype(jnp.float32)


class Booster:
    """Gradient-boosted tree model (reference: core.py:1749, learner.cc:1030)."""

    def __init__(
        self,
        params: Optional[Dict[str, Any]] = None,
        cache: Sequence[DMatrix] = (),
        model_file: Optional[str] = None,
    ) -> None:
        self.params: Dict[str, Any] = canonicalize(dict(params or {}))
        self.trees: List[RegTree] = []
        self.tree_info: List[int] = []  # group id per tree
        self.attributes: Dict[str, str] = {}
        self.feature_names: Optional[List[str]] = None
        self.feature_types: Optional[List[str]] = None
        self._caches: Dict[int, _Cache] = {}
        self._configured = False
        self.best_iteration: Optional[int] = None
        self.best_score: Optional[float] = None
        if model_file is not None:
            self.load_model(model_file)
        for d in cache:
            self._get_cache(d)

    # ------------------------------------------------------------------ config
    def _configure(self) -> None:
        """Lazy config (reference: learner.cc:521 Configure on every call)."""
        if self._configured:
            return
        p = self.params
        unknown = split_unknown(p)
        if unknown and str(p.get("validate_parameters", "")).lower() in ("1", "true"):
            raise ValueError(f"Unknown parameters: {unknown}")
        self.tparam = TrainParam.from_dict(p)
        self.context = Context.create(str(p.get("device", "cpu")),
                                      nthread=int(p.get("nthread", 0) or 0),
                                      seed=int(p.get("seed", 0)))
        # nthread reaches the native ParallelFor pool here (params dict /
        # XGBoosterSetParam("nthread") both land in p); results are bitwise
        # independent of the value (docs/native_threading.md)
        self.context.apply_nthread()
        obj_name = str(p.get("objective", "reg:squarederror"))
        self.objective: ObjFunction = create_objective(obj_name, p)
        self.num_class = int(p.get("num_class", 0))
        self.n_groups = max(1, self.objective.n_groups())
        self._base_score_param = p.get("base_score", None)
        if not hasattr(self, "_base_margin_value"):
            self._base_margin_value: Optional[np.ndarray] = None
        booster = str(p.get("booster", "gbtree"))
        if booster not in ("gbtree", "dart", "gblinear"):
            raise ValueError(f"unknown booster {booster}")
        self.booster_kind = booster
        # multi-chip data parallelism: n_devices = int | "all" (SURVEY §2 L1:
        # row sharding + histogram psum is the reference's whole comm pattern)
        nd = p.get("n_devices", 1)
        if isinstance(nd, bool) or (not isinstance(nd, int) and nd != "all"):
            raise ValueError(f"n_devices must be an int or 'all', got {nd!r}")
        if isinstance(nd, int) and nd < 1:
            raise ValueError(f"n_devices must be >= 1, got {nd}")
        self.n_devices = nd if isinstance(nd, int) else -1  # -1 = all
        self._mesh = None
        self.num_parallel_tree = int(p.get("num_parallel_tree", 1))
        # process_type=update re-processes an existing model's trees with
        # the non-growing updaters (gbtree.cc InitUpdater)
        self.tree_method = str(p.get("tree_method", "hist"))
        if self.tree_method in ("auto", "gpu_hist"):
            self.tree_method = "hist"
        if self.tree_method not in ("hist", "approx", "exact"):
            raise ValueError(f"unknown tree_method {self.tree_method!r}")
        self.process_type = str(p.get("process_type", "default"))
        if self.process_type not in ("default", "update"):
            raise ValueError(f"unknown process_type {self.process_type!r}")
        upd = p.get("updater")
        self.updater_seq = ([u.strip() for u in str(upd).split(",") if u.strip()]
                            if upd else None)
        self.refresh_leaf = str(p.get("refresh_leaf", "1")).lower() in ("1", "true")
        # fixed-point limb histograms (ops/quantise.py): bitwise-identical
        # trees on every chip x process topology — the reference's
        # GradientQuantiser behaviour (src/tree/gpu_hist/quantiser.cuh),
        # exposed as an opt-in because the f32 path is the faster default
        self.deterministic_histogram = str(
            p.get("deterministic_histogram", "0")).lower() in ("1", "true")
        # vector-leaf trees (multi_target_tree_model.h): one tree carries all
        # K outputs when multi_strategy="multi_output_tree"
        self.multi_strategy = str(p.get("multi_strategy", "one_output_per_tree"))
        if self.multi_strategy not in ("one_output_per_tree", "multi_output_tree"):
            raise ValueError(f"unknown multi_strategy {self.multi_strategy!r}")
        if not hasattr(self, "tree_weights"):
            self.tree_weights: List[float] = []
        if not hasattr(self, "linear_weights"):
            self.linear_weights: Optional[np.ndarray] = None  # (F, K)
            self.linear_bias: Optional[np.ndarray] = None  # (K,)
        # DART (reference: src/gbm/gbtree.cc Dart booster)
        self.rate_drop = float(p.get("rate_drop", 0.0))
        self.skip_drop = float(p.get("skip_drop", 0.0))
        self.one_drop = str(p.get("one_drop", "0")).lower() in ("1", "true")
        self.sample_type = str(p.get("sample_type", "uniform"))
        self.normalize_type = str(p.get("normalize_type", "tree"))
        if self.tparam.monotone_constraints is not None:
            pass  # length checked on first training touch (needs n_features)
        self._split_params = SplitParams(
            eta=float(self.tparam.eta),
            gamma=float(self.tparam.gamma),
            min_child_weight=float(self.tparam.min_child_weight),
            lambda_=float(self.tparam.lambda_),
            alpha=float(self.tparam.alpha),
            max_delta_step=float(self.tparam.max_delta_step),
            monotone=self.tparam.monotone_constraints,
            max_cat_to_onehot=int(self.tparam.max_cat_to_onehot),
        )
        self._configured = True

    # params whose change invalidates binned data / margins / objective state
    _STRUCTURAL_KEYS = {"max_bin", "objective", "num_class", "device", "booster",
                        "tree_method", "base_score", "num_target", "multi_strategy"}

    def _invalidate_config(self, structural: bool = True):
        self._configured = False
        if structural:
            self._caches.clear()
            # a TRAINED model's base score is model state, not configuration
            # (learner.cc saves it with the model; continuation never
            # re-estimates): clearing it here would silently rebuild every
            # continued-training margin from base 0
            if not self.trees and getattr(self, "linear_weights", None) is None:
                self._base_margin_value = None

    def set_param(self, params, value=None) -> None:
        if isinstance(params, str):
            params = {params: value}
        elif isinstance(params, (list, tuple)):
            params = dict(params)
        params = canonicalize(params)
        structural = any(
            k in self._STRUCTURAL_KEYS and self.params.get(k) != v
            for k, v in params.items()
        )
        self.params.update(params)
        self._invalidate_config(structural=structural)

    # ------------------------------------------------------------------ caches
    def _get_cache(self, dmat: DMatrix, ref: Optional[DMatrix] = None) -> _Cache:
        self._configure()
        key = id(dmat)
        if key not in self._caches:
            self._caches[key] = _Cache(dmat, self.tparam.max_bin, ref=ref,
                                       mesh=self._get_mesh(),
                                       distributed=self._process_parallel())
            if getattr(self, "_num_feature", None) is None:
                self._num_feature = dmat.num_col()
        return self._caches[key]

    def _ensure_base_margin(self, cache: _Cache):
        if self._base_margin_value is None:
            # InitEstimation / FitStump (src/tree/fit_stump.cc:34)
            if self._base_score_param is not None:
                prob = np.asarray(float(self._base_score_param), np.float32)
                bm = np.asarray(self.objective.prob_to_margin(prob))
            elif len(self.trees) == 0 and (
                cache.ellpack is not None
                or getattr(cache, "_raw_ready", False)
                or (cache.is_extmem and getattr(cache, "_extmem_ready", False))
            ):
                import jax.numpy as jnp

                v = np.asarray(cache.valid)
                lab = np.asarray(cache.labels)[v]
                wts = (None if cache.weights is None
                       else np.asarray(cache.weights)[v])
                if self._process_parallel():
                    # InitEstimation must agree across workers (the reference
                    # allreduces inside FitStump, fit_stump.cc:52); gather the
                    # shards so every process estimates on the global labels
                    from . import collective

                    lab = collective.allgather_ragged(lab)
                    if wts is not None:
                        wts = collective.allgather_ragged(wts)
                bm = np.asarray(
                    self.objective.init_estimation(
                        jnp.asarray(lab),
                        None if wts is None else jnp.asarray(wts),
                    )
                )
            else:
                bm = np.zeros(self.n_groups, np.float32)
            self._base_margin_value = np.broadcast_to(
                np.asarray(bm, np.float32).reshape(-1), (self.n_groups,)
            ).copy()
        if cache.margin is None:
            cache.margin = cache.base_margin_init(self._base_margin_value, self.n_groups)
            cache.n_trees_applied = 0

    def _sync_margin(self, cache: _Cache) -> None:
        """Catch the cached margin up to all committed trees (the prediction
        cache semantics of include/xgboost/cache.h:26) — covers continued
        training via xgb_model= and caches rebuilt mid-train."""
        import jax.numpy as jnp

        if cache.is_extmem:
            cache.ensure_train()
        self._ensure_base_margin(cache)
        if self.booster_kind == "gblinear":
            rounds = getattr(self, "_linear_rounds", 0)
            if self.linear_weights is None or cache.n_trees_applied == rounds > 0:
                if cache.margin is None:
                    cache.margin = cache.base_margin_init(
                        self._base_margin_value, self.n_groups)
                return
            cache.margin = self._linear_margin(cache)
            cache.n_trees_applied = rounds
            return
        if cache.weights_version != getattr(self, "_weights_version", 0):
            # DART rescaled historical trees: rebuild this cache from scratch
            cache.margin = cache.base_margin_init(self._base_margin_value, self.n_groups)
            cache.n_trees_applied = 0
            cache.weights_version = getattr(self, "_weights_version", 0)
        if cache.n_trees_applied < len(self.trees):
            new = slice(cache.n_trees_applied, len(self.trees))
            if cache.is_extmem:
                delta = jnp.asarray(self._predict_extmem(cache.dmat, new))
                cache.margin = cache.margin + delta  # page-padded, aligned
                cache.n_trees_applied = len(self.trees)
                return
            elif self._use_streamed_predict(cache.dmat):
                # large sparse eval/train matrix: never cache a dense copy
                delta = jnp.asarray(self._margin_delta_streamed(cache.dmat, new))
                pad = cache.margin.shape[0] - delta.shape[0]
                if pad:
                    delta = jnp.concatenate(
                        [delta, jnp.zeros((pad, delta.shape[1]), jnp.float32)],
                        axis=0)
                cache.margin = cache.margin + delta
                cache.n_trees_applied = len(self.trees)
                return
            elif (cache.ellpack is not None and self._get_mesh() is None
                  and all(t.split_bins is not None
                          and t.leaf_vector is None
                          for t in self.trees[new])
                  and self._try_rebind_split_bins(new, cache.ellpack.cuts)):
                # binned pages already on device: route through them instead
                # of materializing a second raw f32 copy (the reference's
                # UpdatePredictionCache also reuses the training partition);
                # loaded models without split_bins fall through to raw.
                # Accumulating INTO the existing margin keeps the training
                # loop's f32 addition order: a rebuilt cache is bitwise-
                # identical to the incrementally-updated one, so continued
                # training (xgb_model=) equals one straight run exactly
                cache.margin = self._margin_delta_binned_cache(
                    cache, new, init=cache.margin)
                cache.n_trees_applied = len(self.trees)
                return
            else:
                if cache.raw_X is None:
                    cache.raw_X = jnp.asarray(self.dmat_host_dense(cache), jnp.float32)
                R_raw = cache.raw_X.shape[0]
                m = self._margin_delta_for(cache.raw_X, new,
                                           init=cache.margin[:R_raw])
                if R_raw != cache.margin.shape[0]:
                    m = jnp.concatenate([m, cache.margin[R_raw:]], axis=0)
                cache.margin = m
            cache.n_trees_applied = len(self.trees)

    def dmat_host_dense(self, cache: _Cache) -> np.ndarray:
        return self._host_dense_recoded(cache.dmat)

    def _host_dense_recoded(self, data: DMatrix) -> np.ndarray:
        """Raw matrix with categorical codes remapped onto the TRAINING
        frame's category ordering (encoder/ordinal.h Recode): a frame whose
        pandas categories differ train->inference would otherwise route its
        codes through the wrong split sets silently."""
        from .data.dmatrix import recode_dense

        return recode_dense(data.host_dense(),
                            getattr(self, "_cat_categories", None),
                            getattr(data, "cat_categories", None))

    @property
    def base_score(self) -> np.ndarray:
        self._configure()
        if self._base_margin_value is None:
            return np.full(self.n_groups, 0.5, np.float32)
        return self._base_margin_value

    # ------------------------------------------------------------------ train
    def update(self, dtrain: DMatrix, iteration: int, fobj=None) -> None:
        """One boosting iteration (learner.cc:1108 UpdateOneIter)."""
        import jax.numpy as jnp

        self._configure()
        cache = self._get_cache(dtrain)
        if self.tree_method == "exact" and not cache.is_extmem:
            cache.ensure_train_raw()
        else:
            cache.ensure_train()
        if hasattr(self.objective, "set_bounds"):
            lo = dtrain.info.label_lower_bound
            hi = dtrain.info.label_upper_bound
            if lo is not None:
                self.objective.set_bounds(lo, hi)
        if hasattr(self.objective, "set_group_info"):
            gp = dtrain.info.group_ptr
            # keyed on the DMatrix and a set_group version counter (NOT array
            # id(): the allocator can reuse addresses) so continued training
            # with different query groups rebuilds the layout
            owner = (id(dtrain), getattr(dtrain, "group_version", 0))
            if gp is None:
                gp = np.array([0, dtrain.num_row()], np.int64)
            if getattr(self.objective, "_gidx_owner", None) != owner:
                self.objective.set_group_info(gp)
                self.objective._gidx_owner = owner
        if getattr(dtrain, "cat_categories", None):
            cats = {int(k): list(v) for k, v in dtrain.cat_categories.items()}
            if getattr(self, "_cat_categories", None) is None:
                # remember the training frame's category->code mapping so
                # frames with different orderings recode at inference
                # (reference: src/encoder/ordinal.h:350 Recode)
                self._cat_categories = cats
            elif cats != self._cat_categories:
                # the binned page would be built from the RAW (mismatched)
                # codes while margins are recoded — fail loudly instead of
                # training trees against the wrong code space
                raise ValueError(
                    "continued training requires the training frame's "
                    "category ordering; re-declare the categorical columns "
                    "with the original categories")
        if self.feature_names is None and dtrain.feature_names:
            # inherit the training frame's column names (reference python
            # package: train() carries dtrain.feature_names onto the booster)
            # so dumps, importance and get_categories key by name
            self.feature_names = list(dtrain.feature_names)
        if self.process_type == "update":
            # the update flow keeps its own running margin over the already-
            # updated prefix; the full-model margin/gradient pass below would
            # be recomputed work that is then discarded
            if fobj is not None:
                raise NotImplementedError(
                    "process_type='update' with a custom objective is not "
                    "supported (refresh recomputes gradients internally)")
            self._ensure_base_margin(cache)
            self._update_existing_trees(cache, iteration)
            return
        self._sync_margin(cache)
        drop_idx = self._select_dart_drops(iteration)
        if drop_idx:
            # DART drop round: the gradient must be evaluated on the reduced
            # margin, which _boost_trees builds — skip the full-margin pass
            # so a custom fobj is invoked exactly once
            gpair = None
        else:
            with span("update.gradient"):
                if fobj is not None:
                    # custom objectives receive RAW margins (reference:
                    # Booster.update passes output_margin=True predictions
                    # to fobj, core.py:2277)
                    gpair = self._fobj_gpair(cache, fobj, cache.margin,
                                             dtrain)
                else:
                    gpair = self.objective.get_gradient(
                        cache.margin, cache.labels, cache.weights, iteration
                    )  # (R_pad, K, 2)
        if gpair is not None:
            gpair = gpair * cache.valid[:, None, None]
        from .utils import observer

        if observer.enabled():
            observer.observe_margin(cache.margin, iteration)
            if gpair is not None:
                observer.observe_gradients(gpair, iteration)
        with span("update.update_tree"):
            if self.booster_kind == "gblinear":
                self._boost_linear(cache, gpair)
            else:
                self._boost_trees(cache, gpair, iteration, fobj=fobj,
                                  drop_idx=drop_idx)
        if observer.enabled() and self.trees:
            observer.observe_tree(self.trees[-1], iteration)

    def _fobj_gpair(self, cache, fobj, margin, dmat):
        """Densify a custom objective's (grad, hess) over the padded rows."""
        import jax.numpy as jnp

        valid_np = np.asarray(cache.valid).astype(bool)
        m = np.asarray(margin)[valid_np]
        preds = m[:, 0] if self.n_groups == 1 else m
        grad, hess = fobj(preds, dmat)
        R = int(valid_np.sum())
        grad = np.asarray(grad, np.float32).reshape(R, -1)
        hess = np.asarray(hess, np.float32).reshape(R, -1)
        gp_dense = np.zeros((margin.shape[0], grad.shape[1], 2), np.float32)
        gp_dense[valid_np] = np.stack([grad, hess], axis=-1)
        return jnp.asarray(gp_dense)

    def boost(self, dtrain: DMatrix, grad, hess, iteration: int = 0) -> None:
        """Custom-gradient boost (reference: XGBoosterBoostOneIter)."""
        import jax.numpy as jnp

        self._configure()
        if self.process_type == "update":
            raise NotImplementedError(
                "boost() with raw grad/hess cannot drive process_type="
                "'update' (the refresh updater recomputes gradients per "
                "round); use update() instead")
        if self._select_dart_drops(iteration):
            # this round actually drops trees: gradients would have to be
            # re-evaluated on the reduced margin, impossible with raw values
            raise NotImplementedError(
                "boost() with raw grad/hess cannot honour a DART dropout "
                "round; use update(fobj=...) or set rate_drop=0")
        cache = self._get_cache(dtrain)
        if self.tree_method == "exact" and not cache.is_extmem:
            cache.ensure_train_raw()
        else:
            cache.ensure_train()
        self._sync_margin(cache)
        R = dtrain.num_row()
        g = np.asarray(grad, np.float32).reshape(R, -1)
        h = np.asarray(hess, np.float32).reshape(R, -1)
        valid_np = np.asarray(cache.valid)
        gp_dense = np.zeros((cache.margin.shape[0], g.shape[1], 2), np.float32)
        gp_dense[valid_np] = np.stack([g, h], axis=-1)
        gpair = jnp.asarray(gp_dense)
        gpair = gpair * cache.valid[:, None, None]
        if self.booster_kind == "gblinear":
            self._boost_linear(cache, gpair)
        else:
            self._boost_trees(cache, gpair, iteration)

    def _linear_margin(self, cache: _Cache):
        """Full (padded) margin of the current linear model for a cache."""
        import jax.numpy as jnp

        from .models.gblinear import linear_predict

        if cache.raw_X is None:
            cache.raw_X = jnp.asarray(self._host_dense_recoded(cache.dmat), jnp.float32)
        base = jnp.asarray(self._base_margin_value)[None, :]
        m = linear_predict(cache.raw_X, jnp.asarray(self.linear_weights),
                           jnp.asarray(self.linear_bias)) + base
        pad = (cache.margin.shape[0] if cache.margin is not None else cache.n_padded) - m.shape[0]
        if pad:
            m = jnp.concatenate([m, jnp.zeros((pad, m.shape[1]), jnp.float32)], 0)
        return m

    def _boost_linear(self, cache: _Cache, gpair) -> None:
        """gblinear round (reference: src/gbm/gblinear.cc GBLinear::DoBoost)."""
        import jax.numpy as jnp

        from .models.gblinear import linear_predict, linear_update

        F = cache.dmat.num_col()
        K = gpair.shape[1]
        if self.linear_weights is None:
            self.linear_weights = np.zeros((F, K), np.float32)
            self.linear_bias = np.zeros(K, np.float32)
        if cache.raw_X is None:
            cache.raw_X = jnp.asarray(self._host_dense_recoded(cache.dmat), jnp.float32)
        Xz = jnp.nan_to_num(cache.raw_X, nan=0.0)
        updater = str(self.params.get("updater", "coord_descent"))
        if updater not in ("coord_descent", "shotgun"):
            raise ValueError(
                f"unknown gblinear updater {updater!r}; expected "
                "'coord_descent' or 'shotgun'")
        # reference defaults (coordinate_common.h): shotgun shuffles its
        # visit order every round, coord_descent walks features cyclically
        from .models.gblinear import (SELECTORS, effective_top_k,
                                      linear_update_greedy, selector_order,
                                      thrifty_order)

        selector = str(self.params.get(
            "feature_selector",
            "shuffle" if updater == "shotgun" else "cyclic"))
        if selector not in SELECTORS:
            raise ValueError(
                f"unknown feature_selector {selector!r}; expected one of "
                f"{SELECTORS}")
        top_k = int(self.params.get("top_k", 0) or 0)
        order = None
        if selector not in ("greedy", "thrifty"):
            order = jnp.asarray(selector_order(
                selector, F, getattr(self, "_linear_rounds", 0),
                int(self.params.get("seed", 0))))
        W = jnp.asarray(self.linear_weights)
        b = jnp.asarray(self.linear_bias)
        R = cache.dmat.num_row()
        eta, lam, alpha = (float(self.tparam.eta),
                           float(self.tparam.lambda_),
                           float(self.tparam.alpha))
        for k in range(K):
            if selector == "greedy":
                wk, bk, _ = linear_update_greedy(
                    Xz, gpair[:R, k, :], W[:, k], b[k],
                    steps=effective_top_k(top_k, F), eta=eta, lambda_=lam,
                    alpha=alpha)
            else:
                if selector == "thrifty":
                    # gain-ranked per group from the round-start gradients
                    order = jnp.asarray(thrifty_order(
                        Xz, gpair[:R, k, :], W[:, k], top_k=top_k,
                        alpha=alpha, lambda_=lam))
                wk, bk = linear_update(
                    Xz, gpair[:R, k, :], W[:, k], b[k], order,
                    eta=eta, lambda_=lam, alpha=alpha,
                )
            W = W.at[:, k].set(wk)
            b = b.at[k].set(bk)
        self.linear_weights = np.asarray(W)
        self.linear_bias = np.asarray(b)
        self._linear_rounds = getattr(self, "_linear_rounds", 0) + 1
        cache.margin = self._linear_margin(cache)
        cache.n_trees_applied = self._linear_rounds

    def _resolve_max_depth(self, lossguide: bool) -> int:
        """Default depth cap for the level-synchronous growers when
        max_depth<=0: 10 heap levels under lossguide (static shapes), 6
        depthwise (the reference's default max_depth).  The best-first
        grower resolves 0 as "unbounded" instead and does not use this."""
        md = self.tparam.max_depth
        if md <= 0:
            md = 10 if lossguide else 6
        return md

    def _boost_trees_extmem(self, cache: _Cache, gpair, iteration: int) -> None:
        """Streaming boost over host-resident pages (ExtMemQuantileDMatrix)."""
        from .tree.stream import StreamingHistTreeGrower

        d = cache.dmat
        lossguide = self.tparam.grow_policy == "lossguide"
        max_depth = self._resolve_max_depth(lossguide)
        mesh_ext = self._get_mesh()
        if mesh_ext is not None and 1024 % mesh_ext.devices.size != 0:
            raise ValueError(
                f"external-memory pages are {1024}-row aligned at write time "
                f"(data/extmem.py PAGE_ALIGN); n_devices="
                f"{mesh_ext.devices.size} must divide 1024 for extmem "
                f"training — use a power-of-two device count or in-memory "
                f"DMatrix (which re-aligns to lcm(1024, n_devices))")
        grower = StreamingHistTreeGrower(
            max_depth, self._split_params,
            interaction_sets=self.tparam.interaction_constraints,
            max_leaves=self.tparam.max_leaves, lossguide=lossguide,
            mesh=self._get_mesh(),
            distributed=self._process_parallel(),
            # bench hook: "_extmem_prefetch": 0 serializes page transfer
            # against compute so the prefetch-overlap gain is measurable
            prefetch=str(self.params.get("_extmem_prefetch", "1")).lower()
            in ("1", "true"),
            quantised=self.deterministic_histogram,
            # gradient-based sampling decides page residency: a page whose
            # rows all sampled out is loaded once per tree, not per level
            # ("_extmem_page_skip": 0 keeps every page level-resident — the
            # measurement/parity baseline, tests/test_extmem.py)
            page_skip=(self.tparam.subsample < 1.0
                       and self.tparam.sampling_method == "gradient_based"
                       and str(self.params.get("_extmem_page_skip",
                                               "1")).lower()
                       in ("1", "true")),
        )
        K = gpair.shape[1]
        new_margin = cache.margin
        cat_ft = d.info.feature_types
        cat_mask_np = (np.asarray([t == "c" for t in cat_ft], bool)
                       if cat_ft and "c" in cat_ft else None)
        for p_idx in range(max(self.num_parallel_tree, 1)):
            fmask_fn = self._feature_masks(iteration * 131 + p_idx, p_idx, d.num_col(),
                                           d.info.feature_weights)
            gp_all = self._subsample_mask(gpair, iteration * 131 + p_idx)
            for k in range(K):
                state = grower.grow(
                    d._pages, d.page_offsets(), gp_all[:, k, :], cache.valid,
                    d.cuts_pad, d.n_bins, feature_masks=fmask_fn,
                    cat_mask=cat_mask_np,
                )
                delta = leaf_margin_delta(state.pos, state.leaf_val)
                new_margin = new_margin.at[:, k].add(delta)
                tree = RegTree.from_grown(StreamingHistTreeGrower.to_host(state))
                tree.cuts_token = d._cuts.token
                self.trees.append(tree)
                self.tree_info.append(k)
                self.tree_weights.append(1.0)
        cache.margin = new_margin
        cache.n_trees_applied = len(self.trees)

    def _margin_delta_binned_cache(self, cache: _Cache, tree_slice: slice,
                                   init=None):
        """Margin over the cache's resident binned page (page-padded layout,
        rows align with cache.margin).  With ``init`` the result REPLACES the
        margin (accumulated in training order — bitwise-faithful rebuild)."""
        from .ops.predict import predict_margin_delta_binned

        stacked, groups, depth = self._stacked(tree_slice)
        Bw = cache.ellpack.cuts_pad.shape[1]
        args = (cache.bins, stacked["feat"], stacked["sbin"],
                stacked["dleft"], stacked["left"], stacked["right"],
                stacked["value"], groups)
        if stacked["catm"] is not None:
            args += (stacked["is_cat"], stacked["catm"])
        else:
            args += (None, None)
        return predict_margin_delta_binned(
            *args, init, n_groups=self.n_groups, depth=depth, n_bin=Bw)

    def _predict_extmem(self, data, tree_slice: slice) -> np.ndarray:
        """Batched binned prediction over host pages (no raw data needed)."""
        import jax.numpy as jnp

        from .ops.predict import predict_margin_delta_binned

        self._ensure_split_bins(tree_slice, data)
        stacked, groups, depth = self._stacked(tree_slice)
        Bw = data.cuts_pad.shape[1]
        outs = []
        for i, page in enumerate(data._pages):
            dev = jnp.asarray(np.ascontiguousarray(page))
            if stacked["catm"] is not None:
                m = predict_margin_delta_binned(
                    dev, stacked["feat"], stacked["sbin"], stacked["dleft"],
                    stacked["left"], stacked["right"], stacked["value"], groups,
                    stacked["is_cat"], stacked["catm"],
                    n_groups=self.n_groups, depth=depth, n_bin=Bw)
            else:
                m = predict_margin_delta_binned(
                    dev, stacked["feat"], stacked["sbin"], stacked["dleft"],
                    stacked["left"], stacked["right"], stacked["value"], groups,
                    n_groups=self.n_groups, depth=depth, n_bin=Bw)
            outs.append(np.asarray(m))  # PAGE-PADDED layout (padding rows kept)
        return np.concatenate(outs, axis=0)

    def _try_rebind_split_bins(self, tree_slice: slice, cuts) -> bool:
        """Gate for the binned margin route: True iff every tree's split_bins
        verifiably index THESE cuts.  Trees grown against a different cuts
        object (continued training on a new DMatrix / changed max_bin) are
        re-mapped exactly when possible; unmappable thresholds mean the cuts
        genuinely differ and the caller must take the raw-threshold route."""
        if all(t.cuts_token == cuts.token for t in self.trees[tree_slice]):
            return True
        try:
            self._ensure_split_bins(tree_slice, cuts=cuts)
        except ValueError:
            return False
        return True

    def _ensure_split_bins(self, tree_slice: slice, data=None, *, cuts=None) -> None:
        """Reconstruct split_bins for loaded models (split_bins is internal and
        not serialized): thr == cuts[f][sbin] exactly, so sbin is recoverable
        by an exact searchsorted against this matrix's cuts."""
        if cuts is None:
            cuts = data._cuts
        for t in self.trees[tree_slice]:
            if t.split_bins is not None and t.cuts_token == cuts.token:
                continue
            n = t.n_nodes
            sbin = np.zeros(n, np.int32)
            for nid in range(n):
                if t.left_children[nid] == -1:
                    continue
                if t.split_type is not None and t.split_type[nid] == 1:
                    continue  # categorical routes via the set, sbin unused
                f = int(t.split_indices[nid])
                seg = cuts.feature_cuts(f)
                b = int(np.searchsorted(seg, t.split_conditions[nid], side="left"))
                if b >= len(seg) or seg[b] != t.split_conditions[nid]:
                    raise ValueError(
                        "cannot map split threshold onto this matrix's bin "
                        "cuts; was the model trained with different cuts, or "
                        "with tree_method='exact' (raw-value thresholds)? "
                        "Use an in-memory DMatrix for prediction."
                    )
                sbin[nid] = b
            t.split_bins = sbin
            t.cuts_token = cuts.token

    def _rng(self, iteration: int, tag: int) -> np.random.Generator:
        seed = int(self.params.get("seed", 0))
        return np.random.default_rng((seed * 1_000_003 + iteration * 131 + tag) % (2**63))

    def _feature_masks(self, iteration: int, group: int, n_features: int,
                       feature_weights=None):
        """ColumnSampler (reference: src/common/random.h ColumnSampler):
        each level samples exactly max(1, frac*n_avail) of the surviving
        features without replacement; with ``feature_weights`` set the draw
        is weighted (WeightedSamplingWithoutReplacement — the
        Efraimidis-Spirakis exponential-key method)."""
        tp = self.tparam
        fw = None
        if feature_weights is not None:
            # validate unconditionally (accept-and-ignore is how the silent
            # no-op the reference never had slips back in)
            fw = np.asarray(feature_weights, np.float64).reshape(-1)
            if fw.shape[0] != n_features:
                raise ValueError(
                    f"feature_weights has {fw.shape[0]} entries for "
                    f"{n_features} features")
            if (fw < 0).any():
                raise ValueError("feature_weights must be non-negative")
            if not (fw > 0).any():
                raise ValueError("feature_weights sums to zero")
        if tp.colsample_bytree >= 1.0 and tp.colsample_bylevel >= 1.0 and tp.colsample_bynode >= 1.0:
            return None
        rng = self._rng(iteration, 17 + group)

        def sample(prev_mask, frac):
            if frac >= 1.0:
                return prev_mask
            m2 = np.atleast_2d(prev_mask)
            rows, F = m2.shape
            # exponential keys / weight, k smallest per row = a weighted
            # (uniform when fw is None) draw of k features w/o replacement,
            # vectorized across nodes
            w_row = np.ones(F, np.float64) if fw is None else fw
            with np.errstate(divide="ignore"):
                keys = rng.exponential(size=(rows, F)) / w_row
            keys = np.where(m2 & (w_row > 0), keys, np.inf)
            n_ok = np.isfinite(keys).sum(axis=1)
            if np.any(n_ok == 0):
                raise ValueError(
                    "feature_weights leaves no sampleable feature")
            k = np.minimum(
                np.maximum(1, (frac * m2.sum(axis=1)).astype(np.int64)),
                n_ok)
            order = np.argsort(keys, axis=1, kind="stable")
            ranks = np.empty_like(order)
            np.put_along_axis(
                ranks, order,
                np.broadcast_to(np.arange(F), (rows, F)).copy(), axis=1)
            out = ranks < k[:, None]
            return out if prev_mask.ndim == 2 else out[0]

        tree_mask = sample(np.ones(n_features, bool), tp.colsample_bytree)

        def per_level(depth: int, n_nodes: int):
            import jax.numpy as jnp

            m = sample(tree_mask, tp.colsample_bylevel)
            if tp.colsample_bynode < 1.0:
                mm = np.broadcast_to(m, (n_nodes, n_features)).copy()
                mm = sample(mm, tp.colsample_bynode)
                return jnp.asarray(mm)
            return jnp.asarray(m[None, :])

        return per_level

    def _subsample_mask(self, gpair, iteration: int):
        """Row subsampling: zeroed gpairs drop rows from hist + leaves.

        uniform: Bernoulli(subsample) (reference: src/tree/hist/sampler.cc).
        gradient_based: keep-probability proportional to the gradient norm
        sqrt(g^2 + lambda h^2) with 1/p reweighting so histogram sums stay
        unbiased (reference: src/tree/gpu_hist/sampler.cuh:129-135, the
        Ou 2020 out-of-core sampler).
        """
        import jax
        import jax.numpy as jnp

        if self.tparam.subsample >= 1.0:
            return gpair
        key = jax.random.PRNGKey(
            (int(self.params.get("seed", 0)) * 7919 + iteration) % (2**31)
        )
        if self.tparam.sampling_method == "gradient_based":
            lam = float(self.tparam.lambda_)
            norm = jnp.sqrt(gpair[..., 0] ** 2 + lam * gpair[..., 1] ** 2)
            norm = jnp.max(norm, axis=1)  # (R_pad,) across output groups
            total = jnp.maximum(jnp.sum(norm), 1e-12)
            target = self.tparam.subsample * jnp.sum(norm > 0)
            p = jnp.clip(norm * target / total, 0.0, 1.0)
            keep = jax.random.uniform(key, p.shape) < p
            scale = jnp.where(keep, 1.0 / jnp.maximum(p, 1e-12), 0.0)
            return gpair * scale[:, None, None]
        mask = jax.random.bernoulli(key, self.tparam.subsample, (gpair.shape[0],))
        return gpair * mask[:, None, None]

    def _process_parallel(self) -> bool:
        """True when training spans multiple processes (jax.distributed):
        each process holds a row shard and histograms cross processes via the
        host collective (the reference's rabit/NCCL role)."""
        from . import collective

        return collective.is_distributed()

    def _get_mesh(self):
        if self.n_devices == 1:
            return None
        if self._mesh is None:
            import jax

            from .parallel import make_mesh

            n = (self.n_devices if self.n_devices > 0
                 else jax.local_device_count())
            if n <= 1:
                return None
            self._mesh = make_mesh(n)
        return self._mesh

    def _boost_trees_exact_loop(self, cache: _Cache, gpair, iteration: int,
                                fobj, drop_idx) -> None:
        """The tree_method='exact' boosting round: host colmaker growth,
        reusing the DART / parallel-forest / column-sample machinery of the
        hist path without its sketch/Ellpack/jitted-grower startup."""
        drop_margin = None
        if drop_idx:
            gpair, drop_margin = self._dart_gpair(cache, drop_idx, fobj,
                                                  iteration)
        K = gpair.shape[1]
        if self.multi_strategy == "multi_output_tree" and K > 1:
            raise NotImplementedError(
                "tree_method='exact' with multi_output_tree is not "
                "supported yet")
        new_margin = cache.margin
        n_new = 0
        n_features = cache.dmat.num_col()
        for p_idx in range(max(self.num_parallel_tree, 1)):
            fmask_fn = self._feature_masks(iteration * 131 + p_idx, p_idx,
                                           n_features,
                                           cache.dmat.info.feature_weights)
            gp = self._subsample_mask(gpair, iteration * 131 + p_idx)
            for k in range(K):
                tree, delta = self._grow_exact_one(cache, gp, k, fmask_fn,
                                                   new_margin)
                new_margin = new_margin.at[:, k].add(delta)
                self.trees.append(tree)
                self.tree_info.append(k)
                self.tree_weights.append(1.0)
                n_new += 1
        if drop_idx:
            new_margin = self._dart_commit(cache, new_margin, n_new,
                                           drop_idx, drop_margin)
        cache.margin = new_margin
        cache.n_trees_applied = len(self.trees)

    def _grow_exact_one(self, cache: _Cache, gp, k: int, fmask_fn,
                        new_margin=None):
        """One tree_method="exact" round: host greedy enumeration over raw
        values (updater_colmaker.cc ColMaker) chained with the pruner the
        way the reference chains "grow_colmaker,prune"; returns
        (RegTree, margin delta padded to the cache layout)."""
        from .models.updaters import prune_tree
        from .tree.exact import grow_exact

        tp = self.tparam
        proc = self._process_parallel()
        if self._get_mesh() is not None:
            raise NotImplementedError(
                "tree_method='exact' is host-side greedy enumeration; an "
                "in-process device mesh gives it nothing — use hist")
        if cache.dmat.cat_mask() is not None and np.any(cache.dmat.cat_mask()):
            raise NotImplementedError(
                "tree_method='exact' does not support categorical features "
                "(same as the reference updater)")
        if tp.monotone_constraints is not None or tp.interaction_constraints:
            raise NotImplementedError(
                "constraints are not supported with tree_method='exact'; "
                "use hist or approx")
        if tp.grow_policy == "lossguide":
            raise ValueError("tree_method='exact' only supports depthwise "
                             "growth (driver.h lossguide needs hist/approx)")
        # X and its column argsort are round-invariant: cache both (the
        # colmaker builds its SortedCSC once per Update too); reuse the DART
        # path's device copy rather than recoding a second host copy
        if getattr(cache, "exact_X", None) is None:
            X_local = (np.asarray(cache.raw_X)
                       if cache.raw_X is not None
                       else self._host_dense_recoded(cache.dmat))
            if proc:
                # distributed exact, the updater_sync.cc pattern: every rank
                # sees the FULL row set (exact is a small-data method — the
                # reference steers big data to hist), trees are grown from
                # identical inputs and rank 0's copy is broadcast so the
                # model is bitwise-identical everywhere
                from . import collective

                sizes = collective.allgather(
                    np.asarray([X_local.shape[0]], np.int64))[:, 0]
                cache.exact_row_start = int(
                    sizes[: collective.get_rank()].sum())
                cache.exact_n_local = int(X_local.shape[0])
                cache.exact_X = collective.allgather_ragged(X_local)
            else:
                cache.exact_X = X_local
            cache.exact_order = np.argsort(cache.exact_X, axis=0,
                                           kind="stable").astype(np.int32)
        X = cache.exact_X
        R = X.shape[0]
        R_local = getattr(cache, "exact_n_local", R)
        row_start = getattr(cache, "exact_row_start", 0)

        def gather_rows(a: np.ndarray) -> np.ndarray:
            if not proc:
                return a
            from . import collective

            return collective.allgather_ragged(np.asarray(a))

        gh = np.asarray(
            gather_rows(np.asarray(gp[:R_local, k, :], np.float64)),
            np.float64)
        tree, pos = grow_exact(
            X, gh[:, 0], gh[:, 1],
            max_depth=int(tp.max_depth), max_leaves=int(tp.max_leaves),
            lambda_=float(tp.lambda_), alpha=float(tp.alpha),
            min_child_weight=float(tp.min_child_weight),
            max_delta_step=float(tp.max_delta_step),
            eta=float(tp.eta), feature_masks=fmask_fn,
            col_order=cache.exact_order,
        )
        tree, n_pruned = prune_tree(tree, gamma=float(tp.gamma),
                                    eta=float(tp.eta))
        if n_pruned:
            # node ids changed: re-route rows through the pruned tree
            from .models.updaters import _route_masks

            masks = _route_masks(tree, X)
            leaf_ids = np.nonzero(tree.left_children == -1)[0]
            pos = np.zeros(R, np.int32)
            for nid in leaf_ids:
                pos[masks[nid]] = nid
        if (hasattr(self.objective, "adaptive_leaf")
                and self.objective.adaptive_leaf()):
            # ObjFunction::UpdateTreeLeaf (src/objective/adaptive.cc):
            # refit each leaf to the weighted alpha-quantile of residuals
            # (against the RUNNING margin so num_parallel_tree>1 members
            # see earlier members' contributions, like the hist path)
            if getattr(cache, "exact_adaptive_meta", None) is None:
                # labels/valid/weights are round-invariant: gather once
                cache.exact_adaptive_meta = (
                    gather_rows(np.asarray(cache.labels)[:R_local]),
                    gather_rows(
                        np.asarray(cache.valid)[:R_local]).astype(bool),
                    (gather_rows(np.asarray(cache.weights)[:R_local])
                     if cache.weights is not None else None),
                )
            labels, valid, w = cache.exact_adaptive_meta
            margin_src = cache.margin if new_margin is None else new_margin
            margin_k = gather_rows(np.asarray(margin_src)[:R_local, k])
            residual = labels - margin_k
            alpha_q = float(self.objective.adaptive_alpha(k))
            for nid in np.nonzero(tree.left_children == -1)[0]:
                m = (pos == nid) & valid
                if not np.any(m):
                    continue
                res = residual[m]
                if w is None:
                    q = np.quantile(res, alpha_q)
                else:
                    srt = np.argsort(res)
                    cw = np.cumsum(w[m][srt])
                    q = res[srt][np.searchsorted(cw, alpha_q * cw[-1])]
                tree.split_conditions[nid] = np.float32(float(tp.eta) * q)
        if proc:
            # sync role (updater_sync.cc TreeSyncher): rank 0's tree is
            # authoritative — identical by construction, broadcast makes it
            # bitwise-guaranteed
            from . import collective
            from .models.tree import RegTree

            tree = RegTree.from_json_dict(
                collective.broadcast(tree.to_json_dict(0, 0), 0))
        delta = np.zeros(cache.margin.shape[0], np.float32)
        delta[:R_local] = tree.split_conditions[pos][
            row_start:row_start + R_local]
        return tree, delta

    def _boost_multi_target(self, cache: _Cache, gpair, iteration: int,
                            K: int, scalar_grower, cat_mask_np) -> None:
        """One vector-leaf tree per round: 2K-channel histogram, summed-gain
        splits, K-vector leaves (multi_target_tree_model.h,
        multi_evaluate_splits.cu)."""
        from .tree.grow_multi import (MultiTargetTreeGrower,
                                      leaf_margin_delta_multi)

        if self.booster_kind == "dart":
            raise NotImplementedError(
                "multi_strategy='multi_output_tree' with DART is not supported")
        if self.deterministic_histogram:
            raise NotImplementedError(
                "deterministic_histogram is not supported with "
                "multi_output_tree yet")
        if cat_mask_np is not None and np.any(cat_mask_np):
            raise NotImplementedError(
                "multi_output_tree with categorical features is not supported "
                "yet (same restriction as early reference versions)")
        mono = self.tparam.monotone_constraints
        if mono is not None and any(c != 0 for c in mono):
            raise NotImplementedError(
                "multi_output_tree with monotone constraints is not supported")
        mesh = self._get_mesh()
        proc_par = self._process_parallel()
        if mesh is not None and proc_par:
            raise NotImplementedError(
                "n_devices > 1 within a process is not combined with "
                "multi-process multi-target training yet")
        lossguide = self.tparam.grow_policy == "lossguide"
        # level-synchronous growth only here (no best-first node table), so
        # resolve the depth cap locally — the scalar grower may be a
        # BestFirstGrower whose max_depth of 0 means "unbounded"
        max_depth = self._resolve_max_depth(lossguide)
        ell = cache.ellpack
        mkey = ("multi", max_depth, self._split_params, K,
                id(mesh), proc_par, lossguide, self.tparam.max_leaves)
        grower = self._grower_cache.get(mkey)
        if grower is None:
            if mesh is not None:
                from .parallel.grower import ShardedMultiTargetGrower

                grower = ShardedMultiTargetGrower(
                    max_depth, self._split_params, K, mesh,
                    max_leaves=self.tparam.max_leaves, lossguide=lossguide)
            else:
                grower = MultiTargetTreeGrower(
                    max_depth, self._split_params, K,
                    max_leaves=self.tparam.max_leaves, lossguide=lossguide,
                    distributed=proc_par)
            self._grower_cache[mkey] = grower
        new_margin = cache.margin
        for p_idx in range(max(self.num_parallel_tree, 1)):
            fmask_fn = self._feature_masks(iteration * 131 + p_idx, p_idx,
                                           ell.n_features,
                                           cache.dmat.info.feature_weights)
            gp = self._subsample_mask(gpair, iteration * 131 + p_idx)
            state = grower.grow(cache.bins, gp, cache.valid, ell.cuts_pad,
                                ell.n_bins, feature_masks=fmask_fn)
            delta = leaf_margin_delta_multi(state.pos, state.leaf_val)
            new_margin = new_margin + delta
            tree = RegTree.from_grown_multi(
                MultiTargetTreeGrower.to_host(state), K)
            tree.cuts_token = ell.cuts.token
            self.trees.append(tree)
            self.tree_info.append(0)
            self.tree_weights.append(1.0)
        cache.margin = new_margin
        cache.n_trees_applied = len(self.trees)

    def _update_existing_trees(self, cache: _Cache, iteration: int) -> None:
        """process_type=update: run the non-growing updater sequence over
        one boosting round's worth of existing trees (gbtree.cc DoBoost with
        process_type=kUpdate; updaters prune/refresh/sync).

        Boosting semantics match the reference: round i's gradients come
        from a margin holding only the already-UPDATED trees 0..i-1 — the
        not-yet-updated tail of the old model is excluded, exactly as in
        ordinary boosting."""
        import jax.numpy as jnp

        from .models.updaters import prune_tree, refresh_tree, sync_trees

        if not self.updater_seq:
            raise ValueError(
                "process_type='update' requires updater=..., e.g. "
                "updater='refresh,prune'")
        bad = set(self.updater_seq) - {"prune", "refresh", "sync"}
        if bad:
            raise ValueError(f"unsupported updater(s) for process_type="
                             f"'update': {sorted(bad)}")
        tpr = self.trees_per_round
        start = iteration * tpr
        if start >= len(self.trees):
            raise ValueError(
                f"process_type='update' round {iteration} exceeds the "
                f"model's {len(self.trees) // tpr} boosted rounds")
        if cache.raw_X is None:
            cache.raw_X = jnp.asarray(self._host_dense_recoded(cache.dmat),
                                      jnp.float32)
        if getattr(cache, "_upd_margin_round", None) != iteration:
            # (re)build the margin of the already-updated prefix — correct
            # for a fresh cache at any starting round, not just round 0
            margin = cache.base_margin_init(self._base_margin_value,
                                            self.n_groups)
            if start > 0:
                delta = self._margin_for_trees(cache.raw_X,
                                               list(range(0, start)))
                pad = margin.shape[0] - delta.shape[0]
                if pad:
                    delta = jnp.concatenate(
                        [delta, jnp.zeros((pad, delta.shape[1]), jnp.float32)],
                        axis=0)
                margin = margin + delta
            cache._upd_margin = margin
        gpair = self.objective.get_gradient(
            cache._upd_margin, cache.labels, cache.weights, iteration
        ) * cache.valid[:, None, None]
        gp = np.asarray(gpair)
        valid = np.asarray(cache.valid).astype(bool)
        X = np.asarray(cache.raw_X)
        reduce = None
        if self._process_parallel():
            from . import collective

            reduce = collective.allreduce
        for tid in range(start, min(start + tpr, len(self.trees))):
            k = self.tree_info[tid]
            tree = self.trees[tid]
            for upd in self.updater_seq:
                if upd == "refresh":
                    tree = refresh_tree(
                        tree, X, gp[valid, k, 0], gp[valid, k, 1],
                        eta=float(self.tparam.eta),
                        lambda_=float(self.tparam.lambda_),
                        alpha=float(self.tparam.alpha),
                        refresh_leaf=self.refresh_leaf,
                        reduce=reduce)
                elif upd == "prune":
                    tree, _ = prune_tree(
                        tree, gamma=float(self.tparam.gamma),
                        eta=float(self.tparam.eta),
                        max_depth=max(int(self.tparam.max_depth), 0))
            self.trees[tid] = tree
        if "sync" in self.updater_seq:
            self.trees, self.tree_info, self.tree_weights = sync_trees(
                self.trees, self.tree_info, self.tree_weights)
        # advance the running margin by this round's UPDATED trees
        delta = self._margin_for_trees(
            cache.raw_X, list(range(start, min(start + tpr, len(self.trees)))))
        pad = cache._upd_margin.shape[0] - delta.shape[0]
        if pad:
            delta = jnp.concatenate(
                [delta, jnp.zeros((pad, delta.shape[1]), jnp.float32)], axis=0)
        cache._upd_margin = cache._upd_margin + delta
        cache._upd_margin_round = iteration + 1
        # structure/values changed: every cached margin must rebuild (the
        # weights_version mismatch makes _sync_margin start from scratch)
        self._weights_version = getattr(self, "_weights_version", 0) + 1

    def _select_dart_drops(self, iteration: int) -> List[int]:
        """Draw the round's dropped-tree set (gbtree.cc Dart::DropTrees).
        Deterministic per iteration; empty when dropout does not fire."""
        if not (self.booster_kind == "dart" and self.trees
                and self.rate_drop > 0.0):
            return []
        rng = self._rng(iteration, 97)
        if rng.random() < self.skip_drop:
            return []
        n = len(self.trees)
        if self.sample_type == "weighted":
            wts = np.asarray(self.tree_weights, np.float64)
            prob = wts / max(wts.sum(), 1e-16)
            k_drop = int(rng.binomial(n, self.rate_drop))
            if k_drop == 0 and self.one_drop:
                k_drop = 1
            if k_drop == 0:
                return []
            return list(rng.choice(n, size=min(k_drop, n), replace=False,
                                   p=prob))
        mask = rng.random(n) < self.rate_drop
        drop_idx = list(np.nonzero(mask)[0])
        if not drop_idx and self.one_drop:
            drop_idx = [int(rng.integers(0, n))]
        return drop_idx

    def _boost_trees(self, cache: _Cache, gpair, iteration: int,
                     fobj=None, drop_idx=()) -> None:
        """gpair may be None when drop_idx is non-empty (DART round): the
        gradient is then computed here, on the dropout-reduced margin."""
        import jax.numpy as jnp

        if cache.is_extmem:
            if self.tree_method == "exact":
                raise NotImplementedError(
                    "tree_method='exact' needs raw in-memory values; it is "
                    "not supported with ExtMemQuantileDMatrix (the reference "
                    "restricts exact to SimpleDMatrix too)")
            if self.booster_kind == "dart":
                raise ValueError("booster='dart' is not supported with "
                                 "ExtMemQuantileDMatrix yet")
            # process-DP x chip-DP composes here too: pages GSPMD-shard
            # over the local mesh inside _page_step and the level histogram
            # crosses processes via the host allreduce (the same layering
            # as ProcessHistTreeGrower; exact under deterministic_histogram)
            return self._boost_trees_extmem(cache, gpair, iteration)
        exact = self.tree_method == "exact"
        if exact and self.deterministic_histogram:
            raise NotImplementedError(
                "deterministic_histogram applies to histogram growers; "
                "tree_method='exact' has no histogram")
        if exact:
            # the exact branch walks raw host values: no sketch, no Ellpack,
            # no jitted grower — building them here would be pure waste
            if self.tparam.max_depth <= 0 and self.tparam.max_leaves <= 0:
                raise ValueError(
                    "tree_method='exact' with max_depth=0 needs a positive "
                    "max_leaves to bound the tree")
            self._boost_trees_exact_loop(cache, gpair, iteration, fobj,
                                         drop_idx)
            return
        ell = cache.ellpack
        mono = self.tparam.monotone_constraints
        if mono is not None and len(mono) != ell.n_features:
            raise ValueError(
                f"monotone_constraints has {len(mono)} entries but data has "
                f"{ell.n_features} features"
            )
        lossguide = self.tparam.grow_policy == "lossguide"
        mesh = self._get_mesh()
        proc_par = self._process_parallel()
        # true global best-first for lossguide with a leaf budget (driver.h
        # priority queue): unbounded depth, node-table layout — under mesh
        # sharding (GSPMD hist psum) and process parallelism (host
        # AllReduceHist per expansion) alike, so distributed lossguide grows
        # the same trees as single-device
        best_first = lossguide and self.tparam.max_leaves > 1
        max_depth = self.tparam.max_depth
        if max_depth <= 0:
            # best-first: depth bounded only by the leaf budget
            max_depth = 0 if best_first else self._resolve_max_depth(lossguide)
        det = self.deterministic_histogram
        gkey = (max_depth, id(mesh), self._split_params,
                self.tparam.interaction_constraints, self.tparam.max_leaves,
                lossguide, str(self.params.get("_hist_impl", "xla")), proc_par,
                best_first, det)
        if not hasattr(self, "_grower_cache"):
            self._grower_cache = {}
        grower = self._grower_cache.get(gkey)
        if grower is None:
            if best_first:
                from .tree.bestfirst import BestFirstGrower

                if det:
                    raise NotImplementedError(
                        "deterministic_histogram is not supported with the "
                        "best-first (lossguide + max_leaves) grower yet")
                if proc_par and mesh is not None:
                    raise NotImplementedError(
                        "n_devices > 1 within a process is not combined "
                        "with multi-process training yet; give each process "
                        "one device")
                grower = BestFirstGrower(
                    max_depth,
                    self._split_params,
                    max_leaves=self.tparam.max_leaves,
                    interaction_sets=self.tparam.interaction_constraints,
                    distributed=proc_par,
                    mesh=mesh,
                )
            elif proc_par:
                from .parallel.process import ProcessHistTreeGrower

                # mesh may be non-None here: process-DP x chip-DP — each
                # process shards its rows over its LOCAL chips (GSPMD psum)
                # and histograms cross processes via the host collective
                # (rabit x NCCL layering, src/collective/comm.cuh:51)
                grower = ProcessHistTreeGrower(
                    max_depth,
                    self._split_params,
                    interaction_sets=self.tparam.interaction_constraints,
                    max_leaves=self.tparam.max_leaves,
                    lossguide=lossguide,
                    mesh=mesh,
                    quantised=det,
                )
            elif mesh is not None:
                from .parallel import ShardedHistTreeGrower

                # cached: ShardedHistTreeGrower wraps fresh shard_map jits, so
                # rebuilding per round would recompile every level program
                grower = ShardedHistTreeGrower(
                    max_depth,
                    self._split_params,
                    mesh,
                    hist_impl=str(self.params.get("_hist_impl", "xla")),
                    interaction_sets=self.tparam.interaction_constraints,
                    max_leaves=self.tparam.max_leaves,
                    lossguide=lossguide,
                    quantised=det,
                )
            else:
                grower = HistTreeGrower(
                    max_depth,
                    self._split_params,
                    hist_impl=str(self.params.get("_hist_impl", "xla")),
                    interaction_sets=self.tparam.interaction_constraints,
                    max_leaves=self.tparam.max_leaves,
                    lossguide=lossguide,
                    quantised=det,
                )
            self._grower_cache[gkey] = grower
        adaptive = (
            hasattr(self.objective, "adaptive_leaf") and self.objective.adaptive_leaf()
        )

        # ---- DART dropout (reference: gbtree.cc Dart::DoBoost + DropTrees) ----
        drop_margin = None
        if drop_idx:
            gpair, drop_margin = self._dart_gpair(cache, drop_idx, fobj,
                                                  iteration)

        K = gpair.shape[1]
        new_margin = cache.margin
        n_new = 0
        cat_mask_np = cache.dmat.cat_mask()
        if self.multi_strategy == "multi_output_tree" and K > 1:
            if self.tree_method in ("approx", "exact"):
                raise NotImplementedError(
                    f"tree_method={self.tree_method!r} with multi_output_tree "
                    "is not supported yet")
            return self._boost_multi_target(cache, gpair, iteration, K,
                                            grower, cat_mask_np)
        bins_use, cuts_use, nbins_use = cache.bins, ell.cuts_pad, ell.n_bins
        cuts_token_use = ell.cuts.token
        if self.tree_method == "approx":
            # grow_histmaker (updater_approx.cc): fresh hessian-weighted
            # sketch every iteration, then the same hist machinery; cut
            # width pinned to max_bin so the jitted level programs are
            # shared across rounds
            from .data.ellpack import build_ellpack
            from .data.quantile import sketch_dense, sketch_distributed

            valid_np = np.asarray(cache.valid).astype(bool)
            hess_w = np.asarray(gpair)[..., 1].sum(axis=1)[valid_np]
            Xh = self._host_dense_recoded(cache.dmat)
            if self._process_parallel():
                # per-shard grids must merge or workers bin against
                # different value ranges (quantile.cc AllreduceV role)
                cuts = sketch_distributed(Xh, self.tparam.max_bin,
                                          weights=hess_w.astype(np.float64),
                                          cat_mask=cache.dmat.cat_mask())
            else:
                cuts = sketch_dense(Xh, self.tparam.max_bin,
                                    weights=hess_w.astype(np.float64),
                                    use_device=False,
                                    cat_mask=cache.dmat.cat_mask())
            # must pad exactly like the resident cache page (lcm alignment
            # for arbitrary device counts — see _Cache.ensure)
            import math

            mesh_a = self._get_mesh()
            align_a = 1024 if mesh_a is None else math.lcm(
                1024, mesh_a.devices.size)
            ell_iter = build_ellpack(Xh, cuts, row_align=align_a)
            if ell_iter.n_padded != cache.bins.shape[0]:
                raise AssertionError("approx page padding mismatch")
            bins_use = jnp.asarray(ell_iter.bins)
            cuts_use = jnp.asarray(cuts.padded(self.tparam.max_bin))
            nbins_use = jnp.asarray(cuts.n_bins_array())
            # these trees' split_bins index the per-iteration sketch, NOT the
            # resident ellpack: stamping the ellpack's token would falsely
            # certify the binned cached-margin route
            cuts_token_use = cuts.token
            if self._get_mesh() is not None:
                from .parallel import shard_rows

                (bins_use,) = shard_rows(self._get_mesh(), bins_use)
        # Lockstep class batching (opt-in, _lockstep=1): the K independent
        # per-class trees of a round advance level-by-level together in ONE
        # jitted program per level, sharing the split scan and position
        # rewrite (the reference's all-targets-per-pass shape,
        # src/tree/hist/histogram.h:44).  Bitwise-identical trees to the
        # sequential loop (tests/test_lockstep.py).  Default OFF: on the
        # CPU backend the K-stacked level intermediates measured ~1.5x
        # SLOWER than the sequential padded-level grower at covertype
        # shapes; the batched formulation is aimed at the TPU matmul path,
        # where the class axis widens the MXU output tile — to be
        # re-evaluated on hardware.
        lockstep_ok = (
            K > 1 and mesh is None and not proc_par and not best_first
            and not det and cat_mask_np is None and not adaptive
            and str(self.params.get("_hist_impl", "xla")) == "xla"
            and str(self.params.get("_lockstep", "0")).lower()
            in ("1", "true"))
        for p_idx in range(max(self.num_parallel_tree, 1)):
            fmask_fn = self._feature_masks(iteration * 131 + p_idx, p_idx, ell.n_features,
                                           cache.dmat.info.feature_weights)
            # one independent subsample per parallel tree (reference: each
            # member of the forest draws its own rows)
            gp = self._subsample_mask(gpair, iteration * 131 + p_idx)
            if lockstep_ok and fmask_fn is None:
                from .tree.grow_lockstep import (LockstepHistGrower,
                                                 leaf_margin_delta_k)

                lk_key = ("lockstep", max_depth, self._split_params,
                          self.tparam.interaction_constraints,
                          self.tparam.max_leaves, lossguide)
                lk = self._grower_cache.get(lk_key)
                if lk is None:
                    lk = LockstepHistGrower(
                        max_depth, self._split_params,
                        interaction_sets=self.tparam.interaction_constraints,
                        max_leaves=self.tparam.max_leaves,
                        lossguide=lossguide)
                    self._grower_cache[lk_key] = lk
                state = lk.grow(bins_use, gp, cache.valid, cuts_use,
                                nbins_use)
                new_margin = new_margin + leaf_margin_delta_k(
                    state.pos, state.leaf_val).T
                for k in range(K):
                    tree = RegTree.from_grown(lk.to_host_class(state, k))
                    tree.cuts_token = cuts_token_use
                    self.trees.append(tree)
                    self.tree_info.append(k)
                    self.tree_weights.append(1.0)
                    n_new += 1
                continue
            for k in range(K):
                state = grower.grow(
                    bins_use,
                    gp[:, k, :],
                    cache.valid,
                    cuts_use,
                    nbins_use,
                    feature_masks=fmask_fn,
                    cat_mask=cat_mask_np,
                )
                pos = state.pos
                if best_first:
                    tree, leaf_val = grower.to_regtree(state, cuts_use)
                else:
                    tree = None
                    leaf_val = state.leaf_val
                if adaptive:
                    if best_first:
                        is_leaf = jnp.zeros(grower.n_slots, bool).at[
                            : tree.n_nodes].set(
                                jnp.asarray(tree.left_children == -1))
                        n_slots = grower.n_slots
                    else:
                        is_leaf, n_slots = state.is_leaf, grower.max_nodes
                    # exact quantile leaves (ObjFunction::UpdateTreeLeaf,
                    # src/objective/adaptive.cc)
                    from .ops.adaptive import segment_quantile_leaf

                    residual = cache.labels - new_margin[:, k]
                    q_pos, q_res, q_valid = pos, residual, cache.valid
                    if proc_par:
                        # the quantile must see the GLOBAL leaf population
                        # or ranks refit different leaf values from their
                        # local shards (adaptive.cc runs under the
                        # collective); gather like the exact path does
                        from . import collective

                        q_pos = jnp.asarray(collective.allgather_ragged(
                            np.asarray(pos)))
                        q_res = jnp.asarray(collective.allgather_ragged(
                            np.asarray(residual)))
                        q_valid = jnp.asarray(collective.allgather_ragged(
                            np.asarray(cache.valid)))
                    leaf_val = segment_quantile_leaf(
                        q_pos, q_res, q_valid, is_leaf,
                        float(self.objective.adaptive_alpha(k)),
                        float(self.tparam.eta), max_nodes=n_slots,
                    )
                    if best_first:
                        lv = np.asarray(leaf_val)[: tree.n_nodes]
                        lm = tree.left_children == -1
                        tree.split_conditions[lm] = lv[lm]
                    else:
                        state = state._replace(leaf_val=leaf_val)
                delta = leaf_margin_delta(pos, leaf_val)
                new_margin = new_margin.at[:, k].add(delta)
                if tree is None:
                    tree = RegTree.from_grown(HistTreeGrower.to_host(state))
                tree.cuts_token = cuts_token_use
                self.trees.append(tree)
                self.tree_info.append(k)
                self.tree_weights.append(1.0)
                n_new += 1

        if drop_idx:
            new_margin = self._dart_commit(cache, new_margin, n_new,
                                           drop_idx, drop_margin)

        cache.margin = new_margin
        cache.n_trees_applied = len(self.trees)

    def _dart_gpair(self, cache: _Cache, drop_idx, fobj, iteration: int):
        """Gradients for a DART drop round, computed on the margin WITHOUT
        the dropped trees (gbtree.cc Dart::DoBoost; the caller skipped its
        own gradient pass so a custom fobj runs exactly once per round)."""
        import jax.numpy as jnp

        if cache.raw_X is None:
            cache.raw_X = jnp.asarray(self._host_dense_recoded(cache.dmat),
                                      jnp.float32)
        drop_margin = self._margin_for_trees(cache.raw_X, drop_idx)
        pad = cache.margin.shape[0] - drop_margin.shape[0]
        if pad:
            drop_margin = jnp.concatenate(
                [drop_margin,
                 jnp.zeros((pad, drop_margin.shape[1]), jnp.float32)],
                axis=0,
            )
        reduced = cache.margin - drop_margin
        if fobj is not None:
            # custom objective: invoke on the reduced RAW margin (advisor
            # round-1: silently falling back to the built-in objective
            # trained the drop round on the wrong loss)
            gpair = self._fobj_gpair(cache, fobj, reduced, cache.dmat)
        else:
            gpair = self.objective.get_gradient(
                reduced, cache.labels, cache.weights, iteration
            )
        return gpair * cache.valid[:, None, None], drop_margin

    def _dart_commit(self, cache: _Cache, new_margin, n_new: int, drop_idx,
                     drop_margin):
        """DART post-round rescale (Dart::NormalizeTrees): with k dropped and
        lr=eta — 'tree': new *= 1/(k+lr), dropped *= k/(k+lr); 'forest':
        both /(1+lr).  Returns the rebuilt margin."""
        k_d = len(drop_idx)
        lr = float(self.tparam.eta)
        if self.normalize_type == "forest":
            new_w = 1.0 / (1.0 + lr)
            factor = 1.0 / (1.0 + lr)
        else:
            new_w = 1.0 / (k_d + lr)
            factor = k_d / (k_d + lr)
        for t in range(len(self.trees) - n_new, len(self.trees)):
            self.tree_weights[t] = new_w
        for t in drop_idx:
            self.tree_weights[t] *= factor
        # margin: dropped trees shrank by `factor`, new trees contribute
        # scaled by new_w; rebuild incrementally
        new_contrib = new_margin - cache.margin  # unscaled new trees
        new_margin = (
            cache.margin
            - (1.0 - factor) * drop_margin
            + new_w * new_contrib
        )
        self._weights_version = getattr(self, "_weights_version", 0) + 1
        cache.weights_version = self._weights_version
        return new_margin

    # ------------------------------------------------------------------ eval
    def eval_set(self, evals: Sequence[Tuple[DMatrix, str]], iteration: int = 0,
                 feval=None, output_margin: bool = True) -> str:
        """(reference: learner.cc:1159 EvalOneIter)"""
        self._configure()
        msgs = [f"[{iteration}]"]
        metrics = self._eval_metric_list()
        proc_par = self._process_parallel()
        for dmat, name in evals:
            with span("eval.predict"):
                margin = self._eval_margin(dmat)
            preds = np.asarray(self.objective.pred_transform(margin))
            if self.n_groups == 1:
                preds = preds[:, 0]
            labels = dmat.get_label()
            weights = dmat.get_weight()
            mkw = dict(group_ptr=dmat.info.group_ptr)
            if dmat.info.label_lower_bound is not None:
                mkw["y_lower"] = dmat.info.label_lower_bound
                ub = dmat.info.label_upper_bound
                mkw["y_upper"] = (np.full_like(mkw["y_lower"], np.inf)
                                  if ub is None else ub)
            if hasattr(self.objective, "dist"):
                mkw["dist"] = self.objective.dist
                mkw["sigma"] = self.objective.sigma
            if "huber_slope" in self.params:
                mkw["slope"] = float(self.params["huber_slope"])
            if hasattr(self.objective, "_alphas") and self.n_groups > 1:
                mkw["alphas"] = self.objective._alphas()
            for fn, mname in metrics:
                kw = dict(mkw)
                lab = labels
                if "alphas" in kw:
                    import inspect

                    base_fn = getattr(fn, "__wrapped__", fn)
                    if "alphas" not in inspect.signature(base_fn).parameters:
                        # generic elementwise metric on a multi-alpha model:
                        # tile labels so (R, Q) preds broadcast per level
                        kw.pop("alphas")
                        if np.ndim(preds) == 2 and np.ndim(lab) == 1:
                            lab = np.repeat(np.asarray(lab)[:, None],
                                            preds.shape[1], axis=1)
                if proc_par:
                    # distributed eval: every rank reports the GLOBAL metric
                    # via per-metric partial-sum allreduce (the reference's
                    # aggregator.h GlobalSum/GlobalRatio design) — O(local)
                    # memory per rank, early stopping stays in lockstep
                    from .metric import distributed_reduction

                    with distributed_reduction():
                        v = fn(preds, lab, weights, **kw)
                else:
                    v = fn(preds, lab, weights, **kw)
                msgs.append(f"{name}-{mname}:{v:g}")
            if feval is not None:
                res = feval(margin if output_margin else preds, dmat)
                res = [res] if isinstance(res, tuple) else res
                for mname, v in res:
                    # under process parallelism feval sees only the local
                    # shard while built-in metrics reduce globally; average
                    # it across ranks so eval logs (and early stopping keyed
                    # to it) stay in lockstep (ADVICE r3)
                    if proc_par:
                        from . import collective

                        num, den = collective.global_sum(
                            np.array([float(v), 1.0], np.float64))
                        v = num / den
                    msgs.append(f"{name}-{mname}:{v:g}")
        return "\t".join(msgs)

    def _eval_metric_list(self):
        self._configure()
        names = self.params.get("eval_metric", None)
        if names is None:
            if str(self.params.get("disable_default_eval_metric", "0")).lower() in ("1", "true"):
                return []
            names = [self.objective.default_metric()]
        elif isinstance(names, str):
            names = [names]
        return [create_metric(n) for n in names]

    def _eval_margin(self, dmat: DMatrix) -> np.ndarray:
        """Margin for an eval/predict DMatrix using the incremental cache."""
        import jax.numpy as jnp

        cache = self._get_cache(dmat)
        self._sync_margin(cache)
        if cache.is_extmem:
            cache.ensure_train()
            return np.asarray(cache.margin)[np.asarray(cache.valid)]
        R = dmat.num_row()
        return np.asarray(cache.margin[:R])

    # ------------------------------------------------------------------ predict
    def _stacked(self, tree_slice: slice, tree_ids: Optional[Sequence[int]] = None):
        if tree_ids is not None:
            trees = [self.trees[i] for i in tree_ids]
            info = [self.tree_info[i] for i in tree_ids]
            wts = [self.tree_weights[i] if self.tree_weights else 1.0 for i in tree_ids]
        else:
            trees = self.trees[tree_slice]
            info = self.tree_info[tree_slice]
            wts = (self.tree_weights[tree_slice]
                   if self.tree_weights else [1.0] * len(trees))
        from .ops.predict import bucket_width

        # pow2 node-pad width: stacked shape (and the compiled program) stays
        # put as trees drift in size across rounds (ops/predict.py bucket cache)
        width = bucket_width(max((t.n_nodes for t in trees), default=1))
        depth = max((t.max_depth for t in trees), default=0) + 1
        has_cat = any(t.has_categorical for t in trees)
        is_multi = any(t.leaf_vector is not None for t in trees)
        keys = ("feat", "thr", "dleft", "left", "right", "value", "is_cat",
                "sbin") + (("value_vec",) if is_multi else ())
        cols = {k: [] for k in keys}
        cats = []
        n_cats = max((t.max_category for t in trees), default=-1) + 1 if has_cat else 0
        for t, w in zip(trees, wts):
            arrs = t.padded_arrays(width)
            if w != 1.0:  # DART per-tree weight (gbtree.cc weight_drop_)
                arrs = dict(arrs)
                arrs["value"] = arrs["value"] * np.float32(w)
            for k in cols:
                cols[k].append(arrs[k])
            if has_cat:
                cats.append(t.cat_matrix(width, n_cats))
        import jax.numpy as jnp

        stacked = {k: jnp.asarray(np.stack(v)) for k, v in cols.items()}
        stacked["catm"] = jnp.asarray(np.stack(cats)) if has_cat else None
        groups = jnp.asarray(np.asarray(info, np.int32))
        return stacked, groups, depth

    def _margin_for_trees(self, X_dev, tree_ids: Sequence[int]):
        stacked, groups, depth = self._stacked(slice(0, 0), tree_ids=tree_ids)
        return self._run_predict(X_dev, stacked, groups, depth)

    def _run_predict(self, X_dev, stacked, groups, depth, init=None):
        """Dispatch one stacked-ensemble margin pass through the shared row
        bucket cache (ops/predict.py): rows pad to the bucket shape so repeat
        callers — eval sets, serving, continuation — reuse compiled programs;
        a batch already at its bucket shape is passed through untouched."""
        from .ops.predict import bucket_rows, pad_margin, pad_rows

        R = X_dev.shape[0]
        bucket = bucket_rows(R)
        X_dev = pad_rows(X_dev, bucket)
        init = pad_margin(init, bucket)
        out = self._run_predict_padded(X_dev, stacked, groups, depth, init)
        return out if bucket == R else out[:R]

    def _run_predict_padded(self, X_dev, stacked, groups, depth, init=None):
        from .ops.predict import run_stacked_margin

        return run_stacked_margin(X_dev, stacked, groups, depth,
                                  self.n_groups, init)

    # past this many dense f32 elements (256 MB) sparse inputs are predicted
    # in fixed-size row windows instead of one dense device matrix
    _PREDICT_BUFFER_ELEMS = 1 << 26

    def _margin_delta_for(self, X_dev, tree_slice: slice, init=None):
        stacked, groups, depth = self._stacked(tree_slice)
        return self._run_predict(X_dev, stacked, groups, depth, init=init)

    def _use_streamed_predict(self, data: DMatrix) -> bool:
        """Sparse matrices whose dense form would not fit the predict buffer
        stream through fixed row windows (the role of the SparsePage loader
        vs dense loader split, gpu_predictor.cu:43-90)."""
        if getattr(data, "_kind", "dense") != "csr":
            return False
        R, F = data.num_row(), data.num_col()
        return R * F > self._PREDICT_BUFFER_ELEMS

    def _margin_delta_streamed(self, data: DMatrix, tree_slice: slice) -> np.ndarray:
        """Margin delta over a sparse matrix in bounded memory: densify one
        fixed-shape row window at a time (padded so every window hits the same
        compiled program) and accumulate on host."""
        import jax.numpy as jnp

        stacked, groups, depth = self._stacked(tree_slice)
        R, F = data.num_row(), data.num_col()
        win = max(1024, int((1 << 22) // max(F, 1)))  # ~16 MB dense window
        out = np.empty((R, self.n_groups), np.float32)
        for lo in range(0, R, win):
            hi = min(lo + win, R)
            chunk = data.host_dense_rows(lo, hi)
            if hi - lo < win:  # pad the tail window to the static shape
                chunk = np.pad(chunk, ((0, win - (hi - lo)), (0, 0)),
                               constant_values=np.nan)
            delta = self._run_predict(jnp.asarray(chunk, jnp.float32),
                                      stacked, groups, depth)
            out[lo:hi] = np.asarray(delta)[: hi - lo]
        return out

    def predict(
        self,
        data: DMatrix,
        output_margin: bool = False,
        pred_leaf: bool = False,
        pred_contribs: bool = False,
        approx_contribs: bool = False,
        pred_interactions: bool = False,
        validate_features: bool = True,
        training: bool = False,
        iteration_range: Tuple[int, int] = (0, 0),
        strict_shape: bool = False,
    ) -> np.ndarray:
        """(reference: core.py:2424 Booster.predict)"""
        import jax.numpy as jnp

        self._configure()
        if self.booster_kind == "gblinear":
            if pred_leaf:
                raise ValueError("pred_leaf is not defined for the gblinear booster")
            if pred_interactions:
                raise ValueError("pred_interactions is not supported for gblinear")
            if pred_contribs:
                return self._linear_contribs(data)
            return self._predict_linear(data, output_margin, strict_shape)
        lo, hi = iteration_range
        n_rounds = self.num_boosted_rounds()
        if hi == 0:
            hi = n_rounds
        if self.best_iteration is not None and iteration_range == (0, 0) and not training:
            pass  # reference keeps all trees unless user slices
        tpr = self.trees_per_round
        tree_slice = slice(lo * tpr, hi * tpr)
        if hasattr(data, "_pages"):  # external-memory: binned page predict
            if pred_leaf or pred_contribs or pred_interactions:
                raise ValueError(
                    "pred_leaf/pred_contribs are not supported for "
                    "ExtMemQuantileDMatrix; predict on an in-memory DMatrix"
                )
            base = np.broadcast_to(self.base_score.reshape(-1), (self.n_groups,))
            if len(self.trees) and tree_slice.start < tree_slice.stop:
                if getattr(data, "has_raw_pages", False):
                    # SparsePageDMatrix: raw-value traversal page by page —
                    # exact float thresholds, works for any model (incl.
                    # ones trained on other cuts or with tree_method=exact)
                    import jax.numpy as jnp

                    margin = np.concatenate([
                        np.asarray(self._margin_delta_for(
                            jnp.asarray(pg), tree_slice))
                        for pg in data.raw_dense_pages()
                    ]) + base[None, :]
                else:
                    padded = self._predict_extmem(data, tree_slice)
                    margin = padded[data.valid_mask()] + base[None, :]
            else:
                margin = np.broadcast_to(base, (data.num_row(), self.n_groups)).copy()
            if data.info.base_margin is not None:
                um = np.asarray(data.info.base_margin, np.float32).reshape(
                    data.num_row(), -1)
                margin = margin - base[None, :] + um
            if output_margin:
                out = margin
            else:
                import jax.numpy as jnp

                out = np.asarray(self.objective.pred_transform(jnp.asarray(margin)))
            return out[:, 0] if self.n_groups == 1 and not strict_shape else out
        streamed = self._use_streamed_predict(data)
        X = None if streamed else jnp.asarray(self._host_dense_recoded(data), jnp.float32)
        if pred_leaf:
            if streamed:
                raise ValueError(
                    "pred_leaf on a large sparse matrix would materialize the "
                    "dense form; predict in row slices instead")
            if not self.trees[tree_slice]:
                return np.zeros((data.num_row(), 0), np.int32)
            stacked, groups, depth = self._stacked(tree_slice)
            out = predict_leaf_ids(
                X, stacked["feat"], stacked["thr"], stacked["dleft"],
                stacked["left"], stacked["right"], depth=depth,
            )
            return np.asarray(out)
        if pred_contribs or pred_interactions:
            from .interpret import predict_contribs, predict_interactions

            if pred_interactions:
                return predict_interactions(self, data, tree_slice)
            return predict_contribs(self, data, tree_slice, approx=approx_contribs)
        base = np.broadcast_to(self.base_score.reshape(-1), (self.n_groups,))
        if len(self.trees) and tree_slice.start < tree_slice.stop:
            if streamed:
                margin = self._margin_delta_streamed(data, tree_slice) + base[None, :]
            else:
                margin = np.asarray(self._margin_delta_for(X, tree_slice)) + base[None, :]
        else:
            margin = np.broadcast_to(base, (data.num_row(), self.n_groups)).copy()
        if data.info.base_margin is not None:
            um = np.asarray(data.info.base_margin, np.float32).reshape(data.num_row(), -1)
            margin = margin - base[None, :] + um
        if output_margin:
            out = margin
        else:
            out = np.asarray(self.objective.pred_transform(jnp.asarray(margin)))
        if self.n_groups == 1 and not strict_shape:
            out = out[:, 0]
        return out

    def _linear_contribs(self, data: DMatrix) -> np.ndarray:
        """Linear contributions: phi_f = w_f * x_f, bias column last
        (reference: gblinear.cc PredictContribution)."""
        self._configure()
        X = np.nan_to_num(self._host_dense_recoded(data), nan=0.0)
        R, F = X.shape
        K = self.n_groups
        W = self.linear_weights if self.linear_weights is not None else np.zeros((F, K), np.float32)
        b = self.linear_bias if self.linear_bias is not None else np.zeros(K, np.float32)
        base = np.broadcast_to(self.base_score.reshape(-1), (K,))
        out = np.zeros((R, K, F + 1), np.float64)
        for k in range(K):
            out[:, k, :F] = X * W[:, k][None, :]
            out[:, k, F] = b[k] + base[k]
        return out[:, 0, :] if K == 1 else out

    def _predict_linear(self, data: DMatrix, output_margin: bool, strict_shape: bool):
        import jax.numpy as jnp

        from .models.gblinear import linear_predict

        self._configure()
        X = jnp.asarray(self._host_dense_recoded(data), jnp.float32)
        base = np.broadcast_to(self.base_score.reshape(-1), (self.n_groups,))
        if self.linear_weights is None:
            margin = np.broadcast_to(base, (data.num_row(), self.n_groups)).copy()
        else:
            margin = np.asarray(
                linear_predict(X, jnp.asarray(self.linear_weights),
                               jnp.asarray(self.linear_bias))
            ) + base[None, :]
        if output_margin:
            out = margin
        else:
            out = np.asarray(self.objective.pred_transform(jnp.asarray(margin)))
        if self.n_groups == 1 and not strict_shape:
            out = out[:, 0]
        return out

    def inference_snapshot(self):
        """Freeze this booster into an immutable, device-resident
        :class:`xgboost_tpu.serving.InferenceSnapshot` — the unit the serving
        engine registers, batches over, and LRU-caches.  Mutating the booster
        afterwards (continued training, set_attr) does not affect snapshots
        already taken."""
        from .serving.snapshot import InferenceSnapshot

        return InferenceSnapshot.from_booster(self)

    def get_categories(self) -> Optional[Dict[str, list]]:
        """Train-time category mapping ``{feature name (or index): values}``
        for categorical features, or None when the model was trained without
        frame-level categories (reference: ``XGBoosterGetCategories``,
        src/data/cat_container.h).  Inference frames are recoded against this
        mapping; exporting it lets non-Python consumers do the same."""
        from .data.dmatrix import categories_by_name

        self._configure()
        return categories_by_name(getattr(self, "_cat_categories", None),
                                  self.feature_names)

    def inplace_predict(self, data, iteration_range=(0, 0), predict_type="value",
                        missing=np.nan, validate_features=True, base_margin=None,
                        strict_shape=False):
        """(reference: core.py:2561) — wraps raw arrays without a DMatrix."""
        d = DMatrix(data, missing=missing)
        if base_margin is not None:
            d.set_base_margin(base_margin)
        return self.predict(
            d, output_margin=(predict_type == "margin"),
            iteration_range=iteration_range, strict_shape=strict_shape,
        )

    # ------------------------------------------------------------------ model IO
    @property
    def trees_per_round(self) -> int:
        if getattr(self, "multi_strategy", "") == "multi_output_tree" \
                and self.n_groups > 1:
            return max(self.num_parallel_tree, 1)  # one vector tree per round
        return max(self.n_groups, 1) * max(self.num_parallel_tree, 1)

    def num_boosted_rounds(self) -> int:
        self._configure()
        if self.booster_kind == "gblinear":
            return getattr(self, "_linear_rounds", 0)
        return len(self.trees) // self.trees_per_round

    def num_features(self) -> int:
        if getattr(self, "_num_feature", None):
            return self._num_feature
        for c in self._caches.values():
            return c.dmat.num_col()
        if self.trees:
            return int(max(t.split_indices.max(initial=0) for t in self.trees)) + 1
        return 0

    def save_model(self, fname: Union[str, os.PathLike]) -> None:
        """JSON (``.json``) or UBJSON (``.ubj``) model file
        (reference: learner.cc:950 SaveModel; schema doc/model.schema)."""
        obj = self.save_raw_dict()
        fname = os.fspath(fname)
        if fname.endswith(".ubj"):
            from .utils.ubjson import dump_ubjson

            with open(fname, "wb") as fh:
                dump_ubjson(obj, fh)
        else:
            with open(fname, "w") as fh:
                json.dump(obj, fh)

    def _base_score_str(self) -> str:
        """base_score in probability space, reference model-JSON form
        (scalar, or upstream ≥3.x bracketed vector for per-group offsets)."""
        base_margins = np.asarray(self.base_score, np.float32).reshape(-1)
        base_probs = [
            float(np.asarray(self.objective.margin_to_prob(np.float32(m))))
            for m in base_margins
        ]
        if len(base_probs) > 1 and not np.allclose(base_probs, base_probs[0]):
            return "[" + ",".join(f"{p:.9E}" for p in base_probs) + "]"
        return f"{base_probs[0]:.9E}"

    def save_raw_dict(self) -> dict:
        self._configure()
        n_feat = self.num_features()
        base = self._base_score_str()
        obj_conf = {"name": self.objective.name}
        if self.objective.name.startswith("multi:"):
            obj_conf["softmax_multiclass_param"] = {"num_class": str(self.num_class)}
        if self.booster_kind == "gblinear":
            # reference schema: gblinear.cc SaveModel — feature-major weights,
            # per-group bias at the end
            W = self.linear_weights if self.linear_weights is not None else np.zeros(
                (n_feat, self.n_groups), np.float32)
            b = self.linear_bias if self.linear_bias is not None else np.zeros(
                self.n_groups, np.float32)
            gb = {
                "model": {"weights": [float(x) for x in
                                      np.concatenate([W.reshape(-1), b])],
                          "param": {"num_feature": str(n_feat),
                                    "num_output_group": str(self.n_groups),
                                    "num_boosted_rounds": str(
                                        getattr(self, "_linear_rounds", 0))}},
                "name": "gblinear",
            }
        else:
            trees = [t.to_json_dict(n_feat, tree_id=i)
                     for i, t in enumerate(self.trees)]
            model = {
                "gbtree_model_param": {
                    "num_trees": str(len(self.trees)),
                    "num_parallel_tree": str(self.num_parallel_tree),
                },
                "trees": trees,
                "tree_info": list(self.tree_info),
            }
            if self.booster_kind == "dart":
                gb = {"gbtree": {"model": model},
                      "weight_drop": [float(w) for w in self.tree_weights],
                      "name": "dart"}
            else:
                gb = {"model": model, "name": "gbtree"}
        # exact f32 margin stashed as an attribute (string map — upstream
        # ignores unknown keys): prob<->margin transforms do not round-trip
        # bitwise in f32, so reloading from base_score alone perturbs margins
        attrs = dict(self.attributes)
        attrs["base_margin_exact"] = " ".join(
            repr(float(v)) for v in np.asarray(self.base_score).reshape(-1))
        if getattr(self, "_cat_categories", None):
            # training categories, for inference-time recode (the role of
            # the reference's cat container in the model blob)
            attrs["cat_categories"] = json.dumps(self._cat_categories)
        return {
            "version": [3, 1, 0],
            "learner": {
                "attributes": attrs,
                "feature_names": self.feature_names or [],
                "feature_types": self.feature_types or [],
                "gradient_booster": gb,
                "learner_model_param": {
                    "base_score": base,
                    "boost_from_average": "1",
                    "num_class": str(self.num_class),
                    "num_feature": str(n_feat),
                    "num_target": str(self.n_groups if self.num_class == 0
                                      else 1),
                },
                "objective": obj_conf,
            },
        }

    def load_model(self, fname: Union[str, os.PathLike, bytes, bytearray]) -> None:
        if isinstance(fname, (bytes, bytearray)):
            try:
                obj = json.loads(fname)
            except (UnicodeDecodeError, json.JSONDecodeError):
                import io

                from .utils.ubjson import load_ubjson

                obj = load_ubjson(io.BytesIO(bytes(fname)))
        else:
            fname = os.fspath(fname)
            if fname.endswith(".ubj"):
                from .utils.ubjson import load_ubjson

                with open(fname, "rb") as fh:
                    obj = load_ubjson(fh)
            else:
                with open(fname) as fh:
                    obj = json.load(fh)
        self.load_model_dict(obj)

    def load_model_dict(self, obj: dict) -> None:
        learner = obj["learner"]
        lmp = learner["learner_model_param"]
        self.params.setdefault("objective", learner["objective"]["name"])
        nc = int(lmp.get("num_class", "0"))
        if nc > 0:
            self.params["num_class"] = nc
        nt = int(lmp.get("num_target", "1") or 1)
        if nt > 1:
            self.params["num_target"] = nt
        self._invalidate_config()
        self._configure()
        exact = learner.get("attributes", {}).get("base_margin_exact")
        if exact is not None:
            vals = np.asarray([float(v) for v in str(exact).split()], np.float32)
            self._base_margin_value = np.broadcast_to(
                vals if vals.size > 1 else vals.reshape(-1)[0],
                (self.n_groups,)).astype(np.float32).copy()
        else:
            # upstream ≥3.x may write a bracketed array "[4.5E-1]" (vector
            # leaf support, learner.cc LearnerModelParamLegacy); accept both
            raw = str(lmp["base_score"]).strip().strip("[]()")
            probs = np.asarray([float(v) for v in raw.replace(",", " ").split()],
                               np.float32)
            if probs.size == 0:
                raise ValueError(
                    f"Cannot parse base_score {lmp['base_score']!r}")
            if probs.size not in (1, self.n_groups):
                raise ValueError(
                    f"base_score has {probs.size} entries but the model has "
                    f"{self.n_groups} output groups (multi-target vector "
                    "leaves are not supported yet)")
            base_prob = probs if probs.size > 1 else probs.reshape(-1)[0]
            self._base_margin_value = np.broadcast_to(
                np.asarray(self.objective.prob_to_margin(base_prob), np.float32),
                (self.n_groups,)).astype(np.float32).copy()
        self._num_feature = int(lmp.get("num_feature", "0")) or None
        gbooster = learner["gradient_booster"]
        name = gbooster.get("name", "gbtree")
        self.params.setdefault("booster", name)
        self._invalidate_config(structural=False)
        self._configure()
        if name == "gblinear":
            flat = np.asarray(gbooster["model"]["weights"], np.float32)
            F = self._num_feature or (len(flat) // max(self.n_groups, 1) - 1)
            K = max(self.n_groups, 1)
            self.linear_weights = flat[: F * K].reshape(F, K)
            self.linear_bias = flat[F * K : F * K + K]
            self._linear_rounds = int(
                gbooster["model"].get("param", {}).get("num_boosted_rounds", "0") or 0)
            self.trees, self.tree_info, self.tree_weights = [], [], []
        else:
            gb = gbooster["gbtree"]["model"] if name == "dart" else gbooster["model"]
            self.trees = [RegTree.from_json_dict(t) for t in gb["trees"]]
            self.tree_info = [int(i) for i in gb["tree_info"]]
            self.tree_weights = [float(w) for w in gbooster.get(
                "weight_drop", [1.0] * len(self.trees))]
            self.num_parallel_tree = int(
                gb.get("gbtree_model_param", {}).get("num_parallel_tree", "1") or 1)
            self.params.setdefault("num_parallel_tree", self.num_parallel_tree)
            if any(t.leaf_vector is not None for t in self.trees):
                self.params["multi_strategy"] = "multi_output_tree"
                self.multi_strategy = "multi_output_tree"
        self.attributes = dict(learner.get("attributes", {}))
        self.attributes.pop("base_margin_exact", None)
        cc = self.attributes.pop("cat_categories", None)
        if cc:
            self._cat_categories = {int(k): list(v)
                                    for k, v in json.loads(cc).items()}
        self.feature_names = learner.get("feature_names") or None
        self.feature_types = learner.get("feature_types") or None

    def save_raw(self, raw_format: str = "ubj") -> bytearray:
        obj = self.save_raw_dict()
        if raw_format == "json":
            return bytearray(json.dumps(obj).encode())
        from io import BytesIO

        from .utils.ubjson import dump_ubjson

        buf = BytesIO()
        dump_ubjson(obj, buf)
        return bytearray(buf.getvalue())

    # ---- training-configuration IO (reference: learner.cc:625 SaveConfig /
    # :570 LoadConfig; C API XGBoosterSaveJsonConfig, c_api.cc:1379 area).
    # The model files above carry the MODEL; these carry the training
    # configuration, so a restored process continues training identically.
    def _config_dict(self) -> dict:
        import dataclasses as _dc

        from .params import KNOWN_LEARNER_KEYS, TrainParam

        self._configure()

        def s(v):
            if isinstance(v, bool):
                return "1" if v else "0"
            if isinstance(v, (list, tuple, dict)):
                return json.dumps(v)
            return str(v)

        params = {k: v for k, v in self.params.items() if v is not None}
        tree_keys = {("lambda" if f.name == "lambda_" else f.name)
                     for f in _dc.fields(TrainParam)}
        hist_param = {}
        for k in sorted(tree_keys):
            v = getattr(self.tparam, "lambda_" if k == "lambda" else k)
            if v is not None:
                hist_param[k] = s(v)
        placed = set(tree_keys)

        def take(section: dict, key: str, default=None) -> None:
            if key in params:
                section[key] = s(params[key])
                placed.add(key)
            elif default is not None:
                section[key] = s(default)

        learner_train = {"booster": self.booster_kind,
                         "objective": self.objective.name}
        placed |= {"booster", "objective"}
        take(learner_train, "disable_default_eval_metric", 0)
        take(learner_train, "multi_strategy",
             getattr(self, "multi_strategy", "one_output_per_tree"))

        generic = {}
        take(generic, "device", "tpu")
        take(generic, "seed", 0)
        take(generic, "seed_per_iteration", 0)
        take(generic, "nthread", 0)
        take(generic, "validate_parameters", 0)

        gb: dict = {"name": self.booster_kind}
        if self.booster_kind == "gblinear":
            lin = {}
            for k in ("updater", "feature_selector", "top_k", "eta"):
                take(lin, k)
            lin["lambda"] = hist_param.get("lambda", "0")
            lin["alpha"] = hist_param.get("alpha", "0")
            gb["gblinear_train_param"] = lin
        else:
            gbt = {"num_parallel_tree": s(self.num_parallel_tree)}
            placed.add("num_parallel_tree")
            take(gbt, "process_type", "default")
            take(gbt, "tree_method", "hist")
            take(gbt, "updater")
            gb["gbtree_train_param"] = gbt
            gb["updater"] = {
                "grow_quantile_histmaker": {"hist_train_param": hist_param}}
            if self.booster_kind == "dart":
                dart = {}
                for k in ("rate_drop", "one_drop", "skip_drop",
                          "sample_type", "normalize_type"):
                    take(dart, k)
                gb["dart_train_param"] = dart

        obj_sec: dict = {"name": self.objective.name}
        obj_keys = ("scale_pos_weight", "num_class", "tweedie_variance_power",
                    "huber_slope", "quantile_alpha", "expectile_alpha",
                    "aft_loss_distribution", "aft_loss_distribution_scale",
                    "lambdarank_num_pair_per_sample", "lambdarank_pair_method",
                    "ndcg_exp_gain", "lambdarank_unbiased",
                    "lambdarank_bias_norm")
        for k in obj_keys:
            take(obj_sec, k)

        metric_names = params.get("eval_metric")
        if metric_names is None:
            metrics = []
        elif isinstance(metric_names, (list, tuple)):
            metrics = [{"name": str(m)} for m in metric_names]
        else:
            metrics = [{"name": str(metric_names)}]
        placed.add("eval_metric")

        # user-set params not covered by a named section ride in
        # generic_param (the reference Context also carries a grab-bag of
        # runtime keys there) so load_config restores EVERYTHING
        for k in sorted(params):
            if k not in placed and k in (KNOWN_LEARNER_KEYS | tree_keys):
                generic[k] = s(params[k])

        return {
            "version": [3, 1, 0],
            "learner": {
                "generic_param": generic,
                "gradient_booster": gb,
                "learner_model_param": {
                    "base_score": ("5E-1" if self._base_margin_value is None
                                   else self._base_score_str()),
                    "num_class": str(self.num_class),
                    "num_feature": str(self.num_features()),
                    "num_target": str(self.n_groups if self.num_class == 0
                                      else 1),
                },
                "learner_train_param": learner_train,
                "metrics": metrics,
                "objective": obj_sec,
            },
        }

    def save_config(self) -> str:
        """Current training configuration as a JSON string (reference:
        Booster.save_config / XGBoosterSaveJsonConfig)."""
        return json.dumps(self._config_dict())

    def load_config(self, config: Union[str, bytes, dict]) -> None:
        """Restore a save_config() snapshot (reference learner.cc:570
        LoadConfig): collects every parameter leaf from the reference-shaped
        sections and applies it, so continued training behaves identically."""
        import dataclasses as _dc

        from .params import KNOWN_LEARNER_KEYS, TrainParam

        obj = config if isinstance(config, dict) else json.loads(config)
        learner = obj.get("learner", obj)
        tree_keys = {("lambda" if f.name == "lambda_" else f.name)
                     for f in _dc.fields(TrainParam)}
        known = KNOWN_LEARNER_KEYS | tree_keys
        collected: Dict[str, Any] = {}

        def walk(d: dict) -> None:
            for k, v in d.items():
                if k == "learner_model_param":
                    continue  # model state, not configuration
                if isinstance(v, dict):
                    walk(v)
                elif k != "name" and isinstance(v, (str, int, float, bool)):
                    if k in known:
                        collected[k] = v

        walk(learner)
        metrics = learner.get("metrics") or []
        names = [m["name"] if isinstance(m, dict) else str(m) for m in metrics]
        if names:
            collected["eval_metric"] = names
        else:
            collected.pop("eval_metric", None)
        booster_name = learner.get("gradient_booster", {}).get("name")
        if booster_name:
            collected["booster"] = booster_name
        if collected:
            self.set_param(collected)

    def serialize(self) -> bytearray:
        """Full-state snapshot {"Model": ..., "Config": ...} in UBJSON
        (reference learner.cc:987 Save; C API XGBoosterSerializeToBuffer,
        learner.cc:992): model + training configuration in one buffer."""
        from io import BytesIO

        from .utils.ubjson import dump_ubjson

        snap = {"Model": self.save_raw_dict(), "Config": self._config_dict()}
        buf = BytesIO()
        dump_ubjson(snap, buf)
        return bytearray(buf.getvalue())

    def unserialize(self, buf: Union[bytes, bytearray]) -> None:
        """Restore a serialize() snapshot (learner.cc:1003 Load)."""
        import io

        from .utils.ubjson import load_ubjson

        try:
            snap = json.loads(buf)
        except (UnicodeDecodeError, json.JSONDecodeError):
            snap = load_ubjson(io.BytesIO(bytes(buf)))
        self.load_model_dict(snap["Model"])
        self.load_config(snap["Config"])

    # attributes API (reference: core.py attr/set_attr)
    def attr(self, key: str) -> Optional[str]:
        return self.attributes.get(key)

    def set_attr(self, **kwargs: Optional[str]) -> None:
        for k, v in kwargs.items():
            if v is None:
                self.attributes.pop(k, None)
            else:
                self.attributes[k] = str(v)

    def __getitem__(self, val: slice) -> "Booster":
        """Tree-slice (reference: Booster.__getitem__ / Learner::Slice)."""
        if not isinstance(val, slice):
            raise TypeError("Booster slicing requires a slice of rounds")
        self._configure()
        if self.booster_kind == "gblinear":
            raise ValueError("Slice is not supported by the gblinear booster")
        lo = val.start or 0
        hi = val.stop if val.stop is not None else self.num_boosted_rounds()
        out = Booster(dict(self.params))
        out._configure()
        k = out.trees_per_round
        out.trees = self.trees[lo * k : hi * k]
        out.tree_info = self.tree_info[lo * k : hi * k]
        out.tree_weights = list(self.tree_weights[lo * k : hi * k])
        out._base_margin_value = self._base_margin_value
        out._num_feature = getattr(self, "_num_feature", None)
        out.feature_names = self.feature_names
        out.feature_types = self.feature_types
        out.attributes = dict(self.attributes)
        out.best_iteration = self.best_iteration
        out.best_score = self.best_score
        return out

    def copy(self) -> "Booster":
        return self[0 : self.num_boosted_rounds()]

    def get_dump(self, fmap: str = "", with_stats: bool = False, dump_format: str = "text"):
        names = self.feature_names
        if fmap:
            # feature-map file: "<id>\t<name>\t<type>" per line
            # (reference: src/common/feature_map.h LoadText)
            names = list(names or [f"f{i}" for i in range(self.num_features())])
            with open(fmap) as fh:
                for line in fh:
                    # tab-separated like FeatureMap::LoadText, so names may
                    # contain spaces; whitespace split only as a fallback
                    line = line.rstrip("\n")
                    parts = line.split("\t") if "\t" in line else line.split()
                    if len(parts) >= 2:
                        fid = int(parts[0])
                        while len(names) <= fid:
                            names.append(f"f{len(names)}")
                        names[fid] = parts[1]
        if dump_format == "json":
            return [t.dump_json(names, with_stats) for t in self.trees]
        return [t.dump_text(names, with_stats) for t in self.trees]

    def get_score(self, fmap: str = "", importance_type: str = "weight") -> Dict[str, float]:
        """Feature importance (reference: core.py get_score)."""
        self._configure()
        names = self.feature_names or [f"f{i}" for i in range(self.num_features())]
        acc: Dict[str, float] = {}
        cnt: Dict[str, int] = {}
        for t in self.trees:
            for nid in range(t.n_nodes):
                if t.left_children[nid] == -1:
                    continue
                f = names[t.split_indices[nid]]
                cnt[f] = cnt.get(f, 0) + 1
                if importance_type in ("gain", "total_gain"):
                    acc[f] = acc.get(f, 0.0) + float(t.loss_changes[nid])
                elif importance_type in ("cover", "total_cover"):
                    acc[f] = acc.get(f, 0.0) + float(t.sum_hessian[nid])
                else:
                    acc[f] = acc.get(f, 0.0) + 1.0
        if importance_type in ("gain", "cover"):
            return {k: v / cnt[k] for k, v in acc.items()}
        return acc
