"""Execution context: device parsing and selection.

TPU-native analogue of the reference's ``Context``/``DeviceOrd``
(include/xgboost/context.h:40, src/context.cc:105-155).  The reference parses
``device="cpu"|"cuda[:N]"|"gpu"|"sycl:*"``; here the accelerator is
``device="tpu[:N]"`` and compute is dispatched through JAX, so "device" selects
a ``jax.Device`` rather than a code path.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Optional

_DEVICE_RE = re.compile(r"^(cpu|tpu|gpu|cuda)(:(\d+))?$")


@dataclasses.dataclass(frozen=True)
class DeviceOrd:
    """A parsed device: ``type`` is 'cpu' or 'tpu', ``ordinal`` indexes jax.devices().

    Mirrors DeviceOrd (include/xgboost/context.h:40); 'gpu'/'cuda' are accepted
    and mapped to the accelerator ('tpu') for drop-in compatibility.
    """

    type: str = "cpu"
    ordinal: int = 0

    @staticmethod
    def parse(spec: str) -> "DeviceOrd":
        spec = (spec or "cpu").strip().lower()
        m = _DEVICE_RE.match(spec)
        if m is None:
            raise ValueError(
                f"Invalid device spec: {spec!r}. Expected 'cpu', 'tpu', or 'tpu:<ordinal>'."
            )
        kind = m.group(1)
        if kind in ("gpu", "cuda"):  # accept reference spellings; run on the accelerator
            kind = "tpu"
        ordinal = int(m.group(3) or 0)
        return DeviceOrd(kind, ordinal)

    @property
    def is_accelerator(self) -> bool:
        return self.type == "tpu"

    def jax_device(self):
        """Resolve to a concrete jax.Device, falling back to the default backend."""
        import jax

        if self.type == "tpu":
            for plat in ("tpu", "axon"):
                try:
                    devs = jax.devices(plat)
                except RuntimeError:
                    continue
                if devs:
                    return devs[self.ordinal % len(devs)]
            return jax.devices()[0]
        try:
            return jax.devices("cpu")[self.ordinal % len(jax.devices("cpu"))]
        except RuntimeError:
            return jax.devices()[0]


@dataclasses.dataclass
class Context:
    """Runtime context threaded through training (reference: include/xgboost/context.h).

    nthread/seed mirror the reference Context fields; device selects where
    jitted kernels place their arrays.
    """

    device: DeviceOrd = dataclasses.field(default_factory=DeviceOrd)
    nthread: int = 0
    seed: int = 0

    @staticmethod
    def create(device: str = "cpu", nthread: int = 0, seed: int = 0) -> "Context":
        return Context(device=DeviceOrd.parse(device), nthread=int(nthread),
                       seed=seed)

    def apply_nthread(self) -> int:
        """Push the resolved thread count into the native ParallelFor pools
        (both kernel libraries).  Precedence (docs/native_threading.md):
        explicit ``nthread`` param > ``XGBOOST_TPU_NTHREAD`` env >
        ``os.cpu_count()`` — the reference's nthread/OMP_NUM_THREADS
        resolution (src/common/threading_utils.cc OmpGetNumThreads) with
        the package env var in OMP's seat.  Bitwise-neutral: threaded
        kernels are pinned identical to nthread=1 for every value."""
        from .utils import native

        return native.set_nthread(self.nthread)

    def jax_device(self):
        return self.device.jax_device()
