"""Device-side ranking metrics — segment-vectorized ndcg / map / precision.

The host metrics in ``metric/__init__.py`` loop python-per-query-group,
which crawls at MSLR scale (30k+ queries per eval round).  The reference
solves this with device kernels (src/metric/auc.cu, rank_metric.cc +
ranking_utils.cuh SegmentedTrapezoidThreads); the TPU-native equivalent is
segment arithmetic over ONE global sort — no python loop, no padding:

 - rows -> group ids via searchsorted on the group pointer;
 - one stable ``lexsort`` (group-major, score-descending) puts every group's
   docs in rank order while keeping blocks contiguous, so the within-group
   rank is just ``arange(R) - group_start``;
 - DCG / AP / precision@k become masked ``segment_sum`` reductions, and
   within-group cumulative hit counts come from one global ``cumsum`` minus
   its value at the group start.

Everything jits to one fused XLA program (CPU today, MXU/VPU on TPU); the
python-loop host versions remain the parity oracle
(tests/test_ranking.py::test_device_rank_parity).

Each function returns the pre-reduction pair ``(sum_g w_g * val_g,
sum_g w_g)`` so the caller can feed the distributed ``GlobalRatio``
allreduce exactly like the host path (src/collective/aggregator.h).
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp


def _segment_layout(preds, ptr, k: int):
    """Shared group geometry: (gid, rank-within-group at each SORTED
    position, per-group top-k cut, per-group sizes).

    ``rank`` computed over positions is valid after any gid-primary stable
    sort because rows arrive group-contiguous (ptr is monotone), so each
    group's block occupies the same [lo, hi) slice before and after.
    """
    R = preds.shape[0]
    rows = jnp.arange(R, dtype=jnp.int32)
    gid = jnp.searchsorted(ptr, rows, side="right").astype(jnp.int32) - 1
    starts = ptr[:-1].astype(jnp.int32)
    sizes = (ptr[1:] - ptr[:-1]).astype(jnp.int32)
    rank = rows - starts[gid]
    kk = sizes if k <= 0 else jnp.minimum(k, sizes)  # host: k or group size
    return gid, starts, sizes, rank, kk


@functools.partial(jax.jit, static_argnames=("n_groups", "k", "minus",
                                             "exp_gain"))
def _ndcg_device(preds, labels, ptr, ws, *, n_groups: int, k: int,
                 minus: bool, exp_gain: bool = True):
    gid, _, sizes, rank, kk = _segment_layout(preds, ptr, k)
    mask = (rank < kk[gid]).astype(preds.dtype)
    disc = 1.0 / jnp.log2(rank.astype(preds.dtype) + 2.0)

    def seg_dcg(sort_key):
        order = jnp.lexsort((sort_key, gid))  # stable; blocks stay contiguous
        y_s = labels[order]
        gain = (jnp.exp2(y_s) - 1.0) if exp_gain else y_s
        return jax.ops.segment_sum(gain * disc * mask, gid,
                                   num_segments=n_groups)

    dcg = seg_dcg(-preds)
    idcg = seg_dcg(-labels)
    empty_default = 0.0 if minus else 1.0
    vals = jnp.where(idcg > 0, dcg / jnp.maximum(idcg, 1e-32), empty_default)
    valid = (sizes > 0).astype(preds.dtype)
    return jnp.sum(vals * ws * valid), jnp.sum(ws * valid)


@functools.partial(jax.jit, static_argnames=("n_groups", "k", "minus"))
def _map_device(preds, labels, ptr, ws, *, n_groups: int, k: int, minus: bool):
    gid, starts, sizes, rank, kk = _segment_layout(preds, ptr, k)
    order = jnp.lexsort((-preds, gid))
    y_s = (labels[order] > 0).astype(preds.dtype)
    yk = y_s * (rank < kk[gid]).astype(preds.dtype)
    cs = jnp.cumsum(yk)
    base = jnp.where(starts > 0, cs[jnp.maximum(starts - 1, 0)], 0.0)
    hits = cs - base[gid]  # inclusive within-group cumulative relevant count
    ap_num = jax.ops.segment_sum(
        yk * hits / (rank.astype(preds.dtype) + 1.0), gid,
        num_segments=n_groups)
    npos = jax.ops.segment_sum(yk, gid, num_segments=n_groups)
    empty_default = 0.0 if minus else 1.0
    vals = jnp.where(npos > 0, ap_num / jnp.maximum(npos, 1e-32),
                     empty_default)
    valid = (sizes > 0).astype(preds.dtype)
    return jnp.sum(vals * ws * valid), jnp.sum(ws * valid)


@functools.partial(jax.jit, static_argnames=("n_groups", "k"))
def _precision_device(preds, labels, ptr, ws, *, n_groups: int, k: int):
    gid, _, sizes, rank, n = _segment_layout(preds, ptr, k)
    order = jnp.lexsort((-preds, gid))
    y_s = labels[order]
    mask = (rank < n[gid]).astype(preds.dtype)
    top = jax.ops.segment_sum(y_s * mask, gid, num_segments=n_groups)
    valid = (sizes > 0).astype(preds.dtype)
    vals = top / jnp.maximum(n, 1).astype(preds.dtype)
    return jnp.sum(vals * ws * valid), jnp.sum(ws * valid)


def _group_weights(weights, group_ptr) -> np.ndarray:
    """Host-side group weight resolution (per-group vector, or the group's
    first row of a per-row vector), matching the host metrics exactly."""
    G = len(group_ptr) - 1
    if weights is None:
        return np.ones(G, np.float32)
    w = np.asarray(weights, np.float32)
    if len(w) == G:
        return w
    starts = np.minimum(np.asarray(group_ptr[:-1]), len(w) - 1)
    return w[starts]


def _run_pair(kernel, preds, labels, group_ptr, weights, **static):
    ws = _group_weights(weights, group_ptr)
    num, den = kernel(
        jnp.asarray(preds, jnp.float32), jnp.asarray(labels, jnp.float32),
        jnp.asarray(group_ptr, jnp.int32), jnp.asarray(ws),
        n_groups=len(group_ptr) - 1, **static)
    return float(num), float(den)


def ndcg_pair(preds, labels, group_ptr, weights, k: int, minus: bool):
    return _run_pair(_ndcg_device, preds, labels, group_ptr, weights,
                     k=int(k), minus=bool(minus))


def map_pair(preds, labels, group_ptr, weights, k: int, minus: bool):
    return _run_pair(_map_device, preds, labels, group_ptr, weights,
                     k=int(k), minus=bool(minus))


def precision_pair(preds, labels, group_ptr, weights, k: int):
    return _run_pair(_precision_device, preds, labels, group_ptr, weights,
                     k=int(k))
