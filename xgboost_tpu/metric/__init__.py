"""Evaluation metrics (reference: src/metric/ — elementwise_metric.cu,
multiclass_metric.cu, auc.cc/.cu, rank_metric.cc).

Each metric consumes *transformed* predictions (after the objective's
PredTransform) except where the reference evaluates on margins; all are
weighted and reduce to (sum, wsum) pairs so the distributed path can psum the
partials exactly like the reference's allreduce-of-partials design.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

import numpy as np

_REGISTRY: Dict[str, Callable] = {}

# rank metrics carrying the reference's trailing-minus convention
# (degenerate groups score 0 instead of 1 — ranking_utils.cc ParseMetricName)
_MINUS_METRICS = {"ndcg", "map", "pre"}

_DIST = threading.local()


class distributed_reduction:
    """While active (per thread), metric helpers allreduce their partial
    sums across the collective, so every rank reports the GLOBAL metric
    from O(local) memory — the reference's allreduce-of-partials design
    (src/collective/aggregator.h GlobalSum/GlobalRatio,
    src/metric/elementwise_metric.cu, auc.cc:124-126)."""

    def __enter__(self):
        _DIST.on = True
        return self

    def __exit__(self, *exc):
        _DIST.on = False
        return False


# python-loop host metrics win below this group count (jit dispatch
# overhead); MSLR-scale evals sit far above it
_MIN_DEVICE_GROUPS = 64


def _use_device_rank(group_ptr, preds, kw) -> bool:
    """Route large-cohort ranking evals to the segment-vectorized device
    metrics (device_rank.py); small evals keep the python-loop oracle.
    ``use_device_rank`` in kw forces either way (tests).

    Distributed evals always take the host path unless forced: routing on
    the rank-LOCAL group count would let peers pick different precisions
    for the same allreduce (the aucpr branch-on-structure lesson), making
    the reported global metric sharding-dependent."""
    forced = kw.get("use_device_rank")
    if forced is not None:
        return bool(forced)
    if getattr(_DIST, "on", False):
        return False
    return (np.ndim(preds) == 1
            and len(group_ptr) - 1 >= _MIN_DEVICE_GROUPS)


def _reduce_sums(*vals: float):
    """allreduce-SUM scalars when distributed reduction is active."""
    if not getattr(_DIST, "on", False):
        return vals
    from .. import collective

    out = collective.global_sum(np.asarray(vals, np.float64))
    return tuple(float(v) for v in out)


def register_metric(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def create_metric(name: str):
    """Resolve ``base[@n][-]`` (reference: ranking_utils.cc:138
    ParseMetricName): ``@n`` truncates ranking metrics at n, a trailing
    ``-`` flips degenerate-group scoring from 1 to 0 (``ndcg@5-``,
    ``map-``)."""
    base, minus, arg = name, False, None
    if "@" in name:
        base, param = name.split("@", 1)
        if param.endswith("-"):
            minus, param = True, param[:-1]
        if not param:
            raise ValueError(f"Invalid metric name {name!r}: '@' needs a "
                             "numeric truncation/threshold")
        arg = float(param)
    elif base.endswith("-") and base[:-1] in _MINUS_METRICS:
        minus, base = True, base[:-1]
    if base not in _REGISTRY:
        raise ValueError(f"Unknown metric {name!r}. Known: {sorted(_REGISTRY)}")
    if minus and base not in _MINUS_METRICS:
        # the '-' convention only exists for rank metrics (ranking_utils.cc)
        raise ValueError(f"Unknown metric {name!r}: the '-' suffix applies "
                         f"only to {sorted(_MINUS_METRICS)}")
    fn = _REGISTRY[base]
    if arg is not None or minus:
        extra = {}
        if arg is not None:
            extra["at"] = arg
        if minus:
            extra["minus"] = True
        wrapper = lambda *a, **k: fn(*a, **{**extra, **k})  # noqa: E731
        wrapper.__wrapped__ = fn  # callers introspect the real signature
        return wrapper, name
    return fn, name


def list_metrics():
    return sorted(_REGISTRY)


def _w(labels, weights):
    return np.ones_like(labels, dtype=np.float64) if weights is None else weights.astype(np.float64)


def _wmean(err, labels, weights):
    w = _w(labels if err.ndim == 1 else err[:, 0], weights)
    if err.ndim == 2:  # multi-target: mean over rows x targets
        s, wsum = _reduce_sums(float(np.sum(err * w[:, None])),
                               float(np.sum(w)))
        return s / (wsum * err.shape[1])
    s, wsum = _reduce_sums(float(np.sum(err * w)), float(np.sum(w)))
    return s / wsum


@register_metric("rmse")
def rmse(preds, labels, weights=None, **kw):
    return float(np.sqrt(_wmean((preds - labels) ** 2, labels, weights)))


@register_metric("rmsle")
def rmsle(preds, labels, weights=None, **kw):
    return float(
        np.sqrt(_wmean((np.log1p(np.maximum(preds, 0)) - np.log1p(labels)) ** 2, labels, weights))
    )


@register_metric("mae")
def mae(preds, labels, weights=None, **kw):
    return _wmean(np.abs(preds - labels), labels, weights)


@register_metric("mape")
def mape(preds, labels, weights=None, **kw):
    return _wmean(np.abs((labels - preds) / np.maximum(np.abs(labels), 1e-10)), labels, weights)


@register_metric("mphe")
def mphe(preds, labels, weights=None, slope: float = 1.0, **kw):
    z = (preds - labels) / slope
    return _wmean(slope**2 * (np.sqrt(1 + z**2) - 1), labels, weights)


@register_metric("logloss")
def logloss(preds, labels, weights=None, **kw):
    p = np.clip(np.asarray(preds, np.float64), 1e-16, 1 - 1e-16)
    return _wmean(-(labels * np.log(p) + (1 - labels) * np.log(1 - p)), labels, weights)


@register_metric("error")
def error(preds, labels, weights=None, at: float = 0.5, **kw):
    return _wmean(((preds > at) != (labels > 0.5)).astype(np.float64), labels, weights)


@register_metric("poisson-nloglik")
def poisson_nloglik(preds, labels, weights=None, **kw):
    from scipy.special import gammaln

    p = np.maximum(preds, 1e-16)
    return _wmean(p - labels * np.log(p) + gammaln(labels + 1.0), labels, weights)


@register_metric("gamma-nloglik")
def gamma_nloglik(preds, labels, weights=None, **kw):
    # reference elementwise_metric.cu GammaNLoglik (shape psi = 1)
    p = np.maximum(preds, 1e-16)
    y = np.maximum(labels, 1e-16)
    return _wmean(y / p + np.log(p), labels, weights)


@register_metric("gamma-deviance")
def gamma_deviance(preds, labels, weights=None, **kw):
    p = np.maximum(preds, 1e-16)
    y = np.maximum(labels, 1e-16)
    return _wmean(2 * (np.log(p / y) + y / p - 1), labels, weights)


@register_metric("tweedie-nloglik")
def tweedie_nloglik(preds, labels, weights=None, at: float = 1.5, **kw):
    rho = at
    p = np.maximum(preds, 1e-16)
    a = labels * np.power(p, 1 - rho) / (1 - rho)
    b = np.power(p, 2 - rho) / (2 - rho)
    return _wmean(-a + b, labels, weights)


def _pick_alpha_col(p, alphas, at):
    """For multi-alpha predictions: an explicit `metric@level` selects the
    matching trained column; no explicit level means average across levels."""
    if at is None:
        return p, np.asarray(alphas, np.float64)[None, :]
    a = np.asarray(alphas, np.float64)
    k = int(np.argmin(np.abs(a - at)))
    if abs(a[k] - at) > 1e-6:
        raise ValueError(
            f"metric level {at} was not trained; trained levels: {a.tolist()}")
    return p[:, k], float(a[k])


@register_metric("quantile")
def quantile_loss(preds, labels, weights=None, at=None, alphas=None, **kw):
    """Pinball loss; (R, Q) preds with `alphas` = multi-quantile training
    (quantile_obj.cu: mean over samples x quantile levels, or the requested
    level's column when the metric carries an explicit @level)."""
    p = np.asarray(preds, np.float64)
    if p.ndim == 2 and alphas is not None:
        p, a = _pick_alpha_col(p, alphas, at)
        if p.ndim == 2:
            u = labels[:, None] - p
            return _wmean(np.where(u >= 0, a * u, (a - 1) * u), labels, weights)
        at = a
    at = 0.5 if at is None else at
    u = labels - p
    return _wmean(np.where(u >= 0, at * u, (at - 1) * u), labels, weights)


@register_metric("expectile")
def expectile_loss(preds, labels, weights=None, alphas=None, at=None, **kw):
    """Asymmetric squared loss (elementwise_metric.cu ExpectileError):
    |alpha - I(diff<0)| * diff^2, averaged over samples (x expectiles), or
    the requested level's column under an explicit @level."""
    p = np.asarray(preds, np.float64)
    if p.ndim == 2 and alphas is not None:
        p, a = _pick_alpha_col(p, alphas, at)
        if p.ndim == 2:
            diff = p - labels[:, None]
            return _wmean(np.where(diff >= 0, 1.0 - a, a) * diff ** 2,
                          labels, weights)
        at = a
    at = 0.5 if at is None else at
    diff = p - labels
    return _wmean(np.where(diff >= 0, 1.0 - at, at) * diff ** 2,
                  labels, weights)


@register_metric("pre")
def precision_at(preds, labels, weights=None, group_ptr=None, at: float = 0,
                 **kw):
    """Precision@k (rank_metric.cc EvalPrecision): per group, the label mass
    of the top-k ranked docs over k; group-weighted mean."""
    if group_ptr is None:
        group_ptr = np.array([0, len(labels)])
    k = int(at) if at else 10
    if _use_device_rank(group_ptr, preds, kw):
        from .device_rank import precision_pair

        n, d = precision_pair(preds, labels, group_ptr, weights, k)
        num, den = _reduce_sums(n, d)
        return num / den if den > 0 else 0.0
    n_groups = len(group_ptr) - 1
    vals, ws = [], []
    for g in range(n_groups):
        lo, hi = group_ptr[g], group_ptr[g + 1]
        if hi <= lo:
            continue
        y = labels[lo:hi]
        order = np.argsort(-preds[lo:hi], kind="stable")
        n = min(k, hi - lo)
        wg = 1.0 if weights is None else float(
            weights[g if len(weights) == n_groups else lo])
        vals.append(float(np.sum(y[order[:n]])) * wg / n)
        ws.append(wg)
    s, wsum = _reduce_sums(float(np.sum(vals)), float(np.sum(ws)))
    return s / wsum if wsum > 0 else 0.0


@register_metric("ams")
def ams(preds, labels, weights=None, at: float = 1.0, **kw):
    """Approximate median significance (rank_metric.cc EvalAMS): rank all
    rows by prediction, take the top `ratio` fraction, score
    sqrt(2((s+b+br)ln(1+s/(b+br))-s)) with regularisation br=10."""
    n = len(labels)
    w = _w(labels, weights)
    order = np.argsort(-np.asarray(preds, np.float64), kind="stable")
    ntop = int(at * n) or n
    br = 10.0
    top = order[: min(ntop, n - 1)]
    pos = labels[top] > 0.5
    s_tp = float(np.sum(w[top][pos]))
    b_fp = float(np.sum(w[top][~pos]))
    if ntop >= n:
        # scan variant: best prefix AMS over distinct-threshold cut points
        ps = np.cumsum(np.where(labels[order] > 0.5, w[order], 0.0))
        bs = np.cumsum(np.where(labels[order] > 0.5, 0.0, w[order]))
        sp = np.asarray(preds, np.float64)[order]
        distinct = np.zeros(len(sp), bool)
        if len(sp):
            distinct[:-1] = sp[:-1] != sp[1:]
        cand = np.nonzero(distinct)[0]
        # all-tied shard contributes 0, but must still join the allreduce
        best = (0.0 if len(cand) == 0 else float(np.max(
            np.sqrt(2 * ((ps[cand] + bs[cand] + br)
                         * np.log1p(ps[cand] / (bs[cand] + br)) - ps[cand])))))
        num, den = _reduce_sums(best, 1.0)
        return num / den
    # distributed: AMS needs the global score order; per-rank values are
    # averaged (the top-fraction cut is rank-local, like the reference's
    # rank-local EvalAMS)
    num, den = _reduce_sums(
        float(np.sqrt(2 * ((s_tp + b_fp + br)
                           * np.log1p(s_tp / (b_fp + br)) - s_tp))), 1.0)
    return num / den


@register_metric("merror")
def merror(preds, labels, weights=None, **kw):
    cls = preds if preds.ndim == 1 else np.argmax(preds, axis=1)
    return _wmean((cls != labels).astype(np.float64), labels, weights)


@register_metric("mlogloss")
def mlogloss(preds, labels, weights=None, **kw):
    p = np.clip(np.asarray(preds, np.float64), 1e-16, 1 - 1e-16)
    ll = -np.log(p[np.arange(len(labels)), labels.astype(np.int64)])
    return _wmean(ll, labels, weights)


@register_metric("auc")
def auc(preds, labels, weights=None, group_ptr=None, **kw):
    """Binary ROC-AUC via the rank statistic with exact tie handling
    (reference: src/metric/auc.cc BinaryROCAUC)."""
    s = np.asarray(preds, dtype=np.float64)
    if s.ndim == 2:  # multiclass: 1-vs-rest average (reference MultiClassOVR)
        K = s.shape[1]
        vals = [auc(s[:, k], (labels == k).astype(np.float64), weights) for k in range(K)]
        return float(np.mean(vals))
    y = labels > 0.5
    w = _w(labels, weights)
    order = np.argsort(s, kind="stable")
    ss, yy, ww = s[order], y[order], w[order]
    uniq, first = np.unique(ss, return_index=True)
    grp = np.searchsorted(uniq, ss)
    pos_w = float(np.sum(ww[yy]))
    neg_w = float(np.sum(ww[~yy]))
    # each positive scores (neg weight strictly below) + (tied neg weight)/2
    cw_neg = np.cumsum(ww * (~yy))
    below = np.concatenate([[0.0], cw_neg])[first[grp]]
    ties_neg = np.zeros(len(uniq))
    np.add.at(ties_neg, grp, ww * (~yy))
    score = below + ties_neg[grp] / 2.0
    area = float(np.sum(ww[yy] * score[yy]))
    # distributed: the reference's merge is GlobalRatio(area, fp*tp)
    # (auc.cc:345 + aggregator.h:52) — allreduce BOTH the local pair area
    # and the local pos*neg pair mass, i.e. a pair-count-weighted average
    # of per-rank AUCs; O(local) memory, upstream-identical semantics
    area, pairs = _reduce_sums(area, pos_w * neg_w)
    if pairs == 0:
        return 0.5
    return min(area / pairs, 1.0)


def _pr_area(s, y, w):
    """(PR-AUC, pair mass) of one score/label slice; (0, 0) if degenerate."""
    if len(s) == 0:
        return 0.0, 0.0
    order = np.argsort(-s, kind="stable")
    yy, ww = y[order], w[order]
    tp = np.cumsum(ww * yy)
    fp = np.cumsum(ww * ~yy)
    pos, neg = float(tp[-1]), float(fp[-1])
    if pos <= 0 or neg <= 0:
        return 0.0, 0.0
    precision = tp / np.maximum(tp + fp, 1e-16)
    recall = tp / pos
    return float(np.trapezoid(precision, recall)), pos * neg


@register_metric("aucpr")
def aucpr(preds, labels, weights=None, group_ptr=None, **kw):
    s = np.asarray(preds, dtype=np.float64)
    y = labels > 0.5
    w = _w(labels, weights)
    if group_ptr is not None and len(group_ptr) > 1:
        # ranking variant (auc.cc RankingAUC for the PR curve): weighted
        # mean of per-group PR-AUCs over valid groups,
        # GlobalRatio(sum, valid); weights may be per-group or per-row.
        # Branch on group STRUCTURE, not local group count: a rank whose
        # shard holds a single query group must still contribute per-group
        # partials to the same allreduce as its peers (ADVICE r3).
        n_groups = len(group_ptr) - 1
        group_w = weights is not None and len(weights) == n_groups
        total, valid = 0.0, 0.0
        for g in range(n_groups):
            lo, hi = group_ptr[g], group_ptr[g + 1]
            w_rows = np.ones(hi - lo, np.float64) if group_w else w[lo:hi]
            area, pairs = _pr_area(s[lo:hi], y[lo:hi], w_rows)
            if pairs > 0:
                wg = float(weights[g]) if group_w else 1.0
                total += area * wg
                valid += wg
        num, den = _reduce_sums(total, valid)
        return num / den if den > 0 else 0.0
    # a degenerate shard (empty, or single-class) has zero pair mass and
    # contributes nothing to the merge — but it MUST still enter the
    # allreduce, or the cohort's collectives desynchronize
    local, pairs = _pr_area(s, y, w)
    num, den = _reduce_sums(local * pairs, pairs)
    return num / den if den > 0 else 0.0


@register_metric("aft-nloglik")
def aft_nloglik(preds, labels, weights=None, y_lower=None, y_upper=None,
                dist="normal", sigma=1.0, **kw):
    """(reference: src/metric/survival_metric.cu AFTNegLogLik) — preds are
    exp(margin) (time scale); convert back to margin."""
    import jax.numpy as jnp

    from ..objective.survival import aft_neg_loglik

    if y_lower is None:
        y_lower = labels
        y_upper = labels
    m = np.log(np.maximum(np.asarray(preds, np.float64), 1e-16))
    ll = np.asarray(aft_neg_loglik(jnp.asarray(m, jnp.float32),
                                   jnp.asarray(y_lower, jnp.float32),
                                   jnp.asarray(y_upper, jnp.float32), dist, sigma))
    return _wmean(ll.astype(np.float64), labels, weights)


@register_metric("interval-regression-accuracy")
def interval_accuracy(preds, labels, weights=None, y_lower=None, y_upper=None, **kw):
    """Fraction of predictions inside the label interval
    (reference: survival_metric.cu IntervalRegressionAccuracy)."""
    if y_lower is None:
        y_lower = labels
        y_upper = labels
    p = np.asarray(preds, np.float64)
    ok = (p >= y_lower) & (p <= np.where(np.isfinite(y_upper), y_upper, np.inf))
    return _wmean(ok.astype(np.float64), labels, weights)


@register_metric("cox-nloglik")
def cox_nloglik(preds, labels, weights=None, **kw):
    """Negative partial log-likelihood (reference: rank_metric.cc CoxNLoglik).
    preds are exp(margin) hazard ratios."""
    t = np.abs(labels).astype(np.float64)
    event = labels > 0
    r = np.asarray(preds, np.float64)
    order = np.argsort(t, kind="stable")
    r_s = r[order]
    ev_s = event[order]
    ts = t[order]
    revcum = np.cumsum(r_s[::-1])[::-1]
    g_start = np.searchsorted(ts, ts, side="left")
    risk = revcum[g_start]  # Breslow: tie groups share the denominator
    ll = np.sum(np.log(np.maximum(r_s, 1e-16))[ev_s] - np.log(np.maximum(risk, 1e-16))[ev_s])
    # distributed: risk sets are rank-local (the full ordering would need a
    # gather); partial (sum, events) allreduce matches the objective's
    # per-shard partial-likelihood treatment
    num, den = _reduce_sums(float(-ll), float(ev_s.sum()))
    return num / max(den, 1.0)


def _dcg_at(rel, k, exp_gain=True):
    rel = rel[:k]
    gain = (2.0**rel - 1.0) if exp_gain else rel
    return np.sum(gain / np.log2(np.arange(2, len(rel) + 2)))


@register_metric("ndcg")
def ndcg(preds, labels, weights=None, group_ptr=None, at: float = 0,
         minus: bool = False, **kw):
    """(reference: src/metric/rank_metric.cc NDCG; exp gain by default;
    ``minus`` (the ``ndcg@n-`` suffix) scores all-irrelevant groups 0
    instead of 1 — rank_metric.cc:382)."""
    if group_ptr is None:
        group_ptr = np.array([0, len(labels)])
    k = int(at) if at else None
    if _use_device_rank(group_ptr, preds, kw):
        # segment-vectorized device path (device_rank.py) — no python loop;
        # host loop below stays as the parity oracle
        from .device_rank import ndcg_pair

        n, d = ndcg_pair(preds, labels, group_ptr, weights, k or 0, minus)
        num, den = _reduce_sums(n, d)
        return num / den if den > 0 else 1.0
    vals, ws = [], []
    for g in range(len(group_ptr) - 1):
        lo, hi = group_ptr[g], group_ptr[g + 1]
        if hi <= lo:
            continue
        y = labels[lo:hi]
        s = preds[lo:hi]
        kk = k or (hi - lo)
        order = np.argsort(-s, kind="stable")
        dcg = _dcg_at(y[order], kk)
        idcg = _dcg_at(np.sort(y)[::-1], kk)
        vals.append(dcg / idcg if idcg > 0 else (0.0 if minus else 1.0))
        ws.append(1.0 if weights is None else weights[g if len(weights) == len(group_ptr) - 1 else lo])
    # per-group partials allreduce (rank_metric.cc via GlobalRatio):
    # (sum of weighted group scores, sum of group weights)
    num, den = _reduce_sums(float(np.dot(vals, ws)) if vals else 0.0,
                            float(np.sum(ws)) if ws else 0.0)
    return num / den if den > 0 else 1.0


@register_metric("map")
def map_metric(preds, labels, weights=None, group_ptr=None, at: float = 0,
               minus: bool = False, **kw):
    """(reference: rank_metric.cc MAP; groups without a relevant doc score
    1 by default, 0 under the ``map-`` minus suffix — rank_metric.cc:443)."""
    if group_ptr is None:
        group_ptr = np.array([0, len(labels)])
    k = int(at) if at else None
    if _use_device_rank(group_ptr, preds, kw):
        from .device_rank import map_pair

        n, d = map_pair(preds, labels, group_ptr, weights, k or 0, minus)
        num, den = _reduce_sums(n, d)
        return num / den if den > 0 else 0.0
    vals, ws = [], []
    for g in range(len(group_ptr) - 1):
        lo, hi = group_ptr[g], group_ptr[g + 1]
        if hi <= lo:
            continue
        y = (labels[lo:hi] > 0).astype(np.float64)
        s = preds[lo:hi]
        order = np.argsort(-s, kind="stable")
        yo = y[order][: k or (hi - lo)]
        hits = np.cumsum(yo)
        denom = np.arange(1, len(yo) + 1)
        npos = yo.sum()
        vals.append(float(np.sum(yo * hits / denom) / npos) if npos > 0
                    else (0.0 if minus else 1.0))
        # group weights, like ndcg (rank_metric.cc EvalRank::Eval weights
        # each group's contribution; ADVICE r3: map previously ignored them)
        ws.append(1.0 if weights is None
                  else weights[g if len(weights) == len(group_ptr) - 1 else lo])
    num, den = _reduce_sums(float(np.dot(vals, ws)) if vals else 0.0,
                            float(np.sum(ws)) if ws else 0.0)
    return num / den if den > 0 else 0.0
