"""xgboost_tpu: a TPU-native gradient-boosted decision tree framework.

A from-scratch re-design of dmlc/xgboost for TPU hardware: quantile binning,
histogram construction, split evaluation, and row partitioning run as XLA/MXU
array programs (Pallas kernels on the hot path) over device-resident Ellpack
pages; distributed training is jax.lax.psum over a jax.sharding.Mesh in place
of NCCL/rabit allreduce.  The public API mirrors the reference Python package
(python-package/xgboost): DMatrix/QuantileDMatrix, train/cv, Booster,
sklearn wrappers, callbacks.
"""
from __future__ import annotations

__version__ = "0.1.0"

# arm the runtime lock-order witness (no-op unless XGBOOST_TPU_LOCKDEP=1)
# before any sibling import creates a lock — module-level locks in
# telemetry/reliability/data are only witnessed if the factories are
# patched first (docs/reliability.md "Lockdep witness")
from .reliability import lockdep as _lockdep  # noqa: E402,F401

from .config import config_context, get_config, set_config
from .core import Booster
from .data.dmatrix import DMatrix, MetaInfo, QuantileDMatrix
from .data.extmem import (DataIter, ExtMemConfig, ExtMemQuantileDMatrix,
                          SparsePageDMatrix)
from .data.ellpack import EllpackPage
from .data.quantile import HistogramCuts
from .training import cv, train
from . import collective, elastic, reliability, telemetry, tracker
from .elastic import ElasticConfig, ShardMap
from .reliability import CheckpointCallback
from .telemetry import TelemetryCallback
from .callback import (
    EarlyStopping,
    EvaluationMonitor,
    LearningRateScheduler,
    TrainingCallback,
    TrainingCheckPoint,
)

__all__ = [
    "Booster",
    "DMatrix",
    "QuantileDMatrix",
    "DataIter",
    "ExtMemConfig",
    "ExtMemQuantileDMatrix",
    "SparsePageDMatrix",
    "MetaInfo",
    "EllpackPage",
    "HistogramCuts",
    "train",
    "cv",
    "config_context",
    "set_config",
    "get_config",
    "TrainingCallback",
    "EarlyStopping",
    "EvaluationMonitor",
    "LearningRateScheduler",
    "TrainingCheckPoint",
    "TelemetryCallback",
    "CheckpointCallback",
    "ElasticConfig",
    "ShardMap",
    "elastic",
    "collective",
    "reliability",
    "telemetry",
    "tracker",
    "serving",
    "lifecycle",
    "online",
    "train_distributed",
    "plot_importance",
    "plot_tree",
    "to_graphviz",
    "XGBModel",
    "XGBClassifier",
    "XGBRegressor",
    "XGBRanker",
    "XGBRFClassifier",
    "XGBRFRegressor",
]


def __getattr__(name):  # lazy heavy imports
    if name in ("XGBModel", "XGBClassifier", "XGBRegressor", "XGBRanker",
                "XGBRFClassifier", "XGBRFRegressor"):
        from . import sklearn as _sk

        return getattr(_sk, name)
    if name in ("plot_importance", "plot_tree", "to_graphviz"):
        from . import plotting as _pl

        return getattr(_pl, name)
    if name in ("serving", "lifecycle", "online"):
        # importlib, not `from . import <pkg>`: the fromlist resolution
        # getattr's the package for the name and would re-enter this hook
        import importlib

        return importlib.import_module("." + name, __name__)
    if name == "train_distributed":
        from .distributed import train_distributed

        return train_distributed
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
