"""RegTree: the persisted tree model, struct-of-arrays.

Reference: include/xgboost/tree_model.h:81 (RegTree), src/tree/tree_model.cc
(JSON/UBJSON schema + text/graphviz dump).  The reference's packed 32-byte Node
is already array-shaped; here the arrays are first-class numpy columns in the
reference's JSON field layout (left_children, right_children, parents,
split_indices, split_conditions, default_left, base_weights, loss_changes,
sum_hessian), so ``save_model`` emits the same schema the reference reads.
Node numbering is creation order (root 0, children appended on split in
level order), matching the depthwise updater.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np


@dataclasses.dataclass
class RegTree:
    left_children: np.ndarray  # (n,) int32, -1 for leaf
    right_children: np.ndarray
    parents: np.ndarray
    split_indices: np.ndarray  # int32 feature, 0 for leaf
    split_conditions: np.ndarray  # f32 threshold; LEAF VALUE for leaves
    default_left: np.ndarray  # bool
    base_weights: np.ndarray  # f32
    loss_changes: np.ndarray  # f32
    sum_hessian: np.ndarray  # f32
    split_bins: Optional[np.ndarray] = None  # int32, internal (binned predict)
    split_type: Optional[np.ndarray] = None  # 0 numeric, 1 categorical
    categories: Optional[dict] = None  # nid -> int32 array of cats routed RIGHT
    # vector leaves (multi_target_tree_model.h): (n, K) per-node value/weight;
    # None for scalar trees.  Leaves' split_conditions are 0 when set.
    leaf_vector: Optional[np.ndarray] = None
    base_weight_vec: Optional[np.ndarray] = None
    # identity of the HistogramCuts the split_bins index (not serialized);
    # binned predict routes must verify it matches the resident page's cuts —
    # continued training on a different DMatrix would otherwise mis-route
    cuts_token: Optional[int] = None

    @property
    def n_nodes(self) -> int:
        return len(self.left_children)

    def is_leaf(self, nid: int) -> bool:
        return self.left_children[nid] == -1

    @property
    def num_leaves(self) -> int:
        return int(np.sum(self.left_children == -1))

    @property
    def max_depth(self) -> int:
        depth = np.zeros(self.n_nodes, dtype=np.int32)
        for i in range(1, self.n_nodes):
            depth[i] = depth[self.parents[i]] + 1
        return int(depth.max()) if self.n_nodes else 0

    # ---- construction from the grower's heap layout ----
    @staticmethod
    def from_grown(gt) -> "RegTree":
        """Compact a tree/grow.py GrownTree (heap arrays) into creation order."""
        heap_ids: List[int] = [0]
        id_of = {0: 0}
        # level-order walk over real nodes, children appended in split order
        order: List[int] = []
        queue = [0]
        while queue:
            h = queue.pop(0)
            order.append(h)
            if gt.feat[h] >= 0 and not gt.is_leaf[h]:
                for c in (2 * h + 1, 2 * h + 2):
                    id_of[c] = len(order) + len(queue)
                    queue.append(c)
        n = len(order)
        t = RegTree(
            left_children=np.full(n, -1, np.int32),
            right_children=np.full(n, -1, np.int32),
            parents=np.full(n, -1, np.int32),
            split_indices=np.zeros(n, np.int32),
            split_conditions=np.zeros(n, np.float32),
            default_left=np.zeros(n, bool),
            base_weights=np.zeros(n, np.float32),
            loss_changes=np.zeros(n, np.float32),
            sum_hessian=np.zeros(n, np.float32),
            split_bins=np.zeros(n, np.int32),
            split_type=np.zeros(n, np.int32),
            categories={},
        )
        has_cat = getattr(gt, "is_cat", None) is not None
        for h in order:
            i = id_of[h]
            t.base_weights[i] = gt.base_weight[h]
            t.sum_hessian[i] = gt.sum_hess[h]
            t.default_left[i] = gt.dleft[h]
            if gt.feat[h] >= 0 and not gt.is_leaf[h]:
                t.left_children[i] = id_of[2 * h + 1]
                t.right_children[i] = id_of[2 * h + 2]
                t.parents[id_of[2 * h + 1]] = i
                t.parents[id_of[2 * h + 2]] = i
                t.split_indices[i] = gt.feat[h]
                t.split_conditions[i] = gt.thr[h]
                t.split_bins[i] = gt.sbin[h]
                t.loss_changes[i] = gt.gain[h]
                if has_cat and gt.is_cat[h]:
                    t.split_type[i] = 1
                    t.categories[i] = np.nonzero(gt.cat_set[h])[0].astype(np.int32)
            else:
                t.split_conditions[i] = gt.leaf_val[h]
        return t

    @property
    def has_categorical(self) -> bool:
        return bool(self.categories)

    @property
    def n_targets(self) -> int:
        return 1 if self.leaf_vector is None else self.leaf_vector.shape[1]

    # ---- construction from the vector-leaf grower ----
    @staticmethod
    def from_grown_multi(gt, n_targets: int) -> "RegTree":
        """Compact a grow_multi.GrownMultiTree (heap arrays, K-wide values)."""
        order: list = []
        id_of = {0: 0}
        queue = [0]
        while queue:
            h = queue.pop(0)
            order.append(h)
            if gt.feat[h] >= 0 and not gt.is_leaf[h]:
                for c in (2 * h + 1, 2 * h + 2):
                    id_of[c] = len(order) + len(queue)
                    queue.append(c)
        n = len(order)
        K = n_targets
        t = RegTree(
            left_children=np.full(n, -1, np.int32),
            right_children=np.full(n, -1, np.int32),
            parents=np.full(n, -1, np.int32),
            split_indices=np.zeros(n, np.int32),
            split_conditions=np.zeros(n, np.float32),
            default_left=np.zeros(n, bool),
            base_weights=np.zeros(n, np.float32),
            loss_changes=np.zeros(n, np.float32),
            sum_hessian=np.zeros(n, np.float32),
            split_bins=np.zeros(n, np.int32),
            split_type=np.zeros(n, np.int32),
            categories={},
            leaf_vector=np.zeros((n, K), np.float32),
            base_weight_vec=np.zeros((n, K), np.float32),
        )
        leaf_rank = 0
        for h in order:
            i = id_of[h]
            t.base_weight_vec[i] = gt.base_weight[h]
            t.base_weights[i] = gt.base_weight[h][0]
            t.sum_hessian[i] = gt.sum_hess[h]
            t.default_left[i] = gt.dleft[h]
            if gt.feat[h] >= 0 and not gt.is_leaf[h]:
                t.left_children[i] = id_of[2 * h + 1]
                t.right_children[i] = id_of[2 * h + 2]
                t.parents[id_of[2 * h + 1]] = i
                t.parents[id_of[2 * h + 2]] = i
                t.split_indices[i] = gt.feat[h]
                t.split_conditions[i] = gt.thr[h]
                t.split_bins[i] = gt.sbin[h]
                t.loss_changes[i] = gt.gain[h]
            else:
                t.leaf_vector[i] = gt.leaf_val[h]
        # reference invariant (multi_target_tree_model.cc SetLeaves): a
        # leaf's right_children slot holds its index into leaf_weights
        for i in range(n):
            if t.left_children[i] == -1:
                t.right_children[i] = leaf_rank
                leaf_rank += 1
        return t

    # ---- padded arrays for the vectorized predictor ----
    def padded_arrays(self, width: int):
        n = self.n_nodes
        assert width >= n

        def pad(a, fill=0):
            out = np.full(width, fill, dtype=a.dtype)
            out[:n] = a
            return out

        feat = np.where(self.left_children == -1, -1, self.split_indices).astype(np.int32)
        value = np.where(self.left_children == -1, self.split_conditions, 0.0).astype(np.float32)
        st = (self.split_type if self.split_type is not None
              else np.zeros(n, np.int32))
        sbin = (self.split_bins if self.split_bins is not None
                else np.zeros(n, np.int32))
        out = dict(
            feat=pad(feat, -1),
            thr=pad(np.where(self.left_children == -1, np.float32(0), self.split_conditions)),
            dleft=pad(self.default_left.astype(np.bool_)),
            left=pad(self.left_children, -1),
            right=pad(self.right_children, -1),
            value=pad(value),
            is_cat=pad((st == 1)),
            sbin=pad(sbin.astype(np.int32)),
        )
        if self.leaf_vector is not None:
            vv = np.zeros((width, self.n_targets), np.float32)
            vv[:n] = self.leaf_vector
            out["value_vec"] = vv
        return out

    def cat_matrix(self, width: int, n_cats: int) -> np.ndarray:
        """(width, n_cats) bool membership matrix of right-routed categories."""
        out = np.zeros((width, max(n_cats, 1)), dtype=bool)
        if self.categories:
            for nid, cats in self.categories.items():
                cats = cats[cats < n_cats]
                out[nid, cats] = True
        return out

    @property
    def max_category(self) -> int:
        if not self.categories:
            return -1
        return max((int(c.max()) for c in self.categories.values() if len(c)), default=-1)

    # ---- xgboost JSON schema (tree_model.cc SaveModel) ----
    def to_json_dict(self, n_features: int, tree_id: int = 0) -> dict:
        n = self.n_nodes
        st = self.split_type if self.split_type is not None else np.zeros(n, np.int32)
        cat_nodes, cat_segs, cat_sizes, cat_flat = [], [], [], []
        if self.categories:
            for nid in sorted(self.categories):
                cats = self.categories[nid]
                cat_nodes.append(int(nid))
                cat_segs.append(len(cat_flat))
                cat_sizes.append(len(cats))
                cat_flat.extend(int(c) for c in cats)
        out = {
            # GBTreeModel::LoadModel CHECKs trees[t]["id"] == t (gbtree_model.cc)
            "id": int(tree_id),
            "tree_param": {
                "num_nodes": str(n),
                "num_feature": str(n_features),
                "size_leaf_vector": str(self.n_targets),
            },
            "left_children": self.left_children.tolist(),
            "right_children": self.right_children.tolist(),
            "parents": self.parents.tolist(),
            "split_indices": self.split_indices.tolist(),
            "split_conditions": [float(x) for x in self.split_conditions],
            "split_type": st.tolist(),
            "default_left": self.default_left.astype(np.int32).tolist(),
            "categories": cat_flat,
            "categories_nodes": cat_nodes,
            "categories_segments": cat_segs,
            "categories_sizes": cat_sizes,
            "base_weights": [float(x) for x in self.base_weights],
            "loss_changes": [float(x) for x in self.loss_changes],
            "sum_hessian": [float(x) for x in self.sum_hessian],
        }
        if self.leaf_vector is not None:
            # vector-leaf schema (multi_target_tree_model.cc SaveModel):
            # base_weights is n*K row-major; leaf_weights is n_leaves*K with
            # each leaf's index stored in its right_children slot (the
            # reference reuses the right child as the leaf-weight mapping,
            # SetLeaves / LeafValue's lidx = right_[nidx])
            out["base_weights"] = [float(x)
                                   for x in self.base_weight_vec.reshape(-1)]
            leaf_ids = np.nonzero(self.left_children == -1)[0]
            n_leaves = len(leaf_ids)
            lw = np.zeros((n_leaves, self.n_targets), np.float32)
            for nid in leaf_ids:
                lw[int(self.right_children[nid])] = self.leaf_vector[nid]
            out["leaf_weights"] = [float(x) for x in lw.reshape(-1)]
        return out

    @staticmethod
    def from_json_dict(d: dict) -> "RegTree":
        cats = {}
        flat = d.get("categories", [])
        for nid, seg, size in zip(d.get("categories_nodes", []),
                                  d.get("categories_segments", []),
                                  d.get("categories_sizes", [])):
            cats[int(nid)] = np.asarray(flat[seg : seg + size], np.int32)
        n = len(d["left_children"])
        K = int(d.get("tree_param", {}).get("size_leaf_vector", "1") or 1)
        leaf_vector = base_weight_vec = None
        base_weights = np.asarray(
            d.get("base_weights", np.zeros(n)), np.float32)
        if K > 1:
            base_weight_vec = base_weights.reshape(n, K)
            base_weights = base_weight_vec[:, 0]
            left = np.asarray(d["left_children"], np.int32)
            right = np.asarray(d["right_children"], np.int32)
            leaf_ids = np.nonzero(left == -1)[0]
            lw = np.asarray(d.get("leaf_weights", []), np.float32).reshape(
                len(leaf_ids), K)
            leaf_vector = np.zeros((n, K), np.float32)
            # right_children holds each leaf's index into leaf_weights
            # (multi_target_tree_model.cc LeafValue: lidx = right_[nidx])
            for nid in leaf_ids:
                leaf_vector[nid] = lw[int(right[nid])]
        return RegTree(
            leaf_vector=leaf_vector,
            base_weight_vec=base_weight_vec,
            categories=cats or None,
            left_children=np.asarray(d["left_children"], np.int32),
            right_children=np.asarray(d["right_children"], np.int32),
            parents=np.asarray(d["parents"], np.int32),
            split_indices=np.asarray(d["split_indices"], np.int32),
            split_conditions=np.asarray(d["split_conditions"], np.float32),
            default_left=np.asarray(d["default_left"]).astype(bool),
            base_weights=base_weights,
            loss_changes=np.asarray(d.get("loss_changes", np.zeros(len(d["left_children"]))), np.float32),
            sum_hessian=np.asarray(d.get("sum_hessian", np.zeros(len(d["left_children"]))), np.float32),
            split_type=np.asarray(d.get("split_type", np.zeros(len(d["left_children"])))).astype(np.int32),
        )

    # ---- text dump (tree_model.cc DumpModel, dump_format="text") ----
    def dump_text(self, feature_names: Optional[List[str]] = None, with_stats: bool = False) -> str:
        lines: List[str] = []

        def fname(fid: int) -> str:
            return feature_names[fid] if feature_names else f"f{fid}"

        def rec(nid: int, depth: int):
            indent = "\t" * depth
            if self.is_leaf(nid):
                s = f"{indent}{nid}:leaf={self.split_conditions[nid]:.6g}"
                if with_stats:
                    s += f",cover={self.sum_hessian[nid]:.6g}"
            elif self.categories and nid in self.categories:
                cats = ",".join(str(c) for c in self.categories[nid])
                s = (
                    f"{indent}{nid}:[{fname(self.split_indices[nid])}:{{{cats}}}] "
                    f"yes={self.left_children[nid]},"
                    f"no={self.right_children[nid]},missing="
                    f"{self.left_children[nid] if self.default_left[nid] else self.right_children[nid]}"
                )
            else:
                s = (
                    f"{indent}{nid}:[{fname(self.split_indices[nid])}<"
                    f"{self.split_conditions[nid]:.6g}] yes={self.left_children[nid]},"
                    f"no={self.right_children[nid]},missing="
                    f"{self.left_children[nid] if self.default_left[nid] else self.right_children[nid]}"
                )
                if with_stats:
                    s += f",gain={self.loss_changes[nid]:.6g},cover={self.sum_hessian[nid]:.6g}"
            lines.append(s)
            if not self.is_leaf(nid):
                rec(self.left_children[nid], depth + 1)
                rec(self.right_children[nid], depth + 1)

        rec(0, 0)
        return "\n".join(lines) + "\n"

    def dump_json(self, feature_names: Optional[List[str]] = None,
                  with_stats: bool = False) -> str:
        """Reference dump format (tree_model.cc JsonGenerator): nested
        nodeid/split/children objects — distinct from the model schema."""
        import json as _json

        def fname(fid: int) -> str:
            return feature_names[fid] if feature_names else f"f{fid}"

        def rec(nid: int, depth: int) -> dict:
            if self.is_leaf(nid):
                d = {"nodeid": int(nid),
                     "leaf": float(self.split_conditions[nid])}
                if with_stats:
                    d["cover"] = float(self.sum_hessian[nid])
                return d
            yes, no = int(self.left_children[nid]), int(self.right_children[nid])
            d = {"nodeid": int(nid), "depth": int(depth),
                 "split": fname(int(self.split_indices[nid]))}
            if self.categories and nid in self.categories:
                d["split_condition"] = [int(c) for c in self.categories[nid]]
            else:
                d["split_condition"] = float(self.split_conditions[nid])
            d.update(yes=yes, no=no,
                     missing=yes if self.default_left[nid] else no)
            if with_stats:
                d.update(gain=float(self.loss_changes[nid]),
                         cover=float(self.sum_hessian[nid]))
            d["children"] = [rec(yes, depth + 1), rec(no, depth + 1)]
            return d

        return _json.dumps(rec(0, 0))
