"""Non-growing tree updaters: prune / refresh / sync, and the
``process_type="update"`` flow that re-processes an existing model's trees.

Reference: src/tree/updater_prune.cc (TreePruner: recursively collapse
splits whose recorded loss_chg is below gamma), updater_refresh.cc
(TreeRefresher: recompute per-node stats and leaf values from the current
gradients without touching the structure), updater_sync.cc (TreeSyncher:
broadcast trees from rank 0), and gbtree.cc InitUpdater / the
process_type=update path that replaces trees one boosting round at a time.

All three operate on the host RegTree arrays — tree surgery is pointer
work, not device math; the only data-sized step (routing rows for refresh)
is vectorized numpy over the raw matrix.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .tree import RegTree


def _route_masks(tree: RegTree, X: np.ndarray) -> np.ndarray:
    """(n_nodes, R) bool membership: which rows reach each node."""
    R = X.shape[0]
    n = tree.n_nodes
    masks = np.zeros((n, R), dtype=bool)
    masks[0] = True
    st = (tree.split_type if tree.split_type is not None
          else np.zeros(n, np.int32))
    for nid in range(n):
        l, r = tree.left_children[nid], tree.right_children[nid]
        if l == -1:
            continue
        x = X[:, tree.split_indices[nid]]
        nanmask = np.isnan(x)
        if st[nid] == 1 and tree.categories and nid in tree.categories:
            cats = set(int(c) for c in tree.categories[nid])
            code = np.nan_to_num(x, nan=-1.0).astype(np.int64)
            goleft = ~np.isin(code, list(cats))
        else:
            goleft = x < tree.split_conditions[nid]
        goleft = np.where(nanmask, bool(tree.default_left[nid]), goleft)
        masks[l] = masks[nid] & goleft
        masks[r] = masks[nid] & ~goleft
    return masks


def refresh_tree(tree: RegTree, X: np.ndarray, grad: np.ndarray,
                 hess: np.ndarray, *, eta: float, lambda_: float,
                 alpha: float = 0.0, refresh_leaf: bool = True,
                 reduce=None) -> RegTree:
    """Recompute stats (sum_hessian, base_weights, loss gains) and — when
    refresh_leaf — leaf values from the given gradients, keeping the
    structure (updater_refresh.cc TreeRefresher::Update).

    ``reduce``: optional allreduce over per-node (G, H) partials — the
    reference allreduces stats before computing weights so distributed
    refresh agrees on every rank (updater_refresh.cc:102)."""
    masks = _route_masks(tree, X)
    G = masks @ grad.astype(np.float64)
    H = masks @ hess.astype(np.float64)
    if reduce is not None:
        G = reduce(G)
        H = reduce(H)

    def thr_l1(g):
        return np.sign(g) * np.maximum(np.abs(g) - alpha, 0.0)

    w = -thr_l1(G) / (H + lambda_)
    tree.sum_hessian[:] = H.astype(np.float32)
    tree.base_weights[:] = w.astype(np.float32)
    for nid in range(tree.n_nodes):
        l, r = tree.left_children[nid], tree.right_children[nid]
        if l == -1:
            if refresh_leaf:
                tree.split_conditions[nid] = np.float32(eta * w[nid])
        else:
            gain = (thr_l1(G[l]) ** 2 / (H[l] + lambda_)
                    + thr_l1(G[r]) ** 2 / (H[r] + lambda_)
                    - thr_l1(G[nid]) ** 2 / (H[nid] + lambda_))
            tree.loss_changes[nid] = np.float32(gain)
    return tree


def prune_tree(tree: RegTree, *, gamma: float, eta: float,
               max_depth: int = 0) -> Tuple[RegTree, int]:
    """Collapse splits with loss_chg < gamma (and beyond max_depth when
    set), bottom-up recursively; returns (compacted tree, n_pruned)
    (updater_prune.cc TreePruner::DoPrune/TryPruneLeaf)."""
    n = tree.n_nodes
    left = tree.left_children.copy()
    right = tree.right_children.copy()
    depth = np.zeros(n, np.int32)
    for i in range(1, n):
        depth[i] = depth[tree.parents[i]] + 1
    is_leaf = left == -1
    pruned = 0
    changed = True
    while changed:
        changed = False
        for nid in range(n - 1, -1, -1):
            l, r = left[nid], right[nid]
            if l == -1:
                continue
            if is_leaf[l] and is_leaf[r]:
                too_deep = max_depth > 0 and depth[nid] >= max_depth
                if tree.loss_changes[nid] < gamma or too_deep:
                    # collapse: this node becomes a leaf with its own weight
                    left[nid] = -1
                    right[nid] = -1
                    is_leaf[nid] = True
                    tree.split_conditions[nid] = np.float32(
                        eta * tree.base_weights[nid])
                    pruned += 1
                    changed = True
    if pruned == 0:
        return tree, 0
    # compact away unreachable nodes (renumber in DFS creation order)
    remap = {}
    order = []

    def rec(nid):
        remap[nid] = len(order)
        order.append(nid)
        if left[nid] != -1:
            rec(left[nid])
            rec(right[nid])

    rec(0)
    m = len(order)
    out = RegTree(
        left_children=np.asarray(
            [remap[left[i]] if left[i] != -1 else -1 for i in order], np.int32),
        right_children=np.asarray(
            [remap[right[i]] if left[i] != -1 else -1 for i in order], np.int32),
        parents=np.asarray(
            [remap[tree.parents[i]] if i != 0 else -1 for i in order], np.int32),
        split_indices=np.asarray(
            [tree.split_indices[i] if left[i] != -1 else 0 for i in order],
            np.int32),
        split_conditions=tree.split_conditions[order].astype(np.float32),
        default_left=tree.default_left[order].astype(bool),
        base_weights=tree.base_weights[order].astype(np.float32),
        loss_changes=np.asarray(
            [tree.loss_changes[i] if left[i] != -1 else 0.0 for i in order],
            np.float32),
        sum_hessian=tree.sum_hessian[order].astype(np.float32),
        # preserve None: exact-grown trees deliberately carry no split_bins
        # so binned predict paths fail loudly instead of mis-routing
        split_bins=(tree.split_bins[order].astype(np.int32)
                    if tree.split_bins is not None else None),
        cuts_token=tree.cuts_token,
        split_type=(tree.split_type[order].astype(np.int32)
                    if tree.split_type is not None else np.zeros(m, np.int32)),
        categories={remap[k]: v for k, v in (tree.categories or {}).items()
                    if k in remap and left[k] != -1} or {},
    )
    return out, pruned


def sync_trees(trees, tree_info, tree_weights):
    """Broadcast the model from rank 0 (updater_sync.cc TreeSyncher) —
    identity when not distributed."""
    from .. import collective

    if not collective.is_distributed():
        return trees, tree_info, tree_weights
    payload = collective.broadcast(
        ([t.to_json_dict(0, i) for i, t in enumerate(trees)],
         list(tree_info), list(tree_weights)),
        0)
    tdicts, info, wts = payload
    return [RegTree.from_json_dict(d) for d in tdicts], info, wts
