"""gblinear: the linear booster.

Reference: src/gbm/gblinear.cc + src/linear/ (coordinate descent
updater_coordinate.cc:100, parallel 'shotgun' updater_shotgun.cc:96, GPU
updater_gpu_coordinate.cu:247).  The TPU-native updater is the shotgun shape —
all coordinates updated from one pair of MXU matmuls per round:

    num_j   = sum_r g_r x_rj           (X^T g)
    denom_j = sum_r h_r x_rj^2         (X^T diag(h) X, diagonal only)
    dw_j    = -soft_threshold(num_j + lambda w_j, alpha) / (denom_j + lambda)

which is the reference's CoordinateDelta applied to every feature at the
current round's gradients (parallel coordinate descent).  Fully-parallel
updates can overshoot on correlated features, so ``coord_descent`` (cyclic,
gradients refreshed after every coordinate via lax.scan — bitwise the
reference semantics) is the default; ``shotgun`` applies a 1/sqrt(F) damping
to stay stable.

Missing values are zeros for the linear model, matching the reference (only
stored sparse entries contribute).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _soft_threshold(x, alpha):
    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - alpha, 0.0)


@functools.partial(jax.jit, static_argnames=("updater",))
def linear_update(X, gpair, weights, bias, *, eta: float, lambda_: float,
                  alpha: float, updater: str = "shotgun"):
    """One boosting round of the linear model for one output group.

    X : (R, F) f32 with NaN already zeroed; gpair (R, 2); weights (F,), bias ().
    Returns (new_weights, new_bias).
    """
    g, h = gpair[:, 0], gpair[:, 1]
    # bias first (reference: updater bias update before features)
    db = -jnp.sum(g) / jnp.maximum(jnp.sum(h), 1e-6) * eta
    g = g + h * db  # refresh gradients for the bias shift

    if updater == "coord_descent":
        def body(carry, j):
            w, g = carry
            xj = X[:, j]
            num = jnp.dot(xj, g) + lambda_ * w[j]
            den = jnp.dot(xj * xj, h) + lambda_
            dw = -_soft_threshold(num, alpha) / den * eta
            g = g + h * xj * dw
            return (w.at[j].add(dw), g), None

        (w_new, _), _ = lax.scan(body, (weights, g), jnp.arange(X.shape[1]))
    else:  # shotgun: all coordinates in parallel (two MXU reductions)
        num = X.T @ g + lambda_ * weights
        den = (X * X).T @ h + lambda_
        damp = 1.0 / jnp.sqrt(jnp.float32(X.shape[1]))
        dw = -_soft_threshold(num, alpha) / den * eta * damp
        w_new = weights + dw
    return w_new, bias + db


@jax.jit
def linear_predict(X, weights, bias):
    """margin (R, K) = X @ W + b (NaN treated as 0)."""
    Xz = jnp.nan_to_num(X, nan=0.0)
    return Xz @ weights + bias[None, :]
