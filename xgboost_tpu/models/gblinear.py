"""gblinear: the linear booster.

Reference: src/gbm/gblinear.cc + src/linear/ (coordinate descent
updater_coordinate.cc:100, parallel 'shotgun' updater_shotgun.cc:96, GPU
updater_gpu_coordinate.cu:247) with the feature-selector family from
src/linear/coordinate_common.h (cyclic / shuffle / random selectors).

Two updaters:

``coord_descent``
    Coordinate descent: every feature updated with the gradient refreshed
    after each coordinate via ``lax.scan`` — bitwise the reference
    semantics.  (Default, as in the reference.)  Its default selector is
    ``cyclic`` (index order), but like the reference it honors any
    implemented ``feature_selector``.

``shotgun``
    The reference's shotgun updater runs the same CoordinateDelta updates
    feature-parallel over OpenMP *without locks* — its output is racy and
    run-dependent by design (Bradley et al., the "shotgun" paper).  Under
    this repo's bitwise determinism contract we implement its
    deterministic equivalent: the identical update sequence in the
    selector-chosen feature order with per-coordinate gradient refresh —
    exactly the reference's shotgun at ``nthread=1``, reproducible at any
    thread count.  The ``feature_selector`` param picks the order:

    - ``cyclic``  : 0, 1, ..., F-1 (shotgun output == coord_descent);
    - ``shuffle`` : a fresh deterministic permutation every round (the
      reference's shotgun default), seeded by ``seed`` + round index;
    - ``random``  : sample F coordinates WITH replacement per round
      (coordinate_common.h RandomFeatureSelector);
    - ``thrifty`` : rank features once per round by the magnitude of their
      univariate weight change computed from the ROUND-START gradients
      (ThriftyFeatureSelector::Setup runs before the bias update), visit
      the ``top_k`` largest in decreasing order (0 = all);
    - ``greedy``  : interleaved select-and-update — at each of ``top_k``
      steps recompute every coordinate's weight delta against the CURRENT
      refreshed gradient, apply the largest-magnitude one
      (GreedyFeatureSelector::NextFeature; ties resolve to the lowest
      feature index, and selection stops contributing once every remaining
      delta is exactly zero, as in the reference's ``dw > best`` scan).

    ``greedy`` and ``thrifty`` are gain-ranked (coordinate_common.h), so
    their visit order depends on the gradients: ``thrifty`` goes through
    :func:`thrifty_order` + :func:`linear_update`, ``greedy`` through
    :func:`linear_update_greedy` (selection and update are one chain —
    replaying a pre-computed order against re-derived deltas could drift
    in the last ulp on near-ties).  Both are bitwise-deterministic for a
    given (data, params, round).

Missing values are zeros for the linear model, matching the reference (only
stored sparse entries contribute).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

SELECTORS = ("cyclic", "shuffle", "random", "greedy", "thrifty")


def _soft_threshold(x, alpha):
    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - alpha, 0.0)


def selector_order(selector: str, n_features: int, round_idx: int,
                   seed: int) -> np.ndarray:
    """The coordinate visit order for one boosting round (host-side,
    deterministic): the linear-updater analogue of coordinate_common.h's
    FeatureSelector::NextFeature loop.  Same (selector, seed, round) ->
    same order on every host, so trained models stay bitwise-reproducible.
    """
    if selector not in SELECTORS:
        raise ValueError(
            f"unknown feature_selector {selector!r}; expected one of "
            f"{SELECTORS}")
    if selector in ("greedy", "thrifty"):
        raise ValueError(
            f"feature_selector={selector!r} is gain-ranked — its order "
            "depends on the gradients, not just (round, seed); use "
            "thrifty_order() / linear_update_greedy()")
    if selector == "cyclic":
        return np.arange(n_features, dtype=np.int32)
    rng = np.random.default_rng(
        np.random.SeedSequence([seed & 0x7FFFFFFF, round_idx]))
    if selector == "shuffle":
        return rng.permutation(n_features).astype(np.int32)
    return rng.integers(0, n_features, size=n_features).astype(np.int32)


def effective_top_k(top_k: int, n_features: int) -> int:
    """coordinate_common.h: ``top_k == 0`` means every feature."""
    k = int(top_k)
    return n_features if k <= 0 else min(k, n_features)


def thrifty_order(Xz, gpair, weights, *, top_k: int, alpha: float,
                  lambda_: float) -> np.ndarray:
    """ThriftyFeatureSelector: rank features by |univariate weight change|
    against the ROUND-START gradients (reference Setup runs before the bias
    update), keep the ``top_k`` largest in decreasing order.

    Host-side float64 (the reference accumulates sums in double); stable
    sort so exact-magnitude ties resolve by feature index, deterministically
    on every host.  Returns an int32 order for :func:`linear_update`.
    """
    Xh = np.asarray(Xz, np.float64)
    g = np.asarray(gpair[:, 0], np.float64)
    h = np.asarray(gpair[:, 1], np.float64)
    w = np.asarray(weights, np.float64)
    num = Xh.T @ g + lambda_ * w
    den = (Xh * Xh).T @ h + lambda_
    dw = np.sign(num) * np.maximum(np.abs(num) - alpha, 0.0) / den
    k = effective_top_k(top_k, Xh.shape[1])
    # stable sort on -|dw|: equal magnitudes keep ascending feature order
    return np.argsort(-np.abs(dw), kind="stable")[:k].astype(np.int32)


@functools.partial(jax.jit, static_argnames=("steps",))
def linear_update_greedy(X, gpair, weights, bias, *, steps: int, eta: float,
                         lambda_: float, alpha: float):
    """One boosting round with the greedy selector: bias first, then
    ``steps`` rounds of pick-the-largest-|delta| coordinate against the
    CURRENT gradient, update it, refresh.  Selection and update are one
    chain (GreedyFeatureSelector interleaves NextFeature with
    UpdateFeature), so this returns the final ``(weights, bias, order)``
    directly; ``order`` holds -1 at steps where every remaining delta was
    exactly zero (the reference's ``dw > best`` scan selects nothing and
    the round ends early).
    """
    g, h = gpair[:, 0], gpair[:, 1]
    db = -jnp.sum(g) / jnp.maximum(jnp.sum(h), 1e-6) * eta
    g = g + h * db
    den = jnp.sum(X * X * h[:, None], axis=0) + lambda_  # h fixed all round

    def body(carry, _):
        w, g, used = carry
        num = X.T @ g + lambda_ * w
        dwv = -_soft_threshold(num, alpha) / den * eta
        mag = jnp.where(used, 0.0, jnp.abs(dwv))
        j = jnp.argmax(mag)  # first occurrence wins ties -> lowest index
        live = mag[j] > 0
        dw = jnp.where(live, dwv[j], 0.0)
        g = g + h * X[:, j] * dw
        w = w.at[j].add(dw)
        used = used.at[j].set(True)
        return (w, g, used), jnp.where(live, j.astype(jnp.int32),
                                       jnp.int32(-1))

    used0 = jnp.zeros(X.shape[1], bool)
    (w_new, _, _), order = lax.scan(body, (weights, g, used0), None,
                                    length=steps)
    return w_new, bias + db, order


@jax.jit
def linear_update(X, gpair, weights, bias, order, *, eta: float,
                  lambda_: float, alpha: float):
    """One boosting round of the linear model for one output group.

    X : (R, F) f32 with NaN already zeroed; gpair (R, 2); weights (F,);
    bias (); order (F,) int32 — the coordinate visit order.  Both updaters
    run this one CoordinateDelta chain (the reference's selectors apply to
    coord_descent too, coordinate_common.h) — the updater param only picks
    the default selector; with the defaults (cyclic) order is 0..F-1 and
    the chain is bitwise the pre-selector behaviour.
    Returns (new_weights, new_bias).
    """
    g, h = gpair[:, 0], gpair[:, 1]
    # bias first (reference: updater bias update before features)
    db = -jnp.sum(g) / jnp.maximum(jnp.sum(h), 1e-6) * eta
    g = g + h * db  # refresh gradients for the bias shift

    def body(carry, j):
        w, g = carry
        xj = X[:, j]
        num = jnp.dot(xj, g) + lambda_ * w[j]
        den = jnp.dot(xj * xj, h) + lambda_
        dw = -_soft_threshold(num, alpha) / den * eta
        g = g + h * xj * dw
        return (w.at[j].add(dw), g), None

    (w_new, _), _ = lax.scan(body, (weights, g), order)
    return w_new, bias + db


@jax.jit
def linear_predict(X, weights, bias):
    """margin (R, K) = X @ W + b (NaN treated as 0)."""
    Xz = jnp.nan_to_num(X, nan=0.0)
    return Xz @ weights + bias[None, :]
