"""gblinear: the linear booster.

Reference: src/gbm/gblinear.cc + src/linear/ (coordinate descent
updater_coordinate.cc:100, parallel 'shotgun' updater_shotgun.cc:96, GPU
updater_gpu_coordinate.cu:247) with the feature-selector family from
src/linear/coordinate_common.h (cyclic / shuffle / random selectors).

Two updaters:

``coord_descent``
    Coordinate descent: every feature updated with the gradient refreshed
    after each coordinate via ``lax.scan`` — bitwise the reference
    semantics.  (Default, as in the reference.)  Its default selector is
    ``cyclic`` (index order), but like the reference it honors any
    implemented ``feature_selector``.

``shotgun``
    The reference's shotgun updater runs the same CoordinateDelta updates
    feature-parallel over OpenMP *without locks* — its output is racy and
    run-dependent by design (Bradley et al., the "shotgun" paper).  Under
    this repo's bitwise determinism contract we implement its
    deterministic equivalent: the identical update sequence in the
    selector-chosen feature order with per-coordinate gradient refresh —
    exactly the reference's shotgun at ``nthread=1``, reproducible at any
    thread count.  The ``feature_selector`` param picks the order:

    - ``cyclic``  : 0, 1, ..., F-1 (shotgun output == coord_descent);
    - ``shuffle`` : a fresh deterministic permutation every round (the
      reference's shotgun default), seeded by ``seed`` + round index;
    - ``random``  : sample F coordinates WITH replacement per round
      (coordinate_common.h RandomFeatureSelector).

    ``greedy``/``thrifty`` (coordinate_common.h) remain unimplemented and
    raise — they need the per-coordinate gain ranking, a different shape.

Missing values are zeros for the linear model, matching the reference (only
stored sparse entries contribute).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

SELECTORS = ("cyclic", "shuffle", "random", "greedy", "thrifty")


def _soft_threshold(x, alpha):
    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - alpha, 0.0)


def selector_order(selector: str, n_features: int, round_idx: int,
                   seed: int) -> np.ndarray:
    """The coordinate visit order for one boosting round (host-side,
    deterministic): the linear-updater analogue of coordinate_common.h's
    FeatureSelector::NextFeature loop.  Same (selector, seed, round) ->
    same order on every host, so trained models stay bitwise-reproducible.
    """
    if selector not in SELECTORS:
        raise ValueError(
            f"unknown feature_selector {selector!r}; expected one of "
            f"{SELECTORS}")
    if selector in ("greedy", "thrifty"):
        raise NotImplementedError(
            f"feature_selector={selector!r} is not implemented; use "
            "cyclic, shuffle, or random")
    if selector == "cyclic":
        return np.arange(n_features, dtype=np.int32)
    rng = np.random.default_rng(
        np.random.SeedSequence([seed & 0x7FFFFFFF, round_idx]))
    if selector == "shuffle":
        return rng.permutation(n_features).astype(np.int32)
    return rng.integers(0, n_features, size=n_features).astype(np.int32)


@jax.jit
def linear_update(X, gpair, weights, bias, order, *, eta: float,
                  lambda_: float, alpha: float):
    """One boosting round of the linear model for one output group.

    X : (R, F) f32 with NaN already zeroed; gpair (R, 2); weights (F,);
    bias (); order (F,) int32 — the coordinate visit order.  Both updaters
    run this one CoordinateDelta chain (the reference's selectors apply to
    coord_descent too, coordinate_common.h) — the updater param only picks
    the default selector; with the defaults (cyclic) order is 0..F-1 and
    the chain is bitwise the pre-selector behaviour.
    Returns (new_weights, new_bias).
    """
    g, h = gpair[:, 0], gpair[:, 1]
    # bias first (reference: updater bias update before features)
    db = -jnp.sum(g) / jnp.maximum(jnp.sum(h), 1e-6) * eta
    g = g + h * db  # refresh gradients for the bias shift

    def body(carry, j):
        w, g = carry
        xj = X[:, j]
        num = jnp.dot(xj, g) + lambda_ * w[j]
        den = jnp.dot(xj * xj, h) + lambda_
        dw = -_soft_threshold(num, alpha) / den * eta
        g = g + h * xj * dw
        return (w.at[j].add(dw), g), None

    (w_new, _), _ = lax.scan(body, (weights, g), order)
    return w_new, bias + db


@jax.jit
def linear_predict(X, weights, bias):
    """margin (R, K) = X @ W + b (NaN treated as 0)."""
    Xz = jnp.nan_to_num(X, nan=0.0)
    return Xz @ weights + bias[None, :]
