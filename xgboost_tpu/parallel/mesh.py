"""Device mesh + sharding helpers.

TPU-native replacement for the reference's collective bootstrap
(src/collective/comm_group.h CommGroup + tracker): there is no tracker — the
mesh IS the communicator.  GBDT training is pure row-sharded data parallelism
(SURVEY §2 L1: the only cross-worker primitive is the histogram allreduce), so
the mesh is 1-D over a ``data`` axis; ICI carries the psum on a pod, DCN
across slices, all chosen by XLA.

Multi-host: call ``init_distributed()`` (jax.distributed.initialize) before
building the mesh — the analogue of RabitTracker rendezvous (tracker.h:141).
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

DATA_AXIS = "data"


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap (replaces tracker rendezvous, tracker.cc)."""
    import jax

    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
    jax.distributed.initialize(**kwargs)


def make_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None):
    """1-D data-parallel mesh over the first n LOCAL devices.

    Local, not global: within one process the mesh carries chip-level data
    parallelism (GSPMD psum over ICI); ACROSS processes histograms ride the
    host collective (collective.allreduce) — composing the two is the
    reference's multi-host rabit × per-device NCCL layering
    (src/collective/comm.cuh:51).  Single-process, local == global.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.local_devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(f"requested {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def row_sharding(mesh):
    """NamedSharding: leading (row) dim split over the data axis."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(DATA_AXIS))


def row2d_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(DATA_AXIS, None))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def shard_rows(mesh, *arrays):
    """Place arrays row-sharded over the mesh (no-op copies if already placed)."""
    import jax

    out = []
    for a in arrays:
        sh = row2d_sharding(mesh) if a.ndim >= 2 else row_sharding(mesh)
        out.append(jax.device_put(a, sh))
    return tuple(out)
