"""Distributed training over a jax.sharding.Mesh (reference: src/collective/ —
here the mesh replaces sockets/NCCL/tracker, SURVEY §2 L1)."""
from .mesh import (DATA_AXIS, init_distributed, make_mesh, replicated,
                   row2d_sharding, row_sharding, shard_rows)
from .grower import ShardedHistTreeGrower

__all__ = ["DATA_AXIS", "init_distributed", "make_mesh", "replicated",
           "row2d_sharding", "row_sharding", "shard_rows",
           "ShardedHistTreeGrower"]
