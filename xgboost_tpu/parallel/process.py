"""Multi-process (multi-host) tree grower.

The reference's distributed training (SURVEY §3.4; dask/__init__.py:722
_train_async -> rabit allreduce inside each updater) keeps every worker on
its own row shard and reduces exactly three things: the root gradient sum,
the per-level histograms, and eval metrics.  This grower reproduces that
shape for the multi-*process* case: each process runs the jitted device
pieces (histogram build, split decide, position update — shared with the
in-core growers) on its local rows, and the fixed-size histogram crosses
processes through ``collective.allreduce`` between the build and decide
steps, the role NCCL allreduce plays in updater_gpu_hist.cu:598.  The root
gradient sum is reduced here too; eval metrics are globalized in
``Booster.eval_set`` (shard gather), so early stopping stays in lockstep.

Within a process the single-chip path is used; combine with the shard_map
grower by giving each process its own chip(s) (process-level DP x chip-level
DP).  Determinism: the host allreduce is an ordered f32 sum over the gathered
(world, ...) stack, so every process sees bitwise-identical histograms and
grows bitwise-identical trees — the property the reference engineers via
quantised integer allreduce (quantiser.cuh).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from .. import collective
from ..ops.histogram import combine_sibling_hists
from ..reliability.faults import maybe_inject
from ..ops.split import SplitParams
from ..tree.grow import (TreeState, init_tree_state, make_set_matrix,
                         max_nodes_for_depth)
from ..tree.stream import _decide_level, _page_step


class ProcessHistTreeGrower:
    """Drop-in for HistTreeGrower when jax.process_count() > 1 (or when the
    host-level collective is initialized for a CPU multi-process test)."""

    def __init__(self, max_depth: int, params: SplitParams, *,
                 interaction_sets=None, max_leaves: int = 0,
                 lossguide: bool = False, subtract: bool = True,
                 mesh=None, quantised: bool = False) -> None:
        self.max_depth = max_depth
        self.params = params
        self.interaction_sets = interaction_sets
        self.max_leaves = max_leaves
        self.lossguide = lossguide
        self.subtract = subtract
        # process-DP x chip-DP composition (the reference's multi-host rabit
        # x per-device NCCL layering, src/collective/comm.cuh:51; dask one-
        # GPU-per-worker generalized): rows are sharded over the process's
        # LOCAL mesh, GSPMD partitions the jitted page step (hist partials
        # psum over local chips), and the replicated local hist then crosses
        # processes through the ordered host allreduce below.
        self.mesh = mesh
        # fixed-point limb histograms (ops/quantise.py): the chip psum and
        # the cross-process reduction both run on exact integers, so trees
        # are bitwise-identical across ANY process x chip topology — the
        # reference's GradientQuantiser + integer-rabit contract
        # (src/tree/gpu_hist/quantiser.cuh)
        self.quantised = quantised
        self.max_nodes = max_nodes_for_depth(max_depth)

    def grow(self, bins, gpair, valid, cuts_pad, n_bins, feature_masks=None,
             cat_mask=None) -> TreeState:
        F = bins.shape[1]
        B = cuts_pad.shape[1]
        has_cat = cat_mask is not None
        cm = jnp.asarray(cat_mask) if has_cat else jnp.zeros(0, bool)
        setmat = jnp.asarray(make_set_matrix(self.interaction_sets, F))
        ones = jnp.ones((1, F), dtype=bool)
        state = init_tree_state(
            gpair, valid, max_nodes=self.max_nodes, n_sets=setmat.shape[0],
            max_splits=(self.max_leaves - 1) if self.max_leaves > 0 else 0,
            n_bin=B,
        )
        if self.mesh is not None:
            # chip-level row sharding within this process; jit/GSPMD then
            # partitions _page_step (position update stays elementwise-
            # sharded, the hist contraction all-reduces over local chips)
            from .mesh import row_sharding, shard_rows

            bins, gpair = shard_rows(self.mesh, bins, gpair)
            state = state._replace(
                pos=jax.device_put(state.pos, row_sharding(self.mesh)))
        # root totals: GlobalSum across processes (updater_gpu_hist.cu:581)
        from ..tree.grow import sync_root_totals

        rho = None
        if self.quantised:
            from ..ops.quantise import prepare_quantised

            gpair, rho, state = prepare_quantised(gpair, valid, state,
                                                  distributed=True)
        else:
            state = sync_root_totals(state)

        prev_best, prev_can, prev_d = None, None, -1
        hist_prev = None
        for d in range(self.max_depth + 1):
            build = d < self.max_depth
            subtract = self.subtract and build and d > 0 and hist_prev is not None
            node0 = (1 << d) - 1
            N = 1 << d
            n_build = (N // 2) if subtract else N
            pos, h = _page_step(
                bins, gpair, state.pos, prev_best, prev_can,
                node0_prev=(1 << prev_d) - 1 if prev_d >= 0 else 0,
                n_prev=1 << max(prev_d, 0), node0=node0, n_nodes=n_build,
                n_bin=B, has_prev=prev_best is not None, has_cat=has_cat,
                build=build, stride=2 if subtract else 1,
                quantised=self.quantised,
            )
            state = state._replace(pos=pos)
            if build:
                # seam: the per-level histogram exchange — delay a rank
                # (straggler), raise (failed allreduce -> signal_error),
                # or kill (death inside the collective, the case the
                # tracker's EOF abort fan-out exists for)
                maybe_inject("process.allreduce", rank=collective.get_rank)
                # the one cross-process exchange per level (AllReduceHist);
                # quantised: limbs reduce in int64 on host — exact, so the
                # exchange is order-invariant (integer-rabit role)
                if self.quantised:
                    from ..ops.quantise import allreduce_limbs, dequantise

                    hist = allreduce_limbs(h)
                else:
                    hist = jnp.asarray(collective.allreduce(np.asarray(h)))
                if subtract:
                    alive_lvl = jax.lax.dynamic_slice_in_dim(
                        state.alive, node0, N)
                    hist = combine_sibling_hists(hist, hist_prev, alive_lvl)
                hist_prev = hist
                hist_f = (dequantise(hist, rho) if self.quantised else hist)
            else:
                hist_f = jnp.zeros((N, F, B, 2), jnp.float32)
            fm = ones if feature_masks is None else feature_masks(d, N)
            state, best, can = _decide_level(
                state, hist_f, n_bins, cuts_pad, fm, setmat, cm,
                depth=d, params=self.params, lossguide=self.lossguide,
                last_level=(d == self.max_depth),
            )
            prev_best, prev_can, prev_d = best, can, d
        return state

    @staticmethod
    def to_host(state: TreeState):
        from ..tree.grow import HistTreeGrower

        return HistTreeGrower.to_host(state)
