"""Multi-chip tree grower: shard_map(level_step) + psum histograms.

The distributed design mirrors the reference exactly at the semantic level
(SURVEY §3.4): every shard builds full-width histograms over its row shard,
one ``lax.psum`` replaces AllReduceHist (src/tree/gpu_hist/histogram.cu:598),
and the split decision is computed redundantly-but-identically on every shard
(deterministic f32 psum -> bitwise-identical trees per shard, the property the
reference gets from quantised integer allreduce).  No tracker, no sockets:
the mesh is the communicator.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.5 graduated shard_map to the top-level namespace
    _shard_map = jax.shard_map
except AttributeError:  # jax 0.4.x: pre-graduation home
    from jax.experimental.shard_map import shard_map as _shard_map

from ..ops.split import SplitParams
from ..telemetry import span
from ..tree.grow import (TreeState, init_tree_state, level_step,
                         level_step_padded, make_set_matrix,
                         max_nodes_for_depth)
from .mesh import DATA_AXIS


def _state_specs(data_axis: str):
    """PartitionSpecs for TreeState: pos is row-sharded, tree arrays replicated."""
    return TreeState(
        pos=P(data_axis),
        alive=P(), totals=P(), feat=P(), sbin=P(), thr=P(), dleft=P(),
        is_leaf=P(), leaf_val=P(), gain=P(), base_weight=P(), sum_hess=P(),
        lower=P(), upper=P(), setcompat=P(), splits_left=P(),
        is_cat=P(), cat_set=P(),
    )


class ShardedHistTreeGrower:
    """Drop-in replacement for HistTreeGrower over a 1-D mesh."""

    def __init__(self, max_depth: int, params: SplitParams, mesh, *,
                 hist_impl: str = "xla", interaction_sets=None,
                 max_leaves: int = 0, lossguide: bool = False,
                 quantised: bool = False) -> None:
        self.max_depth = max_depth
        self.params = params
        self.mesh = mesh
        self.hist_impl = hist_impl
        self.interaction_sets = interaction_sets
        self.max_leaves = max_leaves
        self.lossguide = lossguide
        # fixed-point limb histograms (ops/quantise.py): int psum is exact,
        # so trees are bitwise-identical for ANY chip count — the
        # GradientQuantiser contract (src/tree/gpu_hist/quantiser.cuh)
        self.quantised = quantised
        self.max_nodes = max_nodes_for_depth(max_depth)
        self._built_for = None

    def _build(self, n_features: int, n_bin: int = 1, has_cat: bool = False) -> None:
        if self._built_for == (n_features, n_bin, has_cat):
            return
        ax = DATA_AXIS
        sspec = _state_specs(ax)
        n_sets = make_set_matrix(self.interaction_sets, n_features).shape[0]

        self._init_fn = jax.jit(
            _shard_map(
                functools.partial(
                    init_tree_state, max_nodes=self.max_nodes, axis_name=ax,
                    n_sets=n_sets, n_bin=n_bin,
                    max_splits=(self.max_leaves - 1) if self.max_leaves > 0 else 0,
                ),
                mesh=self.mesh,
                in_specs=(P(ax, None), P(ax)),
                out_specs=sspec,
            )
        )

        q = self.quantised
        # quantised: the gpair slot carries (R, C, 3) int8 limbs and every
        # level fn takes a trailing replicated rho (per-channel scale)
        gspec = P(ax, None, None) if q else P(ax, None)
        row_specs = (sspec, P(ax, None), gspec, P(), P(), P(), P(), P())
        rho_specs = (P(),) if q else ()
        self._level_fns = {}
        # one shared padded interior program for all depths 1..max_depth-1
        # (same compile-wall fix as HistTreeGrower; hist psum rides inside
        # level_step_padded via axis_name) — per-depth programs only for the
        # root and the leaf-finalize level, plus the pallas fallback.
        # Same platform rule as HistTreeGrower (shared helper).
        from ..tree.grow import default_padded_levels

        self._padded = (self.hist_impl != "pallas" and self.max_depth >= 2
                        and default_padded_levels(self.max_depth))
        if self._padded:
            W = 1 << (self.max_depth - 1)
            pad_base = functools.partial(
                level_step_padded, width=W, params=self.params, axis_name=ax,
                hist_impl=self.hist_impl, lossguide=self.lossguide,
                has_cat=has_cat, subtract=True, quantised=q,
            )
            self._interior_fn = jax.jit(
                _shard_map(pad_base, mesh=self.mesh,
                              in_specs=row_specs + (P(), P()) + rho_specs,
                              out_specs=(sspec, P()))
            )
        depths = ((0, self.max_depth) if self._padded
                  else range(self.max_depth + 1))
        for d in depths:
            last = d == self.max_depth
            subtract = d > 0 and not last and not self._padded
            base = functools.partial(
                level_step,
                depth=d,
                params=self.params,
                last_level=last,
                axis_name=ax,
                hist_impl=self.hist_impl,
                lossguide=self.lossguide,
                has_cat=has_cat,
                subtract=subtract,
                quantised=q,
            )
            if last:
                # hist neither consumed nor produced on the last level
                def fn(state, bins, gpair, cuts, nb, fm, sm, cmm, *r, _b=base):
                    st, _ = _b(state, bins, gpair, cuts, nb, fm, sm, cmm)
                    return st

                in_specs, out_specs = row_specs + rho_specs, sspec
            elif subtract:
                # hist_prev is replicated (already psummed at its own level)
                fn = base
                in_specs = row_specs + (P(),) + rho_specs
                out_specs = (sspec, P())
            else:
                if q:
                    def fn(state, bins, gq, cuts, nb, fm, sm, cmm, rho,
                           _b=base):
                        return _b(state, bins, gq, cuts, nb, fm, sm, cmm,
                                  None, rho)
                else:
                    fn = base
                in_specs = row_specs + rho_specs
                out_specs = (sspec, P())
            self._level_fns[d] = jax.jit(
                _shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                              out_specs=out_specs)
            )
        self._built_for = (n_features, n_bin, has_cat)

    def grow(self, bins, gpair, valid, cuts_pad, n_bins, feature_masks=None,
             cat_mask=None) -> TreeState:
        F = bins.shape[1]
        self._build(F, cuts_pad.shape[1], has_cat=cat_mask is not None)
        ones = jnp.ones((1, F), dtype=bool)
        setmat = jnp.asarray(make_set_matrix(self.interaction_sets, F))
        cm = jnp.asarray(cat_mask) if cat_mask is not None else jnp.zeros(F, bool)
        state = self._init_fn(gpair, valid)
        rho_args = ()
        if self.quantised:
            from ..ops.quantise import prepare_quantised

            # jit over the already-sharded gpair: GSPMD's all-reduce-max and
            # integer root reduce are exact, so rho and the root totals are
            # identical on every topology
            gpair, rho, state = prepare_quantised(gpair, valid, state)
            rho_args = (rho,)
        # same fused-level span name as HistTreeGrower (each sharded level
        # program is hist psum + split eval + position rewrite in one call)
        _LEVEL = "grow.build_hist+eval_split"
        if self._padded:
            from ..tree.grow import HistTreeGrower

            md = self.max_depth
            W = 1 << (md - 1)
            fm = ones if feature_masks is None else feature_masks(0, 1)
            with span(_LEVEL):
                state, hist = self._level_fns[0](state, bins, gpair, cuts_pad,
                                                 n_bins, fm, setmat, cm,
                                                 *rho_args)
            hist_pad = jnp.zeros((W,) + hist.shape[1:],
                                 hist.dtype).at[:1].set(hist)
            for d in range(1, md):
                fm = (ones if feature_masks is None
                      else HistTreeGrower._pad_mask(feature_masks(d, 1 << d), W))
                with span(_LEVEL):
                    state, hist_pad = self._interior_fn(
                        state, bins, gpair, cuts_pad, n_bins, fm, setmat, cm,
                        hist_pad, jnp.int32((1 << d) - 1), *rho_args)
            fm = ones if feature_masks is None else feature_masks(md, 1 << md)
            with span(_LEVEL):
                state = self._level_fns[md](state, bins, gpair, cuts_pad,
                                            n_bins, fm, setmat, cm, *rho_args)
            return state
        hist_prev = None
        for d in range(self.max_depth + 1):
            fm = ones if feature_masks is None else feature_masks(d, 1 << d)
            with span(_LEVEL):
                if d == self.max_depth:
                    state = self._level_fns[d](state, bins, gpair, cuts_pad,
                                               n_bins, fm, setmat, cm,
                                               *rho_args)
                elif d == 0:
                    state, hist_prev = self._level_fns[d](state, bins, gpair,
                                                          cuts_pad, n_bins, fm,
                                                          setmat, cm,
                                                          *rho_args)
                else:
                    state, hist_prev = self._level_fns[d](state, bins, gpair,
                                                          cuts_pad, n_bins, fm,
                                                          setmat, cm,
                                                          hist_prev,
                                                          *rho_args)
        return state

    @staticmethod
    def to_host(state: TreeState):
        from ..tree.grow import HistTreeGrower

        return HistTreeGrower.to_host(state)


class ShardedMultiTargetGrower:
    """Vector-leaf trees over a 1-D mesh: shard_map(level_step_multi) with
    the 2K-channel histogram crossing shards in one psum (the multi-target
    AllReduceHist; reference: MultiTargetHistBuilder under rabit,
    src/tree/updater_quantile_hist.cc:156)."""

    def __init__(self, max_depth: int, params: SplitParams, n_targets: int,
                 mesh, *, max_leaves: int = 0, lossguide: bool = False) -> None:
        from ..tree.grow_multi import MultiTreeState  # noqa: F401

        self.max_depth = max_depth
        self.params = params
        self.n_targets = n_targets
        self.mesh = mesh
        self.max_leaves = max_leaves
        self.lossguide = lossguide
        self.max_nodes = max_nodes_for_depth(max_depth)
        self._built_for = None

    def _state_specs(self, ax):
        from ..tree.grow_multi import MultiTreeState

        return MultiTreeState(
            pos=P(ax), alive=P(), totals=P(), feat=P(), sbin=P(), thr=P(),
            dleft=P(), is_leaf=P(), leaf_val=P(), gain=P(), base_weight=P(),
            sum_hess=P(), splits_left=P(),
        )

    def _build(self, n_features: int, n_bin: int) -> None:
        if self._built_for == (n_features, n_bin):
            return
        from ..tree.grow_multi import init_multi_state, level_step_multi

        ax = DATA_AXIS
        sspec = self._state_specs(ax)
        self._init_fn = jax.jit(
            _shard_map(
                functools.partial(
                    init_multi_state, max_nodes=self.max_nodes,
                    n_targets=self.n_targets, axis_name=ax,
                    max_splits=(self.max_leaves - 1) if self.max_leaves > 0 else 0,
                ),
                mesh=self.mesh,
                in_specs=(P(ax, None, None), P(ax)),
                out_specs=sspec,
            )
        )
        self._level_fns = {}
        for d in range(self.max_depth + 1):
            last = d == self.max_depth
            subtract = d > 0 and not last
            base = functools.partial(
                level_step_multi, depth=d, params=self.params,
                last_level=last, n_targets=self.n_targets,
                subtract_on=subtract, axis_name=ax, lossguide=self.lossguide,
            )
            row_specs = (sspec, P(ax, None), P(ax, None, None), P(), P(), P())
            if last:
                def fn(state, bins, gpair, cuts, nb, fm, _b=base):
                    st, _ = _b(state, bins, gpair, cuts, nb, fm)
                    return st

                in_specs, out_specs = row_specs, sspec
            elif subtract:
                fn, in_specs, out_specs = base, row_specs + (P(),), (sspec, P())
            else:
                fn, in_specs, out_specs = base, row_specs, (sspec, P())
            self._level_fns[d] = jax.jit(
                _shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                              out_specs=out_specs)
            )
        self._built_for = (n_features, n_bin)

    def grow(self, bins, gpair, valid, cuts_pad, n_bins, feature_masks=None):
        F = bins.shape[1]
        self._build(F, cuts_pad.shape[1])
        ones = jnp.ones((1, F), dtype=bool)
        state = self._init_fn(gpair, valid)
        hist_prev = None
        for d in range(self.max_depth + 1):
            fm = ones if feature_masks is None else feature_masks(d, 1 << d)
            if d == self.max_depth:
                state = self._level_fns[d](state, bins, gpair, cuts_pad,
                                           n_bins, fm)
            elif d == 0:
                state, hist_prev = self._level_fns[d](state, bins, gpair,
                                                      cuts_pad, n_bins, fm)
            else:
                state, hist_prev = self._level_fns[d](state, bins, gpair,
                                                      cuts_pad, n_bins, fm,
                                                      hist_prev)
        return state

    @staticmethod
    def to_host(state):
        from ..tree.grow_multi import MultiTargetTreeGrower

        return MultiTargetTreeGrower.to_host(state)
