"""PySpark estimator frontend — distributed training on a Spark cluster.

Reference shape: python-package/xgboost/spark/ — ``SparkXGBClassifier`` /
``SparkXGBRegressor`` / ``SparkXGBRanker`` estimators (estimator.py:80,249,
437) whose ``_fit`` (core.py:1000) repartitions the DataFrame to
``num_workers``, starts a tracker, runs one barrier-mode training task per
partition under a ``CommunicatorContext`` built from the tracker's args,
and returns rank 0's booster wrapped in a pyspark Model whose
``transform`` maps prediction over partitions.

The TPU port keeps that choreography and swaps the engine (tracker
rendezvous -> jax.distributed; distributed sketch; histogram allreduce
over the host collective; chip-level GSPMD per worker via ``n_devices``).
The partition-level training body is SHARED with the dask frontend
(:func:`xgboost_tpu.dask._dask_worker_train`), so the protocol tested
there (tests/test_dask.py, real subprocess workers + tracker) covers this
module's core; the pyspark-facing adapter below needs a live Spark
cluster and is gated on the import.

Usage (with pyspark installed)::

    from xgboost_tpu.spark import SparkXGBClassifier
    clf = SparkXGBClassifier(features_col="features", label_col="label",
                             num_workers=4, max_depth=6)
    model = clf.fit(df)
    pred_df = model.transform(df)
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .core import Booster

__all__ = ["SparkXGBRegressor", "SparkXGBClassifier", "SparkXGBRanker"]


def _require_pyspark():
    try:
        import pyspark  # noqa: F401

        return pyspark
    except ImportError as e:  # pragma: no cover - exercised only sans spark
        raise ImportError(
            "xgboost_tpu.spark needs pyspark. The estimator layer is a thin "
            "adapter over the tested distributed core (xgboost_tpu.dask / "
            "distributed.py); install pyspark to use it, or call "
            "train_distributed / dask.train directly.") from e


def _rows_to_parts(rows, features_col: str, label_col: str,
                   weight_col: Optional[str], qid_col: Optional[str]):
    """Worker-local: partition rows -> the dict part _dask_worker_train
    consumes (data/label/weight[/group])."""
    feats: List[np.ndarray] = []
    labels: List[float] = []
    weights: List[float] = []
    qids: List[int] = []
    for row in rows:
        v = row[features_col]
        # pyspark ml Vector or array column
        arr = np.asarray(v.toArray() if hasattr(v, "toArray") else v,
                         np.float32)
        feats.append(arr)
        labels.append(float(row[label_col]))
        if weight_col is not None:
            weights.append(float(row[weight_col]))
        if qid_col is not None:
            qids.append(int(row[qid_col]))
    if not feats:
        raise ValueError(
            "empty partition: repartition the DataFrame so every worker "
            "holds rows (the reference has the same requirement)")
    part: Dict[str, Any] = {
        "data": np.stack(feats),
        "label": np.asarray(labels, np.float32),
    }
    if weight_col is not None:
        part["weight"] = np.asarray(weights, np.float32)
    if qid_col is not None:
        q = np.asarray(qids, np.int64)
        if not (np.diff(q) >= 0).all():
            raise ValueError("qid column must be sorted within partitions")
        _, counts = np.unique(q, return_counts=True)
        part["group"] = counts
    return part


def _partition_train_fn(tracker_uri: str, tracker_port: int, world: int,
                        params: Dict[str, Any], num_boost_round: int,
                        spec: Dict[str, Any], features_col: str,
                        label_col: str, weight_col: Optional[str],
                        qid_col: Optional[str]):
    """Returns the barrier-mode mapPartitions body (core.py:1039 role).
    Module-level for picklability; the training choreography is the dask
    worker's (shared code path -> shared test coverage)."""

    def fn(rows):
        from .dask import _dask_worker_train

        part = _rows_to_parts(rows, features_col, label_col, weight_col,
                              qid_col)
        out = _dask_worker_train(tracker_uri, tracker_port, world, params,
                                 num_boost_round, spec, [part])
        # only rank 0 yields the serialized model (full result dict, so
        # best_iteration survives like the dask path)
        if out is not None:
            out = dict(out)
            out["raw"] = bytearray(out["raw"])
            yield out

    return fn


class _SparkXGBEstimator:
    """pyspark.ml Estimator shape (reference: core.py _SparkXGBEstimator).

    Construction and parameter handling are pure python (usable and
    testable without pyspark); ``fit`` needs a live SparkSession.
    """

    _objective = "reg:squarederror"

    def __init__(self, *, features_col: str = "features",
                 label_col: str = "label", prediction_col: str = "prediction",
                 weight_col: Optional[str] = None,
                 qid_col: Optional[str] = None, num_workers: int = 1,
                 num_boost_round: int = 100, **xgb_params: Any) -> None:
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.weight_col = weight_col
        self.qid_col = qid_col
        self.num_workers = int(num_workers)
        self.num_boost_round = int(num_boost_round)
        self.xgb_params = dict(xgb_params)
        if self.num_workers < 1:
            raise ValueError("num_workers must be >= 1")

    def _train_params(self) -> Dict[str, Any]:
        p = dict(self.xgb_params)
        p.setdefault("objective", self._objective)
        return p

    def fit(self, dataset):
        _require_pyspark()
        from .tracker import RabitTracker, get_host_ip

        world = self.num_workers
        if self.qid_col is not None:
            # ranking: a query group must live whole inside one partition
            # and arrive sorted (the reference repartitions/sorts by qid
            # unless allow_group_split; spark/core.py _prepare_input)
            df = (dataset.repartition(world, dataset[self.qid_col])
                  .sortWithinPartitions(self.qid_col))
        else:
            df = dataset.repartition(world)
        tracker = RabitTracker(n_workers=world, host_ip=get_host_ip("auto"))
        tracker.start()
        args = tracker.worker_args()
        spec = {"eval_train": False, "verbose_eval": False,
                "train_kwargs": {}, "dmatrix_kw": {}}
        fn = _partition_train_fn(
            str(args["dmlc_tracker_uri"]), int(args["dmlc_tracker_port"]),
            world, self._train_params(), self.num_boost_round, spec,
            self.features_col, self.label_col, self.weight_col, self.qid_col)
        try:
            # barrier mode: all partitions must schedule together or the
            # tracker rendezvous deadlocks (reference: core.py:1131)
            results = df.rdd.barrier().mapPartitions(fn).collect()
        finally:
            tracker.free()
        if not results:
            raise RuntimeError("no worker returned a model (rank 0 missing)")
        out = results[0]
        bst = Booster(params=self._train_params())
        bst.load_model(bytearray(out["raw"]))
        if out.get("best_iteration") is not None:
            bst.best_iteration = out["best_iteration"]
        return self._make_model(bst, out["history"])

    def _make_model(self, booster: Booster, history) -> "_SparkXGBModel":
        return _SparkXGBModel(booster, history, self)


class _SparkXGBModel:
    """pyspark.ml Model shape: ``transform`` adds the prediction column by
    partition-parallel inference (core.py _SparkXGBModel.transform)."""

    def __init__(self, booster: Booster, history, est: _SparkXGBEstimator):
        self.booster = booster
        self.training_history = history
        self._est = est

    def get_booster(self) -> Booster:
        return self.booster

    @staticmethod
    def _postprocess(preds: np.ndarray) -> np.ndarray:
        """Raw model output -> the prediction column (regressor:
        identity; classifier override emits class labels)."""
        return preds

    def transform(self, dataset):
        _require_pyspark()
        from pyspark.sql.functions import pandas_udf

        raw = bytes(self.booster.save_raw())
        features_col = self._est.features_col
        post = type(self)._postprocess

        @pandas_udf("double")
        def _predict(col):
            import pandas as pd

            import xgboost_tpu as xtb

            # per-process booster cache: pandas_udf fires once per Arrow
            # batch, and re-parsing the model each batch would dominate
            # large scoring jobs (reference uses an executor-cached model)
            b = getattr(_predict, "_bst", None)
            if b is None:
                b = Booster()
                b.load_model(bytearray(raw))
                _predict._bst = b
            X = np.stack([np.asarray(
                v.toArray() if hasattr(v, "toArray") else v, np.float32)
                for v in col])
            out = post(np.asarray(b.predict(xtb.DMatrix(X))))
            return pd.Series(np.asarray(out, np.float64))

        return dataset.withColumn(self._est.prediction_col,
                                  _predict(dataset[features_col]))


class SparkXGBRegressor(_SparkXGBEstimator):
    """reference: estimator.py:80."""

    _objective = "reg:squarederror"


class _SparkXGBClassifierModel(_SparkXGBModel):
    @staticmethod
    def _postprocess(preds: np.ndarray) -> np.ndarray:
        # class labels like the reference model (probabilities stay
        # reachable via get_booster().predict)
        if preds.ndim == 2:  # multi:softprob
            return np.argmax(preds, axis=1).astype(np.float64)
        return (preds > 0.5).astype(np.float64)


class SparkXGBClassifier(_SparkXGBEstimator):
    """reference: estimator.py:249."""

    _objective = "binary:logistic"

    def _make_model(self, booster, history):
        return _SparkXGBClassifierModel(booster, history, self)


class SparkXGBRanker(_SparkXGBEstimator):
    """reference: estimator.py:437 (requires qid_col)."""

    _objective = "rank:ndcg"

    def __init__(self, **kw) -> None:
        super().__init__(**kw)
        if self.qid_col is None:
            raise ValueError("SparkXGBRanker requires qid_col")
