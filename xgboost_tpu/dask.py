"""Dask frontend — distributed training driven by a dask cluster.

Reference shape: python-package/xgboost/dask/__init__.py — ``DaskDMatrix``
(:267) pins partition references to the workers that hold them; ``train``
(:832 -> _train_async:722) starts a RabitTracker, runs one training task on
every holding worker under a ``CommunicatorContext`` built from the
tracker's args, and returns rank 0's booster + eval history; ``predict``
(:1212) maps the model over partitions worker-locally.

The TPU port keeps that choreography but swaps the engine: inside each dask
worker the communicator is ``collective.init`` (tracker rendezvous ->
jax.distributed), cuts merge through the distributed sketch, and the
per-level histogram allreduce rides the host collective — with chip-level
GSPMD meshes composable per worker via ``n_devices`` (the reference's
one-GPU-per-worker becomes one-mesh-per-worker).

Two data paths into :class:`DaskDMatrix`:

- dask collections (dask.array / dask.dataframe), when dask is installed:
  partitions are persisted and mapped to their holding workers
  (``client.who_has``), never moved — the reference's no-repartition rule;
- an explicit list of pre-partitioned parts (numpy tuples/dicts), assigned
  round-robin over the cluster's workers.  This path has no dask
  dependency, so the full train/predict choreography (tracker rendezvous,
  per-worker training, rank-0 result marshaling) is exercised by
  tests/test_dask.py against a subprocess-backed stand-in client; the thin
  collection-mapping adapter is the only code that needs a real dask.

``client`` may be any object with the small surface used here:
``scheduler_info() / submit(fn, *args, workers=, pure=) / gather(futures)``
— the subset of ``distributed.Client`` the reference itself relies on.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .core import Booster

__all__ = ["DaskDMatrix", "DaskQuantileDMatrix", "train", "predict",
           "DaskXGBRegressor", "DaskXGBClassifier"]


def _worker_addrs(client) -> List[str]:
    info = client.scheduler_info()
    addrs = sorted(info["workers"])
    if not addrs:
        raise RuntimeError("dask cluster has no workers")
    return addrs


def _is_dask_collection(data) -> bool:
    try:
        import dask

        return dask.is_dask_collection(data)
    except ImportError:
        return False


class DaskDMatrix:
    """Per-worker partition references (reference: dask/__init__.py:267).

    Does NOT move data between workers; with pre-partitioned list input the
    parts are assigned round-robin (they are shipped to the assigned worker
    by the task that trains there).
    """

    def __init__(self, client, data, label=None, *, weight=None,
                 base_margin=None, group=None, missing=None,
                 feature_names=None, feature_types=None,
                 enable_categorical: bool = False,
                 max_bin: Optional[int] = None) -> None:
        self.client = client
        self.max_bin = max_bin
        self.feature_names = feature_names
        self.feature_types = feature_types
        self.enable_categorical = enable_categorical
        self.missing = missing
        # parts_by_worker: {addr: [part dict | future-of-part, ...]}
        if isinstance(data, (list, tuple)) and not _is_dask_collection(data):
            self._parts_by_worker = self._assign_listed_parts(
                client, list(data), label, weight, base_margin, group)
        else:
            self._parts_by_worker = self._map_dask_collections(
                client, data, label, weight, base_margin, group)
        if not self._parts_by_worker:
            raise ValueError("DaskDMatrix holds no data partitions")

    @staticmethod
    def _assign_listed_parts(client, parts, label, weight, base_margin,
                             group) -> Dict[str, List[Any]]:
        if (label is not None or weight is not None
                or base_margin is not None or group is not None):
            raise ValueError(
                "with pre-partitioned list input, pack label/weight/group/… "
                "into each part: (X, y) tuple or {'data':, 'label':, ...} "
                "dict")
        out: Dict[str, List[Any]] = {}
        addrs = _worker_addrs(client)
        for i, part in enumerate(parts):
            if isinstance(part, tuple):
                part = {"data": part[0], "label": part[1]}
            # _pidx: global partition index, so predict() can reassemble
            # its output in the caller's partition order
            out.setdefault(addrs[i % len(addrs)], []).append(
                {**part, "_pidx": i})
        return out

    @staticmethod
    def _map_dask_collections(client, data, label, weight, base_margin,
                              group) -> Dict[str, List[Any]]:
        """dask collections -> {holding worker: [future-of-part-dict]}
        (persist + who_has; the no-repartition rule)."""
        import dask
        from distributed import wait

        def to_futures(coll):
            if coll is None:
                return None
            coll = coll.persist()
            wait(coll)
            if hasattr(coll, "to_delayed"):
                delayed = list(np.asarray(coll.to_delayed()).flatten())
            else:  # dataframe
                delayed = coll.to_delayed()
            return client.compute(delayed)

        xs = to_futures(data)
        ys = to_futures(label)
        ws = to_futures(weight)
        ms = to_futures(base_margin)
        gs = to_futures(group)
        n = len(xs)
        for other, name in ((ys, "label"), (ws, "weight"),
                            (ms, "base_margin"), (gs, "group")):
            if other is not None and len(other) != n:
                raise ValueError(
                    f"{name} has {len(other)} partitions, data has {n} — "
                    "align the chunking (the reference has the same rule)")
        wait(xs)
        who = client.who_has(xs)
        out: Dict[str, List[Any]] = {}
        for i, xf in enumerate(xs):
            holders = who.get(xf.key) or who.get(str(xf.key))
            addr = sorted(holders)[0] if holders else _worker_addrs(client)[0]
            part = {"data": xf, "_pidx": i}
            if ys is not None:
                part["label"] = ys[i]
            if ws is not None:
                part["weight"] = ws[i]
            if ms is not None:
                part["base_margin"] = ms[i]
            if gs is not None:
                part["group"] = gs[i]
            out.setdefault(addr, []).append(part)
        return out

    @property
    def num_partitions(self) -> int:
        return sum(len(v) for v in self._parts_by_worker.values())


class DaskQuantileDMatrix(DaskDMatrix):
    """Quantile variant (reference: dask/__init__.py:585) — same partition
    mapping; the per-worker QuantileDMatrix is built at training time with
    the distributed sketch merging cuts across workers."""


def _concat_parts(parts: Sequence[Dict[str, Any]], dmatrix_kw: Dict[str, Any]):
    """Worker-local: resolve + concatenate this worker's partitions into one
    DMatrix (reference dask concat path, dask/__init__.py:514).  Delegates
    the dict-part -> DMatrix semantics to distributed._make_dmatrix so the
    two frontends cannot drift."""
    from .distributed import _make_dmatrix

    fields: Dict[str, List[np.ndarray]] = {}
    for p in parts:
        for k, v in p.items():
            if k != "_pidx":
                fields.setdefault(k, []).append(np.asarray(v))
    part = {k: np.concatenate(v, axis=0) for k, v in fields.items()}
    part.update({k: v for k, v in dmatrix_kw.items() if v is not None})
    return _make_dmatrix(part)


def _dask_worker_train(tracker_uri: str, tracker_port: int, world: int,
                       params: Dict[str, Any], num_boost_round: int,
                       spec: Dict[str, Any], parts: List[Dict[str, Any]]):
    """One dask worker's training task (the body of _train_async:768's
    dispatched_train).  Runs under the tracker-rendezvoused communicator;
    only rank 0 returns the model."""
    import xgboost_tpu as xtb
    from xgboost_tpu import collective

    with collective.CommunicatorContext(dmlc_tracker_uri=tracker_uri,
                                        dmlc_tracker_port=tracker_port,
                                        dmlc_nworker=world):
        rank = collective.get_rank()
        try:
            dtrain = _concat_parts(parts, spec.get("dmatrix_kw", {}))
            evals = ([(dtrain, "train")] if spec.get("eval_train") else [])
            history: Dict[str, Any] = {}
            bst = xtb.train(params, dtrain, num_boost_round,
                            evals=evals, evals_result=history,
                            verbose_eval=spec.get("verbose_eval", False),
                            **spec.get("train_kwargs", {}))
            if rank != 0:
                return None
            return {
                "raw": bytes(bst.save_raw()),
                "history": history,
                "best_iteration": getattr(bst, "best_iteration", None),
            }
        except BaseException as e:
            # fan out through the tracker so peers blocked in a collective
            # abort instead of hanging to the dask timeout
            try:
                collective.signal_error(f"dask worker rank {rank}: {e!r}")
            except Exception:
                pass
            raise


def train(client, params: Dict[str, Any], dtrain: DaskDMatrix,
          num_boost_round: int = 10, *, evals=None,
          eval_train: bool = False, verbose_eval: bool = False,
          **train_kwargs) -> Dict[str, Any]:
    """Train over the workers holding ``dtrain``'s partitions; returns
    ``{"booster", "history", "best_iteration"}`` (the reference dask
    ``train()`` contract, dask/__init__.py:930)."""
    if evals:
        raise NotImplementedError(
            "dask train() currently evaluates on dtrain only "
            "(eval_train=True); per-DaskDMatrix evals are not wired yet")
    from .tracker import RabitTracker, get_host_ip

    parts_by_worker = dtrain._parts_by_worker
    addrs = sorted(parts_by_worker)
    world = len(addrs)
    tracker = RabitTracker(n_workers=world, host_ip=get_host_ip("auto"))
    tracker.start()
    args = tracker.worker_args()
    spec = {
        "eval_train": bool(eval_train),
        "verbose_eval": verbose_eval,
        "train_kwargs": train_kwargs,
        "dmatrix_kw": {
            "feature_names": dtrain.feature_names,
            "feature_types": dtrain.feature_types,
            "missing": dtrain.missing,
            "enable_categorical": dtrain.enable_categorical or None,
        },
    }
    p = dict(params)
    if dtrain.max_bin is not None:
        p.setdefault("max_bin", dtrain.max_bin)
    futures = [
        client.submit(_dask_worker_train,
                      str(args["dmlc_tracker_uri"]),
                      int(args["dmlc_tracker_port"]), world, p,
                      int(num_boost_round), spec, parts_by_worker[addr],
                      workers=[addr], pure=False)
        for addr in addrs
    ]
    try:
        results = client.gather(futures)
    finally:
        tracker.free()
    out = next((r for r in results if r is not None), None)
    if out is None:
        raise RuntimeError("no worker returned a model (rank 0 missing)")
    bst = Booster(params=params)
    bst.load_model(bytearray(out["raw"]))
    if out.get("best_iteration") is not None:
        bst.best_iteration = out["best_iteration"]
    return {"booster": bst, "history": out["history"],
            "best_iteration": out.get("best_iteration")}


def _dask_worker_predict(raw: bytes, part: Dict[str, Any],
                         output_margin: bool):
    import xgboost_tpu as xtb

    bst = Booster()
    bst.load_model(bytearray(raw))
    d = _concat_parts([part], {})
    return np.asarray(bst.predict(d, output_margin=output_margin))


def predict(client, model, data, *, output_margin: bool = False) -> np.ndarray:
    """Partition-parallel prediction (reference: dask/__init__.py:1212).
    ``model`` is a Booster or the dict returned by :func:`train`.  Returns
    the concatenated prediction in partition order."""
    bst = model["booster"] if isinstance(model, dict) else model
    raw = bytes(bst.save_raw())
    if isinstance(data, DaskDMatrix):
        futures, pidx = [], []
        for addr in sorted(data._parts_by_worker):
            for part in data._parts_by_worker[addr]:
                futures.append(client.submit(
                    _dask_worker_predict, raw, part, output_margin,
                    workers=[addr], pure=False))
                pidx.append(part.get("_pidx", len(pidx)))
        parts_out = client.gather(futures)
        # reassemble in the caller's partition order, not worker order
        ordered = [p for _, p in sorted(zip(pidx, parts_out),
                                        key=lambda t: t[0])]
        return np.concatenate(ordered, axis=0)
    raise TypeError("predict expects a DaskDMatrix")


class _DaskSklearnBase:
    """Minimal dask sklearn wrappers (DaskScikitLearnBase role,
    dask/__init__.py:1434)."""

    _objective = "reg:squarederror"

    def __init__(self, *, client=None, n_estimators: int = 100,
                 **params) -> None:
        self.client = client
        self.n_estimators = n_estimators
        self.params = params
        self._result: Optional[Dict[str, Any]] = None

    def fit(self, X, y=None, **kw):
        d = (X if isinstance(X, DaskDMatrix)
             else DaskDMatrix(self.client, X, y))
        p = dict(self.params)  # refit-safe: never mutate the constructor's
        p.setdefault("objective", self._objective)
        self._result = train(self.client, p, d, self.n_estimators, **kw)
        return self

    @property
    def booster_(self) -> Booster:
        if self._result is None:
            raise AttributeError("model is not fitted yet")
        return self._result["booster"]

    def predict(self, X):
        return predict(self.client, self._result, X)


class DaskXGBRegressor(_DaskSklearnBase):
    _objective = "reg:squarederror"


class DaskXGBClassifier(_DaskSklearnBase):
    _objective = "binary:logistic"

    def predict_proba(self, X):
        p = predict(self.client, self._result, X)
        return np.stack([1.0 - p, p], axis=1) if p.ndim == 1 else p

    def predict(self, X):
        p = predict(self.client, self._result, X)
        return (p > 0.5).astype(np.int64) if p.ndim == 1 else np.argmax(p, 1)
