"""Training hyper-parameters.

TPU-native re-design of the reference's DMLC parameter DSL (``TrainParam``,
src/tree/param.h:82-173; learner params src/learner.cc).  The reference builds
parameters from string key/value maps with aliases, defaults, and range
validation; we mirror that contract with dataclasses so the public dict-style
``xgb.train(params, ...)`` API keeps working, while the jitted kernels receive
a hashable, static subset.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

# alias -> canonical (reference: DMLC_DECLARE_ALIAS in src/tree/param.h)
_ALIASES = {
    "learning_rate": "eta",
    "min_split_loss": "gamma",
    "reg_lambda": "lambda",
    "reg_alpha": "alpha",
}

_CANON = {v: k for k, v in _ALIASES.items()}


def canonicalize(params: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in params.items():
        out[_ALIASES.get(k, k)] = v
    return out


@dataclasses.dataclass
class TrainParam:
    """Tree-construction parameters (reference: src/tree/param.h:82-173)."""

    eta: float = 0.3
    gamma: float = 0.0  # min_split_loss
    max_depth: int = 6
    max_leaves: int = 0
    max_bin: int = 256
    grow_policy: str = "depthwise"  # depthwise | lossguide
    min_child_weight: float = 1.0
    lambda_: float = 1.0
    alpha: float = 0.0
    max_delta_step: float = 0.0
    subsample: float = 1.0
    sampling_method: str = "uniform"  # uniform | gradient_based
    colsample_bytree: float = 1.0
    colsample_bylevel: float = 1.0
    colsample_bynode: float = 1.0
    monotone_constraints: Optional[Tuple[int, ...]] = None
    interaction_constraints: Optional[Tuple[Tuple[int, ...], ...]] = None
    max_cat_to_onehot: int = 4
    max_cat_threshold: int = 64
    refresh_leaf: bool = True

    @staticmethod
    def from_dict(params: Dict[str, Any]) -> "TrainParam":
        p = canonicalize(params)
        self = TrainParam()
        for f in dataclasses.fields(TrainParam):
            key = "lambda" if f.name == "lambda_" else f.name
            if key in p:
                v = p[key]
                if f.name == "monotone_constraints" and v is not None:
                    if isinstance(v, str):
                        v = v.strip("()[] ")
                        v = tuple(int(x) for x in v.split(",") if x.strip()) if v else None
                    else:
                        v = tuple(int(x) for x in v)
                elif f.name == "interaction_constraints" and v is not None:
                    if isinstance(v, str):
                        import json as _json

                        v = tuple(tuple(int(i) for i in grp) for grp in _json.loads(v))
                    else:
                        v = tuple(tuple(int(i) for i in grp) for grp in v)
                elif f.type == "float":
                    v = float(v)
                elif f.type == "int":
                    v = int(v)
                elif f.type == "bool":
                    v = v if isinstance(v, bool) else str(v).lower() in ("1", "true", "yes")
                setattr(self, f.name, v)
        self.validate()
        return self

    def validate(self) -> None:
        if self.max_depth < 0:
            raise ValueError("max_depth must be >= 0")
        if self.max_depth == 0 and self.max_leaves == 0:
            raise ValueError("one of max_depth / max_leaves must be positive")
        if not (0.0 < self.subsample <= 1.0):
            raise ValueError("subsample must be in (0, 1]")
        for name in ("colsample_bytree", "colsample_bylevel", "colsample_bynode"):
            v = getattr(self, name)
            if not (0.0 < v <= 1.0):
                raise ValueError(f"{name} must be in (0, 1]")
        if self.max_bin < 2:
            raise ValueError("max_bin must be >= 2")
        if self.grow_policy not in ("depthwise", "lossguide"):
            raise ValueError("grow_policy must be 'depthwise' or 'lossguide'")
        if self.sampling_method not in ("uniform", "gradient_based"):
            raise ValueError(
                "sampling_method must be 'uniform' or 'gradient_based'")

    def split_static(self) -> Tuple[float, ...]:
        """Hashable static subset consumed by the jitted split evaluator."""
        return (
            float(self.eta),
            float(self.gamma),
            float(self.min_child_weight),
            float(self.lambda_),
            float(self.alpha),
            float(self.max_delta_step),
        )


# Known learner-level keys (reference: src/learner.cc LearnerTrainParam +
# objective/metric registries); used to warn on unknown parameters like the
# reference's "Parameters: { ... } might not be used" message.
KNOWN_LEARNER_KEYS = {
    "objective", "base_score", "num_class", "eval_metric", "seed", "nthread",
    "device", "tree_method", "booster", "verbosity", "disable_default_eval_metric",
    "num_parallel_tree", "multi_strategy", "num_target",
    # dart
    "rate_drop", "one_drop", "skip_drop", "sample_type", "normalize_type",
    # gblinear
    "updater", "feature_selector", "top_k",
    # ranking
    "lambdarank_num_pair_per_sample", "lambdarank_pair_method", "ndcg_exp_gain",
    "lambdarank_unbiased", "lambdarank_bias_norm",
    "lambdarank_normalization", "lambdarank_score_normalization",
    # survival / quantile
    "aft_loss_distribution", "aft_loss_distribution_scale", "quantile_alpha",
    "expectile_alpha",
    # tweedie / huber
    "tweedie_variance_power", "huber_slope",
    "scale_pos_weight", "enable_categorical", "missing", "validate_parameters",
    "n_devices", "process_type", "refresh_leaf", "deterministic_histogram",
}


def split_unknown(params: Dict[str, Any]) -> List[str]:
    p = canonicalize(params)
    tree_keys = {("lambda" if f.name == "lambda_" else f.name) for f in dataclasses.fields(TrainParam)}
    # leading-underscore keys are internal hooks (_hist_impl,
    # _extmem_prefetch, ...), deliberately outside the public surface
    return [k for k in p if k not in tree_keys
            and k not in KNOWN_LEARNER_KEYS and not k.startswith("_")]
