"""Vectorized tree-traversal prediction.

TPU-native equivalent of the reference's GPU predictor
(src/predictor/gpu_predictor.cu:203 PredictKernel — one CUDA thread per row).
Here the whole row batch advances one tree level per step (rows at leaves
stick), a ``lax.scan`` walks trees, and the per-row feature read is a
``take_along_axis`` gather.  Raw feature values + thresholds are used (not
bins) so the same code serves training-eval and inference on fresh data.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

# --------------------------------------------------------------- bucket cache
# Shared shape-bucket policy for every margin-predict caller (training eval,
# Booster.predict, the serving engine).  jax.jit specializes per shape, so
# without bucketing each distinct row count compiles a fresh program; with it,
# steady-state traffic lands on a handful of padded shapes that all hit the
# same jit cache (the role of the reference GPU predictor's fixed thread-block
# geometry, gpu_predictor.cu).  Rows are padded with NaN — traversal is
# row-independent, so the pad rows change nothing and are sliced off.

_MIN_ROW_BUCKET = 8
# past this, pow2 padding could waste up to 2x; fall back to chunk multiples
_POW2_ROW_CEILING = 4096


def round_up_pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def bucket_rows(n: int) -> int:
    """Padded row count for a batch of ``n`` rows: power-of-two buckets up to
    4096, then multiples of 4096 (bounded <0.1% overhead at scale)."""
    n = int(n)
    if n <= _MIN_ROW_BUCKET:
        return _MIN_ROW_BUCKET
    if n <= _POW2_ROW_CEILING:
        return round_up_pow2(n)
    c = _POW2_ROW_CEILING
    return ((n + c - 1) // c) * c


def bucket_width(w: int) -> int:
    """Padded node count for a stacked tree ensemble.  Trees grown across
    rounds drift in node count; rounding the pad width to a power of two keeps
    the stacked (T, M) shape — and therefore the compiled program — stable, so
    training-eval stops retracing every time a round yields a bushier tree."""
    return round_up_pow2(max(int(w), 2))


def pad_rows(X, bucket: int):
    """Pad a (R, F) batch with NaN rows up to ``bucket``.  No-op (no copy, no
    retrace) when the row count already matches the compiled shape."""
    R = X.shape[0]
    if R == bucket:
        return X
    return jnp.pad(X, ((0, bucket - R), (0, 0)), constant_values=jnp.nan)


def pad_margin(init, bucket: int):
    """Pad an optional (R, K) starting margin to the row bucket with zeros."""
    if init is None:
        return None
    R = init.shape[0]
    if R == bucket:
        return init
    return jnp.pad(init, ((0, bucket - R), (0, 0)))


def predict_cache_size() -> int:
    """Total compiled-program count across the predict entry points — the
    serving engine's recompile gauge (zero growth after warm-up is the SLO)."""
    return sum(
        f._cache_size()
        for f in (predict_margin_delta, predict_margin_delta_multi,
                  predict_leaf_ids, predict_margin_delta_binned)
    )


def run_stacked_margin(X_dev, stacked, groups, depth: int, n_groups: int,
                       init=None):
    """Dispatch a bucket-padded (B, F) batch through the jitted margin kernel
    matching the stacked-ensemble layout (multi-target value vectors,
    categorical masks, or plain scalar leaves).  The single place the stacked
    dict's field contract is interpreted — Booster prediction and the serving
    snapshot both route here so their outputs stay bitwise-identical."""
    if "value_vec" in stacked:
        return predict_margin_delta_multi(
            X_dev, stacked["feat"], stacked["thr"], stacked["dleft"],
            stacked["left"], stacked["right"], stacked["value_vec"],
            init, depth=depth)
    if stacked["catm"] is not None:
        return predict_margin_delta(
            X_dev, stacked["feat"], stacked["thr"], stacked["dleft"],
            stacked["left"], stacked["right"], stacked["value"],
            groups, stacked["is_cat"], stacked["catm"], init,
            n_groups=n_groups, depth=depth)
    return predict_margin_delta(
        X_dev, stacked["feat"], stacked["thr"], stacked["dleft"],
        stacked["left"], stacked["right"], stacked["value"],
        groups, init=init, n_groups=n_groups, depth=depth)


def _traverse_one_tree(X, feat, thr, dleft, left, right, depth: int,
                       is_cat=None, catm=None):
    """Leaf node id per row for one tree. X: (R,F) f32 with NaN missing.

    Categorical nodes route by set membership (in-set -> right, out-of-range
    -> left), matching common/categorical.h Decision."""
    R, F = X.shape
    nid = jnp.zeros(R, jnp.int32)

    def step(_, nid):
        fi = feat[nid]  # (R,) int32, -1 at leaves
        leaf = fi < 0
        x = jnp.take_along_axis(X, jnp.clip(fi, 0, F - 1)[:, None], axis=1)[:, 0]
        gol_num = x < thr[nid]
        if is_cat is None:
            gol = jnp.where(jnp.isnan(x), dleft[nid], gol_num)
        else:
            Bc = catm.shape[1]
            c = jnp.nan_to_num(x, nan=-1.0).astype(jnp.int32)
            in_range = (c >= 0) & (c < Bc)
            member = catm.reshape(-1)[nid * Bc + jnp.clip(c, 0, Bc - 1)] & in_range
            gol = jnp.where(is_cat[nid], ~member, gol_num)
            gol = jnp.where(jnp.isnan(x), dleft[nid], gol)
        nxt = jnp.where(gol, left[nid], right[nid])
        return jnp.where(leaf, nid, nxt)

    return lax.fori_loop(0, depth, step, nid)


def _native_predict_ok() -> bool:
    """CPU-backend gate for the native traversal kernels (same per-host
    agreement rules as the hist/split kernels — utils/native.py)."""
    import os

    if os.environ.get("XTB_NO_NATIVE_PREDICT", ""):
        return False
    if jax.default_backend() != "cpu":
        return False
    from ..utils import native

    return native.ffi_usable()


def _predict_native(X, feat, thr, dleft, left, right, value, groups,
                    is_cat, catm, init, n_groups: int, depth: int):
    """FFI custom call into xtb_predict_raw_impl — rows outer, trees inner,
    per-row adds in tree order (bitwise-identical to the XLA scan).  The
    kernel row-block-shards across the ParallelFor pool; output is bitwise
    identical for every nthread."""
    import numpy as np

    from ..utils import native

    native.ensure_pool()
    R = X.shape[0]
    T, M = feat.shape
    has_cat = is_cat is not None
    ic = (is_cat.astype(jnp.uint8) if has_cat
          else jnp.zeros((T, M), jnp.uint8))
    cm = (catm.astype(jnp.uint8) if has_cat
          else jnp.zeros((T, M, 1), jnp.uint8))
    init_arr = (jnp.zeros((R, n_groups), jnp.float32) if init is None
                else init.astype(jnp.float32))
    call = native.jax_ffi().ffi_call(
        "xtb_predict", jax.ShapeDtypeStruct((R, n_groups), jnp.float32))
    return call(X.astype(jnp.float32), feat.astype(jnp.int32),
                thr.astype(jnp.float32), dleft.astype(jnp.uint8),
                left.astype(jnp.int32), right.astype(jnp.int32),
                value.astype(jnp.float32), groups.astype(jnp.int32),
                ic, cm, init_arr,
                depth=np.int32(depth), has_cat=np.int32(has_cat))


@functools.partial(jax.jit, static_argnames=("n_groups", "depth"))
def predict_margin_delta(X, feat, thr, dleft, left, right, value, groups,
                         is_cat=None, catm=None, init=None, *,
                         n_groups: int, depth: int):
    """Sum leaf values of a stack of trees into (R, n_groups) margin deltas.

    feat..value : (T, M) stacked padded tree arrays; groups: (T,) int32
    (tree_info group ids, reference src/gbm/gbtree_model.h).
    is_cat (T, M) / catm (T, M, Bc): optional categorical routing tables.
    init: optional (R, n_groups) starting margin — accumulating INTO it
    reproduces the training loop's exact f32 addition order, so rebuilt
    prediction caches are bitwise-identical to incrementally-updated ones
    (continuation via xgb_model= yields the same model as one straight run).
    """
    if _native_predict_ok():
        return _predict_native(X, feat, thr, dleft, left, right, value,
                               groups, is_cat, catm, init, n_groups, depth)
    R = X.shape[0]

    def body(margin, t):
        if is_cat is None:
            f, th, dl, l, r, v, grp = t
            nid = _traverse_one_tree(X, f, th, dl, l, r, depth)
        else:
            f, th, dl, l, r, v, grp, ic, cm = t
            nid = _traverse_one_tree(X, f, th, dl, l, r, depth, ic, cm)
        delta = v[nid]
        col = lax.dynamic_slice_in_dim(margin, grp, 1, axis=1)
        margin = lax.dynamic_update_slice_in_dim(margin, col + delta[:, None], grp, axis=1)
        return margin, None

    margin0 = (jnp.zeros((R, n_groups), jnp.float32) if init is None
               else init.astype(jnp.float32))
    xs = ((feat, thr, dleft, left, right, value, groups) if is_cat is None
          else (feat, thr, dleft, left, right, value, groups, is_cat, catm))
    margin, _ = lax.scan(body, margin0, xs)
    return margin


@functools.partial(jax.jit, static_argnames=("depth",))
def predict_margin_delta_multi(X, feat, thr, dleft, left, right, value_vec,
                               init=None, *, depth: int):
    """Vector-leaf ensemble margins: every tree adds its leaf's K-vector to
    all outputs (reference: MultiTargetTree prediction,
    cpu_predictor.cc PredictBatchByBlockKernel vector-leaf path).

    value_vec: (T, M, K) padded per-node leaf vectors.  ``init``: optional
    starting margin (see predict_margin_delta)."""
    if _native_predict_ok():
        # K_leaf > 1 makes the kernel add each leaf vector to all K columns;
        # groups is unused on that path
        T = feat.shape[0]
        return _predict_native(X, feat, thr, dleft, left, right, value_vec,
                               jnp.zeros(T, jnp.int32), None, None, init,
                               value_vec.shape[2], depth)
    R = X.shape[0]
    K = value_vec.shape[2]

    def body(margin, t):
        f, th, dl, l, r, v = t
        nid = _traverse_one_tree(X, f, th, dl, l, r, depth)
        return margin + v[nid], None

    margin0 = (jnp.zeros((R, K), jnp.float32) if init is None
               else init.astype(jnp.float32))
    margin, _ = lax.scan(body, margin0,
                         (feat, thr, dleft, left, right, value_vec))
    return margin


@functools.partial(jax.jit, static_argnames=("depth",))
def predict_leaf_ids(X, feat, thr, dleft, left, right, *, depth: int):
    """(R, T) leaf indices (reference: Predictor::PredictLeaf)."""
    def body(_, t):
        f, th, dl, l, r = t
        return None, _traverse_one_tree(X, f, th, dl, l, r, depth)

    _, nids = lax.scan(body, None, (feat, thr, dleft, left, right))
    return nids.T


@functools.partial(jax.jit, static_argnames=("n_groups", "depth", "n_bin"))
def predict_margin_delta_binned(bins, feat, sbin, dleft, left, right, value,
                                groups, is_cat=None, catm=None, init=None, *,
                                n_groups: int, depth: int, n_bin: int):
    """Ensemble margins over a BINNED page (external-memory predict path).

    Routing uses stored split bins (RegTree.split_bins) so it reproduces the
    training-time partition exactly; sentinel n_bin = missing.  ``init``:
    optional starting margin (see predict_margin_delta — bitwise-faithful
    prediction-cache rebuilds).
    """
    if _native_predict_ok():
        import numpy as np

        from ..utils import native

        native.ensure_pool()
        R = bins.shape[0]
        T, M = feat.shape
        has_cat = is_cat is not None
        ic = (is_cat.astype(jnp.uint8) if has_cat
              else jnp.zeros((T, M), jnp.uint8))
        cm = (catm.astype(jnp.uint8) if has_cat
              else jnp.zeros((T, M, 1), jnp.uint8))
        init_arr = (jnp.zeros((R, n_groups), jnp.float32) if init is None
                    else init.astype(jnp.float32))
        b = bins
        if b.dtype not in (jnp.uint8, jnp.uint16, jnp.int16, jnp.int32):
            b = b.astype(jnp.int32)
        call = native.jax_ffi().ffi_call(
            "xtb_predict_binned",
            jax.ShapeDtypeStruct((R, n_groups), jnp.float32))
        return call(b, feat.astype(jnp.int32), sbin.astype(jnp.int32),
                    dleft.astype(jnp.uint8), left.astype(jnp.int32),
                    right.astype(jnp.int32), value.astype(jnp.float32),
                    groups.astype(jnp.int32), ic, cm, init_arr,
                    depth=np.int32(depth), has_cat=np.int32(has_cat),
                    n_bin=np.int32(n_bin))
    R = bins.shape[0]

    def traverse(f, sb, dl, l, r, ic, cm):
        nid = jnp.zeros(R, jnp.int32)

        def step(_, nid):
            fi = f[nid]
            leaf = fi < 0
            b = jnp.take_along_axis(
                bins, jnp.clip(fi, 0, bins.shape[1] - 1)[:, None].astype(jnp.int32),
                axis=1)[:, 0].astype(jnp.int32)
            gol_num = b <= sb[nid]
            if ic is not None:
                Bc = cm.shape[1]
                member = cm.reshape(-1)[nid * Bc + jnp.clip(b, 0, Bc - 1)] & (b < Bc)
                gol = jnp.where(ic[nid], ~member, gol_num)
            else:
                gol = gol_num
            gol = jnp.where(b >= n_bin, dl[nid], gol)  # sentinel = missing
            nxt = jnp.where(gol, l[nid], r[nid])
            return jnp.where(leaf, nid, nxt)

        return lax.fori_loop(0, depth, step, nid)

    def body(margin, t):
        if is_cat is None:
            f, sb, dl, l, r, v, grp = t
            nid = traverse(f, sb, dl, l, r, None, None)
        else:
            f, sb, dl, l, r, v, grp, ic, cm = t
            nid = traverse(f, sb, dl, l, r, ic, cm)
        delta = v[nid]
        col = lax.dynamic_slice_in_dim(margin, grp, 1, axis=1)
        margin = lax.dynamic_update_slice_in_dim(margin, col + delta[:, None], grp, axis=1)
        return margin, None

    margin0 = (jnp.zeros((R, n_groups), jnp.float32) if init is None
               else init.astype(jnp.float32))
    xs = ((feat, sbin, dleft, left, right, value, groups) if is_cat is None
          else (feat, sbin, dleft, left, right, value, groups, is_cat, catm))
    margin, _ = lax.scan(body, margin0, xs)
    return margin
