"""Split evaluation: gain scan over histogram bins.

TPU-native equivalent of the reference's split evaluators
(src/tree/gpu_hist/evaluate_splits.cu — forward/backward bin scans with
missing-value direction search; CPU src/tree/hist/evaluate_splits.h).
The CUDA code runs a block-parallel segmented scan per (node, feature); here
the whole (N, F, B) gain tensor is computed at once with a cumsum — a few
microseconds of VPU work — and reduced with argmax.

Gain formulae follow src/tree/param.h (CalcGain / CalcWeight / ThresholdL1 /
CalcGainGivenWeight): L1 soft-threshold via ``alpha``, L2 ``lambda``, optional
``max_delta_step`` weight clipping.  Missing-value handling matches
LossChangeMissing (evaluate_splits.cu): both default directions are scored and
the better one becomes the node's default.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

_EPS = 1e-6  # kRtEps (include/xgboost/base.h)


class SplitParams(NamedTuple):
    """Static split hyper-parameters (hashable for jit)."""

    eta: float
    gamma: float
    min_child_weight: float
    lambda_: float
    alpha: float
    max_delta_step: float
    # monotone_constraints: per-feature {-1,0,+1} (src/tree/constraints.cc);
    # None disables the constrained evaluation path entirely
    monotone: "object" = None
    # categorical split config (reference: src/tree/param.h max_cat_to_onehot)
    max_cat_to_onehot: int = 4


class BestSplit(NamedTuple):
    gain: jnp.ndarray  # (N,) loss_chg of best split (-inf if none valid)
    feature: jnp.ndarray  # (N,) int32
    bin: jnp.ndarray  # (N,) int32 — left = bins <= bin
    default_left: jnp.ndarray  # (N,) bool
    left_sum: jnp.ndarray  # (N, 2) (G, H) of left child
    right_sum: jnp.ndarray  # (N, 2)
    left_weight: jnp.ndarray  # (N,) clipped child weights (monotone bounds)
    right_weight: jnp.ndarray  # (N,)
    is_cat: jnp.ndarray  # (N,) bool — categorical split chosen
    cat_set: jnp.ndarray  # (N, B) bool — categories routed RIGHT (reference
    #                        semantics: common/categorical.h Decision)


class BestSplitMulti(NamedTuple):
    """Vector-leaf split decision (reference: multi_evaluate_splits.cu /
    HistMultiEvaluator): one (feature, bin) for all targets, per-target
    child statistics."""

    gain: jnp.ndarray  # (N,)
    feature: jnp.ndarray  # (N,) int32
    bin: jnp.ndarray  # (N,) int32
    default_left: jnp.ndarray  # (N,) bool
    left_sum: jnp.ndarray  # (N, K, 2)
    right_sum: jnp.ndarray  # (N, K, 2)
    left_weight: jnp.ndarray  # (N, K)
    right_weight: jnp.ndarray  # (N, K)


def _threshold_l1(g, alpha):
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - alpha, 0.0)


def calc_weight(G, H, p: SplitParams, lower=None, upper=None):
    """Raw leaf weight -ThresholdL1(G)/(H+lambda), clipped (param.h CalcWeight);
    optional [lower, upper] clamp implements monotone bounds propagation."""
    w = -_threshold_l1(G, p.alpha) / (H + p.lambda_)
    if p.max_delta_step > 0.0:
        w = jnp.clip(w, -p.max_delta_step, p.max_delta_step)
    if lower is not None:
        w = jnp.clip(w, lower, upper)
    return jnp.where(H <= 0.0, 0.0, w)


def gain_given_weight(G, H, w, p: SplitParams):
    """param.h CalcGainGivenWeight — used when weights are bound-clipped."""
    ret = -(2.0 * _threshold_l1(G, p.alpha) * w + (H + p.lambda_) * w * w)
    return jnp.where(H <= 0.0, 0.0, ret)


def calc_gain(G, H, p: SplitParams):
    """param.h CalcGain: ThresholdL1(G)^2/(H+lambda), or gain-given-weight when
    max_delta_step clips."""
    if p.max_delta_step == 0.0:
        return jnp.where(H <= 0.0, 0.0, _threshold_l1(G, p.alpha) ** 2 / (H + p.lambda_))
    w = calc_weight(G, H, p)
    # CalcGainGivenWeight: -(2 G w + (H + lambda) w^2), with L1 adjustment
    ret = -(2.0 * _threshold_l1(G, p.alpha) * w + (H + p.lambda_) * w * w)
    return jnp.where(H <= 0.0, 0.0, ret)


@functools.partial(jax.jit, static_argnames=("params",))
def evaluate_splits_multi(hist, totals, n_bins, params: SplitParams,
                          feature_mask=None) -> BestSplitMulti:
    """Best split per node for vector-leaf trees.

    hist   : (N, F, B, K, 2) f32 — per-target bin (G, H) sums
    totals : (N, K, 2) f32 — per-target node totals (incl. missing rows)

    Gain is the SUM of per-target gains for a shared (feature, bin) — the
    reference's multi-target objective (multi_evaluate_splits.cu accumulates
    per-target CalcGain under one split).  min_child_weight applies to the
    mean per-target hessian, matching the "average tree" reading used by the
    CPU HistMultiEvaluator.  Monotone/categorical are handled by the caller
    (unsupported for multi-target in round 2, like the reference's own
    multi_output_tree restrictions).
    """
    N, F, B, K, _ = hist.shape

    cum = jnp.cumsum(hist, axis=2)  # (N,F,B,K,2) left sums; missing -> right
    feat_sum = cum[:, :, -1]  # (N,F,K,2)
    miss = totals[:, None] - feat_sum  # (N,F,K,2)

    GL_r, HL_r = cum[..., 0], cum[..., 1]  # (N,F,B,K) missing -> right
    GL_l = GL_r + miss[:, :, None, :, 0]
    HL_l = HL_r + miss[:, :, None, :, 1]

    parent_gain = calc_gain(totals[..., 0], totals[..., 1], params).sum(-1)[
        :, None, None]  # (N,1,1)

    def side_gain(GL, HL):
        GR = totals[:, None, None, :, 0] - GL
        HR = totals[:, None, None, :, 1] - HL
        gain = (calc_gain(GL, HL, params) + calc_gain(GR, HR, params)).sum(-1) \
            - parent_gain  # (N,F,B)
        HLm, HRm = HL.mean(-1), HR.mean(-1)
        valid = ((HLm >= params.min_child_weight)
                 & (HRm >= params.min_child_weight)
                 & (HLm > 0.0) & (HRm > 0.0))
        return jnp.where(valid, gain, -jnp.inf), GR, HR

    gain_r, GR_r, HR_r = side_gain(GL_r, HL_r)
    gain_l, GR_l, HR_l = side_gain(GL_l, HL_l)

    bin_idx = jnp.arange(B, dtype=jnp.int32)
    bin_ok = bin_idx[None, :] < (n_bins[:, None] - 1)  # (F,B)
    top_ok = (bin_idx[None, :] == (n_bins[:, None] - 1)) & (
        jnp.abs(miss[..., 1]).sum(-1)[:, :, None] > _EPS)
    ok = bin_ok[None] | top_ok
    if feature_mask is not None:
        fm = feature_mask if feature_mask.ndim == 2 else feature_mask[None, :]
        ok = ok & fm[:, :, None]
    gain_r = jnp.where(ok, gain_r, -jnp.inf)
    gain_l = jnp.where(ok, gain_l, -jnp.inf)
    use_left = gain_l >= gain_r
    gain = jnp.where(use_left, gain_l, gain_r)

    flat = gain.reshape(N, F * B)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    best_f = (best // B).astype(jnp.int32)
    best_b = (best % B).astype(jnp.int32)

    def pick(arr):  # (N,F,B,K) -> (N,K) at the best (feature, bin)
        return jnp.take_along_axis(
            arr.reshape(N, F * B, K), best[:, None, None], axis=1)[:, 0]

    def pick2(arr):  # (N,F,B) -> (N,)
        return jnp.take_along_axis(arr.reshape(N, F * B), best[:, None], axis=1)[:, 0]

    dleft = pick2(use_left)
    GL = jnp.where(dleft[:, None], pick(GL_l), pick(GL_r))
    HL = jnp.where(dleft[:, None], pick(HL_l), pick(HL_r))
    GR = jnp.where(dleft[:, None], pick(GR_l), pick(GR_r))
    HR = jnp.where(dleft[:, None], pick(HR_l), pick(HR_r))

    return BestSplitMulti(
        gain=best_gain,
        feature=best_f,
        bin=best_b,
        default_left=dleft,
        left_sum=jnp.stack([GL, HL], axis=-1),
        right_sum=jnp.stack([GR, HR], axis=-1),
        left_weight=calc_weight(GL, HL, params),
        right_weight=calc_weight(GR, HR, params),
    )


def _native_split_ok(params: SplitParams) -> bool:
    """The native one-pass gain scan covers the numeric, unconstrained case
    (the ladder benchmarks); categorical and monotone keep the XLA path."""
    import os

    if os.environ.get("XTB_NO_NATIVE_SPLIT", ""):
        return False
    if jax.default_backend() != "cpu":
        return False
    if params.monotone is not None and any(c != 0 for c in params.monotone):
        return False
    from ..utils import native

    return native.ffi_usable()


def _evaluate_splits_native(hist, totals, n_bins, params: SplitParams,
                            feature_mask) -> BestSplit:
    """XLA FFI custom call into xtb_split_scan — one bin pass per (node,
    feature) instead of the XLA formulation's ~15 materialized (N,F,B)
    temporaries.  Same decisions (both missing directions scored,
    first-occurrence argmax in (feature, bin) order)."""
    import numpy as np

    N, F, B, _ = hist.shape
    fm = (jnp.ones((N, F), bool) if feature_mask is None
          else jnp.broadcast_to(
              feature_mask if feature_mask.ndim == 2 else feature_mask[None],
              (N, F)))
    shapes = (jax.ShapeDtypeStruct((N,), jnp.float32),
              jax.ShapeDtypeStruct((N,), jnp.int32),
              jax.ShapeDtypeStruct((N,), jnp.int32),
              jax.ShapeDtypeStruct((N,), jnp.uint8),
              jax.ShapeDtypeStruct((N,), jnp.float32),
              jax.ShapeDtypeStruct((N,), jnp.float32))
    from ..utils import native as _native

    _native.ensure_pool()
    call = _native.jax_ffi().ffi_call("xtb_split", shapes)
    gain, feat, bin_, dleft, GL, HL = call(
        hist.astype(jnp.float32), totals.astype(jnp.float32),
        n_bins.astype(jnp.int32), fm.astype(jnp.uint8),
        lam=np.float32(params.lambda_), alpha=np.float32(params.alpha),
        mcw=np.float32(params.min_child_weight),
        mds=np.float32(params.max_delta_step))
    GR = totals[:, 0] - GL
    HR = totals[:, 1] - HL
    return BestSplit(
        gain=gain,
        feature=feat,
        bin=bin_,
        default_left=dleft.astype(bool),
        left_sum=jnp.stack([GL, HL], axis=1),
        right_sum=jnp.stack([GR, HR], axis=1),
        left_weight=calc_weight(GL, HL, params),
        right_weight=calc_weight(GR, HR, params),
        is_cat=jnp.zeros(N, bool),
        cat_set=jnp.zeros((N, B), bool),
    )


@functools.partial(jax.jit, static_argnames=("params",))
def evaluate_splits(
    hist, totals, n_bins, params: SplitParams, feature_mask=None, node_bounds=None,
    cat_mask=None,
) -> BestSplit:
    """Pick the best split per node.

    hist   : (N, F, B, 2) f32 — per-node per-feature bin (G, H) sums
    totals : (N, 2) f32 — node (G, H) including missing rows
    n_bins : (F,) int32 — valid bin count per feature (pads masked out)
    feature_mask : optional (F,) or (N, F) bool — column sampling / interaction
                   constraints (per-node allowed features)
    node_bounds  : optional (N, 2) f32 [lower, upper] monotone weight bounds
    """
    N, F, B, _ = hist.shape
    has_cat = cat_mask is not None
    if not has_cat and _native_split_ok(params):
        return _evaluate_splits_native(hist, totals, n_bins, params,
                                       feature_mask)

    if has_cat:
        # Categorical features (reference: evaluate_splits.cu one-hot pass +
        # sorted-partition pass, max_cat_to_onehot switch in param.h):
        #  - partition: permute bins by grad/hess ratio, then the ordinary
        #    prefix scan below IS the optimal-partition scan;
        #  - one-hot (few categories): left = everything-but-c, expressed by
        #    overriding the prefix sums with feat_sum - hist[c].
        onehot_f = cat_mask & (n_bins < params.max_cat_to_onehot)  # (F,)
        ratio = hist[..., 0] / (hist[..., 1] + _EPS)  # (N,F,B)
        ratio = jnp.where(hist[..., 1] > 0, ratio, jnp.inf)  # empty cats last
        bin_iota = jnp.arange(B, dtype=jnp.float32)
        sort_key = jnp.where(cat_mask[None, :, None], ratio, bin_iota[None, None, :])
        order = jnp.argsort(sort_key, axis=2)  # identity for numeric features
        inv_order = jnp.argsort(order, axis=2).astype(jnp.int32)
        hist_eval = jnp.take_along_axis(hist, order[..., None], axis=2)
    else:
        hist_eval = hist

    cum = jnp.cumsum(hist_eval, axis=2)  # (N,F,B,2): left sums, missing->right
    feat_sum = cum[:, :, -1, :]  # (N,F,2) — uses all bins incl. top
    miss = totals[:, None, :] - feat_sum  # (N,F,2) missing-value stats

    GL_r, HL_r = cum[..., 0], cum[..., 1]  # missing -> right
    if has_cat:
        oh = onehot_f[None, :, None]
        GL_r = jnp.where(oh, feat_sum[:, :, None, 0] - hist[..., 0], GL_r)
        HL_r = jnp.where(oh, feat_sum[:, :, None, 1] - hist[..., 1], HL_r)
    GL_l, HL_l = GL_r + miss[:, :, None, 0], HL_r + miss[:, :, None, 1]  # missing -> left

    monotone = params.monotone is not None and any(c != 0 for c in params.monotone)
    if monotone:
        lo = node_bounds[:, 0][:, None, None] if node_bounds is not None else -jnp.inf
        hi = node_bounds[:, 1][:, None, None] if node_bounds is not None else jnp.inf
        cvec = jnp.asarray(params.monotone, jnp.int32)[None, :, None]  # (1,F,1)
        w_parent = calc_weight(totals[:, 0], totals[:, 1], params,
                               lo if node_bounds is None else node_bounds[:, 0],
                               hi if node_bounds is None else node_bounds[:, 1])
        parent_gain = gain_given_weight(totals[:, 0], totals[:, 1], w_parent, params)[
            :, None, None
        ]
    else:
        parent_gain = calc_gain(totals[:, 0], totals[:, 1], params)[:, None, None]

    def side_gain(GL, HL):
        GR = totals[:, None, None, 0] - GL
        HR = totals[:, None, None, 1] - HL
        if monotone:
            # constrained evaluation (src/tree/constraints.cc / evaluate_splits.cu
            # LossChangeMissing with ValueConstraint): child weights clipped to
            # the node's bounds; monotone violation invalidates the split
            wL = calc_weight(GL, HL, params, lo, hi)
            wR = calc_weight(GR, HR, params, lo, hi)
            gain = (
                gain_given_weight(GL, HL, wL, params)
                + gain_given_weight(GR, HR, wR, params)
                - parent_gain
            )
            viol = ((cvec > 0) & (wL > wR)) | ((cvec < 0) & (wL < wR))
            gain = jnp.where(viol, -jnp.inf, gain)
        else:
            wL = wR = None
            gain = calc_gain(GL, HL, params) + calc_gain(GR, HR, params) - parent_gain
        valid = (
            (HL >= params.min_child_weight)
            & (HR >= params.min_child_weight)
            & (HL > 0.0)
            & (HR > 0.0)
        )
        return jnp.where(valid, gain, -jnp.inf), GR, HR, wL, wR

    gain_r, GR_r, HR_r, wL_r, wR_r = side_gain(GL_r, HL_r)
    gain_l, GR_l, HR_l, wL_l, wR_l = side_gain(GL_l, HL_l)

    # mask padded bins and the top bin (split there = empty right for dense features)
    bin_idx = jnp.arange(B, dtype=jnp.int32)
    bin_ok = bin_idx[None, :] < (n_bins[:, None] - 1)  # (F, B); allow [0, nb-2]
    # allow the top valid bin only when there ARE missing values to send right
    top_ok = (bin_idx[None, :] == (n_bins[:, None] - 1)) & (
        jnp.abs(miss[:, :, 1:2]) > _EPS
    ).reshape(N, F, 1)
    ok = bin_ok[None, :, :] | top_ok
    if has_cat:
        # one-hot: every non-empty category is a valid candidate
        ok = jnp.where(onehot_f[None, :, None],
                       (bin_idx[None, None, :] < n_bins[None, :, None]), ok)
    if feature_mask is not None:
        fm = feature_mask if feature_mask.ndim == 2 else feature_mask[None, :]
        ok = ok & fm[:, :, None]
    gain_r = jnp.where(ok, gain_r, -jnp.inf)
    gain_l = jnp.where(ok, gain_l, -jnp.inf)

    # prefer missing->left on ties? reference default dir comes from scan order;
    # pick strictly-better direction, defaulting left like DeviceSplitCandidate.
    use_left = gain_l >= gain_r
    gain = jnp.where(use_left, gain_l, gain_r)

    flat = gain.reshape(N, F * B)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    best_f = (best // B).astype(jnp.int32)
    best_b = (best % B).astype(jnp.int32)

    def pick(arr):  # (N,F,B) -> (N,) at best
        return jnp.take_along_axis(arr.reshape(N, F * B), best[:, None], axis=1)[:, 0]

    dleft = pick(use_left)
    GL = jnp.where(dleft, pick(GL_l), pick(GL_r))
    HL = jnp.where(dleft, pick(HL_l), pick(HL_r))
    GR = jnp.where(dleft, pick(GR_l), pick(GR_r))
    HR = jnp.where(dleft, pick(HR_l), pick(HR_r))

    if monotone:
        lw = jnp.where(dleft, pick(wL_l), pick(wL_r))
        rw = jnp.where(dleft, pick(wR_l), pick(wR_r))
    else:
        lw = calc_weight(GL, HL, params)
        rw = calc_weight(GR, HR, params)

    if has_cat:
        is_cat = cat_mask[best_f]  # (N,)
        chosen_oh = onehot_f[best_f]
        # categories routed RIGHT (common/categorical.h: in-set -> right):
        #  one-hot: the single chosen category; partition: the sorted suffix
        inv_at = jnp.take_along_axis(
            inv_order, best_f[:, None, None], axis=1
        )[:, 0, :]  # (N, B) rank of each bin in the sorted order
        bb = jnp.arange(B, dtype=jnp.int32)[None, :]
        in_range = bb < n_bins[best_f][:, None]
        set_oh = (bb == best_b[:, None])
        set_part = inv_at > best_b[:, None]
        cat_set = jnp.where(chosen_oh[:, None], set_oh, set_part) & in_range & is_cat[:, None]
    else:
        is_cat = jnp.zeros(N, bool)
        cat_set = jnp.zeros((N, B), bool)

    return BestSplit(
        gain=best_gain,
        feature=best_f,
        bin=best_b,
        default_left=dleft,
        left_sum=jnp.stack([GL, HL], axis=1),
        right_sum=jnp.stack([GR, HR], axis=1),
        left_weight=lw,
        right_weight=rw,
        is_cat=is_cat,
        cat_set=cat_set,
    )
