"""Gradient histogram construction — the hot kernel of hist tree growing.

TPU-native re-design of the reference's histogram build
(src/tree/gpu_hist/histogram.cu:37-120 shared-memory atomic kernels;
CPU src/tree/hist/histogram.h:44).  The CUDA design — atomic adds of quantised
(grad,hess) into per-node bins — does not map to TPU (no fast global atomics).
Instead we reformulate as a **masked one-hot matmul** that runs on the MXU:

    hist[n, f, b, c] = sum_r  onehot(bins[r,f], b) * (pos[r] == node(n)) * gpair[r, c]

i.e. ``A.T @ G`` with ``A = onehot(bins)`` of shape (rows, F*B) and
``G[r, n*2+c] = gpair[r,c] * nodemask[r,n]`` of shape (rows, 2N).  No row
sorting, no scatter, no atomics; per-row node membership lives in a ``pos``
array updated elementwise each level (the analogue of RowPartitioner positions,
src/tree/gpu_hist/row_partitioner.cuh:255, without the physical partition).

Two implementations:
 - ``build_histogram``: chunked XLA einsum (reference path, works everywhere);
 - ``build_histogram_pallas`` (ops/hist_pallas.py): fuses one-hot construction
   into VMEM so the (rows, F*B) operand never touches HBM — the production
   TPU kernel.

Determinism: float32 accumulation in a fixed sequential chunk order — within
one topology, the role played by fixed-point gradient quantisation in the
reference (src/tree/gpu_hist/quantiser.cuh:52) is filled by the absence of
atomics.  For bitwise reproducibility ACROSS topologies (any chip/process
layout), ``deterministic_histogram=True`` switches to exact int8-limb
histograms with integer reductions — see ops/quantise.py.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _hist_chunk(bins_c, gpair_c, pos_c, node0: int, n_nodes: int, n_bin: int,
                stride: int = 1):
    """One row-chunk's contribution: (T,F) bins -> (N,F,B,C) partial histogram."""
    T, F = bins_c.shape
    C = gpair_c.shape[1]
    onehot = (bins_c.astype(jnp.int32)[:, :, None] == jnp.arange(n_bin, dtype=jnp.int32)).astype(
        jnp.float32
    )  # (T, F, B); missing sentinel B compares false everywhere
    nodemask = (
        pos_c[:, None] == (node0 + stride * jnp.arange(n_nodes, dtype=pos_c.dtype))
    ).astype(jnp.float32)  # (T, N)
    gm = (nodemask[:, :, None] * gpair_c[:, None, :]).reshape(T, n_nodes * C)
    out = jnp.dot(
        onehot.reshape(T, F * n_bin).T, gm, preferred_element_type=jnp.float32
    )  # (F*B, N*C)
    return out.reshape(F, n_bin, n_nodes, C).transpose(2, 0, 1, 3)


@functools.partial(jax.jit,
                   static_argnames=("node0", "n_nodes", "n_bin", "chunk", "stride"))
def build_histogram(
    bins, gpair, pos, *, node0: int, n_nodes: int, n_bin: int, chunk: int = 2048,
    stride: int = 1
):
    """hist (n_nodes, F, B, C) for nodes node0 + stride*[0, n_nodes).

    bins  : (R_pad, F) int   — local bin indices, sentinel == n_bin for missing
    gpair : (R_pad, C) f32   — C=2 (grad, hess); padded rows must be zero
    pos   : (R_pad,) int32   — per-row node id (-1 for padded rows)
    stride: 2 selects every other heap slot — the left-children of a level,
            for the subtraction trick (right sibling = parent - left).
    """
    return _hist_accumulate(bins, gpair, pos, node0, n_nodes, n_bin, chunk,
                            stride)


def hist_impl_override():
    """Test hook: XTB_HIST_IMPL=matmul|scatter|native forces the
    implementation regardless of backend, so the TPU matmul path keeps CPU
    CI coverage (tests/test_hist_kernels.py) and vice versa."""
    import os

    v = os.environ.get("XTB_HIST_IMPL", "").lower()
    return v if v in ("matmul", "scatter", "native") else None


def _native_hist_available() -> bool:
    from ..utils import native

    return native.ffi_usable()


def _host_impl():
    """Implementation for the CPU backend: the native C++ row-pass kernel
    (native/xtb_kernels.h via an XLA FFI custom call, ~5-10x the XLA
    scatter's add rate) when the handler library is present, else the XLA
    scatter driver."""
    forced = hist_impl_override()
    if forced == "native":
        # the forced hook must still register the FFI targets (and is the
        # one place where failure should be loud, not a silent fallback)
        from ..utils import native

        if not native.load_ffi():
            raise RuntimeError(
                "XTB_HIST_IMPL=native but the FFI kernel library could not "
                "be built/loaded (see native/Makefile `make ffi`)")
        return "native"
    if forced is not None:
        return forced
    if jax.default_backend() != "cpu":
        return "matmul"
    return "native" if _native_hist_available() else "scatter"


def _use_scatter() -> bool:
    return _host_impl() in ("scatter", "native")


def _native_hist(bins, gpair, pos, node0, n_nodes, n_bin, stride):
    """XLA FFI custom call into the native hist kernel (CPU backend only).

    node0 may be traced (the padded shared level program) — it rides as an
    operand.  Works under shard_map: the custom call fires per shard on that
    shard's rows, exactly the partial-histogram semantics the psum expects.

    The kernel is internally multi-threaded (feature-sharded ParallelFor,
    native/xtb_kernels.h) with bitwise-identical output for every nthread;
    ensure_pool() applies the process's thread-count default before the
    first dispatch."""
    import numpy as np

    from ..utils import native

    native.ensure_pool()
    R, F = bins.shape
    C = gpair.shape[1]
    if bins.dtype not in (jnp.uint8, jnp.uint16, jnp.int16, jnp.int32):
        bins = bins.astype(jnp.int32)
    call = native.jax_ffi().ffi_call(
        "xtb_hist",
        jax.ShapeDtypeStruct((n_nodes, F, n_bin, C), jnp.float32))
    return call(bins, gpair.astype(jnp.float32), pos.astype(jnp.int32),
                jnp.asarray(node0, jnp.int32).reshape(1),
                stride=np.int32(stride))


def scatter_hist_driver(bins, values, pos, node0, n_nodes, n_bin, stride,
                        out_cols, dtype, row_chunk: int = 1 << 18):
    """Shared CPU scatter-add scaffolding (flat index construction, stride
    and missing-sentinel masking, chunk-0-outside-the-scan carry rule) for
    the f32 and quantised-limb histograms: O(R*F) adds instead of the
    matmul's O(R*F*B) MACs (~150x faster on one core; XLA's CPU scatter is
    sequential, hence deterministic).  The TPU path keeps the one-hot
    matmul: on the MXU the matmul wins and scatter serializes (the round-1
    design decision this fallback deliberately inverts).

    values: (R, out_cols) already in the accumulator dtype.
    """
    R, F = bins.shape
    M = n_nodes * F * n_bin

    def chunk_add(flat, sl):
        b, g, p = sl
        local = p - node0
        if stride != 1:
            ok = (local >= 0) & (local % stride == 0) \
                & (local // stride < n_nodes)
            node = jnp.where(ok, local // stride, 0)
        else:
            ok = (local >= 0) & (local < n_nodes)
            node = jnp.where(ok, local, 0)
        idx = (node[:, None] * (F * n_bin)
               + jnp.arange(F, dtype=jnp.int32)[None, :] * n_bin
               + jnp.minimum(b.astype(jnp.int32), n_bin - 1))
        # missing sentinel (bin == n_bin) and out-of-level rows add zero
        w = (ok[:, None] & (b.astype(jnp.int32) < n_bin)).astype(dtype)
        vals = g[:, None, :] * w[:, :, None]          # (T, F, out_cols)
        return flat.at[idx.reshape(-1)].add(vals.reshape(-1, out_cols))

    flat = jnp.zeros((M, out_cols), dtype)
    if R <= row_chunk:
        flat = chunk_add(flat, (bins, values, pos))
    else:
        n_chunks = R // row_chunk
        rem = R - n_chunks * row_chunk
        # chunk 0 outside the scan: the carry must already have the
        # shard-varying type under shard_map (same rule as the matmul path)
        flat = chunk_add(flat, (bins[:row_chunk], values[:row_chunk],
                                pos[:row_chunk]))
        xs = (bins[row_chunk: n_chunks * row_chunk].reshape(
                  n_chunks - 1, row_chunk, F),
              values[row_chunk: n_chunks * row_chunk].reshape(
                  n_chunks - 1, row_chunk, out_cols),
              pos[row_chunk: n_chunks * row_chunk].reshape(
                  n_chunks - 1, row_chunk))
        flat, _ = lax.scan(lambda a, sl: (chunk_add(a, sl), None), flat, xs)
        if rem:
            flat = chunk_add(flat, (bins[-rem:], values[-rem:], pos[-rem:]))
    return flat.reshape(n_nodes, F, n_bin, out_cols)


def _hist_accumulate(bins, gpair, pos, node0, n_nodes, n_bin, chunk, stride):
    """Fixed-order chunked accumulation shared by the static- and
    traced-node0 entry points (node0 may be an int or a traced scalar)."""
    impl = _host_impl()
    if impl == "native":
        return _native_hist(bins, gpair, pos, node0, n_nodes, n_bin, stride)
    if impl == "scatter":
        return scatter_hist_driver(bins, gpair, pos, node0, n_nodes, n_bin,
                                   stride, gpair.shape[1], jnp.float32)
    R, F = bins.shape
    C = gpair.shape[1]
    if R <= chunk:
        return _hist_chunk(bins, gpair, pos, node0, n_nodes, n_bin, stride)
    n_chunks = R // chunk
    rem = R - n_chunks * chunk

    def body(acc, xs):
        b, g, p = xs
        return acc + _hist_chunk(b, g, p, node0, n_nodes, n_bin, stride), None

    # seed the carry with chunk 0 (not zeros): under shard_map the chunk
    # contributions vary over the data axis, and a scan carry must enter
    # with the same varying type it leaves with
    acc0 = _hist_chunk(bins[:chunk], gpair[:chunk], pos[:chunk], node0,
                       n_nodes, n_bin, stride)
    xs = (
        bins[chunk: n_chunks * chunk].reshape(n_chunks - 1, chunk, F),
        gpair[chunk: n_chunks * chunk].reshape(n_chunks - 1, chunk, C),
        pos[chunk: n_chunks * chunk].reshape(n_chunks - 1, chunk),
    )
    acc, _ = lax.scan(body, acc0, xs)
    if rem:
        acc = acc + _hist_chunk(bins[-rem:], gpair[-rem:], pos[-rem:], node0,
                                n_nodes, n_bin, stride)
    return acc


@functools.partial(jax.jit, static_argnames=("n_nodes", "n_bin", "chunk",
                                             "stride"))
def build_histogram_at(bins, gpair, pos, node0, *, n_nodes: int, n_bin: int,
                       chunk: int = 2048, stride: int = 1):
    """build_histogram with a TRACED starting node id.

    The best-first grower expands one node pair at a time with fresh ids,
    and the padded level step walks depths with one compiled program; a
    static node0 would recompile the kernel per expansion/depth, so here
    node0 is an operand (it only feeds the node-mask comparison, never a
    shape).
    """
    node0 = jnp.asarray(node0, jnp.int32)
    return _hist_accumulate(bins, gpair, pos, node0, n_nodes, n_bin, chunk,
                            stride)


@functools.partial(jax.jit, static_argnames=("n_nodes", "n_bin", "stride"))
def build_histogram_multi(bins, gpair_rkc, pos_k, node0, *, n_nodes: int,
                          n_bin: int, stride: int = 1):
    """Class-batched histogram: (K, N, F, B, C) for K trees grown in
    lockstep over the SAME bins (multi:softprob one-tree-per-class).

    bins      : (R, F) int — shared binned page
    gpair_rkc : (R, K, C) f32 — per-class gradient pairs
    pos_k     : (K, R) int32 — per-class row routing
    node0     : traced scalar (padded shared level program compatible)

    The level's K histograms ride ONE jitted program (one dispatch, one
    downstream split scan — the reference's all-targets-per-pass shape,
    src/tree/hist/histogram.h:44).  On CPU the K class hists are built by
    K sequential native calls INSIDE that program rather than a fused
    row pass: a fused row-pass kernel was prototyped and measured ~40%
    SLOWER at covertype shapes (interleaving K node blocks per row blows
    the L2 working set), so it was dropped; the sequential calls keep one
    class's blocks hot and are bitwise-identical to the per-class grower
    by construction.  The XLA fallback vmaps the one-hot matmul — on the
    MXU the K axis just widens the output tile, the shape the TPU wants.
    """
    K = gpair_rkc.shape[1]
    node0 = jnp.asarray(node0, jnp.int32)
    if _host_impl() == "native":
        return jnp.stack([
            _native_hist(bins, gpair_rkc[:, k, :], pos_k[k], node0,
                         n_nodes, n_bin, stride)
            for k in range(K)])
    gpair_krc = jnp.moveaxis(gpair_rkc, 1, 0)  # (K, R, C)
    return jax.vmap(
        lambda g, p: _hist_accumulate(bins, g, p, node0, n_nodes, n_bin,
                                      2048, stride))(gpair_krc, pos_k)


def combine_sibling_hists(left, hist_prev, alive_lvl):
    """Subtraction trick assembly, shared by every grower flavour
    (updater_gpu_hist.cu:309 SubtractHist): given the built left-children
    histogram ``left`` (N/2, ...) and the parent level's ``hist_prev``
    (N/2, ...), derive each right sibling as parent - left and interleave to
    the (N, ...) level layout.  Slots whose parent did not split are zeroed
    (their "derived" hist would otherwise inherit the whole parent
    histogram).  Works for scalar (N,F,B,2) and multi-target (N,F,B,K,2)."""
    right = hist_prev - left
    N = 2 * left.shape[0]
    hist = jnp.stack([left, right], axis=1).reshape(N, *left.shape[1:])
    return hist * alive_lvl.reshape((N,) + (1,) * (hist.ndim - 1))


@functools.partial(jax.jit, static_argnames=("node0", "n_nodes"))
def node_sums(gpair, pos, *, node0: int, n_nodes: int):
    """Per-node gradient totals: (N, C) — masked segment sum, MXU-friendly.

    Used for the root sum (reference: updater_gpu_hist.cu:581 InitRoot device
    reduce followed by collective::GlobalSum).
    """
    nodemask = (pos[:, None] == (node0 + jnp.arange(n_nodes, dtype=pos.dtype))).astype(
        jnp.float32
    )
    return jnp.dot(nodemask.T, gpair, preferred_element_type=jnp.float32)
