"""Pallas TPU histogram kernel — the production hot path.

The CUDA reference builds histograms with shared-memory atomics
(src/tree/gpu_hist/histogram.cu:37-120).  TPU has no atomics; the masked
one-hot matmul formulation (ops/histogram.py) is MXU-shaped, but the plain XLA
lowering materializes the (rows, F*B) one-hot operand in HBM — hundreds of GB
of traffic per level at HIGGS scale.  This kernel fuses one-hot construction
into VMEM so HBM sees only: bins read once (R*F bytes), gpair read once per
feature group, histogram written once.

Layout:
  grid = (F/FG feature groups, R/T row tiles)   [both arbitrary/sequential]
  per step: bins tile (T, FG) + gpair tile (T, 2) + pos tile (T, 1) in VMEM
  out block (FG, B, 2N) stays VMEM-resident across the row-tile loop of one
  feature group (index_map ignores the row index) and accumulates f32 matmuls:
      hist[f] += onehot(bins[:, f]).T @ (nodemask * gpair)    # (B,T)@(T,2N)
  MXU shapes: M=B (256), K=T (512), N=2N -> full utilization at depth >= 6.

Determinism: sequential grid, f32 accumulation, no atomics — the property the
reference buys with int64 fixed-point quantisation (quantiser.cuh:52).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams across 0.4 -> 0.5+
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

# sweep overrides (scripts/pallas_hw_sweep.py); None = VMEM-budget autotune
_ROW_TILE = None
_FEAT_GROUP = None

# Per-core VMEM working budget.  v5e/v5p expose ~128 MiB of VMEM; leaving
# headroom for the compiler's own temporaries and double-buffering slack,
# 64 MiB is the planning number (the role of the reference's CacheManager
# L1/L2 detection for CPU hist blocking, src/common/cache_manager.h — there
# the cache sizes block the CPU hist loop, here the VMEM budget blocks the
# MXU hist kernel).
_VMEM_BUDGET = 64 * 2**20


def choose_tiles(n_features: int, n_bin: int, n_nodes: int,
                 bin_itemsize: int = 1,
                 vmem_budget: int = _VMEM_BUDGET, out_ch: int = 2) -> tuple:
    """Pick (row_tile, feat_group) that fits the VMEM budget.

    Working set per grid step:
      - persistent out block: FG * B * out_ch*N * 4 bytes (lives across row
        tiles; out_ch = 2 for the f32 (g,h) kernel, 6 for the quantised
        (g,h) x 3-limb kernel)
      - double-buffered inputs: 2 * T * (FG*itemsize + 8 + 4)
      - scratch (one feature at a time in the unrolled loop):
        onehot T*B*4 + node-masked gpair T*out_ch*N*4 + nodemask T*N*4
    Preference order: biggest row tile first (deeper MXU K dim), then the
    widest feature group that still fits — the shapes the hardware sweep
    showed to matter most.  Always returns something runnable (1, 256).
    """
    for t in (2048, 1024, 512, 256):
        for fg in (16, 8, 4, 2, 1):
            if fg > max(n_features, 1):
                continue
            out_b = fg * n_bin * out_ch * n_nodes * 4
            in_b = 2 * t * (fg * bin_itemsize + 8 + 4)
            scratch = (t * n_bin * 4 + t * out_ch * n_nodes * 4
                       + t * n_nodes * 4)
            if out_b + in_b + scratch <= vmem_budget:
                return t, fg
    return 256, 1


def _hist_kernel(bins_ref, gpair_ref, pos_ref, out_ref, *, node0: int,
                 n_nodes: int, n_bin: int, feat_group: int, stride: int):
    i = pl.program_id(1)  # row-tile index (innermost)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    pos = pos_ref[:, 0]  # (T,)
    gpair = gpair_ref[:, :2]  # (T, 2)
    nodes = node0 + stride * jax.lax.iota(jnp.int32, n_nodes)
    nodemask = (pos[:, None] == nodes[None, :]).astype(jnp.float32)  # (T, N)
    T = gpair.shape[0]
    gm = (nodemask[:, :, None] * gpair[:, None, :]).reshape(T, n_nodes * 2)

    bin_ids = jax.lax.iota(jnp.int32, n_bin)
    for f in range(feat_group):  # static unroll
        b = bins_ref[:, f].astype(jnp.int32)  # (T,)
        onehot = (b[:, None] == bin_ids[None, :]).astype(jnp.float32)  # (T, B)
        acc = jax.lax.dot_general(
            onehot, gm,
            dimension_numbers=(((0,), (0,)), ((), ())),  # contract rows: (B, 2N)
            preferred_element_type=jnp.float32,
        )
        out_ref[f] = out_ref[f] + acc


@functools.partial(
    jax.jit, static_argnames=("node0", "n_nodes", "n_bin", "interpret",
                              "stride", "row_tile", "feat_group")
)
def build_histogram_pallas(bins, gpair, pos, *, node0: int, n_nodes: int,
                           n_bin: int, interpret=None, stride: int = 1,
                           row_tile: int = 0, feat_group: int = 0):
    """hist (n_nodes, F, B, 2) — drop-in for ops/histogram.build_histogram.

    bins (R_pad, F) int (sentinel == n_bin for missing), gpair (R_pad, 2) f32,
    pos (R_pad,) int32.  Rows are padded up to the row tile internally
    (pad rows carry pos = -1, matching no node).  ``row_tile``/``feat_group``
    of 0 select the VMEM-budget autotune (choose_tiles); the module globals
    remain overridable for sweeps.
    """
    if interpret is None:
        # auto: lower to Mosaic on TPU, run the Pallas interpreter elsewhere
        # so the hist_impl="pallas" grower path works (slowly) off-TPU
        interpret = jax.default_backend() != "tpu"
    R, F = bins.shape
    # explicit kwargs > module-global sweep override > autotune; a partial
    # override (one of the two) autotunes only the missing dimension
    T = row_tile or _ROW_TILE
    FG = feat_group or _FEAT_GROUP
    if not (T and FG):
        at, afg = choose_tiles(F, n_bin, n_nodes, bins.dtype.itemsize)
        T, FG = T or at, FG or afg
    if R % T:
        pad = T - R % T
        bins = jnp.pad(bins, ((0, pad), (0, 0)), constant_values=n_bin)
        gpair = jnp.pad(gpair, ((0, pad), (0, 0)))
        pos = jnp.pad(pos, (0, pad), constant_values=-1)
        R += pad
    n_fg = (F + FG - 1) // FG
    F_pad = n_fg * FG

    kernel = functools.partial(
        _hist_kernel, node0=node0, n_nodes=n_nodes, n_bin=n_bin, feat_group=FG,
        stride=stride,
    )
    out = pl.pallas_call(
        kernel,
        grid=(n_fg, R // T),
        in_specs=[
            pl.BlockSpec((T, FG), lambda fg, i: (i, fg), memory_space=pltpu.VMEM),
            pl.BlockSpec((T, 2), lambda fg, i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((T, 1), lambda fg, i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (FG, n_bin, 2 * n_nodes), lambda fg, i: (fg, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((F_pad, n_bin, 2 * n_nodes), jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * R * F_pad * n_bin * 2 * n_nodes,
            bytes_accessed=R * F_pad * bins.dtype.itemsize + R * 8 * n_fg
            + F_pad * n_bin * 2 * n_nodes * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(bins, gpair, pos[:, None].astype(jnp.int32))
    # (F_pad, B, 2N) -> (N, F, B, 2)
    hist = out[:F].reshape(F, n_bin, n_nodes, 2).transpose(2, 0, 1, 3)
    return hist


def _hist_kernel_q(bins_ref, gq_ref, pos_ref, out_ref, *, node0: int,
                   n_nodes: int, n_bin: int, feat_group: int, stride: int,
                   n_ch: int):
    """Quantised variant: int8 one-hot x int8 limb operand -> int32 MXU
    accumulation.  Integer partial sums are exact and associative, so the
    kernel output is bitwise identical for ANY grid order or topology — the
    reference's GradientQuantiser contract (quantiser.cuh:52) inside the
    production kernel."""
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    pos = pos_ref[:, 0]  # (T,)
    gq = gq_ref[:, :n_ch]  # (T, C*3) int8 limbs
    nodes = node0 + stride * jax.lax.iota(jnp.int32, n_nodes)
    nodemask = (pos[:, None] == nodes[None, :]).astype(jnp.int8)  # (T, N)
    T = gq.shape[0]
    # 0/1 mask times a limb is the limb: product stays int8-safe
    gm = (nodemask[:, :, None] * gq[:, None, :]).reshape(T, n_nodes * n_ch)

    bin_ids = jax.lax.iota(jnp.int32, n_bin)
    for f in range(feat_group):  # static unroll
        b = bins_ref[:, f].astype(jnp.int32)
        onehot = (b[:, None] == bin_ids[None, :]).astype(jnp.int8)  # (T, B)
        acc = jax.lax.dot_general(
            onehot, gm,
            dimension_numbers=(((0,), (0,)), ((), ())),  # (B, N*n_ch)
            preferred_element_type=jnp.int32,
        )
        out_ref[f] = out_ref[f] + acc


@functools.partial(
    jax.jit, static_argnames=("node0", "n_nodes", "n_bin", "interpret",
                              "stride", "row_tile", "feat_group")
)
def build_histogram_pallas_q(bins, gq, pos, *, node0: int, n_nodes: int,
                             n_bin: int, interpret=None,
                             stride: int = 1, row_tile: int = 0,
                             feat_group: int = 0):
    """Quantised Pallas histogram: (n_nodes, F, B, C, 3) int32 — drop-in for
    ops/quantise.hist_accumulate_q on TPU, keeping the bitwise
    topology-free determinism contract inside the fused VMEM kernel.

    gq (R_pad, C, 3) int8 signed base-256 limbs (ops/quantise.quantise_gpair).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    R, F = bins.shape
    C, L = gq.shape[1], gq.shape[2]
    n_ch = C * L
    gq = gq.reshape(R, n_ch)
    T = row_tile or _ROW_TILE
    FG = feat_group or _FEAT_GROUP
    if not (T and FG):
        at, afg = choose_tiles(F, n_bin, n_nodes, bins.dtype.itemsize,
                               out_ch=n_ch)
        T, FG = T or at, FG or afg
    if R % T:
        pad = T - R % T
        bins = jnp.pad(bins, ((0, pad), (0, 0)), constant_values=n_bin)
        gq = jnp.pad(gq, ((0, pad), (0, 0)))
        pos = jnp.pad(pos, (0, pad), constant_values=-1)
        R += pad
    n_fg = (F + FG - 1) // FG
    F_pad = n_fg * FG

    kernel = functools.partial(
        _hist_kernel_q, node0=node0, n_nodes=n_nodes, n_bin=n_bin,
        feat_group=FG, stride=stride, n_ch=n_ch,
    )
    out = pl.pallas_call(
        kernel,
        grid=(n_fg, R // T),
        in_specs=[
            pl.BlockSpec((T, FG), lambda fg, i: (i, fg), memory_space=pltpu.VMEM),
            pl.BlockSpec((T, n_ch), lambda fg, i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((T, 1), lambda fg, i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (FG, n_bin, n_ch * n_nodes), lambda fg, i: (fg, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((F_pad, n_bin, n_ch * n_nodes),
                                       jnp.int32),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * R * F_pad * n_bin * n_ch * n_nodes,
            bytes_accessed=R * F_pad * bins.dtype.itemsize + R * n_ch * n_fg
            + F_pad * n_bin * n_ch * n_nodes * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(bins, gq, pos[:, None].astype(jnp.int32))
    # (F_pad, B, N*C*L) -> (N, F, B, C, L)
    hist = out[:F].reshape(F, n_bin, n_nodes, C, L).transpose(2, 0, 1, 3, 4)
    return hist
