"""Pallas TPU histogram kernel — the production hot path.

The CUDA reference builds histograms with shared-memory atomics
(src/tree/gpu_hist/histogram.cu:37-120).  TPU has no atomics; the masked
one-hot matmul formulation (ops/histogram.py) is MXU-shaped, but the plain XLA
lowering materializes the (rows, F*B) one-hot operand in HBM — hundreds of GB
of traffic per level at HIGGS scale.  This kernel fuses one-hot construction
into VMEM so HBM sees only: bins read once (R*F bytes), gpair read once per
feature group, histogram written once.

Layout:
  grid = (F/FG feature groups, R/T row tiles)   [both arbitrary/sequential]
  per step: bins tile (T, FG) + gpair tile (T, 2) + pos tile (T, 1) in VMEM
  out block (FG, B, 2N) stays VMEM-resident across the row-tile loop of one
  feature group (index_map ignores the row index) and accumulates f32 matmuls:
      hist[f] += onehot(bins[:, f]).T @ (nodemask * gpair)    # (B,T)@(T,2N)
  MXU shapes: M=B (256), K=T (512), N=2N -> full utilization at depth >= 6.

Determinism: sequential grid, f32 accumulation, no atomics — the property the
reference buys with int64 fixed-point quantisation (quantiser.cuh:52).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_ROW_TILE = 512
_FEAT_GROUP = 4


def _hist_kernel(bins_ref, gpair_ref, pos_ref, out_ref, *, node0: int,
                 n_nodes: int, n_bin: int, feat_group: int, stride: int):
    i = pl.program_id(1)  # row-tile index (innermost)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    pos = pos_ref[:, 0]  # (T,)
    gpair = gpair_ref[:, :2]  # (T, 2)
    nodes = node0 + stride * jax.lax.iota(jnp.int32, n_nodes)
    nodemask = (pos[:, None] == nodes[None, :]).astype(jnp.float32)  # (T, N)
    T = gpair.shape[0]
    gm = (nodemask[:, :, None] * gpair[:, None, :]).reshape(T, n_nodes * 2)

    bin_ids = jax.lax.iota(jnp.int32, n_bin)
    for f in range(feat_group):  # static unroll
        b = bins_ref[:, f].astype(jnp.int32)  # (T,)
        onehot = (b[:, None] == bin_ids[None, :]).astype(jnp.float32)  # (T, B)
        acc = jax.lax.dot_general(
            onehot, gm,
            dimension_numbers=(((0,), (0,)), ((), ())),  # contract rows: (B, 2N)
            preferred_element_type=jnp.float32,
        )
        out_ref[f] = out_ref[f] + acc


@functools.partial(
    jax.jit, static_argnames=("node0", "n_nodes", "n_bin", "interpret", "stride")
)
def build_histogram_pallas(bins, gpair, pos, *, node0: int, n_nodes: int,
                           n_bin: int, interpret: bool = False, stride: int = 1):
    """hist (n_nodes, F, B, 2) — drop-in for ops/histogram.build_histogram.

    bins (R_pad, F) int (sentinel == n_bin for missing), gpair (R_pad, 2) f32,
    pos (R_pad,) int32.  Rows are padded up to the 512 row tile internally
    (pad rows carry pos = -1, matching no node).
    """
    R, F = bins.shape
    T = _ROW_TILE
    FG = _FEAT_GROUP
    if R % T:
        pad = T - R % T
        bins = jnp.pad(bins, ((0, pad), (0, 0)), constant_values=n_bin)
        gpair = jnp.pad(gpair, ((0, pad), (0, 0)))
        pos = jnp.pad(pos, (0, pad), constant_values=-1)
        R += pad
    n_fg = (F + FG - 1) // FG
    F_pad = n_fg * FG

    kernel = functools.partial(
        _hist_kernel, node0=node0, n_nodes=n_nodes, n_bin=n_bin, feat_group=FG,
        stride=stride,
    )
    out = pl.pallas_call(
        kernel,
        grid=(n_fg, R // T),
        in_specs=[
            pl.BlockSpec((T, FG), lambda fg, i: (i, fg), memory_space=pltpu.VMEM),
            pl.BlockSpec((T, 2), lambda fg, i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((T, 1), lambda fg, i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (FG, n_bin, 2 * n_nodes), lambda fg, i: (fg, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((F_pad, n_bin, 2 * n_nodes), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * R * F_pad * n_bin * 2 * n_nodes,
            bytes_accessed=R * F_pad * bins.dtype.itemsize + R * 8 * n_fg
            + F_pad * n_bin * 2 * n_nodes * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(bins, gpair, pos[:, None].astype(jnp.int32))
    # (F_pad, B, 2N) -> (N, F, B, 2)
    hist = out[:F].reshape(F, n_bin, n_nodes, 2).transpose(2, 0, 1, 3)
    return hist
