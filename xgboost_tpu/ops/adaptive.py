"""Adaptive (quantile) leaf updates for absolute/quantile error objectives.

Reference: src/objective/adaptive.cc/.cu (ObjFunction::UpdateTreeLeaf,
objective.h:129): after the tree is grown and every row sits on its leaf,
replace each leaf value with eta * alpha-quantile of the residuals
(y - margin_before_tree) of its rows — the exact minimizer for pinball/L1
loss that the second-order approximation cannot reach.

TPU formulation: one lexicographic ``lax.sort`` by (leaf id, residual), then
per-leaf quantile gather via searchsorted on the sorted leaf ids — no dynamic
shapes, no per-leaf loops.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.jit, static_argnames=("max_nodes",))
def segment_quantile_leaf(pos, residual, valid, leaf_mask, alpha, eta,
                          *, max_nodes: int):
    """Per-leaf residual quantiles.

    pos      : (R,) int32 — leaf node id per row (-1 padded)
    residual : (R,) f32 — y - margin (before this tree)
    valid    : (R,) bool
    leaf_mask: (max_nodes,) bool — which heap slots are leaves
    Returns (max_nodes,) f32 leaf values (eta-scaled), zeros for non-leaves.
    """
    R = pos.shape[0]
    big = jnp.int32(max_nodes)
    key = jnp.where(valid, pos, big)  # padded rows sort to the end
    # lexicographic sort by (leaf, residual)
    sk, sr = lax.sort((key, residual), num_keys=2)
    # segment boundaries per node id
    node_ids = jnp.arange(max_nodes, dtype=jnp.int32)
    starts = jnp.searchsorted(sk, node_ids, side="left")
    ends = jnp.searchsorted(sk, node_ids, side="right")
    cnt = (ends - starts).astype(jnp.float32)
    # linear-interpolated quantile index within each segment
    q = alpha * jnp.maximum(cnt - 1.0, 0.0)
    lo = jnp.floor(q).astype(jnp.int32)
    frac = q - lo.astype(jnp.float32)
    i0 = jnp.clip(starts + lo, 0, R - 1)
    i1 = jnp.clip(starts + jnp.minimum(lo + 1, jnp.maximum(ends - starts - 1, 0)), 0, R - 1)
    v = sr[i0] * (1.0 - frac) + sr[i1] * frac
    ok = leaf_mask & (cnt > 0)
    return jnp.where(ok, eta * v, 0.0)
