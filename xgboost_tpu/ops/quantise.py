"""Fixed-point gradient quantisation — order-invariant histogram sums.

The role of the reference's GradientQuantiser (src/tree/gpu_hist/
quantiser.cuh:52): there, gradients become int64 fixed-point so that atomic
adds and the NCCL allreduce are EXACT integer sums, making gpu_hist bitwise
reproducible across any worker/GPU topology.  The default path here gets
per-topology determinism from fixed-order f32 accumulation, but f32 sums
change bits when the REDUCTION SHAPE changes (4-chip psum vs 1-chip scan),
so deep near-tie splits can flip across topologies.

TPU-native equivalent: quantise (g, h) to 22-bit signed fixed point against
a global per-round scale, split each value into three signed int8 limbs
(base 256), and build the histogram as int8 x int8 -> int32 matmuls — the
MXU's native integer path.  Integer partial sums are exact and associative,
so chunk order, chip count (lax.psum over int32), and process count (host
int64 allreduce) all produce identical bits; the one rounding step is a
single deterministic elementwise dequantise AFTER all reductions.

Budget proof (why this is exact):
 - |q| <= 2**22 - 1, so limb 2 after the two base-256 extractions lies in
   [-65, 65] — comfortably int8;
 - a limb-histogram entry accumulates at most R * 128 on device, int32-safe
   up to R = 2**24 (16.7M) rows PER PROCESS — covering the 11M-row HIGGS
   ladder with headroom; every quantised grower entry calls
   ``check_row_budget`` before accumulating, so overflow raises instead of
   wrapping;
 - the cross-process reduction runs (and stays) in int64 on host — no
   global row bound — and ``dequantise`` applies the same elementwise f32
   formula to either limb width, so every topology shares one rounding step.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

# 22-bit signed fixed point: limb decomposition stays int8-safe (see proof
# above) and resolution 2**-22 of the max-gradient scale sits at f32's own
# mantissa floor, so no training-visible precision is lost vs the f32 path.
QUANT_BITS = 22
_QMAX = float((1 << QUANT_BITS) - 1)
# int32 limb-accumulator budget: R_global * 128 must stay below 2**31
MAX_ROWS = 1 << 24


@jax.jit
def local_rho(gpair, valid):
    """Per-channel max |gradient| over valid rows: (C,) f32.

    max is associative/idempotent, so psum-max across chips and host MAX
    allreduce across processes reproduce the same value on every topology
    (the reference derives its scale from global sums the same way,
    quantiser.cuh:23 via InitRoot's allreduce).
    """
    g = jnp.abs(gpair) * valid[:, None].astype(gpair.dtype)
    return jnp.max(g, axis=0)


@jax.jit
def quantise_gpair(gpair, rho):
    """(R, C) f32 -> (R, C, 3) int8 signed base-256 limbs of the fixed-point
    gradient q = round(g / rho * (2**22 - 1))."""
    scale = _QMAX / jnp.maximum(rho, 1e-30)
    q = jnp.clip(jnp.round(gpair * scale[None, :]), -_QMAX, _QMAX).astype(
        jnp.int32)
    limbs = []
    for _ in range(2):
        l = ((q + 128) & 255) - 128          # signed low limb in [-128, 127]
        limbs.append(l)
        q = (q - l) >> 8                     # exact: q - l divisible by 256
    limbs.append(q)                          # |top| <= 65
    return jnp.stack(limbs, axis=-1).astype(jnp.int8)


def _hist_chunk_q(bins_c, gq_c, pos_c, node0, n_nodes: int, n_bin: int,
                  stride: int = 1):
    """One row-chunk's int32 limb histogram: (N, F, B, C, 3).

    Same masked one-hot matmul as the f32 kernel (histogram.py:_hist_chunk)
    but in int8 operands with int32 accumulation — exact, and on TPU the
    MXU's int8 path, so determinism costs no matmul throughput.
    """
    T, F = bins_c.shape
    C, L = gq_c.shape[1], gq_c.shape[2]
    onehot = (bins_c.astype(jnp.int32)[:, :, None]
              == jnp.arange(n_bin, dtype=jnp.int32)).astype(jnp.int8)
    nodemask = (pos_c[:, None]
                == (node0 + stride * jnp.arange(n_nodes, dtype=pos_c.dtype))
                ).astype(jnp.int8)  # (T, N)
    # (T, N*C*L) — int8 product of a 0/1 mask and a limb is the limb
    gm = (nodemask[:, :, None] * gq_c.reshape(T, 1, C * L)).reshape(
        T, n_nodes * C * L)
    out = jnp.dot(onehot.reshape(T, F * n_bin).T, gm,
                  preferred_element_type=jnp.int32)
    return out.reshape(F, n_bin, n_nodes, C, L).transpose(2, 0, 1, 3, 4)


def hist_accumulate_q(bins, gq, pos, node0, n_nodes: int, n_bin: int,
                      chunk: int = 2048, stride: int = 1):
    """Chunked exact int32 limb-histogram accumulation (any chunk order
    produces identical bits — integer addition is associative)."""
    from .histogram import _host_impl, scatter_hist_driver

    impl = _host_impl()
    if impl == "native":
        # native int32 limb row pass (native/xtb_kernels.h xtb_hist_q):
        # exactness makes the accumulation order irrelevant, so the
        # deterministic contract rides the same kernel speed as f32
        import numpy as np

        from ..utils import native

        native.ensure_pool()
        R, F = bins.shape
        C, L = gq.shape[1], gq.shape[2]
        b = bins
        if b.dtype not in (jnp.uint8, jnp.uint16, jnp.int16, jnp.int32):
            b = b.astype(jnp.int32)
        call = native.jax_ffi().ffi_call(
            "xtb_hist_q",
            jax.ShapeDtypeStruct((n_nodes, F, n_bin, C * L), jnp.int32))
        flat = call(b, gq.reshape(R, C * L), pos.astype(jnp.int32),
                    jnp.asarray(node0, jnp.int32).reshape(1),
                    stride=np.int32(stride))
        return flat.reshape(n_nodes, F, n_bin, C, L)
    if impl == "scatter":
        C, L = gq.shape[1], gq.shape[2]
        flat = scatter_hist_driver(
            bins, gq.reshape(gq.shape[0], C * L).astype(jnp.int32), pos,
            node0, n_nodes, n_bin, stride, C * L, jnp.int32)
        return flat.reshape(flat.shape[:3] + (C, L))
    R, F = bins.shape
    if R <= chunk:
        return _hist_chunk_q(bins, gq, pos, node0, n_nodes, n_bin, stride)
    n_chunks = R // chunk
    rem = R - n_chunks * chunk

    def body(acc, xs):
        b, g, p = xs
        return acc + _hist_chunk_q(b, g, p, node0, n_nodes, n_bin, stride), None

    # carry seeded with chunk 0: under shard_map the contributions vary
    # over the data axis and the scan carry type must match (histogram.py
    # _hist_accumulate has the same rule)
    C, L = gq.shape[1], gq.shape[2]
    acc0 = _hist_chunk_q(bins[:chunk], gq[:chunk], pos[:chunk], node0,
                         n_nodes, n_bin, stride)
    xs = (bins[chunk: n_chunks * chunk].reshape(n_chunks - 1, chunk, F),
          gq[chunk: n_chunks * chunk].reshape(n_chunks - 1, chunk, C, L),
          pos[chunk: n_chunks * chunk].reshape(n_chunks - 1, chunk))
    acc, _ = lax.scan(body, acc0, xs)
    if rem:
        acc = acc + _hist_chunk_q(bins[-rem:], gq[-rem:], pos[-rem:], node0,
                                  n_nodes, n_bin, stride)
    return acc


@functools.partial(jax.jit, static_argnames=("n_nodes", "n_bin", "chunk",
                                             "stride"))
def build_histogram_q(bins, gq, pos, node0, *, n_nodes: int, n_bin: int,
                      chunk: int = 2048, stride: int = 1):
    """Traced-node0 quantised histogram build: (N, F, B, C, 3) int32."""
    node0 = jnp.asarray(node0, jnp.int32)
    return hist_accumulate_q(bins, gq, pos, node0, n_nodes, n_bin, chunk,
                             stride)


@jax.jit
def node_sums_q(gq, pos, node0, n_nodes_arr):
    """Per-node quantised gradient totals: (N, C, 3) int32 — exact.

    n_nodes_arr is a length-N arange (static shape carrier); node ids are
    node0 + that range.
    """
    nodemask = (pos[:, None]
                == (node0 + n_nodes_arr)[None, :]).astype(jnp.int8)
    C, L = gq.shape[1], gq.shape[2]
    out = jnp.dot(nodemask.T, gq.reshape(gq.shape[0], C * L),
                  preferred_element_type=jnp.int32)
    return out.reshape(-1, C, L)


@jax.jit
def dequantise(hist_q, rho):
    """int32 limb sums -> f32 values: THE one rounding step, applied after
    every reduction so all topologies share this exact compiled formula.

    hist_q: (..., C, 3) int32;  rho: (C,) f32.
    """
    f = hist_q.astype(jnp.float32)
    combined = f[..., 0] + 256.0 * f[..., 1] + 65536.0 * f[..., 2]
    return combined * (rho / _QMAX)


def quantised_root_state(state, gq, rho, *, axis_name=None,
                         process_reduce: bool = False):
    """Replace the f32 root totals with the exactly-reduced quantised root
    sum (InitRoot + GlobalSum, updater_gpu_hist.cu:581, in fixed point):
    f32 root sums change bits with the reduction shape, quantised ones
    cannot."""
    root = node_sums_q(gq, state.pos, jnp.int32(0),
                       jnp.arange(1, dtype=jnp.int32))
    if axis_name is not None:
        root = jax.lax.psum(root, axis_name)
    if process_reduce:
        root = allreduce_limbs(root)
    totals0 = dequantise(root, rho)[0]
    return state._replace(totals=state.totals.at[0].set(totals0))


def check_row_budget(n_rows: int) -> None:
    """Enforce the int32 limb-accumulator budget BEFORE any device
    accumulation can wrap: per-process padded rows x 128 must stay below
    2**31.  Called by every quantised grower entry point."""
    if n_rows > MAX_ROWS:
        raise ValueError(
            f"deterministic_histogram supports up to {MAX_ROWS} rows per "
            f"process (int32 limb-accumulator budget); got {n_rows}.  Shard "
            "rows over more processes, or use the default f32 histogram.")


def prepare_quantised(gpair, valid, state, *, distributed: bool = False,
                      axis_name=None):
    """The shared quantised-training entry sequence used by every grower
    flavour (single-chip, shard_map mesh, process, streaming): row-budget
    check, global per-channel scale (chip max via GSPMD/psum is exact;
    process max via host MAX allreduce), gradient limb quantisation, and
    the exactly-reduced root totals.  Returns (gq, rho, state).
    """
    check_row_budget(gpair.shape[0])
    rho = local_rho(gpair, valid)
    if axis_name is not None:
        rho = jax.lax.pmax(rho, axis_name)
    if distributed:
        import numpy as np

        from .. import collective

        rho = jnp.asarray(collective.allreduce(np.asarray(rho),
                                               collective.Op.MAX))
    gq = quantise_gpair(gpair, rho)
    state = quantised_root_state(state, gq, rho, axis_name=axis_name,
                                 process_reduce=distributed)
    return gq, rho, state


def allreduce_limbs(hist_q) -> "jnp.ndarray":
    """Cross-process exact limb reduction: gather int32 limbs, sum in int64
    on host (order-free), and hand the int64 limbs back — dequantise casts
    each limb to f32 the same way for either width, so every topology still
    shares one rounding formula.  The role of the reference's integer NCCL
    allreduce (quantiser.cuh + comm.cuh AllReduce<kInt64>)."""
    import numpy as np

    from .. import collective

    return jnp.asarray(collective.allreduce(
        np.asarray(hist_q).astype(np.int64)))
