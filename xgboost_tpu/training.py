"""train() / cv() drivers (reference: python-package/xgboost/training.py:53,435).

The loop shape matches the reference exactly: callbacks wrap a plain
``bst.update`` per round; cv() builds stratified/group folds (CVPack,
training.py:212) and aggregates fold metrics.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .callback import CallbackContainer, EarlyStopping, EvaluationMonitor, TrainingCallback
from .core import Booster
from .data.dmatrix import DMatrix
from .data.extmem import ExtMemConfig
from .elastic import ElasticConfig, RegroupRequired, ShardMap

__all__ = ["train", "cv"]


def _elastic_data(cfg: ElasticConfig, shard_map: ShardMap, rank: int,
                  world: int, default_evals: list):
    """(dtrain, evals) from the user's data_fn — which may return just the
    DMatrix or a (DMatrix, evals) pair when evals re-shard too."""
    built = cfg.data_fn(shard_map, rank, world)
    if isinstance(built, tuple):
        dtrain, ev = built
        return dtrain, list(ev) if ev else []
    return built, default_evals


def _elastic_shard_map(cfg: ElasticConfig, resumed, world: int) -> ShardMap:
    """The canonical shard map at ``world``: restored from the checkpoint
    when one exists (the dead rank's shards re-assign from what was
    actually saved), else created fresh; rebalanced if the world moved.
    ``cfg.num_shards`` is resolved to the initial world size at train()
    entry, so a fresh restart after a pre-checkpoint death keeps the
    ORIGINAL shard universe — absorption back to full strength stays
    possible."""
    smap = None
    if resumed is not None and resumed.shard_map:
        smap = ShardMap.from_dict(resumed.shard_map)
    if smap is None:
        smap = ShardMap.create(cfg.num_shards or world, world)
    if smap.world != world:
        if world > smap.num_shards:
            raise RuntimeError(
                f"cannot regroup to world {world}: this run's shard "
                f"universe has only {smap.num_shards} shards and a rank "
                "with no data cannot train; set ElasticConfig(num_shards=) "
                "to at least the largest world you intend to absorb to "
                "(e.g. 2x the worker count)")
        smap = smap.rebalance(world)
    return smap


def _restore_booster(params, resumed) -> Booster:
    """Booster from a checkpoint's serialized bytes — shared by the
    resume_from start path and in-process elastic regroup recovery so the
    restore semantics (config re-apply, early-stopping best re-exposure)
    cannot drift apart."""
    bst = Booster(params)
    bst.unserialize(resumed.booster_bytes)
    bst.set_param(params)
    bi = bst.attr("best_iteration")
    if bi is not None:  # re-expose early-stopping bests on the object
        bst.best_iteration = int(bi)
        bs = bst.attr("best_score")
        bst.best_score = float(bs) if bs is not None else None
    return bst


def _elastic_regroup(params, cfg: ElasticConfig, cbs, callbacks, ckpt_cb,
                     evals, completed_hint: int):
    """Round-boundary regroup with re-entry: join the new epoch, reload
    training state from the newest checkpoint, rebuild this rank's data
    from the rebalanced shard map.  Membership can change AGAIN while
    recovery is in flight (another death, a replacement arriving) — the
    new epoch's first collective then raises RegroupRequired from inside
    recovery itself, so the whole sequence simply re-enters.  Returns
    (bst, dtrain, evals, next_round)."""
    while True:
        try:
            return _elastic_regroup_once(params, cfg, cbs, callbacks,
                                         ckpt_cb, evals, completed_hint)
        except RegroupRequired:
            continue


def _elastic_regroup_once(params, cfg: ElasticConfig, cbs, callbacks,
                          ckpt_cb, evals, completed_hint: int):
    import time

    from . import collective
    from .elastic import instruments as _elastic_ins
    from .reliability.checkpoint import (latest_checkpoint,
                                         restore_callback_state)

    t0 = time.perf_counter()
    rank, world = collective.regroup(completed_hint)
    resumed = latest_checkpoint(cfg.checkpoint_dir)
    smap = _elastic_shard_map(cfg, resumed, world)
    dtrain, evals = _elastic_data(cfg, smap, rank, world, evals)
    if resumed is not None:
        bst = _restore_booster(params, resumed)
        # REPLACE the in-memory history with the checkpoint's: the partial
        # round being abandoned must not leave duplicate eval entries when
        # the round is re-run at the new world size
        cbs.history.clear()
        for name, metrics in resumed.history.items():
            cbs.history[name] = {k: list(v) for k, v in metrics.items()}
        restore_callback_state(callbacks, resumed.callback_state)
        next_round = resumed.round
    else:
        # death before the first checkpoint: the survivors restart from
        # round 0 at the reduced world size — with callback state reset
        # too (EarlyStopping best/patience from the abandoned rounds must
        # not leak into the restarted run)
        bst = Booster(params, cache=[dtrain])
        cbs.history.clear()
        for cb in callbacks:
            fn = getattr(cb, "load_state", None)
            if fn is not None and getattr(cb, "state_dict", None) is not None:
                fn({})
        next_round = 0
    ckpt_cb.shard_map = smap.to_dict()
    ins = _elastic_ins()
    ins[0].inc()
    ins[2].observe(time.perf_counter() - t0)
    return bst, dtrain, evals, next_round


def train(
    params: Dict[str, Any],
    dtrain: Optional[DMatrix] = None,
    num_boost_round: int = 10,
    *,
    evals: Optional[Sequence[Tuple[DMatrix, str]]] = None,
    obj: Optional[Callable] = None,
    maximize: Optional[bool] = None,
    early_stopping_rounds: Optional[int] = None,
    evals_result: Optional[dict] = None,
    verbose_eval: Union[bool, int, None] = True,
    xgb_model: Optional[Union[str, Booster]] = None,
    callbacks: Optional[Sequence[TrainingCallback]] = None,
    custom_metric: Optional[Callable] = None,
    resume_from: Optional[str] = None,
    elastic: Optional[ElasticConfig] = None,
) -> Booster:
    """``resume_from``: a checkpoint directory written by
    :class:`~xgboost_tpu.reliability.CheckpointCallback`.  When it holds a
    valid checkpoint, training continues from it (overriding ``xgb_model``)
    and ``num_boost_round`` is the TOTAL round target, so an interrupted-
    and-resumed run finishes at the same round — and, under deterministic
    config, the same bits — as an uninterrupted one.  An empty or missing
    directory falls through to a normal start, so the same command line
    works for launch and relaunch (docs/reliability.md).

    ``dtrain`` may also be an
    :class:`~xgboost_tpu.data.extmem.ExtMemConfig`: this rank then builds
    an out-of-core :class:`~xgboost_tpu.data.extmem.ExtMemQuantileDMatrix`
    over its page shard (``ShardMap`` round-robin), with cuts merged by
    the streaming page-wise sketch and per-level histograms allreduced
    across ranks — the launcher-composed full-scale path
    (docs/extmem.md).

    ``elastic``: an :class:`~xgboost_tpu.elastic.ElasticConfig` makes the
    run survive worker loss at reduced world size and absorb replacement
    workers at round boundaries.  ``dtrain`` may then be omitted — the
    config's ``data_fn`` builds it from this rank's shards (and rebuilds
    it after every regroup); a CheckpointCallback on the config's
    directory is appended automatically and ``resume_from`` defaults to
    it.  ``num_boost_round`` is always the TOTAL round target under
    elastic mode.  Requires an elastic-capable collective backend
    (tracker relay or in-memory) — docs/reliability.md § Elastic
    training."""
    from .telemetry import profiler

    # default-on wall sampler (XGBOOST_TPU_PROF_HZ=0 disables): training
    # rounds show up in the merged flame view; sampling only reads
    # frames, so the trained model is bitwise-identical either way
    profiler.maybe_start("train")
    callbacks = list(callbacks) if callbacks else []
    evals = list(evals) if evals else []
    if isinstance(dtrain, ExtMemConfig):
        # out-of-core multi-process composition (docs/extmem.md): this
        # rank builds its page shard's ExtMemQuantileDMatrix — streaming
        # sketch merge and per-level histogram allreduce happen inside the
        # normal distributed paths once the DMatrix is paged
        if elastic is not None:
            raise ValueError(
                "train(ExtMemConfig, elastic=...) is not supported: "
                "elastic re-sharding rebuilds data through "
                "ElasticConfig.data_fn — return the paged DMatrix there "
                "instead")
        dtrain, extmem_evals = dtrain.build()
        if not evals:
            evals = extmem_evals
    if dtrain is None and elastic is None:
        raise TypeError("train() needs dtrain (or an elastic config whose "
                        "data_fn builds it)")
    if early_stopping_rounds is not None:
        if not evals and (elastic is None or dtrain is not None):
            # elastic data_fn may supply evals; re-validated after it runs
            raise ValueError(
                "Must have at least 1 validation dataset for early stopping."
            )
        callbacks.append(EarlyStopping(rounds=early_stopping_rounds, maximize=maximize))
    if verbose_eval:
        period = 1 if verbose_eval is True else int(verbose_eval)
        callbacks.append(EvaluationMonitor(period=period))
    ckpt_cb = None
    if elastic is not None:
        from .reliability.checkpoint import CheckpointCallback

        # regroup recovery reloads from elastic.checkpoint_dir: make sure
        # something is writing there, and resume from it by default so the
        # same invocation serves launch, relaunch, and replacement workers
        ckpt_cb = next((cb for cb in callbacks
                        if isinstance(cb, CheckpointCallback)), None)
        if ckpt_cb is None:
            ckpt_cb = CheckpointCallback(
                elastic.checkpoint_dir, interval=elastic.checkpoint_interval,
                keep_last=elastic.keep_last)
            callbacks.append(ckpt_cb)
        elif (os.path.abspath(ckpt_cb.manager.directory)
              != os.path.abspath(elastic.checkpoint_dir)):
            # a mismatch would silently break regroup recovery: the run
            # would checkpoint to one directory and reload from an
            # empty other, discarding every completed round on a death
            raise ValueError(
                f"CheckpointCallback directory "
                f"{ckpt_cb.manager.directory!r} != "
                f"ElasticConfig.checkpoint_dir "
                f"{elastic.checkpoint_dir!r}: regroup recovery reloads "
                "from the elastic directory, so they must match")
        if resume_from is None:
            resume_from = elastic.checkpoint_dir
    # run-last callbacks (CheckpointCallback) dispatch after the rest so a
    # checkpoint captures the CURRENT round's EarlyStopping state, not the
    # previous round's (stable sort keeps every other relative order)
    callbacks.sort(key=lambda cb: bool(getattr(cb, "_run_last", False)))
    cbs = CallbackContainer(callbacks, metric=custom_metric)
    for cb in callbacks:
        bind = getattr(cb, "_bind_container", None)
        if bind is not None:  # CheckpointCallback snapshots history + peers
            bind(cbs)

    resumed = None
    if resume_from is not None:
        from .reliability.checkpoint import (latest_checkpoint,
                                             restore_callback_state)

        resumed = latest_checkpoint(resume_from)
    from . import collective

    if elastic is not None:
        rank, world = collective.get_rank(), collective.get_world_size()
        if elastic.num_shards is None:
            # pin the shard universe to the INITIAL world: a fresh restart
            # after a pre-checkpoint death must not shrink it, or
            # absorption back to full strength becomes impossible.  Pin on
            # a copy — the caller's config object must stay reusable for
            # a later run at a different world size.
            import copy

            elastic = copy.copy(elastic)
            elastic.num_shards = world
        smap = _elastic_shard_map(elastic, resumed, world)
        if dtrain is None:
            dtrain, evals = _elastic_data(elastic, smap, rank, world, evals)
            if early_stopping_rounds is not None and not evals:
                raise ValueError(
                    "Must have at least 1 validation dataset for early "
                    "stopping (the elastic data_fn returned none)."
                )
        ckpt_cb.shard_map = smap.to_dict()
        from .reliability import watchdog as _wd

        # the shard map rides the liveness markers to the tracker, whose
        # journal then carries it across a coordinator respawn
        _wd.progress("shard_map", map=ckpt_cb.shard_map)
    if resumed is not None:
        bst = _restore_booster(params, resumed)
        for name, metrics in resumed.history.items():
            cbs.history.setdefault(name, {}).update(metrics)
        restore_callback_state(callbacks, resumed.callback_state)
    elif isinstance(xgb_model, (str, bytes, bytearray)):
        bst = Booster(params)
        bst.load_model(xgb_model)
        bst.set_param(params)
    elif isinstance(xgb_model, Booster):
        bst = xgb_model.copy()
        bst.set_param(params)
    else:
        bst = Booster(params, cache=[dtrain])

    bst = cbs.before_training(bst)
    start = bst.num_boosted_rounds()
    # resumed runs count num_boost_round as the TOTAL target (so relaunching
    # the same command converges on the same final round); a fresh or
    # xgb_model continuation keeps the additive reference semantics.
    # Elastic runs are always total: survivors and replacements must agree
    # on the final round whatever state they entered with.
    # process_type=update appends nothing — iterations are tree-SEGMENT
    # indices into the existing model (the reference train() always starts
    # at 0), so a refresh/prune pass over an xgb_model continuation walks
    # rounds 0..num_boost_round-1 instead of past the end of the ensemble.
    if getattr(bst, "process_type", "default") == "update":
        start = 0
    total = (resumed is not None or elastic is not None
             or getattr(bst, "process_type", "default") == "update")
    end = num_boost_round if total else start + num_boost_round
    from .reliability import watchdog as _wd
    from .reliability.faults import maybe_inject
    from .telemetry.distributed import ship_to_tracker

    i = start
    while i < end:
        if elastic is not None and collective.regroup_pending():
            # round-boundary absorption/shrink: membership changed while
            # this worker was between rounds
            bst, dtrain, evals, i = _elastic_regroup(
                params, elastic, cbs, callbacks, ckpt_cb, evals,
                bst.num_boosted_rounds())
            _wd.progress("shard_map", map=ckpt_cb.shard_map)
            continue
        try:
            # liveness marker + (tracker mode) a rate-limited snapshot
            # ship: the tracker's stall watchdog distinguishes a slow
            # round from a frozen one by whether this marker advances,
            # and its journal tracks the per-rank resume round from it
            _wd.progress("train.round", round=i)
            ship_to_tracker()
            # fault seam (kill/exception/delay; no-op without a plan): the
            # round boundary is where a worker death is injected for the
            # kill->resume parity tests
            maybe_inject("train.round", round=i, rank=collective.get_rank)
            if cbs.before_iteration(bst, i, dtrain, evals):
                break
            bst.update(dtrain, i, fobj=obj)
            stop = cbs.after_iteration(bst, i, dtrain, evals)
        except RegroupRequired:
            if elastic is None:
                raise
            # a peer died (or a replacement arrived) mid-round: abandon the
            # partial round, regroup, and re-enter from the last checkpoint
            bst, dtrain, evals, i = _elastic_regroup(
                params, elastic, cbs, callbacks, ckpt_cb, evals,
                bst.num_boosted_rounds())
            _wd.progress("shard_map", map=ckpt_cb.shard_map)
            continue
        if stop:
            break
        i += 1
    bst = cbs.after_training(bst)

    if evals_result is not None:
        evals_result.update(cbs.history)
    return bst


class CVPack:
    """One fold (reference: training.py:212)."""

    def __init__(self, dtrain: DMatrix, dtest: DMatrix, params):
        self.dtrain = dtrain
        self.dtest = dtest
        self.watchlist = [(dtrain, "train"), (dtest, "test")]
        self.bst = Booster(params, cache=[dtrain, dtest])

    def update(self, iteration: int, fobj) -> None:
        self.bst.update(self.dtrain, iteration, fobj)

    def eval(self, iteration: int, feval) -> str:
        return self.bst.eval_set(self.watchlist, iteration, feval)


def _make_folds(dall: DMatrix, nfold: int, params, seed: int, shuffle: bool,
                stratified: bool, folds) -> List[CVPack]:
    R = dall.num_row()
    rng = np.random.default_rng(seed)
    if folds is not None:
        splits = [(np.asarray(tr), np.asarray(te)) for tr, te in folds]
    else:
        idx = np.arange(R)
        label = dall.get_label()
        if stratified:
            if shuffle:
                # random within equal-label blocks, stratified across folds
                order = np.lexsort((rng.random(R), label))
            else:
                order = np.argsort(label, kind="stable")
            fold_of = np.empty(R, np.int64)
            fold_of[order] = np.arange(R) % nfold
        else:
            if shuffle:
                idx = rng.permutation(R)
            fold_of = np.empty(R, np.int64)
            fold_of[idx] = np.arange(R) % nfold
        splits = [
            (np.nonzero(fold_of != k)[0], np.nonzero(fold_of == k)[0]) for k in range(nfold)
        ]
    return [CVPack(dall.slice(tr), dall.slice(te), params) for tr, te in splits]


def cv(
    params: Dict[str, Any],
    dtrain: DMatrix,
    num_boost_round: int = 10,
    nfold: int = 3,
    *,
    stratified: bool = False,
    folds=None,
    metrics: Sequence[str] = (),
    obj: Optional[Callable] = None,
    maximize: Optional[bool] = None,
    early_stopping_rounds: Optional[int] = None,
    as_pandas: bool = True,
    verbose_eval: Union[bool, int, None] = None,
    show_stdv: bool = True,
    seed: int = 0,
    callbacks: Optional[Sequence[TrainingCallback]] = None,
    shuffle: bool = True,
    custom_metric: Optional[Callable] = None,
):
    """K-fold CV (reference: training.py:435). Returns a dict/DataFrame of
    per-round mean/std metric values."""
    params = dict(params)
    if metrics:
        params["eval_metric"] = list(metrics) if len(list(metrics)) > 1 else list(metrics)[0]
    packs = _make_folds(dtrain, nfold, params, seed, shuffle, stratified, folds)

    callbacks = list(callbacks) if callbacks else []
    if early_stopping_rounds is not None:
        callbacks.append(EarlyStopping(rounds=early_stopping_rounds, maximize=maximize))
    if verbose_eval:
        callbacks.append(EvaluationMonitor(
            period=1 if verbose_eval is True else int(verbose_eval),
            show_stdv=show_stdv))
    cbs = CallbackContainer(callbacks, is_cv=True)

    class _Agg:
        """Aggregate booster stand-in handed to callbacks (reference _PackedBooster)."""

        best_iteration: Optional[int] = None
        best_score: Optional[float] = None
        _is_cv = True  # EarlyStopping(save_best=) must not slice this

        def set_attr(self, **kw):
            for p in packs:
                p.bst.set_attr(**kw)

        def set_param(self, k, v=None):
            for p in packs:
                p.bst.set_param(k, v)

        def eval_set(self, evals, iteration):  # unused; cv aggregates manually
            return ""

    agg = _Agg()
    # full callback lifecycle like train(): TelemetryCallback and friends
    # hook before/after_training (the loop below otherwise never fires them)
    agg = cbs.before_training(agg)
    results: Dict[str, List[float]] = {}
    for i in range(num_boost_round):
        if cbs.before_iteration(agg, i, dtrain, []):
            break
        fold_metrics: Dict[str, List[float]] = {}
        for p in packs:
            p.update(i, obj)
            msg = p.eval(i, custom_metric)
            for part in msg.strip().split("\t")[1:]:
                key, v = part.rsplit(":", 1)
                fold_metrics.setdefault(key, []).append(float(v))
        for key, vals in fold_metrics.items():
            mean, std = float(np.mean(vals)), float(np.std(vals))
            results.setdefault(f"{key}-mean", []).append(mean)
            results.setdefault(f"{key}-std", []).append(std)
            # callbacks see (mean, std) tuples (the reference's cv score
            # shape): EvaluationMonitor renders +std under show_stdv,
            # EarlyStopping stops on the mean
            cbs.history.setdefault(key.split("-", 1)[0], {}).setdefault(
                key.split("-", 1)[1], []
            ).append((mean, std))
        if any(cb.after_iteration(agg, i, cbs.history) for cb in cbs.callbacks):
            break
    cbs.after_training(agg)
    if as_pandas:
        try:
            import pandas as pd

            return pd.DataFrame.from_dict(results)
        except ImportError:
            pass
    return results
