"""train() / cv() drivers (reference: python-package/xgboost/training.py:53,435).

The loop shape matches the reference exactly: callbacks wrap a plain
``bst.update`` per round; cv() builds stratified/group folds (CVPack,
training.py:212) and aggregates fold metrics.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .callback import CallbackContainer, EarlyStopping, EvaluationMonitor, TrainingCallback
from .core import Booster
from .data.dmatrix import DMatrix

__all__ = ["train", "cv"]


def train(
    params: Dict[str, Any],
    dtrain: DMatrix,
    num_boost_round: int = 10,
    *,
    evals: Optional[Sequence[Tuple[DMatrix, str]]] = None,
    obj: Optional[Callable] = None,
    maximize: Optional[bool] = None,
    early_stopping_rounds: Optional[int] = None,
    evals_result: Optional[dict] = None,
    verbose_eval: Union[bool, int, None] = True,
    xgb_model: Optional[Union[str, Booster]] = None,
    callbacks: Optional[Sequence[TrainingCallback]] = None,
    custom_metric: Optional[Callable] = None,
    resume_from: Optional[str] = None,
) -> Booster:
    """``resume_from``: a checkpoint directory written by
    :class:`~xgboost_tpu.reliability.CheckpointCallback`.  When it holds a
    valid checkpoint, training continues from it (overriding ``xgb_model``)
    and ``num_boost_round`` is the TOTAL round target, so an interrupted-
    and-resumed run finishes at the same round — and, under deterministic
    config, the same bits — as an uninterrupted one.  An empty or missing
    directory falls through to a normal start, so the same command line
    works for launch and relaunch (docs/reliability.md)."""
    callbacks = list(callbacks) if callbacks else []
    evals = list(evals) if evals else []
    if early_stopping_rounds is not None:
        if not evals:
            raise ValueError(
                "Must have at least 1 validation dataset for early stopping."
            )
        callbacks.append(EarlyStopping(rounds=early_stopping_rounds, maximize=maximize))
    if verbose_eval:
        period = 1 if verbose_eval is True else int(verbose_eval)
        callbacks.append(EvaluationMonitor(period=period))
    # run-last callbacks (CheckpointCallback) dispatch after the rest so a
    # checkpoint captures the CURRENT round's EarlyStopping state, not the
    # previous round's (stable sort keeps every other relative order)
    callbacks.sort(key=lambda cb: bool(getattr(cb, "_run_last", False)))
    cbs = CallbackContainer(callbacks, metric=custom_metric)
    for cb in callbacks:
        bind = getattr(cb, "_bind_container", None)
        if bind is not None:  # CheckpointCallback snapshots history + peers
            bind(cbs)

    resumed = None
    if resume_from is not None:
        from .reliability.checkpoint import (latest_checkpoint,
                                             restore_callback_state)

        resumed = latest_checkpoint(resume_from)
    if resumed is not None:
        bst = Booster(params)
        bst.unserialize(resumed.booster_bytes)
        bst.set_param(params)
        bi = bst.attr("best_iteration")
        if bi is not None:  # re-expose early-stopping bests on the object
            bst.best_iteration = int(bi)
            bs = bst.attr("best_score")
            bst.best_score = float(bs) if bs is not None else None
        for name, metrics in resumed.history.items():
            cbs.history.setdefault(name, {}).update(metrics)
        restore_callback_state(callbacks, resumed.callback_state)
    elif isinstance(xgb_model, (str, bytes, bytearray)):
        bst = Booster(params)
        bst.load_model(xgb_model)
        bst.set_param(params)
    elif isinstance(xgb_model, Booster):
        bst = xgb_model.copy()
        bst.set_param(params)
    else:
        bst = Booster(params, cache=[dtrain])

    bst = cbs.before_training(bst)
    start = bst.num_boosted_rounds()
    # resumed runs count num_boost_round as the TOTAL target (so relaunching
    # the same command converges on the same final round); a fresh or
    # xgb_model continuation keeps the additive reference semantics
    end = num_boost_round if resumed is not None else start + num_boost_round
    from . import collective
    from .reliability.faults import maybe_inject

    for i in range(start, end):
        # fault seam (kill/exception/delay; no-op without a plan): the
        # round boundary is where a worker death is injected for the
        # kill->resume parity tests
        maybe_inject("train.round", round=i, rank=collective.get_rank)
        if cbs.before_iteration(bst, i, dtrain, evals):
            break
        bst.update(dtrain, i, fobj=obj)
        if cbs.after_iteration(bst, i, dtrain, evals):
            break
    bst = cbs.after_training(bst)

    if evals_result is not None:
        evals_result.update(cbs.history)
    return bst


class CVPack:
    """One fold (reference: training.py:212)."""

    def __init__(self, dtrain: DMatrix, dtest: DMatrix, params):
        self.dtrain = dtrain
        self.dtest = dtest
        self.watchlist = [(dtrain, "train"), (dtest, "test")]
        self.bst = Booster(params, cache=[dtrain, dtest])

    def update(self, iteration: int, fobj) -> None:
        self.bst.update(self.dtrain, iteration, fobj)

    def eval(self, iteration: int, feval) -> str:
        return self.bst.eval_set(self.watchlist, iteration, feval)


def _make_folds(dall: DMatrix, nfold: int, params, seed: int, shuffle: bool,
                stratified: bool, folds) -> List[CVPack]:
    R = dall.num_row()
    rng = np.random.default_rng(seed)
    if folds is not None:
        splits = [(np.asarray(tr), np.asarray(te)) for tr, te in folds]
    else:
        idx = np.arange(R)
        label = dall.get_label()
        if stratified:
            if shuffle:
                # random within equal-label blocks, stratified across folds
                order = np.lexsort((rng.random(R), label))
            else:
                order = np.argsort(label, kind="stable")
            fold_of = np.empty(R, np.int64)
            fold_of[order] = np.arange(R) % nfold
        else:
            if shuffle:
                idx = rng.permutation(R)
            fold_of = np.empty(R, np.int64)
            fold_of[idx] = np.arange(R) % nfold
        splits = [
            (np.nonzero(fold_of != k)[0], np.nonzero(fold_of == k)[0]) for k in range(nfold)
        ]
    return [CVPack(dall.slice(tr), dall.slice(te), params) for tr, te in splits]


def cv(
    params: Dict[str, Any],
    dtrain: DMatrix,
    num_boost_round: int = 10,
    nfold: int = 3,
    *,
    stratified: bool = False,
    folds=None,
    metrics: Sequence[str] = (),
    obj: Optional[Callable] = None,
    maximize: Optional[bool] = None,
    early_stopping_rounds: Optional[int] = None,
    as_pandas: bool = True,
    verbose_eval: Union[bool, int, None] = None,
    show_stdv: bool = True,
    seed: int = 0,
    callbacks: Optional[Sequence[TrainingCallback]] = None,
    shuffle: bool = True,
    custom_metric: Optional[Callable] = None,
):
    """K-fold CV (reference: training.py:435). Returns a dict/DataFrame of
    per-round mean/std metric values."""
    params = dict(params)
    if metrics:
        params["eval_metric"] = list(metrics) if len(list(metrics)) > 1 else list(metrics)[0]
    packs = _make_folds(dtrain, nfold, params, seed, shuffle, stratified, folds)

    callbacks = list(callbacks) if callbacks else []
    if early_stopping_rounds is not None:
        callbacks.append(EarlyStopping(rounds=early_stopping_rounds, maximize=maximize))
    if verbose_eval:
        callbacks.append(EvaluationMonitor(
            period=1 if verbose_eval is True else int(verbose_eval),
            show_stdv=show_stdv))
    cbs = CallbackContainer(callbacks, is_cv=True)

    class _Agg:
        """Aggregate booster stand-in handed to callbacks (reference _PackedBooster)."""

        best_iteration: Optional[int] = None
        best_score: Optional[float] = None
        _is_cv = True  # EarlyStopping(save_best=) must not slice this

        def set_attr(self, **kw):
            for p in packs:
                p.bst.set_attr(**kw)

        def set_param(self, k, v=None):
            for p in packs:
                p.bst.set_param(k, v)

        def eval_set(self, evals, iteration):  # unused; cv aggregates manually
            return ""

    agg = _Agg()
    # full callback lifecycle like train(): TelemetryCallback and friends
    # hook before/after_training (the loop below otherwise never fires them)
    agg = cbs.before_training(agg)
    results: Dict[str, List[float]] = {}
    for i in range(num_boost_round):
        if cbs.before_iteration(agg, i, dtrain, []):
            break
        fold_metrics: Dict[str, List[float]] = {}
        for p in packs:
            p.update(i, obj)
            msg = p.eval(i, custom_metric)
            for part in msg.strip().split("\t")[1:]:
                key, v = part.rsplit(":", 1)
                fold_metrics.setdefault(key, []).append(float(v))
        for key, vals in fold_metrics.items():
            mean, std = float(np.mean(vals)), float(np.std(vals))
            results.setdefault(f"{key}-mean", []).append(mean)
            results.setdefault(f"{key}-std", []).append(std)
            # callbacks see (mean, std) tuples (the reference's cv score
            # shape): EvaluationMonitor renders +std under show_stdv,
            # EarlyStopping stops on the mean
            cbs.history.setdefault(key.split("-", 1)[0], {}).setdefault(
                key.split("-", 1)[1], []
            ).append((mean, std))
        if any(cb.after_iteration(agg, i, cbs.history) for cb in cbs.callbacks):
            break
    cbs.after_training(agg)
    if as_pandas:
        try:
            import pandas as pd

            return pd.DataFrame.from_dict(results)
        except ImportError:
            pass
    return results
