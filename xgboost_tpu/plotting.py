"""Plotting: feature importance + tree diagrams.

Reference: python-package/xgboost/plotting.py (plot_importance, plot_tree,
to_graphviz).  matplotlib/graphviz are optional at call time, matching the
reference's lazy imports.
"""
from __future__ import annotations

from io import BytesIO
from typing import Any, Optional

import numpy as np

from .core import Booster

__all__ = ["plot_importance", "plot_tree", "to_graphviz"]


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim=None, ylim=None, title: str = "Feature importance",
                    xlabel: str = "Importance score", ylabel: str = "Features",
                    fmap: str = "", importance_type: str = "weight",
                    max_num_features: Optional[int] = None, grid: bool = True,
                    show_values: bool = True, values_format: str = "{v}",
                    **kwargs: Any):
    """Horizontal bar plot of feature importance (reference: plotting.py:28)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError as e:
        raise ImportError("plot_importance requires matplotlib") from e

    if hasattr(booster, "get_booster"):
        booster = booster.get_booster()
    if not isinstance(booster, Booster):
        raise ValueError("tree must be a Booster or XGBModel")
    importance = booster.get_score(fmap=fmap, importance_type=importance_type)
    if not importance:
        raise ValueError("Booster.get_score() results are empty")
    tuples = sorted(importance.items(), key=lambda x: x[1])
    if max_num_features is not None:
        tuples = tuples[-max_num_features:]
    labels, values = zip(*tuples)

    if ax is None:
        _, ax = plt.subplots(1, 1)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    if show_values:
        for x, y in zip(values, ylocs):
            ax.text(x + 1e-6, y,
                    values_format.format(v=round(x, 2) if isinstance(x, float) else x),
                    va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _attr_str(params: Optional[dict]) -> str:
    if not params:
        return ""
    return "".join(
        ', {}="{}"'.format(k, str(v).replace('"', r'\"')) for k, v in params.items()
    )


def _read_fmap(fmap: str):
    """featmap.txt: '<id>\t<name>\t<type>' per line (reference format)."""
    names = {}
    with open(fmap) as fh:
        for line in fh:
            parts = line.strip().split("\t")
            if len(parts) >= 2:
                names[int(parts[0])] = parts[1]
    return names


def to_graphviz(booster, fmap: str = "", num_trees: int = 0, rankdir: str = "UT",
                yes_color: str = "#0000FF", no_color: str = "#FF0000",
                condition_node_params: Optional[dict] = None,
                leaf_node_params: Optional[dict] = None, **kwargs: Any):
    """Graphviz Source of one tree (reference: plotting.py:118)."""
    if hasattr(booster, "get_booster"):
        booster = booster.get_booster()
    tree = booster.trees[num_trees]
    names = booster.feature_names
    fmap_names = _read_fmap(fmap) if fmap else {}

    def fname(fid):
        if fid in fmap_names:
            return fmap_names[fid]
        return names[fid] if names else f"f{fid}"

    cond_attrs = _attr_str(condition_node_params)
    leaf_attrs = _attr_str({"shape": "box", **(leaf_node_params or {})})
    graph_attrs = "".join(f'  {k}="{v}";\n' for k, v in kwargs.items())
    lines = [f"digraph tree_{num_trees} {{", f'  rankdir="{rankdir}";']
    if graph_attrs:
        lines.append(graph_attrs.rstrip("\n"))
    for nid in range(tree.n_nodes):
        if tree.is_leaf(nid):
            lines.append(
                f'  n{nid} [label="leaf={tree.split_conditions[nid]:.6g}"{leaf_attrs}];'
            )
        else:
            if tree.categories and nid in tree.categories:
                cats = ",".join(str(c) for c in tree.categories[nid])
                cond = f"{fname(tree.split_indices[nid])}:{{{cats}}}"
            else:
                cond = f"{fname(tree.split_indices[nid])}<{tree.split_conditions[nid]:.6g}"
            lines.append(f'  n{nid} [label="{cond}"{cond_attrs}];')
            yes, no = tree.left_children[nid], tree.right_children[nid]
            miss = yes if tree.default_left[nid] else no
            ylab = "yes, missing" if miss == yes else "yes"
            nlab = "no, missing" if miss == no else "no"
            lines.append(f'  n{nid} -> n{yes} [label="{ylab}", color="{yes_color}"];')
            lines.append(f'  n{nid} -> n{no} [label="{nlab}", color="{no_color}"];')
    lines.append("}")
    src = "\n".join(lines)
    try:
        from graphviz import Source

        return Source(src)
    except ImportError:
        return src  # raw DOT text when graphviz isn't installed


def plot_tree(booster, fmap: str = "", num_trees: int = 0, rankdir: str = "UT",
              ax=None, **kwargs: Any):
    """Render one tree with matplotlib (reference: plotting.py:186)."""
    try:
        import matplotlib.image as image
        import matplotlib.pyplot as plt
    except ImportError as e:
        raise ImportError("plot_tree requires matplotlib") from e

    g = to_graphviz(booster, fmap=fmap, num_trees=num_trees, rankdir=rankdir,
                    **kwargs)
    if isinstance(g, str):
        raise ImportError("plot_tree requires graphviz")
    if ax is None:
        _, ax = plt.subplots(1, 1)
    s = BytesIO()
    s.write(g.pipe(format="png"))
    s.seek(0)
    img = image.imread(s)
    ax.imshow(img)
    ax.axis("off")
    return ax
