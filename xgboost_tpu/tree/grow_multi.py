"""Vector-leaf (multi-target) tree growing — ``multi_strategy="multi_output_tree"``.

TPU-native equivalent of the reference's MultiTargetTree training
(include/xgboost/multi_target_tree_model.h:38; GPU evaluator
src/tree/gpu_hist/multi_evaluate_splits.cu; driver updater_quantile_hist.cc:156).
One tree carries all K targets: the histogram gets 2K channels (one matmul on
the MXU — K does not multiply the number of passes over the data), the split
is chosen by the SUM of per-target gains, and every leaf stores a K-vector.

Reuses the scalar grower's heap/level machinery (``_update_positions``) and
layout conventions; the state mirrors TreeState with K-wide value arrays.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.histogram import build_histogram
from ..ops.split import SplitParams, calc_weight, evaluate_splits_multi
from .grow import _update_positions, max_nodes_for_depth

_EPS = 1e-6


class MultiTreeState(NamedTuple):
    pos: jnp.ndarray        # (R_pad,) int32
    alive: jnp.ndarray      # (max_nodes,) bool
    totals: jnp.ndarray     # (max_nodes, K, 2)
    feat: jnp.ndarray       # (max_nodes,) int32
    sbin: jnp.ndarray       # (max_nodes,) int32
    thr: jnp.ndarray        # (max_nodes,) f32
    dleft: jnp.ndarray      # (max_nodes,) bool
    is_leaf: jnp.ndarray    # (max_nodes,) bool
    leaf_val: jnp.ndarray   # (max_nodes, K) eta-scaled leaf vector
    gain: jnp.ndarray       # (max_nodes,) f32
    base_weight: jnp.ndarray  # (max_nodes, K) raw node weights
    sum_hess: jnp.ndarray   # (max_nodes,) mean per-target hessian
    splits_left: jnp.ndarray  # (1,) int32


@functools.partial(jax.jit, static_argnames=("max_nodes", "n_targets"))
def init_multi_state(gpair, valid, *, max_nodes: int, n_targets: int):
    """gpair: (R_pad, K, 2).  All rows at the root."""
    R = gpair.shape[0]
    K = n_targets
    pos = jnp.where(valid, 0, -1).astype(jnp.int32)
    mask = (pos == 0).astype(jnp.float32)
    root = jnp.einsum("r,rkc->kc", mask, gpair)  # (K, 2)
    mn = max_nodes
    return MultiTreeState(
        pos=pos,
        alive=jnp.zeros(mn, bool).at[0].set(True),
        totals=jnp.zeros((mn, K, 2), jnp.float32).at[0].set(root),
        feat=jnp.full(mn, -1, jnp.int32),
        sbin=jnp.zeros(mn, jnp.int32),
        thr=jnp.zeros(mn, jnp.float32),
        dleft=jnp.ones(mn, bool),
        is_leaf=jnp.zeros(mn, bool),
        leaf_val=jnp.zeros((mn, K), jnp.float32),
        gain=jnp.zeros(mn, jnp.float32),
        base_weight=jnp.zeros((mn, K), jnp.float32),
        sum_hess=jnp.zeros(mn, jnp.float32),
        splits_left=jnp.full((1,), jnp.iinfo(jnp.int32).max, jnp.int32),
    )


@functools.partial(
    jax.jit,
    static_argnames=("depth", "params", "last_level", "n_targets", "subtract_on"),
)
def level_step_multi(state: MultiTreeState, bins, gpair, cuts_pad, n_bins,
                     feature_mask, hist_prev=None, *, depth: int,
                     params: SplitParams, last_level: bool, n_targets: int,
                     subtract_on: bool = False):
    """One level: 2K-channel hist -> summed-gain split -> apply.

    Returns (state, hist) with hist (N, F, B, K, 2) for the next level's
    subtraction trick (right sibling = parent - left)."""
    node0 = (1 << depth) - 1
    N = 1 << depth
    B = cuts_pad.shape[1]
    K = n_targets
    R = gpair.shape[0]

    idx = node0 + jnp.arange(N, dtype=jnp.int32)
    totals_lvl = lax.dynamic_slice_in_dim(state.totals, node0, N, axis=0)
    alive_lvl = lax.dynamic_slice_in_dim(state.alive, node0, N, axis=0)
    w = calc_weight(totals_lvl[..., 0], totals_lvl[..., 1], params)  # (N,K)

    if last_level:
        return state._replace(
            is_leaf=state.is_leaf.at[idx].set(alive_lvl),
            leaf_val=state.leaf_val.at[idx].set(
                jnp.where(alive_lvl[:, None], params.eta * w, 0.0)),
            base_weight=state.base_weight.at[idx].set(w),
            sum_hess=state.sum_hess.at[idx].set(totals_lvl[..., 1].mean(-1)),
        ), None

    gflat = gpair.reshape(R, K * 2)  # channels [g0,h0,g1,h1,...]
    if subtract_on:
        half = N // 2
        left = build_histogram(bins, gflat, state.pos, node0=node0,
                               n_nodes=half, n_bin=B, stride=2)
        left = left.reshape(half, bins.shape[1], B, K, 2)
        right = hist_prev - left
        hist = jnp.stack([left, right], axis=1).reshape(
            N, bins.shape[1], B, K, 2)
        hist = hist * alive_lvl[:, None, None, None, None]
    else:
        hist = build_histogram(bins, gflat, state.pos, node0=node0,
                               n_nodes=N, n_bin=B)
        hist = hist.reshape(N, bins.shape[1], B, K, 2)

    fm = feature_mask if feature_mask.ndim == 2 else feature_mask[None, :]
    best = evaluate_splits_multi(hist, totals_lvl, n_bins, params, fm)

    gamma_eps = max(params.gamma, _EPS)
    can_split = alive_lvl & (best.gain > gamma_eps)
    new_leaf = alive_lvl & ~can_split
    thr_lvl = cuts_pad[best.feature, jnp.minimum(best.bin, B - 1)]

    st = state._replace(
        feat=state.feat.at[idx].set(jnp.where(can_split, best.feature, -1)),
        sbin=state.sbin.at[idx].set(jnp.where(can_split, best.bin, 0)),
        thr=state.thr.at[idx].set(jnp.where(can_split, thr_lvl, 0.0)),
        dleft=state.dleft.at[idx].set(best.default_left),
        is_leaf=state.is_leaf.at[idx].set(new_leaf),
        leaf_val=state.leaf_val.at[idx].set(
            jnp.where(new_leaf[:, None], params.eta * w, 0.0)),
        gain=state.gain.at[idx].set(jnp.where(can_split, best.gain, 0.0)),
        base_weight=state.base_weight.at[idx].set(w),
        sum_hess=state.sum_hess.at[idx].set(totals_lvl[..., 1].mean(-1)),
    )
    left_ids = 2 * idx + 1
    right_ids = 2 * idx + 2
    st = st._replace(
        alive=st.alive.at[left_ids].set(can_split).at[right_ids].set(can_split),
        totals=st.totals.at[left_ids].set(best.left_sum)
                        .at[right_ids].set(best.right_sum),
    )

    # reuse the scalar partitioner: it only needs scalar split fields
    class _B(NamedTuple):
        feature: jnp.ndarray
        bin: jnp.ndarray
        default_left: jnp.ndarray
        is_cat: jnp.ndarray
        cat_set: jnp.ndarray

    bb = _B(best.feature, best.bin, best.default_left,
            jnp.zeros(N, bool), jnp.zeros((N, B), bool))
    st = st._replace(
        pos=_update_positions(bins, st.pos, bb, can_split, node0, N, B, False))
    return st, hist


@jax.jit
def leaf_margin_delta_multi(pos, leaf_val):
    """(R_pad, K) margin update: every row adds its leaf's vector."""
    safe = jnp.clip(pos, 0, leaf_val.shape[0] - 1)
    return jnp.where((pos >= 0)[:, None], leaf_val[safe], 0.0)


class GrownMultiTree(NamedTuple):
    feat: "object"
    sbin: "object"
    thr: "object"
    dleft: "object"
    is_leaf: "object"
    leaf_val: "object"   # (max_nodes, K)
    gain: "object"
    base_weight: "object"  # (max_nodes, K)
    sum_hess: "object"
    totals: "object"


class MultiTargetTreeGrower:
    """Host driver for vector-leaf trees (one jitted level per depth)."""

    def __init__(self, max_depth: int, params: SplitParams, n_targets: int,
                 *, subtract: bool = True) -> None:
        self.max_depth = max_depth
        self.params = params
        self.n_targets = n_targets
        self.subtract = subtract
        self.max_nodes = max_nodes_for_depth(max_depth)

    def grow(self, bins, gpair, valid, cuts_pad, n_bins,
             feature_masks=None) -> MultiTreeState:
        F = bins.shape[1]
        ones = jnp.ones((1, F), dtype=bool)
        state = init_multi_state(gpair, valid, max_nodes=self.max_nodes,
                                 n_targets=self.n_targets)
        hist_prev = None
        for d in range(self.max_depth + 1):
            fm = ones if feature_masks is None else feature_masks(d, 1 << d)
            out = level_step_multi(
                state, bins, gpair, cuts_pad, n_bins, fm, hist_prev,
                depth=d, params=self.params,
                last_level=(d == self.max_depth), n_targets=self.n_targets,
                subtract_on=(self.subtract and d > 0 and hist_prev is not None),
            )
            state, hist_prev = out
        return state

    @staticmethod
    def to_host(state: MultiTreeState) -> GrownMultiTree:
        import numpy as np

        return GrownMultiTree(
            feat=np.asarray(state.feat),
            sbin=np.asarray(state.sbin),
            thr=np.asarray(state.thr),
            dleft=np.asarray(state.dleft),
            is_leaf=np.asarray(state.is_leaf),
            leaf_val=np.asarray(state.leaf_val),
            gain=np.asarray(state.gain),
            base_weight=np.asarray(state.base_weight),
            sum_hess=np.asarray(state.sum_hess),
            totals=np.asarray(state.totals),
        )
