"""Vector-leaf (multi-target) tree growing — ``multi_strategy="multi_output_tree"``.

TPU-native equivalent of the reference's MultiTargetTree training
(include/xgboost/multi_target_tree_model.h:38; GPU evaluator
src/tree/gpu_hist/multi_evaluate_splits.cu; driver updater_quantile_hist.cc:156).
One tree carries all K targets: the histogram gets 2K channels (one matmul on
the MXU — K does not multiply the number of passes over the data), the split
is chosen by the SUM of per-target gains, and every leaf stores a K-vector.

Reuses the scalar grower's heap/level machinery (``_update_positions``) and
layout conventions; the state mirrors TreeState with K-wide value arrays.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.histogram import build_histogram, combine_sibling_hists
from ..ops.split import SplitParams, calc_weight, evaluate_splits_multi
from .grow import _update_positions, max_nodes_for_depth

_EPS = 1e-6


class MultiTreeState(NamedTuple):
    pos: jnp.ndarray        # (R_pad,) int32
    alive: jnp.ndarray      # (max_nodes,) bool
    totals: jnp.ndarray     # (max_nodes, K, 2)
    feat: jnp.ndarray       # (max_nodes,) int32
    sbin: jnp.ndarray       # (max_nodes,) int32
    thr: jnp.ndarray        # (max_nodes,) f32
    dleft: jnp.ndarray      # (max_nodes,) bool
    is_leaf: jnp.ndarray    # (max_nodes,) bool
    leaf_val: jnp.ndarray   # (max_nodes, K) eta-scaled leaf vector
    gain: jnp.ndarray       # (max_nodes,) f32
    base_weight: jnp.ndarray  # (max_nodes, K) raw node weights
    sum_hess: jnp.ndarray   # (max_nodes,) mean per-target hessian
    splits_left: jnp.ndarray  # (1,) int32


@functools.partial(jax.jit, static_argnames=("max_nodes", "n_targets",
                                             "axis_name", "max_splits"))
def init_multi_state(gpair, valid, *, max_nodes: int, n_targets: int,
                     axis_name: Optional[str] = None, max_splits: int = 0):
    """gpair: (R_pad, K, 2).  All rows at the root."""
    R = gpair.shape[0]
    K = n_targets
    pos = jnp.where(valid, 0, -1).astype(jnp.int32)
    mask = (pos == 0).astype(jnp.float32)
    root = jnp.einsum("r,rkc->kc", mask, gpair)  # (K, 2)
    if axis_name is not None:
        root = lax.psum(root, axis_name)
    mn = max_nodes
    budget = max_splits if max_splits > 0 else jnp.iinfo(jnp.int32).max
    return MultiTreeState(
        pos=pos,
        alive=jnp.zeros(mn, bool).at[0].set(True),
        totals=jnp.zeros((mn, K, 2), jnp.float32).at[0].set(root),
        feat=jnp.full(mn, -1, jnp.int32),
        sbin=jnp.zeros(mn, jnp.int32),
        thr=jnp.zeros(mn, jnp.float32),
        dleft=jnp.ones(mn, bool),
        is_leaf=jnp.zeros(mn, bool),
        leaf_val=jnp.zeros((mn, K), jnp.float32),
        gain=jnp.zeros(mn, jnp.float32),
        base_weight=jnp.zeros((mn, K), jnp.float32),
        sum_hess=jnp.zeros(mn, jnp.float32),
        splits_left=jnp.full((1,), budget, jnp.int32),
    )


class _ScalarBest(NamedTuple):
    # the subset of split fields the scalar partitioner needs
    feature: jnp.ndarray
    bin: jnp.ndarray
    default_left: jnp.ndarray
    is_cat: jnp.ndarray
    cat_set: jnp.ndarray


def _finalize_leaves_multi(state, params, depth: int):
    """Last level: every surviving node becomes a leaf."""
    node0 = (1 << depth) - 1
    N = 1 << depth
    idx = node0 + jnp.arange(N, dtype=jnp.int32)
    totals_lvl = lax.dynamic_slice_in_dim(state.totals, node0, N, axis=0)
    alive_lvl = lax.dynamic_slice_in_dim(state.alive, node0, N, axis=0)
    w = calc_weight(totals_lvl[..., 0], totals_lvl[..., 1], params)
    return state._replace(
        is_leaf=state.is_leaf.at[idx].set(alive_lvl),
        leaf_val=state.leaf_val.at[idx].set(
            jnp.where(alive_lvl[:, None], params.eta * w, 0.0)),
        base_weight=state.base_weight.at[idx].set(w),
        sum_hess=state.sum_hess.at[idx].set(totals_lvl[..., 1].mean(-1)),
    )


def _decide_body(state: MultiTreeState, hist, bins, cuts_pad, n_bins,
                 feature_mask, *, depth: int, params: SplitParams,
                 lossguide: bool):
    """evaluate + record + partition for one level, given the FINAL (already
    reduced + sibling-combined) level histogram (N, F, B, K, 2)."""
    node0 = (1 << depth) - 1
    N = 1 << depth
    B = cuts_pad.shape[1]
    idx = node0 + jnp.arange(N, dtype=jnp.int32)
    totals_lvl = lax.dynamic_slice_in_dim(state.totals, node0, N, axis=0)
    alive_lvl = lax.dynamic_slice_in_dim(state.alive, node0, N, axis=0)
    w = calc_weight(totals_lvl[..., 0], totals_lvl[..., 1], params)  # (N,K)

    fm = feature_mask if feature_mask.ndim == 2 else feature_mask[None, :]
    best = evaluate_splits_multi(hist, totals_lvl, n_bins, params, fm)

    gamma_eps = max(params.gamma, _EPS)
    can_split = alive_lvl & (best.gain > gamma_eps)

    # split budget (max_leaves): best-first under lossguide, node-order under
    # depthwise — same driver semantics as the scalar level_step (driver.h)
    budget = state.splits_left[0]
    prio = best.gain if lossguide else -idx.astype(jnp.float32)
    prio = jnp.where(can_split, prio, -jnp.inf)
    ranks = jnp.argsort(jnp.argsort(-prio)).astype(jnp.int32)
    can_split = can_split & (ranks < budget)
    new_budget = budget - jnp.sum(can_split).astype(jnp.int32)

    new_leaf = alive_lvl & ~can_split
    thr_lvl = cuts_pad[best.feature, jnp.minimum(best.bin, B - 1)]

    st = state._replace(
        feat=state.feat.at[idx].set(jnp.where(can_split, best.feature, -1)),
        sbin=state.sbin.at[idx].set(jnp.where(can_split, best.bin, 0)),
        thr=state.thr.at[idx].set(jnp.where(can_split, thr_lvl, 0.0)),
        dleft=state.dleft.at[idx].set(best.default_left),
        is_leaf=state.is_leaf.at[idx].set(new_leaf),
        leaf_val=state.leaf_val.at[idx].set(
            jnp.where(new_leaf[:, None], params.eta * w, 0.0)),
        gain=state.gain.at[idx].set(jnp.where(can_split, best.gain, 0.0)),
        base_weight=state.base_weight.at[idx].set(w),
        sum_hess=state.sum_hess.at[idx].set(totals_lvl[..., 1].mean(-1)),
        splits_left=jnp.full((1,), new_budget, jnp.int32),
    )
    left_ids = 2 * idx + 1
    right_ids = 2 * idx + 2
    st = st._replace(
        alive=st.alive.at[left_ids].set(can_split).at[right_ids].set(can_split),
        totals=st.totals.at[left_ids].set(best.left_sum)
                        .at[right_ids].set(best.right_sum),
    )
    bb = _ScalarBest(best.feature, best.bin, best.default_left,
                     jnp.zeros(N, bool), jnp.zeros((N, B), bool))
    st = st._replace(
        pos=_update_positions(bins, st.pos, bb, can_split, node0, N, B, False))
    return st


@functools.partial(
    jax.jit,
    static_argnames=("node0", "n_nodes", "n_bin", "n_targets", "stride"),
)
def build_level_hist_multi(bins, gpair, pos, *, node0: int, n_nodes: int,
                           n_bin: int, n_targets: int, stride: int = 1):
    """Local 2K-channel level histogram (n_nodes, F, B, K, 2) — the piece a
    multi-process grower allreduces before deciding."""
    R, K = gpair.shape[0], n_targets
    h = build_histogram(bins, gpair.reshape(R, K * 2), pos, node0=node0,
                        n_nodes=n_nodes, n_bin=n_bin, stride=stride)
    return h.reshape(n_nodes, bins.shape[1], n_bin, K, 2)


@functools.partial(
    jax.jit,
    static_argnames=("depth", "params", "n_targets", "lossguide"),
)
def decide_level_multi(state: MultiTreeState, hist, bins, cuts_pad, n_bins,
                       feature_mask, *, depth: int, params: SplitParams,
                       n_targets: int, lossguide: bool = False):
    return _decide_body(state, hist, bins, cuts_pad, n_bins, feature_mask,
                        depth=depth, params=params, lossguide=lossguide)


@functools.partial(
    jax.jit,
    static_argnames=("depth", "params", "last_level", "n_targets",
                     "subtract_on", "axis_name", "lossguide"),
)
def level_step_multi(state: MultiTreeState, bins, gpair, cuts_pad, n_bins,
                     feature_mask, hist_prev=None, *, depth: int,
                     params: SplitParams, last_level: bool, n_targets: int,
                     subtract_on: bool = False,
                     axis_name: Optional[str] = None, lossguide: bool = False):
    """One level: 2K-channel hist -> summed-gain split -> apply.

    Returns (state, hist) with hist (N, F, B, K, 2) for the next level's
    subtraction trick (right sibling = parent - left).  ``axis_name``: rows
    are sharded over that mesh axis and the histogram crosses shards in one
    psum (the multi-target AllReduceHist)."""
    node0 = (1 << depth) - 1
    N = 1 << depth
    B = cuts_pad.shape[1]
    K = n_targets

    if last_level:
        return _finalize_leaves_multi(state, params, depth), None

    alive_lvl = lax.dynamic_slice_in_dim(state.alive, node0, N, axis=0)
    if subtract_on:
        half = N // 2
        left = build_level_hist_multi(bins, gpair, state.pos, node0=node0,
                                      n_nodes=half, n_bin=B, n_targets=K,
                                      stride=2)
        if axis_name is not None:
            left = lax.psum(left, axis_name)
        hist = combine_sibling_hists(left, hist_prev, alive_lvl)
    else:
        hist = build_level_hist_multi(bins, gpair, state.pos, node0=node0,
                                      n_nodes=N, n_bin=B, n_targets=K)
        if axis_name is not None:
            hist = lax.psum(hist, axis_name)

    st = _decide_body(state, hist, bins, cuts_pad, n_bins, feature_mask,
                      depth=depth, params=params, lossguide=lossguide)
    return st, hist


@jax.jit
def leaf_margin_delta_multi(pos, leaf_val):
    """(R_pad, K) margin update: every row adds its leaf's vector."""
    safe = jnp.clip(pos, 0, leaf_val.shape[0] - 1)
    return jnp.where((pos >= 0)[:, None], leaf_val[safe], 0.0)


class GrownMultiTree(NamedTuple):
    feat: "object"
    sbin: "object"
    thr: "object"
    dleft: "object"
    is_leaf: "object"
    leaf_val: "object"   # (max_nodes, K)
    gain: "object"
    base_weight: "object"  # (max_nodes, K)
    sum_hess: "object"
    totals: "object"


class MultiTargetTreeGrower:
    """Host driver for vector-leaf trees (one jitted level per depth).

    ``distributed=True``: every process holds a row shard; the level
    histogram crosses processes through ``collective.allreduce`` between
    build and decide (the rabit AllReduceHist role for the reference's
    MultiTargetHistBuilder, updater_quantile_hist.cc:156)."""

    def __init__(self, max_depth: int, params: SplitParams, n_targets: int,
                 *, subtract: bool = True, max_leaves: int = 0,
                 lossguide: bool = False, distributed: bool = False) -> None:
        self.max_depth = max_depth
        self.params = params
        self.n_targets = n_targets
        self.subtract = subtract
        self.max_leaves = max_leaves
        self.lossguide = lossguide
        self.distributed = distributed
        self.max_nodes = max_nodes_for_depth(max_depth)

    def grow(self, bins, gpair, valid, cuts_pad, n_bins,
             feature_masks=None) -> MultiTreeState:
        import numpy as np

        F = bins.shape[1]
        B = cuts_pad.shape[1]
        K = self.n_targets
        ones = jnp.ones((1, F), dtype=bool)
        state = init_multi_state(
            gpair, valid, max_nodes=self.max_nodes, n_targets=K,
            max_splits=(self.max_leaves - 1) if self.max_leaves > 0 else 0)
        if self.distributed:
            from .grow import sync_root_totals

            state = sync_root_totals(state)
        hist_prev = None
        for d in range(self.max_depth + 1):
            fm = ones if feature_masks is None else feature_masks(d, 1 << d)
            if d == self.max_depth:
                state, hist_prev = level_step_multi(
                    state, bins, gpair, cuts_pad, n_bins, fm, None,
                    depth=d, params=self.params, last_level=True,
                    n_targets=K, lossguide=self.lossguide)
                continue
            subtract = self.subtract and d > 0 and hist_prev is not None
            if self.distributed:
                from .. import collective

                node0, N = (1 << d) - 1, 1 << d
                n_build = (N // 2) if subtract else N
                h = build_level_hist_multi(
                    bins, gpair, state.pos, node0=node0, n_nodes=n_build,
                    n_bin=B, n_targets=K, stride=2 if subtract else 1)
                h = jnp.asarray(collective.allreduce(np.asarray(h)))
                if subtract:
                    alive_lvl = lax.dynamic_slice_in_dim(state.alive, node0, N)
                    hist = combine_sibling_hists(h, hist_prev, alive_lvl)
                else:
                    hist = h
                state = decide_level_multi(
                    state, hist, bins, cuts_pad, n_bins, fm, depth=d,
                    params=self.params, n_targets=K, lossguide=self.lossguide)
                hist_prev = hist
            else:
                state, hist_prev = level_step_multi(
                    state, bins, gpair, cuts_pad, n_bins, fm, hist_prev,
                    depth=d, params=self.params, last_level=False,
                    n_targets=K, subtract_on=subtract,
                    lossguide=self.lossguide)
        return state

    @staticmethod
    def to_host(state: MultiTreeState) -> GrownMultiTree:
        import numpy as np

        return GrownMultiTree(
            feat=np.asarray(state.feat),
            sbin=np.asarray(state.sbin),
            thr=np.asarray(state.thr),
            dleft=np.asarray(state.dleft),
            is_leaf=np.asarray(state.is_leaf),
            leaf_val=np.asarray(state.leaf_val),
            gain=np.asarray(state.gain),
            base_weight=np.asarray(state.base_weight),
            sum_hess=np.asarray(state.sum_hess),
            totals=np.asarray(state.totals),
        )
