"""Streaming (external-memory) tree grower.

Reference: the reference's external-memory training re-streams compressed
Ellpack pages from the host cache through every BuildHist pass
(updater_gpu_hist.cu:597 GetBatches inside the driver loop; prefetch window
sparse_page_source.h:293).  Here each level makes ONE pass over the host
pages: a page's rows are routed with the PREVIOUS level's split decisions and
immediately accumulated into the current level's histogram, so the page is
touched once per level; host->HBM transfer of page i+1 overlaps compute on
page i (jax.device_put is async).

Everything except the page loop reuses the in-core grower's pieces
(evaluate_splits / _record_level / _update_positions), so the split semantics
are bitwise identical to HistTreeGrower.
"""
from __future__ import annotations

import functools
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.histogram import build_histogram, combine_sibling_hists
from ..ops.split import SplitParams, calc_weight, evaluate_splits
from .grow import (TreeState, _record_level, _update_positions, init_tree_state,
                   make_set_matrix, max_nodes_for_depth)

_EPS = 1e-6


def _sim_transfer_ms_per_mb() -> float:
    """Test hook: XTB_EXTMEM_SIM_TRANSFER_MS_PER_MB injects a synthetic
    per-byte transfer latency into _put_page (see comment there)."""
    import os

    try:
        return float(os.environ.get("XTB_EXTMEM_SIM_TRANSFER_MS_PER_MB", "0"))
    except ValueError:
        return 0.0


@functools.partial(
    jax.jit,
    static_argnames=("node0_prev", "n_prev", "node0", "n_nodes", "n_bin",
                     "has_prev", "has_cat", "build", "stride", "quantised"),
)
def _page_step(page_bins, gpair_seg, pos_seg, prev_best, prev_can, *,
               node0_prev: int, n_prev: int, node0: int, n_nodes: int,
               n_bin: int, has_prev: bool, has_cat: bool, build: bool = True,
               stride: int = 1, quantised: bool = False):
    """Route one page with the previous level's splits, then accumulate the
    current level's histogram over it (stride=2: left children only, for the
    subtraction trick).  quantised: gpair_seg carries (T, C, 3) int8 limbs
    and the histogram is exact int32 (ops/quantise.py)."""
    if has_prev:
        pos_seg = _update_positions(page_bins, pos_seg, prev_best, prev_can,
                                    node0_prev, n_prev, n_bin, has_cat)
    if build and quantised:
        from ..ops.quantise import hist_accumulate_q

        hist = hist_accumulate_q(page_bins, gpair_seg, pos_seg, node0,
                                 n_nodes, n_bin, stride=stride)
    elif build:
        hist = build_histogram(page_bins, gpair_seg, pos_seg, node0=node0,
                               n_nodes=n_nodes, n_bin=n_bin, stride=stride)
    else:
        hist = jnp.zeros((n_nodes, 1, 1, 2), jnp.float32)
    return pos_seg, hist


@functools.partial(
    jax.jit, static_argnames=("depth", "params", "lossguide", "last_level"),
)
def _decide_level(state: TreeState, hist, n_bins, cuts_pad, feature_mask,
                  set_matrix, cat_mask, *, depth: int, params: SplitParams,
                  lossguide: bool, last_level: bool):
    """evaluate + record for one level (no position update — pages do that)."""
    node0 = (1 << depth) - 1
    N = 1 << depth
    B = cuts_pad.shape[1]
    idx = node0 + jnp.arange(N, dtype=jnp.int32)
    totals_lvl = lax.dynamic_slice_in_dim(state.totals, node0, N, axis=0)
    alive_lvl = lax.dynamic_slice_in_dim(state.alive, node0, N, axis=0)
    lower_lvl = lax.dynamic_slice_in_dim(state.lower, node0, N, axis=0)
    upper_lvl = lax.dynamic_slice_in_dim(state.upper, node0, N, axis=0)
    w = calc_weight(totals_lvl[:, 0], totals_lvl[:, 1], params, lower_lvl, upper_lvl)

    if last_level:
        return state._replace(
            is_leaf=state.is_leaf.at[idx].set(alive_lvl),
            leaf_val=state.leaf_val.at[idx].set(jnp.where(alive_lvl, params.eta * w, 0.0)),
            base_weight=state.base_weight.at[idx].set(w),
            sum_hess=state.sum_hess.at[idx].set(totals_lvl[:, 1]),
        ), None, None

    compat_lvl = lax.dynamic_slice_in_dim(state.setcompat, node0, N, axis=0)
    allowed = jnp.einsum("ns,sf->nf", compat_lvl.astype(jnp.float32),
                         set_matrix.astype(jnp.float32)) > 0.0
    fm = feature_mask if feature_mask.ndim == 2 else feature_mask[None, :]
    node_bounds = jnp.stack([lower_lvl, upper_lvl], axis=1)
    has_cat = bool(cat_mask.shape) and cat_mask.shape[0] > 0
    best = evaluate_splits(hist, totals_lvl, n_bins, params, allowed & fm,
                           node_bounds, cat_mask=cat_mask if has_cat else None)
    gamma_eps = max(params.gamma, _EPS)
    can_split = alive_lvl & (best.gain > gamma_eps)
    budget = state.splits_left[0]
    prio = best.gain if lossguide else -idx.astype(jnp.float32)
    prio = jnp.where(can_split, prio, -jnp.inf)
    ranks = jnp.argsort(jnp.argsort(-prio)).astype(jnp.int32)
    can_split = can_split & (ranks < budget)
    new_budget = budget - jnp.sum(can_split).astype(jnp.int32)
    new_leaf = alive_lvl & ~can_split
    thr_lvl = cuts_pad[best.feature, jnp.minimum(best.bin, B - 1)]
    member = set_matrix.T[jnp.clip(best.feature, 0, set_matrix.shape[1] - 1)]
    st = _record_level(state, best, idx, can_split, new_leaf, w, thr_lvl,
                       totals_lvl, compat_lvl, member, new_budget, lower_lvl,
                       upper_lvl, params)
    return st, best, can_split


class StreamingHistTreeGrower:
    """Grow one tree over host-resident binned pages (ExtMemQuantileDMatrix)."""

    def __init__(self, max_depth: int, params: SplitParams, *,
                 interaction_sets=None, max_leaves: int = 0,
                 lossguide: bool = False, mesh=None,
                 distributed: bool = False, prefetch: bool = True,
                 quantised: bool = False, page_skip: bool = False) -> None:
        self.max_depth = max_depth
        self.params = params
        self.interaction_sets = interaction_sets
        self.max_leaves = max_leaves
        self.lossguide = lossguide
        # multi-device: pages are row-sharded over the mesh at transfer time
        # and GSPMD partitions the histogram matmul (hist reduce = the XLA
        # collective the reference gets from NCCL AllReduceHist); page rows
        # are PAGE_ALIGN(=1024)-aligned so every shard is equal
        self.mesh = mesh
        # multi-process: every process streams its own page shard; the
        # accumulated level histogram crosses processes once per level
        # (the AllReduceHist of the reference's extmem path —
        # updater_gpu_hist.cu:601 runs unchanged under rabit there)
        self.distributed = distributed
        # prefetch=False serializes decompress/H2D against device compute
        # (measurement baseline for the overlap gain; reference knob:
        # n_prefetch_batches=0, sparse_page_source.h:293)
        self.prefetch = prefetch
        # fixed-point limb histograms (ops/quantise.py): page accumulation,
        # chip psum and the cross-process reduce are exact integer sums, so
        # external-memory training is bit-identical on any topology too
        self.quantised = quantised
        # gradient-based sampling decides page residency (arXiv:2005.09148
        # §5): a page whose every row was sampled out (zero gpair) is
        # skipped by all D per-level passes and routed ONCE at the end —
        # page traffic per tree drops from D loads to 1 for sampled-out
        # pages.  Enabled by core.py only under
        # sampling_method=gradient_based (docs/extmem.md).
        self.page_skip = page_skip
        self.max_nodes = max_nodes_for_depth(max_depth)

    def _put_page(self, page_np):
        sim_active = _sim_transfer_ms_per_mb() > 0.0
        if (self.mesh is None and not sim_active
                and jax.default_backend() == "cpu"):
            # CPU backend: "device" memory IS host memory, so re-staging the
            # same immutable page every level just burns memcpy — keep the
            # committed array (budgeted LRU beside the decompress cache).
            # On TPU this cache must NOT exist (streaming exists because
            # HBM cannot hold the pages), and the simulated-transfer
            # harness disables it to preserve TPU-like streaming.
            from ..data.extmem import device_page_cache_get_or_put

            return device_page_cache_get_or_put(
                page_np, lambda: jax.device_put(
                    np.ascontiguousarray(page_np)))
        arr = np.ascontiguousarray(page_np)
        if self.mesh is None:
            out = jax.device_put(arr)
        else:
            from ..parallel.mesh import row2d_sharding

            out = jax.device_put(arr, row2d_sharding(self.mesh))
        sim = _sim_transfer_ms_per_mb()
        if sim > 0.0:
            # Simulated H2D latency (VERDICT r4 #6): a sleep proportional to
            # page bytes stands in for the DMA the CPU backend doesn't have.
            # sleep yields the core, so XLA's async-dispatched page compute
            # proceeds underneath exactly like device compute under a real
            # transfer — making overlap_gain measurable without TPU.  The
            # TPU measurement itself is bench.py's extmem phase (prefetch
            # vs serialized round), unchanged.
            import time

            time.sleep(arr.nbytes / 1e6 * sim / 1e3)
        return out

    def grow(self, pages: List, page_offsets: List[int], gpair, valid,
             cuts_pad, n_bins, feature_masks=None, cat_mask=None) -> TreeState:
        F = pages[0].shape[1]
        B = cuts_pad.shape[1]
        has_cat = cat_mask is not None
        cm = jnp.asarray(cat_mask) if has_cat else jnp.zeros(0, bool)
        setmat = jnp.asarray(make_set_matrix(self.interaction_sets, F))
        ones = jnp.ones((1, F), dtype=bool)
        state = init_tree_state(
            gpair, valid, max_nodes=self.max_nodes,
            n_sets=setmat.shape[0],
            max_splits=(self.max_leaves - 1) if self.max_leaves > 0 else 0,
            n_bin=B,
        )
        n_pages = len(pages)
        # ---- page residency (gradient-based sampling, arXiv:2005.09148):
        # pages whose every row carries zero gpair (sampled out) leave the
        # per-level streaming entirely; their positions are routed once at
        # the end so margin updates stay exact.  Decided on the RAW gpair
        # (before limb quantisation).  At least one page stays resident so
        # a fully-sampled-out rank still joins every per-level allreduce.
        stream_idx = list(range(n_pages))
        skipped_idx: List[int] = []
        if self.page_skip and n_pages > 1:
            row_mass = jnp.sum(jnp.abs(gpair),
                               axis=tuple(range(1, gpair.ndim)))
            page_ids = jnp.asarray(np.repeat(
                np.arange(n_pages), np.diff(np.asarray(page_offsets))))
            pmass = np.asarray(jax.ops.segment_sum(
                row_mass, page_ids, num_segments=n_pages))
            active = pmass > 0.0
            if not active.any():
                active[0] = True
            stream_idx = [i for i in range(n_pages) if active[i]]
            skipped_idx = [i for i in range(n_pages) if not active[i]]
        rho = None
        if self.quantised:
            from ..ops.quantise import prepare_quantised

            gpair, rho, state = prepare_quantised(
                gpair, valid, state, distributed=self.distributed)
        elif self.distributed:
            from .grow import sync_root_totals

            state = sync_root_totals(state)
        from ..data import extmem as _extmem

        events = (_extmem.PAGE_EVENT_LOG if _extmem.event_log_enabled()
                  else None)
        prev_best, prev_can, prev_d = None, None, -1
        hist_prev = None
        decisions = []  # (best, can, depth) per split level, for the replay
        for d in range(self.max_depth + 1):
            build = d < self.max_depth  # last level only finalizes leaves
            subtract = build and d > 0 and hist_prev is not None
            node0 = (1 << d) - 1
            N = 1 << d
            n_build = (N // 2) if subtract else N
            hist_acc = None
            # prefetch pipeline (data/extmem.py PageScheduler): pages
            # decode/stage on the shared worker pool N ahead of the
            # consumer, so the host-side decompress of page j+1..j+N
            # overlaps page j's (async-dispatched) device compute
            if events is not None:
                events.append(("level", d))
            sched = _extmem.PageScheduler(
                [pages[i] for i in stream_idx], self._put_page,
                lookahead=None if self.prefetch else 0, events=events)
            pos = state.pos
            try:
                for j, i in enumerate(stream_idx):
                    dev = sched.get(j)
                    lo, hi = page_offsets[i], page_offsets[i + 1]
                    seg_len = hi - lo
                    pos_seg = lax.dynamic_slice_in_dim(pos, lo, seg_len)
                    gp_seg = lax.dynamic_slice_in_dim(gpair, lo, seg_len)
                    pos_seg, h = _page_step(
                        dev, gp_seg, pos_seg, prev_best, prev_can,
                        node0_prev=(1 << prev_d) - 1 if prev_d >= 0 else 0,
                        n_prev=1 << max(prev_d, 0), node0=node0,
                        n_nodes=n_build, n_bin=B,
                        has_prev=prev_best is not None, has_cat=has_cat,
                        build=build, stride=2 if subtract else 1,
                        quantised=self.quantised,
                    )
                    if not self.prefetch and j + 1 < len(stream_idx):
                        # serialize: page j's compute must finish before
                        # page j+1's host decompress starts (pos_seg too —
                        # on the last level h is a constant dummy while the
                        # position routing still runs)
                        jax.block_until_ready((pos_seg, h))
                    pos = lax.dynamic_update_slice_in_dim(pos, pos_seg, lo,
                                                          axis=0)
                    if build:
                        hist_acc = h if hist_acc is None else hist_acc + h
            finally:
                # on an abort (fault-injected decode, compute error) the
                # not-yet-started prefetch futures must not keep loading
                sched.close()
            state = state._replace(pos=pos)
            fm = ones if feature_masks is None else feature_masks(d, N)
            if hist_acc is not None and self.distributed:
                # one cross-process exchange per level, after the local page
                # accumulation and before the sibling subtraction
                if self.quantised:
                    from ..ops.quantise import allreduce_limbs

                    hist_acc = allreduce_limbs(hist_acc)
                else:
                    from .. import collective

                    hist_acc = jnp.asarray(
                        collective.allreduce(np.asarray(hist_acc)))
            if hist_acc is None:  # last level: dummy hist, leaves only
                hist_acc = jnp.zeros((N, F, B, 2), jnp.float32)
            elif subtract:
                # SubtractHist: right sibling = parent - left (grow.level_step)
                # — exact in limb space when quantised (integer subtract)
                alive_lvl = lax.dynamic_slice_in_dim(state.alive, node0, N)
                hist_acc = combine_sibling_hists(hist_acc, hist_prev, alive_lvl)
            if build:
                hist_prev = hist_acc
            if self.quantised and build:
                from ..ops.quantise import dequantise

                hist_f = dequantise(hist_acc, rho)  # the ONE rounding step
            else:
                hist_f = hist_acc
            state, best, can = _decide_level(
                state, hist_f, n_bins, cuts_pad, fm, setmat, cm,
                depth=d, params=self.params, lossguide=self.lossguide,
                last_level=(d == self.max_depth),
            )
            if best is not None:
                decisions.append((best, can, d))
            prev_best, prev_can, prev_d = best, can, d
        if skipped_idx:
            state = self._route_skipped(state, pages, page_offsets, gpair,
                                        skipped_idx, decisions, B, has_cat,
                                        events)
        return state

    def _route_skipped(self, state, pages, page_offsets, gpair, skipped_idx,
                       decisions, B, has_cat, events):
        """One final pass over the sampled-out pages: replay every level's
        split decisions so their rows' positions (and so their leaf margin
        updates) are identical to a run that streamed them every level —
        D page loads collapse to 1 for pages sampling removed."""
        if events is not None:
            events.append(("route_skipped", len(skipped_idx)))
        from ..data import extmem as _extmem

        sched = _extmem.PageScheduler(
            [pages[i] for i in skipped_idx], self._put_page,
            lookahead=None if self.prefetch else 0, events=events)
        pos = state.pos
        try:
            for j, i in enumerate(skipped_idx):
                dev = sched.get(j)
                lo, hi = page_offsets[i], page_offsets[i + 1]
                seg_len = hi - lo
                pos_seg = lax.dynamic_slice_in_dim(pos, lo, seg_len)
                gp_seg = lax.dynamic_slice_in_dim(gpair, lo, seg_len)
                for best, can, d in decisions:
                    pos_seg, _ = _page_step(
                        dev, gp_seg, pos_seg, best, can,
                        node0_prev=(1 << d) - 1, n_prev=1 << d, node0=0,
                        n_nodes=1, n_bin=B, has_prev=True, has_cat=has_cat,
                        build=False, quantised=self.quantised,
                    )
                pos = lax.dynamic_update_slice_in_dim(pos, pos_seg, lo,
                                                      axis=0)
        finally:
            sched.close()
        state = state._replace(pos=pos)
        return state

    @staticmethod
    def to_host(state: TreeState):
        from .grow import HistTreeGrower

        return HistTreeGrower.to_host(state)

