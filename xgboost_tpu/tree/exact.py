"""Exact greedy tree growth over raw feature values (tree_method="exact").

TPU-native framing of the reference's grow_colmaker
(src/tree/updater_colmaker.cc:608 ColMaker): enumerate every distinct
feature value as a split candidate instead of histogram bins.  The
reference keeps this updater CPU-only (src/gbm/gbtree.cc:62 "exact is
CPU-only") and chains `prune` after it; we mirror both decisions — this
is host numpy (vectorized per-feature prefix scans replace the per-thread
ColMaker enumerators), with models/updaters.prune_tree applied by the
Booster afterwards.

Split semantics kept from the reference enumerator
(updater_colmaker.cc EnumerateSplit):
- forward pass: left = non-missing prefix, right = complement (missing
  rows ride right) -> default_left=False;
- backward pass: right = non-missing suffix, left = complement (missing
  rides left) -> default_left=True;
- candidates only between adjacent *distinct* values, threshold at the
  midpoint, both children must pass min_child_weight;
- gain = score(L) + score(R) - score(parent) with L1 thresholding
  (param.h CalcGain); any positive-gain split is accepted, gamma is the
  pruner's job (colmaker registers no gamma check of its own).

Categorical features are not supported, matching the reference updater.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import numpy as np


def _thr_l1(g: np.ndarray, alpha: float) -> np.ndarray:
    if alpha == 0.0:
        return g
    return np.sign(g) * np.maximum(np.abs(g) - alpha, 0.0)


def _weight(G, H, lambda_: float, alpha: float, max_delta_step: float):
    w = -_thr_l1(G, alpha) / (H + lambda_)
    if max_delta_step > 0.0:
        w = np.clip(w, -max_delta_step, max_delta_step)
    return w


def _score(G, H, lambda_: float, alpha: float, max_delta_step: float = 0.0):
    """param.h CalcGain: closed form when the weight is unclipped, else
    CalcGainGivenWeight (param.h:245 — RAW grad, alpha enters as -2a|w|)
    at the clipped optimum; the two agree when the clip is inactive."""
    t = _thr_l1(G, alpha)
    if max_delta_step == 0.0:
        return t * t / (H + lambda_)
    w = _weight(G, H, lambda_, alpha, max_delta_step)
    return -(2.0 * G * w + (H + lambda_) * w * w
             + 2.0 * alpha * np.abs(w))


def grow_exact(
    X: np.ndarray,
    grad: np.ndarray,
    hess: np.ndarray,
    *,
    max_depth: int = 6,
    max_leaves: int = 0,
    lambda_: float = 1.0,
    alpha: float = 0.0,
    min_child_weight: float = 1.0,
    max_delta_step: float = 0.0,
    eta: float = 0.3,
    feature_masks: Optional[Callable] = None,
    min_split_loss_eps: float = 1e-6,  # colmaker kRtEps acceptance gate
    col_order: Optional[np.ndarray] = None,
) -> Tuple["RegTree", np.ndarray]:
    """Grow one tree depth-wise with exact split enumeration.

    X: (R, F) f32 raw features, NaN = missing.  grad/hess: (R,) f32; rows
    excluded from training (subsample / validity) must carry zero hess.
    max_depth=0 means unbounded (then max_leaves must bound the tree, as
    TrainParam validation requires one of the two to be positive).
    ``col_order`` lets the caller cache the per-column argsort across
    boosting rounds (it only depends on X).
    Returns (RegTree, pos) where pos (R,) int32 is each row's final leaf
    node id (for margin updates and adaptive leaf refitting).
    """
    from ..models.tree import RegTree

    R, F = X.shape
    g64 = grad.astype(np.float64)
    h64 = hess.astype(np.float64)

    # presort every column once (colmaker's column-major SortedCSC role);
    # NaNs sort to the tail and are cut off per column
    if col_order is None:
        col_order = np.argsort(X, axis=0, kind="stable")
    n_valid = R - np.isnan(X).sum(axis=0)

    # growing arrays, creation order (root = 0)
    left: List[int] = [-1]
    right: List[int] = [-1]
    parents: List[int] = [-1]
    split_indices: List[int] = [0]
    split_conditions: List[float] = [0.0]
    default_left: List[bool] = [False]
    base_weights: List[float] = [0.0]
    loss_changes: List[float] = [0.0]
    sum_hessian: List[float] = [0.0]

    pos = np.zeros(R, np.int32)  # row -> node id
    G0, H0 = g64.sum(), h64.sum()
    node_G = {0: G0}
    node_H = {0: H0}
    base_weights[0] = float(_weight(G0, H0, lambda_, alpha, max_delta_step))
    sum_hessian[0] = float(H0)

    n_leaves = 1  # each expansion nets +1
    frontier = [0]
    for depth in range(max_depth if max_depth > 0 else 2**31 - 1):
        if not frontier:
            break
        if max_leaves > 0 and n_leaves >= max_leaves:
            break
        fm = (feature_masks(depth, len(frontier))
              if feature_masks is not None else None)
        if fm is not None:
            fm = np.asarray(fm, bool)

        # ---- level-synchronous split search: ONE pass per column covering
        # every frontier node (ColMaker enumerates all nodes per column in a
        # single sweep too — per-node rescans would cost O(R*F*width)) ----
        act = [nid for nid in frontier if node_H[nid] >= 2 * min_child_weight]
        if not act:
            frontier = []
            break
        n_act = len(act)
        slot_in_frontier = {nid: s for s, nid in enumerate(frontier)}
        slot_of = np.full(len(left), -1, np.int64)
        slot_of[act] = np.arange(n_act)
        sl_rows = slot_of[pos]  # (R,) slot or -1
        member_count = np.bincount(sl_rows[sl_rows >= 0], minlength=n_act)
        Gp_a = np.array([node_G[n] for n in act])
        Hp_a = np.array([node_H[n] for n in act])
        parent_sc = _score(Gp_a, Hp_a, lambda_, alpha, max_delta_step)
        best_gain = np.full(n_act, min_split_loss_eps)
        best_feat = np.full(n_act, -1, np.int64)
        best_thr = np.zeros(n_act)
        best_dleft = np.zeros(n_act, bool)

        def _update_best(gains, slots, thrs, f, dleft):
            """Per-slot strict improvement, first-candidate tie-break
            (matches the scalar enumerator's `gains[j] > best` with
            np.argmax's first-max rule)."""
            finite = gains > -np.inf
            if not finite.any():
                return
            gs, ss, th = gains[finite], slots[finite], thrs[finite]
            # group by slot, best gain first, ties by candidate order
            order2 = np.lexsort((np.arange(gs.size), -gs, ss))
            ss_o = ss[order2]
            win = order2[np.r_[True, ss_o[1:] != ss_o[:-1]]]
            s_w = ss[win]
            upd = gs[win] > best_gain[s_w]
            s_u, w_u = s_w[upd], win[upd]
            best_gain[s_u] = gs[w_u]
            best_feat[s_u] = f
            best_thr[s_u] = th[w_u]
            best_dleft[s_u] = dleft

        act_fslot = np.minimum(
            np.array([slot_in_frontier[n] for n in act]),
            (fm.shape[0] - 1) if fm is not None else 0)
        for f in range(F):
            if n_valid[f] == 0:
                continue
            idx = col_order[: n_valid[f], f]
            sl = sl_rows[idx]
            keep = sl >= 0
            if fm is not None:
                # feature disabled for some nodes: mask their rows out
                fmrow = fm[act_fslot, f]  # (n_act,) allowed per act slot
                if not fmrow.any():
                    continue
                keep &= np.where(sl >= 0, fmrow[np.maximum(sl, 0)], False)
            idx2 = idx[keep]
            if idx2.size == 0:
                continue
            sl2 = sl[keep]
            # stable group-by-slot preserving the value order inside groups
            ordg = np.argsort(sl2, kind="stable")
            sl3 = sl2[ordg]
            idx3 = idx2[ordg]
            v = X[idx3, f]
            cg = np.concatenate(([0.0], np.cumsum(g64[idx3])))
            ch = np.concatenate(([0.0], np.cumsum(h64[idx3])))
            n = sl3.size
            seg_start = np.nonzero(np.r_[True, sl3[1:] != sl3[:-1]])[0]
            seg_end = np.r_[seg_start[1:], n]
            seg_slot = sl3[seg_start]
            seg_of = np.repeat(np.arange(seg_start.size),
                               seg_end - seg_start)
            Gnn_s = cg[seg_end] - cg[seg_start]
            Hnn_s = ch[seg_end] - ch[seg_start]
            has_missing_s = member_count[seg_slot] != (seg_end - seg_start)

            # interior candidates: adjacent distinct values within a segment
            interior = np.nonzero(
                (np.r_[sl3[1:] == sl3[:-1], False])
                & (np.r_[v[1:] != v[:-1], False]))[0]
            Gl = cg[interior + 1] - cg[seg_start[seg_of[interior]]]
            Hl = ch[interior + 1] - ch[seg_start[seg_of[interior]]]
            thr = (v[interior] + v[np.minimum(interior + 1, n - 1)]) * 0.5
            slots_c = sl3[interior]
            segs_c = seg_of[interior]
            # end-of-enumeration candidates where the node has missing rows
            # (colmaker proposes last_fvalue+eps / first_fvalue-eps): route
            # ALL non-missing one way, missing the other
            me = np.nonzero(has_missing_s)[0]
            if me.size:
                v_lo = v[seg_start[me]]
                v_hi = v[seg_end[me] - 1]
                lo_thr = v_lo - 1e-6 * (np.abs(v_lo) + 1.0)
                hi_thr = v_hi + 1e-6 * (np.abs(v_hi) + 1.0)
                Gl = np.concatenate((np.zeros(me.size), Gl, Gnn_s[me]))
                Hl = np.concatenate((np.zeros(me.size), Hl, Hnn_s[me]))
                thr = np.concatenate((lo_thr, thr, hi_thr))
                slots_c = np.concatenate((seg_slot[me], slots_c,
                                          seg_slot[me]))
                segs_c = np.concatenate((me, segs_c, me))
            if Gl.size == 0:
                continue
            Gp_c, Hp_c = Gp_a[slots_c], Hp_a[slots_c]
            psc_c = parent_sc[slots_c]
            # forward: missing rides right
            Gr_f, Hr_f = Gp_c - Gl, Hp_c - Hl
            ok_f = (Hl >= min_child_weight) & (Hr_f >= min_child_weight)
            gain_f = np.where(
                ok_f,
                _score(Gl, Hl, lambda_, alpha, max_delta_step)
                + _score(Gr_f, Hr_f, lambda_, alpha, max_delta_step)
                - psc_c,
                -np.inf)
            # backward: missing rides left
            Gr_b = Gnn_s[segs_c] - Gl
            Hr_b = Hnn_s[segs_c] - Hl
            Gl_b, Hl_b = Gp_c - Gr_b, Hp_c - Hr_b
            ok_b = (Hl_b >= min_child_weight) & (Hr_b >= min_child_weight)
            gain_b = np.where(
                ok_b,
                _score(Gl_b, Hl_b, lambda_, alpha, max_delta_step)
                + _score(Gr_b, Hr_b, lambda_, alpha, max_delta_step)
                - psc_c,
                -np.inf)
            _update_best(gain_f, slots_c, thr, f, False)
            _update_best(gain_b, slots_c, thr, f, True)

        # ---- expand winners (frontier order, leaf budget applies) ----
        next_frontier: List[int] = []
        for nid in frontier:
            if max_leaves > 0 and n_leaves >= max_leaves:
                break
            s = slot_of[nid]
            if s < 0 or best_feat[s] < 0:
                continue
            f = int(best_feat[s])
            # route AND store in f32: the reference computes the midpoint in
            # f32 ((fvalue+last)*0.5f); routing with the f64 midpoint while
            # storing f32 could send boundary rows left at train time but
            # right at predict time
            thr_v = float(np.float32(best_thr[s]))
            dleft = bool(best_dleft[s])
            l_id, r_id = len(left), len(left) + 1
            for arrs, vals in ((left, (-1, -1)), (right, (-1, -1)),
                               (parents, (nid, nid)),
                               (split_indices, (0, 0)),
                               (split_conditions, (0.0, 0.0)),
                               (default_left, (False, False)),
                               (loss_changes, (0.0, 0.0))):
                arrs.extend(vals)
            left[nid], right[nid] = l_id, r_id
            split_indices[nid] = f
            split_conditions[nid] = thr_v
            default_left[nid] = dleft
            loss_changes[nid] = float(best_gain[s])

            members = pos == nid
            x = X[members, f]
            goleft = np.where(np.isnan(x), dleft, x < thr_v)
            midx = np.nonzero(members)[0]
            pos[midx[goleft]] = l_id
            pos[midx[~goleft]] = r_id
            n_leaves += 1
            for cid in (l_id, r_id):
                cm = pos == cid
                Gc = g64[cm].sum()
                Hc = h64[cm].sum()
                node_G[cid], node_H[cid] = Gc, Hc
                base_weights.append(float(
                    _weight(Gc, Hc, lambda_, alpha, max_delta_step)))
                sum_hessian.append(float(Hc))
                next_frontier.append(cid)
        frontier = next_frontier

    # leaves: split_conditions hold eta * weight (RegTree leaf convention)
    larr = np.asarray(left, np.int32)
    sc = np.asarray(split_conditions, np.float32)
    bw = np.asarray(base_weights, np.float32)
    leaf_mask = larr == -1
    sc[leaf_mask] = (eta * bw[leaf_mask]).astype(np.float32)

    tree = RegTree(
        left_children=larr,
        right_children=np.asarray(right, np.int32),
        parents=np.asarray(parents, np.int32),
        split_indices=np.asarray(split_indices, np.int32),
        split_conditions=sc,
        default_left=np.asarray(default_left, bool),
        base_weights=bw,
        loss_changes=np.asarray(loss_changes, np.float32),
        sum_hessian=np.asarray(sum_hessian, np.float32),
        # exact thresholds are raw-value midpoints that exist in no cut grid:
        # leave split_bins None so binned prediction paths fail loudly
        # (_ensure_split_bins) instead of mis-routing
        split_bins=None,
        split_type=np.zeros(len(larr), np.int32),
        categories={},
    )
    return tree, pos
