"""Depthwise hist tree growing — the ``tpu_hist`` updater core.

TPU-native re-design of the reference's GPU hist updater
(src/tree/updater_gpu_hist.cu:617 UpdateTree; Driver loop src/tree/driver.h:30).
The CUDA updater pops variable node batches from a priority queue and mutates
the tree on host; under XLA we need static shapes, so the tree grows strictly
level-by-level over a heap-indexed node array (node i -> children 2i+1, 2i+2),
with one jitted ``level_step`` per depth (compile cache shared across all trees
and boosting rounds).  Dead heap slots cost nothing: their node masks match no
rows, so their histograms are zero and they become weightless leaves.

Everything runs on device — histogram (ops/histogram.py), split choice
(ops/split.py), position update (the RowPartitioner analogue,
src/tree/gpu_hist/row_partitioner.cuh — here an elementwise ``pos`` rewrite,
no physical partition) and tree-array writes — so the whole step can be wrapped
in ``shard_map`` with ``lax.psum`` on the histogram for multi-chip training
(the reference's AllReduceHist, src/tree/gpu_hist/histogram.cu:598-608).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.histogram import (build_histogram, combine_sibling_hists,
                             node_sums)
from ..ops.split import BestSplit, SplitParams, calc_weight, evaluate_splits
from ..telemetry import span

_EPS = 1e-6


class TreeState(NamedTuple):
    """Device-side tree under construction (heap layout, max_nodes slots)."""

    pos: jnp.ndarray  # (R_pad,) int32 — node id per row, -1 = padded/invalid
    alive: jnp.ndarray  # (max_nodes,) bool — candidate for expansion
    totals: jnp.ndarray  # (max_nodes, 2) f32 — node (G, H)
    feat: jnp.ndarray  # (max_nodes,) int32 — split feature, -1 for leaf
    sbin: jnp.ndarray  # (max_nodes,) int32 — split bin (left = bins <= sbin)
    thr: jnp.ndarray  # (max_nodes,) f32 — raw split condition cuts[f][sbin]
    dleft: jnp.ndarray  # (max_nodes,) bool — default direction for missing
    is_leaf: jnp.ndarray  # (max_nodes,) bool
    leaf_val: jnp.ndarray  # (max_nodes,) f32 — eta-scaled leaf weight
    gain: jnp.ndarray  # (max_nodes,) f32 — loss_chg of the split
    base_weight: jnp.ndarray  # (max_nodes,) f32 — raw node weight
    sum_hess: jnp.ndarray  # (max_nodes,) f32
    lower: jnp.ndarray  # (max_nodes,) f32 — monotone weight lower bound
    upper: jnp.ndarray  # (max_nodes,) f32 — monotone weight upper bound
    setcompat: jnp.ndarray  # (max_nodes, n_sets) bool — interaction sets alive
    splits_left: jnp.ndarray  # (1,) int32 — remaining split budget (max_leaves)
    is_cat: jnp.ndarray  # (max_nodes,) bool — categorical split
    cat_set: jnp.ndarray  # (max_nodes, B) bool — categories routed RIGHT


def max_nodes_for_depth(max_depth: int) -> int:
    return (1 << (max_depth + 1)) - 1


def make_set_matrix(interaction_sets, n_features: int):
    """(n_sets, F) bool membership matrix; unlisted features become singleton
    sets (reference semantics: unlisted features cannot interact with listed
    ones).  None -> a single all-True set (constraints disabled)."""
    import numpy as np

    if not interaction_sets:
        return np.ones((1, n_features), dtype=bool)
    listed = set()
    rows = []
    for grp in interaction_sets:
        row = np.zeros(n_features, dtype=bool)
        for f in grp:
            row[f] = True
            listed.add(int(f))
        rows.append(row)
    for f in range(n_features):
        if f not in listed:
            row = np.zeros(n_features, dtype=bool)
            row[f] = True
            rows.append(row)
    return np.stack(rows)


@functools.partial(
    jax.jit, static_argnames=("max_nodes", "axis_name", "n_sets", "max_splits",
                              "n_bin")
)
def init_tree_state(gpair, valid, *, max_nodes: int, axis_name: Optional[str] = None,
                    n_sets: int = 1, max_splits: int = 0, n_bin: int = 1):
    """Fresh state: all rows at the root; root totals (all)reduced.

    valid : (R_pad,) bool — False for padding rows.
    max_splits: total split budget (max_leaves - 1); 0 = unlimited.
    """
    R = gpair.shape[0]
    pos = jnp.where(valid, 0, -1).astype(jnp.int32)
    root = node_sums(gpair, pos, node0=0, n_nodes=1)  # (1, 2)
    if axis_name is not None:
        root = lax.psum(root, axis_name)
    mn = max_nodes
    totals = jnp.zeros((mn, 2), jnp.float32).at[0].set(root[0])
    budget = max_splits if max_splits > 0 else jnp.iinfo(jnp.int32).max
    return TreeState(
        pos=pos,
        alive=jnp.zeros(mn, bool).at[0].set(True),
        totals=totals,
        feat=jnp.full(mn, -1, jnp.int32),
        sbin=jnp.zeros(mn, jnp.int32),
        thr=jnp.zeros(mn, jnp.float32),
        dleft=jnp.ones(mn, bool),
        is_leaf=jnp.zeros(mn, bool),
        leaf_val=jnp.zeros(mn, jnp.float32),
        gain=jnp.zeros(mn, jnp.float32),
        base_weight=jnp.zeros(mn, jnp.float32),
        sum_hess=jnp.zeros(mn, jnp.float32),
        lower=jnp.full(mn, -jnp.inf, jnp.float32),
        upper=jnp.full(mn, jnp.inf, jnp.float32),
        setcompat=jnp.ones((mn, n_sets), bool),
        splits_left=jnp.full((1,), budget, jnp.int32),
        is_cat=jnp.zeros(mn, bool),
        cat_set=jnp.zeros((mn, n_bin), bool),
    )




def sync_root_totals(state):
    """Multi-process root GlobalSum (updater_gpu_hist.cu:581): the local root
    totals computed by init_*_state cross processes once.  Works for both the
    scalar TreeState ((mn, 2) totals) and MultiTreeState ((mn, K, 2))."""
    import numpy as np

    from .. import collective

    root = collective.allreduce(np.asarray(state.totals[:1]))
    return state._replace(totals=state.totals.at[0].set(jnp.asarray(root[0])))


def _record_level(st: TreeState, best, idx, can_split, new_leaf, w, thr_lvl,
                  totals_lvl, compat_lvl, member, new_budget, lower_lvl,
                  upper_lvl, params: SplitParams):
    """Apply one level's split decisions to the tree arrays (shared between
    the in-core level_step and the external-memory streaming grower)."""
    st = st._replace(
        feat=st.feat.at[idx].set(jnp.where(can_split, best.feature, -1)),
        sbin=st.sbin.at[idx].set(jnp.where(can_split, best.bin, 0)),
        thr=st.thr.at[idx].set(jnp.where(can_split, thr_lvl, 0.0)),
        dleft=st.dleft.at[idx].set(best.default_left),
        is_leaf=st.is_leaf.at[idx].set(new_leaf),
        leaf_val=st.leaf_val.at[idx].set(jnp.where(new_leaf, params.eta * w, 0.0)),
        gain=st.gain.at[idx].set(jnp.where(can_split, best.gain, 0.0)),
        base_weight=st.base_weight.at[idx].set(w),
        sum_hess=st.sum_hess.at[idx].set(totals_lvl[:, 1]),
        is_cat=st.is_cat.at[idx].set(can_split & best.is_cat),
        cat_set=st.cat_set.at[idx].set(best.cat_set & can_split[:, None]),
    )
    left_ids = 2 * idx + 1
    right_ids = 2 * idx + 2
    st = st._replace(
        alive=st.alive.at[left_ids].set(can_split).at[right_ids].set(can_split),
        totals=st.totals.at[left_ids].set(best.left_sum).at[right_ids].set(best.right_sum),
        splits_left=jnp.full((1,), new_budget, jnp.int32),
    )
    child_compat = compat_lvl & member
    st = st._replace(
        setcompat=st.setcompat.at[left_ids].set(child_compat).at[right_ids].set(child_compat)
    )
    if params.monotone is not None and any(c != 0 for c in params.monotone):
        # bounds propagation: mid = (wL + wR)/2 splits the feasible interval
        # (reference: constraints.cc ValueConstraint::SetChild)
        cvec = jnp.asarray(params.monotone, jnp.int32)
        c_at = cvec[jnp.clip(best.feature, 0, len(params.monotone) - 1)]
        mid = 0.5 * (best.left_weight + best.right_weight)
        l_lo = jnp.where(c_at < 0, mid, lower_lvl)
        l_hi = jnp.where(c_at > 0, mid, upper_lvl)
        r_lo = jnp.where(c_at > 0, mid, lower_lvl)
        r_hi = jnp.where(c_at < 0, mid, upper_lvl)
        st = st._replace(
            lower=st.lower.at[left_ids].set(l_lo).at[right_ids].set(r_lo),
            upper=st.upper.at[left_ids].set(l_hi).at[right_ids].set(r_hi),
        )
    return st


def _update_positions(bins, pos, best, can_split, node0: int, N: int, B: int,
                      has_cat: bool):
    """Route rows of splitting nodes to their children (RowPartitioner
    analogue) — per-row elementwise, safe to run per page shard."""
    local = pos - node0
    in_lvl = (local >= 0) & (local < N)
    lc = jnp.clip(local, 0, N - 1)
    can_r = can_split[lc]
    fr = best.feature[lc]
    sb = best.bin[lc]
    dl = best.default_left[lc]
    binval = jnp.take_along_axis(
        bins, jnp.clip(fr, 0, bins.shape[1] - 1)[:, None].astype(jnp.int32), axis=1
    )[:, 0].astype(jnp.int32)
    goleft_num = binval <= sb
    if has_cat:
        # categorical: in right-set -> right (common/categorical.h Decision)
        flat = best.cat_set.reshape(-1)
        member = flat[lc * B + jnp.clip(binval, 0, B - 1)]
        goleft_split = jnp.where(best.is_cat[lc], ~member, goleft_num)
    else:
        goleft_split = goleft_num
    goleft = jnp.where(binval >= B, dl, goleft_split)  # sentinel B = missing
    child = 2 * pos + 1 + jnp.where(goleft, 0, 1)
    return jnp.where(in_lvl & can_r, child, pos)

@functools.partial(
    jax.jit,
    static_argnames=("depth", "params", "last_level", "axis_name", "hist_impl",
                     "lossguide", "has_cat", "subtract", "quantised"),
)
def level_step(
    state: TreeState,
    bins,
    gpair,
    cuts_pad,
    n_bins,
    feature_mask,
    set_matrix,
    cat_mask,
    hist_prev=None,
    rho=None,
    *,
    depth: int,
    params: SplitParams,
    last_level: bool,
    axis_name: Optional[str] = None,
    hist_impl: str = "xla",
    lossguide: bool = False,
    has_cat: bool = False,
    subtract: bool = False,
    quantised: bool = False,
):
    """Expand every alive node at ``depth``: hist -> best split -> apply.

    Mirrors one driver iteration of the reference
    (updater_gpu_hist.cu:626-646: PartitionAndBuildHist + ReduceHist +
    EvaluateSplits + ApplySplit), with the node batch = the whole level.

    Returns ``(state, hist)`` — ``hist`` (N, F, B, C) feeds the next level's
    subtraction trick (updater_gpu_hist.cu:309 SubtractHist): with
    ``subtract=True`` and ``hist_prev`` = the parent level's histogram, only
    left children (even level offsets) are built by matmul and each right
    sibling is derived as ``parent - left`` — halving both the hist FLOPs and
    (multi-chip) the psum payload.  ``hist`` is None on the last level.
    """
    node0 = (1 << depth) - 1
    N = 1 << depth
    B = cuts_pad.shape[1]

    idx = node0 + jnp.arange(N, dtype=jnp.int32)
    totals_lvl = lax.dynamic_slice_in_dim(state.totals, node0, N, axis=0)
    alive_lvl = lax.dynamic_slice_in_dim(state.alive, node0, N, axis=0)
    lower_lvl = lax.dynamic_slice_in_dim(state.lower, node0, N, axis=0)
    upper_lvl = lax.dynamic_slice_in_dim(state.upper, node0, N, axis=0)
    w = calc_weight(totals_lvl[:, 0], totals_lvl[:, 1], params, lower_lvl, upper_lvl)

    if last_level:
        # no hist needed: every surviving node becomes a leaf
        return state._replace(
            is_leaf=state.is_leaf.at[idx].set(alive_lvl),
            leaf_val=state.leaf_val.at[idx].set(
                jnp.where(alive_lvl, params.eta * w, 0.0)
            ),
            base_weight=state.base_weight.at[idx].set(w),
            sum_hess=state.sum_hess.at[idx].set(totals_lvl[:, 1]),
        ), None

    if quantised:
        # gpair here is the (R, C, 3) int8 limb array: integer builds and
        # psums are exact/order-invariant, so hist bits are topology-free
        # (the reference's GradientQuantiser contract, quantiser.cuh:52)
        from ..ops.quantise import dequantise, hist_accumulate_q

        if hist_impl == "pallas":
            # int8 x int8 -> int32 MXU kernel: the determinism contract and
            # the production kernel at once (VERDICT r4 #4)
            from ..ops.hist_pallas import build_histogram_pallas_q

            def _build(b, g, p, *, node0, n_nodes, n_bin, stride=1):
                return build_histogram_pallas_q(
                    b, g, p, node0=node0, n_nodes=n_nodes, n_bin=n_bin,
                    stride=stride)
        else:
            def _build(b, g, p, *, node0, n_nodes, n_bin, stride=1):
                return hist_accumulate_q(b, g, p, node0, n_nodes, n_bin,
                                         stride=stride)
    elif hist_impl == "pallas":
        from ..ops.hist_pallas import build_histogram_pallas as _build
    else:
        _build = build_histogram
    if subtract:
        half = N // 2
        # left children sit at even offsets 2j (heap id node0 + 2j); parent j
        # of the previous level maps to offsets (2j, 2j+1)
        left = _build(bins, gpair, state.pos, node0=node0, n_nodes=half,
                      n_bin=B, stride=2)
        if axis_name is not None:
            left = lax.psum(left, axis_name)
        hist = combine_sibling_hists(left, hist_prev, alive_lvl)
    else:
        hist = _build(bins, gpair, state.pos, node0=node0, n_nodes=N, n_bin=B)
        if axis_name is not None:
            hist = lax.psum(hist, axis_name)  # the distributed cost (SURVEY §3.1)
    if quantised:
        hist_eval = dequantise(hist, rho)  # the ONE rounding step
    else:
        hist_eval = hist

    # interaction constraints: allowed feature set per node = union of the
    # constraint sets still compatible with the node's path
    # (reference: src/tree/constraints.cc FeatureInteractionConstraint)
    compat_lvl = lax.dynamic_slice_in_dim(state.setcompat, node0, N, axis=0)
    allowed = jnp.einsum("ns,sf->nf", compat_lvl.astype(jnp.float32),
                         set_matrix.astype(jnp.float32)) > 0.0  # (N, F)
    fm = feature_mask if feature_mask.ndim == 2 else feature_mask[None, :]
    fmask = allowed & fm

    node_bounds = jnp.stack([lower_lvl, upper_lvl], axis=1)
    best = evaluate_splits(hist_eval, totals_lvl, n_bins, params, fmask,
                           node_bounds,
                           cat_mask=cat_mask if has_cat else None)

    gamma_eps = max(params.gamma, _EPS)
    can_split = alive_lvl & (best.gain > gamma_eps)

    # split budget (max_leaves): expand best-first under lossguide, node-order
    # under depthwise (reference: src/tree/driver.h grow-policy queue)
    budget = state.splits_left[0]
    prio = best.gain if lossguide else -idx.astype(jnp.float32)
    prio = jnp.where(can_split, prio, -jnp.inf)
    order = jnp.argsort(-prio)
    ranks = jnp.argsort(order).astype(jnp.int32)
    can_split = can_split & (ranks < budget)
    new_budget = budget - jnp.sum(can_split).astype(jnp.int32)

    new_leaf = alive_lvl & ~can_split

    thr_lvl = cuts_pad[best.feature, jnp.minimum(best.bin, B - 1)]
    member = set_matrix.T[jnp.clip(best.feature, 0, set_matrix.shape[1] - 1)]  # (N, n_sets)
    st = _record_level(state, best, idx, can_split, new_leaf, w, thr_lvl,
                       totals_lvl, compat_lvl, member, new_budget, lower_lvl,
                       upper_lvl, params)
    st = st._replace(
        pos=_update_positions(bins, st.pos, best, can_split, node0, N, B, has_cat)
    )
    return st, hist


@functools.partial(
    jax.jit,
    static_argnames=("width", "params", "axis_name", "hist_impl",
                     "lossguide", "has_cat", "subtract", "quantised"),
)
def level_step_padded(
    state: TreeState,
    bins,
    gpair,
    cuts_pad,
    n_bins,
    feature_mask,
    set_matrix,
    cat_mask,
    hist_prev,
    node0,
    rho=None,
    *,
    width: int,
    params: SplitParams,
    axis_name: Optional[str] = None,
    hist_impl: str = "xla",
    lossguide: bool = False,
    has_cat: bool = False,
    subtract: bool = True,
    quantised: bool = False,
):
    """``level_step`` with the node dimension PADDED to a fixed ``width`` and
    a TRACED ``node0`` — ONE compiled program serves every interior depth
    (VERDICT r3 #4: the per-depth compile wall).

    ``width`` = 2**(max_depth-1), the widest interior level.  Padding is
    cheap by design: the histogram one-hot matmul cost is flat in the node
    count on CPU (operand materialization dominates) and the extra output
    columns ride the same MXU tile on TPU (2*width <= 128 for depth <= 7).

    Correctness of the padding (garbage level offsets j >= 2**depth):
    - their heap slots overlay only DEEPER levels' ids, whose real writes
      happen at later steps, strictly after every garbage write;
    - within one step, left/right child scatter indices are all distinct
      (odd/even disjoint), so garbage and real writes never collide;
    - garbage rows match no ``pos`` (row positions only ever hold ids of
      levels <= current), so their histograms, and hence gains, are zero and
      ``alive`` is False — they can never split or consume ``max_leaves``
      budget (their priority is -inf, which cannot outrank any real
      candidate's finite priority).

    ``hist_prev``/returned ``hist`` use the padded level-offset layout
    (width, F, B, C); row j = heap node ``node0 + j``.
    """
    from ..ops.histogram import build_histogram_at

    W = width
    B = cuts_pad.shape[1]
    node0 = jnp.asarray(node0, jnp.int32)

    idx = node0 + jnp.arange(W, dtype=jnp.int32)
    totals_lvl = lax.dynamic_slice_in_dim(state.totals, node0, W, axis=0)
    alive_lvl = lax.dynamic_slice_in_dim(state.alive, node0, W, axis=0)
    lower_lvl = lax.dynamic_slice_in_dim(state.lower, node0, W, axis=0)
    upper_lvl = lax.dynamic_slice_in_dim(state.upper, node0, W, axis=0)
    w = calc_weight(totals_lvl[:, 0], totals_lvl[:, 1], params, lower_lvl,
                    upper_lvl)

    if hist_impl == "pallas":
        raise NotImplementedError(
            "padded level sharing currently uses the XLA hist path; "
            "hist_impl='pallas' keeps per-depth level_step")
    if quantised:
        from ..ops.quantise import build_histogram_q, dequantise

        _build_at = build_histogram_q
    else:
        _build_at = build_histogram_at
    if subtract:
        half = W // 2
        left = _build_at(bins, gpair, state.pos, node0,
                         n_nodes=half, n_bin=B, stride=2)
        if axis_name is not None:
            left = lax.psum(left, axis_name)
        hist = combine_sibling_hists(left, hist_prev[:half], alive_lvl)
    else:
        hist = _build_at(bins, gpair, state.pos, node0,
                         n_nodes=W, n_bin=B)
        if axis_name is not None:
            hist = lax.psum(hist, axis_name)
    hist_eval = dequantise(hist, rho) if quantised else hist

    compat_lvl = lax.dynamic_slice_in_dim(state.setcompat, node0, W, axis=0)
    allowed = jnp.einsum("ns,sf->nf", compat_lvl.astype(jnp.float32),
                         set_matrix.astype(jnp.float32)) > 0.0
    fm = feature_mask if feature_mask.ndim == 2 else feature_mask[None, :]
    fmask = allowed & fm

    node_bounds = jnp.stack([lower_lvl, upper_lvl], axis=1)
    best = evaluate_splits(hist_eval, totals_lvl, n_bins, params, fmask,
                           node_bounds,
                           cat_mask=cat_mask if has_cat else None)

    gamma_eps = max(params.gamma, _EPS)
    can_split = alive_lvl & (best.gain > gamma_eps)

    budget = state.splits_left[0]
    prio = best.gain if lossguide else -idx.astype(jnp.float32)
    prio = jnp.where(can_split, prio, -jnp.inf)
    order = jnp.argsort(-prio)
    ranks = jnp.argsort(order).astype(jnp.int32)
    can_split = can_split & (ranks < budget)
    new_budget = budget - jnp.sum(can_split).astype(jnp.int32)

    new_leaf = alive_lvl & ~can_split

    thr_lvl = cuts_pad[best.feature, jnp.minimum(best.bin, B - 1)]
    member = set_matrix.T[jnp.clip(best.feature, 0, set_matrix.shape[1] - 1)]
    st = _record_level(state, best, idx, can_split, new_leaf, w, thr_lvl,
                       totals_lvl, compat_lvl, member, new_budget, lower_lvl,
                       upper_lvl, params)
    st = st._replace(
        pos=_update_positions(bins, st.pos, best, can_split, node0, W, B,
                              has_cat)
    )
    return st, hist


@jax.jit
def leaf_margin_delta(pos, leaf_val):
    """Per-row margin update from the finished tree — the prediction-cache
    fast path (reference: TreeUpdater::UpdatePredictionCache,
    include/xgboost/tree_updater.h:92): every row sits on its leaf already."""
    safe = jnp.clip(pos, 0, leaf_val.shape[0] - 1)
    return jnp.where(pos >= 0, leaf_val[safe], 0.0)


class GrownTree(NamedTuple):
    """Host copy of a finished tree (heap layout)."""

    is_cat: "object"
    cat_set: "object"
    feat: "object"
    sbin: "object"
    thr: "object"
    dleft: "object"
    is_leaf: "object"
    leaf_val: "object"
    gain: "object"
    base_weight: "object"
    sum_hess: "object"
    totals: "object"


def default_padded_levels(max_depth: int) -> bool:
    """Platform rule for sharing ONE padded interior level program across
    depths: on accelerators the padding rides the 128-lane MXU tile for
    free and killing the per-depth compile wall matters.  On CPU the rule
    depends on the histogram impl: the native/scatter row-pass kernels add
    only for rows whose node matches, so a padded node dimension costs just
    the wider (memset) output block and the shared program wins there too;
    only the forced matmul impl still pays the full padded operand width
    at every depth (r5: the bench compile_est 8.8s -> ~4s came from
    extending this to the CPU default)."""
    if jax.default_backend() != "cpu" or max_depth <= 5:
        return True
    from ..ops.histogram import _use_scatter

    # native/scatter row-pass kernels: padding costs only the padded hist
    # output blocks (memset + accumulate traffic, 2**(md-1)*F*B*2 floats
    # per level) and the scan over dead slots is short-circuited in the
    # native kernel — a clear win at the bench depth 6, but at depth 8 the
    # 128-wide buffers measurably outweigh the saved compiles, so deep CPU
    # trees keep per-depth programs
    return _use_scatter() and max_depth <= 6


class HistTreeGrower:
    """Host driver looping jitted level steps (reference: GPUHistMaker::Update,
    src/tree/updater_gpu_hist.cu:703)."""

    def __init__(
        self,
        max_depth: int,
        params: SplitParams,
        *,
        axis_name: Optional[str] = None,
        hist_impl: str = "xla",
        interaction_sets=None,
        max_leaves: int = 0,
        lossguide: bool = False,
        subtract: bool = True,
        padded_levels: Optional[bool] = None,
        quantised: bool = False,
    ) -> None:
        self.max_depth = max_depth
        self.params = params
        self.axis_name = axis_name
        self.hist_impl = hist_impl
        self.interaction_sets = interaction_sets
        self.max_leaves = max_leaves
        self.lossguide = lossguide
        self.subtract = subtract
        # fixed-point limb histograms: bitwise-identical trees on EVERY
        # topology (chips x processes) — the GradientQuantiser contract
        # (src/tree/gpu_hist/quantiser.cuh); see ops/quantise.py
        self.quantised = quantised
        # one shared compiled program for all interior depths (padded node
        # dim + traced node0) instead of one per depth — kills the compile
        # wall.  Padding costs FLOPs at the narrow depths (every interior
        # level is built at the widest level's width): on the MXU the extra
        # output columns ride the same 128-lane tile (2**(md-1) <= 128 for
        # md <= 8), but on CPU the matmul pays the full padded width, so
        # deep CPU trees default to per-depth programs (compile there is
        # cheap relative to step time).  Pallas keeps per-depth steps
        # (static node0 kernel).
        if padded_levels is None:
            padded_levels = default_padded_levels(max_depth)
        self.padded_levels = padded_levels and hist_impl != "pallas"
        self.max_nodes = max_nodes_for_depth(max_depth)

    def _set_matrix(self, n_features: int):
        return make_set_matrix(self.interaction_sets, n_features)

    def grow(self, bins, gpair, valid, cuts_pad, n_bins, feature_masks=None,
             cat_mask=None) -> TreeState:
        """feature_masks: None, or callable (depth, n_nodes) -> (1|N, F) bool mask
        (the ColumnSampler hook: bytree/bylevel/bynode, src/common/random.h).
        cat_mask: optional (F,) bool marking categorical features."""
        F = bins.shape[1]
        B = cuts_pad.shape[1]
        ones = jnp.ones((1, F), dtype=bool)
        setmat = jnp.asarray(self._set_matrix(F))
        has_cat = cat_mask is not None
        cm = jnp.asarray(cat_mask) if has_cat else jnp.zeros(F, bool)
        state = init_tree_state(
            gpair, valid, max_nodes=self.max_nodes, axis_name=self.axis_name,
            n_sets=setmat.shape[0],
            max_splits=(self.max_leaves - 1) if self.max_leaves > 0 else 0,
            n_bin=B,
        )
        rho = None
        if self.quantised:
            from ..ops.quantise import prepare_quantised

            gpair, rho, state = prepare_quantised(
                gpair, valid, state, axis_name=self.axis_name)
        md = self.max_depth
        common = dict(params=self.params, axis_name=self.axis_name,
                      lossguide=self.lossguide, has_cat=has_cat,
                      quantised=self.quantised)
        # one span per level: the compiled program fuses build_hist +
        # eval_split + the position rewrite, so the bracket necessarily
        # covers all three — the name keeps the reference phase vocabulary
        # greppable in traces (bestfirst.py times the phases separately)
        _LEVEL = "grow.build_hist+eval_split"
        if not self.padded_levels or md < 2:
            hist_prev = None
            for d in range(md + 1):
                fm = ones if feature_masks is None else feature_masks(d, 1 << d)
                with span(_LEVEL):
                    state, hist_prev = level_step(
                        state, bins, gpair, cuts_pad, n_bins, fm, setmat, cm,
                        hist_prev, rho, depth=d, last_level=(d == md),
                        hist_impl=self.hist_impl,
                        subtract=(self.subtract and d > 0 and hist_prev is not None),
                        **common)
            return state

        # 3 compiled programs regardless of depth: root, shared padded
        # interior (traced node0), leaf finalize
        fm = ones if feature_masks is None else feature_masks(0, 1)
        with span(_LEVEL):
            state, hist = level_step(
                state, bins, gpair, cuts_pad, n_bins, fm, setmat, cm, None,
                rho, depth=0, last_level=False, hist_impl=self.hist_impl,
                subtract=False, **common)
        W = 1 << (md - 1)
        hist_pad = jnp.zeros((W,) + hist.shape[1:], hist.dtype).at[:1].set(hist)
        for d in range(1, md):
            fm = (ones if feature_masks is None
                  else self._pad_mask(feature_masks(d, 1 << d), W))
            with span(_LEVEL):
                state, hist_pad = level_step_padded(
                    state, bins, gpair, cuts_pad, n_bins, fm, setmat, cm,
                    hist_pad, (1 << d) - 1, rho, width=W,
                    subtract=self.subtract, hist_impl=self.hist_impl,
                    **common)
        fm = ones if feature_masks is None else feature_masks(md, 1 << md)
        with span(_LEVEL):
            state, _ = level_step(
                state, bins, gpair, cuts_pad, n_bins, fm, setmat, cm, None,
                rho, depth=md, last_level=True, hist_impl=self.hist_impl,
                subtract=False, **common)
        return state

    @staticmethod
    def _pad_mask(fm, W: int):
        """Pad a (N, F) per-node feature mask to the fixed (W, F) level width
        (False rows can never split); (1, F) masks broadcast unchanged."""
        fm = jnp.asarray(fm)
        if fm.ndim == 2 and 1 < fm.shape[0] < W:
            fm = jnp.concatenate(
                [fm, jnp.zeros((W - fm.shape[0], fm.shape[1]), bool)], axis=0)
        return fm

    @staticmethod
    def to_host(state: TreeState) -> GrownTree:
        import numpy as np

        with span("grow.to_host"):
            return GrownTree(
                is_cat=np.asarray(state.is_cat),
                cat_set=np.asarray(state.cat_set),
                feat=np.asarray(state.feat),
                sbin=np.asarray(state.sbin),
                thr=np.asarray(state.thr),
                dleft=np.asarray(state.dleft),
                is_leaf=np.asarray(state.is_leaf),
                leaf_val=np.asarray(state.leaf_val),
                gain=np.asarray(state.gain),
                base_weight=np.asarray(state.base_weight),
                sum_hess=np.asarray(state.sum_hess),
                totals=np.asarray(state.totals),
            )
