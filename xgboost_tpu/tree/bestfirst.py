"""Global best-first (lossguide) tree growing.

TPU-native equivalent of the reference's Driver priority queue
(src/tree/driver.h:30) + lossguide updater behavior: expand the single
highest-gain leaf anywhere in the tree, repeat until the ``max_leaves``
budget or no positive gain remains.  The round-1 grower approximated this
with a per-level budget over a heap layout, capping growth at 2^10 slots;
here the tree lives in a flat node TABLE (2*max_leaves slots, ids in
creation order), so depth is bounded only by ``max_depth`` (0 = unbounded)
and max_leaves can be arbitrarily large.

Per expansion the device work is: route the chosen node's rows (elementwise
``pos`` rewrite), one histogram matmul for BOTH children (ids are
consecutive, so the standard kernel covers them with n_nodes=2), and a
2-node split evaluation.  The host loop pulls one scalar (chosen node +
gain) per step — the same sequential shape as the reference's driver pop.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..models.tree import RegTree
from ..ops.histogram import build_histogram_at, node_sums
from ..ops.split import SplitParams, calc_weight, evaluate_splits
from ..telemetry import span

_EPS = 1e-6


class BFState(NamedTuple):
    pos: jnp.ndarray        # (R_pad,) int32 — table node id per row
    # tree arrays, creation order (root 0)
    parent: jnp.ndarray     # (N,) int32
    left: jnp.ndarray       # (N,) int32, -1 = leaf/unused
    right: jnp.ndarray      # (N,) int32
    depth: jnp.ndarray      # (N,) int32
    feat: jnp.ndarray       # (N,) int32
    sbin: jnp.ndarray       # (N,) int32
    dleft: jnp.ndarray      # (N,) bool
    gain: jnp.ndarray       # (N,) f32 — recorded loss_chg of applied splits
    totals: jnp.ndarray     # (N, 2) f32
    lower: jnp.ndarray      # (N,) f32 monotone bounds
    upper: jnp.ndarray      # (N,) f32
    setcompat: jnp.ndarray  # (N, n_sets) bool
    is_cat: jnp.ndarray     # (N,) bool
    cat_set: jnp.ndarray    # (N, B) bool
    # candidate split per OPEN leaf (computed when the node was created)
    cand_gain: jnp.ndarray  # (N,) f32, -inf when closed/invalid
    cand_feat: jnp.ndarray  # (N,) int32
    cand_bin: jnp.ndarray   # (N,) int32
    cand_dleft: jnp.ndarray  # (N,) bool
    cand_lsum: jnp.ndarray  # (N, 2)
    cand_rsum: jnp.ndarray  # (N, 2)
    cand_lw: jnp.ndarray    # (N,) f32 clipped child weights
    cand_rw: jnp.ndarray    # (N,) f32
    cand_is_cat: jnp.ndarray  # (N,) bool
    cand_cat_set: jnp.ndarray  # (N, B) bool


@functools.partial(jax.jit, static_argnames=("params", "max_depth", "has_cat",
                                             "n"))
def _eval_nodes(state: BFState, hist, cuts_pad, n_bins, feature_mask,
                set_matrix, cat_mask, i0, *, n: int, params: SplitParams,
                max_depth: int, has_cat: bool):
    """Compute split candidates for the (consecutive) node ids [i0, i0+n)
    from their (already cross-rank-reduced) histogram."""
    ids = i0 + jnp.arange(n, dtype=jnp.int32)
    totals = state.totals[ids]
    compat = state.setcompat[ids]
    allowed = jnp.einsum("ns,sf->nf", compat.astype(jnp.float32),
                         set_matrix.astype(jnp.float32)) > 0.0
    fm = feature_mask if feature_mask.ndim == 2 else feature_mask[None, :]
    bounds = jnp.stack([state.lower[ids], state.upper[ids]], axis=1)
    best = evaluate_splits(hist, totals, n_bins, params, allowed & fm, bounds,
                           cat_mask=cat_mask if has_cat else None)
    gain = best.gain
    if max_depth > 0:
        gain = jnp.where(state.depth[ids] < max_depth, gain, -jnp.inf)
    return state._replace(
        cand_gain=state.cand_gain.at[ids].set(gain),
        cand_feat=state.cand_feat.at[ids].set(best.feature),
        cand_bin=state.cand_bin.at[ids].set(best.bin),
        cand_dleft=state.cand_dleft.at[ids].set(best.default_left),
        cand_lsum=state.cand_lsum.at[ids].set(best.left_sum),
        cand_rsum=state.cand_rsum.at[ids].set(best.right_sum),
        cand_lw=state.cand_lw.at[ids].set(best.left_weight),
        cand_rw=state.cand_rw.at[ids].set(best.right_weight),
        cand_is_cat=state.cand_is_cat.at[ids].set(best.is_cat),
        cand_cat_set=state.cand_cat_set.at[ids].set(best.cat_set),
    )


@functools.partial(jax.jit, static_argnames=("params", "monotone"))
def _apply_split(state: BFState, bins, set_matrix, nid, l_id, r_id,
                 params: SplitParams, monotone: bool):
    """Expand node ``nid`` into (l_id, r_id): record the split, route rows."""
    B = state.cat_set.shape[1]
    f = state.cand_feat[nid]
    sb = state.cand_bin[nid]
    dl = state.cand_dleft[nid]
    is_cat = state.cand_is_cat[nid]
    cset = state.cand_cat_set[nid]

    st = state._replace(
        left=state.left.at[nid].set(l_id),
        right=state.right.at[nid].set(r_id),
        feat=state.feat.at[nid].set(f),
        sbin=state.sbin.at[nid].set(sb),
        dleft=state.dleft.at[nid].set(dl),
        gain=state.gain.at[nid].set(state.cand_gain[nid]),
        is_cat=state.is_cat.at[nid].set(is_cat),
        cat_set=state.cat_set.at[nid].set(cset),
        cand_gain=state.cand_gain.at[nid].set(-jnp.inf),  # closed
        parent=state.parent.at[l_id].set(nid).at[r_id].set(nid),
        depth=state.depth.at[l_id].set(state.depth[nid] + 1)
                         .at[r_id].set(state.depth[nid] + 1),
        totals=state.totals.at[l_id].set(state.cand_lsum[nid])
                           .at[r_id].set(state.cand_rsum[nid]),
    )
    # interaction constraints: children keep only sets containing f
    # (constraints.cc FeatureInteractionConstraint path restriction)
    member = set_matrix[:, jnp.clip(f, 0, set_matrix.shape[1] - 1)]  # (n_sets,)
    child_compat = state.setcompat[nid] & member
    st = st._replace(
        setcompat=st.setcompat.at[l_id].set(child_compat)
                              .at[r_id].set(child_compat))
    if monotone:
        # bounds propagation (constraints.cc ValueConstraint::SetChild)
        cvec = jnp.asarray(params.monotone, jnp.int32)
        c_at = cvec[jnp.clip(f, 0, len(params.monotone) - 1)]
        mid = 0.5 * (state.cand_lw[nid] + state.cand_rw[nid])
        lo, hi = state.lower[nid], state.upper[nid]
        st = st._replace(
            lower=st.lower.at[l_id].set(jnp.where(c_at < 0, mid, lo))
                         .at[r_id].set(jnp.where(c_at > 0, mid, lo)),
            upper=st.upper.at[l_id].set(jnp.where(c_at > 0, mid, hi))
                         .at[r_id].set(jnp.where(c_at < 0, mid, hi)),
        )

    # route rows of nid (RowPartitioner analogue, single node)
    binval = bins[:, jnp.clip(f, 0, bins.shape[1] - 1)].astype(jnp.int32)
    goleft_num = binval <= sb
    in_set = cset[jnp.clip(binval, 0, B - 1)]
    goleft_split = jnp.where(is_cat, ~in_set, goleft_num)
    goleft = jnp.where(binval >= B, dl, goleft_split)
    at_node = state.pos == nid
    new_pos = jnp.where(at_node, jnp.where(goleft, l_id, r_id), state.pos)
    return st._replace(pos=new_pos)


@functools.partial(jax.jit, static_argnames=())
def _pick_best(cand_gain):
    nid = jnp.argmax(cand_gain)
    return nid.astype(jnp.int32), cand_gain[nid]


class BestFirstGrower:
    """Lossguide driver: host loop of device expansions (driver.h pop/push)."""

    def __init__(self, max_depth: int, params: SplitParams, *,
                 max_leaves: int, interaction_sets=None,
                 distributed: bool = False, mesh=None) -> None:
        from .grow import make_set_matrix

        assert max_leaves > 1
        self.max_depth = max_depth  # 0 = unbounded
        self.params = params
        self.max_leaves = max_leaves
        self.interaction_sets = interaction_sets
        self._make_set_matrix = make_set_matrix
        self.n_slots = 2 * max_leaves  # any L-leaf binary tree: 2L-1 nodes
        # distributed=True: row shards live in other PROCESSES — the per-
        # expansion histogram goes through the host collective (the
        # AllReduceHist exchange), after which every rank's driver pops the
        # same node.  mesh: rows sharded over in-process devices — inputs are
        # placed row-sharded and GSPMD inserts the psum inside the hist
        # matmul itself (driver.h queue semantics, global across shards,
        # either way).
        self.distributed = distributed
        self.mesh = mesh

    def _node_hist(self, bins, gpair, pos, i0, n, n_bin):
        # separately-timed phases (unlike the fused depthwise level_step):
        # the best-first host loop dispatches hist, split-eval, and apply as
        # distinct device calls, so the spans attribute them individually
        with span("grow.build_hist"):
            hist = build_histogram_at(bins, gpair, pos, i0, n_nodes=n,
                                      n_bin=n_bin)
            if self.distributed:
                from .. import collective

                hist = jnp.asarray(collective.allreduce(np.asarray(hist)))
        return hist

    def grow(self, bins, gpair, valid, cuts_pad, n_bins, feature_masks=None,
             cat_mask=None) -> BFState:
        F = bins.shape[1]
        B = cuts_pad.shape[1]
        N = self.n_slots
        has_cat = cat_mask is not None
        cm = jnp.asarray(cat_mask) if has_cat else jnp.zeros(F, bool)
        setmat = jnp.asarray(self._make_set_matrix(self.interaction_sets, F))
        # column sampling: fresh bylevel/bynode draw per expansion (the
        # reference's ColumnSampler draws as nodes are created); the bytree
        # mask is shared through the feature_masks closure
        fm = (jnp.ones((1, F), bool) if feature_masks is None
              else feature_masks(0, 1))
        n_sets = setmat.shape[0]

        if self.mesh is not None:
            from ..parallel import shard_rows

            bins, gpair, valid = shard_rows(self.mesh, bins, gpair, valid)
        pos = jnp.where(valid, 0, -1).astype(jnp.int32)
        root = node_sums(gpair, pos, node0=0, n_nodes=1)[0]
        if self.distributed:
            from .. import collective

            root = jnp.asarray(collective.allreduce(np.asarray(root)))
        state = BFState(
            pos=pos,
            parent=jnp.full(N, -1, jnp.int32),
            left=jnp.full(N, -1, jnp.int32),
            right=jnp.full(N, -1, jnp.int32),
            depth=jnp.zeros(N, jnp.int32),
            feat=jnp.full(N, -1, jnp.int32),
            sbin=jnp.zeros(N, jnp.int32),
            dleft=jnp.ones(N, bool),
            gain=jnp.zeros(N, jnp.float32),
            totals=jnp.zeros((N, 2), jnp.float32).at[0].set(root),
            lower=jnp.full(N, -jnp.inf, jnp.float32),
            upper=jnp.full(N, jnp.inf, jnp.float32),
            setcompat=jnp.ones((N, n_sets), bool),
            is_cat=jnp.zeros(N, bool),
            cat_set=jnp.zeros((N, B), bool),
            cand_gain=jnp.full(N, -jnp.inf, jnp.float32),
            cand_feat=jnp.zeros(N, jnp.int32),
            cand_bin=jnp.zeros(N, jnp.int32),
            cand_dleft=jnp.ones(N, bool),
            cand_lsum=jnp.zeros((N, 2), jnp.float32),
            cand_rsum=jnp.zeros((N, 2), jnp.float32),
            cand_lw=jnp.zeros(N, jnp.float32),
            cand_rw=jnp.zeros(N, jnp.float32),
            cand_is_cat=jnp.zeros(N, bool),
            cand_cat_set=jnp.zeros((N, B), bool),
        )
        hist0 = self._node_hist(bins, gpair, state.pos, jnp.int32(0), 1, B)
        with span("grow.eval_split"):
            state = _eval_nodes(state, hist0, cuts_pad, n_bins, fm, setmat,
                                cm, jnp.int32(0), n=1, params=self.params,
                                max_depth=self.max_depth, has_cat=has_cat)

        monotone = (self.params.monotone is not None
                    and any(c != 0 for c in self.params.monotone))
        gamma_eps = max(self.params.gamma, _EPS)
        n_nodes = 1
        for _ in range(self.max_leaves - 1):
            nid, gain = _pick_best(state.cand_gain)
            if float(gain) <= gamma_eps:  # driver.h: queue exhausted
                break
            l_id, r_id = n_nodes, n_nodes + 1
            with span("grow.update_tree"):
                state = _apply_split(state, bins, setmat, nid,
                                     jnp.int32(l_id), jnp.int32(r_id),
                                     self.params, monotone)
            fme = (jnp.ones((1, F), bool) if feature_masks is None
                   else feature_masks(0, 2))
            hist2 = self._node_hist(bins, gpair, state.pos,
                                    jnp.int32(l_id), 2, B)
            with span("grow.eval_split"):
                state = _eval_nodes(
                    state, hist2, cuts_pad, n_bins, fme, setmat, cm,
                    jnp.int32(l_id), n=2, params=self.params,
                    max_depth=self.max_depth, has_cat=has_cat)
            n_nodes += 2
        self._n_nodes = n_nodes
        return state

    def to_regtree(self, state: BFState, cuts_pad) -> "tuple[RegTree, np.ndarray]":
        """(RegTree in table order, leaf_val array for the margin update)."""
        n = self._n_nodes
        left = np.asarray(state.left)[:n]
        right = np.asarray(state.right)[:n]
        parent = np.asarray(state.parent)[:n]
        feat = np.asarray(state.feat)[:n]
        sbin = np.asarray(state.sbin)[:n]
        dleft = np.asarray(state.dleft)[:n]
        gain = np.asarray(state.gain)[:n]
        totals = np.asarray(state.totals)[:n]
        lower = np.asarray(state.lower)[:n]
        upper = np.asarray(state.upper)[:n]
        is_cat = np.asarray(state.is_cat)[:n]
        cat_set = np.asarray(state.cat_set)[:n]
        cuts_np = np.asarray(cuts_pad)
        B = cuts_np.shape[1]

        p = self.params
        w = np.asarray(calc_weight(jnp.asarray(totals[:, 0]),
                                   jnp.asarray(totals[:, 1]), p,
                                   jnp.asarray(lower), jnp.asarray(upper)))
        leaf_mask = left == -1
        thr = np.where(leaf_mask, 0.0,
                       cuts_np[np.clip(feat, 0, None),
                               np.minimum(sbin, B - 1)]).astype(np.float32)
        leaf_val_full = np.zeros(self.n_slots, np.float32)
        leaf_val_full[:n] = np.where(leaf_mask, p.eta * w, 0.0)

        cats = {}
        for i in np.nonzero(~leaf_mask)[0]:
            if is_cat[i]:
                cats[int(i)] = np.nonzero(cat_set[i])[0].astype(np.int32)
        tree = RegTree(
            left_children=left.astype(np.int32),
            right_children=right.astype(np.int32),
            parents=parent.astype(np.int32),
            split_indices=np.where(leaf_mask, 0, feat).astype(np.int32),
            split_conditions=np.where(leaf_mask, p.eta * w, thr).astype(np.float32),
            default_left=dleft.astype(bool),
            base_weights=w.astype(np.float32),
            loss_changes=np.where(leaf_mask, 0.0, gain).astype(np.float32),
            sum_hessian=totals[:, 1].astype(np.float32),
            split_bins=np.where(leaf_mask, 0, sbin).astype(np.int32),
            split_type=is_cat.astype(np.int32),
            categories=cats or {},
        )
        return tree, jnp.asarray(leaf_val_full)
