"""Class-batched (lockstep) depthwise growing for one-tree-per-class
boosting (multi:softmax / multi:softprob).

The default loop grows the K per-class trees of a round sequentially, so
every tree pays its own full row pass per level.  Here the K INDEPENDENT
trees advance level-by-level together: one shared pass over the bins feeds
all K histograms (ops/histogram.build_histogram_multi — the reference's
all-targets-per-pass design, src/tree/hist/histogram.h:44), one split scan
scores all K x N nodes, and one vectorized position rewrite routes all K
`pos` arrays.  Per-class results are BITWISE identical to the sequential
grower (the native kernel adds in the same row order per class; split
decisions are per-(class, node) with unchanged tie-breaking), which
tests/test_lockstep.py pins via dump-hash equality.

State layout: grow.TreeState arrays with a leading K axis (pos (K, R),
node arrays (K, max_nodes, ...)).  Scope: numeric features, f32 hists,
single-device — the per-class fallback covers categorical / quantised /
sharded / best-first.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.histogram import (build_histogram_multi, combine_sibling_hists,
                             node_sums)
from ..ops.split import SplitParams, calc_weight, evaluate_splits
from .grow import (GrownTree, TreeState, make_set_matrix,
                   max_nodes_for_depth)

_EPS = 1e-6


@functools.partial(jax.jit, static_argnames=("max_nodes", "n_sets",
                                             "max_splits", "n_bin"))
def init_lockstep_state(gpair_rkc, valid, *, max_nodes: int, n_sets: int = 1,
                        max_splits: int = 0, n_bin: int = 1):
    """Fresh K-tree state: all rows at every class's root."""
    R, K, _ = gpair_rkc.shape
    pos_row = jnp.where(valid, 0, -1).astype(jnp.int32)
    pos = jnp.broadcast_to(pos_row, (K, R))
    # root totals via the SAME masked-matmul reduction the sequential
    # grower uses (init_tree_state -> node_sums): a plain jnp.sum reduces
    # in a different f32 order and the last-ulp root difference propagates
    # into every level's missing-value stats — breaking bitwise parity
    root = jnp.stack([
        node_sums(gpair_rkc[:, k, :], pos_row, node0=0, n_nodes=1)[0]
        for k in range(K)])  # (K, 2)
    mn = max_nodes
    totals = jnp.zeros((K, mn, 2), jnp.float32).at[:, 0].set(root)
    budget = max_splits if max_splits > 0 else jnp.iinfo(jnp.int32).max
    return TreeState(
        pos=pos,
        alive=jnp.zeros((K, mn), bool).at[:, 0].set(True),
        totals=totals,
        feat=jnp.full((K, mn), -1, jnp.int32),
        sbin=jnp.zeros((K, mn), jnp.int32),
        thr=jnp.zeros((K, mn), jnp.float32),
        dleft=jnp.ones((K, mn), bool),
        is_leaf=jnp.zeros((K, mn), bool),
        leaf_val=jnp.zeros((K, mn), jnp.float32),
        gain=jnp.zeros((K, mn), jnp.float32),
        base_weight=jnp.zeros((K, mn), jnp.float32),
        sum_hess=jnp.zeros((K, mn), jnp.float32),
        lower=jnp.full((K, mn), -jnp.inf, jnp.float32),
        upper=jnp.full((K, mn), jnp.inf, jnp.float32),
        setcompat=jnp.ones((K, mn, n_sets), bool),
        splits_left=jnp.full((K,), budget, jnp.int32),
        is_cat=jnp.zeros((K, mn), bool),
        cat_set=jnp.zeros((K, mn, n_bin), bool),
    )


def _update_positions_k(bins, pos, best_feat, best_bin, best_dleft,
                        can_split, node0: int, N: int, n_bin: int):
    """Vectorized-over-classes position rewrite (numeric features)."""
    local = pos - node0  # (K, R)
    in_lvl = (local >= 0) & (local < N)
    lc = jnp.clip(local, 0, N - 1)
    can_r = jnp.take_along_axis(can_split, lc, axis=1)
    fr = jnp.take_along_axis(best_feat, lc, axis=1)
    sb = jnp.take_along_axis(best_bin, lc, axis=1)
    dl = jnp.take_along_axis(best_dleft, lc, axis=1)
    F = bins.shape[1]
    binval = jax.vmap(
        lambda f: jnp.take_along_axis(
            bins, jnp.clip(f, 0, F - 1)[:, None].astype(jnp.int32),
            axis=1)[:, 0].astype(jnp.int32))(fr)  # (K, R)
    goleft = jnp.where(binval >= n_bin, dl, binval <= sb)
    child = 2 * pos + 1 + jnp.where(goleft, 0, 1)
    return jnp.where(in_lvl & can_r, child, pos)


@functools.partial(
    jax.jit,
    static_argnames=("depth", "params", "last_level", "lossguide",
                     "subtract"),
)
def level_step_lockstep(state: TreeState, bins, gpair_rkc, cuts_pad, n_bins,
                        feature_mask, set_matrix, hist_prev=None, *,
                        depth: int, params: SplitParams, last_level: bool,
                        lossguide: bool = False, subtract: bool = False):
    """One level for all K trees at once (grow.level_step, K-vectorized)."""
    node0 = (1 << depth) - 1
    N = 1 << depth
    B = cuts_pad.shape[1]
    K = gpair_rkc.shape[1]

    idx = node0 + jnp.arange(N, dtype=jnp.int32)
    totals_lvl = lax.dynamic_slice_in_dim(state.totals, node0, N, axis=1)
    alive_lvl = lax.dynamic_slice_in_dim(state.alive, node0, N, axis=1)
    lower_lvl = lax.dynamic_slice_in_dim(state.lower, node0, N, axis=1)
    upper_lvl = lax.dynamic_slice_in_dim(state.upper, node0, N, axis=1)
    w = calc_weight(totals_lvl[..., 0], totals_lvl[..., 1], params,
                    lower_lvl, upper_lvl)

    if last_level:
        return state._replace(
            is_leaf=state.is_leaf.at[:, idx].set(alive_lvl),
            leaf_val=state.leaf_val.at[:, idx].set(
                jnp.where(alive_lvl, params.eta * w, 0.0)),
            base_weight=state.base_weight.at[:, idx].set(w),
            sum_hess=state.sum_hess.at[:, idx].set(totals_lvl[..., 1]),
        ), None

    if subtract:
        half = N // 2
        left = build_histogram_multi(bins, gpair_rkc, state.pos, node0,
                                     n_nodes=half, n_bin=B, stride=2)
        hist = jax.vmap(combine_sibling_hists)(left, hist_prev, alive_lvl)
    else:
        hist = build_histogram_multi(bins, gpair_rkc, state.pos, node0,
                                     n_nodes=N, n_bin=B)

    compat_lvl = lax.dynamic_slice_in_dim(state.setcompat, node0, N, axis=1)
    allowed = jnp.einsum("kns,sf->knf", compat_lvl.astype(jnp.float32),
                         set_matrix.astype(jnp.float32)) > 0.0
    fm = feature_mask if feature_mask.ndim == 2 else feature_mask[None, :]
    fmask = (allowed & fm[None]).reshape(K * N, -1)

    node_bounds = jnp.stack([lower_lvl, upper_lvl], axis=-1).reshape(
        K * N, 2)
    F = bins.shape[1]
    best = evaluate_splits(hist.reshape(K * N, F, B, 2),
                           totals_lvl.reshape(K * N, 2), n_bins, params,
                           fmask, node_bounds)

    def kn(a):
        return a.reshape(K, N, *a.shape[1:])

    b_gain, b_feat, b_bin = kn(best.gain), kn(best.feature), kn(best.bin)
    b_dleft = kn(best.default_left)
    b_left, b_right = kn(best.left_sum), kn(best.right_sum)
    b_lw, b_rw = kn(best.left_weight), kn(best.right_weight)

    gamma_eps = max(params.gamma, _EPS)
    can_split = alive_lvl & (b_gain > gamma_eps)

    budget = state.splits_left  # (K,)
    prio = b_gain if lossguide else jnp.broadcast_to(
        -idx.astype(jnp.float32)[None], b_gain.shape)
    prio = jnp.where(can_split, prio, -jnp.inf)
    ranks = jnp.argsort(jnp.argsort(-prio, axis=1), axis=1).astype(jnp.int32)
    can_split = can_split & (ranks < budget[:, None])
    new_budget = budget - jnp.sum(can_split, axis=1).astype(jnp.int32)
    new_leaf = alive_lvl & ~can_split

    thr_lvl = cuts_pad[b_feat, jnp.minimum(b_bin, B - 1)]
    member = set_matrix.T[jnp.clip(b_feat, 0, set_matrix.shape[1] - 1)]

    st = state._replace(
        feat=state.feat.at[:, idx].set(jnp.where(can_split, b_feat, -1)),
        sbin=state.sbin.at[:, idx].set(jnp.where(can_split, b_bin, 0)),
        thr=state.thr.at[:, idx].set(jnp.where(can_split, thr_lvl, 0.0)),
        dleft=state.dleft.at[:, idx].set(b_dleft),
        is_leaf=state.is_leaf.at[:, idx].set(new_leaf),
        leaf_val=state.leaf_val.at[:, idx].set(
            jnp.where(new_leaf, params.eta * w, 0.0)),
        gain=state.gain.at[:, idx].set(jnp.where(can_split, b_gain, 0.0)),
        base_weight=state.base_weight.at[:, idx].set(w),
        sum_hess=state.sum_hess.at[:, idx].set(totals_lvl[..., 1]),
        splits_left=new_budget,
    )
    left_ids = 2 * idx + 1
    right_ids = 2 * idx + 2
    st = st._replace(
        alive=st.alive.at[:, left_ids].set(can_split)
                      .at[:, right_ids].set(can_split),
        totals=st.totals.at[:, left_ids].set(b_left)
                        .at[:, right_ids].set(b_right),
    )
    child_compat = compat_lvl & member
    st = st._replace(
        setcompat=st.setcompat.at[:, left_ids].set(child_compat)
                              .at[:, right_ids].set(child_compat))
    if params.monotone is not None and any(c != 0 for c in params.monotone):
        cvec = jnp.asarray(params.monotone, jnp.int32)
        c_at = cvec[jnp.clip(b_feat, 0, len(params.monotone) - 1)]
        mid = 0.5 * (b_lw + b_rw)
        l_lo = jnp.where(c_at < 0, mid, lower_lvl)
        l_hi = jnp.where(c_at > 0, mid, upper_lvl)
        r_lo = jnp.where(c_at > 0, mid, lower_lvl)
        r_hi = jnp.where(c_at < 0, mid, upper_lvl)
        st = st._replace(
            lower=st.lower.at[:, left_ids].set(l_lo)
                          .at[:, right_ids].set(r_lo),
            upper=st.upper.at[:, left_ids].set(l_hi)
                          .at[:, right_ids].set(r_hi))
    st = st._replace(
        pos=_update_positions_k(bins, st.pos, b_feat, b_bin, b_dleft,
                                can_split, node0, N, B))
    return st, hist


class LockstepHistGrower:
    """Grow the K per-class trees of one boosting round in lockstep."""

    def __init__(self, max_depth: int, params: SplitParams, *,
                 interaction_sets=None, max_leaves: int = 0,
                 lossguide: bool = False, subtract: bool = True) -> None:
        self.max_depth = max_depth
        self.params = params
        self.interaction_sets = interaction_sets
        self.max_leaves = max_leaves
        self.lossguide = lossguide
        self.subtract = subtract
        self.max_nodes = max_nodes_for_depth(max_depth)

    def grow(self, bins, gpair_rkc, valid, cuts_pad, n_bins,
             feature_masks=None) -> TreeState:
        F = bins.shape[1]
        B = cuts_pad.shape[1]
        ones = jnp.ones((1, F), dtype=bool)
        setmat = jnp.asarray(make_set_matrix(self.interaction_sets, F))
        state = init_lockstep_state(
            gpair_rkc, valid, max_nodes=self.max_nodes,
            n_sets=setmat.shape[0],
            max_splits=(self.max_leaves - 1) if self.max_leaves > 0 else 0,
            n_bin=B)
        hist_prev = None
        md = self.max_depth
        for d in range(md + 1):
            fm = ones if feature_masks is None else feature_masks(d, 1 << d)
            state, hist_prev = level_step_lockstep(
                state, bins, gpair_rkc, cuts_pad, n_bins, fm, setmat,
                hist_prev, depth=d, params=self.params,
                last_level=(d == md), lossguide=self.lossguide,
                subtract=(self.subtract and d > 0 and hist_prev is not None))
        return state

    @staticmethod
    def to_host_class(state: TreeState, k: int) -> GrownTree:
        import numpy as np

        return GrownTree(
            is_cat=np.asarray(state.is_cat[k]),
            cat_set=np.asarray(state.cat_set[k]),
            feat=np.asarray(state.feat[k]),
            sbin=np.asarray(state.sbin[k]),
            thr=np.asarray(state.thr[k]),
            dleft=np.asarray(state.dleft[k]),
            is_leaf=np.asarray(state.is_leaf[k]),
            leaf_val=np.asarray(state.leaf_val[k]),
            gain=np.asarray(state.gain[k]),
            base_weight=np.asarray(state.base_weight[k]),
            sum_hess=np.asarray(state.sum_hess[k]),
            totals=np.asarray(state.totals[k]),
        )


@jax.jit
def leaf_margin_delta_k(pos, leaf_val):
    """(K, R) margin deltas from K finished trees (prediction-cache path)."""
    safe = jnp.clip(pos, 0, leaf_val.shape[1] - 1)
    vals = jnp.take_along_axis(leaf_val, safe, axis=1)
    return jnp.where(pos >= 0, vals, 0.0)
