"""Cluster training driver — the dask/spark frontend role.

Reference shape: python-package/xgboost/dask/__init__.py:267 (DaskDMatrix
carries per-worker data refs), :722 _train_async (start the tracker, launch
one training task per worker under a CommunicatorContext built from the
tracker's worker args, collect rank 0's booster + eval history).

There is no dask scheduler in the TPU stack, so the driver does the
_train_async choreography directly: ``train_distributed(params, parts, ...)``
starts a :class:`~xgboost_tpu.tracker.RabitTracker`, spawns one worker
process per data part, each worker rendezvouses through the tracker (rank
assigned by the tracker, jax.distributed underneath), builds its DMatrix
from its part, trains — cuts merge through the distributed sketch,
histograms allreduce per level — and rank 0's model comes back to the
caller as ``{"booster": Booster, "history": evals_result}``, the reference
dask ``train()`` return shape.

Data parts (one per worker) may be:

- a ``(X, y)`` tuple or ``{"data": X, "label": y, "weight": ..., ...}``
  dict of arrays (shipped to the worker by pickle, one file per part —
  each worker reads only its own shard),
- a URI string (the worker calls ``DMatrix(uri)`` — libsvm/npz), or
- a zero-arg callable returning one of the above (runs IN the worker, the
  dask-delayed role: use this when data must be loaded worker-locally;
  must be picklable, i.e. defined at module level).

This driver is SINGLE-HOST: it spawns local subprocesses and exchanges
results through a local temp directory.  It exists for multi-process
scale-out on one machine and as the reference ``dask.train`` surface.  On
a multi-host TPU pod, start one process per host yourself (any job
launcher), call ``collective.init`` with the tracker's ``worker_args()``
(or jax.distributed direct mode) in each, and train — that is the same
path the workers here take, minus the local spawn.  The default
``platform="cpu"`` keeps local multi-worker runs off the (single) TPU.
"""
from __future__ import annotations

import functools
import json
import os
import pickle
import shutil
import subprocess
import sys
import tempfile
from typing import Any, Dict, List, Optional, Sequence

from .core import Booster

_CHILD = r"""
import json, pickle, sys, traceback
import jax

platform = sys.argv[1]
if platform:
    jax.config.update("jax_platforms", platform)
uri, port, world = sys.argv[2], int(sys.argv[3]), int(sys.argv[4])
tmp, syspaths = sys.argv[5], sys.argv[6]
for p in reversed(syspaths.split(chr(31))):
    if p:
        sys.path.insert(0, p)

from xgboost_tpu import collective

with collective.CommunicatorContext(dmlc_tracker_uri=uri,
                                    dmlc_tracker_port=port,
                                    dmlc_nworker=world):
    rank = collective.get_rank()
    try:
        import os
        with open(os.path.join(tmp, "spec.pkl"), "rb") as fh:
            spec = pickle.load(fh)
        with open(os.path.join(tmp, f"part_{rank}.pkl"), "rb") as fh:
            part = pickle.load(fh)  # only this rank's shard

        import xgboost_tpu as xtb
        from xgboost_tpu.distributed import _make_dmatrix

        dtrain = _make_dmatrix(part)
        evals = [(dtrain, "train")] if spec["eval_train"] else []
        history = {}
        bst = xtb.train(spec["params"], dtrain, spec["num_boost_round"],
                        evals=evals, evals_result=history,
                        verbose_eval=spec["verbose_eval"],
                        **spec["train_kwargs"])
        if rank == 0:
            with open(os.path.join(tmp, "result.bin"), "wb") as fh:
                raw = bytes(bst.save_raw())
                head = json.dumps({
                    "history": history,
                    "best_iteration": getattr(bst, "best_iteration", None),
                }).encode()
                fh.write(len(head).to_bytes(8, "little") + head + raw)
    except BaseException as e:
        traceback.print_exc()
        # fan the failure out through the tracker so peers blocked in a
        # collective abort instead of hanging to the driver timeout
        try:
            collective.signal_error(f"worker rank {rank}: {e!r}")
        except Exception:
            pass
        raise
print("WORKER-DONE", flush=True)
"""


def _make_dmatrix(part: Any):
    """Resolve one worker's data ref into a DMatrix (DaskDMatrix role)."""
    from .data.dmatrix import DMatrix

    if callable(part):
        part = part()
    if isinstance(part, DMatrix):
        return part
    if isinstance(part, str):
        return DMatrix(part)
    if isinstance(part, tuple):
        X, y = part
        return DMatrix(X, label=y)
    if isinstance(part, dict):
        kw = dict(part)
        return DMatrix(kw.pop("data"), **kw)
    raise TypeError(f"cannot build a DMatrix from part of type {type(part)}")


def train_distributed(params: Dict[str, Any], parts: Sequence[Any],
                      num_boost_round: int = 10, *,
                      eval_train: bool = False,
                      verbose_eval: bool = False,
                      platform: Optional[str] = "cpu",
                      host_ip: str = "127.0.0.1",
                      timeout: int = 1200,
                      train_kwargs: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, Any]:
    """Train one model over ``len(parts)`` local workers; returns
    ``{"booster": Booster, "history": dict, "best_iteration": ...}``
    (the reference dask ``train()`` contract, dask/__init__.py:930)."""
    world = len(parts)
    if world == 0:
        raise ValueError("parts is empty — need one data part per worker")

    from .tracker import RabitTracker

    tracker = RabitTracker(n_workers=world, host_ip=host_ip)
    tracker.start()
    args = tracker.worker_args()

    tmp = tempfile.mkdtemp(prefix="xtb_dist_")
    procs: List[subprocess.Popen] = []
    logs: List[Any] = []
    try:
        with open(os.path.join(tmp, "spec.pkl"), "wb") as fh:
            pickle.dump({
                "params": dict(params),
                "num_boost_round": int(num_boost_round),
                "eval_train": bool(eval_train),
                "verbose_eval": verbose_eval,
                "train_kwargs": dict(train_kwargs or {}),
            }, fh)
        # tracker assigns ranks by connection order (sorted): any part can
        # end up at any rank, so every part file must be present; each
        # worker reads ONLY part_<its rank>
        for i, part in enumerate(parts):
            with open(os.path.join(tmp, f"part_{i}.pkl"), "wb") as fh:
                pickle.dump(part, fh)

        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # children pick their own device counts
        repo_root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
        # callable parts unpickle in the worker: the defining module's
        # directory must be importable there (plain-pickle rule, as in dask)
        sys_paths = [repo_root]
        for part in parts:
            fn = part.func if isinstance(part, functools.partial) else part
            if callable(fn):
                mod = sys.modules.get(getattr(fn, "__module__", ""), None)
                f = getattr(mod, "__file__", None)
                if f:
                    d = os.path.dirname(os.path.abspath(f))
                    if d not in sys_paths:
                        sys_paths.append(d)

        for i in range(world):
            # file-backed output: PIPE would deadlock a chatty worker whose
            # buffer fills while the driver waits on a sibling
            log = open(os.path.join(tmp, f"worker_{i}.log"), "w+")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _CHILD, platform or "",
                 str(args["dmlc_tracker_uri"]), str(args["dmlc_tracker_port"]),
                 str(world), tmp, chr(31).join(sys_paths)],
                stdout=log, stderr=subprocess.STDOUT, env=env))
        errs = []
        for i, p in enumerate(procs):
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                # bounded reap (XTB701): SIGKILL is not waitable-proof on
                # a wedged kernel-side process, and this loop must report
                # every worker, not hang on one corpse
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass
                errs.append(f"worker {i}: timed out after {timeout}s")
                continue
            if p.returncode != 0:
                logs[i].seek(0)
                errs.append(f"worker {i} (exit {p.returncode}):\n"
                            + logs[i].read()[-2000:])
        if errs:
            raise RuntimeError("distributed training failed:\n"
                               + "\n---\n".join(errs))
        tracker.wait_for(timeout=60)

        with open(os.path.join(tmp, "result.bin"), "rb") as fh:
            blob = fh.read()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()
        tracker.free()
        shutil.rmtree(tmp, ignore_errors=True)

    n = int.from_bytes(blob[:8], "little")
    meta = json.loads(blob[8:8 + n].decode())
    bst = Booster(params)
    bst.load_model(bytearray(blob[8 + n:]))
    return {"booster": bst, "history": meta["history"],
            "best_iteration": meta["best_iteration"]}
