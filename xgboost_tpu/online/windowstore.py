"""WindowStore: the extmem-paged, CRC-framed sliding training window.

:class:`~xgboost_tpu.lifecycle.window.FreshWindow` keeps every row as a
live numpy array — fine for a window that fits in RAM, a cap on how much
live traffic the online loop can learn from otherwise.  WindowStore
generalizes it with the out-of-core page machinery (arXiv:2005.09148,
``data/extmem.py``): appended rows stage in a small buffer, seal into
fixed-size pages packed ``[X | y | w]``, and each sealed page becomes a
:class:`~xgboost_tpu.data.extmem.CompressedPage` (zstd in RAM) or — when
zstandard is absent, or the ResourceGovernor reports memory pressure — a
CRC-gated :class:`~xgboost_tpu.data.extmem.DiskPage` spill.  Every page
read passes the pages' CRC-verify / retry-once / fail-loud gate, so a
bit-flip in a week-old window page is a detected corruption, not a
silently poisoned retrain.

Eviction is time- and row-bounded at whole-page granularity: the oldest
page falls off while the window exceeds ``max_rows`` (bounded overshoot
of at most one page of rows) or once its newest row ages past
``max_age_s``.  Under memory pressure (``memory_scale() < 1.0``) resident
pages spill to disk and new pages seal straight there — the window sheds
RAM before the governor has to shed anything that serves
(docs/reliability.md "Resource pressure & graceful degradation").

``to_dmatrix`` mirrors FreshWindow's contract: an in-memory DMatrix by
default, or the ExtMemQuantileDMatrix streaming route with
``extmem_chunk_rows`` set — one window page per extmem chunk, so a window
larger than RAM trains without ever being concatenated.
"""
from __future__ import annotations

import os
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from ..reliability import resources as _resources
from ..telemetry.registry import get_registry

__all__ = ["WindowStore"]

_instruments = None


def instruments():
    """(rows gauge, pages gauge, evicted, spilled bytes)
    xtb_online_window_* families."""
    global _instruments
    if _instruments is None:
        reg = get_registry()
        _instruments = (
            reg.gauge("xtb_online_window_rows",
                      "labeled rows currently in the sliding training "
                      "window (sealed pages + staging)"),
            reg.gauge("xtb_online_window_pages",
                      "sealed window pages currently held"),
            reg.counter("xtb_online_window_evicted_total",
                        "window rows evicted, by bound (rows | age)",
                        ("reason",)),
            reg.counter("xtb_online_window_spilled_bytes_total",
                        "window page bytes spilled to disk under memory "
                        "pressure (or sealed there without zstandard)"),
        )
    return _instruments


class _PageRec:
    """One sealed page: the CRC-framed page object plus the bookkeeping
    eviction and spill need (rows, arrival times, backing path)."""

    __slots__ = ("page", "rows", "t_first", "t_last", "path")

    def __init__(self, page, rows: int, t_first: float, t_last: float,
                 path: Optional[str]) -> None:
        self.page = page
        self.rows = rows
        self.t_first = t_first
        self.t_last = t_last
        self.path = path


def _store_iter(blocks: List[np.ndarray], weighted: bool):
    """DataIter over decoded packed blocks — one window page per extmem
    chunk (lazy extmem import keeps WindowStore importable without the
    paged-training stack loaded)."""
    from ..data.extmem import DataIter

    class _StoreIter(DataIter):
        def __init__(self) -> None:
            super().__init__()
            self._i = 0

        def next(self, input_data) -> bool:
            if self._i >= len(blocks):
                return False
            flat = blocks[self._i]
            F = flat.shape[1] - 2
            batch = {"data": flat[:, :F], "label": flat[:, F]}
            if weighted:
                batch["weight"] = flat[:, F + 1]
            input_data(**batch)
            self._i += 1
            return True

        def reset(self) -> None:
            self._i = 0

    return _StoreIter()


class WindowStore:
    """Extmem-paged sliding window of labeled (rows, labels[, weights]).

    ``max_rows``: row bound (whole-page eviction; None = unbounded).
    ``max_age_s``: age bound on a page's NEWEST row (None = no age bound).
    ``page_rows``: rows per sealed page (also the extmem chunk size).
    ``spool_dir``: where spilled pages live (None = private temp dir,
    removed on :meth:`clear`).
    ``clock``: injectable monotonic clock (tests age pages without
    sleeping).
    """

    def __init__(self, max_rows: Optional[int] = None,
                 max_age_s: Optional[float] = None,
                 page_rows: int = 1024,
                 spool_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if page_rows < 1:
            raise ValueError(f"page_rows must be >= 1, got {page_rows}")
        self.max_rows = int(max_rows) if max_rows else None
        self.max_age_s = float(max_age_s) if max_age_s else None
        self.page_rows = int(page_rows)
        self._clock = clock
        self._lock = threading.Lock()
        self._pages: "deque[_PageRec]" = deque()
        self._staging: List[np.ndarray] = []   # packed (r, F+2) blocks
        self._staging_rows = 0
        self._staging_t: List[float] = []      # arrival time per block
        self._num_features: Optional[int] = None
        self._weighted: Optional[bool] = None
        self._spool = spool_dir
        self._own_spool = spool_dir is None
        self._page_seq = 0
        self._spilled_bytes = 0

    # ------------------------------------------------------------- internals
    def _spool_path(self) -> str:
        if self._spool is None:
            self._spool = tempfile.mkdtemp(prefix="xtb_window_")
        else:
            os.makedirs(self._spool, exist_ok=True)
        self._page_seq += 1
        return os.path.join(self._spool, f"page{self._page_seq:06d}.npy")

    def _make_page(self, arr: np.ndarray, spill: bool):
        """Seal one packed block: zstd-compressed in RAM on the happy
        path, CRC-gated disk spill under pressure or without zstandard.
        Returns (page, path-or-None)."""
        from ..data.extmem import CompressedPage, DiskPage, _zstd_available

        if _zstd_available() and not spill:
            return CompressedPage(arr), None
        path = self._spool_path()
        if _zstd_available():
            page = CompressedPage(arr, path=path)
            spilled = page.nbytes_compressed
        else:
            page = DiskPage(arr, path)
            spilled = page.nbytes
        self._spilled_bytes += int(spilled)
        instruments()[3].inc(float(spilled))
        return page, path

    def _seal_locked(self, spill: bool) -> None:
        if not self._staging:
            return
        arr = (self._staging[0] if len(self._staging) == 1
               else np.concatenate(self._staging, axis=0))
        rec = _PageRec(None, int(len(arr)),
                       self._staging_t[0], self._staging_t[-1], None)
        rec.page, rec.path = self._make_page(np.ascontiguousarray(arr),
                                             spill)
        self._pages.append(rec)
        self._staging, self._staging_t, self._staging_rows = [], [], 0

    def _drop_page_locked(self, reason: str) -> None:
        rec = self._pages.popleft()
        instruments()[2].labels(reason).inc(float(rec.rows))
        if rec.path is not None:
            try:
                os.unlink(rec.path)
            except FileNotFoundError:
                pass
            except OSError as e:
                _resources.note_os_error(e, "online.window_unlink")

    def _evict_locked(self, now: float) -> None:
        if self.max_age_s is not None:
            cutoff = now - self.max_age_s
            while self._pages and self._pages[0].t_last < cutoff:
                self._drop_page_locked("age")
        if self.max_rows is not None:
            while self._pages and self._rows_locked() > self.max_rows:
                self._drop_page_locked("rows")

    def _rows_locked(self) -> int:
        return sum(r.rows for r in self._pages) + self._staging_rows

    def _gauges_locked(self) -> None:
        ins = instruments()
        ins[0].set(self._rows_locked())
        ins[1].set(len(self._pages))

    def _spill_resident_locked(self) -> int:
        """Move every RAM-resident page behind a disk path (decode once,
        re-seal spilled); returns pages moved.  The governor's
        memory-pressure response: the window gives its RAM back before
        anything that serves traffic degrades."""
        moved = 0
        for rec in self._pages:
            if rec.path is not None:
                continue
            arr = np.asarray(rec.page)
            rec.page, rec.path = self._make_page(arr, spill=True)
            moved += 1
        return moved

    # ------------------------------------------------------------------- API
    def append(self, X, y, weight=None) -> None:
        """Append one labeled batch.  Same validation contract as
        FreshWindow: row/label/weight lengths agree, and either every
        batch carries weights or none does."""
        X = np.atleast_2d(np.asarray(X, np.float32))
        y = np.asarray(y, np.float32).reshape(-1)
        if len(X) != len(y):
            raise ValueError(f"rows ({len(X)}) != labels ({len(y)})")
        if weight is not None:
            weight = np.asarray(weight, np.float32).reshape(-1)
            if len(weight) != len(y):
                raise ValueError("weight length != label length")
        weighted = weight is not None
        w = weight if weighted else np.ones(len(y), np.float32)
        block = np.concatenate(
            [X, y[:, None], w[:, None]], axis=1).astype(np.float32)
        now = self._clock()
        spill = _resources.get_governor().memory_scale() < 1.0
        with self._lock:
            if self._num_features is None:
                self._num_features = int(X.shape[1])
            elif int(X.shape[1]) != self._num_features:
                raise ValueError(
                    f"batch has {X.shape[1]} features, window holds "
                    f"{self._num_features}")
            if self._weighted is None:
                self._weighted = weighted
            elif weighted != self._weighted:
                raise ValueError(
                    "either every batch carries weights or none")
            self._staging.append(block)
            self._staging_t.append(now)
            self._staging_rows += len(block)
            if spill and any(r.path is None for r in self._pages):
                moved = self._spill_resident_locked()
                if moved:
                    _resources.degraded_event("online", "window_spill",
                                              pages=moved)
            while self._staging_rows >= self.page_rows:
                # seal exactly page_rows per page so the extmem chunk
                # size (and so the quantile sketch schedule) is stable
                # whatever batch sizes arrived
                flat = (self._staging[0] if len(self._staging) == 1
                        else np.concatenate(self._staging, axis=0))
                head, tail = flat[:self.page_rows], flat[self.page_rows:]
                t_head = self._staging_t[0]
                self._staging = [np.ascontiguousarray(head)]
                self._staging_t = [t_head]
                self._staging_rows = len(head)
                self._seal_locked(spill)
                if len(tail):
                    self._staging = [np.ascontiguousarray(tail)]
                    self._staging_t = [now]
                    self._staging_rows = len(tail)
            self._evict_locked(now)
            self._gauges_locked()

    def __len__(self) -> int:
        with self._lock:
            return self._rows_locked()

    @property
    def rows(self) -> int:
        return len(self)

    @property
    def num_pages(self) -> int:
        with self._lock:
            return len(self._pages)

    @property
    def spilled_bytes(self) -> int:
        with self._lock:
            return self._spilled_bytes

    def _blocks(self) -> List[np.ndarray]:
        """Decoded packed blocks, oldest first (each read CRC-gated by
        the page machinery)."""
        with self._lock:
            recs = list(self._pages)
            staging = list(self._staging)
        out = [np.asarray(r.page) for r in recs]
        out.extend(staging)
        return out

    def arrays(self):
        """(X, y, weight-or-None) concatenated — the small-window path."""
        blocks = self._blocks()
        if not blocks:
            raise ValueError("WindowStore is empty")
        flat = (blocks[0] if len(blocks) == 1
                else np.concatenate(blocks, axis=0))
        F = flat.shape[1] - 2
        w = flat[:, F + 1] if self._weighted else None
        return np.ascontiguousarray(flat[:, :F]), flat[:, F], w

    def to_dmatrix(self, extmem_chunk_rows: Optional[int] = None,
                   max_bin: int = 256, **kw):
        """Materialize the window for a continuation cycle.  Default: an
        in-memory DMatrix.  With ``extmem_chunk_rows`` (any truthy value —
        the chunk IS the page) the window streams page-by-page into an
        ExtMemQuantileDMatrix, never concatenated: the window-exceeds-RAM
        path."""
        if extmem_chunk_rows:
            from ..data.extmem import ExtMemQuantileDMatrix

            it = _store_iter(self._blocks(), bool(self._weighted))
            return ExtMemQuantileDMatrix(it, max_bin=max_bin, **kw)
        from ..data.dmatrix import DMatrix

        X, y, w = self.arrays()
        return DMatrix(X, label=y, weight=w, **kw)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            on_disk = sum(1 for r in self._pages if r.path is not None)
            return {"rows": self._rows_locked(),
                    "pages": len(self._pages),
                    "pages_on_disk": on_disk,
                    "staging_rows": self._staging_rows,
                    "spilled_bytes": self._spilled_bytes}

    def clear(self) -> None:
        """Drop every page and staging row; removes spilled page files
        (and the private spool dir when this store created it)."""
        with self._lock:
            while self._pages:
                rec = self._pages.popleft()
                if rec.path is not None:
                    try:
                        os.unlink(rec.path)
                    except OSError:
                        pass
            self._staging, self._staging_t, self._staging_rows = [], [], 0
            self._gauges_locked()
            if self._own_spool and self._spool is not None:
                import shutil

                shutil.rmtree(self._spool, ignore_errors=True)
                self._spool = None
