"""Drift detection over the live feedback stream.

The retrain trigger half of the online loop: the detector holds a
REFERENCE sample (the traffic the serving model was last trained/rebased
on) and a bounded CURRENT sample (what feedback capture is seeing now),
and compares them with the same distribution machinery the shadow
comparator uses (``serving/fleet.py``): two-sample KS per feature and on
the score distribution, plus PSI on the scores.  Three signals, three
deterministic thresholds — crossing any one raises ``drifted`` and the
scheduler's "retrain now" edge:

- ``feature_ks``: max over features of KS(reference, current) — the
  covariate-shift lens (an upstream pipeline change moves the inputs
  before it moves anything else).  The report names the top-K offending
  features (``DriftReport.top_features``: ``(feature_index, ks)`` pairs,
  worst first) so the postmortem starts from "feature 12 moved", not
  "something moved", and the crossing counter carries the worst
  feature's index as a ``feature`` label;
- ``score_ks``: KS between reference and current SERVED scores — the
  model's own output distribution drifting under it;
- ``score_psi``: PSI of current scores against reference deciles — broad
  shift the single worst ECDF gap understates.

Everything is windowed and counter-based — no PRNG, no wall-clock — so a
seeded replay of the same feedback schedule produces the same
DriftReport on the same ``check()`` call (docs/online.md "Determinism
contract").
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..serving.fleet import _ks_stat, _psi
from ..telemetry import flight as _flight
from ..telemetry.registry import get_registry

__all__ = ["DriftConfig", "DriftReport", "DriftDetector"]

_instruments = None


def instruments():
    """xtb_online_drift_total{signal,feature}: ``feature`` is the worst
    offending feature index for ``feature_ks`` crossings and empty for
    the score-level signals (score_ks / score_psi)."""
    global _instruments
    if _instruments is None:
        reg = get_registry()
        _instruments = reg.counter(
            "xtb_online_drift_total",
            "drift threshold crossings, by signal (feature_ks | "
            "score_ks | score_psi) and worst offending feature",
            ("signal", "feature"))
    return _instruments


@dataclasses.dataclass
class DriftConfig:
    """Deterministic thresholds.  ``min_rows``: both sides need at least
    this many rows before any signal can fire (tiny-sample KS is noise).
    ``current_rows``: bound on the current-sample buffer (newest rows
    win — drift is about what traffic looks like NOW).  A ``None``
    threshold disables that signal."""

    max_feature_ks: Optional[float] = 0.25
    max_score_ks: Optional[float] = 0.2
    max_score_psi: Optional[float] = 0.25
    min_rows: int = 64
    current_rows: int = 8192
    top_features: int = 5  # offending features named in the report

    def __post_init__(self) -> None:
        if self.min_rows < 1:
            raise ValueError("min_rows must be >= 1")
        if self.current_rows < self.min_rows:
            raise ValueError("current_rows must be >= min_rows")


@dataclasses.dataclass
class DriftReport:
    """One check(): per-signal statistics, which thresholds tripped, and
    the top-K offending features — ``(feature_index, ks)`` pairs sorted
    worst-first over ALL features (not only past-threshold ones, so a
    quiet report still shows where the pressure is building)."""

    drifted: bool
    triggers: List[str]
    stats: Dict[str, float]
    reference_rows: int
    current_rows: int
    top_features: List[Tuple[int, float]] = dataclasses.field(
        default_factory=list)


class DriftDetector:
    """Reference-vs-current drift over (features, served scores).

    Feed it through :meth:`observe` as matched feedback drains; call
    :meth:`check` on the scheduler's cadence; :meth:`rebase` after a
    successful swap (the new model's traffic IS the new reference).
    Thread-safe — observe runs wherever the scheduler pumps, check on
    its loop.
    """

    def __init__(self, config: Optional[DriftConfig] = None,
                 **overrides) -> None:
        if config is None:
            config = DriftConfig(**overrides)
        elif overrides:
            config = dataclasses.replace(config, **overrides)
        self.config = config
        self._lock = threading.Lock()
        self._ref_X: Optional[np.ndarray] = None
        self._ref_s: Optional[np.ndarray] = None
        self._cur_X: List[np.ndarray] = []
        self._cur_s: List[np.ndarray] = []
        self._cur_rows = 0

    def set_reference(self, X, scores) -> None:
        """Pin the reference sample explicitly (e.g. the training window
        the serving model came from)."""
        with self._lock:
            self._ref_X = np.atleast_2d(np.asarray(X, np.float32))
            self._ref_s = np.asarray(scores, np.float32).ravel()

    def has_reference(self) -> bool:
        with self._lock:
            return self._ref_X is not None

    def observe(self, X, scores) -> None:
        """One matched feedback batch.  With no reference pinned yet, the
        first ``min_rows`` observed rows become the reference — the loop
        self-primes on its own traffic."""
        X = np.atleast_2d(np.asarray(X, np.float32))
        s = np.asarray(scores, np.float32).ravel()[:len(X)]
        with self._lock:
            if self._ref_X is None:
                self._cur_X.append(X)
                self._cur_s.append(s)
                self._cur_rows += len(X)
                if self._cur_rows >= self.config.min_rows:
                    self._ref_X = np.concatenate(self._cur_X, axis=0)
                    self._ref_s = np.concatenate(self._cur_s)
                    self._cur_X, self._cur_s, self._cur_rows = [], [], 0
                return
            self._cur_X.append(X)
            self._cur_s.append(s)
            self._cur_rows += len(X)
            # newest-rows-win bound on the current sample
            while (self._cur_rows - len(self._cur_X[0])
                   >= self.config.current_rows):
                self._cur_rows -= len(self._cur_X[0])
                self._cur_X.pop(0)
                self._cur_s.pop(0)

    def rebase(self) -> None:
        """Current becomes reference (post-swap: the freshly trained
        model's recent traffic is the new normal); current resets."""
        with self._lock:
            if self._cur_rows:
                self._ref_X = np.concatenate(self._cur_X, axis=0)
                self._ref_s = np.concatenate(self._cur_s)
            self._cur_X, self._cur_s, self._cur_rows = [], [], 0

    def check(self) -> DriftReport:
        cfg = self.config
        with self._lock:
            ref_X, ref_s = self._ref_X, self._ref_s
            cur_rows = self._cur_rows
            cur_X = (np.concatenate(self._cur_X, axis=0)
                     if self._cur_X else None)
            cur_s = (np.concatenate(self._cur_s)
                     if self._cur_s else None)
        ref_rows = 0 if ref_X is None else len(ref_X)
        if (ref_X is None or cur_X is None
                or ref_rows < cfg.min_rows or cur_rows < cfg.min_rows):
            return DriftReport(False, [], {}, ref_rows, cur_rows)
        stats: Dict[str, float] = {}
        per_feature = [(j, _ks_stat(ref_X[:, j], cur_X[:, j]))
                       for j in range(min(ref_X.shape[1], cur_X.shape[1]))]
        # worst-first; index breaks ties so the ranking is deterministic
        per_feature.sort(key=lambda jv: (-jv[1], jv[0]))
        top = per_feature[:max(0, cfg.top_features)]
        stats["feature_ks"] = top[0][1] if top else 0.0
        stats["score_ks"] = _ks_stat(ref_s, cur_s)
        stats["score_psi"] = _psi(ref_s, cur_s)
        triggers = [
            name for name, limit in (
                ("feature_ks", cfg.max_feature_ks),
                ("score_ks", cfg.max_score_ks),
                ("score_psi", cfg.max_score_psi))
            if limit is not None and stats[name] > limit]
        for name in triggers:
            # attribution label: the worst offending feature index for the
            # covariate signal, empty for the score-level ones
            feat = str(top[0][0]) if (name == "feature_ks" and top) else ""
            instruments().labels(name, feat).inc()
            _flight.record(
                "event", "online.drift", signal=name, value=stats[name],
                **({"top_features": [[j, round(v, 4)] for j, v in top]}
                   if name == "feature_ks" else {}))
        return DriftReport(bool(triggers), triggers, stats, ref_rows,
                           cur_rows, top)
