"""Label join: pair sampled requests with their late-arriving labels.

The serving half of the online loop ships sampled (features, scores)
records driver-side (``op="feedback"`` frames, docs/online.md); labels
for those requests arrive later, from a different producer, keyed by the
same trace id the dispatcher stamped at submit.  :class:`FeedbackHub` is
the bounded symmetric join between the two streams:

- features arriving before their label wait in the pending-features map;
  labels arriving before their features wait in the pending-labels map
  (the join is symmetric because neither ordering is guaranteed — a
  feedback frame rides the replica's serialized socket behind in-flight
  predicts, a label can land the moment the caller's future resolves);
- a pair that meets inside the ``horizon_s`` join horizon is matched and
  queued for :meth:`drain`;
- anything that waits past the horizon, or overflows ``max_pending``, is
  DROPPED AND COUNTED (``xtb_online_join_dropped_total{reason}``) — the
  window trains on what actually joined, and the drop counters are the
  online loop's data-loss budget, never a silent shortfall.

Thread-safe: ``offer`` runs on fleet rx threads, ``label`` on whatever
thread the label producer owns, ``drain`` on the scheduler's.  The
``online.label_join`` fault seam fires inside :meth:`label` — an injected
exception is a dropped label (reason ``fault``), exercising the loop's
tolerance to a flaky label pipeline.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..reliability import faults as _faults
from ..telemetry import flight as _flight
from ..telemetry.registry import get_registry

__all__ = ["FeedbackHub"]

_instruments = None


def instruments():
    """(matched, dropped, pending) xtb_online_join_* families."""
    global _instruments
    if _instruments is None:
        reg = get_registry()
        _instruments = (
            reg.counter("xtb_online_join_matched_total",
                        "feedback records joined with their label",
                        ("model",)),
            reg.counter("xtb_online_join_dropped_total",
                        "join casualties by reason (expired past the "
                        "horizon, capacity overflow, label-pipeline "
                        "fault, duplicate trace)", ("reason",)),
            reg.gauge("xtb_online_join_pending",
                      "records waiting for their other half "
                      "(features + labels)"),
        )
    return _instruments


class FeedbackHub:
    """Bounded two-sided join of feedback records and labels by trace id.

    ``horizon_s``: how long either half waits for the other.
    ``max_pending``: cap on EACH side's waiting map — beyond it the
    oldest entry on that side is dropped (reason ``capacity``).
    ``clock``: injectable monotonic clock (tests age entries without
    sleeping).
    """

    def __init__(self, horizon_s: float = 60.0, max_pending: int = 4096,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if horizon_s <= 0:
            raise ValueError(f"horizon_s must be > 0, got {horizon_s}")
        if max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        self.horizon_s = float(horizon_s)
        self.max_pending = int(max_pending)
        self._clock = clock
        self._lock = threading.Lock()
        # trace -> (t_arrival, record) / (t_arrival, y); insertion order =
        # arrival order, so expiry and capacity both pop from the front
        self._features: "OrderedDict[str, tuple]" = OrderedDict()
        self._labels: "OrderedDict[str, tuple]" = OrderedDict()
        self._matched: List[dict] = []
        self.offered = 0
        self.labeled = 0
        self.matched = 0
        self.dropped: Dict[str, int] = {}

    # ------------------------------------------------------------- internals
    def _drop_locked(self, reason: str, n: int = 1) -> None:
        self.dropped[reason] = self.dropped.get(reason, 0) + n
        instruments()[1].labels(reason).inc(float(n))

    def _expire_locked(self, now: float) -> None:
        cutoff = now - self.horizon_s
        for side in (self._features, self._labels):
            while side:
                trace, (t, _) = next(iter(side.items()))
                if t >= cutoff:
                    break
                side.pop(trace)
                self._drop_locked("expired")

    def _cap_locked(self, side: "OrderedDict[str, tuple]") -> None:
        while len(side) > self.max_pending:
            side.popitem(last=False)
            self._drop_locked("capacity")

    def _match_locked(self, record: dict, y) -> None:
        out = dict(record)
        out["y"] = np.asarray(y, np.float32).reshape(-1)
        self._matched.append(out)
        self.matched += 1
        instruments()[0].labels(str(record.get("model"))).inc()

    def _gauge_locked(self) -> None:
        instruments()[2].set(len(self._features) + len(self._labels))

    # ------------------------------------------------------------------- API
    def offer(self, record: dict) -> None:
        """One decoded feedback record (the fleet's sink calls this on an
        rx thread).  Joins immediately when its label already waits."""
        trace = record.get("trace")
        if not trace:
            with self._lock:
                self._drop_locked("untraced")
            return
        now = self._clock()
        with self._lock:
            self._expire_locked(now)
            self.offered += 1
            pending = self._labels.pop(trace, None)
            if pending is not None:
                self._match_locked(record, pending[1])
            elif trace in self._features:
                # a duplicate sample for the same request (reroute after a
                # replica death can re-execute a sampled request): keep the
                # first, count the twin — matching both would double-weight
                # the row in the window
                self._drop_locked("duplicate")
            else:
                self._features[trace] = (now, record)
                self._cap_locked(self._features)
            self._gauge_locked()

    def label(self, trace: Optional[str], y) -> bool:
        """One label for ``trace`` (``Future.trace_id`` from submit).
        Returns True when it matched a waiting feedback record, False when
        it is itself now waiting (or was dropped).  The
        ``online.label_join`` seam makes an injected exception a dropped
        label — the loop's flaky-label-pipeline fault point."""
        if not trace:
            with self._lock:
                self._drop_locked("untraced")
            return False
        try:
            _faults.maybe_inject("online.label_join")
        except _faults.FaultInjected as e:
            _flight.record("fault", "online.label_join", trace=trace,
                           error=str(e))
            with self._lock:
                self._drop_locked("fault")
            return False
        now = self._clock()
        with self._lock:
            self._expire_locked(now)
            self.labeled += 1
            pending = self._features.pop(trace, None)
            if pending is not None:
                self._match_locked(pending[1], y)
                self._gauge_locked()
                return True
            if trace in self._labels:
                self._drop_locked("duplicate")
            else:
                self._labels[trace] = (now, y)
                self._cap_locked(self._labels)
            self._gauge_locked()
            return False

    def drain(self) -> List[dict]:
        """Take every matched pair accumulated since the last drain (each
        a feedback record dict plus its ``y``), in match order."""
        with self._lock:
            out, self._matched = self._matched, []
            return out

    def pending(self) -> Dict[str, int]:
        with self._lock:
            return {"features": len(self._features),
                    "labels": len(self._labels),
                    "matched": len(self._matched)}

    def stats(self) -> Dict[str, Any]:
        """Join accounting: offered + labeled = matched*2 + dropped +
        still-pending, the loop's conservation law (asserted by the chaos
        scenario's join invariant)."""
        with self._lock:
            return {"offered": self.offered, "labeled": self.labeled,
                    "matched": self.matched, "dropped": dict(self.dropped),
                    "pending_features": len(self._features),
                    "pending_labels": len(self._labels)}
