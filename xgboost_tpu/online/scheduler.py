"""OnlineScheduler: the always-on loop that closes serving back to training.

One object owns the whole feedback cycle (docs/online.md):

    traffic -> replicas sample 1-in-N -> FeedbackHub joins labels
            -> WindowStore (extmem-paged)  +  DriftDetector
            -> [drift edge or forced]      -> LifecycleManager.run_cycle
            -> gate -> shadow -> hot swap  -> detector rebase

The scheduler never trains when serving needs the host: the
ResourceGovernor is consulted FIRST on every retrain decision, and any
active brownout (or memory level >= 2) defers the cycle outright
(``xtb_online_deferred_total{reason}``) — a continuation retrain is the
single most expendable load on a degraded host, and the gold tenant's p99
never pays for it (docs/reliability.md "Resource pressure & graceful
degradation").

The ``online.retrain`` fault seam fires at the decision point, before
any lifecycle work: an injected exception is a cycle that never started
(outcome ``fault``), the incumbent untouched — the same incumbent-safety
contract every lifecycle reject path keeps.

Deterministic by construction: sampling is a counter off the trace id,
the join is horizon-bounded but clock-injectable, drift thresholds are
fixed numbers, and the lifecycle cycle under a fixed window is the
continuation-training determinism the lifecycle tests already pin — so
a seeded replay of the same request + label schedule retrains the same
model (the ``online`` chaos scenario's digest check).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, Optional

from ..lifecycle.manager import LifecycleConfig, LifecycleManager
from ..reliability import faults as _faults
from ..reliability import resources as _resources
from ..telemetry import flight as _flight
from ..telemetry.registry import get_registry
from .drift import DriftConfig, DriftDetector
from .feedback import FeedbackHub
from .windowstore import WindowStore

__all__ = ["OnlineConfig", "OnlineScheduler"]

_CYCLE_BUCKETS = tuple(0.01 * (2.0 ** i) for i in range(14))

_instruments = None


def instruments():
    """(cycles, deferred, cycle seconds) xtb_online_* families."""
    global _instruments
    if _instruments is None:
        reg = get_registry()
        _instruments = (
            reg.counter("xtb_online_cycles_total",
                        "retrain cycles by outcome (swapped | the "
                        "lifecycle reject reason | fault)", ("outcome",)),
            reg.counter("xtb_online_deferred_total",
                        "retrain decisions deferred, by reason "
                        "(brownout | memory | rows | no_drift)",
                        ("reason",)),
            reg.histogram("xtb_online_cycle_seconds",
                          "wall-clock per attempted retrain cycle",
                          buckets=_CYCLE_BUCKETS),
        )
    return _instruments


@dataclasses.dataclass
class OnlineConfig:
    """Loop knobs.

    ``sample_every``: replica-side 1-in-N feedback capture rate.
    ``join_horizon_s`` / ``max_pending``: the label join's bounds.
    ``min_retrain_rows``: window floor before any cycle may run.
    ``window_rows`` / ``window_age_s`` / ``page_rows`` / ``spool_dir``:
    the WindowStore's bounds (see :class:`WindowStore`).
    ``extmem_chunk_rows``: truthy routes each cycle's window through the
    ExtMemQuantileDMatrix streaming path (the window-exceeds-RAM mode).
    ``drift`` / ``lifecycle``: the detector's thresholds and the
    continuation cycle's knobs (gate, shadow phase, checkpointing).
    """

    sample_every: int = 8
    join_horizon_s: float = 60.0
    max_pending: int = 4096
    min_retrain_rows: int = 256
    window_rows: Optional[int] = 100_000
    window_age_s: Optional[float] = None
    page_rows: int = 1024
    spool_dir: Optional[str] = None
    extmem_chunk_rows: int = 0
    max_bin: int = 256
    drift: DriftConfig = dataclasses.field(default_factory=DriftConfig)
    lifecycle: LifecycleConfig = dataclasses.field(
        default_factory=LifecycleConfig)

    def __post_init__(self) -> None:
        if self.sample_every < 1:
            raise ValueError("sample_every must be >= 1")
        if self.min_retrain_rows < 1:
            raise ValueError("min_retrain_rows must be >= 1")


class OnlineScheduler:
    """Drive the closed loop for one model over a running fleet.

    Construction wires nothing: call :meth:`enable` to start feedback
    capture (broadcasts the sample rate, registers the fleet sink), feed
    labels through :meth:`label`, and either call :meth:`step` on your
    own cadence (tests, smoke scripts — deterministic) or hand a stop
    event to :meth:`run` for the always-on thread loop.
    """

    def __init__(self, fleet, model: str,
                 config: Optional[OnlineConfig] = None,
                 params: Optional[Dict[str, Any]] = None,
                 clock=time.monotonic, **overrides) -> None:
        if config is None:
            config = OnlineConfig(**overrides)
        elif overrides:
            config = dataclasses.replace(config, **overrides)
        self.fleet = fleet
        self.model = model
        self.config = config
        self._params = params
        self.hub = FeedbackHub(horizon_s=config.join_horizon_s,
                               max_pending=config.max_pending, clock=clock)
        self.window = WindowStore(max_rows=config.window_rows,
                                  max_age_s=config.window_age_s,
                                  page_rows=config.page_rows,
                                  spool_dir=config.spool_dir, clock=clock)
        self.detector = DriftDetector(config.drift)
        # the LifecycleManager binds to the fleet's store at first use,
        # not construction: pumping/joining/drift-checking must work
        # against a bare fleet (and in unit tests with a stub)
        self._mgr: Optional[LifecycleManager] = None
        self._lock = threading.Lock()
        self._enabled = False
        self.cycles = 0
        self.swaps = 0

    # ------------------------------------------------------------- capture
    def _on_feedback(self, record: dict) -> None:
        if record.get("model") == self.model:
            self.hub.offer(record)

    def enable(self) -> None:
        """Turn the loop's intake on: broadcast the sample rate, register
        the feedback sink, and (when the fleet supports label-feed
        connections) route remote ``op="label"`` frames into the same
        join as the in-process :meth:`label` API."""
        self.fleet.set_feedback_sink(self._on_feedback)
        # guarded: unit-test stubs implement only the feedback surface
        set_labels = getattr(self.fleet, "set_label_sink", None)
        if set_labels is not None:
            set_labels(self.hub.label)
        self.fleet.set_sampling(self.model, self.config.sample_every)
        with self._lock:
            self._enabled = True
        _flight.record("event", "online.enable", model=self.model,
                       every=self.config.sample_every)

    def disable(self) -> None:
        with self._lock:
            was = self._enabled
            self._enabled = False
        if was:
            self.fleet.set_sampling(self.model, 0)
            self.fleet.set_feedback_sink(None)
            set_labels = getattr(self.fleet, "set_label_sink", None)
            if set_labels is not None:
                set_labels(None)

    def label(self, trace: Optional[str], y) -> bool:
        """Label one request by its trace id (``Future.trace_id``)."""
        return self.hub.label(trace, y)

    def pump(self) -> int:
        """Drain matched (features, label) pairs into the window and the
        drift detector; returns rows absorbed."""
        rows = 0
        for rec in self.hub.drain():
            X, y = rec["X"], rec["y"]
            n = min(len(X), len(y))
            self.window.append(X[:n], y[:n])
            self.detector.observe(X[:n], rec.get("scores"))
            rows += n
        return rows

    # -------------------------------------------------------------- retrain
    def _manager(self) -> LifecycleManager:
        with self._lock:
            if self._mgr is None:
                self._mgr = LifecycleManager(self.fleet, self.model,
                                             params=self._params,
                                             config=self.config.lifecycle)
            return self._mgr

    def _defer(self, reason: str, **detail) -> Dict[str, Any]:
        instruments()[1].labels(reason).inc()
        _flight.record("event", "online.defer", model=self.model,
                       reason=reason, **detail)
        return {"outcome": "deferred", "reason": reason, **detail}

    def maybe_retrain(self, force: bool = False) -> Dict[str, Any]:
        """One retrain decision.  Order is the contract: governor first
        (training yields to serving), then the window floor, then the
        drift edge (unless ``force``), then — and only then — a
        lifecycle cycle."""
        gov = _resources.get_governor()
        if gov.level("memory") >= 2:
            # memory collapse outranks the generic brownout (any level >=1
            # raises the cutoff): name the real reason, not the symptom
            return self._defer("memory", level=gov.level("memory"))
        if gov.brownout_cutoff() is not None:
            # serving is shedding load: a discretionary retrain is the
            # last thing this host should start
            return self._defer("brownout", level=gov.max_level())
        rows = len(self.window)
        if rows < self.config.min_retrain_rows:
            return self._defer("rows", rows=rows,
                               need=self.config.min_retrain_rows)
        drift = None
        if not force:
            drift = self.detector.check()
            if not drift.drifted:
                instruments()[1].labels("no_drift").inc()
                return {"outcome": "idle", "drift": drift.stats}
        t0 = time.perf_counter()
        with self._lock:
            self.cycles += 1
        try:
            _faults.maybe_inject("online.retrain")
        except _faults.FaultInjected as e:
            # the cycle never starts: incumbent untouched, counted as a
            # faulted cycle — same outcome accounting a lifecycle-phase
            # fault lands on
            instruments()[0].labels("fault").inc()
            _flight.record("fault", "online.retrain", model=self.model,
                           error=str(e))
            return {"outcome": "fault", "error": str(e)}
        _flight.record("event", "online.retrain", model=self.model,
                       rows=rows,
                       triggers=list(drift.triggers) if drift else None,
                       forced=bool(force))
        dwin = self.window.to_dmatrix(
            extmem_chunk_rows=self.config.extmem_chunk_rows or None,
            max_bin=self.config.max_bin)
        report = self._manager().run_cycle(dwin)
        seconds = time.perf_counter() - t0
        outcome = ("swapped" if report.swapped
                   else (report.decision.reason if report.decision
                         else "rejected"))
        instruments()[0].labels(outcome).inc()
        instruments()[2].observe(seconds)
        if report.swapped:
            with self._lock:
                self.swaps += 1
            # the freshly swapped model's recent traffic is the new
            # normal: without the rebase the same drift would retrigger
            # every cycle forever
            self.detector.rebase()
        _flight.record("event", "online.cycle", model=self.model,
                       outcome=outcome, seconds=seconds,
                       version=report.candidate_version,
                       trace=report.trace_id)
        return {"outcome": outcome, "report": report, "seconds": seconds,
                "drift": drift.stats if drift else None}

    def step(self, force: bool = False) -> Dict[str, Any]:
        """One deterministic loop iteration: pump, then decide."""
        pumped = self.pump()
        out = self.maybe_retrain(force=force)
        out["pumped_rows"] = pumped
        return out

    def run(self, stop: threading.Event, tick_s: float = 1.0) -> None:
        """The always-on loop: step every ``tick_s`` until ``stop`` is
        set.  Exceptions are recorded and the loop keeps going — an
        online loop that dies on one bad cycle silently stops learning."""
        while not stop.is_set():
            try:
                self.step()
            except Exception as e:  # keep the loop alive
                _flight.record("fault", "online.loop", model=self.model,
                               error=str(e))
            stop.wait(tick_s)
