"""Online learning loop: live-traffic feedback, drift, continuous retraining.

The always-on subsystem that closes the loop from serving back to
training (docs/online.md):

- :class:`FeedbackHub` — bounded symmetric join of replica-sampled
  (features, scores) records with late-arriving labels, by trace id;
- :class:`WindowStore` — the extmem-paged, CRC-framed sliding training
  window those matches land in (FreshWindow generalized: time/row
  eviction, pages spill to disk under memory pressure);
- :class:`DriftDetector` — reference-vs-current KS/PSI over features and
  served scores, deterministic thresholds, the retrain trigger;
- :class:`OnlineScheduler` — the loop: pump matches, watch drift, and
  drive LifecycleManager cycles under the ResourceGovernor (training
  brownout always yields to serving).
"""
from __future__ import annotations

from .drift import DriftConfig, DriftDetector, DriftReport
from .feedback import FeedbackHub
from .scheduler import OnlineConfig, OnlineScheduler
from .windowstore import WindowStore

__all__ = [
    "DriftConfig",
    "DriftDetector",
    "DriftReport",
    "FeedbackHub",
    "OnlineConfig",
    "OnlineScheduler",
    "WindowStore",
]
