"""Global configuration (reference: python-package/xgboost/config.py,
include/xgboost/global_config.h:16-35 — thread-local {verbosity, nthread, ...})."""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict

_DEFAULTS: Dict[str, Any] = {
    "verbosity": 1,
    "use_rmm": False,  # accepted for API parity; no-op on TPU
    "nthread": None,
}

_local = threading.local()


def _store() -> Dict[str, Any]:
    if not hasattr(_local, "config"):
        _local.config = dict(_DEFAULTS)
    return _local.config


def set_config(**new_config: Any) -> None:
    store = _store()
    for k, v in new_config.items():
        if k not in _DEFAULTS:
            raise ValueError(f"Unknown global config key: {k}")
        store[k] = v


def get_config() -> Dict[str, Any]:
    return dict(_store())


@contextlib.contextmanager
def config_context(**new_config: Any):
    old = get_config()
    set_config(**new_config)
    try:
        yield
    finally:
        _store().update(old)
