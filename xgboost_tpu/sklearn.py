"""scikit-learn compatible estimator API (reference:
python-package/xgboost/sklearn.py — XGBModel:820, XGBClassifier:1712,
XGBRegressor:2020, XGBRanker:2176, RF variants :1964/:2057)."""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .core import Booster
from .data.dmatrix import DMatrix
from .training import train as _train

_SKLEARN_PARAMS = [
    "max_depth", "max_leaves", "max_bin", "grow_policy", "learning_rate",
    "n_estimators", "verbosity", "objective", "booster", "tree_method",
    "gamma", "min_child_weight", "max_delta_step", "subsample",
    "sampling_method", "colsample_bytree", "colsample_bylevel",
    "colsample_bynode", "reg_alpha", "reg_lambda", "scale_pos_weight",
    "base_score", "random_state", "missing", "num_parallel_tree",
    "monotone_constraints", "interaction_constraints", "importance_type",
    "device", "validate_parameters", "enable_categorical", "feature_types",
    "max_cat_to_onehot", "max_cat_threshold", "multi_strategy",
    "eval_metric", "early_stopping_rounds", "callbacks",
]


class XGBModel:
    """Base estimator (reference: sklearn.py:820)."""

    def __init__(
        self,
        max_depth: Optional[int] = None,
        max_leaves: Optional[int] = None,
        max_bin: Optional[int] = None,
        grow_policy: Optional[str] = None,
        learning_rate: Optional[float] = None,
        n_estimators: Optional[int] = None,
        verbosity: Optional[int] = None,
        objective: Optional[str] = None,
        booster: Optional[str] = None,
        tree_method: Optional[str] = None,
        n_jobs: Optional[int] = None,
        gamma: Optional[float] = None,
        min_child_weight: Optional[float] = None,
        max_delta_step: Optional[float] = None,
        subsample: Optional[float] = None,
        sampling_method: Optional[str] = None,
        colsample_bytree: Optional[float] = None,
        colsample_bylevel: Optional[float] = None,
        colsample_bynode: Optional[float] = None,
        reg_alpha: Optional[float] = None,
        reg_lambda: Optional[float] = None,
        scale_pos_weight: Optional[float] = None,
        base_score: Optional[float] = None,
        random_state: Optional[int] = None,
        missing: float = np.nan,
        num_parallel_tree: Optional[int] = None,
        monotone_constraints: Optional[Any] = None,
        interaction_constraints: Optional[Any] = None,
        importance_type: Optional[str] = None,
        device: Optional[str] = None,
        validate_parameters: Optional[bool] = None,
        enable_categorical: bool = False,
        feature_types: Optional[Any] = None,
        max_cat_to_onehot: Optional[int] = None,
        max_cat_threshold: Optional[int] = None,
        multi_strategy: Optional[str] = None,
        eval_metric: Optional[Union[str, List[str], Callable]] = None,
        early_stopping_rounds: Optional[int] = None,
        callbacks: Optional[List] = None,
        **kwargs: Any,
    ):
        self.max_depth = max_depth
        self.max_leaves = max_leaves
        self.max_bin = max_bin
        self.grow_policy = grow_policy
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.verbosity = verbosity
        self.objective = objective
        self.booster = booster
        self.tree_method = tree_method
        self.n_jobs = n_jobs
        self.gamma = gamma
        self.min_child_weight = min_child_weight
        self.max_delta_step = max_delta_step
        self.subsample = subsample
        self.sampling_method = sampling_method
        self.colsample_bytree = colsample_bytree
        self.colsample_bylevel = colsample_bylevel
        self.colsample_bynode = colsample_bynode
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.scale_pos_weight = scale_pos_weight
        self.base_score = base_score
        self.random_state = random_state
        self.missing = missing
        self.num_parallel_tree = num_parallel_tree
        self.monotone_constraints = monotone_constraints
        self.interaction_constraints = interaction_constraints
        self.importance_type = importance_type
        self.device = device
        self.validate_parameters = validate_parameters
        self.enable_categorical = enable_categorical
        self.feature_types = feature_types
        self.max_cat_to_onehot = max_cat_to_onehot
        self.max_cat_threshold = max_cat_threshold
        self.multi_strategy = multi_strategy
        self.eval_metric = eval_metric
        self.early_stopping_rounds = early_stopping_rounds
        self.callbacks = callbacks
        self.kwargs = kwargs
        self._Booster: Optional[Booster] = None

    # --- sklearn protocol ---
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        out = {k: getattr(self, k) for k in _SKLEARN_PARAMS if hasattr(self, k)}
        out["n_jobs"] = self.n_jobs
        out["random_state"] = self.random_state
        out.update(self.kwargs)
        return out

    def set_params(self, **params: Any) -> "XGBModel":
        for k, v in params.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self.kwargs[k] = v
        return self

    def _more_tags(self):
        return {"allow_nan": True}

    def __sklearn_tags__(self):
        # sklearn >= 1.6 tags protocol
        try:
            from sklearn.base import BaseEstimator

            tags = BaseEstimator.__sklearn_tags__(self)
        except Exception:
            class _T:  # minimal fallback
                pass

            tags = _T()
        try:
            tags.input_tags.allow_nan = True
        except Exception:
            pass
        return tags

    def _default_objective(self) -> str:
        return "reg:squarederror"

    def _xgb_params(self) -> Dict[str, Any]:
        mapping = {
            "learning_rate": "eta",
            "reg_alpha": "alpha",
            "reg_lambda": "lambda",
            "random_state": "seed",
        }
        skip = {"n_estimators", "n_jobs", "missing", "importance_type",
                "enable_categorical", "feature_types", "early_stopping_rounds",
                "callbacks", "eval_metric", "kwargs"}
        params: Dict[str, Any] = {}
        for k in _SKLEARN_PARAMS:
            if k in skip or not hasattr(self, k):
                continue
            v = getattr(self, k)
            if v is None:
                continue
            params[mapping.get(k, k)] = v
        params.update(self.kwargs)
        fit_obj = getattr(self, "_fit_objective", None)
        if fit_obj is not None:
            params["objective"] = fit_obj
        params.setdefault("objective", self._default_objective())
        if self.eval_metric is not None and not callable(self.eval_metric):
            params["eval_metric"] = self.eval_metric
        return params

    def _n_rounds(self) -> int:
        return self.n_estimators if self.n_estimators is not None else 100

    def fit(
        self,
        X,
        y,
        *,
        sample_weight=None,
        base_margin=None,
        eval_set: Optional[Sequence[Tuple[Any, Any]]] = None,
        verbose: Optional[Union[bool, int]] = False,
        xgb_model=None,
        sample_weight_eval_set=None,
        base_margin_eval_set=None,
        feature_weights=None,
    ) -> "XGBModel":
        dtrain = DMatrix(X, label=y, weight=sample_weight, base_margin=base_margin,
                         missing=self.missing, feature_weights=feature_weights)
        evals = []
        if eval_set:
            for i, (Xe, ye) in enumerate(eval_set):
                we = sample_weight_eval_set[i] if sample_weight_eval_set else None
                bme = base_margin_eval_set[i] if base_margin_eval_set else None
                if Xe is X and ye is y:
                    evals.append((dtrain, f"validation_{i}"))
                else:
                    evals.append(
                        (DMatrix(Xe, label=ye, weight=we, base_margin=bme,
                                 missing=self.missing), f"validation_{i}")
                    )
        res: Dict[str, Dict[str, List[float]]] = {}
        self._Booster = _train(
            self._xgb_params(), dtrain, self._n_rounds(), evals=evals,
            early_stopping_rounds=self.early_stopping_rounds,
            evals_result=res, verbose_eval=verbose,
            xgb_model=xgb_model, callbacks=self.callbacks,
        )
        self.evals_result_ = res
        self.n_features_in_ = dtrain.num_col()
        if self._Booster.best_iteration is not None:
            self.best_iteration = self._Booster.best_iteration
            self.best_score = self._Booster.best_score
        return self

    def get_booster(self) -> Booster:
        if self._Booster is None:
            raise ValueError("need to call fit or load_model first")
        return self._Booster

    def predict(
        self,
        X,
        *,
        output_margin: bool = False,
        validate_features: bool = True,
        base_margin=None,
        iteration_range: Optional[Tuple[int, int]] = None,
    ):
        d = DMatrix(X, missing=self.missing, base_margin=base_margin)
        return self.get_booster().predict(
            d, output_margin=output_margin,
            iteration_range=self._iteration_range(iteration_range),
        )

    def _iteration_range(self, iteration_range):
        """Default to (0, best_iteration+1) after early stopping; upstream
        treats both None and hi == 0 as "unspecified"
        (reference: sklearn.py _get_iteration_range)."""
        if iteration_range is not None and iteration_range[1] != 0:
            return iteration_range
        best = getattr(self._Booster, "best_iteration", None)
        if best is not None:
            return (0, int(best) + 1)
        return (0, 0)

    def apply(self, X, iteration_range=None):
        d = DMatrix(X, missing=self.missing)
        return self.get_booster().predict(
            d, pred_leaf=True,
            iteration_range=self._iteration_range(iteration_range))

    def save_model(self, fname) -> None:
        self.get_booster().save_model(fname)

    def load_model(self, fname) -> None:
        self._Booster = Booster()
        self._Booster.load_model(fname)

    @property
    def feature_importances_(self) -> np.ndarray:
        b = self.get_booster()
        score = b.get_score(importance_type=self.importance_type or "weight")
        n = self.n_features_in_ if hasattr(self, "n_features_in_") else b.num_features()
        names = b.feature_names or [f"f{i}" for i in range(n)]
        total = sum(score.values()) or 1.0
        return np.array([score.get(f, 0.0) / total for f in names], dtype=np.float32)

    @property
    def intercept_(self) -> np.ndarray:
        return np.asarray(self.get_booster().base_score)

    def evals_result(self) -> Dict:
        return getattr(self, "evals_result_", {})


class XGBRegressor(XGBModel):
    """(reference: sklearn.py:2020)"""


class XGBClassifier(XGBModel):
    """(reference: sklearn.py:1712)"""

    def _default_objective(self) -> str:
        return "binary:logistic"

    def fit(self, X, y, **kwargs) -> "XGBClassifier":
        y = np.asarray(y)
        self.classes_ = np.unique(y)
        self.n_classes_ = len(self.classes_)
        y_enc = np.searchsorted(self.classes_, y).astype(np.float32)
        # per-fit objective/num_class (refitting with a different class count
        # must not inherit stale state)
        self.kwargs.pop("num_class", None)
        if self.n_classes_ > 2:
            if self.objective is None or not str(self.objective).startswith("multi"):
                self._fit_objective = "multi:softprob"
            else:
                self._fit_objective = self.objective
            self.kwargs["num_class"] = self.n_classes_
        else:
            self._fit_objective = self.objective or self._default_objective()
        super().fit(X, y_enc, **kwargs)
        return self

    def predict(self, X, *, output_margin=False, validate_features=True,
                base_margin=None, iteration_range=None):
        raw = super().predict(
            X, output_margin=output_margin, base_margin=base_margin,
            iteration_range=iteration_range,
        )
        if output_margin:
            return raw
        if raw.ndim == 2:
            idx = np.argmax(raw, axis=1)
        elif getattr(self, "n_classes_", 2) > 2:
            idx = raw.astype(np.int64)  # multi:softmax emits class ids directly
        else:
            idx = (raw > 0.5).astype(np.int64)
        return self.classes_[idx]

    def predict_proba(self, X, *, validate_features=True, base_margin=None,
                      iteration_range=None):
        if getattr(self, "n_classes_", 2) > 2 and str(getattr(self, "_fit_objective", self.objective)) == "multi:softmax":
            # softmax objective transforms to class ids; recover probabilities
            # from raw margins (reference sklearn.py does the same)
            m = super().predict(X, output_margin=True, base_margin=base_margin,
                                iteration_range=iteration_range)
            e = np.exp(m - m.max(axis=1, keepdims=True))
            return e / e.sum(axis=1, keepdims=True)
        raw = super().predict(X, base_margin=base_margin, iteration_range=iteration_range)
        if raw.ndim == 2:
            return raw
        return np.stack([1 - raw, raw], axis=1)


class XGBRanker(XGBModel):
    """(reference: sklearn.py:2176)"""

    def _default_objective(self) -> str:
        return "rank:ndcg"

    def fit(self, X, y, *, group=None, qid=None, sample_weight=None,
            eval_set=None, eval_group=None, eval_qid=None, verbose=False,
            **kwargs) -> "XGBRanker":
        dtrain = DMatrix(X, label=y, weight=sample_weight, missing=self.missing,
                         group=group, qid=qid)
        evals = []
        if eval_set:
            for i, (Xe, ye) in enumerate(eval_set):
                ge = eval_group[i] if eval_group else None
                qe = eval_qid[i] if eval_qid else None
                evals.append((DMatrix(Xe, label=ye, missing=self.missing,
                                      group=ge, qid=qe), f"validation_{i}"))
        res: Dict = {}
        self._Booster = _train(
            self._xgb_params(), dtrain, self._n_rounds(), evals=evals,
            early_stopping_rounds=self.early_stopping_rounds,
            evals_result=res, verbose_eval=verbose, callbacks=self.callbacks,
        )
        self.evals_result_ = res
        self.n_features_in_ = dtrain.num_col()
        return self


def _rf_defaults(kwargs: Dict[str, Any]) -> Dict[str, Any]:
    kwargs.setdefault("learning_rate", 1.0)
    kwargs.setdefault("subsample", 0.8)
    kwargs.setdefault("colsample_bynode", 0.8)
    kwargs.setdefault("reg_lambda", 1e-5)
    return kwargs


class XGBRFRegressor(XGBRegressor):
    """Random-forest style (reference: sklearn.py:2057)."""

    def __init__(self, **kwargs):
        super().__init__(**_rf_defaults(kwargs))


class XGBRFClassifier(XGBClassifier):
    """(reference: sklearn.py:1964)"""

    def __init__(self, **kwargs):
        super().__init__(**_rf_defaults(kwargs))
