"""Feature attribution: exact TreeSHAP, Saabas approximation, interactions.

Reference: src/predictor/interpretability/shap.cc (exact path-dependent
TreeSHAP, 872 LoC) and shap.cu (warp-parallel GPU rewrite).  This is a
re-implementation of the published TreeSHAP algorithm (Lundberg et al. 2018,
indexed in PAPERS.md) over our struct-of-array RegTree: the EXTEND/UNWIND
recursion walks each tree once per row, weighting by cover fractions
(sum_hessian) exactly like the reference's ``TreePathInfo`` walk.

Local accuracy holds: contribs.sum(-1) == margin prediction (tested).
Host/numpy implementation; a batched device kernel is the planned follow-up
(mirroring the reference's gpu_treeshap split).
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np


class _Path:
    """The m-path of (feature, zero_fraction, one_fraction, pweight) tuples."""

    __slots__ = ("feat", "zero", "one", "pw")

    def __init__(self, capacity: int):
        self.feat = np.full(capacity, -1, np.int64)
        self.zero = np.zeros(capacity, np.float64)
        self.one = np.zeros(capacity, np.float64)
        self.pw = np.zeros(capacity, np.float64)

    def copy(self, length: int) -> "_Path":
        p = _Path(len(self.feat))
        p.feat[:length] = self.feat[:length]
        p.zero[:length] = self.zero[:length]
        p.one[:length] = self.one[:length]
        p.pw[:length] = self.pw[:length]
        return p


def _extend(p: _Path, length: int, pz: float, po: float, pi: int) -> int:
    p.feat[length] = pi
    p.zero[length] = pz
    p.one[length] = po
    p.pw[length] = 1.0 if length == 0 else 0.0
    for i in range(length - 1, -1, -1):
        p.pw[i + 1] += po * p.pw[i] * (i + 1) / (length + 1)
        p.pw[i] = pz * p.pw[i] * (length - i) / (length + 1)
    return length + 1


def _unwind(p: _Path, length: int, i: int) -> int:
    length -= 1
    po, pz = p.one[i], p.zero[i]
    n = p.pw[length]
    for j in range(length - 1, -1, -1):
        if po != 0.0:
            t = p.pw[j]
            p.pw[j] = n * (length + 1) / ((j + 1) * po)
            n = t - p.pw[j] * pz * (length - j) / (length + 1)
        else:
            p.pw[j] = p.pw[j] * (length + 1) / (pz * (length - j))
    for j in range(i, length):
        p.feat[j] = p.feat[j + 1]
        p.zero[j] = p.zero[j + 1]
        p.one[j] = p.one[j + 1]
    return length


def _unwound_sum(p: _Path, length: int, i: int) -> float:
    po, pz = p.one[i], p.zero[i]
    total = 0.0
    n = p.pw[length - 1]
    for j in range(length - 2, -1, -1):
        if po != 0.0:
            t = n * length / ((j + 1) * po)
            total += t
            n = p.pw[j] - t * pz * (length - 1 - j) / length
        else:
            total += p.pw[j] * length / (pz * (length - 1 - j))
    return total


def _tree_shap_recurse(t, x, phi, node: int, p: _Path, length: int,
                       pz: float, po: float, pi: int, cond_feat: int = -1):
    p = p.copy(length)
    length = _extend(p, length, pz, po, pi)
    left, right = t["left"][node], t["right"][node]
    if left < 0:  # leaf
        v = t["value"][node]
        for i in range(1, length):
            w = _unwound_sum(p, length, i)
            phi[p.feat[i]] += w * (p.one[i] - p.zero[i]) * v
        return
    f = t["feat"][node]
    xv = x[f]
    go_left = _go_left(t, node, xv)
    hot, cold = (left, right) if go_left else (right, left)
    cover = t["cover"]
    rj = cover[node]
    rh, rc = cover[hot], cover[cold]
    iz = io = 1.0
    # if this feature already on the path, undo its previous contribution
    k = -1
    for i in range(1, length):
        if p.feat[i] == f:
            k = i
            break
    if k >= 0:
        iz, io = p.zero[k], p.one[k]
        length = _unwind(p, length, k)
    _tree_shap_recurse(t, x, phi, hot, p, length, iz * rh / rj, io, f)
    _tree_shap_recurse(t, x, phi, cold, p, length, iz * rc / rj, 0.0, f)


def _tree_arrays(tree) -> dict:
    n = tree.n_nodes
    value = np.where(tree.left_children == -1, tree.split_conditions, 0.0).astype(np.float64)
    cover = tree.sum_hessian.astype(np.float64)
    cover = np.maximum(cover, 1e-16)
    st = tree.split_type if tree.split_type is not None else np.zeros(n, np.int32)
    cats = {nid: frozenset(int(c) for c in arr)
            for nid, arr in (tree.categories or {}).items()}
    return dict(
        left=tree.left_children, right=tree.right_children,
        feat=tree.split_indices, thr=tree.split_conditions.astype(np.float64),
        dleft=tree.default_left, value=value, cover=cover,
        is_cat=(st == 1), cats=cats,
    )


def _go_left(t, node: int, xv: float) -> bool:
    """Split decision incl. categorical routing (common/categorical.h:
    in right-set -> right; out-of-range -> left; missing -> default)."""
    if np.isnan(xv):
        return bool(t["dleft"][node])
    if t["is_cat"][node]:
        cats = t["cats"].get(int(node))
        c = int(xv)
        return not (cats is not None and c >= 0 and c in cats)
    return xv < t["thr"][node]


def _expected_value(t) -> float:
    """Cover-weighted expectation of the tree output (phi_0 component)."""
    def rec(node: int) -> float:
        if t["left"][node] < 0:
            return t["value"][node]
        l, r = t["left"][node], t["right"][node]
        cl, cr = t["cover"][l], t["cover"][r]
        tot = max(cl + cr, 1e-16)
        return (cl * rec(l) + cr * rec(r)) / tot

    return rec(0)


def shap_values_tree(tree, X: np.ndarray) -> np.ndarray:
    """(R, F+1) exact TreeSHAP values for one tree (last col = bias).

    Numeric scalar-leaf trees dispatch to the row-parallel native kernel
    (native/xtb_kernels.h xtb_shap_values_impl — same f64 recursion in the
    same operation order, threaded across rows with bitwise-identical
    output for every nthread); categorical trees and lib-less installs walk
    the Python recursion below."""
    R, F = X.shape
    t = _tree_arrays(tree)
    ev = _expected_value(t)
    maxd = tree.max_depth + 2
    if not t["is_cat"].any():
        from ..utils import native

        out = native.shap_values_native(t, X, maxd)
        if out is not None:
            out[:, F] = ev
            return out
    out = np.zeros((R, F + 1), np.float64)
    for r in range(R):
        phi = np.zeros(F + 1, np.float64)
        _tree_shap_recurse(t, X[r], phi, 0, _Path(maxd + 1), 0, 1.0, 1.0, -1)
        phi[F] = ev
        out[r] = phi
    return out


def saabas_values_tree(tree, X: np.ndarray, eta_scale: np.ndarray = None) -> np.ndarray:
    """Approximate contributions (Saabas): per-split value deltas along the
    decision path (reference: ApproximateFeatureContributions, shap.cc)."""
    R, F = X.shape
    t = _tree_arrays(tree)
    # internal node values: cover-weighted expectation below each node
    n = len(t["left"])
    nodeval = np.zeros(n, np.float64)

    def fill(node: int) -> float:
        if t["left"][node] < 0:
            nodeval[node] = t["value"][node]
            return nodeval[node]
        l, r = t["left"][node], t["right"][node]
        vl, vr = fill(l), fill(r)
        cl, cr = t["cover"][l], t["cover"][r]
        nodeval[node] = (cl * vl + cr * vr) / max(cl + cr, 1e-16)
        return nodeval[node]

    fill(0)
    out = np.zeros((R, F + 1), np.float64)
    for r in range(R):
        node = 0
        out[r, F] += nodeval[0]
        while t["left"][node] >= 0:
            f = t["feat"][node]
            xv = X[r, f]
            go_left = _go_left(t, node, xv)
            nxt = t["left"][node] if go_left else t["right"][node]
            out[r, f] += nodeval[nxt] - nodeval[node]
            node = nxt
    return out


def predict_contribs(booster, data, tree_slice: slice, approx: bool = False) -> np.ndarray:
    """(R, F+1) or (R, K, F+1) contributions summing to the margin
    (reference: Booster.predict(pred_contribs=True), core.py:2424).

    Exact SHAP runs on the batched device kernel (interpret/device.py, the
    role of shap.cu) whenever the ensemble qualifies; categorical trees and
    the Saabas approximation use the host walk (which needs f64; the device
    path slices f32 chunks itself, so no full f64 copy is made for it)."""
    X = booster._host_dense_recoded(data)
    R, F = X.shape
    K = booster.n_groups
    out = np.zeros((R, K, F + 1), np.float64)
    trees = booster.trees[tree_slice]
    info = booster.tree_info[tree_slice]
    wts = (booster.tree_weights[tree_slice]
           if getattr(booster, "tree_weights", None) else [1.0] * len(trees))
    if not approx:
        from .device import device_shap_supported, shap_values_device

        if trees and device_shap_supported(trees):
            for grp in range(K):
                g_trees = [t for t, g in zip(trees, info) if g == grp]
                g_wts = [w for w, g in zip(wts, info) if g == grp]
                if g_trees:
                    out[:, grp, :] += shap_values_device(g_trees, g_wts, X)
            base = np.asarray(booster.base_score).reshape(-1)
            out[:, :, F] += base[None, :K]
            return out[:, 0, :] if K == 1 else out
    X = X.astype(np.float64)  # the host walk accumulates in f64
    fn = saabas_values_tree if approx else shap_values_tree
    for tree, grp, w in zip(trees, info, wts):
        out[:, grp, :] += w * fn(tree, X)  # DART weight_drop scaling
    base = np.asarray(booster.base_score).reshape(-1)
    out[:, :, F] += base[None, :K]
    return out[:, 0, :] if K == 1 else out


def _leaf_paths_host(tree):
    """Per-leaf path tables with NODE IDS kept, so the per-row decision can
    go through the categorical-aware _go_left (unlike the device tables,
    which inline numeric thresholds only)."""
    t = _tree_arrays(tree)
    cover = np.maximum(t["cover"].astype(np.float64), 1e-16)
    out = []

    def rec(node, nodes):
        if t["left"][node] < 0:
            slots = {}
            z = []
            entries = []  # (node_id, went_left, slot)
            for nid, go_left in nodes:
                f = int(t["feat"][nid])
                child = t["left"][nid] if go_left else t["right"][nid]
                frac = cover[child] / cover[nid]
                if f not in slots:
                    slots[f] = len(z)
                    z.append(frac)
                else:
                    z[slots[f]] *= frac
                entries.append((nid, go_left, slots[f]))
            out.append(dict(entries=entries, z=np.asarray(z),
                            slot_feat=np.asarray(
                                sorted(slots, key=slots.get), np.int64),
                            v=float(t["value"][node])))
            return
        rec(int(t["left"][node]), nodes + [(node, True)])
        rec(int(t["right"][node]), nodes + [(node, False)])

    if t["left"][0] >= 0:
        rec(0, [])
    return t, out


def shap_interactions_tree(tree, X: np.ndarray) -> np.ndarray:
    """(R, F+1, F+1) interaction values — per-path pair formula verified
    cell-exact against the reference oracle (quadrature formulation,
    src/predictor/interpretability/shap.cc ExtractQuadratureInteractionDelta):

        phi_ij += v/2 * (o_i - z_i)(o_j - z_j)
                  * sum_k k!(m-2-k)!/(m-1)! * e_k^{(-i,-j)}

    per ordered slot pair (i, j) of each leaf path (no symmetric add — the
    ordered loop covers both orientations); diagonals are the SHAP values
    minus the off-diagonal row sums; the bias row/column stay empty except
    [F, F] (the reference's convention).  This python-loop version is the
    cat-aware oracle for the batched device kernel
    (interpret/device.py shap_interactions_device)."""
    import math as _math

    R, F = X.shape
    t, paths = _leaf_paths_host(tree)
    out = np.zeros((R, F + 1, F + 1), np.float64)
    base = shap_values_tree(tree, X)
    for r in range(R):
        x = X[r]
        for p in paths:
            m = len(p["z"])
            if m < 2:
                continue
            o = np.ones(m)
            for nid, went_left, slot in p["entries"]:
                if _go_left(t, nid, x[t["feat"][nid]]) != went_left:
                    o[slot] = 0.0
            z = p["z"]
            sf = p["slot_feat"]
            omz = o - z
            for i in range(m):
                for j in range(i + 1, m):
                    # elementary-symmetric coeffs excluding slots i, j
                    c = [1.0] + [0.0] * (m - 2)
                    for e in range(m):
                        if e in (i, j):
                            continue
                        c = [c[k] * z[e] + (c[k - 1] * o[e] if k else 0.0)
                             for k in range(m - 1)]
                    W = sum(_math.factorial(k) * _math.factorial(m - 2 - k)
                            / _math.factorial(m - 1) * c[k]
                            for k in range(m - 1))
                    term = 0.5 * p["v"] * omz[i] * omz[j] * W
                    out[r, sf[i], sf[j]] += term
                    out[r, sf[j], sf[i]] += term
    # main effects on the diagonal: phi_i - sum_j!=i interactions
    for r in range(R):
        for f in range(F + 1):
            out[r, f, f] = base[r, f] - (out[r, f, :].sum() - out[r, f, f])
    return out


def predict_interactions(booster, data, tree_slice: slice,
                         use_device=None) -> np.ndarray:
    X = booster._host_dense_recoded(data)
    R, F = X.shape
    K = booster.n_groups
    out = np.zeros((R, K, F + 1, F + 1), np.float64)
    wts = (booster.tree_weights[tree_slice]
           if getattr(booster, "tree_weights", None) else None)
    trees = booster.trees[tree_slice]
    infos = booster.tree_info[tree_slice]
    ws = [wts[i] if wts else 1.0 for i in range(len(trees))]

    from .device import device_shap_supported, shap_interactions_device

    # batched device kernel for non-categorical scalar ensembles at size
    # (the python recursion is the oracle; reference: shap.cu interactions)
    if use_device is None:
        use_device = device_shap_supported(trees) and R >= 128
    if use_device and device_shap_supported(trees):
        for grp in range(K):
            tg = [t for t, g in zip(trees, infos) if g == grp]
            wg = [w for w, g in zip(ws, infos) if g == grp]
            if tg:
                out[:, grp] += shap_interactions_device(tg, wg, X)
    else:
        X = X.astype(np.float64)  # the host walkers run in f64
        for tree, grp, w in zip(trees, infos, ws):
            out[:, grp] += w * shap_interactions_tree(tree, X)
    base = np.asarray(booster.base_score).reshape(-1)
    out[:, :, F, F] += base[None, :K]
    return out[:, 0] if K == 1 else out
