"""Batched device TreeSHAP.

TPU-native equivalent of the reference's warp-parallel GPU TreeSHAP
(src/predictor/interpretability/shap.cu:439-908, the GPUTreeShap design):
every root->leaf path is extracted once on host into fixed-shape tables,
then a jitted kernel evaluates ALL (row, path) pairs at once.

Math (Lundberg 2018, path-dependent): a leaf L reached through unique
features 1..m, each with "zero fraction" z_i (product of cover ratios) and
row-dependent "one fraction" o_i in {0,1}, contributes

    phi_i += v_L * (o_i - z_i) * sum_k  e_k(i) * k! (m-1-k)! / m!

where e_k(i) are elementary-symmetric coefficients of prod_{j!=i}(z_j+o_j t).
Paths are bucketed by m so every kernel has static shapes; inside a bucket
the per-element polynomial is rebuilt by excluding element i (O(m^2) per
element — numerically safer than the divide-out in shap.cu, and m <= depth
so the unrolled loops stay tiny).  Rows and paths batch into one big
elementwise program; the final feature scatter is a dupe-accumulating
`.at[].add`.

Categorical trees fall back to the host implementation (interpret/__init__).
"""
from __future__ import annotations

import functools
import math
from typing import Dict, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp


def _leaf_paths(tree) -> List[dict]:
    """Walk root->leaf; per leaf return node-level arrays + unique-slot map."""
    t_left = tree.left_children
    t_right = tree.right_children
    t_feat = tree.split_indices
    t_thr = tree.split_conditions
    t_dleft = tree.default_left
    cover = np.maximum(tree.sum_hessian.astype(np.float64), 1e-16)
    leaf_val = np.where(t_left == -1, tree.split_conditions, 0.0)

    out: List[dict] = []

    def rec(node: int, nodes: list):
        if t_left[node] == -1:
            # condense duplicate features into unique slots
            slots: Dict[int, int] = {}
            z_mult: List[float] = []
            node_feat, node_thr, node_dleft, node_dir, node_slot = [], [], [], [], []
            for (nid, go_left) in nodes:
                f = int(t_feat[nid])
                child = t_left[nid] if go_left else t_right[nid]
                frac = cover[child] / cover[nid]
                if f not in slots:
                    slots[f] = len(z_mult)
                    z_mult.append(frac)
                else:
                    z_mult[slots[f]] *= frac
                node_feat.append(f)
                node_thr.append(float(t_thr[nid]))
                node_dleft.append(bool(t_dleft[nid]))
                node_dir.append(bool(go_left))
                node_slot.append(slots[f])
            out.append(dict(
                node_feat=np.asarray(node_feat, np.int32),
                node_thr=np.asarray(node_thr, np.float32),
                node_dleft=np.asarray(node_dleft, bool),
                node_dir=np.asarray(node_dir, bool),
                node_slot=np.asarray(node_slot, np.int32),
                z=np.asarray(z_mult, np.float64),
                slot_feat=np.asarray(
                    sorted(slots, key=slots.get), np.int32),
                v=float(leaf_val[node]),
            ))
            return
        rec(int(t_left[node]), nodes + [(node, True)])
        rec(int(t_right[node]), nodes + [(node, False)])

    if t_left[0] == -1:  # stump: all mass at the bias
        return []
    rec(0, [])
    return out


def _bucket_paths(paths: List[dict], tree_weight: float):
    """Group per-leaf paths by unique length m -> stacked fixed-shape arrays."""
    buckets: Dict[Tuple[int, int], List[dict]] = {}
    for p in paths:
        m = len(p["z"])
        D = len(p["node_feat"])
        buckets.setdefault((m, D), []).append(p)
    out = {}
    for (m, D), plist in buckets.items():
        # every path in a bucket has exactly D nodes and m unique slots, so
        # the stacks need no padding or validity masks
        out[(m, D)] = dict(
            node_feat=np.stack([p["node_feat"] for p in plist]),
            node_thr=np.stack([p["node_thr"] for p in plist]),
            node_dleft=np.stack([p["node_dleft"] for p in plist]),
            node_dir=np.stack([p["node_dir"] for p in plist]),
            node_slot=np.stack([p["node_slot"] for p in plist]),
            z=np.stack([p["z"] for p in plist]).astype(np.float32),
            slot_feat=np.stack([p["slot_feat"] for p in plist]),
            v=np.asarray([p["v"] * tree_weight for p in plist], np.float32),
        )
    return out


@functools.partial(jax.jit, static_argnames=("m", "n_feat"))
def _bucket_phi(X, node_feat, node_thr, node_dleft, node_dir,
                node_slot, z, slot_feat, v, wk, *, m: int, n_feat: int):
    """(R, F+1) SHAP contribution of one bucket of paths.

    X (R, F); path tables (P, D)/(P, m); wk (m,) = k!(m-1-k)!/m!.
    """
    R = X.shape[0]
    P, D = node_feat.shape

    xv = X[:, node_feat.reshape(-1)].reshape(R, P, D)
    gol = jnp.where(jnp.isnan(xv), node_dleft[None], xv < node_thr[None])
    ok = gol == node_dir[None]  # (R,P,D)

    # one fraction per unique slot: AND of its nodes' decisions
    bad = jnp.zeros((R, P, m), bool)
    pidx = jnp.arange(P)[None, :, None]
    ridx = jnp.arange(R)[:, None, None]
    bad = bad.at[ridx, pidx, node_slot[None]].max(~ok)
    o = (~bad).astype(jnp.float32)  # (R, P, m)

    zf = z[None]  # (1, P, m)
    phis = []
    for i in range(m):
        # poly of the other elements: c[k] coefficients, built in f32
        c = [jnp.ones((R, P))] + [jnp.zeros((R, P))] * (m - 1)
        for j in range(m):
            if j == i:
                continue
            zj = zf[..., j]
            oj = o[..., j]
            nc = []
            for k in range(m):
                term = c[k] * zj
                if k > 0:
                    term = term + c[k - 1] * oj
                nc.append(term)
            c = nc
        W = sum(wk[k] * c[k] for k in range(m))  # (R, P)
        phis.append((o[..., i] - zf[..., i]) * v[None] * W)
    phi_elems = jnp.stack(phis, axis=-1)  # (R, P, m)

    out = jnp.zeros((R, n_feat + 1), jnp.float32)
    flat_feat = slot_feat.reshape(-1)  # (P*m,)
    out = out.at[:, flat_feat].add(phi_elems.reshape(R, P * m))
    return out


def shap_values_device(trees, tree_weights, X: np.ndarray,
                       budget_elems: int = 1 << 24) -> np.ndarray:
    """(R, F+1) summed exact SHAP values of scalar, non-categorical trees.

    Host extracts path tables once per ensemble; rows stream in chunks sized
    so R_chunk x paths x depth stays near ``budget_elems`` regardless of
    ensemble size, and the tail chunk is padded to the same static shape (one
    compiled program per bucket).
    """
    from . import _expected_value, _tree_arrays

    R, F = X.shape
    out = np.zeros((R, F + 1), np.float64)

    # merge buckets across trees (same (m, D) shapes share one kernel call)
    merged: Dict[Tuple[int, int], List[dict]] = {}
    for tree, w in zip(trees, tree_weights):
        out[:, F] += w * _expected_value(_tree_arrays(tree))
        for key, b in _bucket_paths(_leaf_paths(tree), w).items():
            merged.setdefault(key, []).append(b)

    for (m, D), parts in sorted(merged.items()):
        b = {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}
        P = b["v"].shape[0]
        wk = np.asarray(
            [math.factorial(k) * math.factorial(m - 1 - k) / math.factorial(m)
             for k in range(m)], np.float32)
        args = tuple(jnp.asarray(b[k]) for k in
                     ("node_feat", "node_thr", "node_dleft", "node_dir",
                      "node_slot", "z", "slot_feat", "v"))
        row_chunk = int(min(R, max(256, budget_elems // max(P * D, 1))))
        for lo in range(0, R, row_chunk):
            hi = min(lo + row_chunk, R)
            chunk = X[lo:hi]
            if hi - lo < row_chunk:  # pad tail to the static chunk shape
                chunk = np.pad(chunk, ((0, row_chunk - (hi - lo)), (0, 0)),
                               constant_values=np.nan)
            contrib = _bucket_phi(jnp.asarray(chunk, jnp.float32), *args,
                                  jnp.asarray(wk), m=m, n_feat=F)
            out[lo:hi] += np.asarray(contrib, np.float64)[: hi - lo]
    return out


def device_shap_supported(trees) -> bool:
    """Device path covers scalar-leaf, non-categorical ensembles."""
    return all(not t.has_categorical and t.leaf_vector is None for t in trees)


@functools.partial(jax.jit, static_argnames=("m", "n_feat"))
def _bucket_interactions(X, node_feat, node_thr, node_dleft, node_dir,
                         node_slot, z, slot_feat, v, wk1, *, m: int,
                         n_feat: int):
    """(R, F+1, F+1) off-diagonal interaction contributions of one bucket.

    The pairwise form of the conditional trick (Lundberg 2018 §4;
    reference: PredictInteractionContributions -> this repo's host
    shap_interactions_tree): only paths CONTAINING the conditioning
    feature contribute to (shap|on - shap|off), and per path the
    contribution for the ordered slot pair (s, j) is

        term(s, j) = v/2 * (o_s - z_s) * (o_j - z_j)
                     * sum_k wk_{m-1}[k] * c_k^{(-s, -j)}

    with c^{(-s,-j)} the elementary-symmetric coefficients excluding both
    slots.  term is symmetric in (s, j), so each unordered pair is built
    once and scattered into both [f_s, f_j] and [f_j, f_s]; the bias
    row/column stay empty except the diagonal (the reference's
    convention, verified cell-exact against the oracle).

    wk1: (m-1,) = k!(m-2-k)!/(m-1)! — the m-1-element Shapley weights.
    """
    R = X.shape[0]
    P, D = node_feat.shape

    xv = X[:, node_feat.reshape(-1)].reshape(R, P, D)
    gol = jnp.where(jnp.isnan(xv), node_dleft[None], xv < node_thr[None])
    ok = gol == node_dir[None]
    bad = jnp.zeros((R, P, m), bool)
    pidx = jnp.arange(P)[None, :, None]
    ridx = jnp.arange(R)[:, None, None]
    bad = bad.at[ridx, pidx, node_slot[None]].max(~ok)
    o = (~bad).astype(jnp.float32)  # (R, P, m)
    zf = jnp.broadcast_to(z[None], o.shape)  # (R, P, m)
    omz = o - zf

    out = jnp.zeros((R, n_feat + 1, n_feat + 1), jnp.float32)
    for s in range(m):
        for j in range(s + 1, m):
            # poly over the other m-2 elements, f32, unrolled
            c = [jnp.ones((R, P))] + [jnp.zeros((R, P))] * max(m - 2, 0)
            for e in range(m):
                if e == s or e == j:
                    continue
                ze = zf[..., e]
                oe = o[..., e]
                nc = []
                for k in range(m - 1):
                    term = c[k] * ze
                    if k > 0:
                        term = term + c[k - 1] * oe
                    nc.append(term)
                c = nc
            W = sum(wk1[k] * c[k] for k in range(m - 1))
            term = 0.5 * v[None] * omz[..., s] * omz[..., j] * W
            # one build per unordered pair (term is s<->j symmetric);
            # scatter covers both orientations
            out = out.at[:, slot_feat[:, s], slot_feat[:, j]].add(term)
            out = out.at[:, slot_feat[:, j], slot_feat[:, s]].add(term)
    return out


def shap_interactions_device(trees, tree_weights, X: np.ndarray,
                             budget_elems: int = 1 << 22) -> np.ndarray:
    """(R, F+1, F+1) summed exact SHAP interactions of scalar,
    non-categorical trees — the batched-device analogue of the reference's
    GPU PredictInteractionContributions (shap.cu interactions path).

    Off-diagonals come from the pairwise kernel; diagonals are fixed up
    with the device SHAP values: phi_ff = phi_f - sum_{j != f} phi_fj.
    """
    R, F = X.shape
    out = np.zeros((R, F + 1, F + 1), np.float64)

    merged: Dict[Tuple[int, int], List[dict]] = {}
    for tree, w in zip(trees, tree_weights):
        for key, b in _bucket_paths(_leaf_paths(tree), w).items():
            merged.setdefault(key, []).append(b)

    for (m, D), parts in sorted(merged.items()):
        if m < 2:
            continue  # single-feature paths have no pairs
        b = {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}
        P = b["v"].shape[0]
        m1 = m - 1
        wk1 = np.asarray(
            [math.factorial(k) * math.factorial(m1 - 1 - k)
             / math.factorial(m1) for k in range(m1)], np.float32)
        args = tuple(jnp.asarray(b[k]) for k in
                     ("node_feat", "node_thr", "node_dleft", "node_dir",
                      "node_slot", "z", "slot_feat", "v"))
        # m^2 pair terms per element: budget accordingly
        row_chunk = int(min(R, max(64, budget_elems // max(P * m * m, 1))))
        for lo in range(0, R, row_chunk):
            hi = min(lo + row_chunk, R)
            chunk = X[lo:hi]
            if hi - lo < row_chunk:
                chunk = np.pad(chunk, ((0, row_chunk - (hi - lo)), (0, 0)),
                               constant_values=np.nan)
            contrib = _bucket_interactions(
                jnp.asarray(chunk, jnp.float32), *args, jnp.asarray(wk1),
                m=m, n_feat=F)
            out[lo:hi] += np.asarray(contrib, np.float64)[: hi - lo]

    # diagonal: phi_f minus the off-diagonal row sum (host convention)
    phi = shap_values_device(trees, tree_weights, X)
    for f in range(F + 1):
        row_sum = out[:, f, :].sum(axis=1) - out[:, f, f]
        out[:, f, f] = phi[:, f] - row_sum
    return out
