"""Elastic training: survive worker loss at reduced world size and absorb
replacements at round boundaries.

The reliability package (PR 3) survives worker death by aborting the
survivors and *relaunching at the same world size* from the last
checkpoint.  This module goes to the spot-instance/preemption reality the
roadmap calls for: when a worker dies, the survivors **regroup** — the
tracker re-forms the relay group at world N-1, the dead rank's data shards
are re-assigned through the :class:`ShardMap`, and training resumes from
the last completed round without any process restart.  Symmetrically, a
late-joining worker is absorbed at the next round boundary with the shard
map rebalanced back up.

Three pieces live here (the protocol itself spans layers):

- :class:`ShardMap` — the deterministic shard→rank assignment that travels
  inside ``CheckpointCallback`` checkpoints (XTBCKPT meta v2), so any
  worker — survivor or replacement — can derive exactly which data it owns
  at the current world size.
- :class:`ElasticConfig` — what ``train(..., elastic=...)`` needs: a
  ``data_fn(shard_map, rank, world)`` that (re)builds the local DMatrix
  from owned shards, and the checkpoint directory regroup recovery reloads
  from.
- :class:`RegroupRequired` — raised by a collective when group membership
  changed mid-operation; ``train()`` catches it at the round boundary,
  discards the partial round, and re-enters after the regroup.

Determinism contract (pinned by ``tests/test_elastic.py`` and
``scripts/elastic_smoke.py``): a rescaled run need not match an
uninterrupted one, but it must be **bitwise-reproducible given the same
fault plan** — the deterministic death schedules in
``reliability/faults.py`` fire at the same seam invocation every run, the
survivors reload the same checkpoint, the :class:`ShardMap` rebalance is a
pure function of ``(num_shards, world)``, and the relay's rank-ordered
host reduction keeps the shrunken world's histograms exactly ordered.

Telemetry: ``xtb_elastic_regroups_total``,
``xtb_elastic_lost_workers_total``, ``xtb_elastic_regroup_seconds``
(docs/observability.md).  docs/reliability.md § "Elastic training" is the
operator guide.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = ["RegroupRequired", "ShardMap", "ElasticConfig"]


class RegroupRequired(RuntimeError):
    """Group membership changed under an in-flight collective.

    Raised instead of a generic failure when the backend knows the job is
    regrouping (elastic mode) rather than dying: the training loop catches
    it at the round boundary, abandons the partial round, and re-enters
    through :func:`xgboost_tpu.collective.regroup`.
    """


@dataclasses.dataclass(frozen=True)
class ShardMap:
    """Deterministic assignment of ``num_shards`` data shards to ``world``
    ranks.

    The shard is the unit of data ownership and re-assignment: a worker
    owns the union of its shards, and a regroup moves *shards*, never row
    ranges, so ownership after any shrink/absorb sequence is a pure
    function of ``(num_shards, world)`` — the property the bitwise
    reproducibility contract needs.  ``assign[s]`` is the rank owning
    shard ``s`` (round-robin: ``s % world``).
    """

    num_shards: int
    world: int
    assign: Tuple[int, ...]

    @classmethod
    def create(cls, num_shards: int, world: int) -> "ShardMap":
        num_shards = int(num_shards)
        world = int(world)
        if num_shards < 1 or world < 1:
            raise ValueError(
                f"ShardMap needs num_shards >= 1 and world >= 1; got "
                f"{num_shards}, {world}")
        if num_shards < world:
            raise ValueError(
                f"num_shards ({num_shards}) must be >= world ({world}): "
                "a rank with no data cannot contribute to the quantile "
                "sketch or the histogram exchange")
        return cls(num_shards=num_shards, world=world,
                   assign=tuple(s % world for s in range(num_shards)))

    def shards_of(self, rank: int) -> Tuple[int, ...]:
        """The shards ``rank`` owns, in ascending shard order."""
        return tuple(s for s, r in enumerate(self.assign) if r == int(rank))

    def rebalance(self, world: int) -> "ShardMap":
        """The canonical map at a new world size (same shard universe)."""
        return ShardMap.create(self.num_shards, world)

    def to_dict(self) -> Dict[str, Any]:
        return {"num_shards": self.num_shards, "world": self.world,
                "assign": list(self.assign)}

    @classmethod
    def from_dict(cls, obj: Dict[str, Any]) -> "ShardMap":
        num_shards = int(obj["num_shards"])
        world = int(obj["world"])
        assign = obj.get("assign")
        if assign is None:
            return cls.create(num_shards, world)
        assign = tuple(int(r) for r in assign)
        if len(assign) != num_shards:
            raise ValueError(
                f"shard map assign length {len(assign)} != num_shards "
                f"{num_shards}")
        return cls(num_shards=num_shards, world=world, assign=assign)


class ElasticConfig:
    """Configuration for ``train(..., elastic=...)``.

    ``data_fn(shard_map, rank, world)`` builds this rank's training data
    from the shards it owns under ``shard_map`` — called at start and
    again after every regroup (the shards a rank owns change with the
    world size).  It returns a DMatrix, or ``(DMatrix, evals)`` to
    re-shard evaluation sets too.  Every shard must be loadable by *any*
    worker (shared storage or a recomputable source): a survivor inherits
    the dead rank's shards.

    ``checkpoint_dir`` is where regroup recovery reloads from; ``train``
    appends a :class:`~xgboost_tpu.reliability.CheckpointCallback` on this
    directory automatically unless the caller already passed one (the
    shard map travels inside those checkpoints).

    ``num_shards`` defaults to the world size at first start and is the
    run's fixed shard universe: the world can never grow PAST it (a rank
    with no shards has no data to train on), so set it to the largest
    world you intend to absorb to — 2×workers is a good default, and
    also gives the rebalance finer granularity.
    """

    def __init__(self, data_fn: Callable[..., Any], checkpoint_dir: str,
                 num_shards: Optional[int] = None,
                 checkpoint_interval: int = 1, keep_last: int = 3) -> None:
        if not callable(data_fn):
            raise TypeError("ElasticConfig.data_fn must be callable")
        self.data_fn = data_fn
        self.checkpoint_dir = str(checkpoint_dir)
        self.num_shards = int(num_shards) if num_shards is not None else None
        self.checkpoint_interval = max(int(checkpoint_interval), 1)
        self.keep_last = max(int(keep_last), 1)


_instruments = None  # (regroups counter, lost counter, seconds histogram)


def instruments():
    """Elastic telemetry family (lazy; docs/observability.md catalog)."""
    global _instruments
    if _instruments is None:
        from .telemetry.registry import get_registry

        reg = get_registry()
        _instruments = (
            reg.counter("xtb_elastic_regroups_total",
                        "elastic regroups, per process: epochs this worker "
                        "joined / epochs this tracker formed"),
            reg.counter("xtb_elastic_lost_workers_total",
                        "workers lost while training continued elastically"),
            reg.histogram("xtb_elastic_regroup_seconds",
                          "regroup latency: epoch formation (tracker) or "
                          "local recovery (worker)"),
        )
    return _instruments
