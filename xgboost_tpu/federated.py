"""Federated learning backend: gRPC central-relay collectives.

Reference: plugin/federated — ``FederatedComm`` (federated_comm.h:18) routes
every collective through a central secure server (``federated_tracker.h:22``
gRPC service, wire format federated.proto) so workers exchange ONLY aggregate
statistics (histograms, sketch grids), never rows.  This module provides the
same topology for the TPU framework: a ``FederatedTracker`` gRPC server that
gathers each round's contributions and fans the stacked result back, and a
``FederatedBackend`` (a ``collective.CollBackend``) selected with
``dmlc_communicator='federated'`` + ``federated_server_address`` /
``federated_world_size`` / ``federated_rank`` — the reference's exact
parameter names (plugin/federated/federated_comm.cc).

No .proto compilation: the single ``Exchange`` method moves opaque bytes
(grpc generic handlers with identity serializers), with pickled envelopes.
Training code is backend-agnostic — the same ProcessHistTreeGrower /
distributed-sketch paths run unchanged; only the transport differs, exactly
as the reference swaps RabitComm for FederatedComm under one Coll interface.
"""
from __future__ import annotations

import pickle
import threading
from concurrent import futures
from typing import Dict, List, Optional

import numpy as np

from .collective import CollBackend

_SERVICE = "xgboost_tpu.Federated"
_METHOD = f"/{_SERVICE}/Exchange"
_IDENT = lambda b: b  # noqa: E731 — raw-bytes (de)serializer


class _Round:
    """One collective round: world payloads in, stacked result out."""

    __slots__ = ("slots", "result", "served")

    def __init__(self) -> None:
        self.slots: Dict[int, bytes] = {}
        self.result: Optional[bytes] = None  # pickled ONCE per round
        self.served = 0


class FederatedTracker:
    """Central relay server (the federated_tracker.h role).

    Collectives are sequence-numbered on the client; workers issue them in
    identical order (the rabit contract), so round ``seq`` is complete when
    all ``world_size`` ranks have contributed.
    """

    def __init__(self, world_size: int, port: int = 0, *,
                 server_key: Optional[bytes] = None,
                 server_cert: Optional[bytes] = None,
                 client_ca: Optional[bytes] = None) -> None:
        import grpc

        self.world_size = world_size
        self._rounds: Dict[int, _Round] = {}
        self._cv = threading.Condition()
        handler = grpc.method_handlers_generic_handler(
            _SERVICE,
            {"Exchange": grpc.unary_unary_rpc_method_handler(
                self._exchange,
                request_deserializer=_IDENT, response_serializer=_IDENT)},
        )
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=world_size + 4))
        self._server.add_generic_rpc_handlers((handler,))
        if server_key is not None and server_cert is not None:
            # TLS (mutual when client_ca given) — the reference federated
            # plugin's secure mode (federated_tracker.h:22 reads
            # server-key/server-cert/client-cert paths the same way)
            creds = grpc.ssl_server_credentials(
                [(server_key, server_cert)],
                root_certificates=client_ca,
                require_client_auth=client_ca is not None)
            self.port = self._server.add_secure_port(
                f"127.0.0.1:{port}", creds)
        else:
            # plaintext: test/loopback use ONLY — aggregate statistics
            # (histograms, sketch grids) still cross the wire readable
            self.port = self._server.add_insecure_port(f"127.0.0.1:{port}")
        self._server.start()

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def _exchange(self, request: bytes, context) -> bytes:
        msg = pickle.loads(request)
        seq, rank = int(msg["seq"]), int(msg["rank"])
        with self._cv:
            rnd = self._rounds.setdefault(seq, _Round())
            rnd.slots[rank] = msg["payload"]
            if len(rnd.slots) == self.world_size:
                # serialize once; every rank gets the same bytes
                rnd.result = pickle.dumps(
                    [rnd.slots[r] for r in range(self.world_size)])
                rnd.slots.clear()
                self._cv.notify_all()
            else:
                self._cv.wait_for(lambda: rnd.result is not None,
                                  timeout=600.0)
            if rnd.result is None:  # pragma: no cover - timeout path
                raise RuntimeError(f"federated round {seq} timed out")
            out = rnd.result
            rnd.served += 1
            if rnd.served == self.world_size:
                del self._rounds[seq]  # round complete: free the payloads
        return out

    def shutdown(self) -> None:
        self._server.stop(grace=None)


class FederatedBackend(CollBackend):
    """Worker-side transport (the FederatedComm role): every primitive is an
    allgather relayed through the tracker; reductions happen locally on the
    gathered stack (identical on every worker -> deterministic trees)."""

    def __init__(self, server_address: str, world_size: int, rank: int,
                 server_cert_path: str = "", client_key_path: str = "",
                 client_cert_path: str = "") -> None:
        """TLS: pass the reference's parameter trio
        (federated_comm.cc: federated_server_cert_path /
        federated_client_key_path / federated_client_cert_path) to dial a
        secure tracker; with none given the channel is PLAINTEXT — fine for
        loopback tests, not for cross-site federation."""
        import grpc

        self._world = int(world_size)
        self._rank = int(rank)
        self._seq = 0
        if server_cert_path:
            def _read(p):
                with open(p, "rb") as fh:
                    return fh.read()

            creds = grpc.ssl_channel_credentials(
                root_certificates=_read(server_cert_path),
                private_key=_read(client_key_path) if client_key_path
                else None,
                certificate_chain=_read(client_cert_path) if client_cert_path
                else None)
            self._channel = grpc.secure_channel(server_address, creds)
        else:
            self._channel = grpc.insecure_channel(server_address)
        self._call = self._channel.unary_unary(
            _METHOD, request_serializer=_IDENT, response_deserializer=_IDENT)

    def rank(self) -> int:
        return self._rank

    def world_size(self) -> int:
        return self._world

    def allgather(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data)
        self._seq += 1
        req = pickle.dumps({"seq": self._seq, "rank": self._rank,
                            "payload": pickle.dumps(data)})
        result = pickle.loads(self._call(req, timeout=600.0))
        return np.stack([pickle.loads(p) for p in result])

    def shutdown(self) -> None:
        self._channel.close()
