"""Benchmark: synthetic HIGGS-shaped binary training on the real TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload mirrors BASELINE.md config #2 scaled to one chip + bench budget:
HIGGS-like dense f32 (28 features), binary:logistic, hist with max_bin=256,
depth 6.  Metric of record is training throughput in M row·rounds/s (train
loop only — DMatrix/sketch/bin time reported separately to stderr, matching
how gpu_hist timings are usually quoted).

vs_baseline compares against an H100 xgboost `gpu_hist` estimate for the same
workload: public gpu_hist results put HIGGS-class training at roughly
100-130 M row·rounds/s on top-end NVIDIA parts (BASELINE.md: the reference
repo itself publishes no absolute numbers); we use 110 M row·rounds/s.
vs_baseline > 1.0 means faster than that estimate.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

H100_BASELINE_ROW_ROUNDS_PER_S = 110e6

N_ROWS = int(os.environ.get("BENCH_ROWS", 2_000_000))
N_FEATURES = int(os.environ.get("BENCH_FEATURES", 28))
N_ROUNDS = int(os.environ.get("BENCH_ROUNDS", 40))
MAX_DEPTH = int(os.environ.get("BENCH_DEPTH", 6))
MAX_BIN = int(os.environ.get("BENCH_MAX_BIN", 256))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_data(n: int, f: int, seed: int = 0):
    """HIGGS-like: informative low-order interactions + noise features."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    logits = (
        1.5 * X[:, 0]
        + X[:, 1] * X[:, 2]
        - 0.8 * np.abs(X[:, 3])
        + 0.5 * X[:, 4]
        + 0.3 * rng.normal(size=n)
    )
    y = (logits > 0).astype(np.float32)
    return X, y


def _init_devices_with_watchdog(timeout_s: float = 120.0):
    """jax.devices() via the tunneled TPU can hang if the relay is wedged
    (claim leg never granted).  Probe it in a SUBPROCESS — a hung in-process
    probe thread would hold jax's backend lock and deadlock the fallback —
    then init for real only on a healthy tunnel."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices(); print(d[0].platform)"],
            capture_output=True, timeout=timeout_s, text=True,
        )
        healthy = r.returncode == 0
        if not healthy:
            log(f"device probe failed: {r.stderr.strip()[-200:]}")
    except subprocess.TimeoutExpired:
        healthy = False
        log(f"device probe did not return within {timeout_s}s "
            f"(TPU tunnel wedged?)")
    import jax

    if healthy:
        return jax.devices(), False
    log("falling back to CPU")
    jax.config.update("jax_platforms", "cpu")
    return jax.devices(), True


def main() -> None:
    global N_ROWS, N_ROUNDS

    if os.environ.get("BENCH_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
        devices, cpu_fallback = jax.devices(), True
    else:
        devices, cpu_fallback = _init_devices_with_watchdog()
    if cpu_fallback and "BENCH_ROWS" not in os.environ:
        N_ROWS, N_ROUNDS = 100_000, 5  # keep the fallback run short

    import jax

    import xgboost_tpu as xtb

    dev = devices[0]
    log(f"device: {dev} platform={dev.platform}")

    X, y = make_data(N_ROWS, N_FEATURES)
    t0 = time.perf_counter()
    dtrain = xtb.QuantileDMatrix(X, label=y, max_bin=MAX_BIN)
    t_data = time.perf_counter() - t0
    log(f"QuantileDMatrix build: {t_data:.2f}s ({N_ROWS} rows x {N_FEATURES} cols)")

    params = {
        "objective": "binary:logistic",
        "max_depth": MAX_DEPTH,
        "max_bin": MAX_BIN,
        "eta": 0.1,
        "device": "tpu",
    }

    # warmup: compile all level steps (cached across rounds)
    t0 = time.perf_counter()
    bst = xtb.train(params, dtrain, num_boost_round=2, verbose_eval=False)
    log(f"warmup (2 rounds + compile): {time.perf_counter() - t0:.2f}s")

    t0 = time.perf_counter()
    bst = xtb.train(params, dtrain, num_boost_round=N_ROUNDS, verbose_eval=False,
                    xgb_model=bst)
    train_s = time.perf_counter() - t0

    # sanity: the model must actually learn
    idx = np.random.default_rng(1).choice(N_ROWS, size=min(200_000, N_ROWS), replace=False)
    from xgboost_tpu.metric import auc as _auc

    preds = bst.predict(xtb.DMatrix(X[idx]))
    auc_v = _auc(preds, y[idx])
    log(f"train: {train_s:.2f}s for {N_ROUNDS} rounds; sample AUC={auc_v:.4f}")
    assert auc_v > 0.75, f"model failed to learn (AUC={auc_v})"

    throughput = N_ROWS * N_ROUNDS / train_s
    size = (f"{N_ROWS // 10**6}M" if N_ROWS >= 10**6 else f"{N_ROWS // 1000}k")
    tag = " [CPU FALLBACK: TPU tunnel unavailable]" if cpu_fallback else ""
    result = {
        "metric": f"synthetic-HIGGS {size}x{N_FEATURES} "
                  f"binary:logistic depth{MAX_DEPTH} train throughput{tag}",
        "value": round(throughput / 1e6, 3),
        "unit": "Mrow_rounds/s",
        "vs_baseline": round(throughput / H100_BASELINE_ROW_ROUNDS_PER_S, 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
