"""Benchmark: synthetic HIGGS-shaped binary training on the real TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload mirrors BASELINE.md config #2 scaled to one chip + bench budget:
HIGGS-like dense f32 (28 features), binary:logistic, hist with max_bin=256,
depth 6.  Metric of record is training throughput in M row·rounds/s (train
loop only — DMatrix/sketch/bin time reported separately to stderr, matching
how gpu_hist timings are usually quoted).

vs_baseline compares against an H100 xgboost `gpu_hist` estimate for the same
workload: public gpu_hist results put HIGGS-class training at roughly
100-130 M row·rounds/s on top-end NVIDIA parts (BASELINE.md: the reference
repo itself publishes no absolute numbers); we use 110 M row·rounds/s.
vs_baseline > 1.0 means faster than that estimate.

CPU-fallback caveat (the canary number when the TPU tunnel is wedged): on
CPU the round is bound by MATERIALIZING the (chunk, F*B) one-hot operand,
not by the matmul — measured ~0.8 GF/s on skinny root builds vs ~23 GF/s
on wide levels, flat in n_nodes.  That term is exactly what the Pallas
kernel fuses into VMEM on TPU, so the CPU number tracks regressions but
must not be read as a TPU performance proxy.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

H100_BASELINE_ROW_ROUNDS_PER_S = 110e6

# Tiers (VERDICT r3 #1ii): "micro" must produce a TPU number within ~2 min of
# healthy tunnel — small shapes, few rounds, phases trimmed — so a short heal
# window still yields hardware evidence.  "full" is the shape of record.
BENCH_TIER = os.environ.get("BENCH_TIER", "full").lower()
if BENCH_TIER not in ("micro", "full"):
    BENCH_TIER = "full"
_TIER_DEFAULTS = {
    "micro": dict(rows=50_000, rounds=3, depth=6),
    "full": dict(rows=2_000_000, rounds=40, depth=6),
}[BENCH_TIER]

N_ROWS = int(os.environ.get("BENCH_ROWS", _TIER_DEFAULTS["rows"]))
N_FEATURES = int(os.environ.get("BENCH_FEATURES", 28))
N_ROUNDS = int(os.environ.get("BENCH_ROUNDS", _TIER_DEFAULTS["rounds"]))
MAX_DEPTH = int(os.environ.get("BENCH_DEPTH", _TIER_DEFAULTS["depth"]))
MAX_BIN = int(os.environ.get("BENCH_MAX_BIN", 256))

# Persistent XLA compilation cache (VERDICT r3 #1i): a retry after a tunnel
# drop must not pay the ~40s train compile again.  Lives under /root (not
# /tmp — /tmp has been wiped twice across rounds).
CACHE_DIR = os.environ.get("JAX_COMPILATION_CACHE_DIR", "/root/jax_cache")


def enable_compile_cache() -> None:
    import jax

    os.makedirs(CACHE_DIR, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_data(n: int, f: int, seed: int = 0):
    """HIGGS-like: informative low-order interactions + noise features."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    logits = (
        1.5 * X[:, 0]
        + X[:, 1] * X[:, 2]
        - 0.8 * np.abs(X[:, 3])
        + 0.5 * X[:, 4]
        + 0.3 * rng.normal(size=n)
    )
    y = (logits > 0).astype(np.float32)
    return X, y


def _init_devices_with_watchdog(timeout_s: float = 120.0):
    """jax.devices() via the tunneled TPU can hang if the relay is wedged
    (claim leg never granted).  Probe it in a SUBPROCESS — a hung in-process
    probe thread would hold jax's backend lock and deadlock the fallback —
    then init for real only on a healthy tunnel."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices(); print(d[0].platform)"],
            capture_output=True, timeout=timeout_s, text=True,
        )
        healthy = r.returncode == 0
        if not healthy:
            log(f"device probe failed: {r.stderr.strip()[-200:]}")
    except subprocess.TimeoutExpired:
        healthy = False
        log(f"device probe did not return within {timeout_s}s "
            f"(TPU tunnel wedged?)")
    import jax

    if healthy:
        return jax.devices(), False
    log("falling back to CPU")
    jax.config.update("jax_platforms", "cpu")
    return jax.devices(), True


def _median_time(fn, reps: int = 5) -> float:
    """Median wall seconds of fn() with device completion; one warmup call
    first so compile time never lands in the samples.  (Shared: the
    scripts/ benches import this.)"""
    import jax

    jax.block_until_ready(fn())  # compile/warmup
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def _hist_flops_per_round(R: int, F: int, B: int, depth: int) -> float:
    """MXU FLOPs of one boosting round's histogram matmuls: each level's
    build is (F*B, R) @ (R, N*2) = 2*R*F*B*N*2 FLOPs; with the subtraction
    trick levels d>0 build only the 2^(d-1) left children."""
    total = 0.0
    for d in range(depth):
        n_build = 1 if d == 0 else 2 ** (d - 1)
        total += 2.0 * R * F * B * n_build * 2
    return total


def phase_bench(cpu_fallback: bool, train_s: float) -> dict:
    """Standalone per-phase timings at bench shapes + an MFU estimate
    (VERDICT r2 #1a/#1c): histogram (XLA + Pallas/Mosaic), split scan,
    position rewrite, H2D.  The Pallas timing doubles as the Mosaic
    lowering proof — interpret=False, so on TPU a compile failure here is
    loud, not hidden behind the interpret-mode tests."""
    import jax
    import jax.numpy as jnp

    from xgboost_tpu.ops.histogram import build_histogram
    from xgboost_tpu.ops.split import SplitParams, evaluate_splits

    R = min(N_ROWS, 1 << 21)
    F, B, depth = N_FEATURES, MAX_BIN, MAX_DEPTH
    N = 2 ** (depth - 1)  # widest built level (subtraction trick)
    rng = np.random.default_rng(0)
    bins_np = rng.integers(0, B, size=(R, F)).astype(np.uint8)
    gp_np = rng.normal(size=(R, 2)).astype(np.float32)
    pos_np = rng.integers((1 << (depth - 1)) - 1, (1 << depth) - 1,
                          size=R).astype(np.int32)
    phases = {}

    t0 = time.perf_counter()
    bins = jax.block_until_ready(jax.device_put(bins_np))
    phases["h2d_bins_s"] = time.perf_counter() - t0
    gp = jax.device_put(gp_np)
    pos = jax.device_put(pos_np)
    root_pos = jnp.zeros(R, jnp.int32)

    phases["hist_root_xla_s"] = _median_time(lambda: build_histogram(
        bins, gp, root_pos, node0=0, n_nodes=1, n_bin=B))
    # the widest level the train loop actually builds: with the subtraction
    # trick only the 2^(depth-2) LEFT children (stride 2) are computed
    n_build = max(N // 2, 1)
    node0 = (1 << (depth - 1)) - 1
    phases["hist_level_xla_s"] = _median_time(lambda: build_histogram(
        bins, gp, pos, node0=node0, n_nodes=n_build, n_bin=B, stride=2))

    if cpu_fallback:
        phases["pallas_mosaic_lowering"] = "skipped: CPU backend (Mosaic is TPU-only)"
    else:
        try:
            from xgboost_tpu.ops.hist_pallas import build_histogram_pallas

            phases["hist_level_pallas_s"] = _median_time(
                lambda: build_histogram_pallas(
                    bins, gp, pos, node0=node0, n_nodes=n_build, n_bin=B,
                    interpret=False, stride=2))
            phases["pallas_mosaic_lowering"] = "ok"
        except Exception as e:  # noqa: BLE001 — report, never kill the bench
            phases["pallas_mosaic_lowering"] = (
                f"FAILED: {type(e).__name__}: {e}"[:300])

    hist = build_histogram(bins, gp, pos, node0=node0, n_nodes=N, n_bin=B)
    totals = hist.sum(axis=(1,)).sum(axis=1) / F  # (N, 2) approximation
    params = SplitParams(eta=0.1, gamma=0.0, min_child_weight=1.0,
                         lambda_=1.0, alpha=0.0, max_delta_step=0.0)
    nb = jnp.full(F, B, jnp.int32)
    phases["split_eval_s"] = _median_time(
        lambda: evaluate_splits(hist, totals, nb, params))

    # position rewrite (RowPartitioner role): per-row gather of the split
    # feature's bin + elementwise route
    feat = jnp.zeros(2 * N, jnp.int32)
    sbin = jnp.full(2 * N, B // 2, jnp.int32)

    @jax.jit
    def _route(pos, bins):
        f = feat[jnp.clip(pos, 0, 2 * N - 1)]
        bv = jnp.take_along_axis(bins, f[:, None], axis=1)[:, 0].astype(jnp.int32)
        return jnp.where(bv <= sbin[jnp.clip(pos, 0, 2 * N - 1)],
                         2 * pos + 1, 2 * pos + 2)

    phases["pos_rewrite_s"] = _median_time(lambda: _route(pos, bins))

    # MFU of the measured train loop: hist matmul FLOPs over wall time.
    # Peak default: TPU v5e bf16 197 TFLOPS (the bench runs f32 on the MXU,
    # so this is a conservative denominator); override via BENCH_PEAK_FLOPS.
    peak = float(os.environ.get("BENCH_PEAK_FLOPS",
                                1e12 if cpu_fallback else 197e12))
    flops_round = _hist_flops_per_round(N_ROWS, F, B, depth)
    phases["hist_flops_per_round"] = flops_round
    if cpu_fallback:
        # the CPU backend runs the scatter-add hist: O(R*F) adds, not the
        # matmul's FLOPs — an MFU against matmul FLOPs would be fiction
        phases["mfu_vs_peak"] = ("n/a on CPU (scatter-add hist does "
                                 "O(R*F) adds, not matmul FLOPs)")
    else:
        phases["mfu_vs_peak"] = (flops_round * N_ROUNDS) / train_s / peak
    # roofline check from the standalone level timing
    phases["hist_level_tflops"] = (
        2.0 * R * F * B * n_build * 2 / phases["hist_level_xla_s"] / 1e12)
    return phases


def bench_extmem() -> dict:
    """Extmem streaming at non-toy page counts (VERDICT r3 #9): >= 20 zstd
    pages through the (mesh-shardable) streaming grower, prefetch overlap
    measured as the wall-clock gain of overlapped host decompress/H2D vs
    the serialized baseline (reference knob: n_prefetch_batches,
    sparse_page_source.h:293)."""
    import xgboost_tpu as xtb
    from xgboost_tpu.data.extmem import DataIter, ExtMemQuantileDMatrix

    rows_page = int(os.environ.get("BENCH_EXTMEM_PAGE_ROWS", "12800"))
    n_pages = int(os.environ.get("BENCH_EXTMEM_PAGES", "24"))
    F = N_FEATURES
    rng = np.random.default_rng(5)
    w = rng.normal(size=F).astype(np.float32)

    class Pages(DataIter):
        def __init__(self):
            super().__init__()
            self._i = 0

        def next(self, input_data):
            if self._i >= n_pages:
                return 0
            r = np.random.default_rng(100 + self._i)
            X = r.normal(size=(rows_page, F)).astype(np.float32)
            y = (X @ w + r.normal(scale=0.5, size=rows_page) > 0
                 ).astype(np.float32)
            input_data(data=X, label=y)
            self._i += 1
            return 1

        def reset(self):
            self._i = 0

    d = ExtMemQuantileDMatrix(Pages(), max_bin=MAX_BIN)
    out = {"pages": len(d._pages), "rows": rows_page * n_pages,
           "compressed_mb": round(sum(
               p.nbytes_compressed if hasattr(p, "nbytes_compressed")
               else p.nbytes for p in d._pages) / 2**20, 2)}
    base = {"objective": "binary:logistic", "max_depth": 6,
            "max_bin": MAX_BIN, "eta": 0.3}

    def one_round(prefetch: bool) -> float:
        p = {**base, "_extmem_prefetch": "1" if prefetch else "0"}
        xtb.train(p, d, 1, verbose_eval=False)  # warm the jit cache
        t0 = time.perf_counter()
        xtb.train(p, d, 1, verbose_eval=False)
        return time.perf_counter() - t0

    out["round_prefetch_s"] = round(one_round(True), 3)
    out["round_serial_s"] = round(one_round(False), 3)
    out["prefetch_overlap_gain"] = round(
        1.0 - out["round_prefetch_s"] / max(out["round_serial_s"], 1e-9), 4)
    return out


def main() -> None:
    global N_ROWS, N_ROUNDS

    if os.environ.get("BENCH_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
        devices, cpu_fallback = jax.devices(), True
    else:
        devices, cpu_fallback = _init_devices_with_watchdog()
    if cpu_fallback and "BENCH_ROWS" not in os.environ and BENCH_TIER == "full":
        # the CPU scatter-add hist (ops/histogram.py) trains ~65x faster
        # than the r1-r3 matmul fallback, so the fallback shape no longer
        # needs to shrink below the HIGGS ladder scale (r3 VERDICT weak #7)
        N_ROWS, N_ROUNDS = 1_000_000, 10

    import jax

    import xgboost_tpu as xtb

    # Persistent cache only on TPU: XLA:CPU AOT entries are keyed to the
    # compiling host's CPU features, and loading them on a different host
    # warns about (and can SIGILL on) mismatched machine types.
    if not cpu_fallback:
        enable_compile_cache()
    dev = devices[0]
    log(f"device: {dev} platform={dev.platform} tier={BENCH_TIER} "
        f"compile_cache={'off (cpu)' if cpu_fallback else CACHE_DIR}")
    # drop any stale phases file so a later copy can't publish old numbers
    # under a fresh run's name
    _phases_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "bench_phases.json")
    if os.path.exists(_phases_path):
        os.remove(_phases_path)

    X, y = make_data(N_ROWS, N_FEATURES)
    t0 = time.perf_counter()
    dtrain = xtb.QuantileDMatrix(X, label=y, max_bin=MAX_BIN)
    t_data = time.perf_counter() - t0
    log(f"QuantileDMatrix build: {t_data:.2f}s ({N_ROWS} rows x {N_FEATURES} cols)")

    params = {
        "objective": "binary:logistic",
        "max_depth": MAX_DEPTH,
        "max_bin": MAX_BIN,
        "eta": 0.1,
        "device": "tpu",
    }

    # warmup: compile all level steps (cached across rounds; the persistent
    # compilation cache makes this near-free on a retry after a tunnel drop)
    t0 = time.perf_counter()
    bst = xtb.train(params, dtrain, num_boost_round=2, verbose_eval=False)
    warmup_s = time.perf_counter() - t0
    log(f"warmup (2 rounds + compile): {warmup_s:.2f}s")

    t0 = time.perf_counter()
    bst = xtb.train(params, dtrain, num_boost_round=N_ROUNDS, verbose_eval=False,
                    xgb_model=bst)
    train_s = time.perf_counter() - t0

    # sanity: the model must actually learn
    idx = np.random.default_rng(1).choice(N_ROWS, size=min(200_000, N_ROWS), replace=False)
    from xgboost_tpu.metric import auc as _auc

    preds = bst.predict(xtb.DMatrix(X[idx]))
    auc_v = _auc(preds, y[idx])
    log(f"train: {train_s:.2f}s for {N_ROUNDS} rounds; sample AUC={auc_v:.4f}")
    assert auc_v > 0.75, f"model failed to learn (AUC={auc_v})"

    # micro tier defaults to skipping the standalone phase sweep — the point
    # is a fast end-to-end TPU number; phases come with the full tier.
    phases_default = "0" if BENCH_TIER == "micro" else "1"
    if os.environ.get("BENCH_PHASES", phases_default) != "0":
        try:
            phases = phase_bench(cpu_fallback, train_s)
            phases["warmup_compile_s"] = warmup_s
            # compile wall estimate: warmup minus its 2 steady-state rounds
            # (VERDICT r3 #4 line item; near-zero once the padded level
            # programs + persistent cache are warm)
            phases["compile_est_s"] = max(
                0.0, warmup_s - 2.0 * train_s / N_ROUNDS)
            log("per-phase timings + MFU: " + json.dumps(
                {k: (round(v, 6) if isinstance(v, float) else v)
                 for k, v in phases.items()}))
            with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "bench_phases.json"), "w") as fh:
                json.dump({"cpu_fallback": cpu_fallback, "rows": N_ROWS,
                           "features": N_FEATURES, "max_bin": MAX_BIN,
                           "depth": MAX_DEPTH, **phases}, fh, indent=1)
        except Exception as e:  # noqa: BLE001 — phases must not kill the bench
            log(f"phase bench failed: {type(e).__name__}: {e}")
        try:
            ext = bench_extmem()
            log("extmem streaming: " + json.dumps(ext))
            pth = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "bench_phases.json")
            blob = {}
            if os.path.exists(pth):
                with open(pth) as fh:
                    blob = json.load(fh)
            blob["extmem"] = ext
            with open(pth, "w") as fh:
                json.dump(blob, fh, indent=1)
        except Exception as e:  # noqa: BLE001
            log(f"extmem bench failed: {type(e).__name__}: {e}")

    throughput = N_ROWS * N_ROUNDS / train_s
    size = (f"{N_ROWS // 10**6}M" if N_ROWS >= 10**6 else f"{N_ROWS // 1000}k")
    tag = " [CPU FALLBACK: TPU tunnel unavailable]" if cpu_fallback else ""
    from xgboost_tpu.utils import native as _native

    result = {
        "metric": f"synthetic-HIGGS {size}x{N_FEATURES} "
                  f"binary:logistic depth{MAX_DEPTH} train throughput{tag}",
        "value": round(throughput / 1e6, 3),
        "unit": "Mrow_rounds/s",
        "vs_baseline": round(throughput / H100_BASELINE_ROW_ROUNDS_PER_S, 4),
        "platform": dev.platform,
        "tier": BENCH_TIER,
        "warmup_s": round(warmup_s, 2),
        "auc": round(float(auc_v), 4),
        # host-parallelism provenance (docs/native_threading.md): the native
        # kernel pool width this run used, and the cores it had to use
        "nthread": _native.get_nthread(),
        "cores": os.cpu_count(),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
