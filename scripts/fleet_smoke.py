#!/usr/bin/env python
"""Serving-fleet smoke for the nightly suite (docs/serving.md "Fleet").

One scenario, end to end against real replica processes:

1. Start a 3-replica fleet over two models with a warm-capable persistent
   compile cache — with tracing configured and the merged `/metrics`
   scrape endpoint up (docs/observability.md "Distributed observability
   plane").
2. Drive mixed two-model traffic from several client threads.
3. SIGKILL one replica mid-stream; scrape `/metrics` MID-RUN and assert
   the merged view carries both per-replica-labeled `xtb_serve_*` series
   and merged `xtb_fleet_*` series.
4. Assert EVERY request completes with the right bits (the dead replica's
   in-flight batch reroutes; nothing is dropped), the respawn brings the
   fleet back to strength, and the p99 over the whole disrupted stream is
   recorded (printed + exit-code-gated on completeness, not speed — this
   host is time-shared).
5. Observability postmortems: the SIGKILL'd replica's driver-side flight
   dump exists, and the merged chrome trace (driver file + per-replica
   sidecars) contains a dispatcher `fleet.request` and a replica
   `replica.execute` event sharing one request trace id across two pids.

Then the **sharded leg** (docs/serving.md "Sharded topology"): a 2-shard
4-replica fleet under tenant-spread traffic, SIGKILL one shard's replica
mid-stream — zero dropped, every answer bitwise, the respawn lands in
the victim's OWN shard (label prefix), and the sibling shard never
respawns.

Usage: JAX_PLATFORMS=cpu python scripts/fleet_smoke.py [n_replicas] [reqs]
"""
import os
import signal
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

N_CLIENTS = 6
BATCH = 256


def train_pair(workdir):
    import xgboost_tpu as xtb

    rng = np.random.default_rng(0)
    X = rng.normal(size=(4000, 12)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    d = xtb.DMatrix(X, label=y)
    paths = {}
    for name, rounds, depth in (("a", 8, 4), ("b", 5, 3)):
        bst = xtb.train({"objective": "binary:logistic", "max_depth": depth,
                         "seed": 1}, d, rounds, verbose_eval=False)
        paths[name] = os.path.join(workdir, f"{name}.json")
        bst.save_model(paths[name])
    return paths, X


def sharded_leg(paths, Xq, ref, workdir, per_client: int) -> list:
    """2-shard fleet, SIGKILL one shard's replica mid-stream: zero
    dropped + bitwise, respawn within the victim's own shard."""
    from xgboost_tpu.serving import ServingFleet
    from xgboost_tpu.serving.fleet import shard_of

    errors = []
    kill_at = threading.Event()
    done = [0]
    lock = threading.Lock()
    with ServingFleet(paths, n_replicas=4, n_shards=2,
                      cache_dir=os.path.join(workdir, "cache"),
                      warmup_buckets=(BATCH,), max_respawns=1) as fleet:
        sh0, sh1 = fleet._shards
        print(f"sharded leg: {fleet.alive_replicas()}/4 replicas across "
              f"{len(fleet._shards)} shards")

        def client(tid):
            tenant = f"smoke{tid}"
            try:
                for i in range(per_client):
                    model = "a" if (tid + i) % 2 == 0 else "b"
                    out = fleet.predict(model, Xq, tenant=tenant,
                                        timeout=600)
                    with lock:
                        done[0] += 1
                    if not np.array_equal(out, ref[model]):
                        errors.append(f"sharded client{tid} req{i}: "
                                      f"WRONG BITS for model {model}")
                    if tid == 0 and i == per_client // 4:
                        kill_at.set()
            except BaseException as e:
                errors.append(f"sharded client{tid}: {e!r}")

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(N_CLIENTS)]
        for t in threads:
            t.start()
        assert kill_at.wait(timeout=600), "traffic never reached kill point"
        with sh0._cv:
            victim = next(r for r in sh0._replicas.values() if r.alive)
        print(f"killing {victim.label} (pid {victim.proc.pid}) in shard 0 "
              f"mid-stream")
        victim.proc.send_signal(signal.SIGKILL)
        for t in threads:
            t.join(900)
        if any(t.is_alive() for t in threads):
            errors.append("sharded: clients never finished")
        deadline = time.monotonic() + 120
        while sh0.alive_replicas() < 2 and time.monotonic() < deadline:
            time.sleep(0.2)
        if sh0.alive_replicas() < 2:
            errors.append("sharded: shard 0 never respawned to strength")
        if sh1._respawned != 0:
            errors.append("sharded: the SIBLING shard respawned — the "
                          "death leaked across the shard boundary")
        with sh0._cv:
            respawns = [lab for lab in sh0._replicas if "respawn" in lab]
        if not respawns or not all(lab.startswith("s0:")
                                   for lab in respawns):
            errors.append(f"sharded: respawn labels {respawns} not owned "
                          f"by shard 0")
        # routing still pure-hash after the respawn
        for tid in range(N_CLIENTS):
            k = shard_of("a", f"smoke{tid}", 2)
            out = fleet.predict("a", Xq, tenant=f"smoke{tid}", timeout=600)
            if not np.array_equal(out, ref["a"]):
                errors.append(f"sharded post-respawn tenant smoke{tid} "
                              f"(shard {k}): WRONG BITS")
    total = N_CLIENTS * per_client
    if done[0] != total:
        errors.append(f"sharded: lost {total - done[0]} of {total} "
                      f"requests")
    if not errors:
        print(f"sharded leg OK: {done[0]}/{total} requests bitwise "
              f"through a shard-0 replica kill; respawn stayed in-shard")
    return errors


def main() -> int:
    n_replicas = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    per_client = (int(sys.argv[2]) if len(sys.argv) > 2 else 120) // N_CLIENTS

    from xgboost_tpu.serving import ServeConfig, ServingEngine, ServingFleet
    from xgboost_tpu.telemetry import distributed, trace

    workdir = tempfile.mkdtemp(prefix="xtb_fleet_smoke_")
    # observability smoke preamble: trace everything (configure exports
    # the env var, so replicas capture <path>.<pid> sidecars), ship fast,
    # and stand up the merged scrape endpoint
    trace_path = os.path.join(workdir, "fleet_trace.jsonl")
    os.environ[distributed.ENV_INTERVAL] = "0.2"
    trace.configure(trace_path)
    metrics_srv = distributed.start_metrics_server(port=0)
    paths, X = train_pair(workdir)
    Xq = X[:BATCH]

    # in-process reference bits: every fleet answer must match these
    eng = ServingEngine(ServeConfig(use_batcher=False))
    eng.add_model("a", paths["a"])
    eng.add_model("b", paths["b"])
    ref = {"a": eng.predict("a", Xq, direct=True),
           "b": eng.predict("b", Xq, direct=True)}
    eng.close()

    lats = []
    lats_lock = threading.Lock()
    errors = []
    kill_at = threading.Event()

    with ServingFleet(paths, n_replicas=n_replicas,
                      cache_dir=os.path.join(workdir, "cache"),
                      warmup_buckets=(BATCH,), max_respawns=1) as fleet:
        print(f"fleet up: {fleet.alive_replicas()}/{n_replicas} replicas, "
              f"coldstart info: {fleet.replica_info()[0]['cache_state']}")

        def client(tid):
            try:
                for i in range(per_client):
                    model = "a" if (tid + i) % 2 == 0 else "b"
                    t0 = time.perf_counter()
                    out = fleet.predict(model, Xq, timeout=600)
                    dt = time.perf_counter() - t0
                    with lats_lock:
                        lats.append(dt)
                    if not np.array_equal(out, ref[model]):
                        errors.append(f"client{tid} req{i}: WRONG BITS "
                                      f"for model {model}")
                    if tid == 0 and i == per_client // 4:
                        kill_at.set()  # a quarter in: release the killer
            except BaseException as e:
                errors.append(f"client{tid}: {e!r}")

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(N_CLIENTS)]
        for t in threads:
            t.start()
        assert kill_at.wait(timeout=600), "traffic never reached kill point"
        victim = next(r for r in fleet._replicas.values() if r.alive)
        victim_label = victim.label
        print(f"killing {victim.label} (pid {victim.proc.pid}) mid-stream")
        victim.proc.send_signal(signal.SIGKILL)
        # mid-run merged scrape: per-replica AND merged series in one GET.
        # Keep traffic flowing through the scrape window — shipping
        # piggybacks on frames, so a ship needs requests spanning the
        # interval (the client threads may already have drained)
        t_end = time.monotonic() + 1.5
        while time.monotonic() < t_end:
            fleet.predict("a", Xq, timeout=600)
            time.sleep(0.04)
        import urllib.request

        body = urllib.request.urlopen(
            f"http://127.0.0.1:{metrics_srv.port}/metrics",
            timeout=30).read().decode()
        if 'xtb_fleet_requests_total{proc="driver",model="a"}' not in body:
            errors.append("scrape: driver-side xtb_fleet_* series missing")
        if not [ln for ln in body.splitlines()
                if ln.startswith('xtb_serve_requests_total{proc="replica')]:
            errors.append("scrape: per-replica xtb_serve_* series missing")
        merged_fleet = [ln for ln in body.splitlines()
                        if ln.startswith('xtb_fleet_requests_total{model=')]
        if not merged_fleet:
            errors.append("scrape: merged xtb_fleet_* series missing")
        else:
            print(f"mid-run scrape OK: {len(body.splitlines())} lines, "
                  f"merged {merged_fleet[0]}")
        for t in threads:
            t.join(900)
        alive = [t for t in threads if t.is_alive()]
        if alive:
            errors.append(f"{len(alive)} clients never finished")

        deadline = time.monotonic() + 120
        while (fleet.alive_replicas() < n_replicas
               and time.monotonic() < deadline):
            time.sleep(0.2)
        respawned = fleet.alive_replicas()
        # the SIGKILL'd replica's postmortem, written driver-side from its
        # last shipped flight ring + final snapshot
        deadline = time.monotonic() + 30
        while (victim_label not in fleet.flight_dumps
               and time.monotonic() < deadline):
            time.sleep(0.1)
        flight_path = fleet.flight_dumps.get(victim_label)
        if not flight_path or not os.path.exists(flight_path):
            errors.append(f"no driver-side flight dump for SIGKILL'd "
                          f"{victim_label}")
        else:
            import json

            dump = json.load(open(flight_path))
            if dump.get("snapshot") is None:
                errors.append("flight dump missing the final snapshot")
            print(f"flight dump OK: {flight_path} "
                  f"({len(dump.get('events', []))} ring events)")

    # merged chrome trace: driver file + per-replica sidecars must pair a
    # dispatcher fleet.request with a replica.execute on ONE trace id
    import glob
    import json

    trace.flush()
    events = []
    for path in [trace_path] + sorted(glob.glob(trace_path + ".*")):
        with open(path) as fh:
            for line in fh:
                events.append(json.loads(line))  # every line must parse
    disp = {e["args"]["trace"]: e["pid"] for e in events
            if e["name"] == "fleet.request" and e.get("args", {}).get(
                "trace")}
    paired = [e for e in events if e["name"] == "replica.execute"
              and e.get("args", {}).get("trace") in disp
              and e["pid"] != disp[e["args"]["trace"]]]
    if not paired:
        errors.append("merged trace: no dispatcher+replica pair sharing a "
                      "request trace id")
    else:
        ex = paired[0]
        print(f"merged trace OK: {len(events)} events across "
              f"{len({e['pid'] for e in events})} pids; e.g. trace "
              f"{ex['args']['trace']} paired across pids "
              f"{disp[ex['args']['trace']]} and {ex['pid']}")
    trace.configure(None)
    distributed.stop_metrics_server()

    total = N_CLIENTS * per_client
    done = len(lats)
    p50, p99 = (np.percentile(lats, [50, 99]) if lats else (0.0, 0.0))
    print(f"fleet smoke: {done}/{total} requests completed through a "
          f"replica kill; p50={p50 * 1e3:.1f}ms p99={p99 * 1e3:.1f}ms; "
          f"fleet back at {respawned}/{n_replicas} replicas")
    errors.extend(sharded_leg(paths, Xq, ref, workdir,
                              max(4, per_client // 2)))
    if errors:
        print(f"FAIL: {errors[:5]}", file=sys.stderr)
        return 1
    if done != total:
        print(f"FAIL: lost {total - done} requests", file=sys.stderr)
        return 1
    if respawned < n_replicas:
        print("FAIL: respawn never restored fleet strength",
              file=sys.stderr)
        return 1
    import shutil

    shutil.rmtree(workdir, ignore_errors=True)
    print("fleet smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
