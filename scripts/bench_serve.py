"""Serving latency/throughput benchmark -> BENCH_SERVE.json.

Measures the ISSUE-1 acceptance numbers on the CPU backend: p50/p99
request latency and rows/s at batch sizes {1, 64, 4096} through the
ServingEngine's pre-compiled bucket path (direct mode isolates per-request
cost from batching delay), plus one concurrent section — 4 threads of
batch-1 traffic through the micro-batcher — whose engine metrics snapshot
(batch-size histogram, queue peak, compiles_steady) is persisted verbatim.
``compiles_steady`` MUST be 0 in the emitted artifact: a recompile in the
timed loop is a serving regression, and the suite's smoke test
(tests/test_serving.py) fails on the same gauge.

Fleet sections (ISSUE-8/ISSUE-9/ISSUE-15, docs/serving.md "Fleet" +
"Online model lifecycle", docs/reliability.md "Resource pressure &
graceful degradation"):

- ``fleet_coldstart`` — replica warm-work seconds against a cold vs a
  warm persistent compile cache (cold gets a FRESH cache dir every rep;
  warm reuses the dir the cold rep just populated — a within-run pair).
- ``fleet_saturation`` — sustained throughput + p99 under mixed
  two-model closed-loop traffic across the (n_replicas, n_shards)
  sweep in ``FLEET_CONFIGS``, all configs measured in this run (the
  1x1 row IS the baseline pair).  Client threads scale with the fleet
  and carry distinct tenants (the shard routing key); sharded rows
  record per-shard rows/s and rx-loop busy fraction.
- ``lifecycle_swap`` — p99 during a hot version swap vs the same run's
  steady state, with the requests in flight during each swap recorded.
- ``shed_vs_degrade`` — per-SLO-class completions/sheds and gold p99
  under the same synthetic overload, static queue-bound shedding vs
  governor brownout (low-SLO tenants refused at admission).

Host-noise convention (the ladder's): this host is time-shared, so walls
swing run to run; every timed section repeats ``BENCH_SERVE_REPS`` times
and reports the MINIMUM wall (min-of-N estimates the code's actual cost;
the mean estimates the host's load average), latency percentiles taken
from the min-wall rep.  The ``reps`` field records N.

Every section carries the host fingerprint (cores + arch + SIMD flag
set, the ladder's convention); ``--diff old.json new.json`` compares
two artifacts section by section and REFUSES (exit 2) any pair stamped
by different hosts.

Usage:  python scripts/bench_serve.py [out.json]   (default BENCH_SERVE.json)
        python scripts/bench_serve.py --diff old.json new.json
Knobs:  BENCH_SERVE_ROUNDS / _DEPTH / _FEATURES for model size,
        BENCH_SERVE_ITERS to scale the timed loops,
        BENCH_SERVE_REPS for min-of-N (default 3),
        BENCH_SERVE_FLEET=0 to skip the (multi-process, slower) fleet
        sections.
"""
from __future__ import annotations

import hashlib
import json
import os
import platform as _platform
import shutil
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

BATCH_SIZES = (1, 64, 4096)
ITERS = {1: 400, 64: 200, 4096: 30}
# (n_replicas, n_shards) sweep: the single-dispatcher column up to 4,
# then the sharded front-end past the dispatcher ceiling
FLEET_CONFIGS = ((1, 1), (2, 1), (4, 1), (4, 2), (8, 2), (8, 4), (12, 4))
FLEET_BATCH = 512       # rows per fleet request
FLEET_CLIENTS = 8       # closed-loop client threads (floor; scales with fleet)
FLEET_REQS_PER_CLIENT = 40

_HOST_FP = None


def _host_fingerprint() -> dict:
    """What makes a wall-clock number comparable: core count, arch, and
    the SIMD capability set (the ladder's convention).  Stamped on every
    section of BENCH_SERVE.json; --diff refuses (exit 2) when the ids
    differ — a cross-host wall ratio is not a regression signal, it is
    two different machines."""
    global _HOST_FP
    if _HOST_FP is None:
        from xgboost_tpu.utils import native as _native

        simd = _native.simd_info()
        info = dict(cores=os.cpu_count(), machine=_platform.machine(),
                    cpu_flags=sorted(simd.get("cpu_flags", [])),
                    lanes=simd.get("lanes"))
        blob = json.dumps(info, sort_keys=True).encode()
        info["id"] = hashlib.sha256(blob).hexdigest()[:12]
        _HOST_FP = info
    return _HOST_FP


def _stamp(section):
    """Attach the host fingerprint to a section dict (or to every row of
    a section list) so any later cross-file comparison can refuse
    cross-host pairs."""
    if isinstance(section, list):
        for row in section:
            _stamp(row)
    elif isinstance(section, dict):
        section["host"] = _host_fingerprint()
    return section


def _reps() -> int:
    return max(1, int(os.environ.get("BENCH_SERVE_REPS", "3")))


def train_model(rounds: int, depth: int, features: int,
                objective: str = "binary:logistic", num_class: int = 0):
    import xgboost_tpu as xtb

    rng = np.random.default_rng(0)
    X = rng.normal(size=(20_000, features)).astype(np.float32)
    margin = X[:, 0] + 0.5 * X[:, 1] - 0.25 * X[:, 2]
    params = {"objective": objective}
    if num_class:
        y = np.digitize(margin, np.linspace(-1.5, 1.5, num_class - 1)
                        ).astype(np.float32)
        params["num_class"] = num_class
    elif objective.startswith("reg:"):
        y = margin.astype(np.float32)
    else:
        y = (margin > 0).astype(np.float32)
    bst = xtb.train({**params, "max_depth": depth, "max_bin": 256},
                    xtb.DMatrix(X, label=y), rounds, verbose_eval=False)
    return bst, X


def bench_direct(eng, X, batch: int, iters: int) -> dict:
    """Per-request latency through the pre-compiled direct path
    (min-of-N walls; percentiles from the min-wall rep)."""
    rng = np.random.default_rng(batch)
    rows = [X[rng.integers(0, len(X) - batch + 1)
              or 0:][:batch] for _ in range(8)]
    for r in rows[:2]:  # shape warm-up (bucket already compiled by warmup())
        eng.predict("bench", r, direct=True)
    best_wall, best_lat = None, None
    for _ in range(_reps()):
        lat = np.empty(iters)
        t_all0 = time.perf_counter()
        for i in range(iters):
            t0 = time.perf_counter()
            eng.predict("bench", rows[i % len(rows)], direct=True)
            lat[i] = time.perf_counter() - t0
        wall = time.perf_counter() - t_all0
        if best_wall is None or wall < best_wall:
            best_wall, best_lat = wall, lat
    p50, p99 = np.percentile(best_lat, [50, 99])
    return {
        "batch": batch,
        "iters": iters,
        "reps": _reps(),
        "p50_ms": round(float(p50) * 1e3, 4),
        "p99_ms": round(float(p99) * 1e3, 4),
        "rows_per_s": round(batch * iters / best_wall, 1),
    }


def bench_concurrent(eng, X, n_threads: int = 4, per_thread: int = 100):
    """Batch-1 traffic from N threads through the micro-batcher: the
    coalescing path the engine exists for (min-of-N walls)."""
    errors = []

    def worker(tid, barrier):
        rng = np.random.default_rng(tid)
        try:
            barrier.wait(30)
            for _ in range(per_thread):
                eng.predict("bench", X[rng.integers(0, len(X))][None, :])
        except BaseException as e:  # pragma: no cover
            errors.append(repr(e))

    best_wall = None
    for _ in range(_reps()):
        barrier = threading.Barrier(n_threads)
        threads = [threading.Thread(target=worker, args=(t, barrier))
                   for t in range(n_threads)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
        wall = time.perf_counter() - t0
        best_wall = wall if best_wall is None else min(best_wall, wall)
    snap = eng.metrics_snapshot()
    return {
        "threads": n_threads,
        "requests": n_threads * per_thread,
        "reps": _reps(),
        "wall_s": round(best_wall, 3),
        "requests_per_s": round(n_threads * per_thread / best_wall, 1),
        "errors": errors,
        "engine_metrics": snap,
    }


# ---------------------------------------------------------------- fleet
def bench_fleet_coldstart(model_paths: dict, workdir: str) -> dict:
    """Replica warm-work seconds, cold vs warm persistent compile cache.

    The replica warms its DEFAULT bucket ladder (8..4096) for every
    model — the production configuration, where the AOT file covers
    every admission-policy bucket.  Within-run pairing: each rep starts
    a 1-replica fleet against a FRESH cache dir (cold: every program
    compiles) and then again against the dir that start just populated
    (warm: every program deserializes).  min-of-N on each side; the
    acceptance ratio compares the two minima.
    """
    from xgboost_tpu.serving import ServingFleet

    cold_s, warm_s = [], []
    info_cold = info_warm = None
    for rep in range(_reps()):
        cache = os.path.join(workdir, f"coldstart_cache_{rep}")
        for side, sink in (("cold", cold_s), ("warm", warm_s)):
            with ServingFleet(model_paths, n_replicas=1,
                              cache_dir=cache) as fleet:
                info = fleet.replica_info()[0]
            assert info["cache_state"] == side, (
                f"rep {rep}: expected a {side} cache, got "
                f"{info['cache_state']} (hits={info['aot_hits']} "
                f"compiled={info['aot_compiled']})")
            sink.append(float(info["warmup_s"]))
            if side == "cold":
                info_cold = info
            else:
                info_warm = info
    cold, warm = min(cold_s), min(warm_s)
    return {
        "reps": _reps(),
        "warmup_buckets": "default ladder (8..4096)",
        "models": len(model_paths),
        "programs": int(info_cold["aot_compiled"]),
        "cold_warmup_s": round(cold, 4),
        "warm_warmup_s": round(warm, 4),
        "speedup": round(cold / warm, 1),
        "pair_speedups": [round(c / w, 1) for c, w in zip(cold_s, warm_s)],
        "cold_info": {k: info_cold[k] for k in
                      ("aot_hits", "aot_compiled", "bringup_s")},
        "warm_info": {k: info_warm[k] for k in
                      ("aot_hits", "aot_compiled", "bringup_s")},
    }


def _fleet_configs() -> tuple:
    """The (n_replicas, n_shards) sweep, capped to what this host can
    actually demonstrate: a config with more replicas than max(4, cores)
    measures core-oversubscription, not dispatcher design.  Skips are
    LOUD (printed and recorded in the report) — a silently truncated
    sweep reads as 'measured everything' when it didn't."""
    cores = os.cpu_count() or 1
    cap = max(4, cores)
    run = tuple(c for c in FLEET_CONFIGS if c[0] <= cap)
    skipped = tuple(c for c in FLEET_CONFIGS if c[0] > cap)
    if skipped:
        print(f"fleet saturation: host has {cores} cores — skipping "
              f"{['%dx%d-shard' % c for c in skipped]} (replica counts "
              f"past max(4, cores)={cap} measure oversubscription)")
    return run, skipped


def _fleet_clients(n_replicas: int) -> int:
    """Closed-loop clients sized to the fleet, not a constant: window-1
    dispatch means a replica idles whenever no request is queued for it,
    so demonstrating N-replica scale-out needs comfortably more than N
    outstanding requests (3x keeps every shard's queue non-empty without
    drowning the host in client threads)."""
    return max(FLEET_CLIENTS, 3 * n_replicas)


def _fleet_load(fleet, Xa, Xb, n_clients) -> dict:
    """One closed-loop mixed two-model load: n_clients threads, each
    with a distinct tenant (the shard-routing key — distinct tenants
    spread a sharded fleet's traffic across every shard), alternating
    models request by request.  Returns wall + latencies."""
    lats = [None] * n_clients
    errors = []
    barrier = threading.Barrier(n_clients)

    def client(tid):
        lat = np.empty(FLEET_REQS_PER_CLIENT)
        tenant = f"c{tid}"
        try:
            barrier.wait(60)
            for i in range(FLEET_REQS_PER_CLIENT):
                model, X = (("a", Xa) if (tid + i) % 2 == 0
                            else ("b", Xb))
                t0 = time.perf_counter()
                fleet.predict(model, X, tenant=tenant, timeout=600)
                lat[i] = time.perf_counter() - t0
            lats[tid] = lat
        except BaseException as e:  # pragma: no cover
            errors.append(repr(e))

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(900)
    wall = time.perf_counter() - t0
    if errors:
        raise RuntimeError(f"fleet load errors: {errors[:3]}")
    return {"wall": wall, "lat": np.concatenate(lats)}


def _shard_counters(fleet, n_shards: int) -> dict:
    """Snapshot the per-shard counters (monotonic; callers diff before/
    after a timed window)."""
    ins = fleet._ins
    return {k: {"rows": ins.shard_rows.get(str(k)),
                "busy": ins.shard_rx_busy.get(str(k))}
            for k in range(n_shards)}


def bench_fleet_saturation(model_paths: dict, workdir: str,
                           features: int) -> list:
    """Sustained mixed-traffic throughput + p99 across the
    (n_replicas, n_shards) sweep in FLEET_CONFIGS.

    All configs run in THIS invocation (within-run pairs: the 1x1 row is
    the baseline every acceptance ratio divides by); per config,
    min-of-N walls with percentiles from the min-wall rep.  The shared
    warm cache keeps what's measured at steady state, not compile time.
    Client threads scale with the fleet (3x replicas) so the closed loop
    never becomes the bottleneck; each client carries its own tenant so
    shard routing spreads the load.  Sharded rows also record per-shard
    rows/s and the rx-loop busy fraction (time the shard's dispatcher-
    side rx threads spent OUT of the blocking recv — the dispatcher-
    ceiling signal the sharding exists to break)."""
    from xgboost_tpu.serving import ServingFleet

    cache = os.path.join(workdir, "saturation_cache")
    rng = np.random.default_rng(7)
    Xa = rng.normal(size=(FLEET_BATCH, features)).astype(np.float32)
    Xb = rng.normal(size=(FLEET_BATCH, features)).astype(np.float32)
    rows = []
    configs, _ = _fleet_configs()
    for n, shards in configs:
        n_clients = _fleet_clients(n)
        n_requests = n_clients * FLEET_REQS_PER_CLIENT
        with ServingFleet(model_paths, n_replicas=n, n_shards=shards,
                          cache_dir=cache,
                          warmup_buckets=(FLEET_BATCH,)) as fleet:
            _fleet_load(fleet, Xa, Xb, n_clients)  # warm pass, untimed
            best = None
            for _ in range(_reps()):
                c0 = _shard_counters(fleet, shards)
                r = _fleet_load(fleet, Xa, Xb, n_clients)
                r["shard_delta"] = {
                    k: {"rows": c1["rows"] - c0[k]["rows"],
                        "busy": c1["busy"] - c0[k]["busy"]}
                    for k, c1 in _shard_counters(fleet, shards).items()}
                if best is None or r["wall"] < best["wall"]:
                    best = r
        p50, p99 = np.percentile(best["lat"], [50, 99])
        wall = best["wall"]
        per_shard = [
            {"shard": k,
             "rows_per_s": round(d["rows"] / wall, 1),
             "rx_busy_frac": round(d["busy"] / wall, 4)}
            for k, d in sorted(best["shard_delta"].items())]
        row = {
            "n_replicas": n,
            "n_shards": shards,
            "clients": n_clients,
            "requests": n_requests,
            "batch": FLEET_BATCH,
            "reps": _reps(),
            "wall_s": round(wall, 3),
            "requests_per_s": round(n_requests / wall, 1),
            "rows_per_s": round(n_requests * FLEET_BATCH / wall, 1),
            "p50_ms": round(float(p50) * 1e3, 3),
            "p99_ms": round(float(p99) * 1e3, 3),
            "per_shard": per_shard,
        }
        rows.append(row)
        busy = max((s["rx_busy_frac"] for s in per_shard), default=0.0)
        print(f"fleet n={n} shards={shards}  "
              f"rows/s={row['rows_per_s']:.0f}  "
              f"p50={row['p50_ms']:.1f}ms  p99={row['p99_ms']:.1f}ms  "
              f"max rx busy={busy:.0%}")
    return rows


def bench_shed_vs_degrade(model_path: str, workdir: str,
                          features: int) -> dict:
    """Static queue-bound shedding vs governor-driven brownout under the
    SAME synthetic overload (docs/reliability.md "Resource pressure &
    graceful degradation").

    One replica, a tight queue (max_queue=8), closed-loop mixed traffic:
    4 gold clients (priority 2) against 8 free clients (priority -1),
    every client sequential.  Leg A (shed): governor nominal — the only
    defense is the queue bound, so free work interleaves into the
    replica whenever gold's queue drains and the window-1 dispatch makes
    every gold request eat head-of-line free execute time.  Leg B
    (degrade): the governor is at overload level 1 — free-class requests
    are browned out AT ADMISSION (`xtb_fleet_brownout_total`), so the
    replica serves gold exclusively.  The row reports per-class
    completions/sheds and gold's p50/p99 for both legs from the same
    fleet (a within-run pair per the host-noise convention; best-of-N
    legs by gold p99).
    """
    import concurrent.futures as cf

    from xgboost_tpu.reliability import resources
    from xgboost_tpu.serving import ServingFleet
    from xgboost_tpu.serving.batcher import QueueFullError
    from xgboost_tpu.serving.fleet import FleetConfig, SLOClass

    classes = {"gold": SLOClass("gold", priority=2, deadline_s=60.0),
               "free": SLOClass("free", priority=-1, deadline_s=60.0)}
    cfg = FleetConfig(n_replicas=1, max_queue=8, slo_classes=classes,
                      nthread_per_replica=1,
                      cache_dir=os.path.join(workdir, "svd_cache"),
                      warmup_buckets=(64,))
    rng = np.random.default_rng(5)
    Xq = rng.normal(size=(64, features)).astype(np.float32)
    gold_clients, free_clients, per_client = 4, 8, 25

    def one_leg(fleet) -> dict:
        out = {c: {"completed": 0, "shed": 0, "expired": 0}
               for c in classes}
        gold_lat = []
        lock = threading.Lock()

        def client(tenant, n):
            for _ in range(n):
                t0 = time.perf_counter()
                try:
                    fleet.predict("m", Xq, tenant=tenant, timeout=120)
                    dt = time.perf_counter() - t0
                    with lock:
                        out[tenant]["completed"] += 1
                        if tenant == "gold":
                            gold_lat.append(dt)
                except QueueFullError:
                    with lock:
                        out[tenant]["shed"] += 1
                except (TimeoutError, cf.TimeoutError):
                    with lock:
                        out[tenant]["expired"] += 1

        threads = ([threading.Thread(target=client,
                                     args=("gold", per_client))
                    for _ in range(gold_clients)]
                   + [threading.Thread(target=client,
                                       args=("free", per_client))
                      for _ in range(free_clients)])
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
        wall = time.perf_counter() - t0
        p99 = (round(float(np.percentile(gold_lat, 99)) * 1e3, 2)
               if gold_lat else None)
        p50 = (round(float(np.percentile(gold_lat, 50)) * 1e3, 2)
               if gold_lat else None)
        return {"classes": out, "wall_s": round(wall, 3),
                "gold_p50_ms": p50, "gold_p99_ms": p99}

    legs = {}
    resources.reset()
    with ServingFleet({"m": model_path}, cfg) as fleet:
        fleet.predict("m", Xq, tenant="gold", timeout=600)  # warm pass
        best_shed = best_deg = None
        for _ in range(_reps()):
            resources.reset()
            r = one_leg(fleet)
            if best_shed is None or (r["gold_p99_ms"] or 1e9) < (
                    best_shed["gold_p99_ms"] or 1e9):
                best_shed = r
            resources.get_governor().degrade(
                "overload", "bench synthetic overload")
            import warnings as _w

            with _w.catch_warnings():
                _w.simplefilter("ignore", RuntimeWarning)
                r = one_leg(fleet)
            if best_deg is None or (r["gold_p99_ms"] or 1e9) < (
                    best_deg["gold_p99_ms"] or 1e9):
                best_deg = r
            resources.reset()
    legs["static_shed"] = best_shed
    legs["brownout_degrade"] = best_deg
    legs["reps"] = _reps()
    legs["clients"] = {"gold": gold_clients, "free": free_clients,
                      "requests_each": per_client}
    legs["max_queue"] = 8
    print(f"shed-vs-degrade: static gold p99={best_shed['gold_p99_ms']}ms "
          f"(free completed {best_shed['classes']['free']['completed']}"
          f"/shed {best_shed['classes']['free']['shed']}) | brownout "
          f"gold p99={best_deg['gold_p99_ms']}ms (free browned out "
          f"{best_deg['classes']['free']['shed']})")
    return legs


def bench_lifecycle_swap(workdir: str, features: int, bst) -> dict:
    """p99 during a hot swap vs steady state, with requests in flight.

    A 2-replica fleet serves v1 from a model store that already holds a
    continuation-trained v2 (training and gating excluded — this times
    the SWAP itself: double-buffered load + serialized activate).  Each
    rep alternates the active version under continuous client traffic;
    min-of-N swap walls with the during-swap p99 from the min-wall rep,
    steady-state p99 from the same run's between-swap windows (a
    within-run pair, per the host-noise convention).
    """
    import xgboost_tpu as xtb
    from xgboost_tpu.lifecycle import LifecycleConfig, LifecycleManager
    from xgboost_tpu.serving import ModelStore, ServingFleet

    store = ModelStore(os.path.join(workdir, "lifecycle_store"))
    store.publish("m", bst)
    store.set_active("m", 1)
    rng = np.random.default_rng(3)
    Xw = rng.normal(size=(4000, features)).astype(np.float32)
    yw = (Xw[:, 0] + 0.5 * Xw[:, 1] > 0).astype(np.float32)
    cont = xtb.train(dict(bst.params), xtb.DMatrix(Xw, label=yw), 2,
                     verbose_eval=False, xgb_model=bst)
    store.publish("m", cont)

    Xq = Xw[:FLEET_BATCH]
    n_clients = 4
    lats, lock, errors = [], threading.Lock(), []
    stop = threading.Event()
    swaps = []
    with ServingFleet(store_dir=store.dir, n_replicas=2,
                      cache_dir=os.path.join(workdir, "lifecycle_cache"),
                      warmup_buckets=(FLEET_BATCH,)) as fleet:

        def client(tid):
            try:
                while not stop.is_set():
                    t0 = time.perf_counter()
                    fleet.predict("m", Xq, timeout=600)
                    with lock:
                        lats.append((t0, time.perf_counter() - t0))
            except BaseException as e:  # pragma: no cover
                errors.append(repr(e))

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(n_clients)]
        for t in threads:
            t.start()
        mgr = LifecycleManager(fleet, "m",
                               config=LifecycleConfig(rounds_per_cycle=1))
        time.sleep(1.0)  # steady-state lead-in
        target = 2
        for _ in range(_reps()):
            t0 = time.perf_counter()
            mgr.swap(target)
            swaps.append((t0, time.perf_counter()))
            target = 1 if target == 2 else 2
            time.sleep(0.5)  # steady window between swaps
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(900)
    if errors:
        raise RuntimeError(f"lifecycle swap bench errors: {errors[:3]}")

    walls = [t1 - t0 for t0, t1 in swaps]
    best = int(np.argmin(walls))
    during_best = [dt for (t, dt) in lats
                   if swaps[best][0] <= t <= swaps[best][1]]
    steady = [dt for (t, dt) in lats
              if not any(a <= t <= b for a, b in swaps)]
    in_flight = [len([1 for (t, _) in lats if a <= t <= b])
                 for a, b in swaps]
    return {
        "reps": _reps(),
        "n_replicas": 2,
        "clients": n_clients,
        "batch": FLEET_BATCH,
        "requests_total": len(lats),
        "swap_wall_s": round(min(walls), 4),
        "swap_walls_s": [round(w, 4) for w in walls],
        "requests_during_swap": in_flight[best],
        "requests_during_swap_all": in_flight,
        "p99_during_ms": round(float(np.percentile(during_best, 99)) * 1e3,
                               3) if during_best else None,
        "p99_steady_ms": round(float(np.percentile(steady, 99)) * 1e3, 3),
        "p50_steady_ms": round(float(np.percentile(steady, 50)) * 1e3, 3),
    }


def main(out_path: str) -> int:
    import jax

    from xgboost_tpu.serving import ServingEngine

    rounds = int(os.environ.get("BENCH_SERVE_ROUNDS", "20"))
    depth = int(os.environ.get("BENCH_SERVE_DEPTH", "6"))
    features = int(os.environ.get("BENCH_SERVE_FEATURES", "28"))
    scale = float(os.environ.get("BENCH_SERVE_ITERS", "1"))

    bst, X = train_model(rounds, depth, features)
    report = {
        "bench": "serving_engine",
        "platform": jax.default_backend(),
        "generated_unix": int(time.time()),
        "reps": _reps(),
        "host_cores": os.cpu_count(),
        "host": _host_fingerprint(),
        "model": {"rounds": rounds, "max_depth": depth, "features": features,
                  "objective": "binary:logistic"},
        "config": {"warmup_buckets": [1, 64, 4096], "max_batch": 4096,
                   "max_delay_us": 2000},
        "results": [],
    }
    with ServingEngine(max_batch=4096, max_delay_us=2000,
                       warmup_buckets=(1, 64, 4096)) as eng:
        eng.add_model("bench", bst)  # compiles every benchmarked bucket
        for b in BATCH_SIZES:
            iters = max(10, int(ITERS[b] * scale))
            r = bench_direct(eng, X, b, iters)
            report["results"].append(_stamp(r))
            print(f"batch={b:5d}  p50={r['p50_ms']:.3f}ms  "
                  f"p99={r['p99_ms']:.3f}ms  rows/s={r['rows_per_s']:.0f}")
        report["concurrent"] = _stamp(bench_concurrent(eng, X))
        steady = report["concurrent"]["engine_metrics"]["compiles_steady"]
        print(f"concurrent: {report['concurrent']['requests_per_s']:.0f} "
              f"req/s over {report['concurrent']['threads']} threads, "
              f"steady-state compiles={steady}")

    rc = 0
    if os.environ.get("BENCH_SERVE_FLEET", "1") != "0":
        workdir = tempfile.mkdtemp(prefix="xtb_bench_fleet_")
        try:
            # mixed-architecture set: the binary model above + a
            # multiclass + a regression one (distinct serve programs per
            # bucket each — a multi-tenant replica's real warm load)
            bst_b, _ = train_model(max(2, rounds // 2), max(3, depth - 2),
                                   features, "multi:softprob", num_class=5)
            bst_c, _ = train_model(max(2, rounds // 2), max(3, depth - 1),
                                   features, "reg:squarederror")
            pa = os.path.join(workdir, "a.json")
            pb = os.path.join(workdir, "b.json")
            pc = os.path.join(workdir, "c.json")
            bst.save_model(pa)
            bst_b.save_model(pb)
            bst_c.save_model(pc)
            cs = bench_fleet_coldstart({"a": pa, "b": pb, "c": pc}, workdir)
            report["fleet_coldstart"] = _stamp(cs)
            print(f"fleet coldstart ({cs['programs']} programs): "
                  f"cold={cs['cold_warmup_s']:.2f}s "
                  f"warm={cs['warm_warmup_s']:.3f}s "
                  f"speedup={cs['speedup']:.0f}x")
            sat = bench_fleet_saturation({"a": pa, "b": pb}, workdir,
                                         features)
            report["fleet_saturation"] = _stamp(sat)
            base = sat[0]["rows_per_s"]
            top_row = max(sat, key=lambda r: r["rows_per_s"])
            top = top_row["rows_per_s"]
            unsharded = [r for r in sat if r["n_shards"] == 1]
            report["fleet_scaling_vs_single"] = round(
                unsharded[-1]["rows_per_s"] / base, 2)
            report["fleet_best_scaling"] = round(top / base, 2)
            report["fleet_best_config"] = {
                "n_replicas": top_row["n_replicas"],
                "n_shards": top_row["n_shards"],
                "rows_per_s": top}
            _, skipped = _fleet_configs()
            if skipped:
                report["fleet_configs_skipped"] = [
                    {"n_replicas": n, "n_shards": s} for n, s in skipped]
            max_reps = max(r["n_replicas"] for r in sat)
            cores = os.cpu_count() or 1
            if cores < 2 * max_reps:
                # N replicas + dispatchers + clients need ~2N cores to
                # demonstrate replica-limited scale-out; below that the
                # rows measure core-oversubscription, not the dispatcher
                # design (total CPU bounds fleet/single at cores/1 when a
                # single replica already saturates its core)
                report["fleet_scaling_note"] = (
                    f"host-bound: {cores} cores for "
                    f"{max_reps} replicas + dispatchers; "
                    f"theoretical scaling ceiling ~{cores}.0x")
            print(f"fleet best {top_row['n_replicas']}x"
                  f"{top_row['n_shards']}-shard vs single: "
                  f"{top / base:.2f}x "
                  f"({report.get('fleet_scaling_note', 'replica-limited')})")
            svd = bench_shed_vs_degrade(pa, workdir, features)
            report["shed_vs_degrade"] = _stamp(svd)
            ls = bench_lifecycle_swap(workdir, features, bst)
            report["lifecycle_swap"] = _stamp(ls)
            print(f"lifecycle swap: wall={ls['swap_wall_s'] * 1e3:.0f}ms  "
                  f"{ls['requests_during_swap']} requests in flight  "
                  f"p99 during={ls['p99_during_ms']}ms "
                  f"steady={ls['p99_steady_ms']}ms")
            # The original 10x acceptance (PR 8) was measured on a 2-core
            # host where the cold side compiled serially (2.31s).  On a
            # many-core host XLA parallelizes the cold compiles (24
            # cores: 1.40s) while the warm side is serial
            # deserialization with a fixed ~0.16s floor — the RATIO
            # shrinks as the host grows even though both absolute walls
            # improve.  Gate at 8x by default, overridable for odd hosts.
            min_x = float(os.environ.get("BENCH_COLDSTART_MIN_X", "8"))
            if cs["speedup"] < min_x:
                print(f"FAIL: warm-cache cold-start speedup "
                      f"{cs['speedup']}x < {min_x}x", file=sys.stderr)
                rc = 1
        finally:
            shutil.rmtree(workdir, ignore_errors=True)

    with open(out_path, "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    print(f"wrote {out_path}")
    if steady:
        print("FAIL: engine recompiled after warm-up", file=sys.stderr)
        rc = 1
    return rc


def diff_main(old_path: str, new_path: str) -> int:
    """Compare two BENCH_SERVE.json files section by section; refuses
    (exit 2) when any compared pair was produced on different hosts —
    cross-host wall-clock ratios are two machines, not a regression."""
    with open(old_path) as fh:
        old = json.load(fh)
    with open(new_path) as fh:
        new = json.load(fh)
    rc = 0

    def hosts_match(name, a, b) -> bool:
        nonlocal rc
        ha, hb = (a or {}).get("host"), (b or {}).get("host")
        if not ha or not hb or ha.get("id") != hb.get("id"):
            print(f"[{name}] REFUSED: rows are from different hosts "
                  f"({(ha or {}).get('id', 'unstamped')} vs "
                  f"{(hb or {}).get('id', 'unstamped')}) — wall-clock "
                  f"deltas across hosts are not comparable")
            rc = 2
            return False
        return True

    def pct(name, wa, wb, unit):
        if wa and wb:
            print(f"[{name}] {wa}{unit} -> {wb}{unit} "
                  f"({(wb - wa) / wa * 100.0:+.1f}%)")

    oldr = {r["batch"]: r for r in old.get("results", [])}
    for b, rb in {r["batch"]: r for r in new.get("results", [])}.items():
        ra = oldr.get(b)
        if ra and hosts_match(f"direct batch={b}", ra, rb):
            pct(f"direct batch={b} p99", ra["p99_ms"], rb["p99_ms"], "ms")
    ca, cb = old.get("concurrent"), new.get("concurrent")
    if ca and cb and hosts_match("concurrent", ca, cb):
        pct("concurrent req/s", ca["requests_per_s"],
            cb["requests_per_s"], "")
    key = lambda r: (r.get("n_replicas"), r.get("n_shards", 1))
    olds = {key(r): r for r in old.get("fleet_saturation", [])}
    for k, rb in {key(r): r
                  for r in new.get("fleet_saturation", [])}.items():
        ra = olds.get(k)
        name = f"fleet {k[0]}x{k[1]}-shard"
        if ra and hosts_match(name, ra, rb):
            pct(f"{name} rows/s", ra["rows_per_s"], rb["rows_per_s"], "")
    return rc


if __name__ == "__main__":
    if "--diff" in sys.argv:
        i = sys.argv.index("--diff")
        sys.exit(diff_main(sys.argv[i + 1], sys.argv[i + 2]))
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "BENCH_SERVE.json"))
