"""Serving-engine latency/throughput benchmark -> BENCH_SERVE.json.

Measures the ISSUE-1 acceptance numbers on the CPU backend: p50/p99
request latency and rows/s at batch sizes {1, 64, 4096} through the
ServingEngine's pre-compiled bucket path (direct mode isolates per-request
cost from batching delay), plus one concurrent section — 4 threads of
batch-1 traffic through the micro-batcher — whose engine metrics snapshot
(batch-size histogram, queue peak, compiles_steady) is persisted verbatim.
``compiles_steady`` MUST be 0 in the emitted artifact: a recompile in the
timed loop is a serving regression, and the suite's smoke test
(tests/test_serving.py) fails on the same gauge.

Usage:  python scripts/bench_serve.py [out.json]   (default BENCH_SERVE.json)
Knobs:  BENCH_SERVE_ROUNDS / _DEPTH / _FEATURES for model size,
        BENCH_SERVE_ITERS to scale the timed loops.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

BATCH_SIZES = (1, 64, 4096)
ITERS = {1: 400, 64: 200, 4096: 30}


def train_model(rounds: int, depth: int, features: int):
    import xgboost_tpu as xtb

    rng = np.random.default_rng(0)
    X = rng.normal(size=(20_000, features)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] - 0.25 * X[:, 2] > 0).astype(np.float32)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": depth,
                     "max_bin": 256}, xtb.DMatrix(X, label=y), rounds,
                    verbose_eval=False)
    return bst, X


def bench_direct(eng, X, batch: int, iters: int) -> dict:
    """Per-request latency through the pre-compiled direct path."""
    rng = np.random.default_rng(batch)
    rows = [X[rng.integers(0, len(X) - batch + 1)
              or 0:][:batch] for _ in range(8)]
    for r in rows[:2]:  # shape warm-up (bucket already compiled by warmup())
        eng.predict("bench", r, direct=True)
    lat = np.empty(iters)
    t_all0 = time.perf_counter()
    for i in range(iters):
        t0 = time.perf_counter()
        eng.predict("bench", rows[i % len(rows)], direct=True)
        lat[i] = time.perf_counter() - t0
    wall = time.perf_counter() - t_all0
    p50, p99 = np.percentile(lat, [50, 99])
    return {
        "batch": batch,
        "iters": iters,
        "p50_ms": round(float(p50) * 1e3, 4),
        "p99_ms": round(float(p99) * 1e3, 4),
        "rows_per_s": round(batch * iters / wall, 1),
    }


def bench_concurrent(eng, X, n_threads: int = 4, per_thread: int = 100):
    """Batch-1 traffic from N threads through the micro-batcher: the
    coalescing path the engine exists for."""
    barrier = threading.Barrier(n_threads)
    errors = []

    def worker(tid):
        rng = np.random.default_rng(tid)
        try:
            barrier.wait(30)
            for _ in range(per_thread):
                eng.predict("bench", X[rng.integers(0, len(X))][None, :])
        except BaseException as e:  # pragma: no cover
            errors.append(repr(e))

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(600)
    wall = time.perf_counter() - t0
    snap = eng.metrics_snapshot()
    return {
        "threads": n_threads,
        "requests": n_threads * per_thread,
        "wall_s": round(wall, 3),
        "requests_per_s": round(n_threads * per_thread / wall, 1),
        "errors": errors,
        "engine_metrics": snap,
    }


def main(out_path: str) -> int:
    import jax

    from xgboost_tpu.serving import ServingEngine

    rounds = int(os.environ.get("BENCH_SERVE_ROUNDS", "20"))
    depth = int(os.environ.get("BENCH_SERVE_DEPTH", "6"))
    features = int(os.environ.get("BENCH_SERVE_FEATURES", "28"))
    scale = float(os.environ.get("BENCH_SERVE_ITERS", "1"))

    bst, X = train_model(rounds, depth, features)
    report = {
        "bench": "serving_engine",
        "platform": jax.default_backend(),
        "generated_unix": int(time.time()),
        "model": {"rounds": rounds, "max_depth": depth, "features": features,
                  "objective": "binary:logistic"},
        "config": {"warmup_buckets": [1, 64, 4096], "max_batch": 4096,
                   "max_delay_us": 2000},
        "results": [],
    }
    with ServingEngine(max_batch=4096, max_delay_us=2000,
                       warmup_buckets=(1, 64, 4096)) as eng:
        eng.add_model("bench", bst)  # compiles every benchmarked bucket
        for b in BATCH_SIZES:
            iters = max(10, int(ITERS[b] * scale))
            r = bench_direct(eng, X, b, iters)
            report["results"].append(r)
            print(f"batch={b:5d}  p50={r['p50_ms']:.3f}ms  "
                  f"p99={r['p99_ms']:.3f}ms  rows/s={r['rows_per_s']:.0f}")
        report["concurrent"] = bench_concurrent(eng, X)
        steady = report["concurrent"]["engine_metrics"]["compiles_steady"]
        print(f"concurrent: {report['concurrent']['requests_per_s']:.0f} "
              f"req/s over {report['concurrent']['threads']} threads, "
              f"steady-state compiles={steady}")

    with open(out_path, "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    print(f"wrote {out_path}")
    if steady:
        print("FAIL: engine recompiled after warm-up", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "BENCH_SERVE.json"))
