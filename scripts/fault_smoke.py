#!/usr/bin/env python
"""Fault-injection smoke for the nightly suite (docs/reliability.md).

Flow: a 4-process distributed training run is killed by the injected fault
plan (rank 2 dies entering round 3); a relaunch with ``resume_from=`` picks
up the newest valid checkpoint; the final model's UBJSON bytes must equal
an uninterrupted 4-process run's.  Exercises the launcher's ``fault_plan``
wiring, the CheckpointCallback, and train() resume in one pass.

Usage: JAX_PLATFORMS=cpu python scripts/fault_smoke.py [workers] [rounds]
"""
import functools
import json
import os
import shutil
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WORKERS = int(sys.argv[1]) if len(sys.argv) > 1 else 4
ROUNDS = int(sys.argv[2]) if len(sys.argv) > 2 else 6
KILL_RANK, KILL_ROUND = min(2, WORKERS - 1), 3

PARAMS = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.3,
          "max_bin": 32}


def worker(rank, world, *, ckpt_dir, out_path, resume, rounds):
    import numpy as np

    import xgboost_tpu as xtb

    rng = np.random.default_rng(0)
    X = rng.normal(size=(2000, 6)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    Xs, ys = X[rank::world], y[rank::world]
    bst = xtb.train(PARAMS, xtb.DMatrix(Xs, label=ys), rounds,
                    verbose_eval=False,
                    callbacks=[xtb.CheckpointCallback(ckpt_dir, interval=1)],
                    resume_from=ckpt_dir if resume else None)
    if rank == 0:
        with open(out_path, "wb") as fh:
            fh.write(bytes(bst.save_raw()))


def main() -> int:
    from xgboost_tpu.launcher import run_distributed
    from xgboost_tpu.reliability import latest_checkpoint

    # pickle the worker under its importable module name, not __main__ —
    # the spawned children re-import it from scripts/ (launcher mod_dir)
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import fault_smoke as _mod

    global worker
    worker = _mod.worker

    tmp = tempfile.mkdtemp(prefix="xtb_fault_smoke_")
    try:
        full_out = os.path.join(tmp, "full.ubj")
        res_out = os.path.join(tmp, "resumed.ubj")
        ckpt_full = os.path.join(tmp, "ckpt_full")
        ckpt_int = os.path.join(tmp, "ckpt_int")

        print(f"[fault_smoke] uninterrupted {WORKERS}-process run ...")
        run_distributed(
            functools.partial(worker, ckpt_dir=ckpt_full, out_path=full_out,
                              resume=False, rounds=ROUNDS),
            num_workers=WORKERS, platform="cpu", timeout=900,
            rendezvous="tracker")
        full = open(full_out, "rb").read()

        print(f"[fault_smoke] injected kill: rank {KILL_RANK} at round "
              f"{KILL_ROUND} ...")
        plan = {"faults": [{"site": "train.round", "kind": "kill",
                            "rank": KILL_RANK, "round": KILL_ROUND,
                            "exit_code": 43}]}
        try:
            run_distributed(
                functools.partial(worker, ckpt_dir=ckpt_int, out_path="",
                                  resume=False, rounds=ROUNDS),
                num_workers=WORKERS, platform="cpu", timeout=900,
                fault_plan=json.dumps(plan), rendezvous="tracker")
        except RuntimeError as e:
            print(f"[fault_smoke] interrupted as planned: {e}")
        else:
            raise SystemExit("fault plan did not interrupt the run")
        st = latest_checkpoint(ckpt_int)
        if st is None or not (1 <= st.round <= KILL_ROUND):
            raise SystemExit(f"no usable checkpoint after the kill: {st}")
        print(f"[fault_smoke] newest valid checkpoint: round {st.round}")

        print("[fault_smoke] resuming ...")
        run_distributed(
            functools.partial(worker, ckpt_dir=ckpt_int, out_path=res_out,
                              resume=True, rounds=ROUNDS),
            num_workers=WORKERS, platform="cpu", timeout=900,
            rendezvous="tracker")
        resumed = open(res_out, "rb").read()
        if resumed != full:
            raise SystemExit(
                "PARITY FAILURE: resumed model differs from the "
                f"uninterrupted run ({len(resumed)} vs {len(full)} bytes)")
        print(f"[fault_smoke] OK: kill/resume parity holds "
              f"({len(full)} identical UBJSON bytes, {WORKERS} workers, "
              f"{ROUNDS} rounds)")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
