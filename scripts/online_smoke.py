#!/usr/bin/env python
"""Online-learning-loop smoke for the nightly suite (docs/online.md).

One closed loop, end to end against real replica processes, run TWICE:

1. **Closed loop under traffic.**  Serve a base model with feedback
   sampling on, join deterministic labels by trace id, shift the traffic
   distribution until the drift detector trips, and let the
   OnlineScheduler drive the retrain + gate + shadow + hot swap — all
   while sustained client traffic flows.  Assert ZERO dropped/failed
   requests, the swap took (bits changed, then stable), and the join
   accounting drops nothing silently.

2. **Seeded replay.**  Run the identical schedule again (same seed, same
   request blocks, same label order) and require the post-swap model to
   serve the SAME BITS — the loop's determinism contract: sampling is a
   counter off the trace id, the join is order-deterministic, and
   continuation training under a fixed window is bitwise-reproducible.

3. **Brownout yields.**  With the governor degraded (overload pressure),
   a forced retrain must DEFER (reason ``brownout``) while serving keeps
   answering; after restore the same call runs a real cycle.  Training
   never competes with serving for a degraded host.

Usage: JAX_PLATFORMS=cpu python scripts/online_smoke.py [n_replicas]
"""
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

N_CLIENTS = 3
BATCH = 16
N_BASE = 8      # base-distribution request blocks (reference traffic)
N_SHIFT = 16    # shifted blocks (what trips the drift edge)

PARAMS = {"objective": "binary:logistic", "max_depth": 3,
          "eval_metric": "logloss", "seed": 7}


def _blocks(seed):
    """The deterministic request schedule both legs replay."""
    rng = np.random.default_rng(seed)
    blocks = [rng.standard_normal((BATCH, 6)).astype(np.float32)
              for _ in range(N_BASE)]
    blocks += [(rng.standard_normal((BATCH, 6)) + 4.0).astype(np.float32)
               for _ in range(N_SHIFT)]
    return blocks


def _label_of(rows):
    return (rows[:, 0] - rows[:, 2] > 0).astype(np.float32)


def _publish_base(store_dir):
    import xgboost_tpu as xtb
    from xgboost_tpu.serving import ModelStore

    rng = np.random.default_rng(20)
    X = rng.standard_normal((2000, 6)).astype(np.float32)
    base = xtb.train(PARAMS, xtb.DMatrix(X, label=_label_of(X)), 4,
                     verbose_eval=False)
    st = ModelStore(store_dir)
    st.publish("m", base)
    st.set_active("m", 1)


def closed_loop(workdir, n_replicas, seed, leg) -> "tuple[int, bytes]":
    """One full loop; returns (rc, post-swap served bytes) — the bytes
    are the replay leg's determinism digest."""
    from xgboost_tpu.lifecycle import GateConfig, LifecycleConfig
    from xgboost_tpu.online import DriftConfig, OnlineConfig, OnlineScheduler
    from xgboost_tpu.reliability import resources
    from xgboost_tpu.serving import ServingFleet

    store_dir = os.path.join(workdir, f"store_{leg}")
    _publish_base(store_dir)
    blocks = _blocks(seed)
    Xq = blocks[0]
    errors, stop = [], threading.Event()
    lats = []
    lats_lock = threading.Lock()

    with ServingFleet(store_dir=store_dir, n_replicas=n_replicas,
                      cache_dir=os.path.join(workdir, "cache"),
                      warmup_buckets=(BATCH,)) as fleet:
        sch = OnlineScheduler(fleet, "m", config=OnlineConfig(
            sample_every=2, join_horizon_s=600.0, min_retrain_rows=64,
            window_rows=8192, page_rows=64,
            spool_dir=os.path.join(workdir, f"window_{leg}"),
            drift=DriftConfig(min_rows=32, max_feature_ks=0.3),
            lifecycle=LifecycleConfig(
                rounds_per_cycle=3,
                checkpoint_dir=os.path.join(workdir, f"ckpt_{leg}"),
                gate=GateConfig(min_improvement=-1e9))))
        sch.enable()

        # the deterministic schedule: serve every block, remember traces
        traces = []
        for rows in blocks:
            fut = fleet.submit("m", rows)
            traces.append(fut.trace_id)
            fut.result(timeout=180)
        deadline = time.monotonic() + 60.0
        want = sum(1 for t in traces
                   if int(t.split("-")[1], 16) % 2 == 0)
        while (sch.hub.stats()["offered"] < want
               and time.monotonic() < deadline):
            time.sleep(0.02)
        for tr, rows in zip(traces, blocks):
            sch.label(tr, _label_of(rows))

        # sustained client traffic across the retrain + swap — every
        # issued request must complete
        def client(tid):
            try:
                while not stop.is_set():
                    t0 = time.perf_counter()
                    fleet.predict("m", Xq, timeout=600)
                    with lats_lock:
                        lats.append(time.perf_counter() - t0)
            except BaseException as e:
                errors.append(f"client{tid}: {e!r}")

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(N_CLIENTS)]
        for t in threads:
            t.start()

        out = sch.step()
        if out["outcome"] != "swapped":
            errors.append(f"loop did not swap: {out['outcome']} "
                          f"(drift={out.get('drift')})")
        # the replay digest: the FIRST swap's bits (leg 0 runs extra
        # cycles below for the brownout demonstration)
        bits = np.ascontiguousarray(
            fleet.predict("m", Xq, timeout=180), np.float32).tobytes()

        # brownout leg (first pass only; the replay leg stays minimal)
        if leg == 0 and not errors:
            gov = resources.get_governor()
            gov.degrade("overload", "online_smoke injected pressure")
            try:
                deferred = sch.maybe_retrain(force=True)
                if (deferred.get("outcome") != "deferred"
                        or deferred.get("reason") != "brownout"):
                    errors.append(f"retrain did not yield to brownout: "
                                  f"{deferred}")
                fleet.predict("m", Xq, timeout=120)  # serving still answers
            finally:
                gov.restore("overload")
            after = sch.maybe_retrain(force=True)
            if after.get("outcome") == "deferred":
                errors.append(f"retrain still deferred after restore: "
                              f"{after}")

        stop.set()
        for t in threads:
            t.join(900)
        if any(t.is_alive() for t in threads):
            errors.append("clients never finished")

        sch.disable()
        served = np.ascontiguousarray(
            fleet.predict("m", Xq, timeout=120), np.float32)
        for _ in range(2):
            if not np.array_equal(
                    fleet.predict("m", Xq, timeout=120), served):
                errors.append("post-swap predictions NOT bitwise-stable")
                break
        join = sch.hub.stats()
        # expired/capacity drops are the hub doing its bounded job on
        # never-labeled traffic samples; fault/duplicate/untraced here
        # would be real bugs
        silent = {k: v for k, v in join["dropped"].items()
                  if k not in ("expired", "capacity")}
        if silent:
            errors.append(f"join dropped records: {silent}")

    p99 = float(np.percentile(lats, 99)) * 1e3 if lats else 0.0
    print(f"online closed-loop leg {leg}: {len(lats)} traffic requests "
          f"completed, zero failed; sampled/joined "
          f"{join['matched']}/{want} blocks into "
          f"{len(sch.window)}-row window; outcome={out['outcome']}; "
          f"p99={p99:.1f}ms")
    if errors:
        print(f"FAIL: {errors[:5]}", file=sys.stderr)
        return 1, b""
    return 0, bits


def main() -> int:
    n_replicas = int(sys.argv[1]) if len(sys.argv) > 1 else 2
    seed = int(os.environ.get("ONLINE_SMOKE_SEED", "20260806"))
    workdir = tempfile.mkdtemp(prefix="xtb_online_smoke_")

    rc, bits0 = closed_loop(workdir, n_replicas, seed, leg=0)
    if rc:
        return rc
    rc, bits1 = closed_loop(workdir, n_replicas, seed, leg=1)
    if rc:
        return rc
    if bits0 != bits1:
        print("FAIL: seeded replay retrained a DIFFERENT model — the "
              "loop's determinism contract is broken", file=sys.stderr)
        return 1
    import shutil

    shutil.rmtree(workdir, ignore_errors=True)
    print("online smoke OK: zero dropped requests, drift-triggered swap, "
          "brownout yielded to serving, seeded replay bitwise-identical")
    return 0


if __name__ == "__main__":
    sys.exit(main())
