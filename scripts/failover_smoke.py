#!/usr/bin/env python
"""Coordinator-failover smoke for the nightly suite (docs/reliability.md
"Coordinator failover & watchdog").

Three legs over a 3-worker tracker-mode CPU run with the tracker as a
SUPERVISED, JOURNALING subprocess (``tracker_failover=True``):

1. **Kill**: the fault plan SIGKILLs the tracker at its 3rd journal
   write, mid-round (rounds paced by a pure-delay fault).  The launcher
   respawns it against the journal, the workers re-adopt with backoff,
   the run finishes with all workers intact — and the **tracker-respawn
   pause wall** (death detection → the respawned tracker accepting) is
   recorded in the smoke output.
2. **Parity**: an undisturbed run of the same job must produce
   bitwise-identical model bytes — a coordinator death costs a pause,
   never a bit.
3. **Stall**: a watchdog leg at tight budgets — one rank sleeps far past
   the collective-wait budget; the guard dumps all-thread stacks and
   severs, the tracker's join ladder declares the sleeper dead, and the
   survivors finish at world N−1.  Asserts the faulthandler dump exists
   and the run needed no outer deadline.

Usage: JAX_PLATFORMS=cpu python scripts/failover_smoke.py [workers] [rounds]
"""
import functools
import glob
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PARAMS = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.3,
          "max_bin": 32}
N_ROWS = 2400


def worker(rank, world, *, ckpt_dir, out_path, rounds, num_shards):
    import numpy as np

    import xgboost_tpu as xtb

    rng = np.random.default_rng(0)
    X = rng.normal(size=(N_ROWS, 6)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)

    def data_fn(shard_map, rank, world):
        shards = shard_map.shards_of(rank)
        rows = np.sort(np.concatenate(
            [np.arange(s, N_ROWS, shard_map.num_shards) for s in shards]))
        return xtb.DMatrix(X[rows], label=y[rows])

    cfg = xtb.ElasticConfig(data_fn, ckpt_dir, num_shards=num_shards)
    bst = xtb.train(PARAMS, None, rounds, elastic=cfg, verbose_eval=False)
    from xgboost_tpu import collective

    if collective.get_rank() == 0 and out_path:
        with open(out_path, "wb") as fh:
            fh.write(bytes(bst.save_raw()))


def _run(tag, *, tmp, workers, rounds, fault_plan=None, failover=True,
         env=None):
    from xgboost_tpu.launcher import run_distributed

    ckpt = os.path.join(tmp, f"ckpt_{tag}")
    out = os.path.join(tmp, f"{tag}.ubj")
    print(f"[failover_smoke] {tag}: {workers} workers, {rounds} rounds",
          flush=True)
    saved = {}
    if env:
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
    try:
        stats = run_distributed(
            functools.partial(worker, ckpt_dir=ckpt, out_path=out,
                              rounds=rounds, num_shards=2 * workers),
            num_workers=workers, platform="cpu", timeout=900,
            rendezvous="tracker", elastic=True,
            fault_plan=json.dumps(fault_plan) if fault_plan else None,
            tracker_failover=failover)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return open(out, "rb").read(), stats, ckpt


def main() -> int:
    from xgboost_tpu.reliability import latest_checkpoint

    WORKERS = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    ROUNDS = int(sys.argv[2]) if len(sys.argv) > 2 else 8

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import failover_smoke as _mod

    global worker
    worker = _mod.worker

    tmp = tempfile.mkdtemp(prefix="xtb_failover_smoke_")
    try:
        # -- leg 1: SIGKILL the tracker mid-round --------------------------
        plan = {"faults": [
            {"site": "tracker.journal", "kind": "kill", "at": 2},
            {"site": "train.round", "kind": "delay", "seconds": 0.6,
             "times": 1000},
        ]}
        model_k, stats_k, _ = _run("tracker_kill", tmp=tmp, workers=WORKERS,
                                   rounds=ROUNDS, fault_plan=plan)
        if stats_k["tracker_respawns"] < 1:
            raise SystemExit("tracker kill never fired (no respawn)")
        if stats_k["succeeded"] != WORKERS:
            raise SystemExit(
                f"failover cost a worker: {stats_k['succeeded']}/{WORKERS}")
        pauses = ", ".join(f"{p:.2f}s" for p in stats_k["tracker_pauses_s"])
        print(f"[failover_smoke] kill OK: {stats_k['tracker_respawns']} "
              f"respawn(s), tracker-respawn pause wall: {pauses}")

        # -- leg 2: bitwise parity vs an undisturbed run -------------------
        model_c, stats_c, ckpt_c = _run("clean", tmp=tmp, workers=WORKERS,
                                        rounds=ROUNDS)
        if stats_c["tracker_respawns"] != 0:
            raise SystemExit("clean leg respawned a tracker?!")
        if model_k != model_c:
            raise SystemExit(
                f"PARITY FAILURE: tracker-kill model ({len(model_k)} B) != "
                f"undisturbed model ({len(model_c)} B)")
        st = latest_checkpoint(ckpt_c)
        if st is None or st.round != ROUNDS:
            raise SystemExit(f"clean run did not complete: {st}")
        print(f"[failover_smoke] parity OK: identical bytes "
              f"({len(model_k)} B) across a coordinator SIGKILL")

        # -- leg 3: stall watchdog ----------------------------------------
        flight_dir = os.path.join(tmp, "flight")
        stall_plan = {"faults": [
            {"site": "train.round", "kind": "delay", "seconds": 12.0,
             "rank": 1, "round": 2, "at": 2},
        ]}
        model_s, stats_s, ckpt_s = _run(
            "stall", tmp=tmp, workers=2, rounds=ROUNDS,
            fault_plan=stall_plan, failover=False,
            env={"XGBOOST_TPU_FLIGHT_DIR": flight_dir,
                 "XGBOOST_TPU_WATCHDOG_COLLECTIVE_WAIT_S": "1.5",
                 "XGBOOST_TPU_WATCHDOG_TRACKER_JOIN_S": "1.5"})
        st = latest_checkpoint(ckpt_s)
        if st is None or st.round != ROUNDS:
            raise SystemExit(f"stall run did not complete: {st}")
        if st.world != 1:
            raise SystemExit(
                f"stalled rank was not declared dead (world {st.world})")
        stacks = glob.glob(os.path.join(flight_dir, "stacks_*.txt"))
        if not stacks:
            raise SystemExit("watchdog left no faulthandler stack dump")
        print(f"[failover_smoke] stall OK: survivors finished at world "
              f"{st.world}, {len(stacks)} stack dump(s)")
        print(f"[failover_smoke] OK: kill + parity + stall "
              f"({WORKERS} workers, {ROUNDS} rounds)")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
