#!/bin/bash
# Full test suite + bench canary (SURVEY §4 nightly role).  The quick tier
# (`pytest -m quick`, <3 min) is the per-commit gate; this is the deep one.
set -e
cd "$(dirname "$0")/.."

# static-analysis gate first (docs/static_analysis.md): fail fast on
# retrace/lock/seam/metric violations before paying for the test suite;
# writes bench_out/lint_report.json for trend tracking
bash scripts/lint_gate.sh

# ThreadSanitizer smoke over the native ParallelFor pool + threaded
# kernels + concurrent dispatch (docs/native_threading.md).  The smoke
# binary itself sweeps BOTH simd levels (scalar + best detected ISA,
# native/xtb_simd.h) through every kernel section, so one run covers the
# scalar and vector paths under TSAN.  Only a toolchain WITHOUT libtsan
# skips (probed with a trivial program, so a real compile error in the
# smoke/kernels cannot masquerade as "no libtsan"); with libtsan present,
# build failures and TSAN findings both fail the nightly.
if echo 'int main(){return 0;}' | g++ -x c++ -fsanitize=thread -o /tmp/_tsan_probe - >/dev/null 2>&1; then
    rm -f /tmp/_tsan_probe
    echo "== native TSAN smoke =="
    make -C native tsan_smoke
    ./native/tsan_smoke
else
    echo "== native TSAN smoke: libtsan unavailable, skipping =="
fi

python -m pytest tests/ -q --durations=25

# lockdep-armed legs (docs/reliability.md "Lockdep witness"): the runtime
# witness watches real multi-process traffic for lock-order inversions
# and locks held across fault seams.  Any violation prints the
# XTB-LOCKDEP-VIOLATION marker on stderr at process exit — a leg passes
# only when its whole process tree stays silent.
run_lockdep_clean() {
    local log
    log=$(mktemp /tmp/xtb_lockdep_leg.XXXXXX.log)
    XGBOOST_TPU_LOCKDEP=1 "$@" >"$log" 2>&1 || { cat "$log"; rm -f "$log"; return 1; }
    if grep -n "XTB-LOCKDEP-VIOLATION" "$log"; then
        echo "lockdep witness reported violations under: $*" >&2
        cat "$log"
        rm -f "$log"
        return 1
    fi
    tail -n 3 "$log"
    rm -f "$log"
}

# chaos soak under the armed witness: every episode additionally checks
# the lockdep_silent invariant (reliability/chaos.py), and the marker
# grep catches violations from killed child processes too
echo "== lockdep-armed chaos soak =="
run_lockdep_clean env JAX_PLATFORMS=cpu python scripts/chaos_soak.py \
    --budget-s 60 --seed "${NIGHTLY_SEED:-20260804}"

# multi-process smokes under the armed witness: tracker fan-out under a
# mid-round kill, and fleet dispatch/heartbeat traffic with a replica
# SIGKILL — the two densest lock/wire interleavings in the tree
echo "== lockdep-armed fault smoke =="
run_lockdep_clean env JAX_PLATFORMS=cpu python scripts/fault_smoke.py 4 6
echo "== lockdep-armed fleet smoke =="
run_lockdep_clean env JAX_PLATFORMS=cpu python scripts/fleet_smoke.py 2 60

# telemetry smoke: a short traced training run must leave a parseable JSONL
# whose span names cover the per-round phases (docs/observability.md)
TRACE_OUT=$(mktemp /tmp/xtb_telemetry_smoke.XXXXXX.jsonl)
XGBOOST_TPU_TRACE="$TRACE_OUT" JAX_PLATFORMS=cpu python - "$TRACE_OUT" <<'EOF'
import json, sys
import numpy as np
import xgboost_tpu as xtb
from xgboost_tpu import telemetry

rng = np.random.default_rng(0)
X = rng.normal(size=(2000, 12)).astype(np.float32)
y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
d = xtb.DMatrix(X, label=y)
cb = telemetry.TelemetryCallback()
xtb.train({"objective": "binary:logistic", "max_depth": 4}, d, 5,
          evals=[(d, "train")], callbacks=[cb], verbose_eval=False)
telemetry.trace.flush()

events = [json.loads(l) for l in open(sys.argv[1])]  # every line must parse
assert events, "trace is empty"
assert all(set(e) >= {"name", "ph", "ts", "dur", "pid", "tid"}
           for e in events), "malformed trace event"
names = "\n".join(sorted({e["name"] for e in events}))
for needle in ("build_hist", "eval_split", "update_tree", "eval.",
               "update.gradient"):
    assert needle in names, f"phase {needle!r} missing from trace:\n{names}"
assert len(cb.history) == 5 and cb.compiles_steady == 0, \
    f"steady-state retraces: {cb.compiles_steady}"
assert "xtb_phase_seconds_bucket" in telemetry.render_prometheus()
print(f"telemetry smoke OK: {len(events)} events, "
      f"{len(names.splitlines())} span names, 0 steady compiles")
EOF
rm -f "$TRACE_OUT"

# profiler smoke (docs/observability.md "Profiling & roofline"): a
# traced AND profiled 5-round training run next to a 2-replica fleet —
# the merged flame view must contain non-empty folded stacks from at
# least two distinct processes (driver + replicas), and the collapsed
# render must be well-formed stackcollapse lines
XGBOOST_TPU_PROF_HZ=100 XGBOOST_TPU_TELEMETRY_INTERVAL=0.2 \
JAX_PLATFORMS=cpu python - <<'EOF'
import re
import numpy as np
import xgboost_tpu as xtb
from xgboost_tpu.serving import ServingFleet
from xgboost_tpu.telemetry import distributed, profiler

rng = np.random.default_rng(0)
X = rng.normal(size=(4000, 12)).astype(np.float32)
y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
bst = xtb.train({"objective": "binary:logistic", "max_depth": 4,
                 "seed": 0}, xtb.DMatrix(X, label=y), 5,
                verbose_eval=False)  # train() arms the profiler
assert profiler.running() and profiler.samples() > 0, "sampler never ran"
with ServingFleet({"m": bst}, n_replicas=2, warmup_buckets=(64,)) as fl:
    import time
    for _ in range(3):
        for f in [fl.submit("m", X[:64]) for _ in range(12)]:
            f.result(timeout=60)
        time.sleep(0.3)
folded = profiler.merged_folded()
pids = {k.split(";", 1)[0] for k in folded}
assert len(pids) >= 2, f"folded stacks from only {pids}"
assert all(c > 0 for c in folded.values())
collapsed = [l for l in profiler.render_folded().splitlines()
             if l and not l.startswith("#") and not l.startswith(" ")]
assert collapsed and all(re.match(r"^\S.* \d+$", l) for l in collapsed), \
    "malformed collapsed-stack lines"
print(f"profiler smoke OK: {len(folded)} stacks from {len(pids)} "
      f"processes, {sum(folded.values())} weighted samples")
EOF

# roofline smoke (docs/observability.md "Profiling & roofline"):
# measured STREAM peak + per-kernel achieved GB/s rows for hist,
# hist_q, split, predict on two ladder configs; fails when any of the
# four headline kernels never recorded (instrumentation regression)
JAX_PLATFORMS=cpu python scripts/bench_roofline.py \
    bench_out/BENCH_ROOFLINE.json --quick

# fault-injection smoke (docs/reliability.md): 4-process train, kill rank 2
# at round 3 via the injected plan, resume from the newest valid checkpoint,
# and require final-model UBJSON parity with an uninterrupted run
JAX_PLATFORMS=cpu python scripts/fault_smoke.py 4 6

# elastic smoke (docs/reliability.md § Elastic training): 4 workers, kill
# rank 2 mid-run via the fault plan, the survivors FINISH at world 3 (no
# restart); the same plan replayed must give bitwise-identical model
# bytes; a respawned replacement is absorbed at a round boundary with the
# shard map restored from the checkpoint
JAX_PLATFORMS=cpu python scripts/elastic_smoke.py 4 8

# coordinator failover + watchdog smoke (docs/reliability.md
# § Coordinator failover & watchdog): SIGKILL the supervised journaling
# tracker mid-round -> respawn + worker re-adoption -> model bytes
# bitwise-identical to an undisturbed run (the respawn pause wall is in
# the output); then a stall leg: a rank sleeping past the watchdog
# budget gets an all-thread stack dump and is declared dead, the
# survivors finish at world N-1 — dump + recovery, no hang
JAX_PLATFORMS=cpu python scripts/failover_smoke.py 3 8

# out-of-core smoke (docs/extmem.md): 2-worker paged run through
# train(ExtMemConfig) over the tracker relay — identical model bytes on
# every rank with peak RSS under the ceiling (pages stream, the full
# matrix never materializes) — then a mid-stream decode failure injected
# at the extmem.page_load seam must fail the job loudly with the cause
# in the worker's stderr tail instead of wedging the relay
JAX_PLATFORMS=cpu python scripts/extmem_smoke.py 8 4

# serving-fleet + observability smoke (docs/serving.md "Fleet",
# docs/observability.md "Distributed observability plane"): 3 replicas
# over two models with a warm compile cache, mixed traffic from 6 client
# threads, one replica SIGKILLed mid-stream — every request must complete
# with the in-process engine's exact bits (the dead replica's in-flight
# batch reroutes), p99 recorded, and the respawn must restore fleet
# strength.  Mid-run, one /metrics scrape must return per-replica-labeled
# xtb_serve_* AND merged xtb_fleet_* series; afterwards the SIGKILL'd
# replica's driver-side flight dump must exist and the merged chrome
# trace (driver + sidecars) must pair a dispatcher fleet.request with a
# replica.execute on one request trace id across two pids
JAX_PLATFORMS=cpu python scripts/fleet_smoke.py 3 120

# observability overhead guard (docs/observability.md): train+serve walls
# with telemetry shipping on vs off on the higgs config shape, min-of-N
# with interleaved legs; fails beyond BENCH_OBS_MAX_PCT (default 5%).
# Runs with the lockdep witness explicitly OFF: the script asserts the
# raw threading factories are in place (witness-off means NOTHING is
# patched — merged-but-unarmed lockdep cannot move this gate)
XGBOOST_TPU_LOCKDEP=0 JAX_PLATFORMS=cpu \
    python scripts/bench_obs.py bench_out/BENCH_OBS.json

# composed-fault chaos soak (docs/reliability.md "Integrity & chaos"):
# >= 20 seeded multi-fault episodes round-robin across the scenario
# templates (extmem / fleet / lifecycle / online / elastic /
# tracker_kill / stall / resource / fleet_degraded / net_partition),
# each checked for no-hang, bitwise-vs-twin, fault
# accounting, zero dropped requests, and a flight dump per death; the
# run ends by replaying episode 0's seed and requiring the identical
# schedule and outcome.  Any red episode prints its one-command repro
# (--replay <scenario> <seed>).
JAX_PLATFORMS=cpu python scripts/chaos_soak.py --budget-s 120 \
    --seed "${NIGHTLY_SEED:-20260804}"

# resource-degradation smoke (docs/reliability.md "Resource pressure &
# graceful degradation"): train with the checkpoint directory on a
# tmpfs too small for the keep-last-K set — the kernel returns REAL
# ENOSPC mid-commit; the ladder must prune-retry then skip, the run
# must finish bitwise-identical to its roomy-disk twin, every committed
# checkpoint must scrub clean (no torn files under a final name), and
# the degradation must be counted + loud.  Falls back to the injected
# disk_full kind (same seam, same ladder) where tmpfs mounts are not
# permitted.
JAX_PLATFORMS=cpu python scripts/resource_smoke.py 10

# online-lifecycle smoke (docs/serving.md "Online model lifecycle"):
# serve -> continuation-train on fresh rows -> gate -> hot-swap under
# sustained traffic (zero dropped requests, post-swap bitwise-stable,
# shadow comparator scored), then the cycle replayed with a
# lifecycle.swap KILL — the manifest must still name the incumbent and a
# restarted fleet must serve its exact bits
JAX_PLATFORMS=cpu python scripts/lifecycle_smoke.py 2 60

# online-learning-loop smoke (docs/online.md): live traffic with feedback
# sampling on -> trace-keyed label join -> drift detector trips on a
# shifted distribution -> OnlineScheduler retrains + hot-swaps under
# sustained traffic (zero dropped requests); a governor-degraded forced
# retrain must DEFER while serving keeps answering; the whole loop
# replayed from the same seed must retrain the bitwise-identical model
JAX_PLATFORMS=cpu python scripts/online_smoke.py 2

BENCH_FORCE_CPU=1 BENCH_ROWS=100000 BENCH_ROUNDS=5 python bench.py
