#!/bin/bash
# Full test suite + bench canary (SURVEY §4 nightly role).  The quick tier
# (`pytest -m quick`, <3 min) is the per-commit gate; this is the deep one.
set -e
cd "$(dirname "$0")/.."
python -m pytest tests/ -q --durations=25
BENCH_FORCE_CPU=1 BENCH_ROWS=100000 BENCH_ROUNDS=5 python bench.py
