#!/usr/bin/env python
"""Out-of-core training smoke for the nightly suite (docs/extmem.md).

Flow, all over the tracker relay:

1. A 2-worker paged run through ``train(params, ExtMemConfig(...))`` —
   each rank owns a page shard, cuts merge through the streaming
   page-wise sketch, per-level histograms allreduce over the relay —
   must produce identical model bytes on every rank, and the driver's
   **peak RSS must stay under a ceiling** far below what the resident
   full matrix would need (``resource.getrusage``; pages are generated
   on the fly, never materialized together).
2. The same run with a ``fault`` at the new ``extmem.page_load`` seam
   (a mid-stream decode failure on a prefetch worker): the affected
   worker must die LOUDLY and the launcher must surface a
   ``WorkerFailedError`` naming it — instead of wedging the relay.

Usage: JAX_PLATFORMS=cpu python scripts/extmem_smoke.py [pages] [rounds]
"""
import functools
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_PAGES = int(sys.argv[1]) if len(sys.argv) > 1 else 8
ROUNDS = int(sys.argv[2]) if len(sys.argv) > 2 else 4
PAGE_ROWS = 65536
N_COLS = 12
WORKERS = 2
# generated pages are u8-binned; the would-be resident f32 matrix is
# pages*rows*cols*4 bytes.  The ceiling leaves room for the interpreter +
# jax runtime (~600 MB here) + per-row training state, but NOT for a
# resident matrix copy per worker.
RSS_CEILING_MB = 1600

PARAMS = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.3,
          "max_bin": 64}


def _page(shard: int):
    """Synthesize one page deterministically from its shard id — any rank
    can own any shard without shared storage."""
    import numpy as np

    rng = np.random.default_rng(1000 + shard)
    X = rng.normal(size=(PAGE_ROWS, N_COLS)).astype(np.float32)
    X[rng.random(X.shape) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) - 0.5 * np.nan_to_num(X[:, 1]) > 0
         ).astype(np.float32)
    return X, y


def worker(rank, world, *, out_dir, rounds, n_pages):
    import resource

    import numpy as np

    import xgboost_tpu as xtb

    class ShardIter(xtb.DataIter):
        def __init__(self, shards):
            super().__init__()
            self._shards, self._i = list(shards), 0

        def reset(self):
            self._i = 0

        def next(self, input_data):
            if self._i >= len(self._shards):
                return 0
            X, y = _page(self._shards[self._i])
            input_data(data=X, label=y)
            self._i += 1
            return 1

    def data_fn(smap, rank, world):
        return ShardIter(smap.shards_of(rank))

    cfg = xtb.ExtMemConfig(data_fn, num_shards=n_pages,
                           max_bin=PARAMS["max_bin"])
    bst = xtb.train(PARAMS, cfg, rounds, verbose_eval=False)
    peak_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
    with open(os.path.join(out_dir, f"rank{rank}.ubj"), "wb") as fh:
        fh.write(bytes(bst.save_raw()))
    with open(os.path.join(out_dir, f"rank{rank}.rss"), "w") as fh:
        fh.write(str(peak_mb))
    print(f"[extmem_smoke] rank {rank}: trained {rounds} rounds over "
          f"{len(xtb.ShardMap.create(n_pages, world).shards_of(rank))} "
          f"pages, peak RSS {peak_mb:.0f} MB", flush=True)


def main() -> int:
    from xgboost_tpu.launcher import WorkerFailedError, run_distributed

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import extmem_smoke as _mod

    global worker
    worker = _mod.worker

    resident_mb = N_PAGES * PAGE_ROWS * N_COLS * 4 / 2**20
    with tempfile.TemporaryDirectory(prefix="xtb_extmem_smoke_") as tmp:
        print(f"[extmem_smoke] {WORKERS}-worker paged run: {N_PAGES} pages "
              f"x {PAGE_ROWS} rows x {N_COLS} cols "
              f"(resident would be {resident_mb:.0f} MB f32) ...",
              flush=True)
        run_distributed(
            functools.partial(worker, out_dir=tmp, rounds=ROUNDS,
                              n_pages=N_PAGES),
            num_workers=WORKERS, platform="cpu", timeout=900,
            rendezvous="tracker")
        models = [open(os.path.join(tmp, f"rank{r}.ubj"), "rb").read()
                  for r in range(WORKERS)]
        if models[0] != models[1]:
            raise SystemExit("ranks disagree on the trained model bytes")
        peaks = [float(open(os.path.join(tmp, f"rank{r}.rss")).read())
                 for r in range(WORKERS)]
        if max(peaks) > RSS_CEILING_MB:
            raise SystemExit(
                f"RSS ceiling exceeded: peak {max(peaks):.0f} MB > "
                f"{RSS_CEILING_MB} MB (resident matrix would be "
                f"{resident_mb:.0f} MB)")
        print(f"[extmem_smoke] OK: identical model bytes "
              f"({len(models[0])}), peak RSS {max(peaks):.0f} MB <= "
              f"{RSS_CEILING_MB} MB ceiling", flush=True)

        # mid-stream decode failure: page_load raises on rank 1 during the
        # second streamed page — the job must FAIL with the cause named,
        # not hang the relay
        plan = {"faults": [{"site": "extmem.page_load", "kind": "exception",
                            "rank": 1, "round": 1}]}
        print("[extmem_smoke] injected decode failure at extmem.page_load "
              "(rank 1, page 1) ...", flush=True)
        try:
            run_distributed(
                functools.partial(worker, out_dir=tmp, rounds=ROUNDS,
                                  n_pages=N_PAGES),
                num_workers=WORKERS, platform="cpu", timeout=300,
                fault_plan=json.dumps(plan), rendezvous="tracker")
        except WorkerFailedError as e:
            tail = "".join(t or "" for _, _, t in e.failures)
            if "FaultInjected" not in tail and "page_load" not in tail:
                raise SystemExit(
                    f"decode failure surfaced without its cause: {e}")
            print(f"[extmem_smoke] OK: decode failure surfaced cleanly "
                  f"({len(e.failures)} failed worker(s), cause in stderr "
                  "tail)", flush=True)
        else:
            raise SystemExit("extmem.page_load fault did not fail the run")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
