#!/bin/bash
# Patient TPU-tunnel watcher: probe every 5 min; when the axon relay heals,
# run the Pallas histogram hardware sweep once and exit.
LOG=/tmp/tpu_watcher.log
SWEEP_LOG=/tmp/pallas_sweep_hw.log
echo "watcher start $(date)" >> "$LOG"
while true; do
  python - <<'EOF' >> "$LOG" 2>&1
import jax
d = jax.devices()
assert d[0].platform == "tpu", d
import jax.numpy as jnp
x = jnp.ones((128, 128))
assert float((x @ x)[0, 0]) == 128.0
print("PROBE-OK", d)
EOF
  if [ $? -eq 0 ]; then
    echo "tunnel healthy $(date); running sweep" >> "$LOG"
    PYTHONPATH=/root/repo:/root/.axon_site python /root/repo/scripts/pallas_hw_sweep.py 2000000 > "$SWEEP_LOG" 2>&1
    echo "sweep exit=$? $(date)" >> "$LOG"
    exit 0
  fi
  sleep 300
done
