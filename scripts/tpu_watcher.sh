#!/bin/bash
# Patient TPU-tunnel watcher: probe (with timeout — a wedged relay hangs
# jax.devices() forever) every 5 min; when the axon relay heals, run the
# HIGGS bench and then the Pallas histogram hardware sweep.  Retries until
# BOTH complete: the relay has been observed to wedge mid-run (probe OK,
# first train compile UNAVAILABLE), so success of the probe alone proves
# nothing.  Never runs two TPU clients concurrently.
LOG=/tmp/tpu_watcher.log
BENCH_OUT=/tmp/bench_tpu.json
BENCH_LOG=/tmp/bench_tpu.log
SWEEP_LOG=/tmp/pallas_sweep_hw.log
echo "watcher start $(date)" >> "$LOG"
bench_done=""
if [ -s "$BENCH_OUT" ] && grep -q Mrow "$BENCH_OUT" \
    && ! grep -q "CPU FALLBACK" "$BENCH_OUT"; then bench_done=1; fi
while true; do
  timeout 90 python - <<'EOF' >> "$LOG" 2>&1
import jax
d = jax.devices()
assert d[0].platform == "tpu", d
import jax.numpy as jnp
x = jnp.ones((128, 128))
assert float((x @ x)[0, 0]) == 128.0
print("PROBE-OK", d)
EOF
  if [ $? -eq 0 ]; then
    if [ -z "$bench_done" ]; then
      echo "tunnel healthy $(date); running bench" >> "$LOG"
      cd /root/repo && timeout 2400 python bench.py > "$BENCH_OUT.tmp" 2> "$BENCH_LOG"
      rc=$?
      echo "bench exit=$rc $(date)" >> "$LOG"
      if [ $rc -eq 0 ] && grep -q Mrow "$BENCH_OUT.tmp" \
          && ! grep -q "CPU FALLBACK" "$BENCH_OUT.tmp"; then
        mv "$BENCH_OUT.tmp" "$BENCH_OUT"
        bench_done=1
      fi
      sleep 30
      continue  # re-probe before the sweep
    fi
    echo "running pallas sweep $(date)" >> "$LOG"
    PYTHONPATH=/root/repo:/root/.axon_site timeout 2400 python /root/repo/scripts/pallas_hw_sweep.py 2000000 > "$SWEEP_LOG" 2>&1
    rc=$?
    echo "sweep exit=$rc $(date)" >> "$LOG"
    if [ $rc -eq 0 ]; then
      echo "ALL DONE $(date)" >> "$LOG"
      exit 0
    fi
  fi
  sleep 300
done
