#!/bin/bash
# Patient TPU-tunnel watcher: probe (with timeout — a wedged relay hangs
# jax.devices() forever) every 5 min; when the axon relay heals, run in
# order (VERDICT r3 #1iii):
#   1. micro bench  (BENCH_TIER=micro, <2 min — grab a TPU number FAST)
#   2. full bench   (shape of record)
#   3. Pallas histogram hardware sweep (Mosaic-lowering evidence)
# Each stage is gated on the previous and the tunnel is re-probed between
# stages: the relay has been observed to wedge mid-run (probe OK, first
# train compile UNAVAILABLE), so success of the probe alone proves nothing.
# Outputs land INSIDE the repo (bench_out/) so the end-of-round driver
# commit captures them even if /tmp is wiped again.  Never two TPU clients
# at once.  The persistent XLA compilation cache (/root/jax_cache) makes a
# retry after a drop skip recompilation.
OUT=/root/repo/bench_out
mkdir -p "$OUT"
# probe-failure tracebacks every 5 min add up — keep the chatty log in /tmp,
# only results + short bench logs in the committed bench_out/
LOG=/tmp/tpu_watcher.log
export JAX_COMPILATION_CACHE_DIR=/root/jax_cache
echo "watcher start $(date)" >> "$LOG"

have() { [ -s "$1" ] && grep -q '"platform": "tpu"' "$1"; }
micro_done=""; full_done=""
have "$OUT/BENCH_TPU_micro.json" && micro_done=1
have "$OUT/BENCH_TPU_full.json" && full_done=1

probe() {
  timeout 90 python - <<'EOF' >> "$LOG" 2>&1
import jax
d = jax.devices()
assert d[0].platform == "tpu", d
import jax.numpy as jnp
x = jnp.ones((128, 128))
assert float((x @ x)[0, 0]) == 128.0
print("PROBE-OK", d)
EOF
}

while true; do
  if probe; then
    if [ -z "$micro_done" ]; then
      echo "tunnel healthy $(date); running MICRO bench" >> "$LOG"
      cd /root/repo && BENCH_TIER=micro timeout 600 python bench.py \
        > "$OUT/BENCH_TPU_micro.json.tmp" 2> "$OUT/bench_micro.log"
      rc=$?
      echo "micro bench exit=$rc $(date)" >> "$LOG"
      if [ $rc -eq 0 ] && have "$OUT/BENCH_TPU_micro.json.tmp"; then
        mv "$OUT/BENCH_TPU_micro.json.tmp" "$OUT/BENCH_TPU_micro.json"
        micro_done=1
      fi
      sleep 15
      continue  # re-probe before the next stage
    fi
    if [ -z "$full_done" ]; then
      echo "running FULL bench $(date)" >> "$LOG"
      cd /root/repo && timeout 2400 python bench.py \
        > "$OUT/BENCH_TPU_full.json.tmp" 2> "$OUT/bench_full.log"
      rc=$?
      echo "full bench exit=$rc $(date)" >> "$LOG"
      if [ $rc -eq 0 ] && have "$OUT/BENCH_TPU_full.json.tmp"; then
        mv "$OUT/BENCH_TPU_full.json.tmp" "$OUT/BENCH_TPU_full.json"
        full_done=1
        if grep -q '"cpu_fallback": false' /root/repo/bench_phases.json 2>/dev/null; then
          cp /root/repo/bench_phases.json "$OUT/bench_phases_tpu.json"
        fi
      fi
      sleep 15
      continue
    fi
    echo "running pallas sweep $(date)" >> "$LOG"
    PYTHONPATH=/root/repo:/root/.axon_site timeout 2400 \
      python /root/repo/scripts/pallas_hw_sweep.py 2000000 \
      > "$OUT/pallas_sweep_hw.log" 2>&1
    rc=$?
    echo "sweep exit=$rc $(date)" >> "$LOG"
    if [ $rc -eq 0 ]; then
      echo "running BASELINE ladder (full scale) $(date)" >> "$LOG"
      cd /root/repo && LADDER_SCALE=1.0 timeout 5400 \
        python scripts/bench_ladder.py "$OUT/BENCH_LADDER_tpu.json" \
        > "$OUT/ladder_tpu.log" 2>&1
      echo "ladder exit=$? $(date)" >> "$LOG"
      echo "ALL DONE $(date)" >> "$LOG"
      exit 0
    fi
  fi
  sleep 300
done
