#!/bin/bash
# Static-analysis gate (docs/static_analysis.md): xtblint over the package
# + a bytecode-compile sweep + an optional mypy pass on the typed core
# (telemetry/ reliability/ analysis/, mypy.ini).  Run per-commit and from
# scripts/nightly_suite.sh; the quick test tier runs the same gate through
# tests/test_analysis.py::test_gate_cli_exits_zero.
#
# `lint_gate.sh --changed` is the fast pre-commit mode: lint only the
# package files touched since the merge-base with the default branch
# (falling back to HEAD for a detached/first commit).  Cross-file
# contracts that reconcile the WHOLE package against a catalog are
# skipped there — on a file subset they would report every
# registration/doc row the subset doesn't contain as stale:
#   XTB302/XTB303 (seam catalog), XTB403 (metric catalog),
#   XTB906 (knob catalog stale rows).
# Per-file families (incl. XTB901/902/903 lock discipline and XTB905
# undocumented-knob reads) still run.  The full gate remains the
# authority; --changed exists so the quick tier stays quick.
#
# The JSON report lands in bench_out/lint_report.json (findings AND
# suppressed findings) for trend tracking — suppression creep is a trend,
# not a silent pass.
set -e
cd "$(dirname "$0")/.."
mkdir -p bench_out

if [ "${1:-}" = "--changed" ]; then
    base=$(git merge-base HEAD origin/main 2>/dev/null \
        || git merge-base HEAD main 2>/dev/null || echo HEAD)
    mapfile -t changed < <( { git diff --name-only --diff-filter=d "$base" -- \
                                'xgboost_tpu/*.py' 'xgboost_tpu/**/*.py';
                              git ls-files --others --exclude-standard -- \
                                'xgboost_tpu/*.py' 'xgboost_tpu/**/*.py'; } \
                            | sort -u )
    if [ "${#changed[@]}" -eq 0 ]; then
        echo "lint_gate --changed: no package files changed vs $base"
        echo "lint_gate OK"
        exit 0
    fi
    echo "== xtblint --changed (${#changed[@]} file(s) vs $base) =="
    python -m xgboost_tpu.analysis "${changed[@]}" \
        --ignore XTB302,XTB303,XTB403,XTB906 \
        --json-out bench_out/lint_report_changed.json
    python -m compileall -q "${changed[@]}"
    echo "lint_gate OK"
    exit 0
fi

echo "== xtblint =="
python -m xgboost_tpu.analysis xgboost_tpu/ \
    --json-out bench_out/lint_report.json

echo "== compileall =="
python -m compileall -q xgboost_tpu/

# blanket (file-level) suppressions are forbidden in-tree; the analysis
# package itself documents the marker, so it is excluded from the sweep
if grep -rn "disable-file=" xgboost_tpu/ --include='*.py' \
        | grep -v "^xgboost_tpu/analysis/"; then
    echo "lint_gate: blanket 'xtblint: disable-file=' suppression found" >&2
    exit 1
fi

# optional: mypy over the typed core when the container has it (the image
# does not bake mypy in; the gate must not fail on its absence)
if python -m mypy --version >/dev/null 2>&1; then
    echo "== mypy (telemetry/ reliability/ analysis/) =="
    python -m mypy --config-file mypy.ini \
        xgboost_tpu/telemetry xgboost_tpu/reliability xgboost_tpu/analysis
else
    echo "== mypy not installed: skipping (optional pass) =="
fi

echo "lint_gate OK"
