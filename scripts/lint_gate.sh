#!/bin/bash
# Static-analysis gate (docs/static_analysis.md): xtblint over the package
# + a bytecode-compile sweep + an optional mypy pass on the typed core
# (telemetry/ reliability/ analysis/, mypy.ini).  Run per-commit and from
# scripts/nightly_suite.sh; the quick test tier runs the same gate through
# tests/test_analysis.py::test_gate_cli_exits_zero.
#
# The JSON report lands in bench_out/lint_report.json (findings AND
# suppressed findings) for trend tracking — suppression creep is a trend,
# not a silent pass.
set -e
cd "$(dirname "$0")/.."
mkdir -p bench_out

echo "== xtblint =="
python -m xgboost_tpu.analysis xgboost_tpu/ \
    --json-out bench_out/lint_report.json

echo "== compileall =="
python -m compileall -q xgboost_tpu/

# blanket (file-level) suppressions are forbidden in-tree; the analysis
# package itself documents the marker, so it is excluded from the sweep
if grep -rn "disable-file=" xgboost_tpu/ --include='*.py' \
        | grep -v "^xgboost_tpu/analysis/"; then
    echo "lint_gate: blanket 'xtblint: disable-file=' suppression found" >&2
    exit 1
fi

# optional: mypy over the typed core when the container has it (the image
# does not bake mypy in; the gate must not fail on its absence)
if python -m mypy --version >/dev/null 2>&1; then
    echo "== mypy (telemetry/ reliability/ analysis/) =="
    python -m mypy --config-file mypy.ini \
        xgboost_tpu/telemetry xgboost_tpu/reliability xgboost_tpu/analysis
else
    echo "== mypy not installed: skipping (optional pass) =="
fi

echo "lint_gate OK"
